"""Deployment package export/import.

TPU-era equivalent of the reference's ``Forward.package_export`` → zip →
libZnicz deployment path (reference nn_units.py:152-161, mnist.py:124-127,
libZnicz/src/all2all.cc).  The package is an **uncompressed** zip:

* ``manifest.json`` — human/python metadata: format version, workflow
  name, per-layer type string + attribute map;
* ``manifest.txt``  — the same layer list in a line-based form the C++
  runtime parses without a JSON dependency:
  ``type=all2all_tanh weights=layer0_weights.npy bias=layer0_bias.npy
  weights_transposed=0 include_bias=1``;
* ``layerN_<attr>.npy`` — one NumPy file per exported array.

Stored (not deflated) entries keep the C++ zip reader trivial; model
weights compress poorly anyway.  ``cpp/`` implements the consumer:
a C++ inference runtime covering the libZnicz unit scope.
"""

import io
import json
import zipfile

import numpy

#: the one format version this writer emits and the readers accept;
#: bump together with a manifest-schema change
PACKAGE_FORMAT = 1


def _layer_type(fwd):
    mapping = getattr(type(fwd), "MAPPING", None)
    if not mapping:
        raise ValueError("%s has no MAPPING type string" % type(fwd))
    return sorted(mapping)[0]


def _plain_scalar(value):
    if isinstance(value, (tuple, set, frozenset)):
        return list(value)
    return value


def input_sample_shape(workflow):
    """Per-sample input shape of the forward stack, when knowable (the
    first forward's allocated input minus the batch axis); None before
    initialize or for input-less stacks."""
    forwards = list(getattr(workflow, "forwards", ()))
    if not forwards:
        return None
    inp = getattr(forwards[0], "input", None)
    if inp is None or not inp:
        return None
    return tuple(int(d) for d in inp.shape[1:])


def forward_manifest(workflow):
    """``workflow``'s forward stack as (manifest dict, {fname: ndarray}).

    The single source of the package schema — :func:`export_package`
    writes exactly this, and the snapshot topology
    (:func:`forward_topology`) is its array-free sibling.
    """
    forwards = list(workflow.forwards)
    layers = []
    files = {}
    pending_mask = None
    pending_grouping = None
    for i, fwd in enumerate(forwards):
        tpe = _layer_type(fwd)
        if tpe == "zero_filter":
            # fold the grouping mask into the NEXT layer's exported
            # weights (the runtime chains pure Execute calls; a
            # weight-mutating unit has no place there) — the masked
            # weights ARE what the training forward used.  The mask
            # comes from the ZeroFiller itself (single source of the
            # grouping formula).
            fwd._ensure_mask()
            pending_mask = numpy.array(fwd.mask.mem)
            pending_grouping = int(fwd.grouping)
            continue
        entry = {"type": tpe, "name": fwd.name, "arrays": {}}
        data = fwd.package_export()
        if pending_mask is not None:
            w = data.get("weights")
            if w is None:
                # silently dropping the mask would make the package
                # lossy (and the served forward wrong for weights the
                # runtime re-randomizes) — refuse instead
                raise ValueError(
                    "zero_filter precedes %r which exports no weights "
                    "to fold the grouping mask into" % entry["name"])
            if w.size != pending_mask.size:
                raise ValueError(
                    "zero_filter mask size %d does not match %r "
                    "weights size %d" % (pending_mask.size,
                                         entry["name"], w.size))
            data = dict(data, weights=(
                w.reshape(pending_mask.shape) *
                pending_mask.astype(w.dtype)).reshape(w.shape))
            # keep the mask itself so the fold round-trips losslessly:
            # import_package recovers grouping + mask instead of only
            # the (already masked) product
            fname = "layer%d_zero_filter_mask.npy" % i
            files[fname] = pending_mask
            entry["arrays"]["zero_filter_mask"] = fname
            entry["zero_filter_grouping"] = pending_grouping
            pending_mask = pending_grouping = None
        for attr, value in data.items():
            if isinstance(value, numpy.ndarray):
                fname = "layer%d_%s.npy" % (i, attr)
                files[fname] = value
                entry["arrays"][attr] = fname
            else:
                entry[attr] = _plain_scalar(value)
        if entry["type"] == "activation_mul" and \
                entry.get("factor") is None:
            # exporting before the first minibatch auto-sets the factor
            # would make the runners disagree (numpy: KeyError; C++:
            # silent identity) — refuse loudly instead
            raise ValueError(
                "%s: activation_mul factor is unset — run at least one "
                "minibatch (or pass factor=) before exporting"
                % entry["name"])
        layers.append(entry)
    if pending_mask is not None:
        raise ValueError(
            "zero_filter is the last forward — no next layer to fold "
            "its grouping mask into")
    manifest = {
        "format": PACKAGE_FORMAT,
        "workflow": type(workflow).__name__,
        "layers": layers,
    }
    shape = input_sample_shape(workflow)
    if shape is not None:
        manifest["input_sample_shape"] = list(shape)
        manifest["serving"] = serving_manifest(shape)
    return manifest, files


def serving_manifest(sample_shape):
    """The ahead-of-time **warmup manifest** recorded at export /
    snapshot time: the shape-bucket ladder a serving replica should
    precompile for this model (from the serving config active at
    export), plus the per-sample input shape and the serving
    **dtype** (``root.common.serving.dtype`` — "f32" unless the
    exporting cluster serves low precision).  A cold replica reads it
    and warms the EXACT executable set the exporter's cluster serves —
    same ladder, same precision mode, so with the persistent
    compilation cache (core/compile_cache.py) every one of those warms
    is a cache load, not a compile, and the replica is ready in
    seconds with zero fresh XLA work.  An engine constructed with an
    explicit ``dtype=`` keeps its pin; the manifest only selects when
    the operator left the choice to the source."""
    from znicz_tpu.core.config import root
    from znicz_tpu.serving.engine import default_buckets
    from znicz_tpu.serving.quant import normalize_dtype
    max_batch = int(root.common.serving.get("max_batch", 64))
    return {
        "buckets": list(default_buckets(max_batch)),
        "max_batch": max_batch,
        "sample_shape": list(sample_shape),
        "dtype": normalize_dtype(
            root.common.serving.get("dtype", None)),
    }


def forward_topology(workflow):
    """Array-free manifest of the forward stack for snapshot payloads:
    each entry carries the layer type string, the owning unit's name
    (whose snapshot state holds the arrays), the array attribute names,
    and the scalar hyperparameters.  ``zero_filter`` units are skipped —
    they mask the next layer's weights in place on every step, so the
    snapshotted weights are already masked.

    Runs on EVERY snapshot, so unlike ``package_export()`` it never
    touches array contents — recording the attr names must not pull a
    full host copy of the weights per checkpoint."""
    from znicz_tpu.core.memory import Array
    layers = []
    for fwd in getattr(workflow, "forwards", ()):
        tpe = _layer_type(fwd)
        if tpe == "zero_filter":
            continue
        entry = {"type": tpe, "unit": fwd.name, "arrays": []}
        for attr in getattr(fwd, "exports", ()):
            value = getattr(fwd, attr, None)
            if value is None:
                continue
            if isinstance(value, Array):
                if value:  # allocated — snapshot state will carry it
                    entry["arrays"].append(attr)
            elif isinstance(value, numpy.ndarray):
                entry["arrays"].append(attr)
            else:
                entry[attr] = _plain_scalar(value)
        layers.append(entry)
    topology = {"layers": layers}
    shape = input_sample_shape(workflow)
    if shape is not None:
        topology["input_sample_shape"] = list(shape)
        topology["serving"] = serving_manifest(shape)
    return topology


def quantize_manifest(manifest, files):
    """Add the **int8 quantization sidecar** to a package manifest in
    place: for every weight-bearing layer, per-output-channel
    symmetric int8 weights (``layerN_weights_q8.npy``) and their f32
    scales (``layerN_weights_scale.npy``), referenced from the entry
    as ``quant_weights_q8`` / ``quant_weights_scale`` plus the scheme
    tag.  The f32 weights stay — the package still serves at any
    dtype; an ``int8`` engine adopts the sidecar verbatim (export-time
    quantization is authoritative) instead of re-quantizing at load.
    Like the zero_filter provenance arrays, the sidecar never appears
    in ``manifest.txt`` — the C++ runtime's flat parser only sees the
    f32 layers.  Returns the number of layers quantized."""
    from znicz_tpu.serving import quant
    quantized = 0
    for entry in manifest["layers"]:
        fname = entry.get("arrays", {}).get("weights")
        if fname is None or not quant.quantizable(entry):
            continue
        q, scale = quant.quantize_weights(files[fname],
                                          quant.quant_axis(entry))
        base = fname[:-len(".npy")]
        files[base + "_q8.npy"] = q
        files[base + "_scale.npy"] = scale
        entry["arrays"]["quant_weights_q8"] = base + "_q8.npy"
        entry["arrays"]["quant_weights_scale"] = base + "_scale.npy"
        entry["quant_scheme"] = quant.QUANT_SCHEME
        quantized += 1
    if quantized:
        manifest["quant_scheme"] = quant.QUANT_SCHEME
    return quantized


def export_package(workflow, path, quantize=False):
    """Write ``workflow``'s forward stack as a deployment package.

    ``workflow`` needs a ``forwards`` list (StandardWorkflow / NNWorkflow
    contract); returns the path written.  ``quantize=True`` adds the
    int8 weight sidecar (:func:`quantize_manifest`) so serving
    replicas in int8 mode load export-time scales instead of
    quantizing per replica.
    """
    manifest, files = forward_manifest(workflow)
    if quantize:
        quantize_manifest(manifest, files)
    layers = manifest["layers"]

    lines = []
    for i, entry in enumerate(layers):
        parts = ["type=%s" % entry["type"]]
        for attr, fname in sorted(entry["arrays"].items()):
            if attr.startswith("zero_filter") or \
                    attr.startswith("quant"):
                # python-side provenance only; the C++ runtime consumes
                # the already-masked f32 weights and its flat parser
                # must not see unknown array attrs
                continue
            parts.append("%s=%s" % (attr, fname))
        # scalar / tuple hyperparameters (conv & pooling geometry, LRN
        # constants, ...) serialize as key=value / key=a,b,c for the
        # C++ runtime's flat parser
        for attr in sorted(entry):
            if attr in ("type", "name", "arrays") or \
                    attr.startswith("zero_filter") or \
                    attr.startswith("quant"):
                continue
            value = entry[attr]
            if isinstance(value, bool):
                parts.append("%s=%d" % (attr, int(value)))
            elif isinstance(value, (int, float)):
                parts.append("%s=%s" % (attr, repr(value)))
            elif isinstance(value, (tuple, list)) and value and \
                    all(isinstance(v, (int, float)) for v in value):
                parts.append("%s=%s" % (attr,
                                        ",".join(repr(v) for v in value)))
        lines.append(" ".join(parts))

    with zipfile.ZipFile(path, "w", zipfile.ZIP_STORED) as zf:
        zf.writestr("manifest.json", json.dumps(manifest, indent=2,
                                                default=repr))
        zf.writestr("manifest.txt", "\n".join(lines) + "\n")
        for fname, value in files.items():
            buf = io.BytesIO()
            numpy.save(buf, numpy.ascontiguousarray(value))
            zf.writestr(fname, buf.getvalue())
    return path


def load_package(path):
    """Read a package back: (manifest dict, {filename: ndarray})."""
    with zipfile.ZipFile(path) as zf:
        manifest = json.loads(zf.read("manifest.json"))
        arrays = {}
        for info in zf.infolist():
            if info.filename.endswith(".npy"):
                arrays[info.filename] = numpy.load(
                    io.BytesIO(zf.read(info.filename)))
    return manifest, arrays


def import_package(path):
    """The validating counterpart of :func:`export_package` — what the
    Python side (the serving engine, tooling) loads packages through.

    Checks the manifest format version and that every referenced array
    file is present, so a truncated or future-format package fails here
    with a clear message instead of deep inside the first forward.
    Returns ``(manifest, arrays)`` like :func:`load_package`.
    """
    manifest, arrays = load_package(path)
    version = manifest.get("format")
    if version != PACKAGE_FORMAT:
        raise ValueError(
            "%s: unknown package format version %r (this build reads "
            "format %d) — re-export the package with a matching "
            "znicz_tpu version" % (path, version, PACKAGE_FORMAT))
    if not isinstance(manifest.get("layers"), list):
        raise ValueError("%s: manifest.json has no layers list" % path)
    for entry in manifest["layers"]:
        if "type" not in entry:
            raise ValueError("%s: manifest layer without type: %r"
                             % (path, entry))
        for attr, fname in entry.get("arrays", {}).items():
            if fname not in arrays:
                raise ValueError(
                    "%s: layer %r references missing array file %r"
                    % (path, entry.get("name", entry["type"]), fname))
    return manifest, arrays


def run_package_numpy(path, x):
    """Execute a package forward in pure numpy — the executable spec the
    C++ runtime (cpp/) must match to 1e-5.

    Supports the FC family plus the spatial tier (conv*, max/avg
    pooling, LRN, standalone activations, dropout-as-identity).  Spatial
    packages take NHWC input."""
    from znicz_tpu.ops import activations, dense
    from znicz_tpu.ops import conv as conv_ops
    from znicz_tpu.ops import normalization as norm_ops
    from znicz_tpu.ops import pooling as pool_ops
    manifest, arrays = load_package(path)
    x = numpy.asarray(x, dtype=numpy.float64)
    y = x
    for entry in manifest["layers"]:
        tpe = entry["type"]
        if tpe == "softmax" or tpe.startswith("all2all"):
            w = arrays[entry["arrays"]["weights"]]
            if entry.get("weights_transposed"):
                w = w.T
            b = arrays.get(entry["arrays"].get("bias", ""), None)
            include_bias = bool(entry.get("include_bias", True)) and \
                b is not None
            y = y.reshape(len(y), -1)
            if tpe == "softmax":
                y = dense.forward_numpy(y, w, b, activation="linear",
                                        include_bias=include_bias)
                y, _ = dense.softmax_numpy(y)
            else:
                act = {"all2all": "linear", "all2all_tanh": "tanh",
                       "all2all_relu": "relu",
                       "all2all_str": "strict_relu",
                       "all2all_sigmoid": "sigmoid"}[tpe]
                y = dense.forward_numpy(y, w, b, activation=act,
                                        include_bias=include_bias)
        elif tpe.startswith("conv"):
            w = arrays[entry["arrays"]["weights"]]
            if entry.get("weights_transposed"):
                w = w.T
            b = arrays.get(entry["arrays"].get("bias", ""), None)
            include_bias = bool(entry.get("include_bias", True)) and \
                b is not None
            act = {"conv": "linear", "conv_tanh": "tanh",
                   "conv_relu": "relu", "conv_str": "strict_relu",
                   "conv_sigmoid": "sigmoid"}[tpe]
            y = conv_ops.forward_numpy(
                y, w, b, int(entry["ky"]), int(entry["kx"]),
                tuple(int(v) for v in entry["padding"]),
                tuple(int(v) for v in entry["sliding"]),
                activation=act, include_bias=include_bias)
        elif tpe in ("max_pooling", "avg_pooling"):
            sliding = tuple(int(v) for v in entry["sliding"])
            if tpe == "max_pooling":
                y, _ = pool_ops.max_pooling_numpy(
                    y, int(entry["ky"]), int(entry["kx"]), sliding)
            else:
                y = pool_ops.avg_pooling_numpy(
                    y, int(entry["ky"]), int(entry["kx"]), sliding)
        elif tpe == "norm":
            y = norm_ops.lrn_forward_numpy(
                y, alpha=float(entry["alpha"]), beta=float(entry["beta"]),
                k=float(entry["k"]), n=int(entry["n"]))
        elif tpe == "activation_mul":
            y = y * float(entry["factor"])
        elif tpe.startswith("activation_"):
            act = {"activation_tanh": "tanh", "activation_sigmoid":
                   "sigmoid", "activation_relu": "relu",
                   "activation_str": "strict_relu"}.get(tpe)
            if act is not None:
                y = activations.apply_numpy(act, y)
            else:  # ext family: log / tanhlog / sincos
                y = activations.ext_apply_numpy(
                    tpe[len("activation_"):], y)
        elif tpe == "dropout":
            pass  # inference identity
        else:
            raise ValueError("package runner: unsupported type %r" % tpe)
    return y
