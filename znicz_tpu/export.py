"""Deployment package export/import.

TPU-era equivalent of the reference's ``Forward.package_export`` → zip →
libZnicz deployment path (reference nn_units.py:152-161, mnist.py:124-127,
libZnicz/src/all2all.cc).  The package is an **uncompressed** zip:

* ``manifest.json`` — human/python metadata: format version, workflow
  name, per-layer type string + attribute map;
* ``manifest.txt``  — the same layer list in a line-based form the C++
  runtime parses without a JSON dependency:
  ``type=all2all_tanh weights=layer0_weights.npy bias=layer0_bias.npy
  weights_transposed=0 include_bias=1``;
* ``layerN_<attr>.npy`` — one NumPy file per exported array.

Stored (not deflated) entries keep the C++ zip reader trivial; model
weights compress poorly anyway.  ``cpp/`` implements the consumer:
a C++ inference runtime covering the libZnicz unit scope.
"""

import io
import json
import zipfile

import numpy


def _layer_type(fwd):
    mapping = getattr(type(fwd), "MAPPING", None)
    if not mapping:
        raise ValueError("%s has no MAPPING type string" % type(fwd))
    return sorted(mapping)[0]


def export_package(workflow, path):
    """Write ``workflow``'s forward stack as a deployment package.

    ``workflow`` needs a ``forwards`` list (StandardWorkflow / NNWorkflow
    contract); returns the path written.
    """
    forwards = list(workflow.forwards)
    layers = []
    files = {}
    pending_mask = None
    for i, fwd in enumerate(forwards):
        tpe = _layer_type(fwd)
        if tpe == "zero_filter":
            # fold the grouping mask into the NEXT layer's exported
            # weights (the runtime chains pure Execute calls; a
            # weight-mutating unit has no place there) — the masked
            # weights ARE what the training forward used.  The mask
            # comes from the ZeroFiller itself (single source of the
            # grouping formula).
            fwd._ensure_mask()
            pending_mask = numpy.array(fwd.mask.mem)
            continue
        entry = {"type": tpe, "name": fwd.name, "arrays": {}}
        data = fwd.package_export()
        if pending_mask is not None:
            w = data.get("weights")
            if w is not None:
                data = dict(data, weights=(
                    w.reshape(pending_mask.shape) *
                    pending_mask.astype(w.dtype)).reshape(w.shape))
            pending_mask = None
        for attr, value in data.items():
            if isinstance(value, numpy.ndarray):
                fname = "layer%d_%s.npy" % (i, attr)
                files[fname] = value
                entry["arrays"][attr] = fname
            else:
                if isinstance(value, (tuple, set, frozenset)):
                    value = list(value)
                entry[attr] = value
        if entry["type"] == "activation_mul" and \
                entry.get("factor") is None:
            # exporting before the first minibatch auto-sets the factor
            # would make the runners disagree (numpy: KeyError; C++:
            # silent identity) — refuse loudly instead
            raise ValueError(
                "%s: activation_mul factor is unset — run at least one "
                "minibatch (or pass factor=) before exporting"
                % entry["name"])
        layers.append(entry)
    manifest = {
        "format": 1,
        "workflow": type(workflow).__name__,
        "layers": layers,
    }

    lines = []
    for i, entry in enumerate(layers):
        parts = ["type=%s" % entry["type"]]
        for attr, fname in sorted(entry["arrays"].items()):
            parts.append("%s=%s" % (attr, fname))
        # scalar / tuple hyperparameters (conv & pooling geometry, LRN
        # constants, ...) serialize as key=value / key=a,b,c for the
        # C++ runtime's flat parser
        for attr in sorted(entry):
            if attr in ("type", "name", "arrays"):
                continue
            value = entry[attr]
            if isinstance(value, bool):
                parts.append("%s=%d" % (attr, int(value)))
            elif isinstance(value, (int, float)):
                parts.append("%s=%s" % (attr, repr(value)))
            elif isinstance(value, (tuple, list)) and value and \
                    all(isinstance(v, (int, float)) for v in value):
                parts.append("%s=%s" % (attr,
                                        ",".join(repr(v) for v in value)))
        lines.append(" ".join(parts))

    with zipfile.ZipFile(path, "w", zipfile.ZIP_STORED) as zf:
        zf.writestr("manifest.json", json.dumps(manifest, indent=2,
                                                default=repr))
        zf.writestr("manifest.txt", "\n".join(lines) + "\n")
        for fname, value in files.items():
            buf = io.BytesIO()
            numpy.save(buf, numpy.ascontiguousarray(value))
            zf.writestr(fname, buf.getvalue())
    return path


def load_package(path):
    """Read a package back: (manifest dict, {filename: ndarray})."""
    with zipfile.ZipFile(path) as zf:
        manifest = json.loads(zf.read("manifest.json"))
        arrays = {}
        for info in zf.infolist():
            if info.filename.endswith(".npy"):
                arrays[info.filename] = numpy.load(
                    io.BytesIO(zf.read(info.filename)))
    return manifest, arrays


def run_package_numpy(path, x):
    """Execute a package forward in pure numpy — the executable spec the
    C++ runtime (cpp/) must match to 1e-5.

    Supports the FC family plus the spatial tier (conv*, max/avg
    pooling, LRN, standalone activations, dropout-as-identity).  Spatial
    packages take NHWC input."""
    from znicz_tpu.ops import activations, dense
    from znicz_tpu.ops import conv as conv_ops
    from znicz_tpu.ops import normalization as norm_ops
    from znicz_tpu.ops import pooling as pool_ops
    manifest, arrays = load_package(path)
    x = numpy.asarray(x, dtype=numpy.float64)
    y = x
    for entry in manifest["layers"]:
        tpe = entry["type"]
        if tpe == "softmax" or tpe.startswith("all2all"):
            w = arrays[entry["arrays"]["weights"]]
            if entry.get("weights_transposed"):
                w = w.T
            b = arrays.get(entry["arrays"].get("bias", ""), None)
            include_bias = bool(entry.get("include_bias", True)) and \
                b is not None
            y = y.reshape(len(y), -1)
            if tpe == "softmax":
                y = dense.forward_numpy(y, w, b, activation="linear",
                                        include_bias=include_bias)
                y, _ = dense.softmax_numpy(y)
            else:
                act = {"all2all": "linear", "all2all_tanh": "tanh",
                       "all2all_relu": "relu",
                       "all2all_str": "strict_relu",
                       "all2all_sigmoid": "sigmoid"}[tpe]
                y = dense.forward_numpy(y, w, b, activation=act,
                                        include_bias=include_bias)
        elif tpe.startswith("conv"):
            w = arrays[entry["arrays"]["weights"]]
            if entry.get("weights_transposed"):
                w = w.T
            b = arrays.get(entry["arrays"].get("bias", ""), None)
            include_bias = bool(entry.get("include_bias", True)) and \
                b is not None
            act = {"conv": "linear", "conv_tanh": "tanh",
                   "conv_relu": "relu", "conv_str": "strict_relu",
                   "conv_sigmoid": "sigmoid"}[tpe]
            y = conv_ops.forward_numpy(
                y, w, b, int(entry["ky"]), int(entry["kx"]),
                tuple(int(v) for v in entry["padding"]),
                tuple(int(v) for v in entry["sliding"]),
                activation=act, include_bias=include_bias)
        elif tpe in ("max_pooling", "avg_pooling"):
            sliding = tuple(int(v) for v in entry["sliding"])
            if tpe == "max_pooling":
                y, _ = pool_ops.max_pooling_numpy(
                    y, int(entry["ky"]), int(entry["kx"]), sliding)
            else:
                y = pool_ops.avg_pooling_numpy(
                    y, int(entry["ky"]), int(entry["kx"]), sliding)
        elif tpe == "norm":
            y = norm_ops.lrn_forward_numpy(
                y, alpha=float(entry["alpha"]), beta=float(entry["beta"]),
                k=float(entry["k"]), n=int(entry["n"]))
        elif tpe == "activation_mul":
            y = y * float(entry["factor"])
        elif tpe.startswith("activation_"):
            act = {"activation_tanh": "tanh", "activation_sigmoid":
                   "sigmoid", "activation_relu": "relu",
                   "activation_str": "strict_relu"}.get(tpe)
            if act is not None:
                y = activations.apply_numpy(act, y)
            else:  # ext family: log / tanhlog / sincos
                y = activations.ext_apply_numpy(
                    tpe[len("activation_"):], y)
        elif tpe == "dropout":
            pass  # inference identity
        else:
            raise ValueError("package runner: unsupported type %r" % tpe)
    return y
