"""User-facing test harness.

TPU-era equivalent of the reference's ``veles.tests`` helpers
(SURVEY.md §2.9: AcceleratedTest / assign_backend / timeout /
multi_device / doubling_reset) — the utilities unit authors use to test
their own units the way the framework tests its:

* :func:`run_both_backends` — build + run a unit on the numpy AND jax
  devices from one factory, compare every declared output;
* :func:`assert_rerun_stable` — the doubling_reset idea: running a unit
  twice on the same inputs must give identical outputs (catches hidden
  state leaking between runs);
* :func:`multi_device_mesh` — the 8-way virtual CPU mesh used for
  sharding tests (no-op when enough real devices exist);
* :class:`AcceleratedTest` — unittest base wiring the above plus a
  per-test timeout.
"""

import functools
import os
import threading
import unittest

import numpy

from znicz_tpu.core.backends import JaxDevice, NumpyDevice
from znicz_tpu.core.memory import Array
from znicz_tpu.core.workflow import DummyWorkflow


def build_fc_package_zip(path, dims, seed=42, scale=None,
                         weights_transposed=True):
    """Write a deterministic synthetic FC deployment-package zip
    (``manifest.json`` + per-layer ``w<i>.npy``/``b<i>.npy``) — the
    ONE builder the serving bench/smoke/fleet tests share instead of
    each hand-rolling the manifest format.

    ``dims`` is the full layer-width chain (``[in, hidden..., out]``;
    hidden layers are ``all2all_tanh``, the head is ``softmax``);
    ``scale`` multiplies the ``randn`` weights (None = raw randn);
    ``weights_transposed`` is recorded per layer (True stores
    ``(in, out)`` arrays — the export convention most tests use).
    Returns ``path``.
    """
    import io
    import json
    import zipfile
    r = numpy.random.RandomState(seed)
    layers, arrays = [], {}
    for i in range(len(dims) - 1):
        kind = "softmax" if i == len(dims) - 2 else "all2all_tanh"
        layers.append(
            {"type": kind, "name": "l%d" % i,
             "arrays": {"weights": "w%d.npy" % i,
                        "bias": "b%d.npy" % i},
             "include_bias": True,
             "weights_transposed": bool(weights_transposed)})
        shape = ((dims[i], dims[i + 1]) if weights_transposed
                 else (dims[i + 1], dims[i]))
        w = r.randn(*shape).astype(numpy.float32)
        if scale is not None:
            w *= scale
        arrays["w%d.npy" % i] = w
        arrays["b%d.npy" % i] = numpy.zeros(dims[i + 1],
                                            numpy.float32)
    manifest = {"format": 1, "layers": layers,
                "input_sample_shape": [int(dims[0])]}
    with zipfile.ZipFile(os.fspath(path), "w") as zf:
        zf.writestr("manifest.json", json.dumps(manifest))
        for fname, arr in arrays.items():
            buf = io.BytesIO()
            numpy.save(buf, arr)
            zf.writestr(fname, buf.getvalue())
    return path


def _collect_outputs(unit, attrs):
    out = {}
    for attr in attrs:
        value = getattr(unit, attr, None)
        if isinstance(value, Array) and value:
            value.map_read()
            out[attr] = numpy.array(value.mem)
    return out


def run_both_backends(build, outputs=("output",), atol=1e-6):
    """Build + run a unit per backend and compare its outputs.

    ``build(workflow, device)`` constructs, initializes, and returns the
    unit (call ``unit.initialize(device)`` inside).  Every attr in
    ``outputs`` present as a non-empty Array is compared.  Returns the
    numpy-side outputs dict.
    """
    results = {}
    for name, device in (("numpy", NumpyDevice()), ("jax", JaxDevice())):
        wf = DummyWorkflow()
        unit = build(wf, device)
        unit.run()
        results[name] = _collect_outputs(unit, outputs)
    missing = set(results["numpy"]) ^ set(results["jax"])
    if missing:
        raise AssertionError(
            "backends disagree on which outputs exist: %s" % missing)
    if not results["numpy"]:
        raise AssertionError(
            "no outputs to compare — none of %r is a non-empty Array "
            "on the unit (typo in the outputs tuple?)" % (outputs,))
    for attr, want in results["numpy"].items():
        got = results["jax"][attr]
        if want.shape != got.shape:
            raise AssertionError(
                "%s shape differs between backends: %s vs %s"
                % (attr, want.shape, got.shape))
        diff = numpy.abs(want.astype(numpy.float64) -
                         got.astype(numpy.float64)).max()
        if not diff <= atol:  # NaN must FAIL, not slip past `>`
            raise AssertionError(
                "%s differs between backends: max |delta| = %g > %g"
                % (attr, diff, atol))
    return results["numpy"]


def assert_rerun_stable(unit, outputs=("output",)):
    """Run ``unit`` twice; outputs must be IDENTICAL (the reference's
    doubling_reset contract — hidden state must not leak into reruns)."""
    unit.run()
    first = _collect_outputs(unit, outputs)
    unit.run()
    second = _collect_outputs(unit, outputs)
    if not first:
        raise AssertionError(
            "no outputs to compare — none of %r is a non-empty Array "
            "on the unit (typo in the outputs tuple?)" % (outputs,))
    for attr, want in first.items():
        got = second[attr]
        if not numpy.array_equal(want, got):
            raise AssertionError(
                "%s changed on re-run: the unit leaks state" % attr)


def multi_device_mesh(n=8, model_parallel=1):
    """An n-device mesh for sharding tests.  Uses the real devices when
    enough exist; otherwise requires the virtual CPU platform (set
    XLA_FLAGS=--xla_force_host_platform_device_count=N before jax
    initializes — tests/conftest.py shows the recipe)."""
    import jax
    from znicz_tpu.parallel import make_mesh
    if len(jax.devices()) < n:
        raise unittest.SkipTest(
            "need %d devices; set "
            "XLA_FLAGS=--xla_force_host_platform_device_count=%d and "
            "JAX_PLATFORMS=cpu before the first jax use" % (n, n))
    return make_mesh(n, model_parallel=model_parallel)


def timeout(seconds):
    """Fail (don't hang) when a test exceeds ``seconds`` — the reference
    tests' @timeout decorator."""
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            result = {}

            def target():
                try:
                    result["value"] = fn(*args, **kwargs)
                except BaseException as e:  # propagated below
                    result["error"] = e

            t = threading.Thread(target=target,
                                 name="znicz:test-timeout",
                                 daemon=True)
            t.start()
            t.join(seconds)
            if t.is_alive():
                raise AssertionError(
                    "%s exceeded %ss timeout" % (fn.__name__, seconds))
            if "error" in result:
                raise result["error"]
            return result.get("value")
        return wrapper
    return deco


class AcceleratedTest(unittest.TestCase):
    """unittest base for unit authors: seeded PRNGs, both devices, the
    comparison helpers as methods, and every test_* method wrapped in
    the class TIMEOUT (override or set ZNICZ_TEST_TIMEOUT)."""

    TIMEOUT = float(os.environ.get("ZNICZ_TEST_TIMEOUT", 300))

    def __init_subclass__(cls, **kwargs):
        super().__init_subclass__(**kwargs)
        for name, fn in list(vars(cls).items()):
            if name.startswith("test") and callable(fn):
                setattr(cls, name, timeout(cls.TIMEOUT)(fn))

    def setUp(self):
        from znicz_tpu.core import prng
        prng.get(1).seed(1234)
        prng.get(2).seed(5678)
        self.numpy_device = NumpyDevice()
        self.jax_device = JaxDevice()
        self.workflow = DummyWorkflow()

    def assertBackendsAgree(self, build, outputs=("output",),
                            atol=1e-6):
        return run_both_backends(build, outputs=outputs, atol=atol)

    def assertRerunStable(self, unit, outputs=("output",)):
        assert_rerun_stable(unit, outputs=outputs)
