"""Dataset loaders.

TPU-era equivalent of the veles-core loader contract + the reference's
``loader/`` tree (SURVEY.md §2.5).  Constants parity: TEST=0, VALID=1,
TRAIN=2 (reference: veles.loader import sites, loader_wine.py:41).
"""

from znicz_tpu.loader.base import (  # noqa: F401
    TEST, VALID, TRAIN, CLASS_NAME, Loader, FullBatchLoader,
    FullBatchLoaderMSE, FullBatchLoaderMSEMixin, LoaderMSEMixin,
    UserLoaderRegistry, ILoader, IFullBatchLoader)
from znicz_tpu.loader.image import (  # noqa: F401
    IImageLoader, ImageLoaderBase, FullBatchImageLoader,
    FileListImageLoader, FullBatchFileListImageLoader,
    AutoLabelFileImageLoader, FullBatchAutoLabelFileImageLoader)
# registration side effects (type-string loaders)
import znicz_tpu.loader.loader_lmdb  # noqa: F401
import znicz_tpu.loader.loader_stl  # noqa: F401
import znicz_tpu.loader.imagenet_loader  # noqa: F401
import znicz_tpu.loader.pickles  # noqa: F401
import znicz_tpu.loader.interactive  # noqa: F401
import znicz_tpu.loader.saver  # noqa: F401
