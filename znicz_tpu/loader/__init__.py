"""Dataset loaders.

TPU-era equivalent of the veles-core loader contract + the reference's
``loader/`` tree (SURVEY.md §2.5).  Constants parity: TEST=0, VALID=1,
TRAIN=2 (reference: veles.loader import sites, loader_wine.py:41).
"""

from znicz_tpu.loader.base import (  # noqa: F401
    TEST, VALID, TRAIN, CLASS_NAME, Loader, FullBatchLoader,
    UserLoaderRegistry, ILoader, IFullBatchLoader)
