"""Caffe LMDB dataset loader.

Parity target: reference loader/loader_lmdb.py:20-169 (``MAPPING =
"lmdb"``) — reads Caffe intermediate databases whose values are serialized
``Datum`` protobufs keyed in iteration order.  Uses the ``lmdb`` package
when importable, else the pure-Python reader
(:mod:`znicz_tpu.loader.lmdb_native`) — this box vendors no C extension.

kwargs parity: ``test_path`` / ``validation_path`` / ``train_path`` point
at the per-class database directories; ``db_shape`` (H, W, C) describes
records whose Datum omits geometry; ``db_splitted_channels`` selects CHW
(Caffe layout) vs HWC record bytes.
"""

import numpy

from znicz_tpu.loader.caffe import Datum
from znicz_tpu.loader.image import ImageLoaderBase, FullBatchImageLoader, \
    IImageLoader


def _open_db(path):
    try:
        import lmdb
    except ImportError:
        from znicz_tpu.loader.lmdb_native import LMDBReader
        return LMDBReader(path)
    env = lmdb.open(path, readonly=True, lock=False)

    class _Env(object):
        def items(self):
            with env.begin() as txn:
                with txn.cursor() as cur:
                    yield from iter(cur)

        def get(self, key):
            with env.begin() as txn:
                return txn.get(key)

    return _Env()


class LMDBLoader(ImageLoaderBase, IImageLoader):
    MAPPING = "lmdb"

    def __init__(self, workflow, **kwargs):
        super(LMDBLoader, self).__init__(workflow, **kwargs)
        self._files = (kwargs.get("test_path"),
                       kwargs.get("validation_path"),
                       kwargs.get("train_path"))
        self.original_shape = tuple(kwargs.get("db_shape", (256, 256, 3)))
        self.db_color_space = kwargs.get("db_colorspace", "RGB")
        self.db_splitted_channels = kwargs.get("db_splitted_channels", True)
        self.use_cache = kwargs.get("use_cache", True)
        self._dbs = [None] * 3
        self._cache = (None, None)
        self._cache_hits = 0
        self._cache_misses = 0
        self._labels_by_key = {}

    @property
    def files(self):
        return self._files

    @property
    def cache_hits(self):
        return self._cache_hits

    @property
    def cache_misses(self):
        return self._cache_misses

    def _db(self, index):
        if self._dbs[index] is None:
            if self._files == (None, None, None):
                raise OSError(
                    "no LMDB paths: pass test_path/validation_path/"
                    "train_path")
            path = self._files[index]
            if not path:
                return None
            self._dbs[index] = _open_db(path)
        return self._dbs[index]

    # -- Datum access -------------------------------------------------------
    def get_datum(self, key):
        index, dkey = key
        datum = Datum()
        datum.ParseFromString(self._db(index).get(dkey))
        self._cache = (key, datum)
        return datum

    def get_cached_data(self, key):
        if self.use_cache:
            if key != self._cache[0]:
                self._cache_misses += 1
                return self.get_datum(key)
            self._cache_hits += 1
            return self._cache[1]
        return self.get_datum(key)

    # -- ImageLoader contract -----------------------------------------------
    def get_keys(self, index):
        db = self._db(index)
        if db is None:
            return []
        # capture labels during the sweep: each value is already in hand,
        # saving the label pre-scan's N point lookups + Datum re-parses
        keys = []
        for k, v in db.items():
            key = (index, k)
            keys.append(key)
            self._labels_by_key[key] = Datum().ParseFromString(v).label
        return keys

    def get_image_label(self, key):
        label = self._labels_by_key.get(key)
        if label is not None:
            return label
        return self.get_cached_data(key).label

    def get_image_info(self, key):
        datum = self.get_cached_data(key)
        return (datum.height, datum.width), self.db_color_space

    def get_image_data(self, key):
        datum = self.get_cached_data(key)
        if datum.data:
            img = numpy.frombuffer(datum.data, dtype=numpy.uint8)
        else:
            img = numpy.asarray(datum.float_data, dtype=numpy.float32)
        if datum.height and datum.width:
            shape = (datum.height, datum.width,
                     datum.channels or self.original_shape[-1])
        else:
            shape = self.original_shape
        if self.db_splitted_channels:
            # Caffe CHW record -> HWC
            img = numpy.transpose(
                img.reshape((shape[-1],) + shape[:-1]), (1, 2, 0))
        else:
            img = img.reshape(shape)
        return img


class FullBatchLMDBLoader(FullBatchImageLoader, LMDBLoader):
    """Whole LMDB decoded up front (for sets that fit in host RAM)."""

    MAPPING = "full_batch_lmdb"
