"""Minimal Caffe protobuf wire codec — ``Datum`` and ``BlobProto``.

The reference vendors a 3580-line *generated* pure-Python protobuf module
(loader/caffe/protobuf2.py) solely so LMDBLoader can parse Caffe ``Datum``
records without protobuf installed.  The wire format is tiny; this is a
hand-written codec for exactly the messages the loaders need.

Schema (reference protobuf2.py:725-788, caffe.proto):

    message Datum {
      optional int32 channels = 1;   optional int32 height = 2;
      optional int32 width = 3;      optional bytes data = 4;
      optional int32 label = 5;      repeated float float_data = 6;
    }
    message BlobProto {
      optional int32 num = 1;        optional int32 channels = 2;
      optional int32 height = 3;     optional int32 width = 4;
      repeated float data = 5 [packed]; repeated float diff = 6 [packed];
    }
"""

import struct


def _read_varint(buf, pos):
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 63:
            raise ValueError("varint too long")


def _write_varint(out, value):
    if value < 0:
        value += 1 << 64  # two's-complement negative int32/int64
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _signed32(value):
    value &= 0xFFFFFFFF
    return value - (1 << 32) if value >= (1 << 31) else value


def _iter_fields(buf):
    """Yield (field_number, wire_type, value) over a serialized message."""
    pos = 0
    n = len(buf)
    while pos < n:
        tag, pos = _read_varint(buf, pos)
        field, wire = tag >> 3, tag & 7
        if wire == 0:                      # varint
            value, pos = _read_varint(buf, pos)
        elif wire == 1:                    # 64-bit
            value = buf[pos:pos + 8]
            pos += 8
        elif wire == 2:                    # length-delimited
            length, pos = _read_varint(buf, pos)
            value = buf[pos:pos + length]
            pos += length
        elif wire == 5:                    # 32-bit
            value = buf[pos:pos + 4]
            pos += 4
        else:
            raise ValueError("unsupported wire type %d" % wire)
        yield field, wire, value


class Datum(object):
    """One Caffe dataset record (image bytes + label)."""

    __slots__ = ("channels", "height", "width", "data", "label",
                 "float_data")

    def __init__(self, channels=0, height=0, width=0, data=b"", label=0,
                 float_data=None):
        self.channels = channels
        self.height = height
        self.width = width
        self.data = data
        self.label = label
        self.float_data = list(float_data or [])

    def ParseFromString(self, buf):
        self.__init__()
        for field, wire, value in _iter_fields(bytes(buf)):
            if field == 1:
                self.channels = _signed32(value)
            elif field == 2:
                self.height = _signed32(value)
            elif field == 3:
                self.width = _signed32(value)
            elif field == 4:
                self.data = bytes(value)
            elif field == 5:
                self.label = _signed32(value)
            elif field == 6:
                if wire == 5:
                    self.float_data.append(struct.unpack("<f", value)[0])
                else:  # packed
                    self.float_data.extend(
                        struct.unpack("<%df" % (len(value) // 4), value))
        return self

    def SerializeToString(self):
        out = bytearray()
        for field, value in ((1, self.channels), (2, self.height),
                             (3, self.width)):
            if value:
                _write_varint(out, field << 3)
                _write_varint(out, value)
        if self.data:
            _write_varint(out, (4 << 3) | 2)
            _write_varint(out, len(self.data))
            out.extend(self.data)
        if self.label:
            _write_varint(out, 5 << 3)
            _write_varint(out, self.label)
        for f in self.float_data:
            _write_varint(out, (6 << 3) | 5)
            out.extend(struct.pack("<f", f))
        return bytes(out)


class BlobProto(object):
    """Caffe blob (used for mean files)."""

    __slots__ = ("num", "channels", "height", "width", "data", "diff")

    def __init__(self):
        self.num = self.channels = self.height = self.width = 0
        self.data = []
        self.diff = []

    def ParseFromString(self, buf):
        self.__init__()
        for field, wire, value in _iter_fields(bytes(buf)):
            if field == 1:
                self.num = _signed32(value)
            elif field == 2:
                self.channels = _signed32(value)
            elif field == 3:
                self.height = _signed32(value)
            elif field == 4:
                self.width = _signed32(value)
            elif field in (5, 6):
                target = self.data if field == 5 else self.diff
                if wire == 5:
                    target.append(struct.unpack("<f", value)[0])
                else:  # packed (the generated schema marks these packed)
                    target.extend(
                        struct.unpack("<%df" % (len(value) // 4), value))
        return self

    def SerializeToString(self):
        out = bytearray()
        for field, value in ((1, self.num), (2, self.channels),
                             (3, self.height), (4, self.width)):
            if value:
                _write_varint(out, field << 3)
                _write_varint(out, value)
        for field, values in ((5, self.data), (6, self.diff)):
            if values:
                payload = struct.pack("<%df" % len(values), *values)
                _write_varint(out, (field << 3) | 2)
                _write_varint(out, len(payload))
                out.extend(payload)
        return bytes(out)
