"""Pure-Python read-only LMDB (data.mdb) access + a bulk fixture writer.

The reference's LMDBLoader needs the ``lmdb`` C extension
(loader/loader_lmdb.py:13); this box has none, so the on-disk format is
implemented directly from the liblmdb layout (mdb.c): 4096-byte pages, two
meta pages, a B+tree of branch/leaf pages for the MAIN db, overflow-page
chains for big values.  :class:`LMDBReader` reads any standard
single-process data.mdb; :func:`write_lmdb` bulk-builds a spec-conformant
database bottom-up (the mdb_load strategy) for fixtures and export.

Layout summary (struct names from mdb.c):

* page header, 16 bytes: pgno u64 | pad u16 | flags u16 |
  (lower u16, upper u16) or, for overflow pages, pages u32.
  Node-pointer array (u16 offsets from page start) follows; nodes are
  packed downward from ``upper``.
* node, 8-byte header: lo u16 | hi u16 | flags u16 | ksize u16 | key |
  data.  Leaf: datasize = lo | hi<<16; F_BIGDATA (0x01) stores an 8-byte
  overflow pgno instead of inline data.  Branch: child pgno = lo |
  hi<<16 | flags<<32 (node 0 has an empty key).
* meta (offset 16 on pages 0/1): magic 0xBEEFC0DE u32 | version u32 |
  address u64 | mapsize u64 | MDB_db[2] (FREE, MAIN) | last_pg u64 |
  txnid u64.  MDB_db, 48 bytes: pad u32 | flags u16 | depth u16 |
  branch_pages u64 | leaf_pages u64 | overflow_pages u64 | entries u64 |
  root u64.  The live meta is the one with the larger txnid.
"""

import os
import struct

PAGESIZE = 4096
PAGEHDRSZ = 16
NODEHDRSZ = 8

P_BRANCH = 0x01
P_LEAF = 0x02
P_OVERFLOW = 0x04
P_META = 0x08
P_LEAF2 = 0x20

F_BIGDATA = 0x01

MDB_MAGIC = 0xBEEFC0DE
MDB_VERSION = 1
P_INVALID = 0xFFFFFFFFFFFFFFFF

_META = struct.Struct("<II Q Q")          # magic, version, address, mapsize
_DB = struct.Struct("<I H H Q Q Q Q Q")   # pad,flags,depth,branch,leaf,ovf,
                                          # entries,root
_PAGEHDR = struct.Struct("<Q H H H H")    # pgno, pad, flags, lower, upper
_NODEHDR = struct.Struct("<H H H H")      # lo, hi, flags, ksize


class LMDBError(Exception):
    pass


class LMDBReader(object):
    """Read-only cursor over the MAIN database of a data.mdb file."""

    def __init__(self, path):
        import mmap
        if os.path.isdir(path):
            path = os.path.join(path, "data.mdb")
        with open(path, "rb") as f:
            # map, don't slurp: real Caffe DBs are tens of GB and the
            # streaming loaders exist precisely to avoid holding them
            self._buf = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
        self.path = path
        # liblmdb sizes pages from the creating host's OS page size and
        # records it in the meta's FREE-db pad field (mm_psize); meta page
        # 0 sits at offset 0 regardless of stride, so read it from there,
        # falling back to probing meta page 1 when implausible
        self.pagesize = PAGESIZE
        meta0 = self._parse_meta(0)
        psize = meta0["free"]["pad"] if meta0 else 0
        if 512 <= psize <= 65536 and psize & (psize - 1) == 0:
            self.pagesize = psize
        else:
            for candidate in (4096, 8192, 16384, 32768, 65536):
                self.pagesize = candidate
                if self._parse_meta(1) is not None:
                    break
            else:
                self.pagesize = PAGESIZE
        meta = None
        for pgno in (0, 1):
            m = self._parse_meta(pgno)
            if m is not None and (meta is None or m["txnid"] > meta["txnid"]):
                meta = m
        if meta is None:
            raise LMDBError("%s: no valid LMDB meta page" % path)
        self._main = meta["main"]
        self.entries = self._main["entries"]

    def _parse_meta(self, pgno):
        off = pgno * self.pagesize
        if len(self._buf) < off + PAGEHDRSZ + _META.size + 2 * _DB.size + 16:
            return None
        _, _, flags, _, _ = _PAGEHDR.unpack_from(self._buf, off)
        if not flags & P_META:
            return None
        magic, version, _, _ = _META.unpack_from(self._buf, off + PAGEHDRSZ)
        if magic != MDB_MAGIC or version != MDB_VERSION:
            return None
        dbs_off = off + PAGEHDRSZ + _META.size
        free = _DB.unpack_from(self._buf, dbs_off)
        main = _DB.unpack_from(self._buf, dbs_off + _DB.size)
        last_pg, txnid = struct.unpack_from(
            "<QQ", self._buf, dbs_off + 2 * _DB.size)
        names = ("pad", "flags", "depth", "branch_pages", "leaf_pages",
                 "overflow_pages", "entries", "root")
        return {"txnid": txnid, "last_pg": last_pg,
                "free": dict(zip(names, free)),
                "main": dict(zip(names, main))}

    # -- page access --------------------------------------------------------
    def _page(self, pgno):
        off = pgno * self.pagesize
        if off + self.pagesize > len(self._buf):
            raise LMDBError("page %d out of range" % pgno)
        return off

    def _page_nodes(self, off):
        _, _, flags, lower, _ = _PAGEHDR.unpack_from(self._buf, off)
        if flags & P_LEAF2:
            raise LMDBError("MDB_DUPFIXED leaf2 pages are not supported")
        nkeys = (lower - PAGEHDRSZ) // 2
        ptrs = struct.unpack_from("<%dH" % nkeys, self._buf, off + PAGEHDRSZ)
        return flags, ptrs

    def _node(self, page_off, ptr):
        off = page_off + ptr
        lo, hi, flags, ksize = _NODEHDR.unpack_from(self._buf, off)
        key = self._buf[off + NODEHDRSZ:off + NODEHDRSZ + ksize]
        return lo, hi, flags, key, off + NODEHDRSZ + ksize

    def _leaf_value(self, lo, hi, nflags, data_off):
        dsize = lo | (hi << 16)
        if nflags & F_BIGDATA:
            (ovf_pgno,) = struct.unpack_from("<Q", self._buf, data_off)
            ooff = self._page(ovf_pgno)
            _, _, oflags, novf_lo, novf_hi = _PAGEHDR.unpack_from(
                self._buf, ooff)
            if not oflags & P_OVERFLOW:
                raise LMDBError("bigdata pgno %d is not an overflow page"
                                % ovf_pgno)
            start = ooff + PAGEHDRSZ
            return self._buf[start:start + dsize]
        return self._buf[data_off:data_off + dsize]

    # -- public api ---------------------------------------------------------
    def items(self):
        """Yield (key, value) in key order (cursor-iteration parity)."""
        root = self._main["root"]
        if root == P_INVALID:
            return
        yield from self._walk(root)

    def _walk(self, pgno):
        off = self._page(pgno)
        flags, ptrs = self._page_nodes(off)
        if flags & P_LEAF:
            for ptr in ptrs:
                lo, hi, nflags, key, data_off = self._node(off, ptr)
                yield bytes(key), bytes(
                    self._leaf_value(lo, hi, nflags, data_off))
        elif flags & P_BRANCH:
            for ptr in ptrs:
                lo, hi, nflags, _, _ = self._node(off, ptr)
                child = lo | (hi << 16) | (nflags << 32)
                yield from self._walk(child)
        else:
            raise LMDBError("unexpected page flags 0x%x" % flags)

    def get(self, key):
        """Point lookup by binary-search descent."""
        pgno = self._main["root"]
        if pgno == P_INVALID:
            return None
        while True:
            off = self._page(pgno)
            flags, ptrs = self._page_nodes(off)
            if flags & P_LEAF:
                for ptr in ptrs:  # pages hold <~100 nodes; linear is fine
                    lo, hi, nflags, nkey, data_off = self._node(off, ptr)
                    if bytes(nkey) == key:
                        return bytes(
                            self._leaf_value(lo, hi, nflags, data_off))
                return None
            child = None
            for ptr in ptrs:
                lo, hi, nflags, nkey, _ = self._node(off, ptr)
                this = lo | (hi << 16) | (nflags << 32)
                if nkey and bytes(nkey) > key:
                    break
                child = this
            if child is None:  # key below the first separator
                lo, hi, nflags, _, _ = self._node(off, ptrs[0])
                child = lo | (hi << 16) | (nflags << 32)
            pgno = child


# -- fixture/bulk writer ----------------------------------------------------

def _even(n):
    return n + (n & 1)


def write_lmdb(path, items):
    """Bulk-build a data.mdb from (key, value) pairs (sorted internally).

    The mdb_load strategy: pack sorted leaves, then branch levels up to a
    single root.  Values too big to share a leaf page go to overflow
    chains.  Returns the file path.
    """
    if os.path.isdir(path) or path.endswith(os.sep) or "." not in \
            os.path.basename(path):
        os.makedirs(path, exist_ok=True)
        path = os.path.join(path, "data.mdb")
    items = sorted((bytes(k), bytes(v)) for k, v in items)
    for k, _ in items:
        if len(k) > 511:  # liblmdb mdb_env_get_maxkeysize default
            raise LMDBError(
                "key of %d bytes exceeds LMDB's 511-byte limit" % len(k))
    space = PAGESIZE - PAGEHDRSZ
    next_pgno = 2
    pages = {}   # pgno -> bytes
    n_leaf = n_branch = n_ovf = 0

    def alloc():
        nonlocal next_pgno
        pgno = next_pgno
        next_pgno += 1
        return pgno

    def write_page(pgno, flags, nodes):
        """nodes: list of (node_header_bytes..., key, data) raw bytes."""
        buf = bytearray(PAGESIZE)
        ptrs = []
        upper = PAGESIZE
        for raw in reversed(nodes):
            upper -= _even(len(raw))
            buf[upper:upper + len(raw)] = raw
            ptrs.append(upper)
        ptrs.reverse()
        lower = PAGEHDRSZ + 2 * len(nodes)
        _PAGEHDR.pack_into(buf, 0, pgno, 0, flags, lower, upper)
        struct.pack_into("<%dH" % len(ptrs), buf, PAGEHDRSZ, *ptrs)
        pages[pgno] = bytes(buf)

    def leaf_node(key, value):
        nonlocal n_ovf
        inline = NODEHDRSZ + len(key) + len(value)
        # liblmdb sends data to overflow when the node exceeds nodemax
        # (~half a page); mirror that threshold
        if inline > (PAGESIZE - PAGEHDRSZ) // 2 and \
                NODEHDRSZ + len(key) + 8 <= (PAGESIZE - PAGEHDRSZ) // 2:
            novf = -(-len(value) // (PAGESIZE - PAGEHDRSZ))
            ovf_pgno = None
            data = value
            first = alloc()
            for i in range(novf - 1):
                alloc()
            n_ovf += novf
            buf = bytearray(novf * PAGESIZE)
            struct.pack_into("<QHHI", buf, 0, first, 0, P_OVERFLOW, novf)
            buf[PAGEHDRSZ:PAGEHDRSZ + len(data)] = data
            for i in range(novf):
                pages[first + i] = bytes(
                    buf[i * PAGESIZE:(i + 1) * PAGESIZE])
            dsize = len(value)
            hdr = _NODEHDR.pack(dsize & 0xFFFF, dsize >> 16, F_BIGDATA,
                                len(key))
            return hdr + key + struct.pack("<Q", first)
        dsize = len(value)
        hdr = _NODEHDR.pack(dsize & 0xFFFF, dsize >> 16, 0, len(key))
        return hdr + key + value

    def branch_node(key, pgno):
        return _NODEHDR.pack(pgno & 0xFFFF, (pgno >> 16) & 0xFFFF,
                             (pgno >> 32) & 0xFFFF, len(key)) + key

    # pack leaves
    level = []  # (first_key, pgno)
    cur_nodes, cur_first, cur_used = [], None, 0
    for key, value in items:
        raw = leaf_node(key, value)
        sz = _even(len(raw)) + 2
        if cur_nodes and cur_used + sz > space:
            pgno = alloc()
            write_page(pgno, P_LEAF, cur_nodes)
            n_leaf += 1
            level.append((cur_first, pgno))
            cur_nodes, cur_used = [], 0
        if not cur_nodes:
            cur_first = key
        cur_nodes.append(raw)
        cur_used += sz
    pgno = alloc()
    write_page(pgno, P_LEAF, cur_nodes)  # possibly empty leaf for empty db
    n_leaf += 1
    level.append((cur_first or b"", pgno))
    depth = 1

    # pack branches up to a single root
    while len(level) > 1:
        nxt = []
        cur_nodes, cur_first, cur_used = [], None, 0
        for i, (first_key, child) in enumerate(level):
            key = b"" if not cur_nodes else first_key
            raw = branch_node(key, child)
            sz = _even(len(raw)) + 2
            if cur_nodes and cur_used + sz > space:
                pg = alloc()
                write_page(pg, P_BRANCH, cur_nodes)
                n_branch += 1
                nxt.append((cur_nodes_first, pg))
                cur_nodes, cur_used = [], 0
                raw = branch_node(b"", child)
                sz = _even(len(raw)) + 2
            if not cur_nodes:
                cur_nodes_first = first_key
            cur_nodes.append(raw)
            cur_used += sz
        pg = alloc()
        write_page(pg, P_BRANCH, cur_nodes)
        n_branch += 1
        nxt.append((cur_nodes_first, pg))
        level = nxt
        depth += 1

    root = level[0][1]
    last_pg = next_pgno - 1

    def meta_page(pgno, txnid):
        buf = bytearray(PAGESIZE)
        _PAGEHDR.pack_into(buf, 0, pgno, 0, P_META, 0, 0)
        # mapsize must cover the whole file (liblmdb maps this many bytes)
        _META.pack_into(buf, PAGEHDRSZ, MDB_MAGIC, MDB_VERSION, 0,
                        max(next_pgno * PAGESIZE, 1 << 20))
        dbs = PAGEHDRSZ + _META.size
        # FREE db; its pad field doubles as mm_psize in the meta layout
        _DB.pack_into(buf, dbs, PAGESIZE, 0, 0, 0, 0, 0, 0, P_INVALID)
        _DB.pack_into(buf, dbs + _DB.size, 0, 0, depth, n_branch, n_leaf,
                      n_ovf, len(items), root)                    # MAIN
        struct.pack_into("<QQ", buf, dbs + 2 * _DB.size, last_pg, txnid)
        return bytes(buf)

    with open(path, "wb") as f:
        f.write(meta_page(0, 0))
        f.write(meta_page(1, 1))
        for pgno in range(2, next_pgno):
            f.write(pages[pgno])
    return path
