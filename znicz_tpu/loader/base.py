"""Loader base classes — the minibatch-serving contract.

The veles core loader is external to the reference repo; this implements the
contract observed at every use site (SURVEY.md §2.5): attributes
``minibatch_data/labels/indices/class/size/offset``, ``class_lengths``,
``total_samples``, ``last_minibatch``, ``epoch_ended``, ``epoch_number``,
``complete``; methods ``load_data``, ``create_minibatch_data``,
``fill_minibatch``.

Epoch semantics:
* One epoch serves every class segment with samples, in order
  TEST -> TRAIN -> VALID.  **Deliberate deviation** from the reference
  core's numeric order: serving VALID last is what the reference's own
  DecisionGD assumes at epoch end (decision.py:478-482 — "minibatch_class
  will be VALID if validation exists"), and measures validation *after*
  that epoch's training, which is the ML-standard reading.
* ``last_minibatch`` is true on each class segment's final minibatch;
  ``epoch_ended`` additionally on the epoch's final segment.
* ``epoch_number`` increments as the epoch wraps — after 3 full epochs
  ``epoch_number == 3`` (reference test contract,
  tests/functional/test_mnist_all2all.py:118).
* The TRAIN segment is reshuffled every epoch from the loader's PRNG
  stream (stream 2 — the functional-test harness seeds it separately).
* The tail minibatch of a segment keeps the buffer size constant
  (static shapes for XLA) and sets ``minibatch_size`` to the true count;
  consumers zero the padded tail (evaluator contract).
"""

import time

import numpy

from znicz_tpu.core.units import Unit
from znicz_tpu.core.memory import Array
from znicz_tpu.core.mutable import Bool
from znicz_tpu.core import faults
from znicz_tpu.core import profiler
from znicz_tpu.core import prng
from znicz_tpu.core import telemetry
from znicz_tpu.core.config import root

TEST, VALID, TRAIN = 0, 1, 2
CLASS_NAME = {TEST: "test", VALID: "validation", TRAIN: "train"}

#: serving order within one epoch (see module docstring)
SERVE_ORDER = (TEST, TRAIN, VALID)


class ILoader(object):
    """Marker interface (parity: veles.loader.ILoader)."""


class IFullBatchLoader(ILoader):
    pass


class UserLoaderRegistry(type):
    """Registry of loader classes by MAPPING name
    (reference: standard_workflow_base.py:113)."""

    loaders = {}

    def __init__(cls, name, bases, clsdict):
        super(UserLoaderRegistry, cls).__init__(name, bases, clsdict)
        mapping = clsdict.get("MAPPING", None)
        if mapping:
            UserLoaderRegistry.loaders[mapping] = cls

    @staticmethod
    def get_factory(name):
        try:
            kls = UserLoaderRegistry.loaders[name]
        except KeyError:
            raise KeyError(
                "Unknown loader %r; known: %s" % (
                    name, sorted(UserLoaderRegistry.loaders)))
        return kls


class Loader(Unit, metaclass=UserLoaderRegistry):
    """Serves minibatches; subclasses provide the data."""

    def __init__(self, workflow, **kwargs):
        super(Loader, self).__init__(workflow, **kwargs)
        self.max_minibatch_size = kwargs.get("minibatch_size", 100)
        self.prng = kwargs.get("prng", prng.get(2))
        self.shuffle_limit = kwargs.get(
            "shuffle_limit", numpy.iinfo(numpy.uint32).max)
        self.normalization_type = kwargs.get("normalization_type", "none")
        self.normalization_parameters = kwargs.get(
            "normalization_parameters", {})
        self.testing = kwargs.get("testing", False)

        self.class_lengths = [0, 0, 0]
        #: CONTRACT under skip_fill: on TRAIN minibatches in windowed
        #: fused mode the host fill is skipped, so minibatch_data /
        #: minibatch_labels (and minibatch_targets) retain the PREVIOUS
        #: fill's contents — only minibatch_indices / size / class /
        #: offsets are valid; units reading data or labels on TRAIN
        #: must link through the fused trainer's window stats instead
        self.minibatch_data = Array(name="minibatch_data")
        self.minibatch_labels = Array(name="minibatch_labels")
        self.minibatch_indices = Array(name="minibatch_indices")
        self.minibatch_size = 0
        self.minibatch_offset = 0
        self.minibatch_class = TRAIN
        self.last_minibatch = Bool(False)
        self.epoch_ended = Bool(False)
        self.epoch_number = 0
        self.complete = Bool(False)
        self.train_ended = Bool(False)
        #: windowed fused mode: the trainer consumes TRAIN minibatches as
        #: device gathers over the on-device dataset, so the host fill is
        #: skipped for them (minibatch_indices/labels flags still serve;
        #: VALID/TEST minibatches always fill)
        self.skip_fill = False
        #: bumped every time the TRAIN order actually reshuffles — the
        #: fused trainer's device-resident permuted dataset is
        #: rematerialized when this changes (per-epoch, not per-window)
        self.shuffle_serial = 0
        #: this minibatch's start offset WITHIN its class segment — for
        #: TRAIN, the row range [offset, offset+size) of the epoch's
        #: shuffled order (minibatches are contiguous slices of
        #: ``_indices[clazz]`` by construction, see run())
        self.minibatch_class_offset = 0
        self._indices = {}       # class -> index array into the dataset
        self._segment = 0        # position in the serving order
        self._offset_in_class = 0
        self._global_offset = 0
        #: snapshotted iteration state — with the PRNG states this makes
        #: resume-retrain exact (epoch position + the shuffled order)
        self.exports = ["epoch_number", "_segment", "_offset_in_class",
                        "_global_offset", "_indices", "shuffle_serial"]
        self.normalizer = None
        self._labels_mapping = {}

    # -- to be provided by subclasses ---------------------------------------
    def load_data(self):
        """Fill class_lengths and prepare the dataset."""
        raise NotImplementedError

    def create_minibatch_data(self):
        """Allocate minibatch_data for max_minibatch_size samples."""
        raise NotImplementedError

    def fill_minibatch(self):
        """Copy the samples at minibatch_indices into minibatch buffers."""
        raise NotImplementedError

    # -- common ------------------------------------------------------------
    #: optional hook called after load_data during initialize (reference:
    #: real_loader.on_initialized, standard_workflow_base.py:334-336)
    on_initialized = None

    @property
    def total_samples(self):
        return int(sum(self.class_lengths))

    @property
    def unique_labels_count(self):
        """Number of distinct labels — sets the softmax head width
        (reference standard_workflow_base.py:324-334)."""
        labels = getattr(self, "original_labels", None)
        if labels is not None and len(labels):
            return len(set(labels))
        raise AttributeError("loader cannot derive unique_labels_count")

    @property
    def effective_class_lengths(self):
        return self.class_lengths

    @property
    def labels_mapping(self):
        return self._labels_mapping

    @property
    def has_labels(self):
        """Whether the dataset carries labels (reference loader/base.py
        Loader.has_labels).  NOT derived from minibatch_labels — that
        buffer is always allocated; subclasses override from their actual
        label source (see FullBatchLoader)."""
        return bool(self._labels_mapping)

    @property
    def shuffled_indices(self):
        """Serving-order -> dataset-index permutation across the whole
        epoch (segments in SERVE_ORDER, matching minibatch_offset) — what
        result exporters need to write per-sample outputs in dataset
        order (reference loader exposes shuffled_indices)."""
        parts = [self._indices[c] for c in self._serve_order()
                 if c in self._indices and len(self._indices[c])]
        if not parts:
            return numpy.arange(0)
        return numpy.concatenate(parts)

    def _serve_order(self):
        return [c for c in SERVE_ORDER if self.class_lengths[c] > 0]

    def class_index_range(self, clazz):
        """[start, end) of this class inside the dataset's sample axis,
        assuming dataset layout [TEST | VALID | TRAIN] (numeric order)."""
        start = sum(self.class_lengths[:clazz])
        return start, start + self.class_lengths[clazz]

    def initialize(self, device=None, **kwargs):
        super(Loader, self).initialize(device=device, **kwargs)
        self.load_data()
        if self.total_samples == 0:
            raise ValueError("%s loaded zero samples" % self.name)
        if self.max_minibatch_size < 1:
            raise ValueError("minibatch_size must be >= 1")
        self.max_minibatch_size = min(self.max_minibatch_size,
                                      max(self.class_lengths))
        for clazz in range(3):
            start, end = self.class_index_range(clazz)
            self._indices[clazz] = numpy.arange(start, end,
                                                dtype=numpy.int32)
        self._shuffle()
        self.create_minibatch_data()
        if not self.minibatch_data:
            raise ValueError("create_minibatch_data did not allocate "
                             "minibatch_data")
        if not self.minibatch_labels:
            self.minibatch_labels.reset(numpy.zeros(
                self.max_minibatch_size, dtype=numpy.int32))
        self.minibatch_indices.reset(numpy.zeros(
            self.max_minibatch_size, dtype=numpy.int32))
        self._segment = 0
        self._offset_in_class = 0
        self._global_offset = 0
        if self.on_initialized is not None:
            self.on_initialized()
        self.info(
            "%s: %d samples (test %d, validation %d, train %d), mb=%d",
            self.name, self.total_samples, self.class_lengths[TEST],
            self.class_lengths[VALID], self.class_lengths[TRAIN],
            self.max_minibatch_size)

    @property
    def train_indices(self):
        """The epoch's shuffled TRAIN order (global dataset indices) —
        the permutation the fused sliced-window path materializes on
        device once per :attr:`shuffle_serial` change."""
        return self._indices[TRAIN]

    def _shuffle(self):
        if self.epoch_number < self.shuffle_limit:
            self.prng.shuffle(self._indices[TRAIN])
            self.shuffle_serial += 1

    def run(self):
        # step-time breakdown: the whole serve (index walk + fill +
        # epoch bookkeeping) is this minibatch's data-wait share
        # (core/profiler.py; disabled cost is this one predicate)
        prof_t0 = time.perf_counter() if profiler.enabled() else None
        order = self._serve_order()
        clazz = order[self._segment]
        length = self.class_lengths[clazz]
        off = self._offset_in_class
        n = min(self.max_minibatch_size, length - off)
        sel = self._indices[clazz][off:off + n]

        self.minibatch_class = clazz
        self.minibatch_size = int(n)
        self.minibatch_class_offset = int(off)
        self._global_offset += n
        self.minibatch_offset = self._global_offset

        idx = self.minibatch_indices.mem
        idx[:n] = sel
        idx[n:] = -1
        traced = telemetry.enabled()
        if traced:
            telemetry.counter("loader.minibatches").inc()
        if not (self.skip_fill and clazz == TRAIN):
            if traced:
                with telemetry.span("loader.fill", size=int(n),
                                    clazz=CLASS_NAME[clazz]):
                    self._fill_resilient()
            else:
                self._fill_resilient()
            if n < self.max_minibatch_size:
                self.minibatch_labels.map_write()
                self.minibatch_labels.mem[n:] = -1
                targets = getattr(self, "minibatch_targets", None)
                if targets:
                    targets.map_write()
                    targets.mem[n:] = 0

        seg_done = off + n >= length
        epoch_done = seg_done and self._segment == len(order) - 1
        self.last_minibatch <<= seg_done
        self.epoch_ended <<= epoch_done
        self.train_ended <<= seg_done and clazz == TRAIN

        if epoch_done:
            self.epoch_number += 1
            if telemetry.enabled():
                telemetry.counter("loader.epochs").inc()
                telemetry.instant("loader.epoch_end",
                                  epoch=self.epoch_number)
            if prof_t0 is not None:
                # epoch-boundary ledger leak check (core/profiler.py)
                profiler.epoch_check(self.epoch_number)
            self._segment = 0
            self._offset_in_class = 0
            self._global_offset = 0
            self._shuffle()
        elif seg_done:
            self._segment += 1
            self._offset_in_class = 0
        else:
            self._offset_in_class = off + n
        if prof_t0 is not None:
            profiler.note_data_wait(time.perf_counter() - prof_t0)

    def _serve_fill(self):
        """One fill attempt, with the ``loader.fill`` fault-injection
        site INSIDE the retried region — an injected (or organic)
        transient I/O error is recovered by the retry below exactly
        like a flaky disk read would be; ``stall`` faults model a slow
        source and simply delay the fill."""
        if faults.enabled():
            faults.check("loader.fill")
        self.fill_minibatch()

    def _fill_resilient(self):
        """``fill_minibatch`` with bounded exponential-backoff retry on
        TRANSIENT failures (core/faults.py classifier + the
        ``root.common.retry`` policy).  A loader that raises a terminal
        error still fails the run; a flaky one costs a logged retry
        instead of an epoch of device-resident state."""
        faults.retry_call(self._serve_fill, "loader.fill")

    def fill_window_slot(self, x_out=None, labels_out=None,
                         targets_out=None, indices_out=None):
        """Overlap-aware window collection: copy the just-served
        minibatch's host buffers straight into caller-owned staging rows
        (the fused trainer's pipelined window assembly,
        units/fused_trainer.py).

        The caller owns the staging lifetime — the trainer rotates
        ``pipeline_depth + 1`` buffer sets so a row is never rewritten
        while the window it was dispatched with may still be reading it
        (``jax.device_put`` may alias aligned host buffers on the CPU
        backend).  ONE copy per minibatch replaces the previous
        per-step ``numpy.array`` copy + ``numpy.stack`` re-copy, and the
        loader's own buffers are free for the next ``run()`` the moment
        this returns — which is what lets collection of window K+1
        overlap the device executing window K.  Padded tail rows carry
        whatever the loader's fill discipline put there (labels -1,
        targets 0 — ``run()``); ``indices_out`` rows are valid under
        ``skip_fill`` too (only index/size/class bookkeeping serves
        then).

        Destination views may carry a PER-SHARD staging layout — under a
        data-parallel mesh the trainer's staging ring is shard-major
        ``(S, B // S, ...)`` so every shard's rows stay one contiguous
        host block for ``device_put`` — so each source reshapes to the
        destination's shape (a view of the contiguous minibatch buffer;
        still exactly one copy per minibatch)."""
        if x_out is not None:
            self.minibatch_data.map_read()
            x_out[...] = self.minibatch_data.mem.reshape(x_out.shape)
        if labels_out is not None:
            self.minibatch_labels.map_read()
            labels_out[...] = self.minibatch_labels.mem.reshape(
                labels_out.shape)
        if targets_out is not None:
            targets = self.minibatch_targets  # MSE mixin contract
            targets.map_read()
            targets_out[...] = targets.mem.reshape(targets_out.shape)
        if indices_out is not None:
            indices_out[...] = self.minibatch_indices.mem.reshape(
                indices_out.shape)

    # -- master-slave stubs (kept for protocol parity) ----------------------
    def generate_data_for_slave(self, slave=None):
        return None

    def apply_data_from_master(self, data):
        pass


class FullBatchLoader(Loader):
    """Loader keeping the whole dataset in memory
    (contract: original_data/original_labels + normalization)."""

    def __init__(self, workflow, **kwargs):
        super(FullBatchLoader, self).__init__(workflow, **kwargs)
        self.original_data = Array(name="original_data")
        self._original_labels = []
        #: cached numpy copy of the label list, rebuilt in initialize
        #: (after load_data) and when the list LENGTH changes; a loader
        #: that relabels IN PLACE mid-run with the same length must
        #: clear this cache itself
        self._labels_array = None
        self.force_numpy = kwargs.get("force_numpy", False)

    @property
    def original_labels(self):
        return self._original_labels

    @property
    def has_labels(self):
        return bool(self._original_labels) or bool(self._labels_mapping)

    def create_minibatch_data(self):
        sample_shape = self.original_data.shape[1:]
        # side-effect-free lookup (plain getattr would auto-vivify an empty
        # Config node into the global config)
        dtype = root.common.engine.get("precision_dtype")
        if dtype is None:
            dtype = self.original_data.dtype
        self.minibatch_data.reset(numpy.zeros(
            (self.max_minibatch_size,) + tuple(sample_shape), dtype=dtype))

    def initialize(self, device=None, **kwargs):
        # load_data just (re)filled the labels — drop any stale cache
        # (re-initialize after an in-place relabel must not serve the
        # old values, ADVICE r4)
        self._labels_array = None
        super(FullBatchLoader, self).initialize(device=device, **kwargs)
        self._apply_normalization()

    def _fit_and_normalize(self, array, norm_type, norm_params):
        """Fit a normalizer on the TRAIN slice of ``array`` and normalize
        the whole array in place (reference semantics: normalizer analyzed
        on the training set, applied everywhere).  Returns the
        normalizer."""
        from znicz_tpu.core import normalization
        if norm_type in (None, "none"):
            return normalization.NoneNormalizer()
        normalizer = normalization.create(norm_type, **norm_params)
        data = array.mem
        flat = data.reshape(data.shape[0], -1)
        start, end = self.class_index_range(TRAIN)
        fit_on = flat[start:end] if end > start else flat
        normalizer.analyze(fit_on)
        array.map_write()
        normalizer.normalize(flat)
        return normalizer

    def _apply_normalization(self):
        self.normalizer = self._fit_and_normalize(
            self.original_data, self.normalization_type,
            self.normalization_parameters)

    def fill_minibatch(self):
        idx = self.minibatch_indices.mem
        n = self.minibatch_size
        self.minibatch_data.map_invalidate()
        self.minibatch_labels.map_write()
        data = self.original_data.mem
        sel = idx[:n]
        # one fancy-index copy, not a per-sample python loop (the hot
        # host-side path of every epoch)
        self.minibatch_data.mem[:n] = data[sel]
        if self._original_labels:
            labels = self._labels_array
            if labels is None or len(labels) != len(self._original_labels):
                labels = self._labels_array = numpy.asarray(
                    self._original_labels)
            self.minibatch_labels.mem[:n] = labels[sel]


class LoaderMSEMixin(object):
    """Per-sample regression targets — the contract EvaluatorMSE trains
    against (reference veles.loader.LoaderMSEMixin, SURVEY.md §2.9;
    used by Kanji/Approximator, evaluator.py:334-556).

    Adds ``minibatch_targets`` (wired to the evaluator's ``target`` by
    StandardWorkflow.link_evaluator), optional ``class_targets`` (enables
    the nearest-class-target error metric), and a targets normalizer
    separate from the data normalizer.
    """

    def __init__(self, workflow, **kwargs):
        super(LoaderMSEMixin, self).__init__(workflow, **kwargs)
        self.minibatch_targets = Array(name="minibatch_targets")
        self.targets_normalization_type = kwargs.get(
            "targets_normalization_type", "none")
        self.targets_normalization_parameters = kwargs.get(
            "targets_normalization_parameters", {})
        self.target_normalizer = None
        self.class_targets = None

    @property
    def targets_shape(self):
        return tuple(self.minibatch_targets.shape[1:])


class FullBatchLoaderMSEMixin(LoaderMSEMixin):
    """FullBatch variant: whole ``original_targets`` in memory, sliced per
    minibatch alongside the data (reference FullBatchLoaderMSEMixin)."""

    def __init__(self, workflow, **kwargs):
        super(FullBatchLoaderMSEMixin, self).__init__(workflow, **kwargs)
        self.original_targets = Array(name="original_targets")

    def create_minibatch_data(self):
        super(FullBatchLoaderMSEMixin, self).create_minibatch_data()
        if not self.original_targets:
            raise ValueError(
                "%s.load_data must fill original_targets" % self.name)
        self.minibatch_targets.reset(numpy.zeros(
            (self.max_minibatch_size,) +
            tuple(self.original_targets.shape[1:]),
            dtype=self.minibatch_data.dtype))

    def initialize(self, device=None, **kwargs):
        super(FullBatchLoaderMSEMixin, self).initialize(
            device=device, **kwargs)
        self._apply_target_normalization()

    def _apply_target_normalization(self):
        self.target_normalizer = self._fit_and_normalize(
            self.original_targets, self.targets_normalization_type,
            self.targets_normalization_parameters)

    def fill_minibatch(self):
        super(FullBatchLoaderMSEMixin, self).fill_minibatch()
        n = self.minibatch_size
        idx = self.minibatch_indices.mem[:n]
        self.minibatch_targets.map_invalidate()
        self.minibatch_targets.mem[:n] = self.original_targets.mem[idx]


class FullBatchLoaderMSE(FullBatchLoaderMSEMixin, FullBatchLoader):
    """Convenience concrete base for full-batch MSE loaders."""
