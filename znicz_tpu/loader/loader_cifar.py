"""CIFAR-10 loader.

TPU-era equivalent of the reference CifarLoader (samples/CIFAR10/cifar.py:
47-66) — reads the python pickle batches from ``cifar-10-batches-py``;
data reshaped CHW -> NHWC (our conv layout).  Layout: [VALID test_batch
10000 | TRAIN data_batch_1..5 50000].

Zero-egress deviation (like MnistLoader): ``synthetic="auto"`` falls back
to a deterministic 32x32x3 class-prototype dataset when the pickles are
absent.
"""

import os
import pickle

import numpy

from znicz_tpu.core.config import root
from znicz_tpu.loader.base import (
    FullBatchLoader, TEST, VALID, TRAIN)


class CifarLoader(FullBatchLoader):
    MAPPING = "cifar_loader"

    def __init__(self, workflow, **kwargs):
        super(CifarLoader, self).__init__(workflow, **kwargs)
        self.data_path = kwargs.get(
            "data_path", os.path.join(root.common.dirs.datasets,
                                      "cifar-10-batches-py"))
        self.synthetic = kwargs.get("synthetic", "auto")
        self.synthetic_train = kwargs.get("synthetic_train", 1000)
        self.synthetic_valid = kwargs.get("synthetic_valid", 250)

    def _batch_files(self):
        train = [os.path.join(self.data_path, "data_batch_%d" % i)
                 for i in range(1, 6)]
        test = os.path.join(self.data_path, "test_batch")
        return train, test

    def _real_files_present(self):
        train, test = self._batch_files()
        return all(os.access(f, os.R_OK) for f in train + [test])

    @staticmethod
    def _read_batch(path):
        with open(path, "rb") as fin:
            d = pickle.load(fin, encoding="bytes")
        data = d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        labels = numpy.asarray(d[b"labels"], dtype=numpy.int32)
        return data.astype(numpy.float32), labels

    def _load_real(self):
        train_files, test_file = self._batch_files()
        self.class_lengths[TEST] = 0
        self.class_lengths[VALID] = 10000
        self.class_lengths[TRAIN] = 50000
        data = numpy.zeros((60000, 32, 32, 3), dtype=numpy.float32)
        labels = numpy.zeros(60000, dtype=numpy.int32)
        data[:10000], labels[:10000] = self._read_batch(test_file)
        for i, f in enumerate(train_files):
            sl = slice(10000 + i * 10000, 10000 + (i + 1) * 10000)
            data[sl], labels[sl] = self._read_batch(f)
        self.original_data.reset(data)
        self._original_labels[:] = labels.tolist()

    def _load_synthetic(self):
        n_valid, n_train = self.synthetic_valid, self.synthetic_train
        total = n_valid + n_train
        self.class_lengths[TEST] = 0
        self.class_lengths[VALID] = n_valid
        self.class_lengths[TRAIN] = n_train
        r = numpy.random.RandomState(20260730)
        protos = r.uniform(0, 255, (10, 32, 32, 3)).astype(numpy.float32)
        for _ in range(2):
            protos = (protos +
                      numpy.roll(protos, 1, 1) + numpy.roll(protos, -1, 1) +
                      numpy.roll(protos, 1, 2) + numpy.roll(protos, -1, 2)
                      ) / 5.0
        labels = r.randint(0, 10, total).astype(numpy.int32)
        noise = r.normal(0, 32.0, (total, 32, 32, 3)).astype(numpy.float32)
        self.original_data.reset(numpy.clip(protos[labels] + noise, 0, 255))
        self._original_labels[:] = labels.tolist()

    def load_data(self):
        if self._real_files_present() and self.synthetic is not True:
            self.info("Loading CIFAR-10 pickles from %s", self.data_path)
            self._load_real()
        elif self.synthetic in (True, "auto"):
            self.info("CIFAR-10 absent (zero-egress environment); using "
                      "the deterministic synthetic fallback "
                      "(%d train / %d validation)",
                      self.synthetic_train, self.synthetic_valid)
            self._load_synthetic()
        else:
            raise OSError("No CIFAR-10 data in %s and synthetic fallback "
                          "disabled" % self.data_path)
