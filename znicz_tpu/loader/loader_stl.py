"""STL-10 dataset loader.

Parity target: reference loader/loader_stl.py:47-116 (``MAPPING =
"full_batch_stl_10"``): binary files ``train_X.bin`` / ``train_y.bin`` /
``test_X.bin`` / ``test_y.bin`` + ``class_names.txt`` in ``directory``;
96x96x3 images stored channel-planar (CHW) uint8, labels 1-based; the
reference serves its test split as VALID.  Published baseline: 35.10% val
err (BASELINE.md, tests/research/Stl10).
"""

import os

import numpy

from znicz_tpu.loader.base import VALID, TRAIN
from znicz_tpu.loader.image import FullBatchImageLoader, IImageLoader


class STL10FullBatchLoader(FullBatchImageLoader, IImageLoader):
    MAPPING = "full_batch_stl_10"
    SIZE = (96, 96)
    SQUARE = SIZE[0] * SIZE[1] * 3

    #: which on-disk split serves which class (reference maps test->VALID)
    FILES = {TRAIN: ("train_X.bin", "train_y.bin"),
             VALID: ("test_X.bin", "test_y.bin")}

    def __init__(self, workflow, **kwargs):
        super(STL10FullBatchLoader, self).__init__(workflow, **kwargs)
        self.directory = kwargs["directory"]
        self._bytes = {}
        self._labels = {}
        self._class_names = []

    def _load_files(self):
        if self._bytes:
            return
        if not os.path.isdir(self.directory):
            raise ValueError('"%s" must be a directory' % self.directory)
        with open(os.path.join(self.directory, "class_names.txt")) as fin:
            self._class_names = fin.read().split()
        for clazz, (xfile, yfile) in self.FILES.items():
            with open(os.path.join(self.directory, xfile), "rb") as f:
                self._bytes[clazz] = f.read()
            self._labels[clazz] = numpy.fromfile(
                os.path.join(self.directory, yfile), dtype=numpy.uint8)
            if len(self._bytes[clazz]) // self.SQUARE != \
                    len(self._labels[clazz]):
                raise ValueError(
                    "%s: %d images != %d labels" % (
                        xfile, len(self._bytes[clazz]) // self.SQUARE,
                        len(self._labels[clazz])))

    def get_keys(self, index):
        if index not in self.FILES:
            return []
        self._load_files()
        return [(index, i)
                for i in range(len(self._bytes[index]) // self.SQUARE)]

    def get_image_label(self, key):
        # labels are 1-based indices into class_names.txt
        return self._class_names[self._labels[key[0]][key[1]] - 1]

    def get_image_info(self, key):
        return self.SIZE, "RGB"

    def get_image_data(self, key):
        clazz, i = key
        raw = self._bytes[clazz][i * self.SQUARE:(i + 1) * self.SQUARE]
        # plain CHW -> HWC, matching the reference exactly
        # (loader_stl.py:107-110; the official files are column-major per
        # plane, so like the reference this yields x/y-swapped images —
        # harmless for training, and parity wins)
        return numpy.transpose(
            numpy.frombuffer(raw, dtype=numpy.uint8).reshape(
                (3,) + self.SIZE), (1, 2, 0))
