"""ImageNet preprocessed-dataset loader.

Parity target: reference loader/imagenet_loader.py:54-208 (``MAPPING =
"imagenet_loader_base"``): a flat ``samples.dat`` of uint8
(sy, sx, channels) records, ``original_labels_filename`` pickle of
(text_label, int_label) pairs, ``count_samples_filename`` JSON
{"test": n, "val": n, "train": n}, and ``matrixes_filename`` pickle of
[mean, rdisp] arrays consumed by MeanDispNormalizer.  Streams minibatches
straight off the file — the set never fits in host RAM.
"""

import json
import os
import pickle

import numpy

from znicz_tpu.core.memory import Array
from znicz_tpu.loader.base import Loader, ILoader, TEST, VALID, TRAIN


class ImagenetLoaderBase(Loader, ILoader):
    MAPPING = "imagenet_loader_base"

    def __init__(self, workflow, **kwargs):
        super(ImagenetLoaderBase, self).__init__(workflow, **kwargs)
        self.mean = Array(name="mean")
        self.rdisp = Array(name="rdisp")
        self.sx = kwargs.get("sx", 256)
        self.sy = kwargs.get("sy", 256)
        self.channels = kwargs.get("channels", 3)
        self.original_labels_filename = kwargs.get(
            "original_labels_filename")
        self.count_samples_filename = kwargs.get("count_samples_filename")
        self.matrixes_filename = kwargs.get("matrixes_filename")
        self.samples_filename = kwargs.get("samples_filename")
        self.class_keys_path = kwargs.get("class_keys_path")
        self.final_sy = self.sy
        self.final_sx = self.sx
        self.class_keys = None
        self.has_mean_file = False
        self._file_samples = None
        self._original_labels_list = []
        self._int_labels = None

        if self.class_keys_path is not None:
            with open(self.class_keys_path) as fin:
                self.class_keys = json.load(fin)

    @property
    def sample_bytes(self):
        return self.sy * self.sx * self.channels

    @property
    def original_labels(self):
        return self._int_labels if self._int_labels is not None else []

    def _require(self, path, what):
        if path is None or not os.path.exists(path):
            raise OSError(
                "%s %s does not exist or None. Generate it with the "
                "dataset preparation tooling first." % (what, path))

    def load_data(self):
        self._require(self.original_labels_filename,
                      "original_labels_filename")
        self._require(self.count_samples_filename,
                      "count_samples_filename")
        self._require(self.samples_filename, "samples_filename")

        with open(self.original_labels_filename, "rb") as fin:
            for txt_lbl, int_lbl in pickle.load(fin):
                self._original_labels_list.append(txt_lbl)
                self._labels_mapping[txt_lbl] = int(int_lbl)

        with open(self.count_samples_filename) as fin:
            set_type = {"test": TEST, "val": VALID, "train": TRAIN}
            for key, value in json.load(fin).items():
                self.class_lengths[set_type[key]] = value

        if self.total_samples != len(self._original_labels_list):
            raise ValueError(
                "number of labels (%d) mismatches sum of class lengths "
                "(%d)" % (len(self._original_labels_list),
                          self.total_samples))
        self._int_labels = numpy.array(
            [self._labels_mapping[l] for l in self._original_labels_list],
            dtype=numpy.int32)

        self._file_samples = open(self.samples_filename, "rb")
        n = self._file_samples.seek(0, 2) // self.sample_bytes
        if n != len(self._original_labels_list):
            raise ValueError(
                "wrong samples.dat size: %d samples != %d labels"
                % (n, len(self._original_labels_list)))
        if self.matrixes_filename is not None:
            self.load_mean()

    def load_mean(self):
        """[mean, rdisp] arrays for MeanDispNormalizer
        (reference imagenet_loader.py:148-166)."""
        self._require(self.matrixes_filename, "matrixes_filename")
        with open(self.matrixes_filename, "rb") as fin:
            matrixes = pickle.load(fin)
        self.mean.reset(numpy.asarray(matrixes[0]))
        self.rdisp.reset(numpy.asarray(matrixes[1], dtype=numpy.float32))
        if numpy.count_nonzero(numpy.isnan(self.rdisp.mem)):
            raise ValueError("rdisp matrix has NaNs")
        if numpy.count_nonzero(numpy.isinf(self.rdisp.mem)):
            raise ValueError("rdisp matrix has Infs")
        if self.mean.shape != self.rdisp.shape:
            raise ValueError("mean.shape != rdisp.shape")
        if self.mean.shape[0] != self.sy or self.mean.shape[1] != self.sx:
            raise ValueError("mean.shape != (%d, %d)" % (self.sy, self.sx))
        self.has_mean_file = True

    def create_minibatch_data(self):
        self.minibatch_data.reset(numpy.zeros(
            (self.max_minibatch_size, self.final_sy, self.final_sx,
             self.channels), dtype=numpy.uint8))

    def fill_minibatch(self):
        idx = self.minibatch_indices.mem
        self.minibatch_data.map_invalidate()
        self.minibatch_labels.map_write()
        for i in range(self.minibatch_size):
            sample_index = int(idx[i])
            self._file_samples.seek(sample_index * self.sample_bytes)
            raw = self._file_samples.read(self.sample_bytes)
            self.minibatch_data.mem[i] = numpy.frombuffer(
                raw, dtype=numpy.uint8).reshape(
                    self.sy, self.sx, self.channels)
            self.minibatch_labels.mem[i] = self._int_labels[sample_index]

    def stop(self):
        super(ImagenetLoaderBase, self).stop()
        if self._file_samples is not None:
            self._file_samples.close()
            self._file_samples = None
