"""Wine dataset loader (reference loader/loader_wine.py:44-66).

Contract parity: reads ``dataset_file`` CSV rows of ``label,feat...``
(labels 1-based in the file, stored 0-based), pointwise normalization,
all samples to TRAIN when training / to TEST when testing.  If the file is
absent, materializes it from sklearn's bundled copy of the same UCI Wine
data (the reference downloads it over HTTP, which a zero-egress box can't).
"""

import os

import numpy

from znicz_tpu.core.config import root
from znicz_tpu.loader.base import (
    FullBatchLoader, IFullBatchLoader, TEST, VALID, TRAIN)


class WineLoader(FullBatchLoader, IFullBatchLoader):
    MAPPING = "wine_loader"

    def __init__(self, workflow, **kwargs):
        kwargs["normalization_type"] = "pointwise"
        super(WineLoader, self).__init__(workflow, **kwargs)
        self.dataset_file = kwargs.get("dataset_file", os.path.join(
            root.common.dirs.datasets, "wine", "wine.txt"))

    def _materialize_dataset(self):
        from sklearn.datasets import load_wine
        wine = load_wine()
        os.makedirs(os.path.dirname(self.dataset_file), exist_ok=True)
        rows = numpy.hstack([(wine.target + 1)[:, None].astype(numpy.float32),
                             wine.data.astype(numpy.float32)])
        numpy.savetxt(self.dataset_file, rows, delimiter=",", fmt="%.6g")

    def load_data(self):
        if not os.path.exists(self.dataset_file):
            self._materialize_dataset()
        arr = numpy.loadtxt(self.dataset_file, delimiter=",",
                            dtype=numpy.float32)
        self.original_data.mem = arr[:, 1:].copy()
        self.original_labels[:] = (
            arr[:, 0].ravel().astype(numpy.int32) - 1)
        if not self.testing:
            self.class_lengths[TEST] = self.class_lengths[VALID] = 0
            self.class_lengths[TRAIN] = self.original_data.shape[0]
        else:
            self.class_lengths[TEST] = self.original_data.shape[0]
            self.class_lengths[VALID] = self.class_lengths[TRAIN] = 0
