"""Pickled-image full-batch loader.

TPU-era equivalent of the veles-core ``loader.PicklesImageFullBatchLoader``
(the base the reference CifarLoader extends, samples/CIFAR10/cifar.py:
47-66): each split is a list of pickle files carrying image arrays —
either the CIFAR batch dict layout ({b"data": (N, rows) uint8,
b"labels": [...]}) or a raw ndarray of images (+ optional separate
labels key).
"""

import pickle

import numpy

from znicz_tpu.loader.base import (FullBatchLoader, IFullBatchLoader,
                                   TEST, VALID, TRAIN)


class PicklesImageFullBatchLoader(FullBatchLoader, IFullBatchLoader):
    """kwargs: ``test_pickles`` / ``validation_pickles`` /
    ``train_pickles`` (lists of file paths), ``color_space`` (metadata),
    optional ``image_shape`` to reshape flat rows (default: CIFAR-style
    (3, 32, 32) CHW, transposed to HWC)."""

    MAPPING = "full_batch_pickles_image"

    def __init__(self, workflow, **kwargs):
        super(PicklesImageFullBatchLoader, self).__init__(workflow,
                                                          **kwargs)
        self.test_pickles = list(kwargs.get("test_pickles", ()))
        self.validation_pickles = list(
            kwargs.get("validation_pickles", ()))
        self.train_pickles = list(kwargs.get("train_pickles", ()))
        self.color_space = kwargs.get("color_space", "RGB")
        self.image_shape = kwargs.get("image_shape", (3, 32, 32))

    def reshape(self, data):
        """Flat rows -> image batch.  CHW pickle layouts transpose to
        the framework's NHWC."""
        shape = tuple(self.image_shape)
        data = data.reshape((-1,) + shape)
        if len(shape) == 3 and shape[0] in (1, 3, 4) and \
                shape[0] < shape[2]:
            data = data.transpose(0, 2, 3, 1)
        return data

    def _read_pickle(self, path):
        with open(path, "rb") as fin:
            d = pickle.load(fin, encoding="bytes")
        if isinstance(d, dict):
            data = d.get(b"data", d.get("data"))
            labels = d.get(b"labels", d.get("labels"))
        else:
            data, labels = d, None
        data = numpy.asarray(data)
        if data.ndim == 2:
            data = self.reshape(data)
        if labels is not None:
            labels = numpy.asarray(labels, dtype=numpy.int32)
        return data.astype(numpy.float32), labels

    def load_data(self):
        datas = []
        del self._original_labels[:]
        for clazz, files in ((TEST, self.test_pickles),
                             (VALID, self.validation_pickles),
                             (TRAIN, self.train_pickles)):
            count = 0
            # per-file fallback labels restart PER SPLIT so the same
            # file position means the same class in train and valid
            next_label = 0
            for path in files:
                data, labels = self._read_pickle(path)
                datas.append(data)
                count += data.shape[0]
                if labels is not None:
                    self._original_labels.extend(int(v) for v in labels)
                else:
                    # unlabeled pickle: one label per FILE (the
                    # reference's per-pickle class convention)
                    self._original_labels.extend(
                        [next_label] * data.shape[0])
                    next_label += 1
            self.class_lengths[clazz] = count
        if not datas:
            raise ValueError("no pickles configured")
        self.original_data.reset(numpy.concatenate(datas, axis=0))
