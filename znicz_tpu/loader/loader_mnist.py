"""MNIST loader.

TPU-era equivalent of reference samples/MNIST/loader_mnist.py (186 LoC) —
parses the original IDX files (magic 2049/2051, big-endian headers) from
``data_path``.  Dataset layout: [VALID 10000 | TRAIN 60000]
(loader_mnist.py:163-183); pixels as float32, normalized by the loader's
normalizer.

**Deviation for the zero-egress environment:** the reference downloads from
yann.lecun.com when files are missing (loader_mnist.py:77-107).  Here,
``synthetic="auto"`` (default) falls back to a deterministic synthetic
MNIST-like dataset — per-class prototype blobs + noise, drawn from a
fixed seed so every run sees the same data — sized by
``synthetic_train``/``synthetic_valid``.  Set
``synthetic=False`` to require the real files, ``synthetic=True`` to force
the fallback.
"""

import os
import struct

import numpy

from znicz_tpu.core.config import root
from znicz_tpu.loader.base import (
    FullBatchLoader, TEST, VALID, TRAIN)


class MnistLoader(FullBatchLoader):
    MAPPING = "mnist_loader"

    TEST_IMAGES = "t10k-images.idx3-ubyte"
    TEST_LABELS = "t10k-labels.idx1-ubyte"
    TRAIN_IMAGES = "train-images.idx3-ubyte"
    TRAIN_LABELS = "train-labels.idx1-ubyte"

    def __init__(self, workflow, **kwargs):
        super(MnistLoader, self).__init__(workflow, **kwargs)
        self.data_path = kwargs.get(
            "data_path", os.path.join(root.common.dirs.datasets, "MNIST"))
        self.synthetic = kwargs.get("synthetic", "auto")
        self.synthetic_train = kwargs.get("synthetic_train", 2000)
        self.synthetic_valid = kwargs.get("synthetic_valid", 500)

    # -- IDX parsing (reference loader_mnist.py:109-160) --------------------
    def _load_idx_labels(self, path, count):
        with open(path, "rb") as fin:
            header, = struct.unpack(">i", fin.read(4))
            if header != 2049:
                raise ValueError("Wrong header in %s" % path)
            n_labels, = struct.unpack(">i", fin.read(4))
            if n_labels != count:
                raise ValueError("Wrong number of labels in %s" % path)
            arr = numpy.frombuffer(fin.read(n_labels), dtype=numpy.uint8)
            if len(arr) != n_labels:
                raise ValueError("EOF while reading labels from %s" % path)
        return arr.astype(numpy.int32)

    def _load_idx_images(self, path, count):
        with open(path, "rb") as fin:
            header, = struct.unpack(">i", fin.read(4))
            if header != 2051:
                raise ValueError("Wrong header in %s" % path)
            n_images, = struct.unpack(">i", fin.read(4))
            if n_images != count:
                raise ValueError("Wrong number of images in %s" % path)
            n_rows, n_cols = struct.unpack(">2i", fin.read(8))
            if n_rows != 28 or n_cols != 28:
                raise ValueError("Images in %s should be 28x28" % path)
            pixels = numpy.frombuffer(
                fin.read(n_images * n_rows * n_cols), dtype=numpy.uint8)
            if len(pixels) != n_images * n_rows * n_cols:
                raise ValueError("EOF while reading images from %s" % path)
        return pixels.astype(numpy.float32).reshape(n_images, 28, 28)

    def _real_files_present(self):
        return all(os.access(os.path.join(self.data_path, f), os.R_OK)
                   for f in (self.TEST_IMAGES, self.TEST_LABELS,
                             self.TRAIN_IMAGES, self.TRAIN_LABELS))

    def _load_real(self):
        self.class_lengths[TEST] = 0
        self.class_lengths[VALID] = 10000
        self.class_lengths[TRAIN] = 60000
        data = numpy.zeros((70000, 28, 28), dtype=numpy.float32)
        labels = numpy.zeros(70000, dtype=numpy.int32)
        labels[:10000] = self._load_idx_labels(
            os.path.join(self.data_path, self.TEST_LABELS), 10000)
        data[:10000] = self._load_idx_images(
            os.path.join(self.data_path, self.TEST_IMAGES), 10000)
        labels[10000:] = self._load_idx_labels(
            os.path.join(self.data_path, self.TRAIN_LABELS), 60000)
        data[10000:] = self._load_idx_images(
            os.path.join(self.data_path, self.TRAIN_IMAGES), 60000)
        self.original_data.reset(data)
        self._original_labels[:] = labels.tolist()

    def _load_synthetic(self):
        """Deterministic MNIST-like set: 10 class-prototype blobs + noise."""
        n_valid, n_train = self.synthetic_valid, self.synthetic_train
        total = n_valid + n_train
        self.class_lengths[TEST] = 0
        self.class_lengths[VALID] = n_valid
        self.class_lengths[TRAIN] = n_train
        r = numpy.random.RandomState(20260729)
        protos = r.uniform(0, 255, (10, 28, 28)).astype(numpy.float32)
        # smooth the prototypes so they have digit-like large-scale structure
        for _ in range(2):
            protos = (protos +
                      numpy.roll(protos, 1, 1) + numpy.roll(protos, -1, 1) +
                      numpy.roll(protos, 1, 2) + numpy.roll(protos, -1, 2)
                      ) / 5.0
        labels = r.randint(0, 10, total).astype(numpy.int32)
        noise = r.normal(0, 32.0, (total, 28, 28)).astype(numpy.float32)
        data = numpy.clip(protos[labels] + noise, 0, 255)
        self.original_data.reset(data)
        self._original_labels[:] = labels.tolist()

    def load_data(self):
        if self._real_files_present() and self.synthetic is not True:
            self.info("Loading original MNIST files from %s", self.data_path)
            self._load_real()
        elif self.synthetic in (True, "auto"):
            self.info("MNIST files absent (zero-egress environment); "
                      "using the deterministic synthetic fallback "
                      "(%d train / %d validation)",
                      self.synthetic_train, self.synthetic_valid)
            self._load_synthetic()
        else:
            raise OSError(
                "No MNIST data in %s and synthetic fallback disabled; "
                "download the IDX files manually" % self.data_path)
