"""Image loaders with per-label target images (the Kanji pattern).

Parity target: the reference's ``full_batch_auto_label_file_image_mse``
loader (samples/Kanji/kanji_config.py:55 — data images labeled by
directory, one target image per label, MSE objective against the label's
target; ``class_targets`` enables the nearest-target classification
metric, evaluator.py:334-556).
"""

import os

import numpy

from znicz_tpu.core.memory import Array
from znicz_tpu.loader.base import FullBatchLoaderMSEMixin, TEST, VALID, TRAIN
from znicz_tpu.loader.image import (
    FullBatchImageLoader, AutoLabelFileImageLoader, IImageLoader)


class FullBatchImageLoaderMSE(FullBatchLoaderMSEMixin, FullBatchImageLoader):
    """Full-batch image loader whose targets are per-label images.

    ``target_paths`` directories hold one image per label, either named
    ``<label>.<ext>`` or inside a ``<label>/`` subdirectory;
    ``targets_shape`` optionally rescales them.
    """

    MAPPING = None
    hide_from_registry = True

    def __init__(self, workflow, **kwargs):
        super(FullBatchImageLoaderMSE, self).__init__(workflow, **kwargs)
        self.target_paths = kwargs.get("target_paths") or []
        if isinstance(self.target_paths, str):
            self.target_paths = [self.target_paths]
        self.targets_scale = kwargs.get("targets_shape")
        self.class_targets = Array(name="class_targets")
        self._target_by_label = {}

    def _load_targets(self):
        exts = AutoLabelFileImageLoader.EXTENSIONS
        for base in self.target_paths:
            for dirpath, _, files in sorted(os.walk(base)):
                for name in sorted(files):
                    stem, ext = os.path.splitext(name)
                    if ext.lower() not in exts:
                        continue
                    label = stem if os.path.abspath(dirpath) == \
                        os.path.abspath(base) else os.path.basename(dirpath)
                    img = self._prepare_target(
                        os.path.join(dirpath, name))
                    self._target_by_label[label] = img
        if not self._target_by_label:
            raise ValueError("%s: no target images under %s"
                             % (self.name, self.target_paths))

    def _prepare_target(self, path):
        from PIL import Image
        img = numpy.asarray(Image.open(path))
        if img.ndim == 3 and img.shape[2] == 1:
            img = img[:, :, 0]
        if self.targets_scale is not None and \
                img.shape[:2] != tuple(self.targets_scale):
            pil = Image.fromarray(img)
            pil = pil.resize((self.targets_scale[1],
                              self.targets_scale[0]), Image.BILINEAR)
            img = numpy.asarray(pil)
        return img.astype(self.source_dtype)

    def load_data(self):
        self._load_targets()
        super(FullBatchImageLoaderMSE, self).load_data()
        # dataset layout [TEST | VALID | TRAIN]
        targets = []
        labels_int = []
        for clazz in (TEST, VALID, TRAIN):
            for key in self._keys[clazz]:
                label = self.get_image_label(key)
                if label not in self._target_by_label:
                    raise KeyError(
                        "no target image for label %r" % (label,))
                targets.append(self._target_by_label[label])
                labels_int.append(self._map_label(label))
        self.original_targets.mem = numpy.stack(targets)
        # one target per distinct DATA label, ordered by the int mapping —
        # enables EvaluatorMSE's nearest-target n_err metric.  Targets for
        # labels with no data samples are skipped (mapping them would add
        # phantom classes).
        by_int = {}
        for label, img in self._target_by_label.items():
            if label in self._label_to_int:
                by_int[self._label_to_int[label]] = img
            else:
                self.warning("target image for unused label %r skipped",
                             label)
        self.class_targets.reset(numpy.stack(
            [by_int[i] for i in sorted(by_int)]))

    def _apply_target_normalization(self):
        super(FullBatchImageLoaderMSE, self)._apply_target_normalization()
        # keep class_targets in the same normalized space as the targets
        ct = self.class_targets.mem
        self.target_normalizer.normalize(ct.reshape(ct.shape[0], -1))


class FullBatchAutoLabelFileImageLoaderMSE(FullBatchImageLoaderMSE,
                                           AutoLabelFileImageLoader,
                                           IImageLoader):
    MAPPING = "full_batch_auto_label_file_image_mse"
