"""Image-loader base classes.

TPU-era equivalent of the core ``veles.loader.image`` /
``veles.loader.fullbatch_image`` family (SURVEY.md §2.9: ImageLoader,
FullBatchImageLoader, FileListImageLoader,
FullBatchAutoLabelFileImageLoader).  The observed contract the reference
loaders fill (loader_lmdb.py, loader_stl.py): subclasses provide

* ``get_keys(index)``       -> list of opaque keys for class ``index``
* ``get_image_data(key)``   -> numpy array (H, W[, C]) uint8/float
* ``get_image_label(key)``  -> int or string label
* ``get_image_info(key)``   -> ((H, W), color_space)

The base turns keys into the Loader minibatch contract: string labels get
an int mapping (``labels_mapping``), images are optionally rescaled to
``scale`` (PIL bilinear) and served NHWC.
"""

import os

import numpy

from znicz_tpu.loader.base import (
    Loader, FullBatchLoader, ILoader, IFullBatchLoader, TEST, VALID, TRAIN)


class IImageLoader(ILoader):
    pass


class ImageLoaderBase(Loader):
    """Streaming image loader: decodes per minibatch, full set never in
    memory (the reference ImageLoader contract)."""

    MAPPING = None
    hide_from_registry = True

    def __init__(self, workflow, **kwargs):
        super(ImageLoaderBase, self).__init__(workflow, **kwargs)
        #: target (H, W) or None to keep source size
        self.scale = kwargs.get("scale")
        self.source_dtype = numpy.float32
        #: cap on TRAIN images decoded for the normalizer's analyze pass
        #: (streaming sets don't fit in RAM; the fit is statistical)
        self.normalizer_analysis_limit = kwargs.get(
            "normalizer_analysis_limit", 2048)
        #: carve VALID out of TRAIN when the source has no validation
        #: split (reference loaders' validation_ratio kwarg)
        self.validation_ratio = kwargs.get("validation_ratio", 0.0)
        self._keys = {TEST: [], VALID: [], TRAIN: []}
        self._label_to_int = {}
        self._distinct_labels = set()

    # -- subclass contract --------------------------------------------------
    def get_keys(self, index):
        raise NotImplementedError

    def get_image_data(self, key):
        raise NotImplementedError

    def get_image_label(self, key):
        raise NotImplementedError

    def get_image_info(self, key):
        raise NotImplementedError

    # -- helpers ------------------------------------------------------------
    @property
    def labels_mapping(self):
        return self._label_to_int

    @property
    def unique_labels_count(self):
        if self._distinct_labels:
            return len(self._distinct_labels)
        return super(ImageLoaderBase, self).unique_labels_count

    def _map_label(self, label):
        if isinstance(label, (int, numpy.integer)):
            self._distinct_labels.add(int(label))
            return int(label)
        if label not in self._label_to_int:
            self._label_to_int[label] = len(self._label_to_int)
        mapped = self._label_to_int[label]
        self._distinct_labels.add(mapped)
        return mapped

    def _prepare_image(self, img):
        """To NHWC float sample, rescaled to ``scale`` if set."""
        img = numpy.asarray(img)
        if img.ndim == 2:
            img = img[:, :, None]
        if self.scale is not None and tuple(img.shape[:2]) != \
                tuple(self.scale):
            from PIL import Image
            chans = []
            for c in range(img.shape[2]):
                pil = Image.fromarray(img[:, :, c])
                # PIL size is (W, H)
                pil = pil.resize((self.scale[1], self.scale[0]),
                                 Image.BILINEAR)
                chans.append(numpy.asarray(pil))
            img = numpy.stack(chans, axis=2)
        return img.astype(self.source_dtype)

    def _sample_shape(self):
        for clazz in (TRAIN, VALID, TEST):
            if self._keys[clazz]:
                # _prepare_image already applies ``scale``
                return self._prepare_image(
                    self.get_image_data(self._keys[clazz][0])).shape
        raise ValueError("%s: no keys in any class" % self.name)

    # -- Loader contract ----------------------------------------------------
    def load_data(self):
        # pre-scan labels in dataset order so the int mapping (and thus
        # the softmax head) is deterministic
        for clazz in (TEST, VALID, TRAIN):
            self._keys[clazz] = list(self.get_keys(clazz))
            for key in self._keys[clazz]:
                self._map_label(self.get_image_label(key))
        if self.validation_ratio > 0 and not self._keys[VALID] and \
                self._keys[TRAIN]:
            n = len(self._keys[TRAIN])
            n_valid = max(1, int(n * self.validation_ratio))
            perm = self.prng.permutation(n)
            keys = self._keys[TRAIN]
            self._keys[VALID] = [keys[i] for i in sorted(perm[:n_valid])]
            self._keys[TRAIN] = [keys[i] for i in sorted(perm[n_valid:])]
        for clazz in (TEST, VALID, TRAIN):
            self.class_lengths[clazz] = len(self._keys[clazz])

    def create_minibatch_data(self):
        shape = self._sample_shape()
        self.minibatch_data.reset(numpy.zeros(
            (self.max_minibatch_size,) + tuple(shape),
            dtype=self.source_dtype))

    def initialize(self, device=None, **kwargs):
        super(ImageLoaderBase, self).initialize(device=device, **kwargs)
        if self.normalizer is None:
            self._fit_normalizer()

    def _fit_normalizer(self):
        """Fit the normalizer on (up to ``normalizer_analysis_limit``)
        TRAIN images; fill_minibatch then normalizes every minibatch —
        the streaming counterpart of FullBatchLoader's whole-set pass."""
        from znicz_tpu.core import normalization
        if self.normalization_type in (None, "none"):
            self.normalizer = normalization.NoneNormalizer()
            return
        self.normalizer = normalization.create(
            self.normalization_type, **self.normalization_parameters)
        keys = self._keys[TRAIN] or self._keys[VALID] or self._keys[TEST]
        keys = keys[:self.normalizer_analysis_limit]
        sample = numpy.stack([
            self._prepare_image(self.get_image_data(k)) for k in keys])
        self.normalizer.analyze(sample.reshape(len(keys), -1))

    def _key_of_global_index(self, idx):
        for clazz in (TEST, VALID, TRAIN):
            start, end = self.class_index_range(clazz)
            if start <= idx < end:
                return self._keys[clazz][idx - start]
        raise IndexError(idx)

    def fill_minibatch(self):
        idx = self.minibatch_indices.mem
        self.minibatch_data.map_invalidate()
        self.minibatch_labels.map_write()
        n = self.minibatch_size
        for i in range(n):
            key = self._key_of_global_index(int(idx[i]))
            self.minibatch_data.mem[i] = self._prepare_image(
                self.get_image_data(key))
            self.minibatch_labels.mem[i] = self._map_label(
                self.get_image_label(key))
        if self.normalizer is not None:
            self.normalizer.normalize(
                self.minibatch_data.mem[:n].reshape(n, -1))


class FullBatchImageLoader(ImageLoaderBase, FullBatchLoader,
                           IFullBatchLoader):
    """Decodes the whole dataset into original_data at load time (the
    reference FullBatchImageLoader contract) — gets normalization and
    vectorized minibatch fill from FullBatchLoader."""

    MAPPING = None
    hide_from_registry = True

    def load_data(self):
        ImageLoaderBase.load_data(self)
        shape = self._sample_shape()
        total = self.total_samples
        data = numpy.zeros((total,) + tuple(shape), dtype=self.source_dtype)
        pos = 0
        for clazz in (TEST, VALID, TRAIN):  # dataset layout order
            for key in self._keys[clazz]:
                data[pos] = self._prepare_image(self.get_image_data(key))
                self._original_labels.append(
                    self._map_label(self.get_image_label(key)))
                pos += 1
        self.original_data.mem = data

    def create_minibatch_data(self):
        FullBatchLoader.create_minibatch_data(self)

    def fill_minibatch(self):
        FullBatchLoader.fill_minibatch(self)


class FileListImageLoader(ImageLoaderBase, IImageLoader):
    """Images listed in an index file of ``path [label]`` lines
    (reference FileListImageLoader contract); one list file per class.
    """

    MAPPING = "file_list_image"

    def __init__(self, workflow, **kwargs):
        super(FileListImageLoader, self).__init__(workflow, **kwargs)
        self.path_to_test_text_file = kwargs.get("test_paths")
        self.path_to_val_text_file = kwargs.get("validation_paths")
        self.path_to_train_text_file = kwargs.get("train_paths")
        self.base_directory = kwargs.get("base_directory", "")
        self._lists = {TEST: self.path_to_test_text_file,
                       VALID: self.path_to_val_text_file,
                       TRAIN: self.path_to_train_text_file}

    def get_keys(self, index):
        paths = self._lists.get(index)
        if not paths:
            return []
        if isinstance(paths, str):
            paths = [paths]
        keys = []
        for list_file in paths:
            with open(list_file) as fin:
                for line in fin:
                    line = line.strip()
                    if not line:
                        continue
                    parts = line.split()
                    path = os.path.join(self.base_directory, parts[0])
                    label = parts[1] if len(parts) > 1 else \
                        os.path.basename(os.path.dirname(path))
                    keys.append((path, label))
        return keys

    def get_image_data(self, key):
        from PIL import Image
        return numpy.asarray(Image.open(key[0]))

    def get_image_label(self, key):
        label = key[1]
        try:
            return int(label)
        except (TypeError, ValueError):
            return label

    def get_image_info(self, key):
        from PIL import Image
        with Image.open(key[0]) as img:
            return (img.height, img.width), img.mode


class FullBatchFileListImageLoader(FullBatchImageLoader,
                                   FileListImageLoader):
    """MRO note: FullBatchImageLoader first so load_data /
    create_minibatch_data / fill_minibatch resolve to the full-batch
    versions; the key/data providers still come from the list loader."""

    MAPPING = "full_batch_file_list_image"


class AutoLabelFileImageLoader(ImageLoaderBase, IImageLoader):
    """Scans directories of images; the label is the parent directory name
    (reference FullBatchAutoLabelFileImageLoader contract)."""

    MAPPING = "auto_label_file_image"
    EXTENSIONS = (".png", ".jpg", ".jpeg", ".bmp", ".pgm", ".ppm")

    def __init__(self, workflow, **kwargs):
        super(AutoLabelFileImageLoader, self).__init__(workflow, **kwargs)
        self._dirs = {TEST: kwargs.get("test_paths"),
                      VALID: kwargs.get("validation_paths"),
                      TRAIN: kwargs.get("train_paths")}

    def get_keys(self, index):
        dirs = self._dirs.get(index)
        if not dirs:
            return []
        if isinstance(dirs, str):
            dirs = [dirs]
        keys = []
        for base in dirs:
            for dirpath, _, files in sorted(os.walk(base)):
                for name in sorted(files):
                    if os.path.splitext(name)[1].lower() in self.EXTENSIONS:
                        path = os.path.join(dirpath, name)
                        keys.append((path, os.path.basename(dirpath)))
        return keys

    get_image_data = FileListImageLoader.get_image_data
    get_image_label = FileListImageLoader.get_image_label
    get_image_info = FileListImageLoader.get_image_info


class FullBatchAutoLabelFileImageLoader(FullBatchImageLoader,
                                        AutoLabelFileImageLoader):
    MAPPING = "full_batch_auto_label_file_image"
