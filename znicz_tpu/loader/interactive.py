"""Interactive loader — feed samples one at a time.

TPU-era equivalent of the veles-core ``loader.interactive.
InteractiveLoader`` (used by the reference's AlexNet forward service,
tests/research/AlexNet/imagenet_workflow.py:131): an inference workflow
pulls minibatches from a host-side queue filled by ``feed()`` calls —
the serving-time counterpart of the file loaders.

Usage::

    loader = InteractiveLoader(wf, sample_shape=(28, 28, 1))
    loader.feed(img1); loader.feed(img2)
    loader.finish()           # no more samples; epoch ends when drained
    wf.run()                  # forward workflow consumes the queue
"""

import collections

import numpy

from znicz_tpu.core.memory import Array
from znicz_tpu.core.mutable import Bool
from znicz_tpu.core.units import Unit
from znicz_tpu.loader.base import TEST, UserLoaderRegistry


class InteractiveLoader(Unit):
    """Loader-contract unit backed by a host queue (class TEST)."""

    MAPPING = "interactive"

    def __init__(self, workflow, **kwargs):
        super(InteractiveLoader, self).__init__(workflow, **kwargs)
        self.sample_shape = tuple(kwargs["sample_shape"])
        self.max_minibatch_size = int(kwargs.get("minibatch_size", 1))
        #: number of classes served (0 = unknown: the softmax-width
        #: auto-set hook then keeps the configured width)
        self.unique_labels_count = int(
            kwargs.get("unique_labels_count", 0))
        self.minibatch_data = Array(name="minibatch_data")
        self.minibatch_labels = Array(name="minibatch_labels")
        self.minibatch_size = 0
        self.minibatch_class = TEST
        self.minibatch_offset = 0
        self.epoch_number = 0
        self.epoch_ended = Bool(False)
        self.last_minibatch = Bool(False)
        self.train_ended = Bool(False)
        self.complete = Bool(False)
        self.class_lengths = [0, 0, 0]
        #: post-initialize hook (same contract as Loader.on_initialized —
        #: StandardWorkflowBase uses it to auto-set the softmax width)
        self.on_initialized = None
        self._queue = collections.deque()
        self._finished = False
        self._served = 0

    def initialize(self, device=None, **kwargs):
        super(InteractiveLoader, self).initialize(device=device, **kwargs)
        self.minibatch_data.reset(numpy.zeros(
            (self.max_minibatch_size,) + self.sample_shape,
            numpy.float32))
        self.minibatch_labels.reset(numpy.zeros(
            self.max_minibatch_size, numpy.int32))
        if self.on_initialized is not None:
            self.on_initialized()

    # -- producer side ------------------------------------------------------
    def feed(self, sample, label=-1):
        """Queue one sample (host array shaped ``sample_shape``).

        Feeding after a drained session re-arms the loader: complete /
        epoch flags clear so the serving workflow can run() again."""
        sample = numpy.asarray(sample, numpy.float32)
        if tuple(sample.shape) != self.sample_shape:
            raise ValueError("sample shape %s != %s"
                             % (sample.shape, self.sample_shape))
        if self._finished:
            self._finished = False
            self.complete <<= False
            self.epoch_ended <<= False
            self.last_minibatch <<= False
            self.train_ended <<= False
        self._queue.append((sample, int(label)))

    def finish(self):
        """No further samples: the current epoch ends once drained."""
        self._finished = True

    # -- consumer side ------------------------------------------------------
    def run(self):
        n = min(len(self._queue), self.max_minibatch_size)
        if n == 0 and not self._finished:
            raise RuntimeError(
                "InteractiveLoader ran with an empty queue — feed() "
                "samples or finish() before running the workflow")
        self.minibatch_data.map_invalidate()
        self.minibatch_labels.map_write()
        for i in range(n):
            sample, label = self._queue.popleft()
            self.minibatch_data.mem[i] = sample
            self.minibatch_labels.mem[i] = label
        self.minibatch_size = n
        self.minibatch_offset = self._served + n
        self._served += n
        self.class_lengths[TEST] = self._served
        drained = self._finished and not self._queue
        self.last_minibatch <<= drained
        self.epoch_ended <<= drained
        self.train_ended <<= drained
        self.complete <<= drained
        if drained:
            self.epoch_number += 1


# Unit-based (not a Loader subclass), so the metaclass registration does
# not fire — register the type string explicitly for loader_name use
UserLoaderRegistry.loaders[InteractiveLoader.MAPPING] = InteractiveLoader
