"""Minibatch stream save / replay.

TPU-era equivalent of the veles-core ``loader.saver`` pair wired by the
reference's ``StandardWorkflow.link_data_saver``
(standard_workflow.py:1121-1149): ``MinibatchesSaver`` records the
minibatch stream a training run actually saw (post-shuffle,
post-normalization) into one pickle-stream file; ``MinibatchesLoader``
replays such a file as a FullBatchLoader — reproducing a run's exact data
without the original dataset or its preprocessing cost.
"""

import os
import pickle

import numpy

from znicz_tpu.core.config import root
from znicz_tpu.core.units import Unit
from znicz_tpu.loader.base import FullBatchLoader, TEST, VALID, TRAIN


class MinibatchesSaver(Unit):
    """Streams every observed minibatch to ``file_name``.

    Header record: dict(class_lengths, max_minibatch_size, has_labels,
    labels_mapping, shuffle_limit).  Then one record per minibatch:
    dict(minibatch_class, minibatch_size, data, labels).  Stop (or
    workflow finish) finalizes the file.
    """

    def __init__(self, workflow, **kwargs):
        super(MinibatchesSaver, self).__init__(workflow, **kwargs)
        self.file_name = kwargs.get("file_name")
        self.only_epoch = int(kwargs.get("only_epoch", -1))
        self.demand("minibatch_data", "minibatch_labels",
                    "minibatch_class", "minibatch_size", "class_lengths",
                    "max_minibatch_size", "has_labels")
        self._file = None
        # epochs counted HERE from epoch_ended edges: the loader's own
        # epoch_number is already incremented when the closing minibatch
        # of an epoch is served
        self._epochs_seen = 0

    def initialize(self, device=None, **kwargs):
        super(MinibatchesSaver, self).initialize(device=device, **kwargs)
        if not self.file_name:
            self.file_name = os.path.join(root.common.dirs.cache,
                                          "minibatches.sav")
        os.makedirs(os.path.dirname(self.file_name), exist_ok=True)
        self._file = open(self.file_name, "wb")
        pickle.dump({
            "format": 1,
            "class_lengths": list(self.class_lengths),
            "max_minibatch_size": int(self.max_minibatch_size),
            "has_labels": bool(self.has_labels),
            "labels_mapping": dict(getattr(self, "labels_mapping", {})
                                   or {}),
            "shuffle_limit": getattr(self, "shuffle_limit", 0),
        }, self._file, protocol=4)
        if self.workflow is not None and \
                hasattr(self.workflow, "on_workflow_finished"):
            self.workflow.on_workflow_finished(self.stop)

    def run(self):
        if self._file is None:
            return
        epoch = self._epochs_seen
        if bool(getattr(self, "epoch_ended", False)):
            self._epochs_seen += 1
        if 0 <= self.only_epoch != epoch:
            return
        self.minibatch_data.map_read()
        n = int(self.minibatch_size)
        record = {
            "minibatch_class": int(self.minibatch_class),
            "minibatch_size": n,
            "data": numpy.array(self.minibatch_data.mem[:n]),
            "labels": None,
        }
        if self.has_labels and self.minibatch_labels:
            self.minibatch_labels.map_read()
            record["labels"] = numpy.array(self.minibatch_labels.mem[:n])
        pickle.dump(record, self._file, protocol=4)

    def stop(self):
        if self._file is not None:
            self._file.close()
            self._file = None
            self.info("saved minibatch stream -> %s", self.file_name)


def read_minibatch_stream(file_name):
    """(header, [records]) from a MinibatchesSaver file."""
    records = []
    with open(file_name, "rb") as f:
        header = pickle.load(f)
        while True:
            try:
                records.append(pickle.load(f))
            except EOFError:
                break
    return header, records


class MinibatchesLoader(FullBatchLoader):
    """Replays a MinibatchesSaver file as a full-batch dataset.

    Samples are grouped by their recorded ``minibatch_class``; duplicate
    appearances (several epochs saved) are collapsed by saving only the
    first epoch — pass MinibatchesSaver(only_epoch=...) when recording,
    or the replay will contain repeats.
    """

    MAPPING = "minibatches"

    def __init__(self, workflow, **kwargs):
        super(MinibatchesLoader, self).__init__(workflow, **kwargs)
        self.file_name = kwargs["file_name"]

    def load_data(self):
        header, records = read_minibatch_stream(self.file_name)
        per_class = {TEST: [], VALID: [], TRAIN: []}
        labels_per_class = {TEST: [], VALID: [], TRAIN: []}
        for rec in records:
            per_class[rec["minibatch_class"]].append(rec["data"])
            if rec["labels"] is not None:
                labels_per_class[rec["minibatch_class"]].append(
                    rec["labels"])
        datas, labels = [], []
        for clazz in (TEST, VALID, TRAIN):
            chunks = per_class[clazz]
            self.class_lengths[clazz] = sum(c.shape[0] for c in chunks)
            datas.extend(chunks)
            labels.extend(labels_per_class[clazz])
        if not datas:
            raise ValueError("empty minibatch stream %s" % self.file_name)
        self.original_data.reset(numpy.concatenate(datas, axis=0))
        del self._original_labels[:]
        for chunk in labels:
            self._original_labels.extend(int(v) for v in chunk)
