"""SPMD parallelism over TPU device meshes.

TPU-era replacement for the reference's master-slave parameter server
(SURVEY.md §2.8): the per-minibatch forward+backward+update runs as ONE
jitted XLA computation over a ``jax.sharding.Mesh``; gradient all-reduce,
weight broadcast and Decision stat aggregation (sum n_err / confusion,
decision.py:529-544) become XLA collectives inserted by GSPMD.
"""

from znicz_tpu.parallel.mesh import make_mesh  # noqa: F401
from znicz_tpu.parallel.fused import (  # noqa: F401
    FusedMLP, FusedNet, build_fc_specs, build_specs, flops_per_image)
from znicz_tpu.parallel import multihost  # noqa: F401
from znicz_tpu.parallel.sequence import (  # noqa: F401
    attention_reference, ring_attention)
