"""Sequence/context parallelism — ring attention over the device mesh.

Long-context support the TPU way: the sequence axis is sharded across
devices, each device holds one block of Q/K/V, and K/V blocks rotate
around the ring (``lax.ppermute`` — neighbor exchanges ride ICI) while
every device accumulates its queries' attention with a flash-style
streaming softmax (running max / normalizer), so the full T x T score
matrix never materializes and context length scales linearly with the
number of devices.

This is the long-sequence counterpart of the reference's LSTM tier: the
reference (2013-2015) predates attention, but its "long sequence"
ambition maps to exactly this primitive on TPU (the scaling-book
recipe: pick a mesh, shard the sequence, let collectives do the rest).

API:

* :func:`attention_reference` — single-device attention, the executable
  spec (numpy-style jnp math);
* :func:`ring_attention` — the same math over a mesh axis, exact to
  float tolerance, causal or full.
"""

import functools
import math

import jax
import jax.numpy as jnp
try:
    from jax import shard_map
except ImportError:  # jax < 0.5 ships it under experimental
    from jax.experimental.shard_map import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

#: newer jax tracks axis-varying values explicitly (lax.pcast + the
#: rep checker); older jax has neither — there the pcast marks are
#: identity and the shard_map rep check is disabled instead
_HAS_PCAST = hasattr(jax.lax, "pcast")


def attention_reference(q, k, v, causal=False):
    """Plain softmax attention, (B, T, H, D) -> (B, T, H, D).

    The single-device spec ring_attention must reproduce."""
    scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        tq, tk = s.shape[-2], s.shape[-1]
        mask = jnp.arange(tk)[None, :] > jnp.arange(tq)[:, None]
        s = jnp.where(mask, -jnp.inf, s)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _ring_body(q, kb, vb, m, l, acc, q_pos, k_pos, scale, causal):
    """One ring step: fold the visiting K/V block into the running
    flash-softmax state."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, kb) * scale
    if causal:
        mask = k_pos[None, :] > q_pos[:, None]      # (T_q, T_k)
        s = jnp.where(mask[None, None], -jnp.inf, s)
    blk_max = jnp.max(s, axis=-1)                   # (B, H, T_q)
    m_new = jnp.maximum(m, blk_max)
    # fully-masked rows keep m = -inf; guard the exp against inf - inf
    safe_m = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
    p = jnp.exp(s - safe_m[..., None])  # masked cells: exp(-inf) == 0
    corr = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - safe_m))
    l_new = l * corr + jnp.sum(p, axis=-1)
    acc_new = acc * corr[..., None] + \
        jnp.einsum("bhqk,bkhd->bhqd", p, vb)
    return m_new, l_new, acc_new


def ring_attention(q, k, v, mesh, axis="data", causal=False):
    """Attention with the SEQUENCE axis sharded over ``mesh[axis]``.

    q/k/v: (B, T, H, D) global arrays (host or device); T must divide
    evenly by the axis size.  Returns the (B, T, H, D) result sharded
    the same way.  K/V blocks rotate around the ring; with ``causal``
    each device masks by GLOBAL positions, so the result matches
    :func:`attention_reference` on the gathered arrays.
    """
    n = mesh.shape[axis]
    t = q.shape[1]
    if tuple(k.shape) != tuple(q.shape) or \
            tuple(v.shape) != tuple(q.shape):
        raise ValueError(
            "ring attention is self-attention: q/k/v must share one "
            "(B, T, H, D) shape, got %s / %s / %s"
            % (q.shape, k.shape, v.shape))
    if t % n:
        raise ValueError("sequence length %d not divisible by %d shards"
                         % (t, n))
    t_local = t // n
    spec = P(None, axis, None, None)
    sharding = NamedSharding(mesh, spec)
    q, k, v = (jax.device_put(a, sharding) for a in (q, k, v))
    return _compiled_ring(mesh, axis, n, t_local, int(q.shape[-1]),
                          causal)(q, k, v)


@functools.lru_cache(maxsize=64)
def _compiled_ring(mesh, axis, n, t_local, d, causal):
    """Cache the jitted shard_map per geometry — rebuilding it per call
    would re-trace and re-compile every step."""
    spec = P(None, axis, None, None)
    fwd = functools.partial(_ring_attention_local, axis=axis, n=n,
                            t_local=t_local,
                            scale=1.0 / math.sqrt(d), causal=causal)
    kwargs = {} if _HAS_PCAST else {"check_rep": False}
    return jax.jit(shard_map(fwd, mesh=mesh,
                             in_specs=(spec, spec, spec),
                             out_specs=spec, **kwargs))


def _ring_attention_local(q, k, v, *, axis, n, t_local, scale, causal):
    """Per-device body: q is MY block; k/v blocks visit via ppermute."""
    my = jax.lax.axis_index(axis)
    b, _, h, d = q.shape
    q_pos = my * t_local + jnp.arange(t_local)
    # pvary: the carry becomes axis-varying on the first iteration (it
    # mixes in axis_index-dependent masks), so the init must be marked
    # varying too or the fori_loop carry types mismatch
    vary = (lambda a: jax.lax.pcast(a, axis, to="varying")) \
        if _HAS_PCAST else (lambda a: a)  # noqa: E731
    m = vary(jnp.full((b, h, t_local), -jnp.inf, q.dtype))
    l = vary(jnp.zeros((b, h, t_local), q.dtype))
    acc = vary(jnp.zeros((b, h, t_local, d), q.dtype))
    perm = [(j, (j + 1) % n) for j in range(n)]

    def body(i, carry):
        m, l, acc, kb, vb = carry
        # after i rotations each device holds the block that STARTED at
        # device (my - i) mod n
        src = (my - i) % n
        k_pos = src * t_local + jnp.arange(t_local)
        m, l, acc = _ring_body(q, kb, vb, m, l, acc, q_pos, k_pos,
                               scale, causal)
        kb = jax.lax.ppermute(kb, axis, perm)
        vb = jax.lax.ppermute(vb, axis, perm)
        return m, l, acc, kb, vb

    m, l, acc, _, _ = jax.lax.fori_loop(0, n, body, (m, l, acc, k, v))
    # fully-masked rows (l == 0) normalize to 0 rather than NaN
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return jnp.transpose(out, (0, 2, 1, 3))  # (B, T_local, H, D)
