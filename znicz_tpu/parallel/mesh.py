"""Device-mesh construction.

The scaling recipe (jax-ml.github.io/scaling-book): pick a mesh, annotate
shardings, let XLA insert the collectives.  Axis names:

* ``data``  — batch dimension (data parallelism; gradient psum rides ICI)
* ``model`` — parameter dimension (tensor parallelism for wide layers)
"""

import numpy

import jax
from jax.sharding import Mesh


def make_mesh(n_devices=None, model_parallel=1, devices=None):
    """Build a (data, model) mesh over the first ``n_devices`` devices.

    ``model_parallel`` sets the model-axis extent; the rest goes to data.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = n_devices or len(devices)
    if n > len(devices):
        raise ValueError("requested %d devices, have %d" % (n, len(devices)))
    if n % model_parallel:
        raise ValueError("n_devices %d not divisible by model_parallel %d"
                         % (n, model_parallel))
    arr = numpy.array(devices[:n]).reshape(n // model_parallel,
                                           model_parallel)
    return Mesh(arr, ("data", "model"))


def data_parallel_size(mesh):
    return mesh.shape["data"] if mesh is not None else 1


def model_parallel_size(mesh):
    return mesh.shape["model"] if mesh is not None else 1


def check_data_batch(mesh, batch):
    """Loud divisibility contract of every batch-sharded entry point:
    a global batch must split evenly over the mesh's ``data`` axis
    (jagged shards would silently change the per-step math).  No-op
    without a mesh."""
    dsize = data_parallel_size(mesh)
    if batch % dsize:
        raise ValueError("batch %d not divisible by data-parallel %d"
                         % (batch, dsize))
