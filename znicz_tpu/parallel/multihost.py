"""Multi-host (DCN) distributed training.

TPU-era replacement for the reference's master-slave socket transport
(SURVEY.md §5.8, veles launcher + nn_units.py:178-211 broadcast/
aggregate): every host runs the SAME SPMD program; the mesh spans all
hosts' devices; XLA routes per-layer collectives over ICI within a host
and only the gradient reduction over DCN.

Recipe::

    from znicz_tpu.parallel import multihost
    multihost.initialize()                 # no-op when single-process
    mesh = multihost.make_hybrid_mesh(model_parallel=2)
    net = FusedNet(layers, shape, mesh=mesh)
    for local_x, local_l in my_hosts_shard_of_the_data:
        x, l = multihost.global_batch(mesh, local_x, local_l)
        net.step(x, l)

Elasticity: the reference's master keeps training while slaves join and
leave; the SPMD equivalent is gang-scheduled, so host failure is handled
by checkpoint-restart instead — snapshots (core/snapshotter.py) carry
the full training state and the launcher's ``--snapshot`` resumes it.
"""

import os


import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


_initialized = False


def initialize(coordinator_address=None, num_processes=None,
               process_id=None, **kwargs):
    """Bring up the JAX distributed runtime across hosts.

    A no-op for single-process runs (the common case and every test),
    and IDEMPOTENT: a second call in an already-distributed process
    returns True without touching the runtime (jax.distributed raises
    on double-initialize, and e.g. a serial GA constructs one Launcher
    per evaluation).  Arguments default from the standard env vars
    (JAX_COORDINATOR_ADDRESS, JAX_NUM_PROCESSES, JAX_PROCESS_ID) —
    under TPU pod runtimes jax.distributed autodetects and none are
    needed.
    """
    global _initialized
    if _initialized:
        return True
    is_init = getattr(jax.distributed, "is_initialized", None)
    if is_init is not None and is_init():
        return True
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS")
    if num_processes is None:
        num_processes = int(os.environ.get("JAX_NUM_PROCESSES", "0")) \
            or None
    if process_id is None:
        pid = os.environ.get("JAX_PROCESS_ID")
        process_id = int(pid) if pid is not None else None
    def _cpu_collectives():
        # multi-process CPU (tests / dev boxes) needs a cross-process
        # collectives implementation; gloo is the one shipped with jax.
        # Harmless if the backend turns out to be TPU (config is only
        # read by the CPU client).
        if "cpu" in (os.environ.get("JAX_PLATFORMS") or "cpu"):
            try:
                jax.config.update(
                    "jax_cpu_collectives_implementation", "gloo")
            except Exception:
                pass

    if coordinator_address is None and num_processes in (None, 1):
        # no explicit config: managed cluster runtimes (TPU pods, GKE,
        # Slurm/MPI) carry their own env markers and jax.distributed
        # autodetects from them — skipping initialize there would let
        # every host train independently with NO gradient sync
        if _cluster_env_detected():
            _cpu_collectives()
            jax.distributed.initialize(**kwargs)
            _initialized = True
            return True
        return False  # genuinely single process
    _cpu_collectives()
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes, process_id=process_id, **kwargs)
    _initialized = True
    return True


#: env markers of the cluster runtimes jax.distributed can autodetect
_CLUSTER_ENV_VARS = (
    "MEGASCALE_COORDINATOR_ADDRESS",   # multislice
    "COORDINATOR_ADDRESS",
    "SLURM_JOB_ID",                    # Slurm
    "JOB_COMPLETION_INDEX",            # GKE indexed jobs
)


def _cluster_env_detected():
    if any(os.environ.get(v) for v in _CLUSTER_ENV_VARS):
        return True
    # TPU pod slice: only a MULTI-worker hostname list means multi-host
    # (single-host setups — incl. tunneled dev boxes — set one name)
    hostnames = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    if len([h for h in hostnames.split(",") if h.strip()]) > 1:
        return True
    try:
        if int(os.environ.get("OMPI_COMM_WORLD_SIZE", "1")) > 1:
            return True
    except ValueError:
        pass
    return False


def make_hybrid_mesh(model_parallel=1, devices=None):
    """(data, model) mesh over ALL processes' devices, laid out so that
    the model axis (all-gather heavy) stays inside one host's ICI domain
    and only the data-axis gradient psum crosses DCN.

    Single-process: equivalent to :func:`make_mesh` over the local
    devices.  Multi-process: uses mesh_utils.create_hybrid_device_mesh,
    which groups devices by process and orders DCN as the outermost
    axis.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if n % model_parallel:
        raise ValueError("%d devices not divisible by model_parallel %d"
                         % (n, model_parallel))
    n_processes = len({d.process_index for d in devices})
    if n_processes > 1:
        from jax.experimental import mesh_utils
        per_host = n // n_processes
        # TPU multislice: the DCN boundary is the SLICE (hosts inside a
        # slice are ICI-connected even across processes) — group by
        # slice with one DCN granule per slice.  Everything else
        # (multi-host single slice, CPU/GPU clusters, the 2-process CPU
        # elastic test) groups by process.
        n_slices = len({getattr(d, "slice_index", 0) or 0
                        for d in devices})
        if n_slices > 1:
            per_granule, n_granules, by_process = n // n_slices, \
                n_slices, False
        else:
            per_granule, n_granules, by_process = per_host, \
                n_processes, True
        if per_granule % model_parallel:
            raise ValueError(
                "model_parallel %d does not fit inside one DCN "
                "granule's %d devices — the model axis must not cross "
                "DCN" % (model_parallel, per_granule))
        arr = mesh_utils.create_hybrid_device_mesh(
            mesh_shape=(per_granule // model_parallel, model_parallel),
            dcn_mesh_shape=(n_granules, 1), devices=devices,
            process_is_granule=by_process)
        return Mesh(arr, ("data", "model"))
    from znicz_tpu.parallel.mesh import make_mesh
    return make_mesh(model_parallel=model_parallel, devices=devices)


def global_batch(mesh, local_x, local_labels):
    """Assemble per-process host shards into GLOBAL device arrays
    sharded over the mesh's data axis.

    Each process passes only ITS slice of the global batch (global batch
    size = sum of local batch sizes).  Single-process this is just a
    sharded device_put.
    """
    xs = NamedSharding(mesh, P("data", *([None] * (local_x.ndim - 1))))
    ls = NamedSharding(mesh, P("data"))
    if jax.process_count() == 1:
        return jax.device_put(local_x, xs), jax.device_put(local_labels, ls)
    x = jax.make_array_from_process_local_data(xs, local_x)
    labels = jax.make_array_from_process_local_data(ls, local_labels)
    return x, labels


# -- telemetry aggregation ---------------------------------------------------

def _flatten_telemetry(snap):
    """Deterministic (kind, name) -> float flattening of the numeric
    parts of a telemetry snapshot.  SPMD gangs run the same program, so
    every host produces the same key list — verified by the caller."""
    items = []
    for kind in ("counters", "gauges"):
        for k in sorted(snap.get(kind, {})):
            items.append((kind, k, float(snap[kind][k])))
    for k in sorted(snap.get("histograms", {})):
        h = snap["histograms"][k]
        items.append(("hist_count", k, float(h.get("count", 0))))
        items.append(("hist_sum", k, float(h.get("sum", 0.0))))
    return items


def merge_telemetry_snapshots(snaps):
    """Merge per-host telemetry snapshots into one view: counters and
    histogram count/sum are SUMMED, gauges take the MAX (a summed
    "loader.epoch" gauge would be nonsense).  Histogram percentiles
    are kept from the FIRST snapshot (this host) and flagged — exact
    cross-host percentile merge would need the raw reservoirs over
    DCN, which the counters' one-allgather budget doesn't buy."""
    if not snaps:
        return {}
    merged = {"counters": {}, "gauges": {}, "histograms": {}}
    for kind, agg in (("counters", sum), ("gauges", max)):
        keys = set()
        for s in snaps:
            keys.update(s.get(kind, {}))
        for k in sorted(keys):
            vals = [s.get(kind, {}).get(k, 0) for s in snaps]
            v = agg(vals)
            merged[kind][k] = int(v) if kind == "counters" else v
    hkeys = set()
    for s in snaps:
        hkeys.update(s.get("histograms", {}))
    for k in sorted(hkeys):
        hs = [s.get("histograms", {}).get(k) or {} for s in snaps]
        h = dict(hs[0])
        h["count"] = int(sum(x.get("count", 0) for x in hs))
        h["sum"] = float(sum(x.get("sum", 0.0) for x in hs))
        if any(x.get("count") for x in hs[1:]):
            h["percentiles_local_host_only"] = True
        merged["histograms"][k] = h
    merged["hosts"] = len(snaps)
    return merged


def aggregate_telemetry(snap):
    """Reduce every host's numeric telemetry into ONE merged view with
    a single allgather (collective — every process of the gang must
    call it, e.g. via ``telemetry.merged_snapshot()``).  Single-process
    it is the identity.  If the hosts' key sets disagree (a
    non-SPMD-identical code path registered an extra series), the
    local snapshot is returned unreduced rather than mis-summing
    misaligned columns."""
    import numpy
    import zlib

    if jax.process_count() == 1:
        return snap
    from jax.experimental import multihost_utils
    items = _flatten_telemetry(snap)
    keys_sig = zlib.crc32("|".join(
        "%s:%s" % (kind, k) for kind, k, _ in items).encode())
    # two collectives, BOTH shape-consistent across hosts: the first is
    # a fixed-shape (2,) signature exchange — hosts whose registries
    # diverged (a rank-0-only series like snapshotter.exports) would
    # otherwise feed different-length vectors into ONE allgather, which
    # crashes or hangs the collective before any guard can run.  Every
    # host sees every signature, so every host takes the same branch.
    sig = numpy.array([float(len(items)), float(keys_sig)],
                      dtype=numpy.float64)
    sigs = numpy.asarray(multihost_utils.process_allgather(sig))
    if not (sigs[:, 0] == len(items)).all() or \
            not (sigs[:, 1] == float(keys_sig)).all():
        snap = dict(snap)
        snap["aggregated"] = False
        return snap
    # signatures agree -> identical keys -> identical vector length
    vec = numpy.array([v for _, _, v in items], dtype=numpy.float64)
    gathered = numpy.asarray(
        multihost_utils.process_allgather(vec))  # (nproc, n)
    # rebuild per-host snapshots from the gathered columns, merge
    snaps = []
    for row in gathered:
        s = {"counters": {}, "gauges": {}, "histograms": {}}
        for (kind, k, _), v in zip(items, row):
            if kind in ("counters", "gauges"):
                s[kind][k] = v
            elif kind == "hist_count":
                s["histograms"].setdefault(k, {})["count"] = v
            else:
                s["histograms"].setdefault(k, {})["sum"] = v
        snaps.append(s)
    # carry this host's percentiles into slot 0 so the merge keeps them
    for k, h in snap.get("histograms", {}).items():
        snaps[jax.process_index()]["histograms"][k] = dict(
            h, **snaps[jax.process_index()]["histograms"].get(k, {}))
    local = snaps.pop(jax.process_index())
    merged = merge_telemetry_snapshots([local] + snaps)
    merged["hosts"] = int(jax.process_count())
    if "trace" in snap:
        merged["trace"] = snap["trace"]
    return merged


def host_shard(global_size, process_index=None, process_count=None):
    """(start, stop) of this host's contiguous slice of a global batch
    or dataset — the per-host data-loading contract."""
    process_index = jax.process_index() if process_index is None \
        else process_index
    process_count = jax.process_count() if process_count is None \
        else process_count
    if global_size % process_count:
        raise ValueError("global size %d not divisible by %d processes"
                         % (global_size, process_count))
    per = global_size // process_count
    return process_index * per, (process_index + 1) * per
