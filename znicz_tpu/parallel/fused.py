"""Fused SPMD training — one jitted XLA computation per minibatch.

SURVEY.md §7 design stance: the unit graph remains the epoch-level control
plane, but the hot loop — forward, loss gradient, backward, per-layer
update — compiles to a single XLA computation.  This module is the fused
path for whole feed-forward topologies: the FC family (reference
all2all.py:53-474 + gd.py:73-551), the conv family (conv.py:71-568 +
gd_conv.py:60-750), pooling (pooling.py:122-548), LRN (normalization.py),
standalone activations (activation.py) and dropout (dropout.py).

Parity: weight init matches the unit path exactly (magnitude heuristics
all2all.py:106-117 / conv.py:137-146, fill semantics all2all.py:119-127,
same PRNG draw order), and the update algebra is literally
:func:`znicz_tpu.ops.gd_math.update` with ``xp=jnp`` — the same function
the unit-at-a-time path runs.  Gradients come from ``jax.grad`` of the
softmax-CE loss, which reproduces the reference's hand-written chain rule
(verified by the float64 parity tests against the unit-graph path in
tests/unit/test_fused.py).

Sharding: parameters and inputs carry ``NamedSharding`` annotations over a
``(data, model)`` mesh; GSPMD inserts the gradient all-reduce (psum over
``data``) and the activation all-gathers (over ``model``) — the TPU-native
replacement for the reference's parameter-server broadcast/aggregate cycle
(nn_units.py:178-208, 644-694).  Conv parameters replicate (they are
small); wide FC layers shard over ``model``.
"""

from dataclasses import dataclass, field

import numpy

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from znicz_tpu.core import faults
from znicz_tpu.core import profiler
from znicz_tpu.core import prng
from znicz_tpu.core import telemetry
from znicz_tpu.parallel import mesh as mesh_mod
from znicz_tpu.ops import activations, gd_math
from znicz_tpu.ops import conv as conv_ops
from znicz_tpu.ops import pooling as pool_ops
from znicz_tpu.ops import normalization as norm_ops

#: the FC family (reference all2all.py classes); activation + magnitude
#: constants come from the registered unit classes — single source of truth
#: with the unit-graph path.
FC_TYPES = ("all2all", "all2all_tanh", "all2all_relu", "all2all_str",
            "all2all_sigmoid", "softmax")
CONV_TYPES = ("conv", "conv_tanh", "conv_sigmoid", "conv_relu", "conv_str")
#: stochastic variants sample winners from a jax PRNG key on the fused
#: path — same distribution as the unit path's host uint16 stream
#: (reference pooling.py:368-508), exact host-stream parity explicitly
#: waived like dropout's (docs/distributed.md)
POOL_TYPES = ("max_pooling", "maxabs_pooling", "avg_pooling",
              "stochastic_pooling", "stochastic_abs_pooling",
              "stochastic_pool_depool", "stochastic_abs_pool_depool")
_POOL_MODES = {"max_pooling": "max", "maxabs_pooling": "maxabs",
               "avg_pooling": "avg",
               "stochastic_pooling": "stochastic",
               "stochastic_abs_pooling": "stochasticabs",
               "stochastic_pool_depool": "stochastic_depool",
               "stochastic_abs_pool_depool": "stochasticabs_depool"}
ACTIVATION_TYPES = ("activation_tanh", "activation_sigmoid",
                    "activation_relu", "activation_str", "activation_log",
                    "activation_tanhlog", "activation_sincos")


def _forward_class(tpe):
    from znicz_tpu.units import nn_units
    import znicz_tpu.units  # noqa: F401 (registers every unit module)
    return nn_units.mapping[tpe].forward

#: strictly monotonically increasing activations — safe to commute past a
#: following max pooling (see forward()).  NOTE "relu" is excluded: the
#: reference's "relu" is log(1 + exp(x)) with a piecewise seam at x=15
#: (activations.py) and is not monotonic across the seam.
_MONOTONIC_ACTS = frozenset(("linear", "tanh", "sigmoid"))

DEFAULT_HYPER = dict(lr=0.01, wd=0.00005, l1_vs_l2=0.0, moment=0.0,
                     acc_alpha=0.0, acc_beta=0.0, gd_alpha=0.0, gd_beta=1.0,
                     factor_ortho=0.0)


def layer_hyper(layer, defaults=None):
    """(hyper, hyper_bias, flags) for one layer dict — the same parse
    ``build_specs`` runs: shared top-level keys merged under the "<-"
    backward kwargs (the reference routes shared kwargs to both sides,
    standard_workflow_base.py:406-422)."""
    layer = dict(layer)
    for k in ("type", "name", "->"):
        layer.pop(k, None)
    bwd = dict(layer.pop("<-", {}))
    merged = dict(layer)
    merged.update(bwd)
    return _parse_hyper(merged, dict(DEFAULT_HYPER, **(defaults or {})))


def _parse_hyper(bwd, defaults):
    """Extract (hyper, hyper_bias, flags) from a layer's "<-" dict —
    the reference backward-kwargs contract (standard_workflow_base.py:
    406-422)."""
    hyper = dict(defaults)
    hyper.update(
        lr=bwd.get("learning_rate", defaults["lr"]),
        wd=bwd.get("weights_decay", defaults["wd"]),
        l1_vs_l2=bwd.get("l1_vs_l2", defaults["l1_vs_l2"]),
        moment=bwd.get("gradient_moment", defaults["moment"]),
        acc_alpha=bwd.get("acc_alpha", defaults["acc_alpha"]),
        acc_beta=bwd.get("acc_beta", defaults["acc_beta"]),
        gd_alpha=bwd.get("gd_alpha", defaults["gd_alpha"]),
        gd_beta=bwd.get("gd_beta", defaults["gd_beta"]),
        factor_ortho=bwd.get("factor_ortho", defaults["factor_ortho"]))
    hyper_bias = dict(hyper)
    hyper_bias.update(
        lr=bwd.get("learning_rate_bias", hyper["lr"]),
        wd=bwd.get("weights_decay_bias", 0.0),
        l1_vs_l2=bwd.get("l1_vs_l2_bias", hyper["l1_vs_l2"]),
        moment=bwd.get("gradient_moment_bias", hyper["moment"]),
        factor_ortho=0.0)
    flags = dict(accumulate=bool(bwd.get("accumulate_gradient", False)),
                 apply=True,
                 solvers=frozenset(bwd.get("solvers", ())),
                 ortho=bool(hyper["factor_ortho"]),
                 variant_moment=bwd.get("variant_moment_gradient", True))
    return hyper, hyper_bias, flags


@dataclass
class FCSpec:
    """One fully-connected layer of the fused stack."""
    type: str
    n_in: int
    n_out: int
    activation: str
    hyper: dict = field(default_factory=dict)        # weights hyper
    hyper_bias: dict = field(default_factory=dict)   # bias hyper
    flags: dict = field(default_factory=dict)
    weights_stddev: float = None
    bias_stddev: float = None
    weights_filling: str = "uniform"
    bias_filling: str = "uniform"
    include_bias: bool = True

    kind = "fc"

    @property
    def is_softmax(self):
        return self.type == "softmax"

    @property
    def out_shape(self):
        return (self.n_out,)

    def init_stddev(self):
        """Reference magnitude heuristic (all2all.py:106-117), using the
        registered unit class's C constant."""
        if self.weights_stddev is not None:
            return self.weights_stddev
        from znicz_tpu.units.nn_units import weights_magnitude
        vle = weights_magnitude(_forward_class(self.type).C,
                                self.n_in, self.n_out, self.weights_filling)
        return min(vle, 0.5)


@dataclass
class ConvSpec:
    """One convolutional layer (reference conv.py:71-475 geometry:
    NHWC, weights (n_kernels, ky*kx*C), padding LTRB, sliding (x, y))."""
    type: str
    in_shape: tuple      # sample (H, W, C)
    out_shape: tuple     # sample (ny, nx, K)
    n_kernels: int
    kx: int
    ky: int
    padding: tuple
    sliding: tuple
    activation: str
    hyper: dict = field(default_factory=dict)
    hyper_bias: dict = field(default_factory=dict)
    flags: dict = field(default_factory=dict)
    weights_stddev: float = None
    bias_stddev: float = None
    weights_filling: str = "uniform"
    bias_filling: str = "uniform"
    include_bias: bool = True
    max_supposed: float = 1.0

    kind = "conv"
    is_softmax = False

    @property
    def n_channels(self):
        return self.in_shape[2]

    def init_stddev(self):
        """Reference conv magnitude heuristic (conv.py:137-146), capped at
        0.05 like Conv.initialize."""
        if self.weights_stddev is not None:
            return self.weights_stddev
        vle = 1.0 / (self.max_supposed *
                     numpy.sqrt(self.kx * self.ky * self.n_channels))
        if self.weights_filling == "gaussian":
            vle /= 3
        return min(vle, 0.05)


@dataclass
class PoolSpec:
    """max / maxabs / avg pooling (reference pooling.py ceil-mode
    geometry; winner-take-all gradient comes from the VJP of the gather —
    the same scatter-add the unit path runs, gd_pooling.py:233-247).

    ``impl`` selects the max-pool lowering:

    * "reduce_window" (DEFAULT): XLA select-and-scatter VJP; tie
      routing implementation-defined.  Measured the FASTEST lowering
      on a real v5e (r5 microbench, BENCH_NOTES.md).
    * "reshape" (sliding == kernel only): ky*kx strided slices +
      compare/select chain; VJP is a recomputed winner mask routed by
      interleave reshapes — no reduce_window/select-and-scatter/
      gather, unit-path first-winner ties.  Kept selectable as a
      measured negative result: TPU sublane-strided slices relayout,
      making it ~3x slower than reduce_window.
    * "offsets": the custom-VJP op ``ops/pooling.max_pooling_train_jax``
      — Pallas one-pass forward on a single-device TPU (window-view
      argmax elsewhere) and a dense shifted-accumulation backward to
      the recorded winners.  First-winner tie rule = the unit path's;
      no select-and-scatter and no scatter-add in the compiled
      program, but the per-row Pallas grid and the expansion traffic
      lose to select-and-scatter at large batch (kept selectable; the
      production pin proves all three lowerings agree on untied data).
    * "gather": argmax + gather with a scatter-add VJP — the float64
      parity/golden tests use it (its backward's summation ORDER
      matches the unit path's scatter on overlapping windows).

    avg uses reduce_window unless pool_impl forces "reshape" (no ties
    to break either way)."""
    type: str
    in_shape: tuple
    out_shape: tuple
    mode: str            # "max" | "maxabs" | "avg" | stochastic modes
    kx: int
    ky: int
    sliding: tuple
    impl: str = "reduce_window"

    kind = "pool"
    is_softmax = False


@dataclass
class LRNSpec:
    """Cross-channel local response normalization (normalization.py)."""
    type: str
    in_shape: tuple
    out_shape: tuple
    alpha: float = 1e-4
    beta: float = 0.75
    k: float = 2.0
    n: int = 5

    kind = "lrn"
    is_softmax = False


@dataclass
class ActivationSpec:
    """Standalone activation layer (activation.py)."""
    type: str
    in_shape: tuple
    out_shape: tuple
    activation: str = "linear"

    kind = "activation"
    is_softmax = False


@dataclass
class DeconvSpec:
    """Transposed conv SHARING the weights of a tied conv layer
    (reference deconv.py:55-347 — Deconv always demands external
    weights).  ``tied`` is the spec index of the conv whose weights it
    applies; reference parity means the loss gradient reaches those
    weights ONLY through the deconv application (MnistAE/ImagenetAE
    train GDDeconv alone, mnist_ae.py:146-153), so the tied conv's own
    application runs under ``stop_gradient``."""
    type: str
    in_shape: tuple      # (ny, nx, K)
    out_shape: tuple     # (H, W, C) — the tied conv's input shape
    tied: int
    n_kernels: int
    kx: int
    ky: int
    padding: tuple
    sliding: tuple
    unsafe_padding: bool = False

    kind = "deconv"
    is_softmax = False


@dataclass
class DepoolSpec:
    """Depooling — scatters activations to the winner offsets recorded
    by the tied pooling layer during THIS forward pass (reference
    depooling.py:48-144; the offsets contract of OffsetPooling)."""
    type: str
    in_shape: tuple
    out_shape: tuple     # the tied pool's input shape
    tied: int            # spec index of the pooling whose offsets to use

    kind = "depool"
    is_softmax = False


@dataclass
class ZeroFillSpec:
    """Placeholder for a ``zero_filter`` layer (reference
    weights_zerofilling.py:46-137): identity in the forward chain; its
    grouping mask attaches to the NEXT parameterized spec (the unit
    graph links the next forward's weights into the ZeroFiller).  Kept
    as a spec so the spec list stays 1:1 with the layer list."""
    type: str
    in_shape: tuple
    out_shape: tuple
    grouping: int

    kind = "zerofill"
    is_softmax = False


@dataclass
class DropoutSpec:
    """Inverted dropout: keep-mask / (1 - ratio) in train mode
    (reference dropout.py:147-153; the fused path draws the mask from a
    jax PRNG key instead of the host stream — same Bernoulli(1-ratio)
    distribution, device-resident)."""
    type: str
    in_shape: tuple
    out_shape: tuple
    ratio: float = 0.5

    kind = "dropout"
    is_softmax = False


def _normalize_sample_shape(shape):
    if isinstance(shape, (int, numpy.integer)):
        return (int(shape),)
    shape = tuple(int(s) for s in shape)
    if len(shape) == 2:    # (H, W) -> single implicit channel, as_nhwc
        shape = shape + (1,)
    return shape


def build_specs(layers, input_sample_shape, defaults=None):
    """Build the spec list from a declarative ``layers`` config.

    Each entry is a dict with "type" plus forward kwargs (optionally under
    "->") and backward kwargs (under "<-") — the reference config format
    (standard_workflow_base.py:406-422).  Sample shapes thread through the
    conv/pooling geometry exactly as the unit graph's initialize() chain
    does.
    """
    defaults = dict(DEFAULT_HYPER, **(defaults or {}))
    specs = []
    names = {}  # layer name -> spec index (for tied deconv/depool)
    pending_grouping = None  # zero_filter masks the NEXT layer's weights
    shape = _normalize_sample_shape(input_sample_shape)
    for index, layer in enumerate(layers):
        orig_layer = layer
        layer = dict(layer)
        tpe = layer.pop("type")
        name = layer.pop("name", None) or "%s_%d" % (tpe, index)
        fwd = dict(layer.pop("->", {}))
        layer.pop("<-", None)
        fwd.update({k: v for k, v in layer.items()})
        if tpe in FC_TYPES:
            oshape = fwd.get("output_sample_shape",
                             fwd.get("output_samples"))
            if oshape is None:
                raise ValueError("layer %r needs output_sample_shape" % tpe)
            n_out = int(numpy.prod(oshape))
            # ONE merge implementation shared with the GDProxy
            # surface (units/fused_trainer.py seeds proxies from the
            # same parse)
            hyper, hyper_bias, flags = layer_hyper(orig_layer, defaults)
            specs.append(FCSpec(
                type=tpe, n_in=int(numpy.prod(shape)), n_out=n_out,
                activation=("linear" if tpe == "softmax"
                            else _forward_class(tpe).ACTIVATION),
                hyper=hyper, hyper_bias=hyper_bias, flags=flags,
                weights_stddev=fwd.get("weights_stddev"),
                bias_stddev=fwd.get("bias_stddev"),
                weights_filling=fwd.get("weights_filling", "uniform"),
                bias_filling=fwd.get("bias_filling", "uniform"),
                include_bias=fwd.get("include_bias", True)))
            shape = (n_out,)
        elif tpe in CONV_TYPES:
            if len(shape) != 3:
                raise ValueError(
                    "conv layer %r needs a (H, W, C) input, have %r"
                    % (tpe, shape))
            kx, ky = int(fwd["kx"]), int(fwd["ky"])
            n_kernels = int(fwd["n_kernels"])
            padding = tuple(fwd.get("padding", (0, 0, 0, 0)))
            sliding = tuple(fwd.get("sliding", (1, 1)))
            ny, nx = conv_ops.output_spatial(
                shape[0], shape[1], ky, kx, padding, sliding)
            # ONE merge implementation shared with the GDProxy
            # surface (units/fused_trainer.py seeds proxies from the
            # same parse)
            hyper, hyper_bias, flags = layer_hyper(orig_layer, defaults)
            specs.append(ConvSpec(
                type=tpe, in_shape=shape, out_shape=(ny, nx, n_kernels),
                n_kernels=n_kernels, kx=kx, ky=ky,
                padding=padding, sliding=sliding,
                activation=_forward_class(tpe).ACTIVATION,
                hyper=hyper, hyper_bias=hyper_bias, flags=flags,
                weights_stddev=fwd.get("weights_stddev"),
                bias_stddev=fwd.get("bias_stddev"),
                weights_filling=fwd.get("weights_filling", "uniform"),
                bias_filling=fwd.get("bias_filling", "uniform"),
                include_bias=fwd.get("include_bias", True),
                max_supposed=fwd.get("input_max_supposed", 1.0)))
            shape = (ny, nx, n_kernels)
        elif tpe in POOL_TYPES:
            if len(shape) != 3:
                raise ValueError(
                    "pooling layer %r needs a (H, W, C) input, have %r"
                    % (tpe, shape))
            kx, ky = int(fwd["kx"]), int(fwd["ky"])
            sliding = tuple(fwd.get("sliding") or (kx, ky))
            mode = _POOL_MODES[tpe]
            if mode.endswith("_depool"):
                # pool+depool runs in place: output keeps the input
                # shape (reference stochastic_pooling_depooling kernel)
                out_shape = shape
            else:
                ny, nx = pool_ops.output_spatial(
                    shape[0], shape[1], ky, kx, sliding)
                out_shape = (ny, nx, shape[2])
            specs.append(PoolSpec(
                type=tpe, in_shape=shape, out_shape=out_shape,
                mode=mode, kx=kx, ky=ky, sliding=sliding))
            shape = out_shape
        elif tpe == "norm":
            if len(shape) != 3:
                raise ValueError(
                    "LRN layer needs a (H, W, C) input, have %r" % (shape,))
            specs.append(LRNSpec(
                type=tpe, in_shape=shape, out_shape=shape,
                alpha=fwd.get("alpha", 1e-4), beta=fwd.get("beta", 0.75),
                k=fwd.get("k", 2), n=fwd.get("n", 5)))
        elif tpe in ACTIVATION_TYPES:
            specs.append(ActivationSpec(
                type=tpe, in_shape=shape, out_shape=shape,
                activation=_forward_class(tpe).ACTIVATION))
        elif tpe == "dropout":
            specs.append(DropoutSpec(
                type=tpe, in_shape=shape, out_shape=shape,
                ratio=fwd.get("dropout_ratio", 0.5)))
        elif tpe == "zero_filter":
            pending_grouping = int(fwd.get("grouping", 2))
            if pending_grouping < 2:
                raise ValueError("grouping value %d is invalid"
                                 % pending_grouping)
            specs.append(ZeroFillSpec(
                type=tpe, in_shape=shape, out_shape=shape,
                grouping=pending_grouping))
        elif tpe == "deconv":
            tied_name = fwd.get("tied_to")
            if tied_name is None or tied_name not in names:
                raise ValueError(
                    "fused deconv needs tied_to=<conv layer name> "
                    "(the reference Deconv always shares weights, "
                    "deconv.py:55)")
            tied = names[tied_name]
            conv_spec = specs[tied]
            if conv_spec.kind != "conv":
                raise ValueError("tied_to %r is not a conv layer"
                                 % tied_name)
            if shape != conv_spec.out_shape:
                raise ValueError(
                    "deconv input %r != tied conv output %r"
                    % (shape, conv_spec.out_shape))
            out_shape = conv_spec.in_shape
            # the deconv runs in the tied conv's geometry — padding
            # included (reference AE stages link_conv_attrs copy the
            # conv's CONV_ATTRS onto the Deconv, mnist_ae.py:148-151)
            sl = conv_spec.sliding
            kx, ky = conv_spec.kx, conv_spec.ky
            padding = tuple(conv_spec.padding)
            # reference parity: only the deconv application trains the
            # shared weights (GDDeconv is the sole gradient unit in the
            # AE stages) — mark the conv to stop_gradient its own use
            conv_spec.stop_gradient = True
            # a "<-" on the deconv governs the SHARED weights' update
            # (reference: GDDeconv's kwargs), overriding the conv's
            if orig_layer.get("<-"):
                (conv_spec.hyper, conv_spec.hyper_bias,
                 conv_spec.flags) = layer_hyper(orig_layer, defaults)
            specs.append(DeconvSpec(
                type=tpe, in_shape=shape, out_shape=out_shape, tied=tied,
                n_kernels=conv_spec.n_kernels, kx=kx, ky=ky,
                padding=padding, sliding=sl,
                unsafe_padding=fwd.get("unsafe_padding", False)))
            shape = out_shape
        elif tpe == "depooling":
            tied_name = fwd.get("tied_to")
            if tied_name is None or tied_name not in names:
                raise ValueError(
                    "fused depooling needs tied_to=<pooling layer name>")
            tied = names[tied_name]
            pool_spec = specs[tied]
            if pool_spec.kind != "pool" or pool_spec.mode not in (
                    "max", "maxabs", "stochastic", "stochasticabs"):
                raise ValueError(
                    "tied_to %r is not an offset-recording pooling"
                    % tied_name)
            if shape != pool_spec.out_shape:
                raise ValueError(
                    "depooling input %r != tied pool output %r"
                    % (shape, pool_spec.out_shape))
            # the tied max pool must run the gather path to yield
            # offsets (stochastic pools always record winners)
            pool_spec.impl = "gather"
            pool_spec.record_offsets = True
            specs.append(DepoolSpec(
                type=tpe, in_shape=shape, out_shape=pool_spec.in_shape,
                tied=tied))
            shape = pool_spec.in_shape
        else:
            raise ValueError("fused path does not support layer type %r"
                             % tpe)
        names[name] = len(specs) - 1
        spec = specs[-1]
        if pending_grouping is not None and spec.kind in ("fc", "conv"):
            # the zero_filter grouping mask for this layer's weights
            # (reference mask: (k % G != c % G), zerofilling.py)
            if spec.kind == "fc":
                kernels, chans = spec.n_out, spec.n_in
            else:
                kernels = spec.n_kernels
                chans = spec.kx * spec.ky * spec.n_channels
            g = pending_grouping
            if chans % g:
                raise ValueError(
                    "Non-multiple of grouping weights shape: (%d, %d), "
                    "grouping=%d" % (kernels, chans, g))
            krow = numpy.arange(kernels)[:, None] % g
            ccol = numpy.arange(chans)[None, :] % g
            spec.weight_mask = (krow != ccol).astype(numpy.float64)
            pending_grouping = None
    return specs


def build_fc_specs(layers, input_sample_size, defaults=None):
    """FC-only builder (back-compat): rejects non-FC layer types."""
    specs = build_specs(layers, int(input_sample_size), defaults)
    for spec in specs:
        if spec.kind != "fc":
            raise ValueError("fused FC path does not support layer type %r"
                             % spec.type)
    return specs


def init_params(specs, rand=None, dtype=numpy.float32):
    """Host-side init with the unit path's exact draw order and fill
    semantics (weights then bias per layer, all2all.py:119-127 /
    conv.py:100-111; param-less layers draw nothing)."""
    rand = rand or prng.get()
    params = []
    for spec in specs:
        if spec.kind == "fc":
            w_shape = (spec.n_out, spec.n_in)
            n_bias = spec.n_out
        elif spec.kind == "conv":
            w_shape = (spec.n_kernels,
                       spec.kx * spec.ky * spec.n_channels)
            n_bias = spec.n_kernels
        else:
            params.append({})
            continue
        stddev = spec.init_stddev()
        bias_stddev = spec.bias_stddev if spec.bias_stddev is not None \
            else stddev
        w = numpy.zeros(w_shape, dtype=dtype)
        _fill(rand, spec.weights_filling, w, stddev)
        p = {"w": w}
        if spec.include_bias:
            b = numpy.zeros(n_bias, dtype=dtype)
            _fill(rand, spec.bias_filling, b, bias_stddev)
            p["b"] = b
        params.append(p)
    return params


def _fill(rand, filling, array, stddev):
    from znicz_tpu.units.nn_units import fill_array
    fill_array(rand, filling, array, stddev)


def init_opt_state(specs, params):
    """Optimizer-state pytree mirroring the per-layer Arrays of the unit
    path (vel = gradient_*_with_moment, acc, solver slots)."""
    states = []
    for spec, p in zip(specs, params):
        st = {}
        if "w" in p:
            st["w"] = gd_math.init_state(
                p["w"], dict(spec.flags, need_vel=True))
        if "b" in p:
            st["b"] = gd_math.init_state(
                p["b"], dict(spec.flags, need_vel=True))
        states.append(st)
    return states


def forward(params, x, specs, return_logits=False, key=None, train=False,
            compute_dtype=None):
    """Pure forward pass through the whole spec stack.

    With ``return_logits`` the softmax head is left un-normalized (for the
    CE loss); otherwise softmax is applied.  ``key``/``train`` drive
    dropout masks; inference leaves dropout as identity (reference
    dropout.py:84-190 TRAIN gating).

    ``compute_dtype`` (e.g. ``jnp.bfloat16``) casts activations and
    parameters at each matmul/conv so the GEMMs run at the MXU's native
    rate; master parameters stay float32 and the softmax/loss math is
    always done in float32.
    """
    cd = compute_dtype

    def _p(arr):
        return arr if (cd is None or arr is None) else arr.astype(cd)

    y = x if cd is None else x.astype(cd)
    deferred_act = None  # activation commuted past a following max-pool
    offsets = {}         # spec index -> winner offsets (for tied depool)
    for i, (p, spec) in enumerate(zip(params, specs)):
        if deferred_act is not None and spec.kind != "pool":
            raise AssertionError("deferred activation not consumed")
        if spec.kind == "fc":
            y = y.reshape(y.shape[0], -1)
            w = _p(p["w"])
            mask = getattr(spec, "weight_mask", None)
            if mask is not None:
                w = w * jnp.asarray(mask, w.dtype)
            y = y @ w.T
            if "b" in p:
                y = y + _p(p["b"])
            if not spec.is_softmax:
                y = activations.apply_jax(spec.activation, y)
            elif not return_logits:
                if cd is not None:
                    y = y.astype(jnp.float32)
                y = jax.nn.softmax(y, axis=1)
        elif spec.kind == "conv":
            y = y.reshape((y.shape[0],) + spec.in_shape)
            w = _p(p["w"])
            mask = getattr(spec, "weight_mask", None)
            if mask is not None:
                w = w * jnp.asarray(mask, w.dtype)
            if getattr(spec, "stop_gradient", False):
                # weights shared with a tied deconv: only the DECONV
                # application trains them (reference AE stages run
                # GDDeconv as the sole gradient unit)
                w = jax.lax.stop_gradient(w)
            act = spec.activation
            # strictly monotonic activations commute with max pooling
            # (max(f(x)) == f(max(x)), bit-exact for the same winner);
            # applying f AFTER the pool does 1/(kx*ky) the transcendental
            # + HBM work — the dominant non-GEMM cost on TPU
            if (act in _MONOTONIC_ACTS
                    and i + 1 < len(specs)
                    and specs[i + 1].kind == "pool"
                    and specs[i + 1].mode == "max"):
                deferred_act, act = act, "linear"
            y = conv_ops.forward_jax(
                y, w, _p(p.get("b")), spec.ky, spec.kx,
                spec.padding, spec.sliding, activation=act,
                include_bias="b" in p)
        elif spec.kind == "pool":
            y = y.reshape((y.shape[0],) + spec.in_shape)
            if spec.mode.startswith("stochastic"):
                # winners sampled from the jax PRNG key (distribution
                # parity with the unit path's host uint16 stream,
                # reference pooling.py:434-480; exact stream parity
                # waived like dropout's) — the SAME op as the unit jax
                # path, fed device-drawn u16s
                if key is None:
                    raise ValueError(
                        "stochastic pooling needs a PRNG key (fused nets "
                        "with stochastic specs thread one through "
                        "predict too)")
                key, sub = jax.random.split(key)
                b = y.shape[0]
                if spec.mode.endswith("_depool"):
                    ny, nx = pool_ops.output_spatial(
                        spec.in_shape[0], spec.in_shape[1], spec.ky,
                        spec.kx, (spec.kx, spec.ky))
                else:
                    ny, nx, _ = spec.out_shape
                n = b * ny * nx * spec.in_shape[2]
                u16 = jax.random.randint(
                    sub, (n,), 0, 65536, dtype=jnp.int32).astype(
                        jnp.uint16)
                use_abs = "abs" in spec.mode
                if spec.mode.endswith("_depool"):
                    y, offs = pool_ops.stochastic_pool_depool_jax(
                        y, u16, spec.ky, spec.kx, use_abs=use_abs)
                else:
                    y, offs = pool_ops.stochastic_pooling_jax(
                        y, u16, spec.ky, spec.kx, spec.sliding,
                        use_abs=use_abs)
                offsets[i] = offs
            elif getattr(spec, "record_offsets", False):
                y, offs = pool_ops.max_pooling_gather_jax(
                    y, spec.ky, spec.kx, spec.sliding,
                    use_abs=spec.mode == "maxabs")
                offsets[i] = offs
            elif spec.impl == "reshape":
                # non-overlapping windows: strided-slice compare/select
                # chain, elementwise VJP — no reduce_window, no
                # select-and-scatter, no gather (ops/pooling.py;
                # opt-in via pool_impl — measured slower than
                # reduce_window on TPU, BENCH_NOTES.md r5)
                if spec.mode == "avg":
                    y = pool_ops.avg_pooling_reshape_jax(
                        y, spec.ky, spec.kx)
                else:
                    y = pool_ops.max_pooling_reshape_jax(
                        y, spec.ky, spec.kx, spec.mode == "maxabs")
            elif spec.mode != "avg" and spec.impl == "offsets":
                # production path: custom-VJP op — Pallas/window-view
                # forward with recorded winners, dense accumulation
                # backward (no select-and-scatter, no scatter-add)
                y, offs = pool_ops.max_pooling_train_jax(
                    y, spec.ky, spec.kx, spec.sliding,
                    spec.mode == "maxabs",
                    getattr(spec, "prefer_pallas", True))
                offsets[i] = offs
            elif spec.mode != "avg" and spec.impl == "gather":
                # gather path: gradient scatters to the FIRST maximum —
                # exact tie parity with the unit path (flat regions tie;
                # reduce_window's select-and-scatter routes ties
                # implementation-defined, maxabs even breaks |tie|s
                # toward the positive value).  NOT max_pooling_jax: that
                # routes to the Pallas kernel, which has no autodiff rule
                # (this forward is grad'd).
                y, _ = pool_ops.max_pooling_gather_jax(
                    y, spec.ky, spec.kx, spec.sliding,
                    use_abs=spec.mode == "maxabs")
            else:
                y = pool_ops.pooling_fwd_jax(
                    y, spec.ky, spec.kx, spec.sliding, mode=spec.mode)
            if deferred_act is not None:
                y = activations.apply_jax(deferred_act, y)
                deferred_act = None
        elif spec.kind == "deconv":
            y = y.reshape((y.shape[0],) + spec.in_shape)
            w = _p(params[spec.tied]["w"])
            out_shape = (y.shape[0],) + spec.out_shape
            y = conv_ops.deconv_forward_jax(
                y, w, spec.ky, spec.kx, spec.padding, spec.sliding,
                out_shape)
            if spec.unsafe_padding:
                hits = conv_ops.deconv_hits_jax(
                    (y.shape[0],) + spec.in_shape[:2], spec.ky, spec.kx,
                    spec.padding, spec.sliding, out_shape)
                div = y / jnp.maximum(hits, 1).astype(y.dtype)[:, :, :, None]
                # value = y/hits, gradient = identity: the reference
                # GDDeconv backpropagates the UNDIVIDED scatter (the
                # hits normalization is absent from gd_deconv's
                # gradient, deconv.py/gd_deconv.py) — keep that parity
                y = y + jax.lax.stop_gradient(div - y)
        elif spec.kind == "depool":
            y = y.reshape((y.shape[0],) + spec.in_shape)
            full = (y.shape[0],) + spec.out_shape
            y = pool_ops.max_pooling_backward_jax(
                y, offsets[spec.tied],
                int(numpy.prod(full)), full)
        elif spec.kind == "lrn":
            y = y.reshape((y.shape[0],) + spec.in_shape)
            y = norm_ops.lrn_forward_jax(
                y, alpha=spec.alpha, beta=spec.beta, k=spec.k, n=spec.n)
        elif spec.kind == "activation":
            y = activations.apply_jax(spec.activation, y)
        elif spec.kind == "dropout":
            if train and key is not None:
                key, sub = jax.random.split(key)
                keep = jax.random.uniform(sub, y.shape) >= spec.ratio
                y = y * keep.astype(y.dtype) / (1.0 - spec.ratio)
        elif spec.kind == "zerofill":
            pass  # identity: its mask is applied at the target layer
        else:  # pragma: no cover - build_specs rejects unknown kinds
            raise AssertionError(spec.kind)
    return y


def _loss_and_stats(params, x, labels, specs, key=None, compute_dtype=None):
    """Mean softmax-CE loss (matches evaluator err_output scaling,
    ops/evaluator.py) + error count + softmax output/argmax.  Loss math is
    float32 even when the forward GEMMs run in a lower ``compute_dtype``."""
    y = forward(params, x, specs, return_logits=True, key=key, train=True,
                compute_dtype=compute_dtype)
    if compute_dtype is not None:
        y = y.astype(jnp.float32)
    logp = jax.nn.log_softmax(y, axis=1)
    valid = labels >= 0
    lbl = jnp.maximum(labels, 0)
    ce = -jnp.take_along_axis(logp, lbl[:, None], axis=1)[:, 0]
    ce = jnp.where(valid, ce, 0.0)
    loss = ce.sum() / jnp.maximum(valid.sum(), 1)
    max_idx = jnp.argmax(y, axis=1).astype(jnp.int32)
    n_err = (valid & (max_idx != lbl)).sum()
    probs = jnp.exp(logp)
    return loss, (n_err, probs, max_idx)


def _loss_and_stats_mse(params, x, target, batch_size, specs, key=None,
                        compute_dtype=None):
    """MSE objective: loss = sum((y-t)^2) / (2*batch) so that
    d(loss)/dy == (y - t)/batch — exactly the unit evaluator's
    ``err_output`` scaling (ops/evaluator.py mse, mean=True; reference
    evaluator.py:334-556).  Rows past ``batch_size`` (padded tail
    minibatch) are masked out like the evaluator does."""
    y = forward(params, x, specs, key=key, train=True,
                compute_dtype=compute_dtype)
    if compute_dtype is not None:
        y = y.astype(jnp.float32)
    B = y.shape[0]
    o2 = y.reshape(B, -1)
    t2 = target.reshape(B, -1).astype(o2.dtype)
    valid = jnp.arange(B) < batch_size
    diff = jnp.where(valid[:, None], o2 - t2, 0)
    loss = 0.5 * (diff * diff).sum() / jnp.maximum(batch_size, 1)
    return loss, y


def _eval_stats(probs, max_idx, labels, batch_size, n_classes, mean,
                shards=1):
    """Evaluator-identical per-minibatch stats computed INSIDE the
    compiled window (ops/evaluator.softmax_ce_jax semantics, reference
    evaluator.py:271-312): n_err_delta[2], confusion_delta[C,C],
    max_err_output_sum.  Same masking (in-batch AND label >= 0) and the
    same ``err = (probs - onehot) * mult`` row math, so the windowed
    control plane accumulates the exact integers/floats the per-minibatch
    evaluator would.

    ``shards > 1`` (a data-parallel mesh): every reduction runs over the
    LOCAL batch rows only — outputs gain a leading ``shards`` axis
    (n_err[S,2], confusion[S,C,C], max_err_sum[S]) that stays sharded
    ``P("data", ...)``, so mid-epoch windows insert NO stats collective;
    the per-segment all-reduce folds the partials once, at the
    segment-final window (see _get_window_fn).  Integer partials reduce
    exactly; the max is order-independent — the sharded aggregates equal
    the single-device fold bit for bit (docs/distributed.md)."""
    B = probs.shape[0]
    idx = jnp.arange(B)
    in_batch = idx < batch_size
    valid = in_batch & (labels >= 0)
    hits = valid & (max_idx == labels)
    if shards == 1:
        n_total = valid.sum()
        n_ok = hits.sum()
        n_err2 = jnp.stack([n_total - n_ok, n_total]).astype(jnp.int32)
        onehot = jax.nn.one_hot(jnp.maximum(labels, 0), n_classes,
                                dtype=probs.dtype)
        # confusion[pred, label] += valid — as a one-hot GEMM, not a
        # scatter-add (TPU scatters with duplicate indices serialize; the
        # f32 accumulation is exact for counts < 2^24)
        pred_onehot = jax.nn.one_hot(max_idx, n_classes, dtype=jnp.float32)
        conf = ((pred_onehot * valid[:, None].astype(jnp.float32)).T
                @ onehot.astype(jnp.float32)).astype(jnp.int32)
        mult = jnp.where(mean, 1.0 / jnp.maximum(batch_size, 1), 1.0)
        err = (probs - onehot) * mult.astype(probs.dtype)
        mx = jnp.where(valid, jnp.abs(err).sum(axis=1), 0).max()
        return n_err2, conf, mx
    b = B // shards
    n_total = valid.reshape(shards, b).sum(axis=1)
    n_ok = hits.reshape(shards, b).sum(axis=1)
    n_err2 = jnp.stack([n_total - n_ok, n_total],
                       axis=-1).astype(jnp.int32)
    onehot = jax.nn.one_hot(jnp.maximum(labels, 0), n_classes,
                            dtype=probs.dtype)
    pred_onehot = jax.nn.one_hot(max_idx, n_classes, dtype=jnp.float32)
    pv = (pred_onehot * valid[:, None].astype(jnp.float32)).reshape(
        shards, b, n_classes)
    oh = onehot.astype(jnp.float32).reshape(shards, b, n_classes)
    # per-shard one-hot GEMM: the batch contraction stays inside the
    # shard's local rows — no cross-shard traffic
    conf = jnp.einsum("sbp,sbl->spl", pv, oh).astype(jnp.int32)
    mult = jnp.where(mean, 1.0 / jnp.maximum(batch_size, 1), 1.0)
    err = (probs - onehot) * mult.astype(probs.dtype)
    mx = jnp.where(valid, jnp.abs(err).sum(axis=1),
                   0).reshape(shards, b).max(axis=1)
    return n_err2, conf, mx


def _train_step_mse(params, state, x, target, batch_size, specs, key=None,
                    compute_dtype=None, hypers=None):
    params = _apply_weight_masks(params, specs)
    (loss, y), grads = jax.value_and_grad(
        lambda p: _loss_and_stats_mse(p, x, target, batch_size, specs,
                                      key, compute_dtype),
        has_aux=True)(params)
    new_params, new_state = [], []
    if hypers is None:
        hypers = [None] * len(params)
    for spec, p, st, g, hy in zip(specs, params, state, grads, hypers):
        np_, nst = {}, {}
        if "w" in p:
            np_["w"], nst["w"], _ = gd_math.update(
                jnp, p["w"], g["w"].astype(p["w"].dtype), st["w"],
                hy["w"] if hy else spec.hyper, spec.flags)
        if "b" in p:
            hyper_b = hy["b"] if hy else spec.hyper_bias
            flags_b = dict(spec.flags, ortho=False)
            np_["b"], nst["b"], _ = gd_math.update(
                jnp, p["b"], g["b"].astype(p["b"].dtype), st["b"],
                hyper_b, flags_b)
        new_params.append(np_)
        new_state.append(nst)
    return new_params, new_state, {"loss": loss, "output": y}


class ShardMajorWindow(object):
    """A host-staged ``(K, B, ...)`` window laid out SHARD-MAJOR:
    ``base`` has shape ``(S, K, B // S, ...)`` where ``S`` is the data-
    parallel shard count, so each shard's rows are one contiguous host
    block (``base[s]``) and :meth:`FusedNet._place_window` can feed
    ``device_put`` per-shard memcpys instead of strided splits of a
    batch-major stack (units/fused_trainer.py allocates these via the
    staging ring; Loader.fill_window_slot writes straight into the
    per-step ``base[:, i]`` views)."""

    __slots__ = ("base",)

    def __init__(self, base):
        self.base = base

    @property
    def shape(self):
        """The LOGICAL (K, B, ...) window shape."""
        s, k, b = self.base.shape[:3]
        return (k, s * b) + tuple(self.base.shape[3:])

    @property
    def ndim(self):
        return self.base.ndim - 1


def reduce_window_partials(stats, objective):
    """Host-side fold of per-shard window partials (leading ``S`` axis,
    see ``_eval_stats(shards=...)``) into the single-device aggregate
    shapes — the synchronous control plane's per-window host reduce
    under a data mesh (the async path folds the same reduction into the
    segment-final window executable instead)."""
    out = dict(stats)
    if objective == "mse":
        m = numpy.asarray(stats["metrics"])
        out["metrics"] = numpy.stack(
            [m[:, 0].sum(), m[:, 1].max(), m[:, 2].min()])
        out["n_err"] = numpy.asarray(stats["n_err"]).sum(axis=0)
    else:
        out["n_err"] = numpy.asarray(stats["n_err"]).sum(axis=0)
        if "confusion" in stats:
            out["confusion"] = numpy.asarray(
                stats["confusion"]).sum(axis=0)
        if "max_err_sum" in stats:
            out["max_err_sum"] = numpy.asarray(
                stats["max_err_sum"]).max(axis=0)
    return out


def flops_per_image(specs):
    """Analytic forward FLOPs per sample (matmul/conv MACs × 2) — the
    basis for the bench's MFU estimate (train step ≈ 3 × forward)."""
    total = 0
    for spec in specs:
        if spec.kind == "fc":
            total += 2 * spec.n_in * spec.n_out
        elif spec.kind == "conv":
            ny, nx, k = spec.out_shape
            total += 2 * ny * nx * k * spec.kx * spec.ky * spec.n_channels
        elif spec.kind == "deconv":
            ny, nx, k = spec.in_shape
            total += 2 * ny * nx * k * spec.kx * spec.ky * spec.out_shape[2]
    return total


class FusedNet:
    """Compiled trainer for a feed-forward spec stack over an optional
    device mesh."""

    def __init__(self, layers, input_sample_shape, mesh=None, rand=None,
                 dtype=numpy.float32, defaults=None, dropout_seed=0,
                 compute_dtype=None, pool_impl=None,
                 objective="softmax"):
        self.specs = build_specs(layers, input_sample_shape, defaults)
        for spec in self.specs:
            if spec.kind == "pool" and \
                    not getattr(spec, "record_offsets", False):
                nonoverlap = tuple(spec.sliding) == (spec.kx, spec.ky)
                if pool_impl is None:
                    # production default: reduce_window — measured
                    # FASTEST on a real v5e (r5 microbench: pool1 f+b
                    # 10.3ms vs 30.8ms "reshape" / 73.8ms "offsets";
                    # TPU sublane-strided slices force relayout copies,
                    # so the elementwise-VJP lowerings lose despite
                    # their lower op count — see BENCH_NOTES.md)
                    spec.impl = "reduce_window"
                else:
                    if pool_impl == "reshape" and not nonoverlap:
                        raise ValueError(
                            "pool_impl='reshape' needs sliding == kernel "
                            "(got %r vs (%d, %d))"
                            % (spec.sliding, spec.kx, spec.ky))
                    spec.impl = pool_impl
            if spec.kind == "pool":
                # the Pallas forward is single-device; under a mesh the
                # offsets impl keeps the window-view forward (GSPMD
                # partitions it like any XLA op)
                spec.prefer_pallas = mesh is None
        self.compute_dtype = compute_dtype
        self.input_sample_shape = _normalize_sample_shape(input_sample_shape)
        self.objective = objective
        #: master-parameter dtype (the forward's output dtype when no
        #: compute_dtype is forced)
        self.dtype = dtype
        #: evaluator ``mean`` flag mirrored into the in-scan stats
        #: (window mode) — the trainer unit copies it from the linked
        #: evaluator before initialize
        self.stats_mean = True
        #: compiled window functions keyed by (n_steps, mode[, batch])
        self._window_fns = {}
        #: device-resident epoch accumulators for the decision aggregates
        #: (n_err / confusion / max_err_sum, or the MSE [sum,max,min]
        #: metrics + class-target n_err).  Every window executable takes
        #: the running accumulator as a donated argument and returns the
        #: folded total under ``stats["acc"]`` — the asynchronous control
        #: plane reads them back ONCE per segment instead of per window
        #: (units/fused_trainer.py).  None = zeros on the next window.
        self._win_acc = None
        self._data_d = None
        self._labels_d = None
        #: per-epoch materialized permutation of the device dataset
        #: (set_epoch_perm) — consumed by contiguous dynamic slices
        self._data_p = None
        self._labels_p = None
        self._targets_d = None
        self._targets_p = None
        self._perm_fns = {}
        #: MSE extras mirrored from the evaluator by the trainer unit
        #: BEFORE the first window: per-sample sqrt (EvaluatorMSE.root)
        #: and the optional nearest-class-target matrix
        self.mse_root = True
        self.class_targets = None
        if objective == "softmax":
            if not self.specs[-1].is_softmax:
                raise ValueError(
                    "the fused softmax objective needs a 'softmax' head "
                    "(got %r); pass objective='mse' for regression/AE "
                    "topologies." % self.specs[-1].type)
            if any(s.is_softmax for s in self.specs[:-1]):
                raise ValueError(
                    "softmax is only supported as the head of a fused net")
        elif objective == "mse":
            if any(s.is_softmax for s in self.specs):
                raise ValueError(
                    "the mse objective does not take a softmax head")
        else:
            raise ValueError("unknown objective %r" % objective)
        self.mesh = mesh
        #: data-parallel shard count (1 without a mesh).  When > 1 the
        #: windowed epoch accumulators keep a leading shard axis
        #: (sharded P("data", ...)) and mid-epoch windows run with ZERO
        #: stats collectives; the segment-final window folds the one
        #: all-reduce per segment (_get_window_fn final=True).
        self._dp = 1 if mesh is None else int(mesh.shape["data"])
        params_host = init_params(self.specs, rand, dtype)
        states_host = init_opt_state(self.specs, params_host)
        self.params = self._place_params(params_host)
        # state slots shard exactly like their parameter (vel mirrors w);
        # mismatched initial placement would force a second full compile
        # when the donated step returns GSPMD-sharded state.
        self.state = self._place_state(states_host)
        self._key = jax.random.PRNGKey(dropout_seed)
        if mesh is not None:
            # replicate the key over the mesh up front: a default single-
            # device placement would differ from the sharding the compiled
            # step/scan returns, costing a recompile on the second call
            self._key = jax.device_put(
                self._key, NamedSharding(mesh, P()))
        self._has_dropout = any(s.kind == "dropout" for s in self.specs)
        self._has_stochastic = any(
            s.kind == "pool" and s.mode.startswith("stochastic")
            for s in self.specs)
        #: specs that consume PRNG draws per step (dropout masks,
        #: stochastic-pool winners) advance the key chain
        self._needs_key = self._has_dropout or self._has_stochastic
        #: live hyperparameters — mutated by LR schedules / rollback and
        #: passed to the jitted step as traced scalars (no recompile)
        self.hypers = default_hypers(self.specs)
        # specs close over the traced functions (they carry dicts, so they
        # can't be hashable static args); only the FLAGS stay compile-time
        # constants — hyper values are traced arguments.
        specs = tuple(self.specs)
        if objective == "mse":
            step_fn = lambda p, s, x, t, bs, k, hy: _train_step_mse(  # noqa: E731,E501
                p, s, x, t, bs, specs, k, compute_dtype, hy)
        else:
            step_fn = lambda p, s, x, l, k, hy: _train_step(  # noqa: E731
                p, s, x, l, specs, k, compute_dtype, hy, with_output=True)
        #: multi-host: batch-sharded outputs are not fully addressable
        #: for device_get.  The WINDOW outputs stay data-sharded (they
        #: are read only on segment-final windows — replicating inside
        #: every compiled window would pay a per-window DCN all-gather
        #: for unread buffers) and :meth:`host_fetch` reshards at
        #: readback; the PREDICT outputs are consumed every call, so
        #: those jits return replicated directly
        self._replicate_outputs = (mesh is not None
                                   and jax.process_count() > 1)
        if mesh is not None:
            # Pin output shardings to the input placements: GSPMD would
            # otherwise return spec variants (P('model',) vs
            # P('model', None)) that hash differently and force a second
            # full compile of the donated step.
            pshard = [{k: NamedSharding(mesh, self._param_spec(s, k))
                       for k in p} for s, p in zip(self.specs, self.params)]
            sshard = [{k: {kk: NamedSharding(mesh, self._param_spec(s, k))
                           for kk in slots.keys()}
                       for k, slots in st.items()}
                      for s, st in zip(self.specs, self.state)]
            out_ndim = 1 + len(self.specs[-1].out_shape)
            rep = NamedSharding(mesh, P())
            oshard = NamedSharding(
                mesh, P("data", *([None] * (out_ndim - 1))))
            ishard = NamedSharding(mesh, P("data"))
            if objective == "mse":
                mshard = {"loss": rep, "output": oshard}
            else:
                mshard = {"loss": rep, "n_err": rep,
                          "output": oshard, "max_idx": ishard}
            self._pshard, self._sshard = pshard, sshard
            self._step = jax.jit(step_fn, donate_argnums=(0, 1),
                                 out_shardings=(pshard, sshard, mshard))
        else:
            self._pshard = self._sshard = None
            self._step = jax.jit(step_fn, donate_argnums=(0, 1))
        # stochastic-pool nets sample winners at inference too (reference
        # StochasticPooling draws on every run, pooling.py:368-460) — the
        # compiled forward takes a key; others keep the keyless signature
        fwd_kw = {}
        if self._replicate_outputs:
            # inference outputs are host-read by the evaluator — same
            # multi-host addressability rule as the train-step outputs
            fwd_kw["out_shardings"] = NamedSharding(mesh, P())
        self._fwd = jax.jit(
            lambda p, x, k=None: forward(p, x, specs, key=k,
                                         compute_dtype=compute_dtype),
            **fwd_kw)

        def fwd_idx(p, x, k=None):
            probs = forward(p, x, specs, key=k,
                            compute_dtype=compute_dtype)
            return probs, jnp.argmax(probs, axis=1).astype(jnp.int32)

        self._fwd_idx = jax.jit(fwd_idx, **({"out_shardings": (
            fwd_kw["out_shardings"], fwd_kw["out_shardings"])}
            if fwd_kw else {}))

    # -- sharding -----------------------------------------------------------
    @property
    def data_shards(self):
        """The mesh's data-parallel extent (1 when unsharded)."""
        return self._dp

    def _param_spec(self, spec, name):
        """model-axis sharding for wide FC layers, replicated otherwise
        (conv kernels are small — replication beats the all-gather)."""
        if self.mesh is None:
            return None
        msize = self.mesh.shape["model"]
        if (spec.kind == "fc" and msize > 1 and spec.n_out % msize == 0):
            return P("model", None) if name == "w" else P("model")
        return P()

    def _place_params(self, params_host):
        if self.mesh is None:
            return jax.tree.map(jax.device_put, params_host)
        placed = []
        for spec, p in zip(self.specs, params_host):
            q = {}
            for name, arr in p.items():
                ns = NamedSharding(self.mesh, self._param_spec(spec, name))
                q[name] = jax.device_put(arr, ns)
            placed.append(q)
        return placed

    def _place_state(self, states_host):
        if self.mesh is None:
            return jax.tree.map(jax.device_put, states_host)
        placed = []
        for spec, st in zip(self.specs, states_host):
            q = {}
            for name, slots in st.items():
                ns = NamedSharding(self.mesh, self._param_spec(spec, name))
                q[name] = {k: jax.device_put(v, ns)
                           for k, v in slots.items()}
            placed.append(q)
        return placed

    def _place_batch(self, x, labels):
        if self.mesh is None:
            return jax.device_put(x), jax.device_put(labels)
        mesh_mod.check_data_batch(self.mesh, x.shape[0])
        xs = NamedSharding(self.mesh, P("data", *([None] * (x.ndim - 1))))
        ls = NamedSharding(self.mesh, P("data"))
        return jax.device_put(x, xs), jax.device_put(labels, ls)

    # -- cost accounting ----------------------------------------------------
    def _register_cost(self, name, fn, args, steps, batch, train=True):
        """Executable cost-registry hook (core/profiler.py): lower the
        already-traced jit BEFORE its first dispatch and record XLA's
        ``cost_analysis`` FLOPs/bytes next to the analytic estimate
        (train step ≈ 3 × forward — the bench's MFU convention;
        forward-only for predict).  Window executables pass their step
        count as ``scan_steps`` — HLO cost analysis counts the scan
        body once, so the profiler scales by K.  Registered names are
        checked FIRST so the armed steady-state cost really is one
        dict lookup per dispatch — the analytic spec walk and the meta
        tuple are built only for the first dispatch of each name."""
        if profiler.cost_entry(name) is not None:
            return
        fpi = flops_per_image(self.specs)
        mult = 3.0 if train else 1.0
        profiler.register_jit_cost(
            name, fn, args,
            analytic_flops=mult * fpi * int(batch) * int(steps),
            scan_steps=int(steps),
            steps=int(steps), batch=int(batch),
            analytic_flops_per_image=mult * fpi)

    # -- public api ---------------------------------------------------------
    def step(self, x, labels, hypers=None):
        """One fused train step.  Returns {"loss", "n_err", "output",
        "max_idx"} (output/max_idx device-resident).  ``hypers`` overrides
        the live hyperparameter pytree for this step (traced — schedules
        cost no recompile)."""
        if self.objective != "softmax":
            raise ValueError("use step_mse for objective %r"
                             % self.objective)
        x, labels = self._place_batch(x, labels)
        if self._needs_key:
            self._key, key = jax.random.split(self._key)
        else:
            key = self._key
        hy = self.hypers if hypers is None else hypers
        if profiler.enabled():
            self._register_cost(
                "fused.step", self._step,
                (self.params, self.state, x, labels, key, hy),
                steps=1, batch=x.shape[0])
        self.params, self.state, metrics = self._step(
            self.params, self.state, x, labels, key, hy)
        return metrics

    def step_mse(self, x, target, batch_size=None, hypers=None):
        """One fused MSE train step.  ``batch_size`` masks the padded
        tail rows (defaults to the full batch).  Returns {"loss",
        "output"}."""
        if self.objective != "mse":
            raise ValueError("use step for objective %r" % self.objective)
        if batch_size is None:
            batch_size = x.shape[0]
        x, _ = self._place_batch(x, numpy.zeros(x.shape[0], numpy.int32))
        target = jax.device_put(
            numpy.asarray(target),
            None if self.mesh is None else NamedSharding(
                self.mesh, P("data", *([None] * (target.ndim - 1)))))
        if self._needs_key:
            self._key, key = jax.random.split(self._key)
        else:
            key = self._key
        hy = self.hypers if hypers is None else hypers
        if profiler.enabled():
            self._register_cost(
                "fused.step_mse", self._step,
                (self.params, self.state, x, target,
                 numpy.int32(batch_size), key, hy),
                steps=1, batch=x.shape[0])
        self.params, self.state, metrics = self._step(
            self.params, self.state, x, target,
            numpy.int32(batch_size), key, hy)
        return metrics

    def run_steps(self, xs, labels_s):
        """Many fused train steps in ONE compiled call via ``lax.scan``.

        ``xs``: (n_steps, batch, *sample), ``labels_s``: (n_steps, batch).
        The whole loop is a single XLA computation — no per-step dispatch,
        which matters when launch latency is non-trivial (remote/tunneled
        devices) and is the idiomatic TPU epoch loop.  Returns stacked
        per-step metrics.
        """
        if self.objective != "softmax":
            raise ValueError("run_steps supports the softmax objective; "
                             "drive step_mse per minibatch instead")
        if not hasattr(self, "_scan_step"):
            specs = tuple(self.specs)
            cd = self.compute_dtype

            def body(carry, batch):
                p, s, k, hy = carry
                x, l = batch
                if self._needs_key:
                    k, sub = jax.random.split(k)
                else:
                    sub = k
                p, s, m = _train_step(p, s, x, l, specs, sub, cd, hy)
                return (p, s, k, hy), m

            def scan_fn(p, s, k, xs, ls, hy):
                (p, s, k, hy), ms = jax.lax.scan(body, (p, s, k, hy),
                                                 (xs, ls))
                return p, s, k, ms

            if self.mesh is not None:
                # pin output shardings to the input placements, same as
                # _step in __init__: un-pinned GSPMD output spec variants
                # would force a full recompile of the donated scan on the
                # next call
                rep = NamedSharding(self.mesh, P())
                mshard = {"loss": rep, "n_err": rep}
                self._scan_step = jax.jit(
                    scan_fn, donate_argnums=(0, 1),
                    out_shardings=(self._pshard, self._sshard, rep, mshard))
            else:
                self._scan_step = jax.jit(scan_fn, donate_argnums=(0, 1))
        if self.mesh is not None:
            mesh_mod.check_data_batch(self.mesh, xs.shape[1])
            xs = jax.device_put(xs, NamedSharding(
                self.mesh, P(None, "data", *([None] * (xs.ndim - 2)))))
            labels_s = jax.device_put(
                labels_s, NamedSharding(self.mesh, P(None, "data")))
        else:
            xs = jax.device_put(xs)
            labels_s = jax.device_put(labels_s)
        self.params, self.state, self._key, metrics = self._scan_step(
            self.params, self.state, self._key, xs, labels_s, self.hypers)
        return metrics

    # -- windowed training (the control plane's hot loop) -------------------
    def set_dataset(self, data, labels, targets=None):
        """Place the WHOLE training dataset on device once (replicated
        over the mesh).  Windowed train steps then gather their
        minibatches on device from ``(window, batch)`` index arrays — the
        TPU-native data path: per window only the indices cross the
        host/device boundary (SURVEY.md §7; the reference's equivalent is
        the loader's host-side fancy-index fill, loader/base observed
        contract).

        Under a bf16 ``compute_dtype`` the dataset is STORED in bf16:
        the forward casts x to bf16 anyway, gather commutes with the
        cast (bit-identical), and the row gather is the one HBM-
        bandwidth-bound op of the window (XLA's TPU gather runs far
        below stream bandwidth, so bytes matter — see BENCH_NOTES.md)."""
        data = numpy.ascontiguousarray(data)
        if self.compute_dtype is not None:
            data = jnp.asarray(data).astype(self.compute_dtype)
        rep = None if self.mesh is None else NamedSharding(self.mesh, P())
        self._data_d = jax.device_put(data, rep)
        if labels is None or not len(labels):
            # MSE datasets may carry no labels; the padded sentinel
            # keeps every label-consuming path inert
            labels = numpy.full(len(data), -1, numpy.int32)
        self._labels_d = jax.device_put(
            numpy.asarray(labels, dtype=numpy.int32), rep)
        self._targets_d = None
        if targets is not None:
            # targets keep float32 (not the bf16 compute dtype): the
            # MSE loss/stats math is float32 even in bf16 mode and the
            # per-minibatch path feeds it unrounded targets — storing
            # bf16 would change the loss, unlike the data rows where
            # the forward's cast commutes with the gather
            targets = numpy.ascontiguousarray(targets)
            if self.compute_dtype is not None:
                targets = numpy.asarray(targets, dtype=numpy.float32)
            self._targets_d = jax.device_put(targets, rep)

    @property
    def has_dataset(self):
        return self._data_d is not None

    def set_epoch_perm(self, perm, pad):
        """Materialize the epoch's shuffled dataset ON DEVICE, once per
        reshuffle: ``data_p[i] = data[perm[i]]`` plus ``pad`` trailing
        zero rows (labels -1) so every window's dynamic slice stays in
        range on the tail minibatch.

        This replaces the per-window row gather (19.5% of the r4
        flagship window's device time at ~10 GB/s,
        profiles/r4_summary.md) with ONE gather per epoch; windowed
        steps then read their minibatches as contiguous
        ``dynamic_slice`` loads at HBM stream rate
        (:meth:`run_window_sliced`).  Identical rows to the per-window
        gather by construction — the loader serves TRAIN minibatches
        as contiguous slices of its shuffled order (loader/base.py
        run())."""
        if not self.has_dataset:
            raise RuntimeError("set_dataset() before set_epoch_perm")
        has_targets = self._targets_d is not None
        key_ = (int(len(perm)), int(pad), has_targets)
        fn = self._perm_fns.get(key_)
        if fn is None:
            def _mat_one(arr, p, fill):
                ap = jnp.take(arr, p, axis=0)
                tail = jnp.full((pad,) + ap.shape[1:], fill, ap.dtype)
                return jnp.concatenate([ap, tail])

            def materialize(data, labels, targets, p):
                out = (_mat_one(data, p, 0), _mat_one(labels, p, -1),
                       _mat_one(targets, p, 0) if has_targets else 0)
                return out

            if self.mesh is not None:
                rep = NamedSharding(self.mesh, P())
                fn = jax.jit(materialize,
                             out_shardings=(rep, rep,
                                            rep if has_targets else None))
            else:
                fn = jax.jit(materialize)
            self._perm_fns[key_] = fn
        rep = None if self.mesh is None else NamedSharding(self.mesh, P())
        # SNAPSHOT the permutation (numpy.array copies; asarray would
        # alias): device_put may alias aligned host memory on the CPU
        # backend and the materialize dispatch below is ASYNCHRONOUS —
        # the caller's buffer is the loader's live train_indices, which
        # the epoch-end reshuffle mutates IN PLACE mid window-collection.
        # Without the copy the gather raced the shuffle and the epoch's
        # tail window could train on next-epoch rows (the flaky
        # test_window_sliced_no_valid_segment_epoch_boundary failure).
        perm_d = jax.device_put(
            numpy.array(perm, dtype=numpy.int32), rep)
        self._data_p, self._labels_p, tp = fn(
            self._data_d, self._labels_d,
            self._targets_d if has_targets else 0, perm_d)
        self._targets_p = tp if has_targets else None

    @property
    def has_epoch_perm(self):
        return self._data_p is not None

    def _get_window_fn(self, n_steps, mode, batch=None, final=False):
        """Build (and cache) the compiled K-step window: one ``lax.scan``
        over ``_train_step`` with per-step traced hypers + in-scan
        evaluator stats.  Aggregates (n_err, confusion, max_err_sum) ride
        the carry so only the per-step losses stack; the LAST step's
        output/max_idx come back for the downstream units
        (evaluator/decision/plotters keep their reference roles).

        ``mode``: "stacked" (host-stacked minibatches), "indexed"
        (device-resident dataset + per-row gather), or "sliced"
        (per-epoch materialized permutation + contiguous dynamic
        slices — the production data path; ``batch`` is the static
        minibatch row count).

        Data-parallel mesh (data shards S > 1): per-step stats and the
        epoch accumulator keep a leading ``S`` shard axis sharded
        ``P("data", ...)`` — every in-scan reduction is LOCAL to its
        shard's batch rows, so mid-epoch windows insert no stats
        collective beyond the gradient psum the update itself needs.
        ``final=True`` (the segment-final window) additionally folds the
        segment's ONE stats all-reduce into the executable and returns
        the replicated totals under ``stats["acc_reduced"]`` — exactly
        one aggregate all-reduce per segment, none on the host path."""
        dp = self._dp
        final = bool(final) and dp > 1
        key_ = (int(n_steps), mode, batch, final)
        fn = self._window_fns.get(key_)
        if fn is not None:
            return fn
        specs = tuple(self.specs)
        cd = self.compute_dtype
        mesh = self.mesh
        needs_key = self._needs_key
        n_classes = int(self.specs[-1].n_out)
        mean = bool(self.stats_mean)
        out_dtype = jnp.float32 if cd is not None else self.dtype

        def body(carry, step):
            if dp > 1:
                p, s, k, _, _, nerr, conf, mx, i, lbuf = carry
            else:
                p, s, k, _, _, nerr, conf, mx = carry
            if mode == "indexed":
                data, lbl_all, idx, bs, hy = step
                safe = jnp.maximum(idx, 0)
                x = jnp.take(data, safe, axis=0)
                lbl = jnp.where(idx < 0, jnp.int32(-1),
                                jnp.take(lbl_all, safe, axis=0))
            elif mode == "sliced":
                data, lbl_all, start, bs, hy = step
                x = jax.lax.dynamic_slice_in_dim(data, start, batch,
                                                 axis=0)
                lbl = jax.lax.dynamic_slice_in_dim(lbl_all, start, batch)
                # the materialized tail padding already carries -1
                # labels; the bs mask additionally guards any contract
                # drift (padded slots must never count)
                lbl = jnp.where(jnp.arange(batch) < bs, lbl,
                                jnp.int32(-1))
            else:
                x, lbl, bs, hy = step
            if dp > 1:
                # pin the minibatch to the data axis INSIDE the scan:
                # the indexed gather / dynamic slice reads a replicated
                # dataset, and without the constraint GSPMD is free to
                # keep the whole step replicated (no scaling)
                x = jax.lax.with_sharding_constraint(x, NamedSharding(
                    mesh, P("data", *([None] * (x.ndim - 1)))))
                lbl = jax.lax.with_sharding_constraint(
                    lbl, NamedSharding(mesh, P("data")))
            if needs_key:
                k, sub = jax.random.split(k)
            else:
                sub = k
            p, s, m = _train_step(p, s, x, lbl, specs, sub, cd, hy,
                                  with_output=True)
            d_nerr, d_conf, d_mx = _eval_stats(
                m["output"], m["max_idx"], lbl, bs, n_classes, mean,
                shards=dp)
            stats_c = (nerr + d_nerr, conf + d_conf,
                       jnp.maximum(mx, d_mx))
            if dp > 1:
                # per-step losses accumulate into a CARRIED buffer via a
                # one-hot add instead of the scan's ys stacking: a
                # dynamic-update-slice over a (K,) buffer is sharded by
                # GSPMD whenever K divides by the shard count, and the
                # installed jaxlib's partitioner then emits a mixed
                # s64/s32 offset compare under x64 (hlo verifier error).
                # The elementwise add partitions trivially.
                loss = jax.lax.with_sharding_constraint(
                    m["loss"], NamedSharding(mesh, P()))
                lbuf = lbuf + loss.astype(lbuf.dtype) * \
                    jax.nn.one_hot(i, lbuf.shape[0], dtype=lbuf.dtype)
                carry = (p, s, k, m["output"], m["max_idx"]) + stats_c \
                    + (i + 1, lbuf)
                return carry, None
            carry = (p, s, k, m["output"], m["max_idx"]) + stats_c
            return carry, m["loss"]

        def window_fn(p, s, k, data, lbl_all, xs, ls, bs_s, hy_s, acc):
            b = batch if mode == "sliced" else xs.shape[1]
            out0 = jnp.zeros((b, n_classes), dtype=out_dtype)
            idx0 = jnp.zeros((b,), dtype=jnp.int32)
            lead = (dp,) if dp > 1 else ()
            nerr0 = jnp.zeros(lead + (2,), dtype=jnp.int32)
            conf0 = jnp.zeros(lead + (n_classes, n_classes),
                              dtype=jnp.int32)
            mx0 = jnp.zeros(lead, dtype=out_dtype)
            if mode in ("indexed", "sliced"):
                # the dataset enters once as a plain argument (closing
                # over it would bake a huge constant into the program;
                # scanning it would copy it per step)
                def scan_body(carry, step):
                    idx, bs, hy = step
                    return body(carry, (data, lbl_all, idx, bs, hy))
                xs_scan = (xs, bs_s, hy_s)
            else:
                xs_scan = (xs, ls, bs_s, hy_s)
                scan_body = body
            carry0 = (p, s, k, out0, idx0, nerr0, conf0, mx0)
            if dp > 1:
                carry0 = carry0 + (jnp.int32(0),
                                   jnp.zeros((n_steps,), dtype=out_dtype))
                carry1, _ = jax.lax.scan(scan_body, carry0, xs_scan)
                (p, s, k, out, midx, nerr, conf, mx) = carry1[:8]
                losses = carry1[9]
            else:
                (p, s, k, out, midx, nerr, conf, mx), losses = \
                    jax.lax.scan(scan_body, carry0, xs_scan)
            # fold this window's deltas into the device-resident epoch
            # accumulator OUTSIDE the scan (acc + window_delta is the
            # exact f32/int op sequence the synchronous host fold ran,
            # so the async segment total is bit-identical; under a data
            # mesh the fold stays per-shard — elementwise, no collective)
            acc = {"n_err": acc["n_err"] + nerr,
                   "confusion": acc["confusion"] + conf,
                   "max_err_sum": jnp.maximum(acc["max_err_sum"], mx)}
            stats = {"loss": losses, "n_err": nerr, "confusion": conf,
                     "max_err_sum": mx, "output": out, "max_idx": midx,
                     "acc": acc}
            if final:
                # the segment's ONE stats all-reduce: integer sums and a
                # max over the shard axis — order-independent, so the
                # reduced totals equal the single-device fold bit for bit
                stats["acc_reduced"] = {
                    "n_err": acc["n_err"].sum(axis=0),
                    "confusion": acc["confusion"].sum(axis=0),
                    "max_err_sum": acc["max_err_sum"].max(axis=0)}
            return p, s, k, stats

        if self.mesh is not None:
            rep = NamedSharding(self.mesh, P())
            oshard = NamedSharding(self.mesh, P("data", None))
            ishard = NamedSharding(self.mesh, P("data"))
            if dp > 1:
                sh1 = NamedSharding(self.mesh, P("data"))
                sh2 = NamedSharding(self.mesh, P("data", None))
                sh3 = NamedSharding(self.mesh, P("data", None, None))
                stat_shard = {"n_err": sh2, "confusion": sh3,
                              "max_err_sum": sh1}
            else:
                stat_shard = {"n_err": rep, "confusion": rep,
                              "max_err_sum": rep}
            mshard = dict(stat_shard)
            mshard.update({"loss": rep, "output": oshard,
                           "max_idx": ishard, "acc": dict(stat_shard)})
            if final:
                mshard["acc_reduced"] = {"n_err": rep, "confusion": rep,
                                         "max_err_sum": rep}
            fn = jax.jit(window_fn, donate_argnums=(0, 1, 9),
                         out_shardings=(self._pshard, self._sshard, rep,
                                        mshard))
        else:
            fn = jax.jit(window_fn, donate_argnums=(0, 1, 9))
        self._window_fns[key_] = fn
        return fn

    # -- device-resident epoch accumulators ---------------------------------
    def _window_acc(self):
        """The running decision-aggregate accumulator (device arrays),
        created as zeros on the first window after a
        :meth:`reset_window_acc`.  Carried INTO every window executable
        as a donated argument and OUT under ``stats["acc"]`` — the async
        control plane's one readback per segment.

        Data-parallel mesh: the leaves keep a leading ``data_shards``
        axis and live SHARDED ``P("data", ...)`` — each shard
        accumulates its local batch rows' partials with no collective
        until the segment-final window's one all-reduce."""
        if self._win_acc is not None:
            return self._win_acc
        acc = self.window_acc_zeros()
        shard = self._acc_shardings(acc)
        self._win_acc = {k: jax.device_put(v, shard[k])
                         for k, v in acc.items()}
        return self._win_acc

    def window_acc_zeros(self):
        """Host-side zero epoch accumulators — the shape/dtype
        authority for the device leaves.  Shared by the zero-init path
        and by launcher auto-resume's compatibility check, which must
        validate a candidate snapshot's ``epoch_acc`` (including the
        leading data-shard axis — a mesh=4 capture cannot resume into a
        mesh=2 run) WITHOUT forcing a device drain."""
        out_dtype = jnp.float32 if self.compute_dtype is not None \
            else self.dtype
        lead = (self._dp,) if self._dp > 1 else ()
        if self.objective == "mse":
            metrics = numpy.zeros(lead + (3,), dtype=out_dtype)
            metrics[..., 2] = numpy.inf
            return {"metrics": metrics,
                    "n_err": numpy.zeros(lead + (2,), numpy.int32)}
        n_classes = int(self.specs[-1].n_out)
        return {"n_err": numpy.zeros(lead + (2,), numpy.int32),
                "confusion": numpy.zeros(
                    lead + (n_classes, n_classes), numpy.int32),
                "max_err_sum": numpy.zeros(lead, out_dtype)}

    def _acc_shardings(self, acc):
        """Accumulator leaf placements — replicated off-mesh, sharded
        ``P("data", ...)`` partials under a data mesh (shared by the
        zero-init path and mid-epoch resume's :meth:`set_window_acc`)."""
        if self.mesh is None:
            return {k: None for k in acc}
        if self._dp > 1:
            return {k: NamedSharding(
                self.mesh, P("data", *([None] * (numpy.ndim(v) - 1))))
                for k, v in acc.items()}
        rep = NamedSharding(self.mesh, P())
        return {k: rep for k in acc}

    @property
    def window_acc(self):
        """The last window's folded epoch accumulator (device; None
        before the first window of a segment)."""
        return self._win_acc

    def window_acc_host(self):
        """Drained host copy of the epoch accumulator for the mid-epoch
        snapshot payload — ONE batched readback (:meth:`host_fetch`),
        transitively waiting on every in-flight window.  None when the
        accumulator is at its zero state (segment boundary)."""
        if self._win_acc is None:
            return None
        return self.host_fetch(self._win_acc)

    def set_window_acc(self, host_acc):
        """Restore a :meth:`window_acc_host` capture (mid-epoch
        resume): leaves re-placed with the accumulator shardings, so
        the next dispatched window folds onto the pre-crash partials —
        async and mesh modes included."""
        if host_acc is None:
            self._win_acc = None
            return
        host_acc = {k: numpy.asarray(v) for k, v in host_acc.items()}
        shard = self._acc_shardings(host_acc)
        self._win_acc = {k: jax.device_put(v, shard[k])
                         for k, v in host_acc.items()}

    def reset_window_acc(self):
        """Zero the epoch accumulator (the trainer calls this at every
        segment boundary, after its one batched readback)."""
        self._win_acc = None

    def _place_window(self, arr, tail_dims):
        """Device-put a (K, batch, ...) stacked window input: scan dim
        unsharded, batch dim over ``data``.  A :class:`ShardMajorWindow`
        (the trainer's shard-aligned staging layout) is assembled from
        its per-shard contiguous blocks — each device receives one
        memcpy'able block instead of a strided split of the batch-major
        stack."""
        if isinstance(arr, ShardMajorWindow):
            return self._place_window_shard_major(arr.base, tail_dims)
        if self.mesh is None:
            return jax.device_put(arr)
        return jax.device_put(arr, NamedSharding(
            self.mesh, P(None, "data", *([None] * tail_dims))))

    def _place_window_shard_major(self, base, tail_dims):
        """Build the global sharded (K, B, ...) window array from a
        shard-major host base ``(S, K, B // S, ...)``: every addressable
        device gets its data shard's contiguous block via one
        ``device_put`` and the global array is assembled without a host
        restack (``jax.make_array_from_single_device_arrays``)."""
        if self.mesh is None or self._dp == 1:
            raise ValueError("shard-major staging needs a data mesh")
        dp, k, b = base.shape[:3]
        if dp != self._dp:
            raise ValueError("staging shards %d != mesh data shards %d"
                             % (dp, self._dp))
        gshape = (k, dp * b) + tuple(base.shape[3:])
        ns = NamedSharding(self.mesh,
                           P(None, "data", *([None] * tail_dims)))
        bufs = []
        for dev, idx in ns.addressable_devices_indices_map(
                gshape).items():
            start = idx[1].start or 0
            bufs.append(jax.device_put(base[start // b], dev))
        return jax.make_array_from_single_device_arrays(gshape, ns, bufs)

    def _place_window_scalars(self, batch_sizes, hypers_s):
        """Commit the per-step (K,) scalar rails — batch sizes and the
        stacked hyper pytree — REPLICATED on the mesh.  Left unpinned,
        GSPMD is free to shard a (K,) rail over ``data`` whenever K is
        divisible by the shard count, which both serializes the scan's
        per-step reads behind collectives and trips the installed
        jaxlib's s64/s32 dynamic-slice partitioner bug under x64."""
        bs = numpy.asarray(batch_sizes, dtype=numpy.int32)
        if self.mesh is None:
            return jnp.asarray(bs), hypers_s
        rep = NamedSharding(self.mesh, P())
        if self._dp > 1 and jax.tree.leaves(hypers_s):
            first = jax.tree.leaves(hypers_s)[0]
            if not isinstance(first, jax.Array):
                hypers_s = jax.device_put(hypers_s, rep)
        return jax.device_put(bs, rep), hypers_s

    def _check_window_batch(self, batch):
        if self.mesh is not None:
            mesh_mod.check_data_batch(self.mesh, batch)

    def _cost_name(self, kind, n_steps, final):
        name = "fused.window.%s.k%d" % (kind, n_steps)
        if final and self._dp > 1:
            # the segment-final variant is a DISTINCT executable (it
            # folds the per-segment stats all-reduce) — keep the cost
            # registry 1:1 with compiled programs
            name += ".final"
        return name

    def run_window(self, xs, labels_s, batch_sizes, hypers_s,
                   final=False):
        """K train steps in ONE compiled dispatch over host-stacked
        minibatches ``xs (K, B, *sample)`` / ``labels_s (K, B)``.
        ``batch_sizes (K,)`` masks padded tail minibatches exactly like
        the per-minibatch evaluator; ``hypers_s`` is the hyper pytree
        with a leading K axis (policy(k) applies to step k — LR-schedule
        step accuracy inside the window).  Returns the aggregated window
        stats (see _get_window_fn).  ``final`` marks the segment-final
        window (under a data mesh it selects the executable variant
        that folds the per-segment stats all-reduce); ``xs``/``labels_s``
        may be :class:`ShardMajorWindow` staging views."""
        if self.objective != "softmax":
            raise ValueError("run_window supports the softmax objective")
        self._check_window_batch(xs.shape[1])
        n_steps = xs.shape[0]
        fn = self._get_window_fn(n_steps, "stacked", final=final)
        if not isinstance(xs, ShardMajorWindow):
            xs = numpy.ascontiguousarray(xs)
        xs = self._place_window(xs, xs.ndim - 2)
        if not isinstance(labels_s, ShardMajorWindow):
            labels_s = numpy.asarray(labels_s, dtype=numpy.int32)
        labels_s = self._place_window(labels_s, 0)
        bs, hypers_s = self._place_window_scalars(batch_sizes, hypers_s)
        acc = self._window_acc()
        if profiler.enabled():
            self._register_cost(
                self._cost_name("stacked", n_steps, final), fn,
                (self.params, self.state, self._key, 0, 0, xs, labels_s,
                 bs, hypers_s, acc),
                steps=n_steps, batch=xs.shape[1])
        self.params, self.state, self._key, stats = fn(
            self.params, self.state, self._key, 0, 0, xs, labels_s, bs,
            hypers_s, acc)
        self._win_acc = stats["acc"]
        return stats

    def run_window_indexed(self, idx_s, batch_sizes, hypers_s,
                           final=False):
        """Windowed training over the device-resident dataset
        (:meth:`set_dataset`): ``idx_s (K, B)`` dataset row indices
        (-1 = padded tail slot).  Only the indices cross the host/device
        boundary; the gather runs inside the compiled window."""
        if not self.has_dataset:
            raise RuntimeError("set_dataset() before run_window_indexed")
        self._check_window_batch(idx_s.shape[1])
        n_steps = idx_s.shape[0]
        fn = self._get_window_fn(n_steps, "indexed", final=final)
        if not isinstance(idx_s, ShardMajorWindow):
            idx_s = numpy.asarray(idx_s, dtype=numpy.int32)
        idx_s = self._place_window(idx_s, 0)
        bs, hypers_s = self._place_window_scalars(batch_sizes, hypers_s)
        acc = self._window_acc()
        if profiler.enabled():
            self._register_cost(
                self._cost_name("indexed", n_steps, final), fn,
                (self.params, self.state, self._key, self._data_d,
                 self._labels_d, idx_s, None, bs, hypers_s, acc),
                steps=n_steps, batch=idx_s.shape[1])
        self.params, self.state, self._key, stats = fn(
            self.params, self.state, self._key, self._data_d,
            self._labels_d, idx_s, None, bs, hypers_s, acc)
        self._win_acc = stats["acc"]
        return stats

    def run_window_sliced(self, starts, batch, batch_sizes, hypers_s,
                          final=False):
        """Windowed training over the epoch-materialized permuted
        dataset (:meth:`set_epoch_perm`): ``starts (K,)`` are the
        minibatches' row offsets into the epoch order (the loader's
        ``minibatch_class_offset``); each step reads its ``batch`` rows
        as one contiguous ``dynamic_slice`` — no per-row gather
        anywhere in the steady-state window.  Rows are identical to
        :meth:`run_window_indexed` by construction."""
        if not self.has_epoch_perm:
            raise RuntimeError("set_epoch_perm() before run_window_sliced")
        self._check_window_batch(batch)
        n_steps = len(starts)
        fn = self._get_window_fn(n_steps, "sliced", int(batch),
                                 final=final)
        rep = None if self.mesh is None else NamedSharding(self.mesh, P())
        starts = jax.device_put(
            numpy.asarray(starts, dtype=numpy.int32), rep)
        bs, hypers_s = self._place_window_scalars(batch_sizes, hypers_s)
        acc = self._window_acc()
        if profiler.enabled():
            self._register_cost(
                self._cost_name("sliced", n_steps, final), fn,
                (self.params, self.state, self._key, self._data_p,
                 self._labels_p, starts, None, bs, hypers_s, acc),
                steps=n_steps, batch=batch)
        self.params, self.state, self._key, stats = fn(
            self.params, self.state, self._key, self._data_p,
            self._labels_p, starts, None, bs, hypers_s, acc)
        self._win_acc = stats["acc"]
        return stats

    # -- windowed MSE (the AE/regression hot loop) --------------------------
    def _get_window_fn_mse(self, n_steps, mode, batch=None, final=False):
        """K-step MSE scan window (reference evaluator contract:
        /root/reference/evaluator.py:334-556).  Carry aggregates the
        evaluator-identical metrics ([sum, max, min] of per-sample mse,
        ops/evaluator.mse_jax semantics, with ``mse_root`` mirrored
        from EvaluatorMSE.root) and — when ``class_targets`` is set —
        the nearest-class-target n_err integers.  The LAST step's
        output and per-sample mse come back for the downstream units.

        ``mode``: "stacked" or "sliced" (MSE has no indexed-gather
        variant; non-contiguous loaders use the host-stacked window).

        Data-parallel mesh: same sharded-partial discipline as
        :meth:`_get_window_fn` — metrics/n_err keep a leading shard
        axis, ``final=True`` folds the per-segment all-reduce into the
        executable (``stats["acc_reduced"]``).  The mse SUM partial is
        f32-reassociated across shards (per-shard sums, then one
        cross-shard sum) — the ONE documented reduction-order deviation
        from the single-device fold (MESH_MSE_SUM; max/min and the
        integer n_err stay exact)."""
        dp = self._dp
        final = bool(final) and dp > 1
        ct = self.class_targets
        key_ = ("mse", int(n_steps), mode, batch, ct is not None, final)
        fn = self._window_fns.get(key_)
        if fn is not None:
            return fn
        specs = tuple(self.specs)
        cd = self.compute_dtype
        mesh = self.mesh
        needs_key = self._needs_key
        root = bool(self.mse_root)
        mean = bool(self.stats_mean)
        out_dtype = jnp.float32 if cd is not None else self.dtype
        ct_c = None if ct is None else jnp.asarray(ct, out_dtype)
        out_shape = tuple(self.specs[-1].out_shape)

        def _stats(out, target, lbl, bs):
            """Evaluator-identical per-minibatch MSE stats — THE
            evaluator op itself runs inside the scan (its err output is
            unused and dead-code-eliminated under jit), so the windowed
            parity has one source of truth — plus the optional
            nearest-class-target error (the evaluator's host loop:
            squared distance summed over the sample axis, argmin vs
            label).  Under a data mesh the reductions run per shard
            (leading ``dp`` axis, see _eval_stats)."""
            from znicz_tpu.ops import evaluator as ev_ops
            out = out.astype(out_dtype)
            B = out.shape[0]
            o2 = out.reshape(B, -1)
            t2 = target.reshape(B, -1).astype(out_dtype)
            _, md, mse_per = ev_ops.mse_jax(o2, t2, bs, mean=mean,
                                            root=root)
            in_batch = jnp.arange(B) < bs
            if dp > 1:
                b = B // dp
                m2 = mse_per.reshape(dp, b)
                md = jnp.stack(
                    [m2.sum(axis=1), m2.max(axis=1),
                     jnp.where(in_batch.reshape(dp, b), m2,
                               jnp.inf).min(axis=1)], axis=-1)
            if ct_c is None:
                lead = (dp,) if dp > 1 else ()
                nerr_d = jnp.zeros(lead + (2,), jnp.int32)
            else:
                d = ((ct_c[None, :, :] - o2[:, None, :]) ** 2).sum(-1)
                pred = jnp.argmin(d, axis=1).astype(jnp.int32)
                if dp > 1:
                    b = B // dp
                    cnt = in_batch.reshape(dp, b).sum(axis=1)
                    n_ok = (in_batch & (pred == lbl)).reshape(
                        dp, b).sum(axis=1)
                    nerr_d = jnp.stack([cnt - n_ok, cnt],
                                       axis=-1).astype(jnp.int32)
                else:
                    n_ok = (in_batch & (pred == lbl)).sum()
                    nerr_d = jnp.stack([bs - n_ok, bs]).astype(jnp.int32)
            return md, mse_per, nerr_d, out

        def body(carry, step):
            if dp > 1:
                p, s, k, _, _, msum, mmax, mmin, nerr, i, lbuf = carry
            else:
                p, s, k, _, _, msum, mmax, mmin, nerr = carry
            if mode == "sliced":
                data, tgt_all, lbl_all, start, bs, hy = step
                x = jax.lax.dynamic_slice_in_dim(data, start, batch,
                                                 axis=0)
                t = jax.lax.dynamic_slice_in_dim(tgt_all, start, batch,
                                                 axis=0)
                lbl = jax.lax.dynamic_slice_in_dim(lbl_all, start, batch)
                lbl = jnp.where(jnp.arange(batch) < bs, lbl,
                                jnp.int32(-1))
            else:
                x, t, lbl, bs, hy = step
            if dp > 1:
                # pin the minibatch to the data axis (see _get_window_fn)
                x = jax.lax.with_sharding_constraint(x, NamedSharding(
                    mesh, P("data", *([None] * (x.ndim - 1)))))
                t = jax.lax.with_sharding_constraint(t, NamedSharding(
                    mesh, P("data", *([None] * (t.ndim - 1)))))
                lbl = jax.lax.with_sharding_constraint(
                    lbl, NamedSharding(mesh, P("data")))
            if needs_key:
                k, sub = jax.random.split(k)
            else:
                sub = k
            p, s, m = _train_step_mse(p, s, x, t, bs, specs, sub, cd, hy)
            md, mse_per, nerr_d, out = _stats(m["output"], t, lbl, bs)
            stats_c = (msum + md[..., 0], jnp.maximum(mmax, md[..., 1]),
                       jnp.minimum(mmin, md[..., 2]), nerr + nerr_d)
            if dp > 1:
                # carried one-hot loss accumulation — see _get_window_fn
                # (the scan ys dynamic-update-slice trips the jaxlib
                # partitioner when K divides by the shard count)
                loss = jax.lax.with_sharding_constraint(
                    m["loss"], NamedSharding(mesh, P()))
                lbuf = lbuf + loss.astype(lbuf.dtype) * \
                    jax.nn.one_hot(i, lbuf.shape[0], dtype=lbuf.dtype)
                carry = (p, s, k, out, mse_per) + stats_c + (i + 1, lbuf)
                return carry, None
            carry = (p, s, k, out, mse_per) + stats_c
            return carry, m["loss"]

        def window_fn(p, s, k, data, tgt_all, lbl_all, xs, ts, ls,
                      bs_s, hy_s, acc):
            b = batch if mode == "sliced" else xs.shape[1]
            lead = (dp,) if dp > 1 else ()
            out0 = jnp.zeros((b,) + out_shape, dtype=out_dtype)
            mse0 = jnp.zeros((b,), dtype=out_dtype)
            msum0 = jnp.zeros(lead, dtype=out_dtype)
            mmax0 = jnp.zeros(lead, dtype=out_dtype)
            mmin0 = jnp.full(lead, jnp.inf, dtype=out_dtype)
            nerr0 = jnp.zeros(lead + (2,), dtype=jnp.int32)
            if mode == "sliced":
                def scan_body(carry, step):
                    start, bs, hy = step
                    return body(carry, (data, tgt_all, lbl_all, start,
                                        bs, hy))
                xs_scan = (xs, bs_s, hy_s)
            else:
                xs_scan = (xs, ts, ls, bs_s, hy_s)
                scan_body = body
            carry0 = (p, s, k, out0, mse0, msum0, mmax0, mmin0, nerr0)
            if dp > 1:
                carry0 = carry0 + (jnp.int32(0),
                                   jnp.zeros((n_steps,), dtype=out_dtype))
                carry1, _ = jax.lax.scan(scan_body, carry0, xs_scan)
                (p, s, k, out, mse_per, msum, mmax, mmin,
                 nerr) = carry1[:9]
                losses = carry1[10]
            else:
                (p, s, k, out, mse_per, msum, mmax, mmin, nerr), \
                    losses = jax.lax.scan(scan_body, carry0, xs_scan)
            # epoch-accumulator fold — the exact op sequence of the
            # synchronous host fold (window sum computed in-scan from
            # zero, THEN one add onto the running total), so the async
            # segment aggregate is bit-identical (see _get_window_fn);
            # under a data mesh the fold stays per-shard (axis -1 keeps
            # the leading shard axis) with no collective
            acc = {"metrics": jnp.stack(
                       [acc["metrics"][..., 0] + msum,
                        jnp.maximum(acc["metrics"][..., 1], mmax),
                        jnp.minimum(acc["metrics"][..., 2], mmin)],
                       axis=-1),
                   "n_err": acc["n_err"] + nerr}
            stats = {"loss": losses,
                     "metrics": jnp.stack([msum, mmax, mmin], axis=-1),
                     "mse_per": mse_per, "n_err": nerr, "output": out,
                     "acc": acc}
            if final:
                # the segment's ONE stats all-reduce (the mse SUM is the
                # documented f32 reassociation — max/min/integers exact)
                stats["acc_reduced"] = {
                    "metrics": jnp.stack(
                        [acc["metrics"][:, 0].sum(),
                         acc["metrics"][:, 1].max(),
                         acc["metrics"][:, 2].min()]),
                    "n_err": acc["n_err"].sum(axis=0)}
            return p, s, k, stats

        if self.mesh is not None:
            rep = NamedSharding(self.mesh, P())
            oshard = NamedSharding(
                self.mesh, P("data", *([None] * len(out_shape))))
            if dp > 1:
                sh2 = NamedSharding(self.mesh, P("data", None))
                stat_shard = {"metrics": sh2, "n_err": sh2}
            else:
                stat_shard = {"metrics": rep, "n_err": rep}
            mshard = dict(stat_shard)
            mshard.update({"loss": rep,
                           "mse_per": NamedSharding(self.mesh, P("data")),
                           "output": oshard,
                           "acc": dict(stat_shard)})
            if final:
                mshard["acc_reduced"] = {"metrics": rep, "n_err": rep}
            fn = jax.jit(window_fn, donate_argnums=(0, 1, 11),
                         out_shardings=(self._pshard, self._sshard, rep,
                                        mshard))
        else:
            fn = jax.jit(window_fn, donate_argnums=(0, 1, 11))
        self._window_fns[key_] = fn
        return fn

    def run_window_mse(self, xs, ts, lbl_s, batch_sizes, hypers_s,
                       final=False):
        """K MSE train steps in ONE compiled dispatch over host-stacked
        minibatches ``xs (K, B, *sample)`` / ``ts (K, B, *target)``;
        ``lbl_s (K, B)`` feeds the nearest-class-target error when
        ``class_targets`` is set (pass -1s otherwise)."""
        if self.objective != "mse":
            raise ValueError("run_window_mse needs the mse objective")
        self._check_window_batch(xs.shape[1])
        n_steps = xs.shape[0]
        fn = self._get_window_fn_mse(n_steps, "stacked", final=final)
        if not isinstance(xs, ShardMajorWindow):
            xs = numpy.ascontiguousarray(xs)
        xs = self._place_window(xs, xs.ndim - 2)
        if not isinstance(ts, ShardMajorWindow):
            ts = numpy.ascontiguousarray(ts)
        ts = self._place_window(ts, ts.ndim - 2)
        if not isinstance(lbl_s, ShardMajorWindow):
            lbl_s = numpy.asarray(lbl_s, dtype=numpy.int32)
        lbl_s = self._place_window(lbl_s, 0)
        bs, hypers_s = self._place_window_scalars(batch_sizes, hypers_s)
        acc = self._window_acc()
        if profiler.enabled():
            self._register_cost(
                self._cost_name("mse", n_steps, final), fn,
                (self.params, self.state, self._key, 0, 0, 0, xs, ts,
                 lbl_s, bs, hypers_s, acc),
                steps=n_steps, batch=xs.shape[1])
        self.params, self.state, self._key, stats = fn(
            self.params, self.state, self._key, 0, 0, 0, xs, ts, lbl_s,
            bs, hypers_s, acc)
        self._win_acc = stats["acc"]
        return stats

    def run_window_mse_sliced(self, starts, batch, batch_sizes, hypers_s,
                              final=False):
        """Windowed MSE training over the epoch-materialized dataset —
        the sliced production path (see :meth:`run_window_sliced`);
        needs targets passed to :meth:`set_dataset`."""
        if self.objective != "mse":
            raise ValueError("run_window_mse_sliced needs the mse "
                             "objective")
        if not self.has_epoch_perm or self._targets_p is None:
            raise RuntimeError("set_epoch_perm() with targets before "
                               "run_window_mse_sliced")
        self._check_window_batch(batch)
        n_steps = len(starts)
        fn = self._get_window_fn_mse(n_steps, "sliced", int(batch),
                                     final=final)
        rep = None if self.mesh is None else NamedSharding(self.mesh, P())
        starts = jax.device_put(
            numpy.asarray(starts, dtype=numpy.int32), rep)
        bs, hypers_s = self._place_window_scalars(batch_sizes, hypers_s)
        acc = self._window_acc()
        if profiler.enabled():
            self._register_cost(
                self._cost_name("mse_sliced", n_steps, final), fn,
                (self.params, self.state, self._key, self._data_p,
                 self._targets_p, self._labels_p, starts, None, None,
                 bs, hypers_s, acc),
                steps=n_steps, batch=batch)
        self.params, self.state, self._key, stats = fn(
            self.params, self.state, self._key, self._data_p,
            self._targets_p, self._labels_p, starts, None, None, bs,
            hypers_s, acc)
        self._win_acc = stats["acc"]
        return stats

    def host_fetch(self, tree):
        """``jax.device_get`` that works across processes: leaves whose
        shards live on other hosts are resharded to replicated first
        (one all-gather at READBACK time — window outputs stay
        data-sharded on the hot path and only segment-final reads pay
        the transfer).  Metered on the telemetry d2h byte/call counters
        (ONE call per fetch, however many leaves ride it) — the async
        control plane's zero-mid-epoch-readback pin reads this meter."""
        if faults.enabled():
            # readback injection site (transient RESOURCE_EXHAUSTED /
            # stalled-transfer class).  Like the dispatch site, not
            # retried in place — the supervised launcher's restart +
            # mid-epoch resume is the recovery path.
            faults.check("fused.host_fetch")
        if not self._replicate_outputs:
            host = jax.device_get(tree)
        else:
            rep = NamedSharding(self.mesh, P())

            def _rep(x):
                if isinstance(x, jax.Array) and not x.is_fully_addressable:
                    return jax.jit(lambda a: a, out_shardings=rep)(x)
                return x

            host = jax.device_get(jax.tree.map(_rep, tree))
        if telemetry.enabled():
            telemetry.add_bytes("d2h", sum(
                leaf.nbytes for leaf in jax.tree.leaves(host)
                if hasattr(leaf, "nbytes")))
        return host

    def params_finite(self):
        """Device-side all-finite reduction over every parameter — the
        rollback's NaN probe without a full host pull (reference
        nn_rollback.py:105-111 counts NaNs on host; at AlexNet scale
        that is a whole-model D2H per epoch)."""
        if not hasattr(self, "_finite_fn"):
            self._finite_fn = jax.jit(lambda ps: jnp.all(jnp.stack(
                [jnp.isfinite(leaf).all()
                 for leaf in jax.tree.leaves(ps)])))
        return bool(self._finite_fn(self.params))

    def _predict_key(self):
        """Stochastic-pool nets consume PRNG draws at inference too
        (advancing the same key chain the train steps use — resume
        stays exact because the key is snapshot state)."""
        if not self._has_stochastic:
            return None
        self._key, sub = jax.random.split(self._key)
        return sub

    def predict(self, x):
        x, _ = self._place_batch(x, numpy.zeros(x.shape[0], numpy.int32))
        key = self._predict_key()
        if profiler.enabled():
            self._register_cost("fused.predict.b%d" % x.shape[0],
                                self._fwd, (self.params, x, key),
                                steps=1, batch=x.shape[0], train=False)
        return self._fwd(self.params, x, key)

    def predict_with_idx(self, x):
        """Compiled inference: (softmax output, argmax) — what the
        evaluator unit consumes on VALID/TEST minibatches."""
        x, _ = self._place_batch(x, numpy.zeros(x.shape[0], numpy.int32))
        key = self._predict_key()
        if profiler.enabled():
            self._register_cost("fused.predict_idx.b%d" % x.shape[0],
                                self._fwd_idx, (self.params, x, key),
                                steps=1, batch=x.shape[0], train=False)
        return self._fwd_idx(self.params, x, key)

    def host_params(self):
        return jax.tree.map(lambda a: numpy.asarray(a), self.params)

    # -- checkpoint / resume ------------------------------------------------
    def state_dict(self):
        """Full training state as host numpy pytrees: parameters,
        optimizer slots (vel/acc/solver), the dropout PRNG key, and the
        live hyperparameters — everything needed for bit-exact resume
        (the fused twin of the unit path's exports, nn_units.py:316-319)."""
        return {
            "params": jax.tree.map(numpy.asarray, self.params),
            "opt": jax.tree.map(numpy.asarray, self.state),
            "key": numpy.asarray(self._key),
            "hypers": jax.tree.map(float, self.hypers),
        }

    def load_state_dict(self, sd):
        """Restore :meth:`state_dict` output, re-placing every leaf with
        its mesh sharding."""
        self.params = self._place_params(sd["params"])
        self.state = self._place_state(sd["opt"])
        key = jnp.asarray(sd["key"])
        if self.mesh is not None:
            key = jax.device_put(key, NamedSharding(self.mesh, P()))
        self._key = key
        if sd.get("hypers") is not None:
            self.hypers = jax.tree.map(float, sd["hypers"])


class FusedMLP(FusedNet):
    """FC-only fused trainer (back-compat name; flat input)."""

    def __init__(self, layers, input_sample_size, **kwargs):
        # validate BEFORE the base init so a rejected config consumes no
        # PRNG draws from a shared rand (fail-fast like build_fc_specs)
        build_fc_specs(layers, int(input_sample_size),
                       kwargs.get("defaults"))
        super(FusedMLP, self).__init__(
            layers, int(input_sample_size), **kwargs)


def default_hypers(specs):
    """The live hyperparameter pytree: one ``{"w": {...}, "b": {...}}`` per
    parameterized spec (``{}`` for param-less layers), seeded from the
    config values.  Passed to the jitted step as a TRACED argument so LR
    schedules (lr_adjust.py policies) apply per iteration without a
    recompile — the reference mutates ``gd.learning_rate`` the same way
    (lr_adjust.py:61)."""
    hypers = []
    for spec in specs:
        if spec.kind in ("fc", "conv"):
            h = {"w": dict(spec.hyper)}
            if spec.include_bias:
                h["b"] = dict(spec.hyper_bias)
            hypers.append(h)
        else:
            hypers.append({})
    return hypers


def _apply_weight_masks(params, specs):
    """The zero_filter pass: re-zero grouped weight positions before the
    step (the unit graph's ZeroFiller masks the shared Array in place
    each forward pass, BEFORE the GD update — so weight decay/ortho see
    masked weights; parity requires the same order here)."""
    out = []
    for spec, p in zip(specs, params):
        mask = getattr(spec, "weight_mask", None)
        if mask is not None and "w" in p:
            p = dict(p, w=p["w"] * jnp.asarray(mask, p["w"].dtype))
        out.append(p)
    return out


def _train_step(params, state, x, labels, specs, key=None,
                compute_dtype=None, hypers=None, with_output=False):
    params = _apply_weight_masks(params, specs)
    (loss, (n_err, probs, max_idx)), grads = jax.value_and_grad(
        lambda p: _loss_and_stats(p, x, labels, specs, key, compute_dtype),
        has_aux=True)(params)
    new_params, new_state = [], []
    if hypers is None:
        hypers = [None] * len(params)
    for spec, p, st, g, hy in zip(specs, params, state, grads, hypers):
        np_, nst = {}, {}
        if "w" in p:
            np_["w"], nst["w"], _ = gd_math.update(
                jnp, p["w"], g["w"].astype(p["w"].dtype), st["w"],
                hy["w"] if hy else spec.hyper, spec.flags)
        if "b" in p:
            hyper_b = hy["b"] if hy else spec.hyper_bias
            flags_b = dict(spec.flags, ortho=False)
            np_["b"], nst["b"], _ = gd_math.update(
                jnp, p["b"], g["b"].astype(p["b"].dtype), st["b"],
                hyper_b, flags_b)
        new_params.append(np_)
        new_state.append(nst)
    metrics = {"loss": loss, "n_err": n_err}
    if with_output:
        metrics["output"] = probs
        metrics["max_idx"] = max_idx
    return new_params, new_state, metrics
