"""Fused SPMD training — one jitted XLA computation per minibatch.

SURVEY.md §7 design stance: the unit graph remains the epoch-level control
plane, but the hot loop — forward, loss gradient, backward, per-layer
update — compiles to a single XLA computation.  This module is the fused
path for fully-connected stacks (the reference's all2all family,
all2all.py:53-474 + gd.py:73-551); conv models plug in as further spec
types.

Parity: weight init matches ``All2All.initialize`` (magnitude heuristic
all2all.py:106-117, fill semantics all2all.py:119-127, same PRNG draw
order), and the update algebra is literally :func:`znicz_tpu.ops.gd_math.
update` with ``xp=jnp`` — the same function the unit-at-a-time path runs.
Gradients come from ``jax.grad`` of the softmax-CE loss, which reproduces
the reference's hand-written chain rule (verified by the parity test
against the unit-graph path in float64).

Sharding: parameters and inputs carry ``NamedSharding`` annotations over a
``(data, model)`` mesh; GSPMD inserts the gradient all-reduce (psum over
``data``) and the activation all-gathers (over ``model``) — the TPU-native
replacement for the reference's parameter-server broadcast/aggregate cycle
(nn_units.py:178-208, 644-694).
"""

from dataclasses import dataclass, field

import numpy

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from znicz_tpu.core import prng
from znicz_tpu.ops import activations, gd_math

#: the FC family the fused path can compile (reference all2all.py classes);
#: activation + magnitude constants come from the registered unit classes —
#: single source of truth with the unit-graph path.
FC_TYPES = ("all2all", "all2all_tanh", "all2all_relu", "all2all_str",
            "all2all_sigmoid", "softmax")


def _forward_class(tpe):
    from znicz_tpu.units import nn_units, all2all  # noqa: F401 (registers)
    return nn_units.mapping[tpe].forward

DEFAULT_HYPER = dict(lr=0.01, wd=0.00005, l1_vs_l2=0.0, moment=0.0,
                     acc_alpha=0.0, acc_beta=0.0, gd_alpha=0.0, gd_beta=1.0,
                     factor_ortho=0.0)


@dataclass
class FCSpec:
    """One fully-connected layer of the fused stack."""
    type: str
    n_in: int
    n_out: int
    activation: str
    hyper: dict = field(default_factory=dict)        # weights hyper
    hyper_bias: dict = field(default_factory=dict)   # bias hyper
    flags: dict = field(default_factory=dict)
    weights_stddev: float = None
    bias_stddev: float = None
    weights_filling: str = "uniform"
    bias_filling: str = "uniform"
    include_bias: bool = True

    @property
    def is_softmax(self):
        return self.type == "softmax"

    def init_stddev(self):
        """Reference magnitude heuristic (all2all.py:106-117), using the
        registered unit class's C constant."""
        if self.weights_stddev is not None:
            return self.weights_stddev
        from znicz_tpu.units.nn_units import weights_magnitude
        vle = weights_magnitude(_forward_class(self.type).C,
                                self.n_in, self.n_out, self.weights_filling)
        return min(vle, 0.5)


def build_fc_specs(layers, input_sample_size, defaults=None):
    """Build FCSpec list from a declarative ``layers`` config.

    Each entry is a dict with "type" plus forward kwargs (optionally under
    "->") and backward kwargs (under "<-") — the reference config format
    (standard_workflow_base.py:406-422).
    """
    defaults = dict(DEFAULT_HYPER, **(defaults or {}))
    specs = []
    n_in = int(input_sample_size)
    for layer in layers:
        layer = dict(layer)
        tpe = layer.pop("type")
        if tpe not in FC_TYPES:
            raise ValueError("fused path does not support layer type %r"
                             % tpe)
        fwd = dict(layer.pop("->", {}))
        bwd = dict(layer.pop("<-", {}))
        fwd.update({k: v for k, v in layer.items()})
        shape = fwd.get("output_sample_shape", fwd.get("output_samples"))
        if shape is None:
            raise ValueError("layer %r needs output_sample_shape" % tpe)
        n_out = int(numpy.prod(shape))
        hyper = dict(defaults)
        hyper.update(
            lr=bwd.get("learning_rate", defaults["lr"]),
            wd=bwd.get("weights_decay", defaults["wd"]),
            l1_vs_l2=bwd.get("l1_vs_l2", defaults["l1_vs_l2"]),
            moment=bwd.get("gradient_moment", defaults["moment"]),
            acc_alpha=bwd.get("acc_alpha", defaults["acc_alpha"]),
            acc_beta=bwd.get("acc_beta", defaults["acc_beta"]),
            gd_alpha=bwd.get("gd_alpha", defaults["gd_alpha"]),
            gd_beta=bwd.get("gd_beta", defaults["gd_beta"]),
            factor_ortho=bwd.get("factor_ortho", defaults["factor_ortho"]))
        hyper_bias = dict(hyper)
        hyper_bias.update(
            lr=bwd.get("learning_rate_bias", hyper["lr"]),
            wd=bwd.get("weights_decay_bias", 0.0),
            l1_vs_l2=bwd.get("l1_vs_l2_bias", hyper["l1_vs_l2"]),
            moment=bwd.get("gradient_moment_bias", hyper["moment"]),
            factor_ortho=0.0)
        flags = dict(accumulate=bool(bwd.get("accumulate_gradient", False)),
                     apply=True,
                     solvers=frozenset(bwd.get("solvers", ())),
                     ortho=bool(hyper["factor_ortho"]),
                     variant_moment=bwd.get("variant_moment_gradient", True))
        specs.append(FCSpec(
            type=tpe, n_in=n_in, n_out=n_out,
            activation=("linear" if tpe == "softmax"
                        else _forward_class(tpe).ACTIVATION),
            hyper=hyper, hyper_bias=hyper_bias, flags=flags,
            weights_stddev=fwd.get("weights_stddev"),
            bias_stddev=fwd.get("bias_stddev"),
            weights_filling=fwd.get("weights_filling", "uniform"),
            bias_filling=fwd.get("bias_filling", "uniform"),
            include_bias=fwd.get("include_bias", True)))
        n_in = n_out
    return specs


def init_params(specs, rand=None, dtype=numpy.float32):
    """Host-side init with the unit path's exact draw order and fill
    semantics (weights then bias per layer, all2all.py:119-127)."""
    rand = rand or prng.get()
    params = []
    for spec in specs:
        stddev = spec.init_stddev()
        bias_stddev = spec.bias_stddev if spec.bias_stddev is not None \
            else stddev
        w = numpy.zeros((spec.n_out, spec.n_in), dtype=dtype)
        _fill(rand, spec.weights_filling, w, stddev)
        p = {"w": w}
        if spec.include_bias:
            b = numpy.zeros(spec.n_out, dtype=dtype)
            _fill(rand, spec.bias_filling, b, bias_stddev)
            p["b"] = b
        params.append(p)
    return params


def _fill(rand, filling, array, stddev):
    from znicz_tpu.units.nn_units import fill_array
    fill_array(rand, filling, array, stddev)


def init_opt_state(specs, params):
    """Optimizer-state pytree mirroring the per-layer Arrays of the unit
    path (vel = gradient_*_with_moment, acc, solver slots)."""
    states = []
    for spec, p in zip(specs, params):
        st = {"w": gd_math.init_state(
            p["w"], dict(spec.flags, need_vel=True))}
        if "b" in p:
            st["b"] = gd_math.init_state(
                p["b"], dict(spec.flags, need_vel=True))
        states.append(st)
    return states


def forward(params, x, specs, return_logits=False):
    """Pure forward pass.  With ``return_logits`` the softmax head is left
    un-normalized (for the CE loss); otherwise softmax is applied."""
    y = x.reshape(x.shape[0], -1)
    for p, spec in zip(params, specs):
        y = y @ p["w"].T
        if "b" in p:
            y = y + p["b"]
        if not spec.is_softmax:
            y = activations.apply_jax(spec.activation, y)
        elif not return_logits:
            y = jax.nn.softmax(y, axis=1)
    return y


def _loss_and_stats(params, x, labels, specs):
    """Mean softmax-CE loss (matches evaluator err_output scaling,
    ops/evaluator.py) + error count."""
    y = forward(params, x, specs, return_logits=True)
    logp = jax.nn.log_softmax(y, axis=1)
    valid = labels >= 0
    lbl = jnp.maximum(labels, 0)
    ce = -jnp.take_along_axis(logp, lbl[:, None], axis=1)[:, 0]
    ce = jnp.where(valid, ce, 0.0)
    loss = ce.sum() / jnp.maximum(valid.sum(), 1)
    n_err = (valid & (jnp.argmax(y, axis=1) != lbl)).sum()
    return loss, n_err


class FusedMLP:
    """Compiled trainer for an FC stack over an optional device mesh."""

    def __init__(self, layers, input_sample_size, mesh=None, rand=None,
                 dtype=numpy.float32, defaults=None):
        self.specs = build_fc_specs(layers, input_sample_size, defaults)
        if not self.specs[-1].is_softmax:
            raise ValueError(
                "FusedMLP trains a softmax-CE objective; the last layer "
                "must be type 'softmax' (got %r). Use the unit-graph path "
                "for other heads." % self.specs[-1].type)
        if any(s.is_softmax for s in self.specs[:-1]):
            raise ValueError(
                "softmax is only supported as the head of a FusedMLP")
        self.mesh = mesh
        params_host = init_params(self.specs, rand, dtype)
        states_host = init_opt_state(self.specs, params_host)
        self.params = self._place_params(params_host)
        # state slots shard exactly like their parameter (vel mirrors w);
        # mismatched initial placement would force a second full compile
        # when the donated step returns GSPMD-sharded state.
        self.state = self._place_state(states_host)
        # specs close over the traced functions (they carry dicts, so they
        # can't be hashable static args); hyperparameters bake in as XLA
        # constants.
        specs = tuple(self.specs)
        step_fn = lambda p, s, x, l: _train_step(p, s, x, l, specs)  # noqa
        if mesh is not None:
            # Pin output shardings to the input placements: GSPMD would
            # otherwise return spec variants (P('model',) vs
            # P('model', None)) that hash differently and force a second
            # full compile of the donated step.
            pshard = [{k: NamedSharding(mesh, self._param_spec(s, k))
                       for k in p} for s, p in zip(self.specs, self.params)]
            sshard = [{k: {kk: NamedSharding(mesh, self._param_spec(s, k))
                           for kk in slots.keys()}
                       for k, slots in st.items()}
                      for s, st in zip(self.specs, self.state)]
            mshard = {"loss": NamedSharding(mesh, P()),
                      "n_err": NamedSharding(mesh, P())}
            self._step = jax.jit(step_fn, donate_argnums=(0, 1),
                                 out_shardings=(pshard, sshard, mshard))
        else:
            self._step = jax.jit(step_fn, donate_argnums=(0, 1))
        self._fwd = jax.jit(lambda p, x: forward(p, x, specs))

    # -- sharding -----------------------------------------------------------
    def _param_spec(self, spec, name):
        """model-axis sharding for wide layers, replicated otherwise."""
        if self.mesh is None:
            return None
        msize = self.mesh.shape["model"]
        if msize > 1 and spec.n_out % msize == 0:
            return P("model", None) if name == "w" else P("model")
        return P()

    def _place_params(self, params_host):
        if self.mesh is None:
            return jax.tree.map(jax.device_put, params_host)
        placed = []
        for spec, p in zip(self.specs, params_host):
            q = {}
            for name, arr in p.items():
                ns = NamedSharding(self.mesh, self._param_spec(spec, name))
                q[name] = jax.device_put(arr, ns)
            placed.append(q)
        return placed

    def _place_state(self, states_host):
        if self.mesh is None:
            return jax.tree.map(jax.device_put, states_host)
        placed = []
        for spec, st in zip(self.specs, states_host):
            q = {}
            for name, slots in st.items():
                ns = NamedSharding(self.mesh, self._param_spec(spec, name))
                q[name] = {k: jax.device_put(v, ns)
                           for k, v in slots.items()}
            placed.append(q)
        return placed

    def _place_batch(self, x, labels):
        if self.mesh is None:
            return jax.device_put(x), jax.device_put(labels)
        dsize = self.mesh.shape["data"]
        if x.shape[0] % dsize:
            raise ValueError("batch %d not divisible by data-parallel %d"
                             % (x.shape[0], dsize))
        xs = NamedSharding(self.mesh, P("data", *([None] * (x.ndim - 1))))
        ls = NamedSharding(self.mesh, P("data"))
        return jax.device_put(x, xs), jax.device_put(labels, ls)

    # -- public api ---------------------------------------------------------
    def step(self, x, labels):
        """One fused train step.  Returns {"loss": float, "n_err": int}."""
        x, labels = self._place_batch(x, labels)
        self.params, self.state, metrics = self._step(
            self.params, self.state, x, labels)
        return metrics

    def predict(self, x):
        x, _ = self._place_batch(x, numpy.zeros(x.shape[0], numpy.int32))
        return self._fwd(self.params, x)

    def host_params(self):
        return jax.tree.map(lambda a: numpy.asarray(a), self.params)


def _train_step(params, state, x, labels, specs):
    (loss, n_err), grads = jax.value_and_grad(
        lambda p: _loss_and_stats(p, x, labels, specs), has_aux=True)(params)
    new_params, new_state = [], []
    for spec, p, st, g in zip(specs, params, state, grads):
        np_, nst = {}, {}
        np_["w"], nst["w"], _ = gd_math.update(
            jnp, p["w"], g["w"], st["w"], spec.hyper, spec.flags)
        if "b" in p:
            hyper_b = spec.hyper_bias
            flags_b = dict(spec.flags, ortho=False)
            np_["b"], nst["b"], _ = gd_math.update(
                jnp, p["b"], g["b"], st["b"], hyper_b, flags_b)
        new_params.append(np_)
        new_state.append(nst)
    return new_params, new_state, {"loss": loss, "n_err": n_err}
