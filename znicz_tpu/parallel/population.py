"""Population-parallel GA evaluation — vmap the fused trainer.

The reference's genetic optimizer sprayed workflow evaluations across a
master–slave cluster (SURVEY.md §3.5).  The TPU-native equivalent
batches them: every individual of a GA generation trains CONCURRENTLY as
one vmapped XLA computation over the fused train step — the population
axis becomes a batch axis of the compiled program, so N individuals cost
roughly one individual's wall-clock on an undersubscribed chip.

All individuals share one weight init (drawn once from the seeded PRNG,
same draw order as the unit path) and a FIXED minibatch order — the GA
compares hyperparameters, so the data stream must be identical across
individuals anyway.
"""

import numpy

import jax
import jax.numpy as jnp

from znicz_tpu.core import prng
from znicz_tpu.parallel import fused


def make_population_evaluator(layers, input_sample_shape,
                              train_x, train_y, val_x, val_y,
                              values_to_hypers, epochs=6,
                              minibatch_size=None, rand=None,
                              dtype=numpy.float32, defaults=None):
    """Build ``evaluate_population(value_vectors) -> [fitness]`` for
    :class:`znicz_tpu.core.genetics.GeneticsOptimizer`.

    ``values_to_hypers(values, specs)`` maps one GA value vector onto a
    fused hyper pytree (see :func:`znicz_tpu.parallel.fused
    .default_hypers`); fitness is the negative validation error PERCENT
    after ``epochs`` of training (softmax objective) — the same scale
    the serial ``--optimize`` fallback reports (-best_n_err_pt).
    """
    specs = tuple(fused.build_specs(layers, input_sample_shape, defaults))
    if not specs[-1].is_softmax:
        raise ValueError("population evaluator scores a softmax head")
    params0 = fused.init_params(specs, rand or prng.get(), dtype)
    state0 = fused.init_opt_state(specs, params0)
    train_x = numpy.asarray(train_x, dtype)
    train_y = numpy.asarray(train_y, numpy.int32)
    n = len(train_x)
    # one fixed shuffle: datasets often arrive class-ordered (UCI Wine),
    # and class-homogeneous minibatches cripple SGD; a deterministic
    # permutation keeps the stream identical across individuals
    perm = numpy.random.RandomState(0x5EED).permutation(n)
    train_x, train_y = train_x[perm], train_y[perm]
    mb = minibatch_size or n
    steps = max(1, n // mb)
    xs = jnp.asarray(train_x[:steps * mb].reshape((steps, mb) +
                                                  train_x.shape[1:]))
    ys = jnp.asarray(train_y[:steps * mb].reshape(steps, mb))
    vx = jnp.asarray(numpy.asarray(val_x, dtype))
    vy = jnp.asarray(numpy.asarray(val_y, numpy.int32))
    p0 = jax.tree.map(jnp.asarray, params0)
    s0 = jax.tree.map(jnp.asarray, state0)

    def train_eval(hypers):
        def epoch(carry, _):
            def step(carry, batch):
                p, s = carry
                x, y = batch
                p, s, m = fused._train_step(p, s, x, y, specs,
                                            hypers=hypers)
                return (p, s), m["loss"]
            carry, losses = jax.lax.scan(step, carry, (xs, ys))
            return carry, losses[-1]

        (p, _), _ = jax.lax.scan(epoch, (p0, s0), None, length=epochs)
        probs = fused.forward(p, vx, specs)
        n_err = (jnp.argmax(probs, axis=1) != vy).sum()
        return -100.0 * n_err.astype(jnp.float32) / vy.shape[0]

    fn = jax.jit(jax.vmap(train_eval))

    def evaluate_population(value_vectors):
        hypers = [values_to_hypers(list(v), specs) for v in value_vectors]
        stacked = jax.tree.map(lambda *leaves: jnp.stack(
            [jnp.asarray(l, jnp.float32) for l in leaves]), *hypers)
        return [float(f) for f in numpy.asarray(fn(stacked))]

    return evaluate_population


def uniform_lr_hypers(values, specs):
    """The common single-site mapping: one GA value = the learning rate
    of every parameterized layer (weights and bias)."""
    lr = float(values[0])
    hypers = []
    for spec in specs:
        if spec.kind in ("fc", "conv"):
            h = {"w": dict(spec.hyper, lr=lr)}
            if spec.include_bias:
                h["b"] = dict(spec.hyper_bias, lr=lr)
            hypers.append(h)
        else:
            hypers.append({})
    return hypers
