"""Population-parallel GA evaluation — vmap the fused trainer.

The reference's genetic optimizer sprayed workflow evaluations across a
master–slave cluster (SURVEY.md §3.5).  The TPU-native equivalent
batches them: every individual of a GA generation trains CONCURRENTLY as
one vmapped XLA computation over the fused train step — the population
axis becomes a batch axis of the compiled program, so N individuals cost
roughly one individual's wall-clock on an undersubscribed chip.

All individuals share one weight init (drawn once from the seeded PRNG,
same draw order as the unit path) and a FIXED minibatch order — the GA
compares hyperparameters, so the data stream must be identical across
individuals anyway.
"""

import numpy

import jax
import jax.numpy as jnp

from znicz_tpu.core import prng
from znicz_tpu.parallel import fused


def make_population_evaluator(layers, input_sample_shape,
                              train_x, train_y, val_x, val_y,
                              values_to_hypers, epochs=6,
                              minibatch_size=None, rand=None,
                              dtype=numpy.float32, defaults=None):
    """Build ``evaluate_population(value_vectors) -> [fitness]`` for
    :class:`znicz_tpu.core.genetics.GeneticsOptimizer`.

    ``values_to_hypers(values, specs)`` maps one GA value vector onto a
    fused hyper pytree (see :func:`znicz_tpu.parallel.fused
    .default_hypers`); fitness is the negative validation error PERCENT
    after ``epochs`` of training (softmax objective) — the same scale
    the serial ``--optimize`` fallback reports (-best_n_err_pt).
    """
    specs = tuple(fused.build_specs(layers, input_sample_shape, defaults))
    if not specs[-1].is_softmax:
        raise ValueError("population evaluator scores a softmax head")
    params0 = fused.init_params(specs, rand or prng.get(), dtype)
    state0 = fused.init_opt_state(specs, params0)
    train_x = numpy.asarray(train_x, dtype)
    train_y = numpy.asarray(train_y, numpy.int32)
    n = len(train_x)
    # one fixed shuffle: datasets often arrive class-ordered (UCI Wine),
    # and class-homogeneous minibatches cripple SGD; a deterministic
    # permutation keeps the stream identical across individuals
    perm = numpy.random.RandomState(0x5EED).permutation(n)
    train_x, train_y = train_x[perm], train_y[perm]
    mb = minibatch_size or n
    steps = max(1, n // mb)
    xs = jnp.asarray(train_x[:steps * mb].reshape((steps, mb) +
                                                  train_x.shape[1:]))
    ys = jnp.asarray(train_y[:steps * mb].reshape(steps, mb))
    vx = jnp.asarray(numpy.asarray(val_x, dtype))
    vy = jnp.asarray(numpy.asarray(val_y, numpy.int32))
    p0 = jax.tree.map(jnp.asarray, params0)
    s0 = jax.tree.map(jnp.asarray, state0)

    def train_eval(hypers):
        def epoch(carry, _):
            def step(carry, batch):
                p, s = carry
                x, y = batch
                p, s, m = fused._train_step(p, s, x, y, specs,
                                            hypers=hypers)
                return (p, s), m["loss"]
            carry, losses = jax.lax.scan(step, carry, (xs, ys))
            return carry, losses[-1]

        (p, _), _ = jax.lax.scan(epoch, (p0, s0), None, length=epochs)
        probs = fused.forward(p, vx, specs)
        n_err = (jnp.argmax(probs, axis=1) != vy).sum()
        return -100.0 * n_err.astype(jnp.float32) / vy.shape[0]

    fn = jax.jit(jax.vmap(train_eval))

    def evaluate_population(value_vectors):
        hypers = [values_to_hypers(list(v), specs) for v in value_vectors]
        stacked = jax.tree.map(lambda *leaves: jnp.stack(
            [jnp.asarray(l, jnp.float32) for l in leaves]), *hypers)
        return [float(f) for f in numpy.asarray(fn(stacked))]

    return evaluate_population


def uniform_lr_hypers(values, specs):
    """The common single-site mapping: one GA value = the learning rate
    of every parameterized layer (weights and bias)."""
    lr = float(values[0])
    hypers = []
    for spec in specs:
        if spec.kind in ("fc", "conv"):
            h = {"w": dict(spec.hyper, lr=lr)}
            if spec.include_bias:
                h["b"] = dict(spec.hyper_bias, lr=lr)
            hypers.append(h)
        else:
            hypers.append({})
    return hypers


#: backward-kwargs key -> (gd_math hyper field, is_bias_slot,
#: couples_to_bias) — coupling mirrors the config parser exactly
#: (fused._parse_hyper: bias lr/moment/l1_vs_l2 default to the weights
#: value, bias wd defaults to 0, ortho never applies to bias;
#: reference "<-" contract, standard_workflow_base.py:406-422)
HYPER_KEYS = {
    "learning_rate": ("lr", False, True),
    "learning_rate_bias": ("lr", True, False),
    "weights_decay": ("wd", False, False),
    "weights_decay_bias": ("wd", True, False),
    "gradient_moment": ("moment", False, True),
    "gradient_moment_bias": ("moment", True, False),
    "l1_vs_l2": ("l1_vs_l2", False, True),
    "l1_vs_l2_bias": ("l1_vs_l2", True, False),
    "factor_ortho": ("factor_ortho", False, False),
}


def config_values_to_hypers(sites, layers, specs):
    """Build ``values_to_hypers`` automatically from the Range-tagged
    sites of a sample's config (VERDICT r3 next #6 — the reference GA
    tunes arbitrary ``Range`` config scalars, SURVEY.md §3.5).

    Each site maps onto fused hyper slots:

    * a Range inside a specific layer's dict (or its "<-" sub-dict)
      tunes THAT layer's slot;
    * a Range anywhere else with a known hyper key (``learning_rate``,
      ``weights_decay``, ``gradient_moment``, ...) tunes the slot on
      EVERY parameterized layer — the common global-hyper pattern
      (reference mnist_config.py:62);
    * the weights slot also drives the bias slot when the layer declares
      no explicit ``<key>_bias`` — the same coupling the config parser
      applies (fused._parse_hyper).

    Returns ``values_to_hypers(values, specs) -> hyper pytree`` or
    ``None`` when any site cannot be mapped (the serial GA path remains
    the general fallback)."""
    param_idx = [i for i, s in enumerate(specs)
                 if s.kind in ("fc", "conv")]
    plans = []  # per site: [(spec index, field, bias?, couple_bias)...]
    for container, key, _rng in sites:
        if key not in HYPER_KEYS:
            return None
        field, bias, couples = HYPER_KEYS[key]

        def _couple(i):
            # parser parity: the bias slot follows the weights value
            # only for coupling keys AND only when the layer declares
            # no explicit <key>_bias override
            sub = (layers[i].get("<-") or {}) \
                if isinstance(layers[i], dict) else {}
            return couples and (key + "_bias") not in sub

        targets = None
        for i, layer in enumerate(layers):
            sub = layer.get("<-") if isinstance(layer, dict) else None
            if container is sub or container is layer:
                if i not in param_idx:
                    return None
                targets = [(i, field, bias, _couple(i))]
                break
        if targets is None:
            # global site: every parameterized layer
            targets = [(i, field, bias, _couple(i)) for i in param_idx]
        plans.append(targets)

    def values_to_hypers(values, specs):
        hypers = []
        for spec in specs:
            if spec.kind in ("fc", "conv"):
                h = {"w": dict(spec.hyper)}
                if spec.include_bias:
                    h["b"] = dict(spec.hyper_bias)
                hypers.append(h)
            else:
                hypers.append({})
        for value, targets in zip(values, plans):
            value = float(value)
            for i, field, bias, couple_bias in targets:
                if bias:
                    if "b" in hypers[i]:
                        hypers[i]["b"][field] = value
                else:
                    hypers[i]["w"][field] = value
                    if couple_bias and "b" in hypers[i]:
                        hypers[i]["b"][field] = value
        return hypers

    return values_to_hypers


def _collapse_ranges(obj):
    """Deep-copy a layers config with Range values collapsed to their
    defaults (the evaluator's baseline; the GA overrides via the mapped
    hyper slots, not by mutating the config)."""
    from znicz_tpu.core.genetics import Range
    if isinstance(obj, Range):
        return obj.default
    if isinstance(obj, dict):
        return {k: _collapse_ranges(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_collapse_ranges(v) for v in obj)
    return obj


def workflow_population_evaluator(ns, sites, epochs=None, seed=12,
                                  loader_kwargs=None, verbose=False):
    """Generic ``--optimize`` fused path for StandardWorkflow samples:
    builds the sample's registered loader from its config namespace
    ``ns`` (root.<sample>), maps the Range ``sites`` onto fused hyper
    slots, and returns the vmapped population evaluator — or ``None``
    when the topology/sites are not fusable (serial fallback; with
    ``verbose`` the reason is printed so the fallback is visible)."""
    from znicz_tpu.core.workflow import DummyWorkflow
    from znicz_tpu.loader.base import UserLoaderRegistry, VALID, TRAIN

    def bail(reason):
        if verbose:
            import logging
            from znicz_tpu.core.logger import setup_logging
            setup_logging()
            logging.getLogger("genetics").info(
                "fused GA unavailable: %s; evaluating serially", reason)
        return None

    layers = _collapse_ranges(list(ns.layers))
    loader_cfg = dict(ns.loader.as_dict() if hasattr(ns.loader, "as_dict")
                      else ns.loader)
    loader_cfg.update(loader_kwargs or {})
    try:
        loader_cls = UserLoaderRegistry.get_factory(ns.loader_name)
        loader = loader_cls(DummyWorkflow(), **loader_cfg)
        loader.initialize()
    except Exception as e:
        return bail("loader %r failed to initialize (%s)"
                    % (ns.loader_name, e))
    data = getattr(loader, "original_data", None)
    labels = getattr(loader, "original_labels", None)
    if data is None or not data or not labels:
        return bail("loader exposes no in-memory dataset/labels")
    x = numpy.asarray(data.mem)
    y = numpy.asarray(labels, dtype=numpy.int32)
    vs, ve = loader.class_index_range(VALID)
    ts, te = loader.class_index_range(TRAIN)
    if te <= ts:
        return bail("loader has no TRAIN segment")
    if ve <= vs:  # no validation split: score on train
        vs, ve = ts, te
    sample_shape = tuple(x.shape[1:])
    last = layers[-1] if layers else {}
    if isinstance(last, dict) and last.get("type") == "softmax":
        # head width comes from the loader at link time when the config
        # omits it (StandardWorkflowBase link_forwards parity)
        fwd = last.setdefault("->", {})
        if "output_sample_shape" not in fwd and \
                "output_samples" not in fwd:
            try:
                fwd["output_sample_shape"] = int(
                    loader.unique_labels_count)
            except Exception:
                pass
    try:
        specs = tuple(fused.build_specs(layers, sample_shape, None))
    except Exception as e:
        return bail("topology is not fusable (%s)" % e)
    if not specs[-1].is_softmax:
        return bail("population fitness needs a softmax head")
    # site identity must match the ORIGINAL config dicts (the collapsed
    # copy exists only for spec building)
    mapper = config_values_to_hypers(sites, list(ns.layers), specs)
    if mapper is None:
        return bail("a Range site does not map onto fused hyper slots")
    max_epochs = getattr(ns.decision, "max_epochs", None)
    return make_population_evaluator(
        layers, sample_shape, x[ts:te], y[ts:te], x[vs:ve], y[vs:ve],
        mapper, epochs=epochs or min(int(max_epochs or 10), 10),
        minibatch_size=int(loader_cfg.get("minibatch_size") or 0) or None,
        rand=prng.RandomGenerator().seed(seed))
