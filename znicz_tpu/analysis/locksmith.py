"""Runtime lock-order sanitizer — the dynamic half of graftlint.

The serving control plane is ~13 threaded modules whose locks nest:
the registry lock around engine load locks, dispatch slots around the
batcher condition, breaker locks under the engine's breaker-creation
lock.  Three rounds of review hardening were almost entirely ordering
bugs in exactly this web (the PR 8 predict-racing-evict and
``stats()``-iterating-a-mutating-dict races, the PR 7 half-open-probe
leaks).  The static ``lock-guard`` checker pins per-class guard
discipline; this module watches the *cross-object* property no
intraprocedural analysis can see — the global acquisition ORDER:

* every lock created through :func:`lock` / :func:`rlock` /
  :func:`condition` while the sanitizer is enabled is a tracked
  wrapper that records, per thread, the stack of locks currently held;
* acquiring B while holding A adds the edge ``A -> B`` (role names,
  first-seen acquisition stacks kept) to a process-global graph; an
  edge that closes a cycle is a potential ABBA deadlock and is
  recorded as a violation with BOTH stacks;
* **blocking-while-holding**: ``concurrent.futures.Future.result``
  (patched by :func:`arm`) and ``Condition.wait`` entered while the
  thread holds any *other* tracked lock record a violation carrying
  the blocked call's stack and every held lock's acquisition stack —
  the ``future.result()``-under-the-registry-lock class of bug.

Gate discipline (the health.py/profiler.py contract): everything is
behind ``root.common.analysis.lock_sanitizer``.  Disabled, the
factories read ONE config predicate and return plain ``threading``
primitives — zero wrappers, zero per-acquire overhead, pinned by a
monkeypatch-boom test.  Tracking is decided at lock CREATION, so arm
the sanitizer before constructing the objects under test (the
conftest fixture arms it around the concurrent serving tests);
:func:`arm` additionally retro-wraps the known MODULE-level locks
(created at import, necessarily before any arm) in place.

Violations are recorded, never raised mid-flight — a sanitizer must
observe the race, not perturb it.  ``assert_clean()`` raises
:class:`LockOrderViolation` with the full report for CI teardowns.
"""

import threading
import traceback

from znicz_tpu.core.config import root

_cfg = root.common.analysis

#: stack-capture depth for violation reports — enough to see the call
#: path without drowning the report in pytest frames
_STACK_LIMIT = 16


class LockOrderViolation(RuntimeError):
    """Raised by :func:`assert_clean` when the armed sanitizer saw a
    cycle or a blocking call under a held lock.  Carries the full
    report (``.report``) including both stacks per violation."""

    def __init__(self, message, report):
        super(LockOrderViolation, self).__init__(message)
        self.report = report


def enabled():
    """The one gate (live config read, health.py discipline)."""
    return bool(_cfg.get("lock_sanitizer", False))


# ---------------------------------------------------------------------------
# Process-global state
# ---------------------------------------------------------------------------

_tls = threading.local()

#: guards the graph + violation lists (a plain lock on purpose: the
#: sanitizer must never track itself)
_state_lock = threading.Lock()

#: (from_role, to_role) -> {"stack_from", "stack_to", "count"} —
#: first-seen stacks per edge
_edges = {}
#: adjacency view of _edges for cycle search
_adj = {}
#: recorded cycle violations (deduped by node set)
_cycles = []
_cycle_keys = set()
#: recorded blocking-while-holding violations
_blocking = []


def _held():
    """This thread's stack of (tracked lock, acquisition stack)."""
    held = getattr(_tls, "held", None)
    if held is None:
        held = _tls.held = []
    return held


def _capture():
    return "".join(traceback.format_stack(limit=_STACK_LIMIT)[:-2])


def _find_path(src, dst):
    """DFS: a role path src -> ... -> dst through recorded edges, or
    None.  Called under _state_lock."""
    stack, seen = [(src, (src,))], {src}
    while stack:
        node, path = stack.pop()
        for nxt in _adj.get(node, ()):
            if nxt == dst:
                return path + (dst,)
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + (nxt,)))
    return None


def _record_edge(held_entry, target, acq_stack):
    """Holding ``held_entry``'s lock, the thread is acquiring
    ``target``: record the order edge and check for a cycle."""
    a, b = held_entry[0].role, target.role
    if a == b:
        # same role, different instance (e.g. two engines' load
        # locks): no defined order to learn, and an RLock's re-entry
        # of the SAME instance never reaches here
        return
    with _state_lock:
        edge = _edges.get((a, b))
        if edge is None:
            # close a cycle?  b ~> a must be checked BEFORE inserting
            # a -> b so the reported path is the pre-existing reverse
            # ordering this acquisition contradicts
            rev = _find_path(b, a)
            _edges[(a, b)] = {"stack_from": held_entry[1],
                              "stack_to": acq_stack, "count": 1}
            _adj.setdefault(a, set()).add(b)
            if rev is not None:
                key = frozenset(rev)
                if key not in _cycle_keys:
                    _cycle_keys.add(key)
                    fwd = _edges[(a, b)]
                    rev_edge = _edges.get((rev[0], rev[1])) or {}
                    _cycles.append({
                        "kind": "lock-order-cycle",
                        "cycle": list(rev) + [b],
                        "edge": [a, b],
                        "held_stack": fwd["stack_from"],
                        "acquire_stack": fwd["stack_to"],
                        "reverse_edge": [rev[0], rev[1]],
                        "reverse_held_stack": rev_edge.get(
                            "stack_from", ""),
                        "reverse_acquire_stack": rev_edge.get(
                            "stack_to", ""),
                    })
        else:
            edge["count"] += 1


def note_blocking(what, ignore=None):
    """Record a blocking-while-holding violation if this thread holds
    any tracked lock (other than ``ignore`` — a Condition's own lock
    is RELEASED by its wait).  The public hook for call sites that
    want to annotate their own blocking operations."""
    held = [e for e in _held() if e[0] is not ignore]
    if not held:
        return None
    v = {"kind": "blocking-under-lock",
         "blocking": what,
         "held": [e[0].role for e in held],
         "held_stacks": {e[0].role: e[1] for e in held},
         "stack": _capture()}
    with _state_lock:
        _blocking.append(v)
    return v


# ---------------------------------------------------------------------------
# Tracked primitives
# ---------------------------------------------------------------------------

class _TrackedLock(object):
    """Order-tracking wrapper over a ``threading`` lock.  ``role`` is
    the module-level name edges aggregate by (two registry instances'
    locks are the same role); re-entrant acquisition of the SAME
    instance (RLock) is tracked by depth and never records edges."""

    def __init__(self, role, inner, reentrant=False):
        self.role = role
        self._inner = inner
        self._reentrant = reentrant

    def acquire(self, blocking=True, timeout=-1):
        held = _held()
        mine = [e for e in held if e[0] is self]
        if not mine:
            # record the would-be edges BEFORE blocking on the inner
            # lock: a real ABBA interleaving must be reported, not
            # hung on.  A re-entered RLock sits in the held stack once
            # per level — one edge per DISTINCT held lock.
            stack = _capture()
            seen = set()
            for entry in held:
                if id(entry[0]) not in seen:
                    seen.add(id(entry[0]))
                    _record_edge(entry, self, stack)
        elif not self._reentrant:
            # a plain Lock re-acquired by its holder is a guaranteed
            # self-deadlock — report it as a one-lock cycle
            with _state_lock:
                _cycles.append({
                    "kind": "lock-order-cycle",
                    "cycle": [self.role, self.role],
                    "edge": [self.role, self.role],
                    "held_stack": mine[0][1],
                    "acquire_stack": _capture(),
                    "reverse_edge": [self.role, self.role],
                    "reverse_held_stack": "",
                    "reverse_acquire_stack": "",
                })
        ok = (self._inner.acquire(blocking, timeout)
              if timeout != -1 else self._inner.acquire(blocking))
        if ok:
            held.append((self, _capture()))
        return ok

    def release(self):
        held = _held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] is self:
                del held[i]
                break
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __getattr__(self, name):
        # exact API parity with the wrapped primitive: locked() etc.
        # exist on the wrapper iff the inner lock has them (RLock and
        # Condition grow locked() only in Python 3.14)
        return getattr(self._inner, name)


class _TrackedCondition(_TrackedLock):
    """Condition variable with the same order tracking.  ``wait``
    RELEASES the underlying lock, so the held stack drops this lock
    for the duration — but waiting while holding any OTHER tracked
    lock is blocking-under-lock and is recorded."""

    def __init__(self, role):
        super(_TrackedCondition, self).__init__(
            role, threading.Condition(), reentrant=False)

    def _drop_for_wait(self):
        held = _held()
        mine = [(i, e) for i, e in enumerate(held) if e[0] is self]
        for i, _ in reversed(mine):
            del held[i]
        return [e for _, e in mine]

    def _restore_after_wait(self, entries):
        _held().extend(entries)

    def wait(self, timeout=None):
        note_blocking("Condition.wait(%s)" % self.role, ignore=self)
        entries = self._drop_for_wait()
        try:
            return self._inner.wait(timeout)
        finally:
            self._restore_after_wait(entries)

    def wait_for(self, predicate, timeout=None):
        note_blocking("Condition.wait_for(%s)" % self.role,
                      ignore=self)
        entries = self._drop_for_wait()
        try:
            return self._inner.wait_for(predicate, timeout)
        finally:
            self._restore_after_wait(entries)

    def notify(self, n=1):
        self._inner.notify(n)

    def notify_all(self):
        self._inner.notify_all()


# ---------------------------------------------------------------------------
# Factories — what the threaded modules call
# ---------------------------------------------------------------------------

def lock(role):
    """A mutex for ``role`` (e.g. ``"serving.registry"``): a tracked
    wrapper while the sanitizer is enabled, a plain
    ``threading.Lock`` otherwise.  The disabled path is ONE config
    predicate — tracking is decided at creation, so arm the sanitizer
    before constructing the objects under test."""
    if not enabled():
        return threading.Lock()
    return _TrackedLock(role, threading.Lock())


def rlock(role):
    """Re-entrant variant — same-instance re-entry never records."""
    if not enabled():
        return threading.RLock()
    return _TrackedLock(role, threading.RLock(), reentrant=True)


def condition(role):
    """Condition-variable variant (``wait`` drops the lock from the
    held stack; waiting while holding another tracked lock is a
    blocking-under-lock violation)."""
    if not enabled():
        return threading.Condition()
    return _TrackedCondition(role)


# ---------------------------------------------------------------------------
# Arming, reporting
# ---------------------------------------------------------------------------

_future_orig = None

#: module-level locks created at IMPORT time (always before any arm()
#: can flip the gate, so the factories handed out plain locks) —
#: arm() retro-wraps these in place, wrapping the EXISTING inner lock
#: so a thread already inside one keeps mutual exclusion, and
#: disarm() restores the originals
_MODULE_LOCKS = (
    ("znicz_tpu.core.telemetry", "_lock", "telemetry.registry"),
    ("znicz_tpu.core.compile_cache", "_lock", "compile_cache"),
    ("znicz_tpu.core.faults", "_registry_lock", "faults.module"),
    ("znicz_tpu.core.health", "_monitor_lock", "health.module"),
    ("znicz_tpu.core.profiler", "_state_lock", "profiler.module"),
    ("znicz_tpu.core.profiler", "_capture_lock", "profiler.capture"),
)
_module_lock_originals = {}


def _wrap_module_locks():
    import sys
    for modname, attr, role in _MODULE_LOCKS:
        mod = sys.modules.get(modname)   # never force an import
        if mod is None:
            continue
        cur = getattr(mod, attr, None)
        if cur is None or isinstance(cur, _TrackedLock):
            continue
        _module_lock_originals[(modname, attr)] = cur
        setattr(mod, attr, _TrackedLock(role, cur))


def _unwrap_module_locks():
    import sys
    for (modname, attr), orig in _module_lock_originals.items():
        mod = sys.modules.get(modname)
        if mod is not None and isinstance(getattr(mod, attr, None),
                                          _TrackedLock):
            setattr(mod, attr, orig)
    _module_lock_originals.clear()


def arm(patch_future=True):
    """Enable the sanitizer: flip the gate (object-scoped locks
    created from here on are tracked), retro-wrap the known
    module-level locks (created at import, before any arm() could
    run), and — by default — patch
    ``concurrent.futures.Future.result`` so a result() wait under any
    tracked lock is recorded.  Idempotent; pair with :func:`disarm`."""
    global _future_orig
    root.common.analysis.lock_sanitizer = True
    _wrap_module_locks()
    if patch_future and _future_orig is None:
        import concurrent.futures
        _future_orig = concurrent.futures.Future.result

        def result(self, timeout=None):
            note_blocking("Future.result")
            return _future_orig(self, timeout)

        concurrent.futures.Future.result = result
    return True


def disarm():
    """Restore the gate, the module-level locks and the
    ``Future.result`` patch.  Recorded state survives until
    :func:`reset` — a teardown disarms first, then asserts."""
    global _future_orig
    root.common.analysis.lock_sanitizer = False
    _unwrap_module_locks()
    if _future_orig is not None:
        import concurrent.futures
        concurrent.futures.Future.result = _future_orig
        _future_orig = None
    return False


def reset():
    """Drop the recorded graph and violations (per-test isolation).
    Live threads' held stacks are thread-local and drain naturally."""
    with _state_lock:
        _edges.clear()
        _adj.clear()
        _cycles[:] = []
        _cycle_keys.clear()
        _blocking[:] = []


def report():
    """The sanitizer's view: the acquisition-order edges (with
    counts) and every recorded violation, stacks included."""
    with _state_lock:
        return {
            "enabled": enabled(),
            "edges": {"%s -> %s" % k: v["count"]
                      for k, v in _edges.items()},
            "cycles": [dict(c) for c in _cycles],
            "blocking": [dict(b) for b in _blocking],
        }


def assert_clean():
    """Raise :class:`LockOrderViolation` if any cycle or
    blocking-under-lock was recorded; returns the report otherwise."""
    rep = report()
    if not rep["cycles"] and not rep["blocking"]:
        return rep
    lines = []
    for c in rep["cycles"]:
        lines.append("lock-order cycle %s (edge %s -> %s):"
                     % (" -> ".join(c["cycle"]), c["edge"][0],
                        c["edge"][1]))
        lines.append("  held %s at:\n%s" % (c["edge"][0],
                                            c["held_stack"]))
        lines.append("  acquiring %s at:\n%s" % (c["edge"][1],
                                                 c["acquire_stack"]))
        if c.get("reverse_acquire_stack"):
            lines.append("  reverse edge %s -> %s acquired at:\n%s"
                         % (c["reverse_edge"][0], c["reverse_edge"][1],
                            c["reverse_acquire_stack"]))
    for b in rep["blocking"]:
        lines.append("blocking call %r while holding %s:"
                     % (b["blocking"], ", ".join(b["held"])))
        lines.append("  blocked at:\n%s" % b["stack"])
        for role, stack in b["held_stacks"].items():
            lines.append("  %s acquired at:\n%s" % (role, stack))
    raise LockOrderViolation(
        "%d lock-order cycle(s), %d blocking-under-lock call(s)\n%s"
        % (len(rep["cycles"]), len(rep["blocking"]),
           "\n".join(lines)), rep)
