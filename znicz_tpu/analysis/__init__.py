"""Project-invariant analysis layer.

Two halves, sharing the knob registry ``core/config.py`` declares:

* :mod:`znicz_tpu.analysis.graftlint` — dependency-free AST checkers
  for the invariants the stack otherwise only enforces dynamically
  (config-knob vocabulary, telemetry series/label discipline,
  lock-guard discipline, JAX tracing hazards, gate discipline) plus
  the legacy style checks, driven by ``tools/graftlint.py``.
* :mod:`znicz_tpu.analysis.locksmith` — an opt-in runtime lock-order
  sanitizer the threaded modules create their locks through; armed, it
  records the acquisition-order graph, detects ABBA cycles and
  blocking-while-holding, and reports held-lock stacks.  Off (the
  default), the factories hand out plain ``threading`` primitives
  after ONE config predicate.

Neither module imports jax — the CLI and the sanitizer gate stay
usable from config-only tools.
"""
