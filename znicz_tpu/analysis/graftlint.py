"""graftlint — project-invariant static analysis for the znicz_tpu tree.

Dependency-free (stdlib ``ast`` only, never imports jax) checkers for
the invariant classes the stack otherwise enforces only dynamically —
each grounded in a real shipped bug:

* ``knob-vocabulary`` — every ``root.common.*`` read/write (attribute
  chains, ``.get("key")`` literals, ``getattr``/``setattr``, and
  module aliases like ``_cfg = root.common.serving``) must resolve to
  a knob declared in ``core/config.py`` (``config.declare``).  The
  config tree auto-vivifies, so an undeclared read is a silent —
  *truthy* — default: ``core/interaction.py`` shipped with
  ``getattr(root.common, "interactive", False)`` returning an empty
  Config node that made every tty run interactive.
* ``telemetry-series`` / ``telemetry-collision`` /
  ``telemetry-cardinality`` — metric call sites must use the bounded
  series vocabulary, must not pass ``labeled()`` a label literally
  named ``name`` (it collides with the positional parameter — the
  PR 12 breaker bug, latent since PR 7), and must not derive label
  values from request data (every distinct label value is a registry
  entry forever).
* ``lock-guard`` — per class, an attribute ever written under ``with
  self.<lock>`` is flagged where written (or container-mutated)
  outside it; ``# graftlint: guarded-by(self._lock)`` on a ``def``
  declares a method that runs with the lock already held (the
  ``stats()``-iterating-a-mutating-dict and predict-racing-evict bug
  class from the PR 7/8 hardening rounds).
* ``jax-host-sync`` / ``jax-rng`` / ``jax-time`` / ``jax-donation`` —
  inside jitted / scanned function bodies: no ``float()`` / ``int()``
  / ``.item()`` / ``numpy.asarray`` on traced parameters (each is a
  device sync, breaking the zero-mid-epoch-d2h invariant), no Python
  RNG or wall-clock reads (baked in at trace time), and accumulator-
  shaped jit arguments should be donated.
* ``gate-order`` — the disabled-by-default subsystems (health,
  profiler, faults, telemetry, locksmith) must hit their one-predicate
  gate before any config walk or jax touch in the declared hot entry
  points — the zero-overhead-off contract every monkeypatch-boom test
  pins dynamically.

Plus the legacy style checks folded in from the retired
``tools/lint.py``: ``syntax``, ``tabs``, ``trailing-whitespace``,
``line-length``, ``unused-import`` (now also counting names used only
inside string constants — f-string templates, docstring doctests),
``bare-except``, ``library-print``.

Suppression: ``# noqa`` keeps its legacy meaning on style lines;
``# graftlint: disable=check-id[,check-id...]`` suppresses named
checks on that line (on a ``def``/``class`` line: for the whole
body); the CLI additionally honors a reviewed baseline file of
``path :: check :: token`` fingerprints (``tools/graftlint_baseline``).

Entry point: ``tools/graftlint.py`` (CLI + ``--selftest``).
"""

import ast
import os
import re

# ---------------------------------------------------------------------------
# Vocabulary
# ---------------------------------------------------------------------------

#: first dotted segment of every legal telemetry series name — extend
#: ONLY with a reviewed family prefix (each series is a /metrics entry)
SERIES_PREFIXES = frozenset((
    "analysis",
    # the durable blackbox (ISSUE 19): writer meters — records/bytes
    # persisted, segment rotations, retention deletions, torn tails
    # found on recovery (core/blackbox.py)
    "blackbox",
    "faults",
    # the multi-replica serving fleet (ISSUE 15): replica-count
    # gauges + autoscaler decision counters (serving/router.py,
    # serving/autoscaler.py) and the front-end router's proxy/retry
    # counters; ISSUE 16 adds the fleet.hop_seconds.<kind> histogram
    # family — per-model router hop-phase timings fed from sampled
    # trace spans (kind is bounded by reqtrace.ROUTER_SPAN_KINDS)
    "fleet",
    "health", "jax", "launcher", "loader",
    "memory", "profiler",
    # the continuous Python sampling profiler (ISSUE 18):
    # pyprof.samples (sweep yield) and pyprof.gil_wait_ms (calibrated
    # scheduling-delay excess) — core/pyprof.py, sampled into rings
    # by core/timeseries.py
    "pyprof",
    "registry",
    # the release plane (ISSUE 17): shadow-compare / canary-state
    # series per (model, generation) — release.shadow_compares,
    # release.shadow_mismatches, release.shadow_dropped,
    # release.state, release.canary_pct (serving/release.py)
    "release",
    "router",
    "serving",
    # the serving SLO plane (ISSUE 14): per-model good/total,
    # burn-rate and error-budget series (serving/slo.py) and the
    # time-series sampler's own meters (core/timeseries.py)
    "slo", "snapshotter", "timeseries",
    "trainer", "transfer", "unit",
    # the binary framed relay (ISSUE 20): frame/byte/error meters on
    # both the listener and the router-side mux (serving/wire.py) —
    # wire.frames_in, wire.bytes_in, wire.protocol_errors,
    # wire.round_trips, wire.dead_conns, ...
    "wire",
    "workflow",
))

#: legal ``labeled()`` label keys — a bounded set by design (every
#: (key, value) pair mints a new series)
LABEL_KEYS = frozenset((
    "bucket", "breaker", "device", "dtype",
    # the release plane (ISSUE 17): generation ordinals ("1", "2",
    # ...) on the release.* series — bounded by promote cadence (one
    # value per deployed generation), never by request data
    "gen",
    "model",
    # the priority lanes (ISSUE 15): bounded by the PRIORITIES
    # vocabulary in serving/continuous.py (high/normal/low)
    "priority",
    # the fleet tracing plane (ISSUE 16): replica ids ("r0", "r1",
    # ...) on the router's stitched-trace counters — bounded by fleet
    # membership (autoscaler churn is cooldown-limited), never by
    # request data
    "replica",
    "scenario", "site",
    # the binary framed relay (ISSUE 20): which transport carried a
    # request into serving.codec_requests — exactly two values
    # ("binary" / "http"), serving/server.py
    "codec",
))

#: identifiers that mark a label VALUE as derived from request data —
#: unbounded cardinality (one series per request id/payload)
LABEL_VALUE_DENY = frozenset((
    "request_id", "request_ids", "rid", "rids", "request", "req",
    "payload", "body", "uuid",
))

_SERIES_RE = re.compile(r"^[a-z][a-z0-9_.]*$")

#: Config methods that may terminate a knob chain
_CFG_METHODS = frozenset(("get", "update", "items", "keys", "as_dict",
                          "print_", "to_json"))

#: container-mutating method names counted as writes by lock-guard
_MUTATORS = frozenset((
    "append", "appendleft", "extend", "extendleft", "insert", "add",
    "discard", "remove", "pop", "popleft", "popitem", "clear",
    "update", "setdefault", "sort", "reverse", "rotate",
))

#: gated subsystems: per-module gate-function names and the hot entry
#: points REQUIRED to gate (the zero-overhead-off contract)
GATED_MODULES = {
    "znicz_tpu/core/health.py": {
        "gates": ("enabled",),
        "required": ("check_training_step", "check_gd_unit",
                     "observe_loss"),
    },
    "znicz_tpu/core/profiler.py": {
        "gates": ("enabled",),
        "required": ("register_jit_cost", "ledger_swap", "epoch_check",
                     "note_data_wait", "note_gd_step", "window_probe"),
    },
    "znicz_tpu/core/faults.py": {
        "gates": ("enabled",),
        "required": (),
    },
    "znicz_tpu/core/telemetry.py": {
        "gates": ("enabled", "journal_enabled", "_get_metric"),
        "required": ("span", "instant", "record_event", "counter",
                     "gauge", "histogram"),
    },
    "znicz_tpu/analysis/locksmith.py": {
        "gates": ("enabled",),
        "required": ("lock", "rlock", "condition"),
    },
    "znicz_tpu/core/timeseries.py": {
        "gates": ("enabled",),
        "required": ("sample_once", "maybe_start"),
    },
    "znicz_tpu/core/pyprof.py": {
        "gates": ("enabled",),
        "required": ("sample_once", "maybe_start", "gil_probe_once"),
    },
    "znicz_tpu/serving/reqtrace.py": {
        "gates": ("enabled", "sampled"),
        "required": ("begin",),
    },
    "znicz_tpu/core/blackbox.py": {
        "gates": ("enabled",),
        "required": ("maybe_arm",),
    },
}

# legacy style-check knobs (tools/lint.py heritage)
MAX_LINE = 80
LIB_DIRS = ("znicz_tpu",)
PRINT_OK = ("samples", "__main__.py", "launcher.py", "parity.py")

#: accumulator-shaped jit parameters that should be donated
_ACC_PARAM_RE = re.compile(r"(^|_)acc(um)?(_|$|s$)")


class Finding(object):
    """One reported violation."""

    __slots__ = ("path", "line", "check", "message", "token")

    def __init__(self, path, line, check, message, token=""):
        self.path = path
        self.line = int(line)
        self.check = check
        self.message = message
        self.token = token or ""

    @property
    def fingerprint(self):
        """Line-number-free identity for the baseline file."""
        return "%s :: %s :: %s" % (self.path, self.check, self.token)

    def __str__(self):
        return "%s:%d: [%s] %s" % (self.path, self.line, self.check,
                                   self.message)

    def __repr__(self):
        return "<Finding %s>" % self


# ---------------------------------------------------------------------------
# Pragmas
# ---------------------------------------------------------------------------

_PRAGMA_RE = re.compile(r"#\s*graftlint:\s*([^#]*)")
_GUARDED_RE = re.compile(r"guarded-by\(([^)]+)\)")
_DISABLE_RE = re.compile(r"disable=([A-Za-z0-9_,-]+)")


class _Pragmas(object):
    """Per-file pragma index: line -> disabled checks / guard lock."""

    def __init__(self, lines):
        self.disabled = {}    # lineno -> set of check ids
        self.guarded = {}     # lineno -> lock attr name (e.g. "_lock")
        for i, line in enumerate(lines, 1):
            m = _PRAGMA_RE.search(line)
            if not m:
                continue
            text = m.group(1)
            d = _DISABLE_RE.search(text)
            if d:
                self.disabled[i] = set(
                    c.strip() for c in d.group(1).split(",") if c)
            g = _GUARDED_RE.search(text)
            if g:
                lock = g.group(1).strip()
                if lock.startswith("self."):
                    lock = lock[len("self."):]
                self.guarded[i] = lock

    def allows(self, check, lineno):
        return check in self.disabled.get(lineno, ())

    def allows_span(self, check, node):
        """A pragma anywhere on the lines a (possibly multi-line)
        expression spans suppresses it."""
        end = getattr(node, "end_lineno", None) or node.lineno
        return any(self.allows(check, i)
                   for i in range(node.lineno, end + 1))


# ---------------------------------------------------------------------------
# Shared AST helpers
# ---------------------------------------------------------------------------

def _attr_chain(node):
    """``a.b.c`` -> ["a", "b", "c"]; None for non-trivial bases."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return parts[::-1]
    return None


def _walk(node):
    """Depth-first pre-order (ast.walk is BFS; checker logic needs
    source order)."""
    yield node
    for child in ast.iter_child_nodes(node):
        for sub in _walk(child):
            yield sub


def _parent_map(tree):
    parents = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def _const_str(node):
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _names_in(node):
    out = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Name):
            out.add(n.id)
        elif isinstance(n, ast.Attribute):
            out.add(n.attr)
    return out


# ---------------------------------------------------------------------------
# Knob vocabulary
# ---------------------------------------------------------------------------

def load_vocabulary():
    """The declared knob/namespace paths from ``core/config.py`` (a
    jax-free import)."""
    from znicz_tpu.core import config
    return config.declared_knobs(), config.declared_nodes()


def _knob_declared(path, knobs, nodes):
    if path in knobs or path in nodes:
        return True
    parts = path.split(".")
    for i in range(1, len(parts)):
        if ".".join(parts[:i]) in knobs:
            return True   # payload inside a dict-valued knob
    return False


def check_knobs(tree, rel, pragmas, knobs, nodes, findings):
    """Every ``root.common.*`` path must resolve to a declared knob."""
    if rel.replace(os.sep, "/").endswith("znicz_tpu/core/config.py"):
        return   # the declaration site itself
    parents = _parent_map(tree)
    # module/function aliases: NAME = root.common.<chain>
    aliases = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            chain = _attr_chain(node.value) \
                if isinstance(node.value, ast.Attribute) else None
            if chain and chain[:2] == ["root", "common"]:
                aliases[node.targets[0].id] = ".".join(chain[1:])

    def resolve(chain):
        """Dotted path relative to ``root`` or None if unrelated."""
        if chain[:2] == ["root", "common"]:
            return ".".join(chain[1:])
        if chain[0] in aliases:
            return ".".join([aliases[chain[0]]] + chain[1:])
        return None

    def report(path, node):
        if pragmas.allows("knob-vocabulary", node.lineno):
            return
        if not _knob_declared(path, knobs, nodes):
            findings.append(Finding(
                rel, node.lineno, "knob-vocabulary",
                "undeclared config knob root.%s — declare it in "
                "core/config.py (config.declare) or fix the typo; an "
                "undeclared read auto-vivifies a truthy empty node"
                % path, token=path))

    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute):
            parent = parents.get(node)
            if isinstance(parent, ast.Attribute) and \
                    parent.value is node:
                continue   # not a maximal chain
            chain = _attr_chain(node)
            if not chain:
                continue
            # chain ending in a Config method call: validate the base,
            # plus the literal key of .get(...)
            call = parent if isinstance(parent, ast.Call) and \
                parent.func is node else None
            if call is not None and chain[-1] in _CFG_METHODS:
                base = resolve(chain[:-1])
                if base is None:
                    continue
                report(base, node)
                if chain[-1] == "get" and call.args:
                    key = _const_str(call.args[0])
                    if key is not None:
                        report("%s.%s" % (base, key), node)
                continue
            path = resolve(chain)
            if path is not None:
                report(path, node)
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Name) and \
                node.func.id in ("getattr", "setattr") and \
                len(node.args) >= 2:
            chain = _attr_chain(node.args[0]) \
                if isinstance(node.args[0], ast.Attribute) else (
                    [node.args[0].id]
                    if isinstance(node.args[0], ast.Name) else None)
            if not chain:
                continue
            base = resolve(chain) if len(chain) > 1 else (
                "common" if chain == ["root"] else
                aliases.get(chain[0]))
            if chain == ["root"]:
                base = None   # root.<x> only matters under common
            if base is None and chain[:1] == ["root"]:
                continue
            if base is None:
                continue
            key = _const_str(node.args[1])
            if key is not None:
                report("%s.%s" % (base, key), node)


# ---------------------------------------------------------------------------
# Telemetry series / label discipline
# ---------------------------------------------------------------------------

def _series_static_prefix(node, constants):
    """(full_name, prefix) for a statically-known series-name
    expression; (None, None) when dynamic.  ``full_name`` is set only
    for complete literals; templates yield just their static prefix."""
    s = _const_str(node)
    if s is not None:
        return s, s
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mod):
        left = _const_str(node.left)
        if left is not None:
            return None, left.split("%")[0]
    if isinstance(node, ast.JoinedStr) and node.values:
        head = _const_str(node.values[0])
        if head is not None:
            return None, head
    if isinstance(node, ast.Name) and node.id in constants:
        s = constants[node.id]
        return s, s
    return None, None


def _check_series_name(node, call, rel, pragmas, findings):
    """Validate one series-name expression; returns True if it was
    statically checkable."""
    # module-level string constants are resolved by the caller's
    # ``constants`` map threaded through check_telemetry
    full, prefix = node._graftlint_resolved
    lineno = node.lineno
    if pragmas.allows_span("telemetry-series", call):
        return True
    if full is not None:
        if not _SERIES_RE.match(full) or \
                full.split(".")[0] not in SERIES_PREFIXES or \
                "." not in full:
            findings.append(Finding(
                rel, lineno, "telemetry-series",
                "series name %r is outside the bounded vocabulary "
                "(family prefixes: %s)"
                % (full, ", ".join(sorted(SERIES_PREFIXES))),
                token=full))
        return True
    if prefix is not None:
        fam = prefix.split(".")[0]
        if "." not in prefix or fam not in SERIES_PREFIXES:
            findings.append(Finding(
                rel, lineno, "telemetry-series",
                "templated series name %r* does not start with a "
                "known family prefix" % prefix, token=prefix))
        return True
    findings.append(Finding(
        rel, lineno, "telemetry-series",
        "dynamic series name — metric names must be statically "
        "bounded (literal, literal template, or module constant)",
        token="<dynamic>"))
    return False


def _check_labels(call, rel, pragmas, findings):
    for kw in call.keywords:
        lineno = getattr(kw.value, "lineno", call.lineno)
        if kw.arg is None:
            if not pragmas.allows_span("telemetry-cardinality", call):
                findings.append(Finding(
                    rel, lineno, "telemetry-cardinality",
                    "**labels unpacking is not statically checkable "
                    "— pass explicit label keys (or pragma a reviewed "
                    "wrapper)", token="**"))
            continue
        if kw.arg == "name":
            if not pragmas.allows_span("telemetry-collision", call):
                findings.append(Finding(
                    rel, lineno, "telemetry-collision",
                    "label key 'name' collides with labeled()'s "
                    "positional parameter — TypeError at runtime "
                    "(the PR 12 breaker bug); pick another key",
                    token="name"))
            continue
        if kw.arg not in LABEL_KEYS:
            if not pragmas.allows_span("telemetry-cardinality", call):
                findings.append(Finding(
                    rel, lineno, "telemetry-cardinality",
                    "unknown label key %r — extend the reviewed "
                    "LABEL_KEYS vocabulary (analysis/graftlint.py) "
                    "only for bounded label sets" % kw.arg,
                    token=kw.arg))
            continue
        tainted = _names_in(kw.value) & LABEL_VALUE_DENY
        if tainted and not pragmas.allows_span(
                "telemetry-cardinality", call):
            findings.append(Finding(
                rel, lineno, "telemetry-cardinality",
                "label %r value derives from request data (%s) — "
                "unbounded cardinality mints one series per request"
                % (kw.arg, ", ".join(sorted(tainted))),
                token="%s=%s" % (kw.arg, ",".join(sorted(tainted)))))


def check_telemetry(tree, rel, pragmas, findings):
    in_telemetry = rel.replace(os.sep, "/").endswith(
        "znicz_tpu/core/telemetry.py")
    constants = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            s = _const_str(node.value)
            if s is not None:
                constants[node.targets[0].id] = s

    def api_name(func):
        if isinstance(func, ast.Attribute):
            chain = _attr_chain(func)
            if chain and len(chain) >= 2 and \
                    chain[-2] == "telemetry" and \
                    chain[-1] in ("counter", "gauge", "histogram",
                                  "labeled"):
                return chain[-1]
            return None
        if in_telemetry and isinstance(func, ast.Name) and \
                func.id in ("counter", "gauge", "histogram",
                            "labeled"):
            return func.id
        return None

    def resolve_mark(expr):
        expr._graftlint_resolved = _series_static_prefix(expr,
                                                         constants)

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        api = api_name(node.func)
        if api is None:
            continue
        name_arg = node.args[0] if node.args else None
        for kw in node.keywords:
            if kw.arg == "name":
                name_arg = kw.value if api != "labeled" else name_arg
        if api == "labeled":
            if name_arg is not None:
                resolve_mark(name_arg)
                _check_series_name(name_arg, node, rel, pragmas,
                                   findings)
            _check_labels(node, rel, pragmas, findings)
            continue
        # counter/gauge/histogram
        if name_arg is None:
            continue
        if isinstance(name_arg, ast.Call):
            inner_api = api_name(name_arg.func)
            if inner_api == "labeled":
                continue   # the labeled() call is checked on its own
            # wrapper pattern (engine._label(series, **labels)): the
            # first argument must be a checkable series name and the
            # keywords are labels
            if name_arg.args:
                resolve_mark(name_arg.args[0])
                _check_series_name(name_arg.args[0], name_arg, rel,
                                   pragmas, findings)
                _check_labels(name_arg, rel, pragmas, findings)
                continue
            if not pragmas.allows_span("telemetry-series", node):
                findings.append(Finding(
                    rel, name_arg.lineno, "telemetry-series",
                    "series name computed by an opaque call — not "
                    "statically bounded", token="<call>"))
            continue
        resolve_mark(name_arg)
        _check_series_name(name_arg, node, rel, pragmas, findings)


# ---------------------------------------------------------------------------
# Lock-guard discipline
# ---------------------------------------------------------------------------

_LOCK_FACTORIES = {
    ("threading", "Lock"), ("threading", "RLock"),
    ("threading", "Condition"),
    ("locksmith", "lock"), ("locksmith", "rlock"),
    ("locksmith", "condition"),
}


def _is_lock_factory(node):
    if not isinstance(node, ast.Call):
        return False
    chain = _attr_chain(node.func)
    return bool(chain) and len(chain) >= 2 and \
        (chain[-2], chain[-1]) in _LOCK_FACTORIES


def _self_attr_target(node):
    """'self.X' / 'self.X[...]' -> 'X' (write target extraction)."""
    if isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and \
            node.value.id == "self":
        return node.attr
    return None


def check_lock_guard(tree, rel, pragmas, findings):
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        methods = [n for n in cls.body
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))]
        lock_attrs = set()
        for m in methods:
            for node in ast.walk(m):
                if isinstance(node, ast.Assign) and \
                        _is_lock_factory(node.value):
                    for t in node.targets:
                        attr = _self_attr_target(t)
                        if attr is not None:
                            lock_attrs.add(attr)
        if not lock_attrs:
            continue
        writes = []   # (attr, lineno, held frozenset, method name)

        def visit(node, held, init):
            if isinstance(node, ast.With):
                extra = set()
                for item in node.items:
                    attr = _self_attr_target(item.context_expr)
                    if attr in lock_attrs:
                        extra.add(attr)
                inner = held | extra
                for child in node.body:
                    visit(child, inner, init)
                return
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef, ast.Lambda)):
                # a nested function runs LATER, not under the lock
                body = node.body if not isinstance(node, ast.Lambda) \
                    else [node.body]
                nested_held = frozenset()
                g = pragmas.guarded.get(node.lineno)
                if g in lock_attrs:
                    nested_held = frozenset((g,))
                for child in body:
                    visit(child, set(nested_held), init)
                return
            if isinstance(node, (ast.Assign, ast.AugAssign,
                                 ast.AnnAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    elts = t.elts if isinstance(t, (ast.Tuple,
                                                    ast.List)) else [t]
                    for e in elts:
                        attr = _self_attr_target(e)
                        if attr is not None and not init:
                            writes.append((attr, node.lineno,
                                           frozenset(held)))
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _MUTATORS:
                attr = _self_attr_target(node.func.value)
                if attr is not None and not init:
                    writes.append((attr, node.lineno, frozenset(held)))
            for child in ast.iter_child_nodes(node):
                visit(child, held, init)

        for m in methods:
            init = m.name in ("__init__", "__new__")
            held = set()
            g = pragmas.guarded.get(m.lineno)
            if g in lock_attrs:
                held.add(g)
            for child in m.body:
                visit(child, held, init)

        guarded_by = {}   # attr -> set of locks it is written under
        for attr, _, held in writes:
            if held:
                guarded_by.setdefault(attr, set()).update(held)
        for attr, lineno, held in writes:
            locks = guarded_by.get(attr)
            if not locks or held & locks:
                continue
            if attr in lock_attrs:
                continue
            if pragmas.allows("lock-guard", lineno):
                continue
            findings.append(Finding(
                rel, lineno, "lock-guard",
                "%s.%s is written under %s elsewhere but unguarded "
                "here — take the lock, or mark the method "
                "'# graftlint: guarded-by(self.%s)' if the caller "
                "already holds it"
                % (cls.name, attr,
                   "/".join("self.%s" % x for x in sorted(locks)),
                   sorted(locks)[0]),
                token="%s.%s" % (cls.name, attr)))


# ---------------------------------------------------------------------------
# JAX tracing hazards
# ---------------------------------------------------------------------------

def _is_jax_jit(func):
    chain = _attr_chain(func)
    return bool(chain) and chain[-2:] == ["jax", "jit"]


def _is_lax_scan(func):
    chain = _attr_chain(func)
    return bool(chain) and chain[-2:] == ["lax", "scan"]


def _static_params(fn, call):
    """Parameter names a jit call marks static (static_argnums /
    static_argnames) — their values are Python constants, not traced."""
    if call is None:
        return frozenset()
    names = set()
    ordered = [a.arg for a in fn.args.posonlyargs + fn.args.args] \
        if not isinstance(fn, ast.Lambda) \
        else [a.arg for a in fn.args.args]
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            for n in ast.walk(kw.value):
                s = _const_str(n)
                if s is not None:
                    names.add(s)
        elif kw.arg == "static_argnums":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and \
                        isinstance(n.value, int):
                    if 0 <= n.value < len(ordered):
                        names.add(ordered[n.value])
    return frozenset(names)


def check_jax(tree, rel, pragmas, findings):
    # collect every def/lambda by name for call-site resolution
    defs = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, node)
    traced = []   # (fn node, why, static param names)

    def resolve_fn(arg):
        if isinstance(arg, ast.Lambda):
            return arg
        if isinstance(arg, ast.Name):
            return defs.get(arg.id)
        return None

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                call = dec if isinstance(dec, ast.Call) else None
                if _is_jax_jit(dec) or (
                        call is not None
                        and (_is_jax_jit(call.func)
                             or (_attr_chain(call.func) or [])[-1:]
                             == ["partial"]
                             and any(_is_jax_jit(a)
                                     for a in call.args))):
                    traced.append((node, "jit",
                                   _static_params(node, call)))
                    _check_donation(node, call, rel, pragmas,
                                    findings)
        elif isinstance(node, ast.Call):
            if _is_jax_jit(node.func) and node.args:
                fn = resolve_fn(node.args[0])
                if fn is not None:
                    traced.append((fn, "jit",
                                   _static_params(fn, node)))
                    _check_donation(fn, node, rel, pragmas, findings)
            elif _is_lax_scan(node.func) and node.args:
                fn = resolve_fn(node.args[0])
                if fn is not None:
                    traced.append((fn, "scan", frozenset()))

    seen = set()
    for fn, why, static in traced:
        if id(fn) in seen:
            continue
        seen.add(id(fn))
        _scan_traced_body(fn, why, static, rel, pragmas, findings)


def _fn_params(fn):
    args = fn.args
    names = [a.arg for a in args.args + args.posonlyargs +
             args.kwonlyargs]
    if args.vararg:
        names.append(args.vararg.arg)
    return set(n for n in names if n != "self")


def _check_donation(fn, call, rel, pragmas, findings):
    acc = sorted(p for p in _fn_params(fn) if _ACC_PARAM_RE.search(p))
    if not acc:
        return
    if call is not None and any(
            kw.arg in ("donate_argnums", "donate_argnames")
            for kw in call.keywords):
        return
    lineno = call.lineno if call is not None else fn.lineno
    if pragmas.allows("jax-donation", lineno):
        return
    findings.append(Finding(
        rel, lineno, "jax-donation",
        "jit of %r takes accumulator-shaped arg(s) %s without "
        "donate_argnums — the carried buffer is copied every dispatch"
        % (fn.name if hasattr(fn, "name") else "<lambda>",
           ", ".join(acc)),
        token=(fn.name if hasattr(fn, "name") else "<lambda>")))


def _scan_traced_body(fn, why, static, rel, pragmas, findings):
    params = _fn_params(fn) - static
    body = fn.body if not isinstance(fn, ast.Lambda) else [fn.body]
    for stmt in body:
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Call):
                continue
            lineno = node.lineno
            chain = _attr_chain(node.func) or []
            # host syncs on traced names
            if isinstance(node.func, ast.Name) and \
                    node.func.id in ("float", "int", "bool") and \
                    node.args and (_names_in(node.args[0]) & params) \
                    and not any(
                        isinstance(n, ast.Attribute)
                        and n.attr in ("shape", "ndim", "size")
                        for n in ast.walk(node.args[0])):
                # .shape/.ndim metadata is static even on traced values
                if not pragmas.allows("jax-host-sync", lineno):
                    findings.append(Finding(
                        rel, lineno, "jax-host-sync",
                        "%s() on a traced value inside a %s body is "
                        "a device sync" % (node.func.id, why),
                        token=node.func.id))
            elif chain[-1:] == ["item"] and len(chain) >= 2:
                if not pragmas.allows("jax-host-sync", lineno):
                    findings.append(Finding(
                        rel, lineno, "jax-host-sync",
                        ".item() inside a %s body is a device sync"
                        % why, token="item"))
            elif len(chain) >= 2 and chain[0] in ("numpy", "np") and \
                    chain[1] in ("asarray", "array") and node.args \
                    and (_names_in(node.args[0]) & params):
                if not pragmas.allows("jax-host-sync", lineno):
                    findings.append(Finding(
                        rel, lineno, "jax-host-sync",
                        "%s on a traced value inside a %s body "
                        "forces a host transfer"
                        % (".".join(chain[:2]), why),
                        token=".".join(chain[:2])))
            # wall clock
            elif chain[:1] == ["time"] and len(chain) == 2 and \
                    chain[1] in ("time", "monotonic", "perf_counter",
                                 "sleep"):
                if not pragmas.allows("jax-time", lineno):
                    findings.append(Finding(
                        rel, lineno, "jax-time",
                        "time.%s() inside a %s body is baked in at "
                        "trace time (and syncs nothing)"
                        % (chain[1], why), token="time." + chain[1]))
            # Python / numpy RNG
            elif (chain[:1] == ["random"] and len(chain) >= 2) or (
                    len(chain) >= 3 and chain[0] in ("numpy", "np")
                    and chain[1] == "random"):
                if not pragmas.allows("jax-rng", lineno):
                    findings.append(Finding(
                        rel, lineno, "jax-rng",
                        "Python/numpy RNG inside a %s body is drawn "
                        "ONCE at trace time — use jax.random with a "
                        "threaded key" % why,
                        token=".".join(chain[:2])))


# ---------------------------------------------------------------------------
# Gate discipline
# ---------------------------------------------------------------------------

def check_gate_order(tree, rel, pragmas, findings):
    spec = None
    rel_posix = rel.replace(os.sep, "/")
    for suffix, s in GATED_MODULES.items():
        if rel_posix.endswith(suffix):
            spec = s
            break
    if spec is None:
        return
    gates = set(spec["gates"])
    required = set(spec["required"])

    for fn in tree.body:
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if fn.name.startswith("_") and fn.name not in required:
            continue
        if fn.name in gates or fn.name in ("enable", "disable",
                                           "reset"):
            continue
        if pragmas.allows("gate-order", fn.lineno):
            continue
        gate_line = None
        hot = None   # (lineno, what) of the first hot touch
        for node in _walk(fn):
            if node is fn:
                continue
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Name) and \
                    node.func.id in gates:
                gate_line = node.lineno
                break
            if hot is not None:
                continue
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                mod = getattr(node, "module", None) or ",".join(
                    a.name for a in node.names)
                if mod.split(".")[0] == "jax":
                    hot = (node.lineno, "jax import")
            elif isinstance(node, ast.Attribute):
                chain = _attr_chain(node)
                if not chain:
                    continue
                if chain[0] in ("jax", "jnp"):
                    hot = (node.lineno, "jax touch")
                elif chain[:2] == ["root", "common"]:
                    if chain[-1] == "enabled":
                        continue   # the gate's own knob
                    hot = (node.lineno,
                           "config walk root.%s" % ".".join(chain[1:]))
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "get" and node.args:
                key = _const_str(node.args[0])
                base = _attr_chain(node.func.value)
                if key not in (None, "enabled") and base and \
                        (base[0].endswith("cfg")
                         or base[:2] == ["root", "common"]):
                    hot = (node.lineno, "config read %r" % key)
        if fn.name in required and gate_line is None:
            findings.append(Finding(
                rel, fn.lineno, "gate-order",
                "%s() is a hot entry point of a disabled-by-default "
                "subsystem and never checks the %s gate"
                % (fn.name, "/".join(sorted(gates))), token=fn.name))
        elif gate_line is not None and hot is not None:
            findings.append(Finding(
                rel, hot[0], "gate-order",
                "%s() does %s before the gate at line %d — the "
                "disabled path must be ONE predicate"
                % (fn.name, hot[1], gate_line), token=fn.name))


def check_thread_name(tree, rel, pragmas, findings):
    """Every thread the codebase spawns must carry a stable
    ``znicz:<component>`` name — the thread-name registry half of the
    continuous profiler's contract (ISSUE 18, core/pyprof.py): the
    sampler attributes stack samples BY THREAD NAME, so a thread
    constructed without one surfaces as ``Thread-12`` and every one
    of its samples lands in the ``unnamed`` bucket.  Flags
    ``threading.Thread(...)`` construction without ``name=`` and
    ``ThreadPoolExecutor(...)`` without ``thread_name_prefix=``
    (tests are style-scope only and exempt; a ``**kwargs`` splat is
    trusted to carry the name)."""
    for node in _walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if isinstance(node.func, ast.Attribute):
            fname = node.func.attr
        elif isinstance(node.func, ast.Name):
            fname = node.func.id
        else:
            continue
        if fname not in ("Thread", "ThreadPoolExecutor"):
            continue
        kw = "name" if fname == "Thread" else "thread_name_prefix"
        passed = {k.arg for k in node.keywords}
        if None in passed or kw in passed:
            continue
        if pragmas.allows("thread-name", node.lineno):
            continue
        findings.append(Finding(
            rel, node.lineno, "thread-name",
            "%s(...) constructed without %s= — every spawned thread "
            "needs a stable znicz:<component> name so pyprof sample "
            "attribution never reads Thread-N (core/pyprof.py "
            "thread_name())" % (fname, kw), token=fname))


# ---------------------------------------------------------------------------
# Legacy style checks (tools/lint.py heritage)
# ---------------------------------------------------------------------------

def check_style(tree, lines, rel, pragmas, findings):
    rel_posix = rel.replace(os.sep, "/")
    for i, line in enumerate(lines, 1):
        stripped = line.rstrip("\n")
        indent = stripped[:len(stripped) - len(stripped.lstrip())]
        if "\t" in indent and not pragmas.allows("tabs", i):
            findings.append(Finding(rel, i, "tabs",
                                    "tab in indentation"))
        if stripped != stripped.rstrip() and \
                not pragmas.allows("trailing-whitespace", i):
            findings.append(Finding(rel, i, "trailing-whitespace",
                                    "trailing whitespace"))
        if len(stripped) > MAX_LINE and "noqa" not in stripped and \
                not pragmas.allows("line-length", i):
            findings.append(Finding(
                rel, i, "line-length",
                "line too long (%d > %d)" % (len(stripped),
                                             MAX_LINE)))
    findings.extend(_unused_imports(tree, lines, rel, pragmas))
    for node in ast.walk(tree):
        if isinstance(node, ast.ExceptHandler) and node.type is None \
                and not pragmas.allows("bare-except", node.lineno):
            findings.append(Finding(rel, node.lineno, "bare-except",
                                    "bare except"))
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "print"
                and rel_posix.startswith(LIB_DIRS)
                and not any(p in rel_posix for p in PRINT_OK)
                and node.lineno <= len(lines)
                and "noqa" not in lines[node.lineno - 1]
                and not pragmas.allows("library-print", node.lineno)):
            findings.append(Finding(
                rel, node.lineno, "library-print",
                "print() in library code (use the logger)"))


def _unused_imports(tree, lines, rel, pragmas):
    imported = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                name = alias.asname or alias.name.split(".")[0]
                imported[name] = node.lineno
        elif isinstance(node, ast.ImportFrom):
            for alias in node.names:
                if alias.name == "*":
                    continue
                imported[alias.asname or alias.name] = node.lineno
    if not imported:
        return []
    used = set()
    string_text = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            n = node
            while isinstance(n, ast.Attribute):
                n = n.value
            if isinstance(n, ast.Name):
                used.add(n.id)
        elif isinstance(node, ast.Constant) and \
                isinstance(node.value, str):
            string_text.append(node.value)
    # the legacy checker's blind spot: a name referenced only inside a
    # string constant — an f-string template kept as a plain string, a
    # docstring doctest (`>>> numpy.ones(...)`) — is still a use.
    # Only DOTTED usage (`name.attr`) or a doctest line mentioning the
    # name counts: a bare prose word ("baked in at trace time") must
    # not grandfather a dead `import time`
    blob = "\n".join(string_text)
    out = []
    for name, lineno in imported.items():
        if name in used:
            continue
        line = lines[lineno - 1] if lineno <= len(lines) else ""
        if "noqa" in line or pragmas.allows("unused-import", lineno):
            continue
        esc = re.escape(name)
        if blob and (re.search(r"\b%s\s*\.\s*\w" % esc, blob)
                     or re.search(r"^\s*>>>.*\b%s\b" % esc, blob,
                                  re.MULTILINE)):
            continue
        out.append(Finding(rel, lineno, "unused-import",
                           "unused import %r" % name, token=name))
    return out


# ---------------------------------------------------------------------------
# Driver
# ---------------------------------------------------------------------------

#: directories the legacy style checks cover (lint.py heritage)
STYLE_SCAN = ("znicz_tpu", "tests", "tools")
#: scope of the project-invariant checkers (ISSUE 13: the library, the
#: tools, and bench.py — tests intentionally monkeypatch around every
#: invariant and are style-checked only)
INVARIANT_SCAN = ("znicz_tpu", "tools")
INVARIANT_FILES = ("bench.py",)
SKIP_PARTS = ("__pycache__",)


def check_source(src, rel, vocab=None, style=True, invariants=True):
    """Run every applicable checker over one source blob; the unit of
    both the CLI and the selftest fixtures."""
    findings = []
    lines = src.splitlines()
    try:
        tree = ast.parse(src, filename=rel)
    except SyntaxError as e:
        return [Finding(rel, e.lineno or 0, "syntax",
                        "syntax error: %s" % e.msg)]
    pragmas = _Pragmas(lines)
    if style:
        check_style(tree, lines, rel, pragmas, findings)
    if invariants:
        if vocab is None:
            vocab = load_vocabulary()
        knobs, nodes = vocab
        check_knobs(tree, rel, pragmas, knobs, nodes, findings)
        check_telemetry(tree, rel, pragmas, findings)
        check_lock_guard(tree, rel, pragmas, findings)
        check_jax(tree, rel, pragmas, findings)
        check_gate_order(tree, rel, pragmas, findings)
        check_thread_name(tree, rel, pragmas, findings)
    return findings


def iter_py(root):
    """(path, rel, style?, invariants?) over the repo scan scope."""
    seen = set()
    for base, style, inv in (
            ("znicz_tpu", True, True),
            ("tests", True, False),
            ("tools", True, True)):
        top = os.path.join(root, base)
        for dirpath, _, filenames in os.walk(top):
            if any(p in dirpath for p in SKIP_PARTS):
                continue
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fn)
                rel = os.path.relpath(path, root)
                if rel in seen:
                    continue
                seen.add(rel)
                yield path, rel, style, inv
    for fn in INVARIANT_FILES:
        path = os.path.join(root, fn)
        if os.path.exists(path):
            yield path, fn, False, True


def run(root, vocab=None):
    """Scan the whole tree; returns the finding list."""
    if vocab is None:
        vocab = load_vocabulary()
    findings = []
    for path, rel, style, inv in iter_py(root):
        with open(path, encoding="utf-8") as f:
            src = f.read()
        findings.extend(check_source(src, rel, vocab=vocab,
                                     style=style, invariants=inv))
    findings.sort(key=lambda f: (f.path, f.line, f.check))
    return findings


# ---------------------------------------------------------------------------
# Baseline
# ---------------------------------------------------------------------------

def load_baseline(path):
    """Fingerprints from the reviewed baseline file (``path :: check
    :: token`` lines; '#' comments and blanks ignored)."""
    entries = set()
    if not path or not os.path.exists(path):
        return entries
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if line and not line.startswith("#"):
                entries.add(line)
    return entries


def apply_baseline(findings, baseline):
    """(kept, suppressed, stale-entries)."""
    kept, suppressed = [], []
    hit = set()
    for f in findings:
        if f.fingerprint in baseline:
            suppressed.append(f)
            hit.add(f.fingerprint)
        else:
            kept.append(f)
    return kept, suppressed, sorted(baseline - hit)


# ---------------------------------------------------------------------------
# Selftest — a seeded violation + clean twin per checker (bench_gate
# style: the CI run proves every checker can still reject before
# trusting a clean scan)
# ---------------------------------------------------------------------------

#: check id -> {rel, bad, clean}.  The violating line carries the word
#: "seeded"; the clean twin must produce ZERO findings of any kind.
FIXTURES = {
    "knob-vocabulary": {
        "rel": "znicz_tpu/fixture_knob.py",
        "bad": '''\
from znicz_tpu.core.config import root

limit = root.common.serving.breaker_treshold  # seeded typo
''',
        "clean": '''\
from znicz_tpu.core.config import root

limit = root.common.serving.get("breaker_threshold", 5)
''',
    },
    "telemetry-series": {
        "rel": "znicz_tpu/fixture_series.py",
        "bad": '''\
from znicz_tpu.core import telemetry

telemetry.counter("oops.requests").inc()  # seeded bad family
''',
        "clean": '''\
from znicz_tpu.core import telemetry

telemetry.counter("serving.predictions").inc()
''',
    },
    "telemetry-collision": {
        "rel": "znicz_tpu/fixture_collision.py",
        "bad": '''\
from znicz_tpu.core import telemetry


def note(which):
    telemetry.gauge(telemetry.labeled(
        "serving.breaker_open", name=which)).set(1)  # seeded
''',
        "clean": '''\
from znicz_tpu.core import telemetry


def note(which):
    telemetry.gauge(telemetry.labeled(
        "serving.breaker_open", breaker=which)).set(1)
''',
    },
    "telemetry-cardinality": {
        "rel": "znicz_tpu/fixture_cardinality.py",
        "bad": '''\
from znicz_tpu.core import telemetry


def note(request_id):
    telemetry.counter(telemetry.labeled(
        "serving.rejected", model=request_id)).inc()  # seeded
''',
        "clean": '''\
from znicz_tpu.core import telemetry


def note(model):
    telemetry.counter(telemetry.labeled(
        "serving.rejected", model=model)).inc()
''',
    },
    "lock-guard": {
        "rel": "znicz_tpu/fixture_lock.py",
        "bad": '''\
import threading


class Box(object):
    def __init__(self):
        self._lock = threading.Lock()
        self.items = []

    def put(self, x):
        with self._lock:
            self.items.append(x)

    def drop(self):
        self.items = []  # seeded unguarded write
''',
        "clean": '''\
import threading


class Box(object):
    def __init__(self):
        self._lock = threading.Lock()
        self.items = []

    def put(self, x):
        with self._lock:
            self.items.append(x)

    def drop(self):
        with self._lock:
            self.items = []
''',
    },
    "jax-host-sync": {
        "rel": "znicz_tpu/fixture_sync.py",
        "bad": '''\
import jax


def step(x):
    return float(x) + 1.0  # seeded host sync


fn = jax.jit(step)
''',
        "clean": '''\
import jax


def step(x):
    return x + 1.0


fn = jax.jit(step)
''',
    },
    "jax-rng": {
        "rel": "znicz_tpu/fixture_rng.py",
        "bad": '''\
import jax
import numpy


def body(carry, x):
    noise = numpy.random.random()  # seeded trace-time draw
    return carry + noise, x


out = jax.lax.scan(body, 0.0, None)
''',
        "clean": '''\
import jax


def body(carry, x):
    return carry + x, x


out = jax.lax.scan(body, 0.0, None)
''',
    },
    "jax-time": {
        "rel": "znicz_tpu/fixture_time.py",
        "bad": '''\
import time

import jax


def step(x):
    return x + time.time()  # seeded trace-time clock


fn = jax.jit(step)
''',
        "clean": '''\
import time

import jax


def step(x):
    return x + 1.0


fn = jax.jit(step)
t0 = time.time()
''',
    },
    "jax-donation": {
        "rel": "znicz_tpu/fixture_donate.py",
        "bad": '''\
import jax


def step(acc, x):
    return acc + x


fn = jax.jit(step)  # seeded copy per dispatch
''',
        "clean": '''\
import jax


def step(acc, x):
    return acc + x


fn = jax.jit(step, donate_argnums=(0,))
''',
    },
    "gate-order": {
        "rel": "znicz_tpu/core/health.py",
        "bad": '''\
from znicz_tpu.core.config import root


def enabled():
    return bool(root.common.health.get("enabled", False))


def observe_loss(value):
    interval = root.common.health.get("interval", 1)  # seeded
    if not enabled():
        return None
    return interval + value
''',
        "clean": '''\
from znicz_tpu.core.config import root


def enabled():
    return bool(root.common.health.get("enabled", False))


def observe_loss(value):
    if not enabled():
        return None
    return root.common.health.get("interval", 1) + value


def check_training_step(steps=1):
    if not enabled():
        return None
    return steps


def check_gd_unit(unit):
    if not enabled():
        return None
    return unit
''',
    },
    "thread-name": {
        "rel": "znicz_tpu/fixture_thread.py",
        "bad": '''\
import threading


def start(worker):
    t = threading.Thread(target=worker, daemon=True)  # seeded
    t.start()
    return t
''',
        "clean": '''\
import threading


def start(worker):
    t = threading.Thread(target=worker, name="znicz:worker",
                         daemon=True)
    t.start()
    return t
''',
    },
    "syntax": {
        "rel": "znicz_tpu/fixture_syntax.py",
        "bad": "def broken(:\n",
        "clean": "X = 1\n",
    },
    "tabs": {
        "rel": "znicz_tpu/fixture_tabs.py",
        "bad": "def f():\n\treturn 1  # seeded tab indent\n",
        "clean": "def f():\n    return 1\n",
    },
    "trailing-whitespace": {
        "rel": "znicz_tpu/fixture_ws.py",
        "bad": "X = 1  # seeded trailing blanks   \n",
        "clean": "X = 1\n",
    },
    "line-length": {
        "rel": "znicz_tpu/fixture_len.py",
        "bad": ("X = 1  # seeded: " + "x" * 70 + "\n"),
        "clean": "X = 1\n",
    },
    "unused-import": {
        "rel": "znicz_tpu/fixture_imports.py",
        "bad": '''\
import os  # seeded: never referenced anywhere
import math

S = f"pi is {math.pi}"
''',
        # the legacy checker's blind spot: names used only inside a
        # docstring doctest (plain string constants) were flagged
        "clean": '''\
"""Helpers.

>>> import znicz_tpu.fixture_imports
>>> math.floor(1.5)
1
"""
import math

S = f"pi is {math.pi}"
''',
    },
    "bare-except": {
        "rel": "znicz_tpu/fixture_except.py",
        "bad": '''\
try:
    X = 1
except:  # seeded
    X = 2
''',
        "clean": '''\
try:
    X = 1
except ValueError:
    X = 2
''',
    },
    "library-print": {
        "rel": "znicz_tpu/fixture_print.py",
        "bad": '''\
def report(x):
    print(x)  # seeded stdout in library code
''',
        "clean": '''\
import logging


def report(x):
    logging.getLogger("fixture").info("%s", x)
''',
    },
}


def selftest(vocab=None):
    """Prove every checker still rejects its seeded violation (with
    the right check id and line) and passes the clean twin.  Returns a
    list of problem strings — empty means the selftest passed."""
    if vocab is None:
        vocab = load_vocabulary()
    problems = []
    for check, fx in sorted(FIXTURES.items()):
        bad = check_source(fx["bad"], fx["rel"], vocab=vocab)
        hits = [f for f in bad if f.check == check]
        if not hits:
            problems.append(
                "%s: seeded violation NOT rejected (findings: %s)"
                % (check, [str(f) for f in bad]))
        elif check != "syntax":
            expected = next(
                (i for i, line in
                 enumerate(fx["bad"].splitlines(), 1)
                 if "seeded" in line), None)
            if expected is not None and \
                    not any(f.line == expected for f in hits):
                problems.append(
                    "%s: rejected at line(s) %s, expected %d"
                    % (check, sorted(f.line for f in hits), expected))
        clean = check_source(fx["clean"], fx["rel"], vocab=vocab)
        if clean:
            problems.append(
                "%s: clean twin produced findings: %s"
                % (check, [str(f) for f in clean]))
    return problems
