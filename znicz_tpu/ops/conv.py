"""Convolution ops.

TPU-era equivalent of the reference conv kernel stack (conv.py:185-313:
im2col ``Unpack1D`` + GEMM + bias/activation kernel; gd_conv.py:313-452:
col2im scatter + GEMM).  On TPU the forward lowers to
``lax.conv_general_dilated`` — XLA picks the im2col-equivalent internally
and tiles it onto the MXU (SURVEY.md §7: do not port Unpack1D) — and the
backward comes from ``jax.vjp`` of that same forward, which reproduces the
reference's hand-written col2im/GEMM math exactly.

Geometry (reference conv.py:57-140):
* layout NHWC — ``input`` (batch, sy, sx, n_channels);
* ``weights`` (n_kernels, ky*kx*n_channels), flattened from (ky, kx, C);
* ``padding`` (left, top, right, bottom) — zero padding;
* ``sliding`` (x, y) strides;
* output (batch, ny, nx, n_kernels) with
  ``nx = (left + sx + right - kx) // sliding[0] + 1`` (same for y).
"""

from functools import partial

import numpy
import jax
import jax.numpy as jnp
from jax import lax

from znicz_tpu.ops import activations


def output_spatial(sy, sx, ky, kx, padding, sliding):
    left, top, right, bottom = padding
    nx = (left + sx + right - kx) // sliding[0] + 1
    ny = (top + sy + bottom - ky) // sliding[1] + 1
    return ny, nx


def _conv_linear_jax(x, w, padding, sliding):
    """x NHWC, w (K, ky*kx*C) -> (B, ny, nx, K), no bias/activation."""
    k, ky, kx, c = w.shape
    left, top, right, bottom = padding
    dn = lax.conv_dimension_numbers(x.shape, (ky, kx, c, k),
                                    ("NHWC", "HWIO", "NHWC"))
    return lax.conv_general_dilated(
        x, jnp.transpose(w, (1, 2, 3, 0)),
        window_strides=(sliding[1], sliding[0]),
        padding=((top, bottom), (left, right)),
        dimension_numbers=dn)


def _w4(weights, ky, kx, n_channels):
    return weights.reshape(weights.shape[0], ky, kx, n_channels)


@partial(jax.jit, static_argnames=("ky", "kx", "padding", "sliding",
                                   "activation", "include_bias"))
def forward_jax(x, weights, bias, ky, kx, padding, sliding,
                activation="linear", include_bias=True):
    w4 = _w4(weights, ky, kx, x.shape[3])
    y = _conv_linear_jax(x, w4, padding, sliding)
    if include_bias:
        y = y + bias
    return activations.apply_jax(activation, y)


@partial(jax.jit, static_argnames=("ky", "kx", "padding", "sliding",
                                   "need_err_input", "include_bias"))
def backward_jax(inp, err_output, weights, ky, kx, padding, sliding,
                 need_err_input=True, include_bias=True):
    """Returns (err_input, gradient_weights, gradient_bias).

    The VJP of the linear conv reproduces the reference col2im scatter
    (gd_conv.py:313-378) and im2col weights-gradient GEMM (379-452).
    """
    w4 = _w4(weights, ky, kx, inp.shape[3])
    _, vjp = jax.vjp(
        lambda x, w: _conv_linear_jax(x, w, padding, sliding), inp, w4)
    gx, gw4 = vjp(err_output)
    grad_w = gw4.reshape(weights.shape)
    grad_b = err_output.sum(axis=(0, 1, 2)) if include_bias else None
    return (gx if need_err_input else None), grad_w, grad_b


# -- deconv (transposed conv) -----------------------------------------------

@partial(jax.jit, static_argnames=("ky", "kx", "padding", "sliding",
                                   "out_shape"))
def deconv_forward_jax(x, weights, ky, kx, padding, sliding, out_shape):
    """Transposed conv: the col2im scatter of ``x @ W`` (reference
    deconv.py — the forward is the conv's err_input computation).

    Matches the numpy twin's scatter-crop semantics for ANY geometry:
    window (i, j) lands at canvas position (i*stride, j*stride) of a
    (top + H + bottom, left + W + right) canvas, then the padding margins
    are cropped away.  The reference AE stages produce geometries where
    the conv of out_shape with this padding does NOT reproduce (ny, nx)
    (e.g. MnistAE's 24->28 with padding 4), so a plain conv-VJP over
    out_shape would shape-error; the scatter formulation is the spec
    (deconv.py col2im + crop)."""
    b, ny, nx, _ = x.shape
    left, top, right, bottom = padding
    c = out_shape[3]
    # exact-geometry canvas for the windows, via the conv VJP (lowers to
    # the XLA transposed-conv path — no explicit gathers)
    sy_eff = (ny - 1) * sliding[1] + ky
    sx_eff = (nx - 1) * sliding[0] + kx
    w4 = _w4(weights, ky, kx, c)
    zeros = jnp.zeros((b, sy_eff, sx_eff, c), dtype=x.dtype)
    _, vjp = jax.vjp(
        lambda z: _conv_linear_jax(z, w4, (0, 0, 0, 0), sliding), zeros)
    canvas = vjp(x)[0]
    H, W = out_shape[1], out_shape[2]
    pad_y = max(0, top + H - sy_eff)
    pad_x = max(0, left + W - sx_eff)
    if pad_y or pad_x:
        canvas = jnp.pad(canvas, ((0, 0), (0, pad_y), (0, pad_x), (0, 0)))
    return canvas[:, top:top + H, left:left + W, :]


@partial(jax.jit, static_argnames=("batch_ny_nx", "ky", "kx", "padding",
                                   "sliding", "out_shape"))
def deconv_hits_jax(batch_ny_nx, ky, kx, padding, sliding, out_shape):
    """Overlap counts per output cell (reference Deconv ``hits`` array for
    unsafe padding)."""
    b, ny, nx = batch_ny_nx
    w1 = jnp.ones((1, ky, kx, 1))
    x1 = jnp.ones((b, ny, nx, 1))
    return deconv_forward_jax(
        x1, w1.reshape(1, -1), ky, kx, padding, sliding,
        (b, out_shape[1], out_shape[2], 1))[:, :, :, 0]


def deconv_forward_numpy(x, weights, ky, kx, padding, sliding, out_shape):
    b, ny, nx, k = x.shape
    c = out_shape[3]
    left, top = padding[0], padding[1]
    gxp = numpy.zeros((b, top + out_shape[1] + padding[3],
                       left + out_shape[2] + padding[2], c), dtype=x.dtype)
    contrib = x @ weights  # (B, ny, nx, ky*kx*C)
    for i in range(ny):
        y1 = i * sliding[1]
        for j in range(nx):
            x1 = j * sliding[0]
            gxp[:, y1:y1 + ky, x1:x1 + kx, :] += \
                contrib[:, i, j, :].reshape(b, ky, kx, c)
    return gxp[:, top:top + out_shape[1], left:left + out_shape[2], :]


@partial(jax.jit, static_argnames=("ky", "kx", "padding", "sliding"))
def deconv_backward_jax(inp, err_output, weights, ky, kx, padding, sliding):
    """VJP of the transposed conv: returns (err_input, gradient_weights).

    ``inp`` is the deconv's input (B, ny, nx, K); ``err_output`` lives in
    the deconv's output space (B, sy, sx, C).
    """
    out_shape = tuple(err_output.shape)
    _, vjp = jax.vjp(
        lambda x, w: deconv_forward_jax(x, w, ky, kx, padding, sliding,
                                        out_shape),
        inp, weights)
    return vjp(err_output)


def deconv_backward_numpy(inp, err_output, weights, ky, kx, padding,
                          sliding):
    # err_input = conv(err_output, W); grad_w: roles of input/err swap
    err_in = forward_numpy(err_output, weights, None, ky, kx, padding,
                           sliding, include_bias=False)
    _, grad_w, _ = backward_numpy(err_output, inp, weights, ky, kx, padding,
                                  sliding, need_err_input=False,
                                  include_bias=False)
    return err_in, grad_w


# -- numpy twins (the executable spec) --------------------------------------

def _pad_numpy(x, padding):
    left, top, right, bottom = padding
    return numpy.pad(x, ((0, 0), (top, bottom), (left, right), (0, 0)))


def _patches_numpy(xp, ky, kx, sliding, ny, nx):
    """im2col: (B, ny, nx, ky*kx*C) from the padded input."""
    b, _, _, c = xp.shape
    out = numpy.empty((b, ny, nx, ky * kx * c), dtype=xp.dtype)
    for i in range(ny):
        y1 = i * sliding[1]
        for j in range(nx):
            x1 = j * sliding[0]
            out[:, i, j, :] = xp[:, y1:y1 + ky, x1:x1 + kx, :].reshape(b, -1)
    return out


def forward_numpy(x, weights, bias, ky, kx, padding, sliding,
                  activation="linear", include_bias=True):
    ny, nx = output_spatial(x.shape[1], x.shape[2], ky, kx, padding, sliding)
    xp = _pad_numpy(x, padding)
    patches = _patches_numpy(xp, ky, kx, sliding, ny, nx)
    y = patches @ weights.T
    if include_bias:
        y = y + bias
    return activations.apply_numpy(activation, y)


def backward_numpy(inp, err_output, weights, ky, kx, padding, sliding,
                   need_err_input=True, include_bias=True):
    b, sy, sx, c = inp.shape
    ny, nx = err_output.shape[1], err_output.shape[2]
    left, top = padding[0], padding[1]
    xp = _pad_numpy(inp, padding)
    patches = _patches_numpy(xp, ky, kx, sliding, ny, nx)
    e2 = err_output.reshape(-1, err_output.shape[3])
    grad_w = e2.T @ patches.reshape(-1, patches.shape[3])
    grad_b = err_output.sum(axis=(0, 1, 2)) if include_bias else None
    err_input = None
    if need_err_input:
        gxp = numpy.zeros_like(xp)
        contrib = err_output @ weights  # (B, ny, nx, ky*kx*C)
        for i in range(ny):
            y1 = i * sliding[1]
            for j in range(nx):
                x1 = j * sliding[0]
                gxp[:, y1:y1 + ky, x1:x1 + kx, :] += \
                    contrib[:, i, j, :].reshape(b, ky, kx, c)
        err_input = gxp[:, top:top + sy, left:left + sx, :]
    return err_input, grad_w, grad_b
