"""Dropout mask generation and application.

Reference dropout.py:84-190: ``mask = ceil(max(U(-ratio, 1-ratio), 0)) /
(1-ratio)`` — i.e. Bernoulli(keep=1-ratio) scaled by 1/(1-ratio) —
regenerated each TRAIN minibatch; forward multiplies, backward multiplies
``err`` by the same mask; testing/validation passes through unchanged.
"""

import numpy
import jax


def mask_from_uniform(u, dropout_ratio, dtype):
    """Build the mask from U(0,1) draws with the reference's formula
    (dropout.py:147-153): exact same keep/drop decision boundary."""
    xp = jax.numpy if not isinstance(u, numpy.ndarray) else numpy
    leave_ratio = 1.0 - dropout_ratio
    # U(-ratio, 1-ratio) = u * 1 - ratio; ceil(max(., 0)) -> {0, 1}
    shifted = u - dropout_ratio
    keep = (shifted > 0).astype(dtype)
    return keep / xp.asarray(leave_ratio, dtype=dtype)


def apply_jax(x, mask):
    return x * mask


def apply_numpy(x, mask):
    return x * mask
