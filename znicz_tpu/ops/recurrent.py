"""Recurrent sequence drivers — the LSTM unroll as ONE compiled scan.

The unit graph runs the LSTM cell sub-workflow (units/lstm.py, reference
lstm.py:52-144) once per timestep — a separate graph pass each step.
TPU-first sequence training unrolls inside the compiled computation:
``lstm_scan_jax`` carries (h, c) through ``lax.scan``, so T timesteps
are one XLA program with one compile, and the whole unroll is
differentiable end to end (``jax.grad`` through the scan replaces the
per-step GDLSTM chain).

Math parity with the cell sub-workflow (verified to 1e-12 by
tests/unit/test_lstm_scan.py):

* joined input z = [x, h_prev] (InputJoiner order, lstm.py:71);
* gates use the framework's activations — the reference's SCALED tanh
  (1.7159 tanh(2x/3), all2all.py:271) for the memory maker and the
  output squash, plain sigmoid for the three gates;
* c = i * g + f * c_prev;  y = o * tanh_act(c)  (simple=True topology —
  the output gate reads z, not the memory cell).
"""

from functools import partial

import numpy

import jax
import jax.numpy as jnp

from znicz_tpu.ops import activations

#: gate order in the packed parameter dict
GATES = ("input_gate", "forget_gate", "memory_maker", "output_gate")


def lstm_cell_jax(params, x, h, c):
    """One cell step.  ``params``: {gate: {"w": (hidden, in+hidden),
    "b": (hidden,)}} in the All2All layout (y = z @ W.T + b)."""
    z = jnp.concatenate([x, h], axis=1)

    def gate(name, act):
        p = params[name]
        return activations.apply_jax(act, z @ p["w"].T + p["b"])

    i = gate("input_gate", "sigmoid")
    f = gate("forget_gate", "sigmoid")
    g = gate("memory_maker", "tanh")
    o = gate("output_gate", "sigmoid")
    c_new = i * g + f * c
    y = o * activations.apply_jax("tanh", c_new)
    return y, c_new


@partial(jax.jit, static_argnames=())
def lstm_scan_jax(params, xs, h0, c0):
    """Unroll the cell over ``xs`` (T, B, in) in one compiled scan.

    Returns (ys, h_T, c_T) with ys stacked (T, B, hidden).
    """
    def body(carry, x):
        h, c = carry
        y, c = lstm_cell_jax(params, x, h, c)
        return (y, c), y

    (h, c), ys = jax.lax.scan(body, (h0, c0), xs)
    return ys, h, c


def params_from_cell(cell):
    """Extract the packed parameter pytree from a built
    :class:`znicz_tpu.units.lstm.LSTM` cell (host numpy)."""
    out = {}
    for name in GATES:
        unit = getattr(cell, name)
        out[name] = {"w": numpy.array(unit.weights.mem),
                     "b": numpy.array(unit.bias.mem)}
    return out
