"""Local response normalization (AlexNet/Caffe cross-channel LRN).

Reference normalization.py:49-287: with ``s_i = k + alpha *
sum_{j in window(i)} x_j^2`` over the channel window ``[i-n//2, i+n//2]``,

* forward:  ``y_i = x_i / s_i^beta``  (normalization.py:143-154)
* backward: ``dL/dx_i = sum_{j in window(i)} (delta_ij * s_j
  - 2 beta alpha x_i x_j) * err_j / s_j^(beta+1)``
  (normalization.py:223-262)

Defaults alpha=1e-4, beta=0.75, k=2, n=5.
"""

from functools import partial

import numpy
import jax
import jax.numpy as jnp


def _subsums_jax(x2, n):
    """Windowed channel sums (reference _subsums, normalization.py:64-78)."""
    c = x2.shape[3]
    half = n // 2
    padded = jnp.pad(x2, ((0, 0), (0, 0), (0, 0), (half, half)))
    csum = jnp.cumsum(padded, axis=3)
    csum = jnp.pad(csum, ((0, 0), (0, 0), (0, 0), (1, 0)))
    upper = jnp.arange(c) + 2 * half + 1
    lower = jnp.arange(c)
    return csum[:, :, :, upper] - csum[:, :, :, lower]


@partial(jax.jit, static_argnames=("alpha", "beta", "k", "n"))
def lrn_forward_jax(x, alpha=1e-4, beta=0.75, k=2, n=5):
    s = k + alpha * _subsums_jax(jnp.square(x), n)
    return x / jnp.power(s, beta)


@partial(jax.jit, static_argnames=("alpha", "beta", "k", "n"))
def lrn_backward_jax(x, err_output, alpha=1e-4, beta=0.75, k=2, n=5):
    s = k + alpha * _subsums_jax(jnp.square(x), n)
    sp = jnp.power(s, beta + 1)
    t = err_output / sp  # (B, H, W, C)
    # err_i = s_i * t_i - 2 beta alpha x_i * window_sum_j(x_j t_j)
    xt = _subsums_jax(x * t, n)
    return s * t - 2.0 * beta * alpha * x * xt


def _subsums_numpy(src, n):
    c = src.shape[3]
    out = numpy.empty_like(src)
    half = n // 2
    for i in range(c):
        lo = max(0, i - half)
        hi = min(i + half, c - 1)
        out[:, :, :, i] = src[:, :, :, lo:hi + 1].sum(axis=3)
    return out


def lrn_forward_numpy(x, alpha=1e-4, beta=0.75, k=2, n=5):
    s = k + alpha * _subsums_numpy(numpy.square(x), n)
    return x / numpy.power(s, beta)


def lrn_backward_numpy(x, err_output, alpha=1e-4, beta=0.75, k=2, n=5):
    """Direct port of the reference double loop (normalization.py:223-262),
    vectorized over the window offset."""
    s = k + alpha * _subsums_numpy(numpy.square(x), n)
    sp = numpy.power(s, beta + 1)
    t = err_output / sp
    xt = _subsums_numpy(x * t, n)
    return s * t - 2.0 * beta * alpha * x * xt
