"""Local response normalization (AlexNet/Caffe cross-channel LRN).

Reference normalization.py:49-287: with ``s_i = k + alpha *
sum_{j in window(i)} x_j^2`` over the channel window ``[i-n//2, i+n//2]``,

* forward:  ``y_i = x_i / s_i^beta``  (normalization.py:143-154)
* backward: ``dL/dx_i = sum_{j in window(i)} (delta_ij * s_j
  - 2 beta alpha x_i x_j) * err_j / s_j^(beta+1)``
  (normalization.py:223-262)

Defaults alpha=1e-4, beta=0.75, k=2, n=5.
"""

from functools import partial

import numpy
import jax
import jax.numpy as jnp


def _band_matrix(c, n):
    """(c, c) 0/1 band: M[i, j] = 1 iff j is inside i's channel window.
    Trace-time constant (channel counts are static)."""
    idx = numpy.arange(c)
    return (numpy.abs(idx[:, None] - idx[None, :]) <= n // 2)


def _subsums_jax(x2, n):
    """Windowed channel sums (reference _subsums, normalization.py:64-78)
    as ONE band-matrix matmul on the channel (lane) axis.

    The r4 north-star profile measured 34% of cifar-caffe device time
    in copy-transpose: the previous cumsum/fancy-index formulation
    produced odd-width channel tensors (C+2·half, C+2·half+1) and a
    lane-axis gather, forcing Mosaic relayouts between every stage.
    ``x2 @ M`` (M symmetric banded, a trace-time constant) keeps the
    NHWC layout bit-for-bit — lanes contract to lanes on the MXU, no
    pads, no gathers — and its autodiff VJP is the same matmul with
    M^T = M.  In bf16 the MXU accumulates in f32, strictly better
    than the bf16 cumsum it replaces."""
    c = x2.shape[3]
    m = jnp.asarray(_band_matrix(c, n), x2.dtype)
    return x2 @ m


@partial(jax.jit, static_argnames=("alpha", "beta", "k", "n"))
def lrn_forward_jax(x, alpha=1e-4, beta=0.75, k=2, n=5):
    s = k + alpha * _subsums_jax(jnp.square(x), n)
    return x / jnp.power(s, beta)


@partial(jax.jit, static_argnames=("alpha", "beta", "k", "n"))
def lrn_backward_jax(x, err_output, alpha=1e-4, beta=0.75, k=2, n=5):
    s = k + alpha * _subsums_jax(jnp.square(x), n)
    sp = jnp.power(s, beta + 1)
    t = err_output / sp  # (B, H, W, C)
    # err_i = s_i * t_i - 2 beta alpha x_i * window_sum_j(x_j t_j)
    xt = _subsums_jax(x * t, n)
    return s * t - 2.0 * beta * alpha * x * xt


def _subsums_numpy(src, n):
    c = src.shape[3]
    out = numpy.empty_like(src)
    half = n // 2
    for i in range(c):
        lo = max(0, i - half)
        hi = min(i + half, c - 1)
        out[:, :, :, i] = src[:, :, :, lo:hi + 1].sum(axis=3)
    return out


def lrn_forward_numpy(x, alpha=1e-4, beta=0.75, k=2, n=5):
    s = k + alpha * _subsums_numpy(numpy.square(x), n)
    return x / numpy.power(s, beta)


def lrn_backward_numpy(x, err_output, alpha=1e-4, beta=0.75, k=2, n=5):
    """Direct port of the reference double loop (normalization.py:223-262),
    vectorized over the window offset."""
    s = k + alpha * _subsums_numpy(numpy.square(x), n)
    sp = numpy.power(s, beta + 1)
    t = err_output / sp
    xt = _subsums_numpy(x * t, n)
    return s * t - 2.0 * beta * alpha * x * xt
