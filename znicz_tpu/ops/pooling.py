"""Pooling ops — max / maxabs / avg / stochastic, forward + backward.

Reference semantics (pooling.py:67-548, gd_pooling.py:58-287):
* layout NHWC; ``sliding`` (x, y); ceil-mode output size
  ``out = ceil((s - k) / stride) + 1`` — windows may overhang the
  right/bottom edge and are then truncated (pooling.py:96-105);
* max/maxabs record ``input_offset``: the FLAT index into the input array
  of the winning element (pooling.py:303-312); backward scatters
  ``err_output`` additively to those offsets (gd_pooling.py:233-247);
* avg divides by the TRUNCATED window size (pooling.py:548) and backward
  spreads err/(window size) over the truncated window (gd_pooling.py:272);
* stochastic pooling samples an element with probability proportional to
  its (abs) value using a uint16 random stream (pooling.py:368-480);
  samples uniformly when the window sums to zero.

The jax paths build strided window views via advanced indexing (the
patches are fused away by XLA) and use masked argmax/segment-sum —
one jitted computation per op, no host round-trips.
"""

from functools import partial

import numpy
import jax
import jax.numpy as jnp
from jax import lax


def output_spatial(sy, sx, ky, kx, sliding):
    """Ceil-mode output geometry (reference pooling.py:96-105)."""
    outs = []
    for last, stride in ((sx - kx, sliding[0]), (sy - ky, sliding[1])):
        o = last // stride + 1
        if last % stride != 0:
            o += 1
        outs.append(o)
    return outs[1], outs[0]  # ny, nx


def _ceil_mode_pads(sy, sx, ky, kx, sliding):
    """Right/bottom padding that makes every ceil-mode window in range."""
    ny, nx = output_spatial(sy, sx, ky, kx, sliding)
    pad_y = (ny - 1) * sliding[1] + ky - sy
    pad_x = (nx - 1) * sliding[0] + kx - sx
    return ny, nx, ((0, 0), (0, pad_y), (0, pad_x), (0, 0))


def _window_view_jax(x, ky, kx, sliding, fill):
    """(B, ny, nx, ky*kx, C) window view + validity mask (ky*kx,) grids.

    Overhanging cells are filled with ``fill`` and masked invalid.
    """
    b, sy, sx, c = x.shape
    ny, nx, pads = _ceil_mode_pads(sy, sx, ky, kx, sliding)
    xp = jnp.pad(x, pads, constant_values=fill)
    rows = (jnp.arange(ny) * sliding[1])[:, None] + jnp.arange(ky)[None, :]
    cols = (jnp.arange(nx) * sliding[0])[:, None] + jnp.arange(kx)[None, :]
    # (B, ny, ky, nx, kx, C) -> (B, ny, nx, ky, kx, C)
    win = xp[:, rows[:, None, :, None], cols[None, :, None, :], :]
    valid = ((rows < sy)[:, None, :, None] &
             (cols < sx)[None, :, None, :])  # (ny, nx, ky, kx)
    return (win.reshape(b, ny, nx, ky * kx, c),
            valid.reshape(ny, nx, ky * kx), ny, nx)


def _flat_offsets_jax(shape, ny, nx, ky, kx, sliding, q):
    """Flat input index for window cell q (B, ny, nx, C) of each output."""
    b, sy, sx, c = shape
    dy, dx = q // kx, q % kx  # (B, ny, nx, C)
    y = jnp.arange(ny).reshape(1, ny, 1, 1) * sliding[1] + dy
    x = jnp.arange(nx).reshape(1, 1, nx, 1) * sliding[0] + dx
    bi = jnp.arange(b).reshape(b, 1, 1, 1)
    ci = jnp.arange(c).reshape(1, 1, 1, c)
    return ((bi * sy + y) * sx + x) * c + ci


def max_pooling_jax(x, ky, kx, sliding, use_abs=False):
    """Returns (output, input_offset) — offsets are flat input indices.

    Float inputs run the fused Pallas kernel
    (ops/pallas_pooling.py — one VMEM pass, 30-50x the gather
    formulation on TPU); other dtypes and oversized feature maps use
    the window-view gather path.  Both reproduce the numpy twin
    bit-exactly, offsets included.

    NOT differentiable through the Pallas path — this is the
    unit-graph op whose backward is the offset scatter
    (max_pooling_backward_jax); autodiff users take pooling_fwd_jax
    or max_pooling_gather_jax."""
    from znicz_tpu.ops import pallas_pooling
    if pallas_pooling.supported(x, ky, kx, sliding, use_abs):
        return pallas_pooling.max_pooling_offsets_pallas(
            x, ky, kx, tuple(sliding), use_abs=use_abs)
    return max_pooling_gather_jax(x, ky, kx, tuple(sliding), use_abs)


@partial(jax.jit, static_argnames=("ky", "kx", "sliding", "use_abs"))
def max_pooling_gather_jax(x, ky, kx, sliding, use_abs=False):
    win, valid, ny, nx = _window_view_jax(x, ky, kx, sliding, 0.0)
    key = jnp.abs(win) if use_abs else win
    key = jnp.where(valid[None, :, :, :, None], key, -jnp.inf)
    q = jnp.argmax(key, axis=3)  # (B, ny, nx, C) in (dy, dx) C-order
    val = jnp.take_along_axis(win, q[:, :, :, None, :], axis=3)[:, :, :, 0, :]
    offs = _flat_offsets_jax(x.shape, ny, nx, ky, kx, sliding, q)
    return val, offs.astype(jnp.int32)


def _winner_qyx(offs, x_shape, ny, nx, sliding):
    """Decode winner flat offsets back into within-window (qy, qx)."""
    b, h, w, c = x_shape
    wy = (offs // (w * c)) % h
    wx = (offs // c) % w
    qy = wy - jnp.arange(ny).reshape(1, ny, 1, 1) * sliding[1]
    qx = wx - jnp.arange(nx).reshape(1, 1, nx, 1) * sliding[0]
    return qy, qx


def _maxpool_bwd_dense(err, offs, x_shape, ky, kx, sliding):
    """Max-pool input gradient WITHOUT a scatter: route each window's
    cotangent to its recorded winner by dense shifted accumulation.

    TPU scatters serialize (select-and-scatter was ~16% of the flagship
    window's device time, profiles/r4_summary.md); this formulation is
    ky*kx masked dense adds — and ONE fused expansion when windows do
    not overlap (sliding == kernel), the common case."""
    b, h, w, c = x_shape
    ny, nx = err.shape[1], err.shape[2]
    sy, sx = sliding[1], sliding[0]
    qy, qx = _winner_qyx(offs, x_shape, ny, nx, sliding)
    if (sy, sx) == (ky, kx):
        # disjoint windows: expand (B, ny, nx, C) -> (B, ny, ky, nx, kx,
        # C) with the winner one-hot, collapse to the input grid — one
        # fused elementwise, no accumulation
        oh_y = (qy[:, :, None, :, :] ==
                jnp.arange(ky).reshape(1, 1, ky, 1, 1))
        oh_x = (qx[:, :, :, None, :] ==
                jnp.arange(kx).reshape(1, 1, 1, kx, 1))
        exp = (err[:, :, None, :, None, :] *
               (oh_y[:, :, :, :, None, :] &
                oh_x[:, :, None, :, :, :]).astype(err.dtype))
        full = exp.reshape(b, ny * ky, nx * kx, c)
        return full[:, :h, :w, :]
    hp = max(h, (ny - 1) * sy + ky)
    wp = max(w, (nx - 1) * sx + kx)
    acc = jnp.zeros((b, hp, wp, c), err.dtype)
    for dy in range(ky):
        for dx in range(kx):
            contrib = jnp.where((qy == dy) & (qx == dx), err, 0)
            acc = acc + lax.pad(
                contrib, jnp.asarray(0, err.dtype),
                ((0, 0, 0),
                 (dy, hp - (ny - 1) * sy - 1 - dy, sy - 1),
                 (dx, wp - (nx - 1) * sx - 1 - dx, sx - 1),
                 (0, 0, 0)))
    return acc[:, :h, :w, :]


def _offsets_forward(x, ky, kx, sliding, use_abs, prefer_pallas):
    """(values, offsets) with first-winner ties: the Pallas one-pass
    kernel on a real single-device TPU, the window-view argmax
    elsewhere (identical semantics; interpret-mode Pallas off-TPU and
    GSPMD-partitioned custom calls are both avoided)."""
    from znicz_tpu.ops import pallas_pooling
    if (prefer_pallas and jax.default_backend() == "tpu"
            and pallas_pooling.supported(x, ky, kx, sliding, use_abs)):
        return pallas_pooling.max_pooling_offsets_pallas(
            x, ky, kx, tuple(sliding), use_abs=use_abs)
    return max_pooling_gather_jax(x, ky, kx, tuple(sliding), use_abs)


@partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5))
def max_pooling_train_jax(x, ky, kx, sliding, use_abs=False,
                          prefer_pallas=True):
    """Differentiable max/maxabs pooling returning (values, winner
    offsets) with the unit path's FIRST-winner tie rule.

    Backward: dense shifted accumulation to the recorded winners
    (``_maxpool_bwd_dense``) — neither the gather formulation's
    scatter-add nor reduce_window's select-and-scatter appears in the
    compiled program.  This is the fused path's production pooling
    ("offsets" impl)."""
    return _offsets_forward(x, ky, kx, sliding, use_abs, prefer_pallas)


def _mpt_fwd(x, ky, kx, sliding, use_abs, prefer_pallas):
    y, offs = _offsets_forward(x, ky, kx, sliding, use_abs, prefer_pallas)
    return (y, offs), (offs, x.shape)


def _mpt_bwd(ky, kx, sliding, use_abs, prefer_pallas, res, cts):
    offs, x_shape = res
    err, _ = cts  # the integer offsets output takes no cotangent
    return (_maxpool_bwd_dense(err, offs, x_shape, ky, kx,
                               tuple(sliding)),)


max_pooling_train_jax.defvjp(_mpt_fwd, _mpt_bwd)


# -- non-overlapping "reshape" lowering ---------------------------------
#
# When sliding == kernel (the common MP2/MP3 case) every pooling window
# is a disjoint (ky, kx) block, so the whole op decomposes into ky*kx
# STRIDED SLICES of the input — no window-view gather, no
# lax.reduce_window, and (crucially) no select-and-scatter in the VJP.
# The r4 flagship profile (profiles/r4_summary.md) measured
# select-and-scatter at ~16% and the reduce_window forward fusion at
# ~13% of device time; both are replaced here by elementwise
# compare/select chains that run at HBM stream rate.  First-winner tie
# routing matches the unit path (reference pooling.py:303-312) — unlike
# select-and-scatter, whose tie routing is implementation-defined.


def _trunc_divisor(sy, sx, ky, kx, sliding, ny, nx):
    """Truncated-window element counts (ny, nx) — the reference's avg
    divisor (pooling.py:548); pure geometry, a trace-time constant."""
    t_y = numpy.minimum(ky, sy - numpy.arange(ny) * sliding[1])
    t_x = numpy.minimum(kx, sx - numpy.arange(nx) * sliding[0])
    return (t_y[:, None] * t_x[None, :]).astype(numpy.float32)


def _pad_nonoverlap(x, ky, kx, fill):
    """Pad right/bottom to multiples of the kernel (ceil-mode overhang;
    with sliding == kernel the ceil-mode geometry IS pad-to-multiple)."""
    b, sy, sx, c = x.shape
    py = (-sy) % ky
    px = (-sx) % kx
    if py or px:
        x = jnp.pad(x, ((0, 0), (0, py), (0, px), (0, 0)),
                    constant_values=fill)
    return x


def _nonoverlap_slices(xp, ky, kx):
    """The ky*kx disjoint-window cell planes, in the reference's
    row-major window scan order (dy outer, dx inner) — the order that
    defines FIRST-winner ties."""
    return [xp[:, dy::ky, dx::kx, :] for dy in range(ky) for dx in range(kx)]


def _reshape_max_val(x, ky, kx, use_abs):
    fill = 0.0 if use_abs else -numpy.inf
    xp = _pad_nonoverlap(x, ky, kx, fill)
    slices = _nonoverlap_slices(xp, ky, kx)
    val = slices[0]
    key = jnp.abs(val) if use_abs else val
    for s in slices[1:]:
        k = jnp.abs(s) if use_abs else s
        take = k > key  # strict: earlier slices keep ties (first winner)
        val = jnp.where(take, s, val)
        key = jnp.where(take, k, key)
    return val


@partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def max_pooling_reshape_jax(x, ky, kx, use_abs=False):
    """Non-overlapping max/maxabs pooling as strided slices + a
    compare/select chain; backward = winner mask recomputed from the
    saved (input, output) pair and routed by pure interleave reshapes.
    Residuals alias tensors the surrounding autodiff keeps alive anyway,
    so the op adds no residual memory.  Requires sliding == (kx, ky)."""
    return _reshape_max_val(x, ky, kx, use_abs)


def _mpr_fwd(x, ky, kx, use_abs):
    y = _reshape_max_val(x, ky, kx, use_abs)
    return y, (x, y)


def _mpr_bwd(ky, kx, use_abs, res, err):
    x, y = res
    b, sy, sx, c = x.shape
    fill = 0.0 if use_abs else -numpy.inf
    xp = _pad_nonoverlap(x, ky, kx, fill)
    wkey = jnp.abs(y) if use_abs else y
    ny, nx = y.shape[1], y.shape[2]
    zero = jnp.zeros((), err.dtype)
    seen = jnp.zeros(y.shape, dtype=bool)
    parts = []
    for s in _nonoverlap_slices(xp, ky, kx):
        k = jnp.abs(s) if use_abs else s
        win = (k == wkey) & ~seen
        seen = seen | win
        parts.append(jnp.where(win, err, zero))
    rows = []
    for dy in range(ky):
        row = jnp.stack(parts[dy * kx:(dy + 1) * kx], axis=3)
        rows.append(row.reshape(b, ny, nx * kx, c))
    g = jnp.stack(rows, axis=2).reshape(b, ny * ky, nx * kx, c)
    return (g[:, :sy, :sx, :],)


max_pooling_reshape_jax.defvjp(_mpr_fwd, _mpr_bwd)


@partial(jax.jit, static_argnames=("ky", "kx"))
def avg_pooling_reshape_jax(x, ky, kx):
    """Non-overlapping avg pooling as a strided-slice sum; the autodiff
    VJP is pure pad/interleave (no reduce_window).  The divisor is the
    reference's TRUNCATED window size (geometry constant), so overhang
    semantics match pooling_fwd_jax exactly."""
    b, sy, sx, c = x.shape
    ny, nx = output_spatial(sy, sx, ky, kx, (kx, ky))
    xp = _pad_nonoverlap(x, ky, kx, 0.0)
    s = None
    for sl in _nonoverlap_slices(xp, ky, kx):
        s = sl if s is None else s + sl
    cnt = _trunc_divisor(sy, sx, ky, kx, (kx, ky), ny, nx)
    return s / jnp.asarray(cnt, x.dtype)[None, :, :, None]


@partial(jax.jit, static_argnames=("ky", "kx", "sliding", "mode"))
def pooling_fwd_jax(x, ky, kx, sliding, mode="max"):
    """Offset-free pooling via ``lax.reduce_window`` — the TPU-native
    formulation (no gathers; the max VJP lowers to select-and-scatter).

    Used by the fused path, where the backward comes from ``jax.grad``
    and the reference's flat ``input_offset`` bookkeeping is not needed.
    NOTE maxabs breaks exact-|tie| windows toward the positive value; the
    reference (and ``max_pooling_jax``) take the first occurrence — use
    the offset path where that parity matters.
    Ceil-mode overhang is realized as right/bottom window padding: padded
    cells contribute the reduction identity, which reproduces the
    reference's truncated-window semantics for max and (with the
    geometry-constant divisor below) for avg.
    """
    b, sy, sx, c = x.shape
    dims = (1, ky, kx, 1)
    strides = (1, sliding[1], sliding[0], 1)
    ny, nx, pads = _ceil_mode_pads(sy, sx, ky, kx, sliding)
    # init values must be CONCRETE numpy scalars so jax recognizes the
    # monoid (max/min/add) and uses the differentiable specialized
    # reduce-window primitives; traced inits fall back to the generic,
    # non-differentiable form
    ninf = numpy.asarray(-numpy.inf, x.dtype)
    pinf = numpy.asarray(numpy.inf, x.dtype)
    if mode == "max":
        return lax.reduce_window(x, ninf, lax.max, dims, strides, pads)
    if mode == "maxabs":
        # the max-|x| element is either the window max or the window min;
        # max/min reductions keep the op differentiable (custom reducers
        # have no VJP)
        mx = lax.reduce_window(x, ninf, lax.max, dims, strides, pads)
        mn = lax.reduce_window(x, pinf, lax.min, dims, strides, pads)
        return jnp.where(jnp.abs(mx) >= jnp.abs(mn), mx, mn)
    if mode == "avg":
        s = lax.reduce_window(x, numpy.asarray(0, x.dtype), lax.add,
                              dims, strides, pads)
        cnt = _trunc_divisor(sy, sx, ky, kx, sliding, ny, nx)
        return s / jnp.asarray(cnt, x.dtype)[None, :, :, None]
    raise ValueError(mode)


@partial(jax.jit, static_argnames=("ky", "kx", "sliding"))
def avg_pooling_jax(x, ky, kx, sliding):
    return pooling_fwd_jax(x, ky, kx, sliding, mode="avg")


@partial(jax.jit, static_argnames=("ky", "kx", "sliding", "use_abs"))
def stochastic_pooling_jax(x, rand_u16, ky, kx, sliding, use_abs=False):
    """rand_u16: uint16 stream of size >= output size (row-major order).

    Reference pooling.py:434-480: position = rnd * vsum / 65536 over the
    running prefix of positive (abs) values; uniform window index when the
    window sum is zero.
    """
    b, sy, sx, c = x.shape
    win, valid, ny, nx = _window_view_jax(x, ky, kx, sliding, 0.0)
    key = jnp.abs(win) if use_abs else jnp.maximum(win, 0.0)
    key = key * valid[None, :, :, :, None]
    vsum = key.sum(axis=3)  # (B, ny, nx, C)
    rnd = rand_u16[:b * ny * nx * c].reshape(b, ny, nx, c).astype(x.dtype)
    position = rnd * vsum / 65536.0
    csum = jnp.cumsum(key, axis=3)
    # first q with position <= csum[q] (and a positive contribution)
    hit = position[:, :, :, None, :] <= csum
    q_prop = jnp.argmax(hit, axis=3)
    # zero-sum window: uniform index into the TRUNCATED window
    # (reference indexes the truncated cut, pooling.py:437-440)
    ty = jnp.minimum(ky, sy - jnp.arange(ny) * sliding[1]).reshape(
        1, ny, 1, 1)
    tx = jnp.minimum(kx, sx - jnp.arange(nx) * sliding[0]).reshape(
        1, 1, nx, 1)
    rnd32 = rand_u16[:b * ny * nx * c].reshape(b, ny, nx, c).astype(
        jnp.uint32)
    k_trunc = (rnd32 * (ty * tx).astype(jnp.uint32) >> 16).astype(jnp.int32)
    q_unif = (k_trunc // tx) * kx + k_trunc % tx
    q = jnp.where(vsum > 0, q_prop, q_unif)
    val = jnp.take_along_axis(win, q[:, :, :, None, :], axis=3)[:, :, :, 0, :]
    offs = _flat_offsets_jax(x.shape, ny, nx, ky, kx, sliding, q)
    return val, offs.astype(jnp.int32)


@partial(jax.jit, static_argnames=("ky", "kx", "use_abs"))
def stochastic_pool_depool_jax(x, rand_u16, ky, kx, use_abs=False):
    """Stochastic pooling + depooling in place (reference ocl/pooling.cl
    ``stochastic_pooling_depooling``): one winner per non-overlapping
    window, sampled with probability proportional to max(x, 0) (or |x|);
    the output has the INPUT shape — the winner keeps its original signed
    value, every other cell becomes 0.  Zero-sum windows sample uniformly
    over the truncated window via the kernel's pos_add=1 cumsum walk.

    Returns (y, offs): y is input-shaped, offs the winners' flat input
    indices (window-grid shaped, for IDistributable/export parity).
    """
    sliding = (kx, ky)
    b, sy, sx, c = x.shape
    win, valid, ny, nx = _window_view_jax(x, ky, kx, sliding, 0.0)
    vmask = valid[None, :, :, :, None]
    key = jnp.abs(win) if use_abs else jnp.maximum(win, 0.0)
    key = key * vmask
    vsum = key.sum(axis=3)                      # (B, ny, nx, C)
    cnt = valid.sum(axis=2).astype(x.dtype)     # (ny, nx)
    rnd = rand_u16[:b * ny * nx * c].reshape(b, ny, nx, c).astype(x.dtype)
    nonzero = vsum > 0
    total = jnp.where(nonzero, vsum, cnt[None, :, :, None])
    pos = rnd * total / 65536.0
    # zero-sum windows walk a cumsum of ones over the valid cells
    keyz = jnp.where(nonzero[:, :, :, None, :], key,
                     vmask.astype(x.dtype) * jnp.ones_like(win))
    csum = jnp.cumsum(keyz, axis=3)
    hit = pos[:, :, :, None, :] <= csum
    q = jnp.argmax(hit, axis=3)
    offs = _flat_offsets_jax(x.shape, ny, nx, ky, kx, sliding, q)
    vals = jnp.take_along_axis(win, q[:, :, :, None, :], axis=3)[:, :, :, 0, :]
    y = jnp.zeros((x.size,), x.dtype).at[offs.reshape(-1)].set(
        vals.reshape(-1))
    return y.reshape(x.shape), offs.astype(jnp.int32)


@partial(jax.jit, static_argnames=("input_size", "input_shape"))
def max_pooling_backward_jax(err_output, input_offset, input_size,
                             input_shape):
    """Scatter-add err to the winning offsets (gd_pooling.py:233-247)."""
    flat = jnp.zeros((input_size,), dtype=err_output.dtype)
    flat = flat.at[input_offset.reshape(-1)].add(err_output.reshape(-1))
    return flat.reshape(input_shape)


@partial(jax.jit, static_argnames=("ky", "kx", "sliding", "input_shape"))
def avg_pooling_backward_jax(err_output, ky, kx, sliding, input_shape):
    """Spread err/(truncated window size) over each window
    (gd_pooling.py:272-287) — via VJP of the forward average."""
    zeros = jnp.zeros(input_shape, dtype=err_output.dtype)
    _, vjp = jax.vjp(
        lambda x: avg_pooling_jax(x, ky, kx, sliding), zeros)
    return vjp(err_output)[0]


# -- numpy twins (the executable spec) --------------------------------------

def max_pooling_numpy(x, ky, kx, sliding, use_abs=False):
    b, sy, sx, c = x.shape
    ny, nx = output_spatial(sy, sx, ky, kx, sliding)
    out = numpy.empty((b, ny, nx, c), dtype=x.dtype)
    offs = numpy.empty((b, ny, nx, c), dtype=numpy.int32)
    for bi in range(b):
        for ci in range(c):
            for i in range(ny):
                y1 = i * sliding[1]
                y2 = min(y1 + ky, sy)
                for j in range(nx):
                    x1 = j * sliding[0]
                    x2 = min(x1 + kx, sx)
                    cut = x[bi, y1:y2, x1:x2, ci]
                    k = numpy.abs(cut).argmax() if use_abs else cut.argmax()
                    di, dj = numpy.unravel_index(k, cut.shape)
                    out[bi, i, j, ci] = cut[di, dj]
                    offs[bi, i, j, ci] = numpy.ravel_multi_index(
                        (bi, y1 + di, x1 + dj, ci), x.shape)
    return out, offs


def avg_pooling_numpy(x, ky, kx, sliding):
    b, sy, sx, c = x.shape
    ny, nx = output_spatial(sy, sx, ky, kx, sliding)
    out = numpy.empty((b, ny, nx, c), dtype=x.dtype)
    for i in range(ny):
        y1 = i * sliding[1]
        y2 = min(y1 + ky, sy)
        for j in range(nx):
            x1 = j * sliding[0]
            x2 = min(x1 + kx, sx)
            cut = x[:, y1:y2, x1:x2, :]
            out[:, i, j, :] = cut.sum(axis=(1, 2)) / (
                (y2 - y1) * (x2 - x1))
    return out


def stochastic_pooling_numpy(x, rand_u16, ky, kx, sliding, use_abs=False):
    """Bit-exact port of the reference selection loop
    (pooling.py:434-480)."""
    b, sy, sx, c = x.shape
    ny, nx = output_spatial(sy, sx, ky, kx, sliding)
    out = numpy.empty((b, ny, nx, c), dtype=x.dtype)
    offs = numpy.empty((b, ny, nx, c), dtype=numpy.int32)
    oshape = (b, ny, nx, c)
    for bi in range(b):
        for i in range(ny):
            y1 = i * sliding[1]
            y2 = min(y1 + ky, sy)
            for j in range(nx):
                x1 = j * sliding[0]
                x2 = min(x1 + kx, sx)
                for ci in range(c):
                    cut = x[bi, y1:y2, x1:x2, ci]
                    index = numpy.ravel_multi_index((bi, i, j, ci), oshape)
                    rnd = int(rand_u16[index])
                    vals = cut.ravel()
                    key = numpy.abs(vals) if use_abs else \
                        numpy.where(vals > 0, vals, 0)
                    vsum = key.sum()
                    if vsum == 0:
                        k = int(rnd * vals.size) >> 16
                    else:
                        position = rnd * vsum / 65536.0
                        acc = 0.0
                        k = vals.size - 1
                        for t in range(vals.size):
                            acc += key[t]
                            if position <= acc:
                                k = t
                                break
                    di, dj = numpy.unravel_index(k, cut.shape)
                    out[bi, i, j, ci] = cut[di, dj]
                    offs[bi, i, j, ci] = numpy.ravel_multi_index(
                        (bi, y1 + di, x1 + dj, ci), x.shape)
    return out, offs


def stochastic_pool_depool_numpy(x, rand_u16, ky, kx, use_abs=False):
    """Numpy twin of :func:`stochastic_pool_depool_jax` — a direct port of
    the OpenCL kernel's three-pass walk (sum, select, zero-fill)."""
    sliding = (kx, ky)
    b, sy, sx, c = x.shape
    ny, nx = output_spatial(sy, sx, ky, kx, sliding)
    y = numpy.zeros_like(x)
    offs = numpy.empty((b, ny, nx, c), dtype=numpy.int32)
    oshape = (b, ny, nx, c)
    for bi in range(b):
        for i in range(ny):
            y1 = i * sliding[1]
            y2 = min(y1 + ky, sy)
            for j in range(nx):
                x1 = j * sliding[0]
                x2 = min(x1 + kx, sx)
                for ci in range(c):
                    cut = x[bi, y1:y2, x1:x2, ci]
                    vals = cut.ravel()
                    key = numpy.abs(vals) if use_abs else \
                        numpy.maximum(vals, 0)
                    vsum = key.sum()
                    index = numpy.ravel_multi_index((bi, i, j, ci), oshape)
                    rnd = int(rand_u16[index])
                    pos_add = 1.0 if vsum == 0 else 0.0
                    pos_factor = vals.size if vsum == 0 else vsum
                    pos = pos_factor * rnd / 65536.0
                    acc = 0.0
                    k = 0
                    for t in range(vals.size):
                        acc += key[t] + pos_add
                        if pos <= acc:
                            k = t
                            break
                    di, dj = numpy.unravel_index(k, cut.shape)
                    off = numpy.ravel_multi_index(
                        (bi, y1 + di, x1 + dj, ci), x.shape)
                    y[bi, y1 + di, x1 + dj, ci] = cut[di, dj]
                    offs[bi, i, j, ci] = off
    return y, offs


def max_pooling_backward_numpy(err_output, input_offset, input_shape):
    err_input = numpy.zeros(input_shape, dtype=err_output.dtype)
    flat = err_input.reshape(-1)
    for err, off in numpy.nditer([err_output, input_offset]):
        flat[off] += err
    return err_input


def avg_pooling_backward_numpy(err_output, ky, kx, sliding, input_shape):
    b, sy, sx, c = input_shape
    err_input = numpy.zeros(input_shape, dtype=err_output.dtype)
    ny, nx = err_output.shape[1], err_output.shape[2]
    for i in range(ny):
        y1 = i * sliding[1]
        y2 = min(y1 + ky, sy)
        for j in range(nx):
            x1 = j * sliding[0]
            x2 = min(x1 + kx, sx)
            err_input[:, y1:y2, x1:x2, :] += (
                err_output[:, i:i + 1, j:j + 1, :] /
                ((y2 - y1) * (x2 - x1)))
    return err_input
