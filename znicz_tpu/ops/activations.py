"""Activation functions and their output-space derivatives.

Parity targets (constants from the reference kernel tree):
* scaled tanh  f(x) = 1.7159 tanh(0.6666 x)          (all2all.py:271-279)
  f'(y) = 1.14381894 - 0.388484177 y^2
  (cuda/gradient_descent_tanh.cu)
* relu (softplus) f(x) = log(1+e^x), clamp at x>15 (all2all.py:298-317)
  f'(y) = 1 - e^{-y} (cuda/gradient_descent_relu.cu)
* strict relu f(x) = max(x, 0), f'(y) = [y > 0]
  (cuda/gradient_descent_strict_relu.cu)
* sigmoid f(x) = 1/(1+e^{-x}), f'(y) = y(1-y)
  (cuda/gradient_descent_sigmoid.cu)

All derivatives are functions of the OUTPUT y, matching the reference's
``err_y_update`` kernels so backward units need only the forward's output.
"""

import numpy
import jax
import jax.numpy as jnp

TANH_A = 1.7159
TANH_B = 0.6666
TANH_DA = 1.14381894     # A * B
TANH_DB = -0.388484177   # -(B / A)

# TanhLog hybrid constants (reference activation.py:525-532)
TANHLOG_D = 3
TANHLOG_A = 0.242528761112
TANHLOG_B = 305.459953195


# -- jax twins --------------------------------------------------------------
#
# The output-space activations carry a custom VJP built from the SAME
# f'(y) formulas (derivative_jax) the backward units run — not jax's
# autodiff of the forward.  The formulas are the executable spec down to
# the reference's rounded constants (TANH_DB prints -0.388484177 where
# -(B/A) is ...77399...), so autodiff-vs-unit gradients would differ at
# ~1e-9 relative per tanh layer and the fused path's float64 parity with
# the unit graph would erode; with the custom VJP both paths apply the
# identical backward expression.

def _with_output_vjp(name, fwd):
    f = jax.custom_vjp(fwd)

    def fwd_rule(x):
        y = fwd(x)
        return y, y

    def bwd_rule(y, ct):
        return (ct * derivative_jax(name, y),)

    f.defvjp(fwd_rule, bwd_rule)
    return f


_tanh_scaled = _with_output_vjp(
    "tanh", lambda x: TANH_A * jnp.tanh(TANH_B * x))
_softplus = _with_output_vjp(
    "relu", lambda x: jnp.where(
        x > 15, x, jnp.log1p(jnp.exp(jnp.minimum(x, 15.0)))))
_sigmoid = _with_output_vjp(
    "sigmoid", lambda x: 1.0 / (1.0 + jnp.exp(-x)))
# strict relu: the unit derivative is [y > 0]; autodiff of maximum
# routes the x == 0 tie as 0.5 — pin the unit formula
_strict_relu = _with_output_vjp(
    "strict_relu", lambda x: jnp.maximum(x, 0))


def apply_jax(name, x):
    if name == "linear":
        return x
    if name == "tanh":
        return _tanh_scaled(x)
    if name == "relu":
        return _softplus(x)
    if name == "strict_relu":
        return _strict_relu(x)
    if name == "sigmoid":
        return _sigmoid(x)
    raise ValueError("unknown activation %r" % name)


def _ext_apply(xp, name, x):
    """Standalone-unit activations (reference activation.py:477-626).

    ``log``/``sincos``/``tanhlog`` exist only as standalone units, not as
    fused layer epilogues.
    """
    if name == "log":
        return xp.log(x + xp.sqrt(xp.square(x) + 1))
    if name == "tanhlog":
        return xp.where(
            x > TANHLOG_D, xp.log(xp.abs(x) * TANHLOG_B + 1e-30) * TANHLOG_A,
            xp.where(x < -TANHLOG_D,
                     -xp.log(xp.abs(x) * TANHLOG_B + 1e-30) * TANHLOG_A,
                     TANH_A * xp.tanh(TANH_B * x)))
    if name == "sincos":
        flat = x.reshape(-1)
        idx = numpy.arange(flat.shape[0]) if xp is numpy \
            else jnp.arange(flat.shape[0])
        out = xp.where(idx % 2 == 1, xp.sin(flat), xp.cos(flat))
        return out.reshape(x.shape)
    raise ValueError("unknown activation %r" % name)


def _ext_derivative(xp, name, x, y):
    """d/dx of the standalone activations, from input x (and output y for
    tanhlog) — reference backward formulas (activation.py:499-626)."""
    if name == "log":
        return 1.0 / xp.sqrt(xp.square(x) + 1)
    if name == "tanhlog":
        return xp.where(
            x > TANHLOG_D, TANHLOG_A / x,
            xp.where(x < -TANHLOG_D, -TANHLOG_A / x,
                     xp.square(y) * TANH_DB + TANH_DA))
    if name == "sincos":
        flat = x.reshape(-1)
        idx = numpy.arange(flat.shape[0]) if xp is numpy \
            else jnp.arange(flat.shape[0])
        d = xp.where(idx % 2 == 1, xp.cos(flat), -xp.sin(flat))
        return d.reshape(x.shape)
    raise ValueError("unknown activation %r" % name)


def ext_apply_jax(name, x):
    return _ext_apply(jnp, name, x)


def ext_apply_numpy(name, x):
    return _ext_apply(numpy, name, x)


def ext_derivative_jax(name, x, y):
    return _ext_derivative(jnp, name, x, y)


def ext_derivative_numpy(name, x, y):
    return _ext_derivative(numpy, name, x, y)


def derivative_jax(name, y):
    """f'(x) expressed through the output y = f(x)."""
    if name == "linear":
        return jnp.ones_like(y)
    if name == "tanh":
        return y * y * TANH_DB + TANH_DA
    if name == "relu":
        return 1.0 - jnp.exp(-y)
    if name == "strict_relu":
        return (y > 0).astype(y.dtype)
    if name == "sigmoid":
        return y * (1.0 - y)
    raise ValueError("unknown activation %r" % name)


# -- numpy twins (the executable spec) --------------------------------------

def apply_numpy(name, x):
    if name == "linear":
        return x
    if name == "tanh":
        return TANH_A * numpy.tanh(TANH_B * x)
    if name == "relu":
        return numpy.where(x > 15, x,
                           numpy.log1p(numpy.exp(numpy.minimum(x, 15.0))))
    if name == "strict_relu":
        return numpy.maximum(x, 0)
    if name == "sigmoid":
        return 1.0 / (1.0 + numpy.exp(-x))
    raise ValueError("unknown activation %r" % name)


def derivative_numpy(name, y):
    if name == "linear":
        return numpy.ones_like(y)
    if name == "tanh":
        return y * y * TANH_DB + TANH_DA
    if name == "relu":
        return 1.0 - numpy.exp(-y)
    if name == "strict_relu":
        return (y > 0).astype(y.dtype)
    if name == "sigmoid":
        return y * (1.0 - y)
    raise ValueError("unknown activation %r" % name)
