"""Fully-connected forward/backward ops.

TPU-era equivalent of the reference's GEMM + ``apply_bias_with_activation``
kernel pair (all2all.py:195-254, ocl/all2all/forward.cl) and the backward
GEMM trio (gd.py:421-482).  One jitted function each; XLA fuses bias and
activation into the matmul epilogue — the hand-written fusion the reference
did with #define'd kernels.

Convention (matches the reference): ``weights`` has shape
(neurons, input_sample_size) unless ``weights_transposed``; forward computes
``y = x @ W^T + b``.
"""

from functools import partial

import numpy
import jax
import jax.numpy as jnp

from znicz_tpu.ops import activations


# -- forward ----------------------------------------------------------------

@partial(jax.jit, static_argnames=("activation", "weights_transposed",
                                   "include_bias"))
def forward_jax(x, weights, bias, activation="linear",
                weights_transposed=False, include_bias=True):
    x2 = x.reshape(x.shape[0], -1)
    y = x2 @ weights if weights_transposed else x2 @ weights.T
    if include_bias:
        y = y + bias
    return activations.apply_jax(activation, y)


@jax.jit
def softmax_jax(y):
    """Exp-normalize with winner index (reference fused ``apply_exp`` kernel,
    all2all.py:418-443): returns (softmax(y), argmax(y))."""
    max_idx = jnp.argmax(y, axis=1).astype(jnp.int32)
    m = jnp.max(y, axis=1, keepdims=True)
    e = jnp.exp(y - m)
    return e / jnp.sum(e, axis=1, keepdims=True), max_idx


def forward_numpy(x, weights, bias, activation="linear",
                  weights_transposed=False, include_bias=True):
    x2 = x.reshape(x.shape[0], -1)
    y = x2 @ weights if weights_transposed else x2 @ weights.T
    if include_bias:
        y = y + bias
    return activations.apply_numpy(activation, y)


def softmax_numpy(y):
    max_idx = numpy.argmax(y, axis=1).astype(numpy.int32)
    m = numpy.max(y, axis=1, keepdims=True)
    e = numpy.exp(y - m)
    return e / numpy.sum(e, axis=1, keepdims=True), max_idx


# -- backward ---------------------------------------------------------------

@partial(jax.jit, static_argnames=("weights_transposed", "need_err_input",
                                   "include_bias"))
def backward_jax(inp, err_output, weights, weights_transposed=False,
                 need_err_input=True, include_bias=True):
    """Returns (err_input, gradient_weights, gradient_bias).

    Math parity: grad_w = err_output^T @ input (gd.py:436-439),
    grad_b = err_output.sum(0) (gd.py:449),
    err_input = err_output @ weights (gd.py:467-470).
    """
    x2 = inp.reshape(inp.shape[0], -1)
    e2 = err_output.reshape(err_output.shape[0], -1)
    if weights_transposed:
        grad_w = x2.T @ e2
        err_in = e2 @ weights.T if need_err_input else None
    else:
        grad_w = e2.T @ x2
        err_in = e2 @ weights if need_err_input else None
    grad_b = e2.sum(axis=0) if include_bias else None
    if err_in is not None:
        err_in = err_in.reshape(inp.shape)
    return err_in, grad_w, grad_b


def backward_numpy(inp, err_output, weights, weights_transposed=False,
                   need_err_input=True, include_bias=True):
    x2 = inp.reshape(inp.shape[0], -1)
    e2 = err_output.reshape(err_output.shape[0], -1)
    if weights_transposed:
        grad_w = x2.T @ e2
        err_in = e2 @ weights.T if need_err_input else None
    else:
        grad_w = e2.T @ x2
        err_in = e2 @ weights if need_err_input else None
    grad_b = e2.sum(axis=0) if include_bias else None
    if err_in is not None:
        err_in = err_in.reshape(inp.shape)
    return err_in, grad_w, grad_b
