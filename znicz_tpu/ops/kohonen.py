"""Kohonen self-organizing map ops.

TPU-era equivalent of the reference's OpenCL-only kohonen kernels
(ocl/kohonen.cl — distance, argmin reduce, neighborhood gravity, gradient
apply; SURVEY.md §2.2).  One jitted computation per trainer step: winner
search, winner histogram, and the gravity-weighted batch gradient.

Math parity (reference kohonen.py:473-496):
  winner_i = argmin_j ||w_j - x_i||
  gravity_ij = exp(-||coords_j - coords_winner_i||^2 / (2 sigma^2))
  W += sum_i gravity_i[:, None] * (x_i - W) * gmult
"""


import numpy
import jax
import jax.numpy as jnp


def make_coords(neurons_number):
    """Hexagonal-ish grid in [-1, 1]^2 (reference kohonen.py:374-396)."""
    sz = neurons_number
    rows = int(numpy.round(numpy.sqrt(sz)))
    cols = sz // rows
    if sz % rows != 0:
        cols += 1
    coords = numpy.zeros((sz, 2))
    x_min, x_max, y_min, y_max = -1.0, 1.0, -1.0, 1.0
    x_step = (x_max - x_min) / (cols - 1) if cols > 1 else 0
    y_step = (y_max - y_min) / (rows - 1) if rows > 1 else 0
    y = y_min
    offs = 0
    for row in range(rows):
        x = x_min + (x_step * 0.5 if row & 1 else 0)
        for _col in range(cols):
            if offs >= sz:
                break
            coords[offs, 0] = x
            coords[offs, 1] = y
            offs += 1
            x += x_step
        y += y_step
    return coords


@jax.jit
def winners_jax(x, w):
    """argmin_j ||w_j - x_i|| for each sample."""
    x2 = x.reshape(x.shape[0], -1)
    d2 = ((x2[:, None, :] - w[None, :, :]) ** 2).sum(axis=2)
    return jnp.argmin(d2, axis=1).astype(jnp.int32)


@jax.jit
def train_step_jax(x, w, coords, sigma, gmult):
    """Returns (new_w, winner_histogram, argmins)."""
    x2 = x.reshape(x.shape[0], -1)
    d2 = ((x2[:, None, :] - w[None, :, :]) ** 2).sum(axis=2)
    argmins = jnp.argmin(d2, axis=1).astype(jnp.int32)
    hist = jnp.zeros(w.shape[0], jnp.int32).at[argmins].add(1)
    cd2 = ((coords[:, None, :] - coords[None, :, :]) ** 2).sum(axis=2)
    gravity = jnp.exp(cd2[argmins] / (-2.0 * sigma * sigma))  # (B, N)
    # sum_i g_i[:,None] * (x_i - W) = G^T x - (G^T 1)[:,None] * W
    gw = gravity.sum(axis=0)[:, None]
    gradients = (gravity.T @ x2 - gw * w) * gmult
    return w + gradients, hist, argmins


def train_step_sharded(mesh, x, w, coords, sigma, gmult):
    """Data-parallel SOM step over a device mesh: the batch shards over
    the ``data`` axis, weights/coords replicate, and GSPMD inserts the
    gravity-sum all-reduce (the batch-additive ``gravity.T @ x`` term) —
    the SPMD replacement for aggregating Kohonen updates through the
    reference's master-slave protocol.  Returns the same
    (new_w, winner_histogram, argmins) as :func:`train_step_jax`, with
    argmins sharded over ``data``."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    xs = NamedSharding(mesh, P("data", *([None] * (x.ndim - 1))))
    rep = NamedSharding(mesh, P())
    x = jax.device_put(numpy.asarray(x), xs)
    w = jax.device_put(numpy.asarray(w), rep)
    coords = jax.device_put(numpy.asarray(coords), rep)
    return train_step_jax(x, w, coords, sigma, gmult)


def winners_numpy(x, w):
    x2 = x.reshape(x.shape[0], -1)
    out = numpy.empty(x2.shape[0], dtype=numpy.int32)
    for i in range(x2.shape[0]):
        dist = w - x2[i]
        out[i] = numpy.argmin(numpy.linalg.norm(dist, axis=1))
    return out


def train_step_numpy(x, w, coords, sigma, gmult):
    """Direct port of the reference loop (kohonen.py:473-496)."""
    x2 = x.reshape(x.shape[0], -1)
    neurons_number = w.shape[0]
    hist = numpy.zeros(neurons_number, dtype=numpy.int32)
    gradients = numpy.zeros(w.shape)
    dists = numpy.empty(neurons_number)
    argmins = numpy.empty(x2.shape[0], dtype=numpy.int32)
    for i in range(x2.shape[0]):
        dist = w - x2[i]
        winner = int(numpy.argmin(numpy.linalg.norm(dist, axis=1)))
        argmins[i] = winner
        hist[winner] += 1
        wc = coords[winner]
        for n in range(neurons_number):
            d = coords[n] - wc
            dists[n] = numpy.sum(d * d)
        gravity = numpy.exp(dists / (-2 * sigma * sigma))
        gradients += gravity[:, None] * (x2[i] - w) * gmult
    return w + gradients, hist, argmins
