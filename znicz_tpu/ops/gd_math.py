"""The gradient-descent update algebra — exact parity with the reference.

This is the per-layer optimizer the whole framework shares
(nn_units.py:696-719, gd.py:314-419, cuda/gradient_descent_common.cu
``gradient_step_l12``):

1. ``step = grad + wd * ((1 - l1_vs_l2) * w + 0.5 * l1_vs_l2 * sign(w))
            [+ ortho]``;  ``gradient = -lr * step``
2. accumulate (nn_units.py:419-428):
   ``acc = acc_alpha * gradient + acc_beta * acc``
   ``gradient = gd_beta * gradient + gd_alpha * acc``
3. moment (gd.py:314-326, variant_moment_gradient=True):
   ``vel = gradient + moment * vel``; applied gradient is ``vel``
4. ``w += gradient`` when apply_gradient.

The ortho regularizer (nn_units.py:713-717): each gradient row i gains
``(col_sums - w[i]) * factor_ortho / n_rows`` where col_sums = w.sum(axis=0).

Solvers adagrad/adadelta/fast (gd.py:395-419) transform the velocity before
application; they compose with the above exactly as the reference's
``numpy_update`` does.

State per parameter tensor is a dict pytree: ``acc`` (accumulated gradient),
``vel`` (gradient with moment), plus solver slots.  The same function runs
under jit (jax arrays) and eagerly (numpy) — pure jnp/numpy-agnostic algebra
via the ``xp`` module argument.
"""

from functools import partial

import numpy
import jax
import jax.numpy as jnp


def _gradient_step(xp, w, grad, lr, wd, l1_vs_l2, factor_ortho, use_ortho):
    step = grad + wd * ((1.0 - l1_vs_l2) * w +
                        0.5 * l1_vs_l2 * xp.sign(w))
    if use_ortho:
        col_sums = w.sum(axis=0)
        step = step + (col_sums[None, :] - w) * (factor_ortho / w.shape[0])
    return lr * step


def update(xp, w, grad, state, hyper, flags):
    """One parameter update.  Returns (new_w, new_state, applied_gradient).

    hyper: dict(lr, wd, l1_vs_l2, moment, acc_alpha, acc_beta, gd_alpha,
                gd_beta, factor_ortho)
    flags: dict(accumulate, apply, solvers=frozenset, variant_moment=True)
    state: dict(acc, vel, [adagrad], [adadelta_v, adadelta_gv], [fast])
    """
    gradient = -_gradient_step(
        xp, w, grad, hyper["lr"], hyper["wd"], hyper["l1_vs_l2"],
        hyper.get("factor_ortho", 0.0), flags.get("ortho", False))
    new_state = dict(state)

    if flags.get("accumulate") and state.get("acc") is not None:
        acc = hyper["acc_alpha"] * gradient + hyper["acc_beta"] * state["acc"]
        gradient = hyper["gd_beta"] * gradient + hyper["gd_alpha"] * acc
        new_state["acc"] = acc

    if state.get("vel") is not None:
        if flags.get("variant_moment", True):
            vel = gradient + hyper["moment"] * state["vel"]
        else:
            vel = ((1.0 - hyper["moment"]) * gradient +
                   hyper["moment"] * state["vel"])
        new_state["vel"] = vel
        gradient = vel
    solvers = flags.get("solvers") or frozenset()
    if "adagrad" in solvers:
        ada = state["adagrad"] + new_state["vel"] ** 2
        gradient = gradient * xp.sqrt(ada + hyper.get("adagrad_eps", 1e-8))
        new_state["adagrad"] = ada
    if "adadelta" in solvers:
        eps = hyper.get("adadelta_eps", 1e-8)
        adom = hyper.get("adadelta_adom", 0.3)
        gv = (adom * state["adadelta_gv"] +
              (1.0 - adom) * new_state["vel"] ** 2)
        s1 = xp.sqrt(state["adadelta_v"] + eps)
        s2 = xp.sqrt(gv + eps)
        gradient = gradient * (s1 / s2)
        v = adom * state["adadelta_v"] + (1.0 - adom) * gradient ** 2
        new_state["adadelta_gv"] = gv
        new_state["adadelta_v"] = v
    if "fast" in solvers:
        fast = (state["fast"] * 0.95 +
                hyper.get("fast_lr", 0.02) * new_state["vel"])
        new_state["fast"] = fast

    new_w = w
    if flags.get("apply", True):
        new_w = w + gradient
        if "fast" in solvers:
            new_w = new_w - new_state["fast"]
    return new_w, new_state, gradient


# jit-compiled entry for the jax path; hyper values become traced scalars so
# learning-rate schedules don't retrigger compilation.
@partial(jax.jit, static_argnames=("flags_key",))
def _update_jax(w, grad, state, hyper, flags_key):
    flags = dict(flags_key)
    flags["solvers"] = frozenset(flags.get("solvers") or ())
    return update(jnp, w, grad, state, hyper, flags)[:2] + (None,)


def _flags_key(flags):
    """Hashable static-arg form of a flags dict (jit cache key)."""
    return tuple(sorted(
        (k, tuple(sorted(v)) if isinstance(v, (set, frozenset)) else v)
        for k, v in flags.items()))


def update_jax(w, grad, state, hyper, flags):
    new_w, new_state, _ = _update_jax(w, grad, state, hyper,
                                      _flags_key(flags))
    return new_w, new_state


def register_update_cost(name, w, grad, state, hyper, flags):
    """Executable cost-registry hook for the jitted GD update kernel
    (core/profiler.py): lower ``_update_jax`` with the exact dispatch
    arguments BEFORE the first call, recording XLA's FLOPs and bytes
    accessed.  Call sites guard with ``profiler.enabled()``; the
    registered-name check FIRST keeps the armed steady state at one
    dict lookup per update."""
    from znicz_tpu.core import profiler
    entry = profiler.cost_entry(name)
    if entry is not None:
        return entry
    return profiler.register_jit_cost(
        name, _update_jax, (w, grad, state, hyper),
        kwargs={"flags_key": _flags_key(flags)},
        param_elements=int(getattr(w, "size", 0) or 0))


def update_numpy(w, grad, state, hyper, flags):
    return update(numpy, w, grad, state, hyper, flags)[:2]


def init_state(w, flags, like=numpy):
    """Allocate the optimizer-state pytree for one parameter tensor."""
    z = (lambda: like.zeros_like(w))
    state = {}
    if flags.get("accumulate"):
        state["acc"] = z()
    if flags.get("need_vel", True):
        state["vel"] = z()
    solvers = flags.get("solvers") or frozenset()
    if "adagrad" in solvers:
        state["adagrad"] = z()
    if "adadelta" in solvers:
        state["adadelta_v"] = z()
        state["adadelta_gv"] = z()
    if "fast" in solvers:
        state["fast"] = z()
    return state
