"""Pallas TPU kernel: max/maxabs pooling WITH winner offsets, one pass.

The unit-graph path needs the reference's flat ``input_offset``
bookkeeping (pooling.py:303-312) so GD pooling can scatter gradients to
the winners.  The XLA formulation materializes a (B, ny, nx, ky*kx, C)
window view and gathers through argmax indices — several HBM round
trips.  This kernel keeps one batch row in VMEM and computes value +
winner offset in a single fused pass: a running strict-greater max over
the ky*kx window cells (unrolled — kernels are small), which also
reproduces the argmax first-winner tie rule.

On non-TPU backends the kernel runs in interpreter mode, so the numpy
twins remain the executable spec everywhere (guide:
/opt/skills/guides/pallas_guide.md).
"""

import functools

import numpy

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _kernel(x_ref, out_ref, off_ref, *, ky, kx, sy, sx, ny, nx,
            h, w, c, use_abs):
    b = pl.program_id(0)
    x = x_ref[0]  # (h, w, c) in VMEM
    neg = jnp.finfo(x.dtype).min
    # pad so every strided window position exists; Mosaic has no
    # stride>1 vector slices, so striding is done by reshape-and-select
    # enough slack that every (dy, dx) shift has ny*sy / nx*sx rows/cols
    ph = ny * sy + ky - 1 - h
    pw = nx * sx + kx - 1 - w
    xp = jnp.pad(x, ((0, ph), (0, pw), (0, 0)))
    hp, wp = h + ph, w + pw
    best_key = jnp.full((ny, nx, c), neg, x.dtype)
    best_val = jnp.zeros((ny, nx, c), x.dtype)
    best_q = jnp.zeros((ny, nx, c), jnp.int32)
    found = jnp.zeros((ny, nx, c), jnp.bool_)
    ii = jax.lax.broadcasted_iota(jnp.int32, (ny, nx, c), 0)
    jj = jax.lax.broadcasted_iota(jnp.int32, (ny, nx, c), 1)
    for dy in range(ky):
        rows = jax.lax.slice(xp, (dy, 0, 0), (dy + ny * sy, wp, c))
        rows = rows.reshape(ny, sy, wp, c)[:, 0]  # stride sy
        for dx in range(kx):
            cols = jax.lax.slice(rows, (0, dx, 0), (ny, dx + nx * sx, c))
            val = cols.reshape(ny, nx, sx, c)[:, :, 0]  # stride sx
            key = jnp.abs(val) if use_abs else val
            # cells beyond the true input are invalid (overhang)
            valid = (ii * sy + dy < h) & (jj * sx + dx < w)
            # strict > keeps the FIRST window cell on ties; the ~found
            # term lets the first VALID cell win even when its key is
            # -inf / finfo.min (the sentinel must not beat real data).
            # NaN windows are undefined behavior here (numpy argmax
            # would return the NaN's index; training NaN-guards apart).
            better = valid & (~found | (key > best_key))
            found = found | valid
            best_key = jnp.where(better, key, best_key)
            best_val = jnp.where(better, val, best_val)
            best_q = jnp.where(better, dy * kx + dx, best_q)
    out_ref[0] = best_val
    cc = jax.lax.broadcasted_iota(jnp.int32, (ny, nx, c), 2)
    wy = ii * sy + best_q // kx
    wx = jj * sx + best_q % kx
    off_ref[0] = ((b * h + wy) * w + wx) * c + cc


@functools.partial(jax.jit,
                   static_argnames=("ky", "kx", "sliding", "use_abs"))
def max_pooling_offsets_pallas(x, ky, kx, sliding, use_abs=False):
    """(output, flat winner offsets) — drop-in for the window-view
    formulation of ops/pooling.max_pooling_jax."""
    from znicz_tpu.ops.pooling import output_spatial
    b, h, w, c = x.shape
    ny, nx = output_spatial(h, w, ky, kx, sliding)
    kernel = functools.partial(
        _kernel, ky=ky, kx=kx, sx=int(sliding[0]), sy=int(sliding[1]),
        ny=ny, nx=nx, h=h, w=w, c=c, use_abs=use_abs)
    return pl.pallas_call(
        kernel,
        grid=(b,),
        in_specs=[pl.BlockSpec((1, h, w, c), lambda i: (i, 0, 0, 0))],
        out_specs=[pl.BlockSpec((1, ny, nx, c), lambda i: (i, 0, 0, 0)),
                   pl.BlockSpec((1, ny, nx, c), lambda i: (i, 0, 0, 0))],
        out_shape=[jax.ShapeDtypeStruct((b, ny, nx, c), x.dtype),
                   jax.ShapeDtypeStruct((b, ny, nx, c), jnp.int32)],
        interpret=jax.default_backend() != "tpu",
    )(x)


#: VMEM budget for one batch row (input + padded copy + outputs must
#: fit in ~16MB/core; stay well under)
_VMEM_BYTES_LIMIT = 4 * 1024 * 1024


def supported(x, ky, kx, sliding, use_abs):
    """Whether the kernel covers this case: float dtypes (the sentinel
    needs a float lattice bottom) whose per-row block fits VMEM.
    dtype inspection only — works on tracers, no host transfer."""
    if not numpy.issubdtype(x.dtype, numpy.floating):
        return False
    h, w, c = x.shape[1], x.shape[2], x.shape[3]
    return h * w * c * x.dtype.itemsize <= _VMEM_BYTES_LIMIT
