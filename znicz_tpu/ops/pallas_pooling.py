"""Pallas TPU kernel: max/maxabs pooling WITH winner offsets, one pass.

The unit-graph path needs the reference's flat ``input_offset``
bookkeeping (pooling.py:303-312) so GD pooling can scatter gradients to
the winners.  The XLA formulation materializes a (B, ny, nx, ky*kx, C)
window view and gathers through argmax indices — several HBM round
trips.  This kernel keeps one batch row in VMEM and computes value +
winner offset in a single fused pass: a running strict-greater max over
the ky*kx window cells (unrolled — kernels are small), which also
reproduces the argmax first-winner tie rule.

On non-TPU backends the kernel runs in interpreter mode, so the numpy
twins remain the executable spec everywhere (guide:
/opt/skills/guides/pallas_guide.md).
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _kernel(x_ref, out_ref, off_ref, *, ky, kx, sy, sx, ny, nx,
            h, w, c, use_abs):
    b = pl.program_id(0)
    # compute in f32: sub-32-bit dtypes tile (2,128)/(4,128) and their
    # i1 comparison masks cannot relayout against the (8,128) int32
    # winner-index selects (Mosaic rejects the mixed layouts); the
    # bf16->f32->bf16 round trip is value-exact.  supported() rejects
    # dtypes wider than f32 (f64 would round).
    x = x_ref[0].astype(jnp.float32)  # (h, w, c) in VMEM
    # pad so every strided window position exists; Mosaic has no
    # stride>1 vector slices, so striding is done by reshape-and-select
    # enough slack that every (dy, dx) shift has ny*sy / nx*sx rows/cols.
    # Overhang cells carry a KEY of -inf: under the strict-> update an
    # overhang cell can NEVER replace the incumbent (even a real -inf
    # cell, since -inf > -inf is false, and the (0,0) init cell is
    # always real) — no boolean validity masks (Mosaic's i1 relayouts
    # reject the (ny, nx, c) broadcast shapes).  NaN windows remain
    # undefined behavior (select semantics, not numpy argmax).
    ph = ny * sy + ky - 1 - h
    pw = nx * sx + kx - 1 - w
    neg = jnp.float32(-jnp.inf)
    xv = jnp.pad(x, ((0, ph), (0, pw), (0, 0)))
    xk = jnp.pad(jnp.abs(x) if use_abs else x,
                 ((0, ph), (0, pw), (0, 0)), constant_values=neg)
    hp, wp = h + ph, w + pw

    def row_strip(src, dy):
        rows = jax.lax.slice(src, (dy, 0, 0), (dy + ny * sy, wp, c))
        return rows.reshape(ny, sy, wp, c)[:, 0]  # stride sy

    def cell(rows, dx):
        cols = jax.lax.slice(rows, (0, dx, 0), (ny, dx + nx * sx, c))
        return cols.reshape(ny, nx, sx, c)[:, :, 0]  # stride sx

    best_key = best_val = best_q = None
    for dy in range(ky):
        # hoist the row strips: one slice pair per dy, not per cell
        rows_k = row_strip(xk, dy)
        rows_v = row_strip(xv, dy)
        for dx in range(kx):
            key = cell(rows_k, dx)
            val = cell(rows_v, dx)
            if best_key is None:
                # cell (0, 0) — the window origin is always in-bounds
                best_key, best_val = key, val
                best_q = jnp.zeros((ny, nx, c), jnp.int32)
                continue
            # strict > keeps the FIRST window cell on ties (the unit
            # path's argmax rule)
            better = key > best_key
            best_key = jnp.where(better, key, best_key)
            best_val = jnp.where(better, val, best_val)
            best_q = jnp.where(better, dy * kx + dx, best_q)
    out_ref[0] = best_val.astype(out_ref.dtype)
    ii = jax.lax.broadcasted_iota(jnp.int32, (ny, nx, c), 0)
    jj = jax.lax.broadcasted_iota(jnp.int32, (ny, nx, c), 1)
    cc = jax.lax.broadcasted_iota(jnp.int32, (ny, nx, c), 2)
    wy = ii * sy + best_q // kx
    wx = jj * sx + best_q % kx
    off_ref[0] = ((b * h + wy) * w + wx) * c + cc


@functools.partial(jax.jit,
                   static_argnames=("ky", "kx", "sliding", "use_abs"))
def max_pooling_offsets_pallas(x, ky, kx, sliding, use_abs=False):
    """(output, flat winner offsets) — drop-in for the window-view
    formulation of ops/pooling.max_pooling_jax."""
    from znicz_tpu.ops.pooling import output_spatial
    b, h, w, c = x.shape
    ny, nx = output_spatial(h, w, ky, kx, sliding)
    kernel = functools.partial(
        _kernel, ky=ky, kx=kx, sx=int(sliding[0]), sy=int(sliding[1]),
        ny=ny, nx=nx, h=h, w=w, c=c, use_abs=use_abs)
    return pl.pallas_call(
        kernel,
        grid=(b,),
        in_specs=[pl.BlockSpec((1, h, w, c), lambda i: (i, 0, 0, 0))],
        out_specs=[pl.BlockSpec((1, ny, nx, c), lambda i: (i, 0, 0, 0)),
                   pl.BlockSpec((1, ny, nx, c), lambda i: (i, 0, 0, 0))],
        out_shape=[jax.ShapeDtypeStruct((b, ny, nx, c), x.dtype),
                   jax.ShapeDtypeStruct((b, ny, nx, c), jnp.int32)],
        interpret=jax.default_backend() != "tpu",
    )(x)


#: VMEM budget for one batch row; Mosaic's scoped stack is 16MB/core —
#: stay well under (the estimate below is approximate)
_VMEM_BYTES_LIMIT = 8 * 1024 * 1024


def supported(x, ky, kx, sliding, use_abs):
    """Whether the kernel covers this case: float dtypes (the sentinel
    needs a float lattice bottom) whose per-row working set fits the
    Mosaic VMEM stack.  The estimate accounts for LANE padding (the
    minor dim tiles to 128) and the per-unrolled-cell temporaries —
    measured against real Mosaic scoped-vmem failures, not just the
    input bytes.  Shape/dtype inspection only — works on tracers."""
    if not jnp.issubdtype(x.dtype, jnp.floating):
        # jnp (not numpy) so bfloat16 qualifies
        return False
    if x.dtype.itemsize > 4:
        # the kernel computes in f32 — f64 would silently round values
        # and could flip winners; wide dtypes take the window-view path
        return False
    from znicz_tpu.ops.pooling import output_spatial
    h, w, c = int(x.shape[1]), int(x.shape[2]), int(x.shape[3])
    ny, nx = output_spatial(h, w, ky, kx, sliding)
    c_pad = -(-c // 128) * 128
    hp = ny * sliding[1] + ky - 1
    wp = nx * sliding[0] + kx - 1
    # two padded copies + per-dy hoisted row strips (2*ky) + per-cell
    # strided views + bests; the kernel computes in f32 regardless of
    # the input dtype.  Calibrated against Mosaic's scoped-vmem
    # accounting (it rejected ~17.6M for the 33x33x32 k=3 case).
    est = 4 * c_pad * (hp * wp * (2 + 2 * ky) +
                       ny * nx * (2 * ky * kx + 8))
    return est <= _VMEM_BYTES_LIMIT
