"""Pure compute functions — the TPU-era equivalent of the reference's
``ocl/`` + ``cuda/`` kernel trees (SURVEY.md §2.6).

Every op has two twins:

* a **jax** function (jitted; XLA fuses bias+activation into the GEMM the way
  the reference's hand-written ``apply_bias_with_activation`` kernels did) —
  the TPU path;
* a **numpy** function — the executable spec, used by ``numpy_run`` and by
  cross-validation tests (replacing the reference's numpy-vs-OpenCL/CUDA
  pattern, tests/unit/test_all2all.py:95-152).

No im2col staging, no hand-scheduled reductions: ``lax.conv_general_dilated``
and XLA fusion own that on TPU (SURVEY.md §7 design stance).
"""
