"""Evaluator ops — the boundary between forward and backward.

TPU-era equivalent of the reference's fused evaluator kernels
(evaluator.jcl/.jcu): ONE jitted computation produces the softmax-CE
gradient, the error count, the confusion matrix and the max gradient sum
(reference numpy spec: evaluator.py:271-312).  MSE twin below
(evaluator.py:334-556).

Semantics parity:
* ``err_output = (softmax_output - onehot(label)) * (1/batch if mean else 1)``
* samples with ``label < 0`` contribute zero error and no stats;
* samples beyond ``batch_size`` (padded tail minibatch) zeroed;
* ``n_err = [misclassified, evaluated]`` accumulated across minibatches;
* confusion_matrix[max_idx, label] += 1.
"""

from functools import partial

import numpy
import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnames=("n_classes", "mean"))
def softmax_ce_jax(output, max_idx, labels, batch_size, n_classes, mean=True):
    """Returns (err_output, n_err_delta[2], confusion_delta, max_err_sum).

    ``output`` is the softmax output (B, C); ``labels`` int (B,);
    ``batch_size`` may be < B for the padded tail minibatch.
    """
    B, C = output.shape
    idx = jnp.arange(B)
    in_batch = idx < batch_size
    valid = in_batch & (labels >= 0)

    onehot = jax.nn.one_hot(jnp.maximum(labels, 0), C, dtype=output.dtype)
    mult = jnp.where(mean, 1.0 / jnp.maximum(batch_size, 1), 1.0)
    err = (output - onehot) * mult.astype(output.dtype)
    err = jnp.where(valid[:, None], err, 0)

    hits = valid & (max_idx == labels)
    n_total = valid.sum()
    n_ok = hits.sum()
    n_err_delta = jnp.stack([n_total - n_ok, n_total]).astype(jnp.int32)

    conf = jnp.zeros((n_classes, n_classes), dtype=jnp.int32)
    conf = conf.at[max_idx, jnp.maximum(labels, 0)].add(
        valid.astype(jnp.int32))

    max_err_sum = jnp.where(valid, jnp.abs(err).sum(axis=1), 0).max()
    return err, n_err_delta, conf, max_err_sum


def softmax_ce_numpy(output, max_idx, labels, batch_size, n_classes,
                     mean=True):
    B, C = output.shape
    err = numpy.zeros_like(output)
    conf = numpy.zeros((n_classes, n_classes), dtype=numpy.int32)
    mult = 1.0 / batch_size if mean else 1.0
    n_ok = 0
    n_total = 0
    max_err_sum = 0.0
    for i in range(int(batch_size)):
        if labels[i] < 0:
            continue
        err[i] = output[i]
        err[i, labels[i]] -= 1.0
        err[i] *= mult
        conf[max_idx[i], labels[i]] += 1
        if max_idx[i] == labels[i]:
            n_ok += 1
        n_total += 1
        max_err_sum = max(max_err_sum, numpy.abs(err[i]).sum())
    n_err_delta = numpy.array([n_total - n_ok, n_total], dtype=numpy.int32)
    return err, n_err_delta, conf, max_err_sum


@partial(jax.jit, static_argnames=("mean", "root"))
def mse_jax(output, target, batch_size, mean=True, root=False):
    """Returns (err_output, metrics_delta[3], per-sample mse).

    metrics = [sum_mse, max_mse, min_mse] (reference evaluator.py:334-556).
    """
    B = output.shape[0]
    o2 = output.reshape(B, -1)
    t2 = target.reshape(B, -1)
    idx = jnp.arange(B)
    in_batch = idx < batch_size
    mult = jnp.where(mean, 1.0 / jnp.maximum(batch_size, 1), 1.0)
    err = (o2 - t2) * mult.astype(output.dtype)
    err = jnp.where(in_batch[:, None], err, 0)
    diff = jnp.where(in_batch[:, None], o2 - t2, 0)
    mse_per = (diff * diff).sum(axis=1) / o2.shape[1]
    mse_per = jnp.where(root, jnp.sqrt(mse_per), mse_per)
    s = mse_per.sum()
    mx = mse_per.max()
    mn = jnp.where(in_batch, mse_per, jnp.inf).min()
    return err.reshape(output.shape), jnp.stack([s, mx, mn]), mse_per


def mse_numpy(output, target, batch_size, mean=True, root=False):
    B = output.shape[0]
    o2 = output.reshape(B, -1)
    t2 = target.reshape(B, -1)
    err = numpy.zeros_like(o2)
    mult = 1.0 / batch_size if mean else 1.0
    bs = int(batch_size)
    err[:bs] = (o2[:bs] - t2[:bs]) * mult
    diff = numpy.zeros_like(o2)
    diff[:bs] = o2[:bs] - t2[:bs]
    mse_per = (diff * diff).sum(axis=1) / o2.shape[1]
    if root:
        mse_per = numpy.sqrt(mse_per)
    s = mse_per[:bs].sum()
    mx = mse_per[:bs].max() if bs else 0.0
    mn = mse_per[:bs].min() if bs else 0.0
    return (err.reshape(output.shape),
            numpy.array([s, mx, mn]), mse_per)
