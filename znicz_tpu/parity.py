"""One-command real-data accuracy parity runs (BASELINE.md bar).

``python -m znicz_tpu mnist --parity`` provisions the real dataset
(manifest-style URLs, the role of the reference's per-sample
``manifest.json`` + Downloader), trains the published config to its
stopping criterion, and prints the parity-table row against the
reference baseline (reference snapshot names encode
``validation_<err>_train_<err>`` — BASELINE.md).

In a zero-egress environment the provisioning step fails FAST with an
explicit "network required" message (short socket timeout) instead of
silently training on the synthetic fallback.
"""

import gzip
import os
import shutil
import tarfile
import urllib.error
import urllib.request

from znicz_tpu.core.config import root

#: dataset provisioning manifests: file list the loader needs + the
#: archives/URLs that produce them (reference samples/MNIST/manifest.json
#: role).  Mirrors listed in preference order.
DATASETS = {
    "mnist": {
        "subdir": "MNIST",
        "files": ("train-images.idx3-ubyte", "train-labels.idx1-ubyte",
                  "t10k-images.idx3-ubyte", "t10k-labels.idx1-ubyte"),
        "sources": [
            # (url, member -> target) gz files, one per idx file
            ("https://ossci-datasets.s3.amazonaws.com/mnist/%s.gz", {
                "train-images-idx3-ubyte": "train-images.idx3-ubyte",
                "train-labels-idx1-ubyte": "train-labels.idx1-ubyte",
                "t10k-images-idx3-ubyte": "t10k-images.idx3-ubyte",
                "t10k-labels-idx1-ubyte": "t10k-labels.idx1-ubyte"}),
            ("https://storage.googleapis.com/cvdf-datasets/mnist/%s.gz", {
                "train-images-idx3-ubyte": "train-images.idx3-ubyte",
                "train-labels-idx1-ubyte": "train-labels.idx1-ubyte",
                "t10k-images-idx3-ubyte": "t10k-images.idx3-ubyte",
                "t10k-labels-idx1-ubyte": "t10k-labels.idx1-ubyte"}),
        ],
    },
    "cifar": {
        "subdir": "CIFAR10",
        "files": tuple(["data_batch_%d" % i for i in range(1, 6)] +
                       ["test_batch"]),
        "tar": ("https://www.cs.toronto.edu/~kriz/"
                "cifar-10-python.tar.gz", "cifar-10-batches-py"),
    },
}

#: parity rows: sample -> [(label, reference val err %, build kwargs)]
PARITY_RUNS = {
    "mnist": [
        ("MNIST MLP", 1.92, {}),
        ("MNIST conv", 0.75, {"layers_key": "mnistr_conv"}),
        ("MNIST caffe", 0.80, {"layers_key": "mnistr_caffe"}),
    ],
    "cifar": [
        ("CIFAR-10 caffe conv", 17.21, {}),
    ],
}

TIMEOUT = 30  # seconds per HTTP request — fail fast offline


class NetworkRequired(SystemExit):
    pass


def _fetch(url, dest):
    tmp = dest + ".part"
    with urllib.request.urlopen(url, timeout=TIMEOUT) as r, \
            open(tmp, "wb") as f:
        shutil.copyfileobj(r, f)
    os.replace(tmp, dest)


def ensure_dataset(name, directory=None):
    """Make the real dataset available; returns its directory.

    Raises :class:`NetworkRequired` (a SystemExit) with an explicit
    message when files are absent and the network is unreachable.
    """
    spec = DATASETS[name]
    directory = directory or os.path.join(root.common.dirs.datasets,
                                          spec["subdir"])
    missing = [f for f in spec["files"]
               if not os.path.exists(os.path.join(directory, f))]
    if not missing:
        return directory
    os.makedirs(directory, exist_ok=True)
    errors = []
    if "tar" in spec:
        url, member_dir = spec["tar"]
        dest = os.path.join(directory, os.path.basename(url))
        try:
            if not os.path.exists(dest):
                _fetch(url, dest)
            try:
                with tarfile.open(dest) as tf:
                    try:
                        # confine members to the target directory (a
                        # compromised mirror must not traverse paths)
                        tf.extractall(directory, filter="data")
                    except TypeError:  # Python < 3.12
                        tf.extractall(directory)
            except tarfile.TarError as e:
                # truncated/corrupt cache poisons every retry — drop it
                os.remove(dest)
                raise OSError("corrupt archive removed, re-run: %s" % e)
            src = os.path.join(directory, member_dir)
            if os.path.isdir(src):
                for f in spec["files"]:
                    p = os.path.join(src, f)
                    if os.path.exists(p):
                        shutil.move(p, os.path.join(directory, f))
            still = [f for f in spec["files"]
                     if not os.path.exists(os.path.join(directory, f))]
            if still:
                raise OSError("archive did not contain %s"
                              % ", ".join(still))
            return directory
        except (urllib.error.URLError, OSError) as e:
            errors.append("%s: %s" % (url, e))
    for pattern, members in spec.get("sources", ()):
        try:
            for member, target in members.items():
                tpath = os.path.join(directory, target)
                if os.path.exists(tpath):
                    continue
                gz = os.path.join(directory, member + ".gz")
                if not os.path.exists(gz):
                    _fetch(pattern % member, gz)
                with gzip.open(gz, "rb") as fin, \
                        open(tpath + ".part", "wb") as fout:
                    shutil.copyfileobj(fin, fout)
                os.replace(tpath + ".part", tpath)
            return directory
        except (urllib.error.URLError, OSError) as e:
            errors.append("%s: %s" % (pattern, e))
    raise NetworkRequired(
        "network required: the %s parity run needs the real dataset "
        "(missing %s under %s) and no mirror was reachable:\n  %s\n"
        "Download the files manually into that directory and re-run."
        % (name, ", ".join(missing), directory,
           "\n  ".join(errors) or "no sources configured"))


def run_parity(sample, device=None, data_dir=None):
    """Provision data, train every parity config of ``sample`` to its
    stopping criterion, print the comparison table.  Returns the rows as
    (label, reference_err_pt, our_err_pt)."""
    if sample not in PARITY_RUNS:
        raise SystemExit(
            "no parity baseline registered for %r (have: %s)"
            % (sample, ", ".join(sorted(PARITY_RUNS))))
    data_dir = ensure_dataset(sample, directory=data_dir)
    import importlib
    module = importlib.import_module("znicz_tpu.samples." + sample)
    rows = []
    for label, ref_err, opts in PARITY_RUNS[sample]:
        kwargs = {}
        layers_key = opts.get("layers_key")
        if layers_key is not None:
            kwargs["layers"] = getattr(root, layers_key).layers
        wf = module.build(
            loader_config={"synthetic": False, "data_path": data_dir},
            **kwargs)
        wf.initialize(device=device)
        wf.run()
        ours = wf.decision.best_n_err_pt[1]
        rows.append((label, ref_err, ours))
        print("| %-22s | reference %6.2f%% | ours %8s | %s |"
              % (label, ref_err,
                 "%.2f%%" % ours if ours is not None else "n/a",
                 "PASS" if ours is not None and ours <= ref_err + 0.15
                 else "CHECK"))
    return rows
