"""One-command real-data accuracy parity runs (BASELINE.md bar).

``python -m znicz_tpu mnist --parity`` provisions the real dataset
(manifest-style URLs, the role of the reference's per-sample
``manifest.json`` + Downloader), trains the published config to its
stopping criterion, and prints the parity-table row against the
reference baseline (reference snapshot names encode
``validation_<err>_train_<err>`` — BASELINE.md).

In a zero-egress environment the provisioning step fails FAST with an
explicit "network required" message (short socket timeout) instead of
silently training on the synthetic fallback.
"""

import gzip
import os
import shutil
import tarfile
import urllib.error
import urllib.request

from znicz_tpu.core.config import root

#: dataset provisioning manifests: file list the loader needs + the
#: archives/URLs that produce them (reference samples/MNIST/manifest.json
#: role).  Mirrors listed in preference order.
DATASETS = {
    "mnist": {
        "subdir": "MNIST",
        "files": ("train-images.idx3-ubyte", "train-labels.idx1-ubyte",
                  "t10k-images.idx3-ubyte", "t10k-labels.idx1-ubyte"),
        "sources": [
            # (url, member -> target) gz files, one per idx file
            ("https://ossci-datasets.s3.amazonaws.com/mnist/%s.gz", {
                "train-images-idx3-ubyte": "train-images.idx3-ubyte",
                "train-labels-idx1-ubyte": "train-labels.idx1-ubyte",
                "t10k-images-idx3-ubyte": "t10k-images.idx3-ubyte",
                "t10k-labels-idx1-ubyte": "t10k-labels.idx1-ubyte"}),
            ("https://storage.googleapis.com/cvdf-datasets/mnist/%s.gz", {
                "train-images-idx3-ubyte": "train-images.idx3-ubyte",
                "train-labels-idx1-ubyte": "train-labels.idx1-ubyte",
                "t10k-images-idx3-ubyte": "t10k-images.idx3-ubyte",
                "t10k-labels-idx1-ubyte": "t10k-labels.idx1-ubyte"}),
        ],
    },
    "cifar": {
        "subdir": "CIFAR10",
        "files": tuple(["data_batch_%d" % i for i in range(1, 6)] +
                       ["test_batch"]),
        "tar": ("https://www.cs.toronto.edu/~kriz/"
                "cifar-10-python.tar.gz", "cifar-10-batches-py"),
    },
}

#: parity rows: sample -> [(label, reference val err %, build kwargs)]
PARITY_RUNS = {
    "mnist": [
        ("MNIST MLP", 1.92, {}),
        ("MNIST conv", 0.75, {"layers_key": "mnistr_conv"}),
        ("MNIST caffe", 0.80, {"layers_key": "mnistr_caffe"}),
    ],
    "cifar": [
        ("CIFAR-10 caffe conv", 17.21, {}),
    ],
}

TIMEOUT = 30  # seconds per HTTP request — fail fast offline


class NetworkRequired(SystemExit):
    pass


def _fetch(url, dest):
    tmp = dest + ".part"
    with urllib.request.urlopen(url, timeout=TIMEOUT) as r, \
            open(tmp, "wb") as f:
        shutil.copyfileobj(r, f)
    os.replace(tmp, dest)


def ensure_dataset(name, directory=None):
    """Make the real dataset available; returns its directory.

    Raises :class:`NetworkRequired` (a SystemExit) with an explicit
    message when files are absent and the network is unreachable.
    """
    spec = DATASETS[name]
    directory = directory or os.path.join(root.common.dirs.datasets,
                                          spec["subdir"])
    missing = [f for f in spec["files"]
               if not os.path.exists(os.path.join(directory, f))]
    if not missing:
        return directory
    os.makedirs(directory, exist_ok=True)
    errors = []
    if "tar" in spec:
        url, member_dir = spec["tar"]
        dest = os.path.join(directory, os.path.basename(url))
        try:
            if not os.path.exists(dest):
                _fetch(url, dest)
            try:
                with tarfile.open(dest) as tf:
                    try:
                        # confine members to the target directory (a
                        # compromised mirror must not traverse paths)
                        tf.extractall(directory, filter="data")
                    except TypeError:  # Python < 3.12
                        tf.extractall(directory)
            except tarfile.TarError as e:
                # truncated/corrupt cache poisons every retry — drop it
                os.remove(dest)
                raise OSError("corrupt archive removed, re-run: %s" % e)
            src = os.path.join(directory, member_dir)
            if os.path.isdir(src):
                for f in spec["files"]:
                    p = os.path.join(src, f)
                    if os.path.exists(p):
                        shutil.move(p, os.path.join(directory, f))
            still = [f for f in spec["files"]
                     if not os.path.exists(os.path.join(directory, f))]
            if still:
                raise OSError("archive did not contain %s"
                              % ", ".join(still))
            return directory
        except (urllib.error.URLError, OSError) as e:
            errors.append("%s: %s" % (url, e))
    for pattern, members in spec.get("sources", ()):
        try:
            for member, target in members.items():
                tpath = os.path.join(directory, target)
                if os.path.exists(tpath):
                    continue
                gz = os.path.join(directory, member + ".gz")
                if not os.path.exists(gz):
                    _fetch(pattern % member, gz)
                with gzip.open(gz, "rb") as fin, \
                        open(tpath + ".part", "wb") as fout:
                    shutil.copyfileobj(fin, fout)
                os.replace(tpath + ".part", tpath)
            return directory
        except (urllib.error.URLError, OSError) as e:
            errors.append("%s: %s" % (pattern, e))
    raise NetworkRequired(
        "network required: the %s parity run needs the real dataset "
        "(missing %s under %s) and no mirror was reachable:\n  %s\n"
        "Download the files manually into that directory and re-run."
        % (name, ", ".join(missing), directory,
           "\n  ".join(errors) or "no sources configured"))


#: accuracy slack vs the reference baseline before a row reads CHECK
TOLERANCE_PT = 0.15


def _train_n_minibatches(wf, n):
    """Run the workflow's dataflow loop but stop after the loader has
    served ``n`` minibatches (NoMoreJobs unwinds the engine cleanly —
    the same mechanism the reference master uses, decision.py:218-220).
    Works for both execution modes: the fused trainer's window
    collection drives loader.run() directly (each collected minibatch
    counts), and the nth fill forces ``last_minibatch`` so an OPEN scan
    window flushes its stats through the evaluator/decision before the
    stop."""
    from znicz_tpu.core.workflow import NoMoreJobs
    loader = wf.loader
    count = [0]
    real_run = loader.run

    def limited_run():
        if count[0] >= n:
            raise NoMoreJobs()
        count[0] += 1
        real_run()
        if count[0] >= n:
            loader.last_minibatch <<= True

    loader.run = limited_run
    try:
        wf.run()
    finally:
        loader.run = real_run


def _cross_check(module, build_kwargs, loader_config, fused_cfg,
                 device, n_minibatches=16):
    """Train the FIRST ``n_minibatches`` on both execution modes from the
    same seeds and compare the observed training error rates — a cheap
    wiring check (labels, objective, gather, window bookkeeping) so the
    fast fused parity run stays validated against the unit path.  Exact
    float64 trajectory equality is pinned offline
    (tests/functional/test_fused_window.py); this guards the REAL-data
    run against configuration drift, so the tolerance is loose (bf16 vs
    f32 diverge numerically within a few minibatches)."""
    from znicz_tpu.core import prng

    from znicz_tpu.loader.base import TRAIN

    def train(fused):
        prng.get(1).seed(1234)
        prng.get(2).seed(5678)
        kwargs = dict(build_kwargs)
        if fused is not None:
            kwargs["fused"] = dict(fused)
        wf = module.build(loader_config=dict(loader_config), **kwargs)
        wf.initialize(device=device)
        _train_n_minibatches(wf, n_minibatches)
        # the forced segment boundary made the decision record the
        # partial-segment stats (the evaluator accumulators are reset
        # by that same bookkeeping)
        errs = wf.decision.epoch_n_err[TRAIN] or 0
        total = wf.decision.epoch_n_evaluated_samples[TRAIN]
        return errs / max(total, 1), total

    rate_f, seen_f = train(fused_cfg)
    rate_u, seen_u = train(None)
    if seen_f == 0 or seen_u == 0:
        raise SystemExit("parity cross-check saw no training samples")
    if abs(rate_f - rate_u) > 0.05:
        raise SystemExit(
            "parity cross-check FAILED: first-%d-minibatch train error "
            "%.3f (fused) vs %.3f (unit graph) — the fast path is "
            "mis-wired; rerun with --fused window=1 or file the "
            "divergence" % (n_minibatches, rate_f, rate_u))
    print("cross-check ok: first %d minibatches, train err %.3f (fused) "
          "vs %.3f (unit graph)" % (n_minibatches, rate_f, rate_u))


def run_parity(sample, device=None, data_dir=None, fused="auto",
               cross_check=16):
    """Provision data, train every parity config of ``sample`` to its
    stopping criterion, print the comparison table.  Returns the rows as
    (label, reference_err_pt, our_err_pt).

    Parity runs train on the FUSED path (compiled scan windows, bf16
    GEMMs + f32 master weights) so the real-data bar is a
    minutes-not-days command; a short unit-path cross-check validates
    the wiring first, and a row missing the accuracy bar in bf16 is
    retrained in f32 before it reads CHECK.  ``fused=None`` forces the
    unit-graph path; a dict overrides the fused config (e.g.
    ``{"window": 1}``)."""
    if sample not in PARITY_RUNS:
        raise SystemExit(
            "no parity baseline registered for %r (have: %s)"
            % (sample, ", ".join(sorted(PARITY_RUNS))))
    data_dir = ensure_dataset(sample, directory=data_dir)
    import importlib
    module = importlib.import_module("znicz_tpu.samples." + sample)
    if fused == "auto" or fused is True:
        # bare `--parity --fused` == the default fused parity config
        import jax.numpy as jnp
        fused = {"compute_dtype": jnp.bfloat16}
    loader_config = {"synthetic": False, "data_path": data_dir}
    rows = []
    for label, ref_err, opts in PARITY_RUNS[sample]:
        kwargs = {}
        layers_key = opts.get("layers_key")
        if layers_key is not None:
            kwargs["layers"] = getattr(root, layers_key).layers
        if fused is not None and cross_check:
            _cross_check(module, kwargs, loader_config, fused, device,
                         n_minibatches=cross_check)

        def train_full(fused_cfg):
            from znicz_tpu.core import prng
            prng.get(1).seed(1234)
            prng.get(2).seed(5678)
            kw = dict(kwargs)
            if fused_cfg is not None:
                kw["fused"] = dict(fused_cfg)
            wf = module.build(loader_config=dict(loader_config), **kw)
            wf.initialize(device=device)
            wf.run()
            return wf.decision.best_n_err_pt[1]

        ours = train_full(fused)
        if fused is None:
            mode = "unit graph"
        elif fused.get("compute_dtype") is not None:
            mode = "fused bf16"
        else:
            mode = "fused f32"
        if (fused is not None and fused.get("compute_dtype") is not None
                and (ours is None or ours > ref_err + TOLERANCE_PT)):
            # bf16 missed the bar — retrain the row in f32 on the same
            # compiled path before conceding
            print("| %-22s | bf16 %s missed %.2f%% bar; retrying f32 |"
                  % (label, "%.2f%%" % ours if ours is not None else "n/a",
                     ref_err))
            f32_cfg = dict(fused, compute_dtype=None)
            ours_f32 = train_full(f32_cfg)
            if ours is None or (ours_f32 is not None and ours_f32 < ours):
                ours, mode = ours_f32, "fused f32"
        rows.append((label, ref_err, ours))
        print("| %-22s | reference %6.2f%% | ours %8s (%s) | %s |"
              % (label, ref_err,
                 "%.2f%%" % ours if ours is not None else "n/a", mode,
                 "PASS" if ours is not None and
                 ours <= ref_err + TOLERANCE_PT else "CHECK"))
    return rows
