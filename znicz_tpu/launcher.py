"""Workflow launcher — the reference ``run(load, main)`` contract + CLI
backend.

Reference contract (every sample module ends with it — samples/MNIST/
mnist.py:128-137, samples/Wine/wine.py:178-181): the veles CLI imports the
workflow module and calls ``module.run(load, main)`` where

* ``load(factory, **kwargs) -> (workflow, snapshot_loaded)`` constructs
  the workflow — or marks it for restoration when the launcher carries a
  ``--snapshot`` path;
* ``main(**kwargs)`` initializes (forwarding kwargs), applies any pending
  snapshot state, and runs.

The reference launcher's other role — master/slave distribution over
sockets (veles launcher.py, nn_units.py:178-211 broadcast/aggregate) — is
deliberately NOT reproduced: the TPU-native equivalent is SPMD over a
``jax.sharding.Mesh`` (:mod:`znicz_tpu.parallel`), where XLA's collectives
replace the parameter-server cycle.  This launcher runs the unit-graph
control plane in one process, standalone.
"""

import importlib
import importlib.util
import os

from znicz_tpu.core.logger import Logger


class Launcher(Logger):
    """Standalone launcher implementing ``load``/``main``.

    Modes:
    * ``testing`` — forward-only run (the reference ``--test`` flag):
      after initialize, decision/loader are put into testing mode when
      they support it;
    * ``dry_run`` — build + initialize only, skip ``run()``;
    * ``snapshot`` — path of a :class:`SnapshotterToFile` pickle to
      restore into the freshly-built workflow before running.
    """

    def __init__(self, testing=False, snapshot=None, device=None,
                 dry_run=False, fused=None, auto_resume=False):
        super(Launcher, self).__init__(logger_name="Launcher")
        # multi-host SPMD: bring up jax.distributed from the env
        # (JAX_COORDINATOR_ADDRESS/JAX_NUM_PROCESSES/JAX_PROCESS_ID or
        # a managed-cluster runtime) BEFORE any backend use; a no-op
        # for single-process runs.  A failed init degrades to
        # single-process ONLY for autodetected cluster markers (a stale
        # SLURM_JOB_ID in an interactive shell); with an EXPLICIT
        # JAX_COORDINATOR_ADDRESS/JAX_NUM_PROCESSES config it stays
        # fatal — silently training unsynced on one host while the
        # gang expects gradient sync would corrupt the job
        import os as _os
        from znicz_tpu.parallel import multihost
        explicit = bool(_os.environ.get("JAX_COORDINATOR_ADDRESS")
                        or _os.environ.get("JAX_NUM_PROCESSES"))
        try:
            up = multihost.initialize()
        except Exception as e:
            if explicit:
                raise
            self.warning("jax.distributed init failed (%s); continuing "
                         "single-process", e)
            up = False
        if up:
            self.info("jax.distributed up: process %d of %d",
                      __import__("jax").process_index(),
                      __import__("jax").process_count())
        self.testing = testing
        self.snapshot_path = snapshot
        self.device = device
        self.dry_run = dry_run
        #: fused execution mode forwarded to StandardWorkflow-based
        #: samples (True or a config dict — see link_fused_trainer)
        self.fused = fused
        #: job-level elastic recovery (reference slave-loss semantics
        #: re-provided at the job level, SURVEY.md §2.8 / nn_rollback.py
        #: 87-97): on start, restore the NEWEST matching snapshot the
        #: workflow's snapshotter would have written, fast-forward (the
        #: snapshot carries loader position + PRNG streams + optimizer
        #: state) and continue training
        self.auto_resume = auto_resume
        self.workflow = None
        self.interactive = False
        self._state = None

    # -- the role the workflow sees (reference Launcher interface) ----------
    @property
    def is_master(self):
        return False

    @property
    def is_slave(self):
        return False

    @property
    def is_standalone(self):
        return True

    def add_unit(self, unit):
        # a Workflow constructed with the launcher as parent registers here
        self.workflow = unit

    add_ref = add_unit

    def del_ref(self, unit):
        pass

    # -- run(load, main) contract -------------------------------------------
    def load(self, factory, **kwargs):
        """Construct the workflow.  ``factory`` is a Workflow subclass
        (instantiated with this launcher as parent) or a builder callable
        returning the workflow.  Returns (workflow, snapshot_loaded)."""
        if self.snapshot_path:
            from znicz_tpu.core.snapshotter import SnapshotterToFile
            self._state = SnapshotterToFile.import_(self.snapshot_path)
            self.info("will restore snapshot %s", self.snapshot_path)
        if self.fused is not None:
            kwargs.setdefault("fused", self.fused)
        if isinstance(factory, type):
            wf = factory(self, **kwargs)
        else:
            wf = factory(**kwargs)
        self.workflow = wf
        if self.fused is not None and \
                getattr(wf, "fused_trainer", None) is None:
            self.warning("--fused requested but %s does not build a "
                         "fused trainer (hand-wired workflow?); running "
                         "the unit-graph path", type(wf).__name__)
        return wf, self._state is not None

    def _snapshot_incompatible(self, state, wf):
        """Reason the snapshot cannot be applied to ``wf`` (None = OK):
        a different workflow class, or any exported Array whose shape
        differs from the live one (e.g. the same snapshot prefix used by
        two topologies) — applying blindly would corrupt state or crash
        deep inside the first train step."""
        import numpy
        from znicz_tpu.core.memory import Array
        snap_wf = state.get("workflow")
        if snap_wf not in (None, type(wf).__name__):
            return "workflow class %r != %r" % (snap_wf,
                                                type(wf).__name__)
        units = {u.name: u for u in wf.units}
        for uname, ustate in state.get("units", {}).items():
            u = units.get(uname)
            if u is None:
                continue
            for attr, value in ustate.items():
                if value is None:
                    continue
                if attr == "epoch_acc":
                    # mid-epoch accumulator capture: validate against
                    # the net's zero-acc layout (host-side shapes — the
                    # live getattr would force a device drain per
                    # candidate).  A lead-dim mismatch means a
                    # different data-shard count; resuming it would
                    # crash the first window dispatch and, under
                    # run_supervised, burn every restart on the same
                    # bad snapshot instead of falling back
                    net = getattr(u, "net", None)
                    if net is None or not isinstance(value, dict):
                        continue
                    expect = net.window_acc_zeros()
                    for leaf, zero in expect.items():
                        got = value.get(leaf)
                        if got is None or \
                                tuple(numpy.shape(got)) != zero.shape:
                            return "unit %s.epoch_acc[%s] shape %s " \
                                "!= %s" % (
                                    uname, leaf,
                                    None if got is None
                                    else tuple(numpy.shape(got)),
                                    zero.shape)
                    continue
                cur = getattr(u, attr, None)
                if isinstance(cur, Array) and cur and \
                        tuple(cur.shape) != tuple(numpy.shape(value)):
                    return "unit %s.%s shape %s != %s" % (
                        uname, attr, numpy.shape(value), tuple(cur.shape))
                if attr == "fused_state" and isinstance(value, dict) and \
                        getattr(u, "net", None) is not None:
                    cur_sd = u.fused_state
                    snap_params = list(value.get("params", ()))
                    # zip would truncate: a different topology with
                    # fewer/more layers whose leading shapes agree must
                    # still be rejected (ADVICE r4 medium)
                    if len(snap_params) != len(cur_sd["params"]):
                        return ("fused layer count %d != %d"
                                % (len(snap_params),
                                   len(cur_sd["params"])))
                    for p_cur, p_new in zip(cur_sd["params"],
                                            snap_params):
                        if set(p_cur) != set(p_new):
                            return ("fused param keys %s != %s"
                                    % (sorted(p_new), sorted(p_cur)))
                        for k in p_cur:
                            if numpy.shape(p_cur[k]) != \
                                    numpy.shape(p_new[k]):
                                return ("fused param shape %s != %s"
                                        % (numpy.shape(p_new[k]),
                                           numpy.shape(p_cur[k])))
        # shape agreement is not enough: a DIFFERENT topology under the
        # same snapshot prefix has disjoint unit names, every check
        # above passes vacuously, and "resume" would restore epoch
        # bookkeeping with freshly random weights.  Require the snapshot
        # to actually cover the workflow's trainable state (directly or
        # via the cross-mode fused<->unit-graph mapping).
        forwards = [f for f in getattr(wf, "forwards", ())]
        has_fused_state = any(
            isinstance(us.get("fused_state"), dict)
            for us in state.get("units", {}).values())
        has_unit_weights = any(
            us.get("weights") is not None
            for us in state.get("units", {}).values())
        trainable = [f for f in forwards
                     if getattr(f, "weights", None) is not None
                     and f.weights] or \
                    ([wf.fused_trainer]
                     if getattr(wf, "fused_trainer", None) is not None
                     else [])
        if trainable and not (has_fused_state or has_unit_weights):
            return "snapshot carries no trainable weights"
        if trainable and has_unit_weights and not has_fused_state:
            trainer = getattr(wf, "fused_trainer", None)
            if trainer is None:
                covered = sum(
                    1 for f in forwards
                    if state.get("units", {}).get(f.name, {})
                    .get("weights") is not None)
            else:
                # fused target: the cross-mode map looks the layers up
                # by their unit-graph forward names
                covered = 0
                for i, layer in enumerate(trainer.layers):
                    name = (layer["name"] + "_forward") \
                        if "name" in layer \
                        else "%s_%d_forward" % (layer.get("type"), i)
                    if state.get("units", {}).get(name, {}) \
                            .get("weights") is not None:
                        covered += 1
            if not covered:
                return ("snapshot's unit names cover none of this "
                        "workflow's layers (different topology under "
                        "the same prefix?)")
        return None

    def _find_resume_state(self, wf):
        """Newest importable AND compatible snapshot matching the
        workflow's snapshotter prefix/directory; corrupt files (a crash
        can interrupt even an atomic-rename write of the PREVIOUS run's
        file on some systems) and incompatible topologies are skipped
        newest-first."""
        from znicz_tpu.core.snapshotter import SnapshotterToFile
        snap = getattr(wf, "snapshotter", None)
        if snap is None:
            self.warning("--auto-resume: workflow has no snapshotter")
            return None
        from znicz_tpu.core import telemetry
        for path in snapshot_candidates(snap.directory, snap.prefix):
            try:
                state = SnapshotterToFile.import_(path)
            except Exception as e:  # noqa: BLE001 - corrupt snapshot
                self.warning("auto-resume: skipping unreadable snapshot "
                             "%s (%s)", path, e)
                telemetry.record_event("resume.skipped", path=path,
                                       why="unreadable",
                                       error=repr(e))
                continue
            reason = self._snapshot_incompatible(state, wf)
            if reason:
                self.warning("auto-resume: skipping incompatible "
                             "snapshot %s (%s)", path, reason)
                telemetry.record_event("resume.skipped", path=path,
                                       why="incompatible",
                                       reason=reason)
                continue
            self.info("auto-resume: restoring %s", path)
            return state
        return None

    def main(self, **kwargs):
        """Initialize (+restore), then run unless dry_run."""
        wf = self.workflow
        if wf is None:
            raise RuntimeError("main() before load()")
        wf.initialize(device=self.device, **kwargs)
        if self.auto_resume:
            found = self._find_resume_state(wf)
            if found is not None:
                # the newest resumable state wins over an explicit
                # --snapshot (which stays the fallback seed): a
                # supervised restart that crashed BEFORE the first new
                # snapshot write must re-enter the user's warm start,
                # and one that crashed after must continue the run,
                # not rewind to the seed
                self._state = found
            elif self._state is not None:
                self.info("auto-resume: no resumable snapshot; "
                          "falling back to explicit snapshot %s",
                          self.snapshot_path)
        if self._state is not None:
            from znicz_tpu.units.nn_units import load_snapshot_into_workflow
            load_snapshot_into_workflow(self._state, wf)
        if self.testing:
            for unit in wf.units:
                if hasattr(unit, "testing"):
                    unit.testing = True
        if not self.dry_run:
            from znicz_tpu.core import telemetry
            # black-box the run: SIGTERM and unhandled exceptions dump
            # the flight recorder + metrics + traceback to a crash
            # directory (only when telemetry/health journaling is on)
            telemetry.install_crash_handler()
            try:
                wf.run()
            except Exception as e:
                if telemetry.journal_enabled() and \
                        getattr(e, "crash_report", None) is None:
                    # the health halt policy already wrote its own
                    import sys
                    path = telemetry.write_crash_report(
                        reason="workflow run failed: %r" % e,
                        exc_info=sys.exc_info())
                    try:
                        # tag it so the sys.excepthook crash handler
                        # does not write a SECOND report for the same
                        # exception on its way out
                        e.crash_report = path
                    except AttributeError:  # __slots__ exception type
                        pass
                raise
        return wf


def snapshot_candidates(directory, prefix):
    """Snapshot paths under ``directory`` matching the snapshotter
    naming scheme for ``prefix``, newest first — the one listing shared
    by ``--auto-resume`` (Launcher) and ``serve --latest``
    (znicz_tpu.serving).  In-flight ``.part`` files are excluded."""
    if not directory or not os.path.isdir(directory):
        return []
    cands = [os.path.join(directory, f) for f in os.listdir(directory)
             if f.startswith(prefix + "_")
             and ".pickle" in f and not f.endswith(".part")]
    cands.sort(key=os.path.getmtime, reverse=True)
    return cands


def newest_snapshot(directory, prefix):
    """The newest snapshot for ``prefix`` (None when there is none)."""
    cands = snapshot_candidates(directory, prefix)
    return cands[0] if cands else None


def resolve_workflow_module(spec):
    """CLI workflow argument -> imported module.

    Accepts a file path (``samples/mnist.py``), a dotted module name
    (``znicz_tpu.samples.mnist``), or a bare registered sample name
    (``mnist``)."""
    if os.path.sep in spec or spec.endswith(".py"):
        path = os.path.abspath(spec)
        name = os.path.splitext(os.path.basename(path))[0]
        module_spec = importlib.util.spec_from_file_location(name, path)
        module = importlib.util.module_from_spec(module_spec)
        module_spec.loader.exec_module(module)
        return module
    try:
        return importlib.import_module(spec)
    except ImportError as e:
        # fall back to the samples namespace only when SPEC itself was
        # not found (for dotted names like "research.stl10" the error
        # names the unresolvable first component).  A spec already under
        # the project namespace never falls back: its ImportErrors come
        # from INSIDE the module and must surface.
        first = spec.split(".")[0]
        if spec.startswith("znicz_tpu") or \
                e.name not in (spec, first) or first == "znicz_tpu":
            raise
        return importlib.import_module("znicz_tpu.samples." + spec)


def list_samples():
    """Registered sample names (modules under znicz_tpu.samples,
    including the research tier as ``research.<name>``)."""
    import znicz_tpu.samples as samples_pkg
    pkg_dir = os.path.dirname(samples_pkg.__file__)
    names = []
    for prefix, directory in (("", pkg_dir),
                              ("research.",
                               os.path.join(pkg_dir, "research"))):
        if not os.path.isdir(directory):
            continue
        for fn in sorted(os.listdir(directory)):
            if fn.endswith(".py") and not fn.startswith("_"):
                names.append(prefix + fn[:-3])
    return names


def run_workflow(spec, snapshot=None, testing=False, dry_run=False,
                 device=None, fused=None, auto_resume=False):
    """Drive a workflow module's ``run(load, main)``.

    ``spec`` is a module object or anything
    :func:`resolve_workflow_module` accepts.  Falls back to the module's
    ``run_sample()`` when no ``run`` is exported (plain-run only — the
    fallback cannot honor snapshot/testing/dry_run).  Returns the
    workflow."""
    module = spec if hasattr(spec, "__file__") else \
        resolve_workflow_module(spec)
    launcher = Launcher(testing=testing, snapshot=snapshot,
                        device=device, dry_run=dry_run, fused=fused,
                        auto_resume=auto_resume)
    if hasattr(module, "run"):
        module.run(launcher.load, launcher.main)
        return launcher.workflow
    if hasattr(module, "run_sample"):
        if snapshot or testing or dry_run or fused is not None \
                or auto_resume:
            raise SystemExit(
                "%s exposes only run_sample(); --snapshot/--testing/"
                "--dry-run/--fused/--auto-resume need the "
                "run(load, main) contract" % spec)
        return module.run_sample(device=device)
    raise SystemExit(
        "%s exposes neither run(load, main) nor run_sample()" % spec)


def run_supervised(spec, max_restarts=0, restart_backoff_ms=1000.0,
                   restart_backoff_max_ms=30000.0, snapshot=None,
                   testing=False, dry_run=False, device=None, fused=None,
                   auto_resume=False):
    """Supervised :func:`run_workflow`: a crashed run is caught, backed
    off (exponentially, ``restart_backoff_ms * 2**attempt`` capped at
    ``restart_backoff_max_ms``) and re-entered up to ``max_restarts``
    times with ``auto_resume`` forced on — the restarted attempt
    rebuilds the workflow and restores the newest readable snapshot,
    including mid-epoch ``window_interval`` captures, so a preempted
    training run continues instead of restarting the epoch.

    The job-level twin of the reference's slave-loss recovery
    (a worker dies, the master re-issues its work): here the whole
    process is the worker and the snapshot directory is the master.

    Deliberately NOT restarted:

    * ``KeyboardInterrupt`` / ``SystemExit`` — operator intent;
    * :class:`~znicz_tpu.core.health.HealthViolationError` — the halt
      policy asked to stop; resuming would replay into the same
      violation, forever.

    Each restart is metered (``launcher.restarts`` counter) and
    journaled (``launcher.restart`` events carry the attempt number,
    the error and the backoff).  Returns the finished workflow.
    """
    import time

    from znicz_tpu.core import telemetry
    from znicz_tpu.core.health import HealthViolationError
    from znicz_tpu.core.logger import Logger

    log = Logger(logger_name="Supervisor")
    attempt = 0
    while True:
        try:
            # the explicit snapshot rides along on EVERY attempt: with
            # auto-resume forced on, a restart prefers the newest
            # resumable snapshot but a crash before the first write
            # falls back to the user's warm start instead of fresh
            # random weights
            return run_workflow(
                spec, snapshot=snapshot,
                testing=testing, dry_run=dry_run, device=device,
                fused=fused, auto_resume=auto_resume or attempt > 0)
        except (KeyboardInterrupt, SystemExit):
            raise
        except HealthViolationError:
            raise
        except Exception as e:  # noqa: BLE001 - the supervised surface
            attempt += 1
            if attempt > max_restarts:
                raise
            delay = min(float(restart_backoff_ms) / 1e3
                        * (2 ** (attempt - 1)),
                        float(restart_backoff_max_ms) / 1e3)
            if telemetry.enabled():
                telemetry.counter("launcher.restarts").inc()
            telemetry.record_event("launcher.restart", attempt=attempt,
                                   max_restarts=max_restarts,
                                   error=repr(e),
                                   backoff_ms=round(delay * 1e3, 3))
            log.warning(
                "run crashed (%r); restart %d/%d with auto-resume in "
                "%.1f s", e, attempt, max_restarts, delay)
            if delay > 0:
                time.sleep(delay)
