"""Workflow launcher — the reference ``run(load, main)`` contract + CLI
backend.

Reference contract (every sample module ends with it — samples/MNIST/
mnist.py:128-137, samples/Wine/wine.py:178-181): the veles CLI imports the
workflow module and calls ``module.run(load, main)`` where

* ``load(factory, **kwargs) -> (workflow, snapshot_loaded)`` constructs
  the workflow — or marks it for restoration when the launcher carries a
  ``--snapshot`` path;
* ``main(**kwargs)`` initializes (forwarding kwargs), applies any pending
  snapshot state, and runs.

The reference launcher's other role — master/slave distribution over
sockets (veles launcher.py, nn_units.py:178-211 broadcast/aggregate) — is
deliberately NOT reproduced: the TPU-native equivalent is SPMD over a
``jax.sharding.Mesh`` (:mod:`znicz_tpu.parallel`), where XLA's collectives
replace the parameter-server cycle.  This launcher runs the unit-graph
control plane in one process, standalone.
"""

import importlib
import importlib.util
import os

from znicz_tpu.core.logger import Logger


class Launcher(Logger):
    """Standalone launcher implementing ``load``/``main``.

    Modes:
    * ``testing`` — forward-only run (the reference ``--test`` flag):
      after initialize, decision/loader are put into testing mode when
      they support it;
    * ``dry_run`` — build + initialize only, skip ``run()``;
    * ``snapshot`` — path of a :class:`SnapshotterToFile` pickle to
      restore into the freshly-built workflow before running.
    """

    def __init__(self, testing=False, snapshot=None, device=None,
                 dry_run=False, fused=None):
        super(Launcher, self).__init__(logger_name="Launcher")
        self.testing = testing
        self.snapshot_path = snapshot
        self.device = device
        self.dry_run = dry_run
        #: fused execution mode forwarded to StandardWorkflow-based
        #: samples (True or a config dict — see link_fused_trainer)
        self.fused = fused
        self.workflow = None
        self.interactive = False
        self._state = None

    # -- the role the workflow sees (reference Launcher interface) ----------
    @property
    def is_master(self):
        return False

    @property
    def is_slave(self):
        return False

    @property
    def is_standalone(self):
        return True

    def add_unit(self, unit):
        # a Workflow constructed with the launcher as parent registers here
        self.workflow = unit

    add_ref = add_unit

    def del_ref(self, unit):
        pass

    # -- run(load, main) contract -------------------------------------------
    def load(self, factory, **kwargs):
        """Construct the workflow.  ``factory`` is a Workflow subclass
        (instantiated with this launcher as parent) or a builder callable
        returning the workflow.  Returns (workflow, snapshot_loaded)."""
        if self.snapshot_path:
            from znicz_tpu.core.snapshotter import SnapshotterToFile
            self._state = SnapshotterToFile.import_(self.snapshot_path)
            self.info("will restore snapshot %s", self.snapshot_path)
        if self.fused is not None:
            kwargs.setdefault("fused", self.fused)
        if isinstance(factory, type):
            wf = factory(self, **kwargs)
        else:
            wf = factory(**kwargs)
        self.workflow = wf
        if self.fused is not None and \
                getattr(wf, "fused_trainer", None) is None:
            self.warning("--fused requested but %s does not build a "
                         "fused trainer (hand-wired workflow?); running "
                         "the unit-graph path", type(wf).__name__)
        return wf, self._state is not None

    def main(self, **kwargs):
        """Initialize (+restore), then run unless dry_run."""
        wf = self.workflow
        if wf is None:
            raise RuntimeError("main() before load()")
        wf.initialize(device=self.device, **kwargs)
        if self._state is not None:
            from znicz_tpu.units.nn_units import load_snapshot_into_workflow
            load_snapshot_into_workflow(self._state, wf)
        if self.testing:
            for unit in wf.units:
                if hasattr(unit, "testing"):
                    unit.testing = True
        if not self.dry_run:
            wf.run()
        return wf


def resolve_workflow_module(spec):
    """CLI workflow argument -> imported module.

    Accepts a file path (``samples/mnist.py``), a dotted module name
    (``znicz_tpu.samples.mnist``), or a bare registered sample name
    (``mnist``)."""
    if os.path.sep in spec or spec.endswith(".py"):
        path = os.path.abspath(spec)
        name = os.path.splitext(os.path.basename(path))[0]
        module_spec = importlib.util.spec_from_file_location(name, path)
        module = importlib.util.module_from_spec(module_spec)
        module_spec.loader.exec_module(module)
        return module
    try:
        return importlib.import_module(spec)
    except ImportError as e:
        # fall back to the samples namespace only when SPEC itself was
        # not found (for dotted names like "research.stl10" the error
        # names the unresolvable first component).  A spec already under
        # the project namespace never falls back: its ImportErrors come
        # from INSIDE the module and must surface.
        first = spec.split(".")[0]
        if spec.startswith("znicz_tpu") or \
                e.name not in (spec, first) or first == "znicz_tpu":
            raise
        return importlib.import_module("znicz_tpu.samples." + spec)


def list_samples():
    """Registered sample names (modules under znicz_tpu.samples,
    including the research tier as ``research.<name>``)."""
    import znicz_tpu.samples as samples_pkg
    pkg_dir = os.path.dirname(samples_pkg.__file__)
    names = []
    for prefix, directory in (("", pkg_dir),
                              ("research.",
                               os.path.join(pkg_dir, "research"))):
        if not os.path.isdir(directory):
            continue
        for fn in sorted(os.listdir(directory)):
            if fn.endswith(".py") and not fn.startswith("_"):
                names.append(prefix + fn[:-3])
    return names


def run_workflow(spec, snapshot=None, testing=False, dry_run=False,
                 device=None, fused=None):
    """Drive a workflow module's ``run(load, main)``.

    ``spec`` is a module object or anything
    :func:`resolve_workflow_module` accepts.  Falls back to the module's
    ``run_sample()`` when no ``run`` is exported (plain-run only — the
    fallback cannot honor snapshot/testing/dry_run).  Returns the
    workflow."""
    module = spec if hasattr(spec, "__file__") else \
        resolve_workflow_module(spec)
    launcher = Launcher(testing=testing, snapshot=snapshot,
                        device=device, dry_run=dry_run, fused=fused)
    if hasattr(module, "run"):
        module.run(launcher.load, launcher.main)
        return launcher.workflow
    if hasattr(module, "run_sample"):
        if snapshot or testing or dry_run or fused is not None:
            raise SystemExit(
                "%s exposes only run_sample(); --snapshot/--testing/"
                "--dry-run/--fused need the run(load, main) contract"
                % spec)
        return module.run_sample(device=device)
    raise SystemExit(
        "%s exposes neither run(load, main) nor run_sample()" % spec)
