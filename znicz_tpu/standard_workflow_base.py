"""Self-constructing workflow base — forward chain from declarative config.

TPU-era equivalent of reference standard_workflow_base.py (456 LoC —
SURVEY.md §2.1).  A ``layers`` config is a list of dicts::

    {"type": "conv", "->": {forward kwargs}, "<-": {backward kwargs},
     other: shared kwargs}

plus the mcdnnic topology shorthand ``"784x28x28-32C5-MP2-100N-10N"``
(reference standard_workflow_base.py:72-270).  Forward units are created
from the type-string registry and auto-chained; the softmax head's width is
auto-set from the loader's label count.
"""

import re

import numpy

from znicz_tpu.loader.base import UserLoaderRegistry
from znicz_tpu.units import nn_units
from znicz_tpu.units.all2all import All2AllSoftmax
from znicz_tpu.units.dropout import DropoutForward


class StandardWorkflowBase(nn_units.NNWorkflow):
    """Builds the forward chain from the ``layers`` config
    (reference standard_workflow_base.py:59-456)."""

    mcdnnic_layer_pattern = re.compile(
        r"(?P<C>\d+C\d+)|(?P<MP>MP\d+)|(?P<N>\d+N)")

    def __init__(self, workflow=None, **kwargs):
        super(StandardWorkflowBase, self).__init__(workflow, **kwargs)
        self.layer_map = nn_units.mapping
        self.preprocessing = kwargs.get("preprocessing", False)
        # fused execution mode: collapse forwards+gds into one jitted
        # SPMD train-step unit (True or a config dict; see
        # StandardWorkflow.link_fused_trainer)
        fused_cfg = kwargs.get("fused", None)
        if fused_cfg is True:
            fused_cfg = {}
        elif fused_cfg is False:
            fused_cfg = None
        self.fused_config = fused_cfg
        self.fused_trainer = None
        self.mcdnnic_topology = kwargs.get("mcdnnic_topology", None)
        self.mcdnnic_parameters = kwargs.get("mcdnnic_parameters", None)
        self.layers = kwargs.get("layers", [{}])
        self.loader_config = dict(self.dictify(
            kwargs.get("loader_config", {})))
        self._loader_name = None
        self._loader_factory = None
        self.real_loader = None
        if "loader_name" in kwargs:
            self.loader_name = kwargs["loader_name"]
        elif "loader_factory" in kwargs:
            self.loader_factory = kwargs["loader_factory"]

    # -- config plumbing ----------------------------------------------------
    @staticmethod
    def dictify(obj):
        return getattr(obj, "__content__", obj)

    def config2kwargs(self, unit_config):
        return {} if unit_config is None else dict(self.dictify(unit_config))

    @property
    def loader_name(self):
        return self._loader_name

    @loader_name.setter
    def loader_name(self, value):
        if value is None:
            self._loader_name = None
            return
        kwargs = dict(self.loader_config)
        if self.mcdnnic_topology is not None:
            kwargs = self._update_loader_kwargs_from_mcdnnic(
                kwargs, self.mcdnnic_topology)
        kls = UserLoaderRegistry.get_factory(value)
        self._loader_factory = lambda wf: kls(wf, name="loader", **kwargs)
        self._loader_name = value

    @property
    def loader_factory(self):
        return self._loader_factory

    @loader_factory.setter
    def loader_factory(self, value):
        if not callable(value):
            raise TypeError("loader_factory must be callable")
        self._loader_name = None
        self._loader_factory = value

    # -- layers config ------------------------------------------------------
    @property
    def layers(self):
        if self.mcdnnic_topology is not None:
            return self._get_layers_from_mcdnnic(self.mcdnnic_topology)
        return self._layers

    @layers.setter
    def layers(self, value):
        if self.mcdnnic_topology is not None and value != [{}]:
            raise ValueError(
                "Do not set mcdnnic_topology and layers at the same time")
        if not isinstance(value, list) or \
                any(not isinstance(l, dict) for l in value):
            raise ValueError("layers should be a list of dicts")
        if (value == [{}] and self.mcdnnic_topology is None and
                not self.preprocessing):
            raise ValueError(
                "layers is empty and mcdnnic_topology is not defined")
        self._layers = value

    # -- mcdnnic topology parser (reference 218-270) ------------------------
    def _get_mcdnnic_parameters(self, arrow):
        params = self.mcdnnic_parameters or {}
        return dict(params.get(arrow, {}))

    @staticmethod
    def _parse_mcdnnic_c(is_last, value):
        kernels, kx = value.split("C")
        return {"type": "conv",
                "->": {"n_kernels": int(kernels), "kx": int(kx),
                       "ky": int(kx)}}

    @staticmethod
    def _parse_mcdnnic_mp(is_last, value):
        _, kx = value.split("MP")
        return {"type": "max_pooling", "->": {"kx": int(kx), "ky": int(kx)}}

    @staticmethod
    def _parse_mcdnnic_n(is_last, value):
        neurons, _ = value.split("N")
        tpe = "softmax" if is_last else "all2all"
        return {"type": tpe, "->": {"output_sample_shape": int(neurons)}}

    def _get_layers_from_mcdnnic(self, description):
        layers = []
        fwd_params = self._get_mcdnnic_parameters("->")
        bwd_params = self._get_mcdnnic_parameters("<-")
        parse = {"C": self._parse_mcdnnic_c, "MP": self._parse_mcdnnic_mp,
                 "N": self._parse_mcdnnic_n}
        matches = tuple(re.finditer(self.mcdnnic_layer_pattern, description))
        for index, match in enumerate(matches):
            name = next(n for n, v in match.groupdict().items() if v)
            cfg = parse[name](index == len(matches) - 1, match.group(name))
            cfg["->"].update(fwd_params)
            cfg["<-"] = dict(bwd_params)
            layers.append(cfg)
        return layers

    @staticmethod
    def _update_loader_kwargs_from_mcdnnic(kwargs, description):
        inp = description.split("-")[0]
        minibatch_size, y_size, x_size = inp.split("x")
        kwargs["minibatch_size"] = int(minibatch_size)
        kwargs["scale"] = (int(y_size), int(x_size))
        return kwargs

    # -- layer instantiation ------------------------------------------------
    def _get_layer_type_kwargs(self, layer, index=None):
        """Split one layer dict into (type, forward kwargs, backward kwargs)
        (reference standard_workflow_base.py:406-422)."""
        tpe = layer.get("type", "").strip()
        if not tpe:
            raise ValueError("layer type must not be an empty string")
        if tpe not in self.layer_map:
            raise ValueError("Unknown layer type %r" % tpe)
        kwargs_forward = dict(layer.get("->", {}))
        kwargs_backward = dict(layer.get("<-", {}))
        others = {k: v for k, v in layer.items()
                  if k not in ("type", "->", "<-", "name")}
        kwargs_forward.update(others)
        kwargs_backward.update(others)
        if "name" in layer:
            kwargs_forward["name"] = layer["name"] + "_forward"
            kwargs_backward["name"] = "gd_" + layer["name"]
        elif index is not None:
            # unnamed layers get INDEX-unique names: class-name defaults
            # collide for duplicate layer types, silently merging their
            # snapshot state and any per-unit stats keyed by name
            kwargs_forward.setdefault("name", "%s_%d_forward"
                                      % (tpe, index))
            kwargs_backward.setdefault("name", "gd_%s_%d" % (tpe, index))
        return tpe, kwargs_forward, kwargs_backward

    # -- graph construction -------------------------------------------------
    def link_repeater(self, *parents):
        self.repeater.link_from(*parents)
        return self.repeater

    def link_loader(self, *parents):
        if self.loader_factory is None:
            raise ValueError(
                "no loader: pass loader_name= or loader_factory=")
        self.loader = self.loader_factory(self)
        self.loader.link_from(*parents)
        self.real_loader = self.loader
        return self.loader

    def link_forwards(self, init_attrs, *parents):
        """Create + chain forward units (reference 272-336)."""
        del self.forwards[:]
        for index, layer in enumerate(self.layers):
            tpe, kwargs, _ = self._get_layer_type_kwargs(layer, index)
            if not self.layer_map[tpe].has_forward:
                raise ValueError("no Forward registered for %r" % tpe)
            unit = self.layer_map[tpe].forward(self, **kwargs)
            self._add_forward_unit(unit, init_attrs, *parents)

        # ZeroFiller-style units mask the NEXT layer's weights
        for prev_fwd, fwd in zip(self.forwards, self.forwards[1:]):
            if getattr(prev_fwd, "LINKS_NEXT_WEIGHTS", False):
                prev_fwd.link_attrs(fwd, "weights")

        last_fwd = self.forwards[-1]
        if isinstance(last_fwd, All2AllSoftmax) and \
                self.real_loader is not None:
            loader = self.real_loader

            def on_initialized():
                ulc = loader.unique_labels_count
                if not ulc:
                    # label-less serving loaders (InteractiveLoader)
                    # keep the configured width
                    return
                oss = last_fwd.output_sample_shape
                if oss != tuple() and numpy.prod(oss) != ulc:
                    self.warning(
                        "Overriding %s.output_sample_shape %s with (%d,)",
                        last_fwd.name, oss, ulc)
                else:
                    self.info("Setting %s.output_sample_shape to %d",
                              last_fwd.name, ulc)
                last_fwd.output_sample_shape = ulc

            loader.on_initialized = on_initialized
        elif (self.real_loader is not None and
              hasattr(self.real_loader, "minibatch_targets") and
              hasattr(last_fwd, "output_sample_shape")):
            # MSE topologies: the last FC layer's width comes from the
            # loader's target sample shape (reference
            # standard_workflow_base.py:324-334, LoaderMSEMixin path).
            loader = self.real_loader

            def on_initialized_mse():
                tshape = loader.targets_shape
                oss = last_fwd.output_sample_shape
                if oss != tuple() and tuple(numpy.ravel(oss)) != tshape \
                        and numpy.prod(oss) != numpy.prod(tshape):
                    self.warning(
                        "Overriding %s.output_sample_shape %s with %s "
                        "(loader targets)", last_fwd.name, oss, tshape)
                last_fwd.output_sample_shape = tshape

            loader.on_initialized = on_initialized_mse
        return last_fwd

    def _add_forward_unit(self, new_unit, init_attrs=None, *parents):
        """(reference 424-452)"""
        if self.forwards:
            prev = (self.forwards[-1],)
        else:
            if not parents:
                raise ValueError(
                    "No parent units were specified for the first forward!")
            prev = parents
        new_unit.link_from(*prev)
        if isinstance(new_unit, DropoutForward):
            new_unit.link_attrs(self.loader, "minibatch_class")
        self.forwards.append(new_unit)

        if "input" not in new_unit._demanded and \
                getattr(new_unit, "input", None) is None and \
                not new_unit.has_linked_attr("input"):
            return
        for fwd in reversed(self.forwards[:-1]):
            if getattr(fwd, "output", None) is not None:
                new_unit.link_attrs(fwd, ("input", "output"))
                break
        else:
            new_unit.link_attrs(parents[0], init_attrs)

    def link_end_point(self, *parents):
        self.repeater.link_from(*parents)
        self.end_point.link_from(*parents)
        return self.end_point

    def create_workflow(self):
        """Forward-only graph: loop the loader until one full epoch was
        served — or until the loader reports ``complete`` (e.g. an
        InteractiveLoader's drained queue).  The reference forward
        workflows run the whole set the same way (mnist_forward.py)."""
        self.link_repeater(self.start_point)
        self.link_loader(self.repeater)
        self.link_forwards(("input", "minibatch_data"), self.loader)
        done = self.loader.complete | self.loader.epoch_ended
        self.link_end_point(self.forwards[-1])
        self.end_point.gate_block = ~done
        self.loader.gate_block = done

    def run(self):
        """Re-arm the per-epoch serving gates before each run, so a
        forward workflow is REUSABLE: without this, a latched
        epoch_ended would gate the loader off forever and a second
        run() would silently serve stale outputs."""
        loader = getattr(self, "loader", None)
        for attr in ("epoch_ended", "last_minibatch"):
            b = getattr(loader, attr, None)
            if b is not None and getattr(b, "_expr", True) is None:
                b <<= False
        return super(StandardWorkflowBase, self).run()
