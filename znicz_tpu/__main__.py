"""``python -m znicz_tpu`` — the workflow CLI (the veles launcher's
user-facing contract: run a workflow module with config overrides).

Examples::

    python -m znicz_tpu wine
    python -m znicz_tpu znicz_tpu.samples.mnist \
        --config mnistr.decision.max_epochs=3
    python -m znicz_tpu samples/mnist.py --snapshot snap.pickle
    python -m znicz_tpu mnist --testing
    python -m znicz_tpu --list
"""

import argparse
import ast
import sys

from znicz_tpu.core.config import root
from znicz_tpu.launcher import list_samples, run_workflow


def apply_override(root_cfg, assignment):
    """Apply one ``dotted.path=value`` override onto the config root.
    Values parse as Python literals, falling back to strings."""
    path, sep, raw = assignment.partition("=")
    if not sep:
        raise SystemExit("--config needs KEY=VALUE, got %r" % assignment)
    try:
        value = ast.literal_eval(raw)
    except (ValueError, SyntaxError):
        value = raw
    parts = path.strip().split(".")
    node = root_cfg
    for p in parts[:-1]:
        node = getattr(node, p)
    setattr(node, parts[-1], value)


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m znicz_tpu",
        description="Run a znicz_tpu workflow (module path, file, or "
                    "sample name).")
    parser.add_argument("workflow", nargs="?",
                        help="dotted module, .py file, or sample name")
    parser.add_argument("--config", action="append", default=[],
                        metavar="KEY=VALUE",
                        help="config-root override, e.g. "
                             "wine.decision.max_epochs=5")
    parser.add_argument("--snapshot", help="snapshot file to resume from")
    parser.add_argument("--testing", action="store_true",
                        help="forward-only run (reference --test)")
    parser.add_argument("--dry-run", action="store_true",
                        help="build + initialize only")
    parser.add_argument("--dump-graph", metavar="FILE.dot",
                        help="write the workflow control graph as DOT; "
                             "skips training unless combined with "
                             "--testing")
    parser.add_argument("--list", action="store_true",
                        help="list bundled samples and exit")
    args = parser.parse_args(argv)

    if args.list:
        for name in list_samples():
            print(name)
        return 0
    if not args.workflow:
        parser.error("workflow required (or --list)")
    # import FIRST: sample modules install their root.<ns> defaults at
    # import time, which would clobber any override applied before it
    from znicz_tpu.launcher import resolve_workflow_module
    module = resolve_workflow_module(args.workflow)
    for assignment in args.config:
        apply_override(root, assignment)
    dry_run = args.dry_run or (bool(args.dump_graph) and not args.testing)
    wf = run_workflow(module, snapshot=args.snapshot,
                      testing=args.testing, dry_run=dry_run)
    if args.dump_graph:
        wf.dump_graph(args.dump_graph)
    decision = getattr(wf, "decision", None)
    if decision is not None and hasattr(decision, "best_n_err_pt"):
        print("best val/train err%%: %s" % (decision.best_n_err_pt,))
    return 0


if __name__ == "__main__":
    sys.exit(main())
