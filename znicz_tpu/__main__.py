"""``python -m znicz_tpu`` — the workflow CLI (the veles launcher's
user-facing contract: run a workflow module with config overrides).

Examples::

    python -m znicz_tpu wine
    python -m znicz_tpu znicz_tpu.samples.mnist \
        --config mnistr.decision.max_epochs=3
    python -m znicz_tpu samples/mnist.py --snapshot snap.pickle
    python -m znicz_tpu mnist --testing
    python -m znicz_tpu --list
    python -m znicz_tpu serve --latest wine --port 8899
    python -m znicz_tpu profile wine --out /tmp/trace
    python -m znicz_tpu profile http://127.0.0.1:8899 --seconds 5

The ``serve`` subcommand hands off to the online inference tier
(:mod:`znicz_tpu.serving`): a snapshot or deployment package served
over HTTP with dynamic micro-batching — see ``serve --help`` and
docs/serving.md.  The ``profile`` subcommand drives the performance
introspection layer (:mod:`znicz_tpu.core.profiler`): run a workflow
under the profiler, or hit a running server's
``GET /debug/profile?seconds=N`` — see docs/observability.md.
"""

import argparse
import ast
import sys

from znicz_tpu.core.config import root
from znicz_tpu.launcher import list_samples, run_workflow


def apply_override(root_cfg, assignment):
    """Apply one ``dotted.path=value`` override onto the config root
    (delegates to the ONE shared parser in core/config.py — the serve
    CLI's ``--config`` uses the same rule)."""
    from znicz_tpu.core.config import apply_override as _apply
    _apply(assignment, root_cfg=root_cfg)


def _generic_population_evaluator(sites):
    """DEFAULT fused GA path (VERDICT r4 missing #4): find the
    top-level config namespace whose subtree holds every Range site
    (a StandardWorkflow sample's root.<ns> with layers + loader_name)
    and build the generic vmapped evaluator for it — no sample-file
    opt-in needed.  Returns None (with a printed reason) when the
    sample/sites are not fusable; the serial path remains the general
    fallback."""
    from znicz_tpu.parallel.population import workflow_population_evaluator
    from znicz_tpu.core.genetics import enumerate_ranges
    want = {(id(c), k) for c, k, _ in sites}
    try:
        for name, node in root.items():
            if not isinstance(node, type(root)):
                continue
            if "layers" not in node or "loader_name" not in node:
                continue
            found = {(id(c), k) for c, k, _ in enumerate_ranges(node)}
            if found and found == want:
                ev = workflow_population_evaluator(node, sites,
                                                   verbose=True)
                if ev is not None:
                    print("fused GA: vmapping each generation over "
                          "root.%s (generic Range-site mapping)" % name)
                return ev
    except Exception as e:  # the serial path is the promised fallback
        print("fused GA unavailable (%s); evaluating serially" % e)
        return None
    print("fused GA unavailable: no single sample namespace holds all "
          "Range sites; evaluating serially")
    return None


def run_genetics(module, spec, fused=None):
    """--optimize GENSxPOP: evolve the Range values found anywhere under
    the config root (the reference's GA tier, SURVEY.md §3.5 —
    samples/MNIST/mnist_config.py:62 declares Range sites the same way).
    The whole generation trains as ONE vmapped XLA computation whenever
    the sites map onto fused hyper slots (any registered sample —
    generic path); otherwise each fitness evaluation is a full training
    run of the workflow (fused when ``--fused`` is given)."""
    from znicz_tpu.core.genetics import GeneticsOptimizer, enumerate_ranges
    from znicz_tpu.launcher import run_workflow
    gens_s, _, pop_s = spec.partition("x")
    try:
        gens = int(gens_s or 4)
        pop = int(pop_s or 8)
    except ValueError:
        raise SystemExit("--optimize wants GENSxPOP (e.g. 4x8), got %r"
                         % spec)
    if gens < 1 or pop < 1:
        raise SystemExit("--optimize needs at least 1 generation and 1 "
                         "individual, got %r" % spec)
    if not enumerate_ranges(root):
        raise SystemExit(
            "--optimize needs Range(...) values in the config; e.g. "
            'root.myns.learning_rate = Range(0.01, 0.001, 0.1)')

    # fused population path: a sample-level population_evaluator factory
    # takes precedence (it may carry sample-specific epochs/seeds); the
    # generic Range-site mapping is the default for everything else
    evaluate_population = None
    factory = getattr(module, "population_evaluator", None)
    if factory is not None:
        # a factory that returns None already probed (and logged) its
        # namespace — do not re-initialize the dataset loader generically
        try:
            evaluate_population = factory(enumerate_ranges(root))
        except Exception as e:
            print("sample population evaluator unavailable (%s); "
                  "evaluating serially" % e)
    else:
        evaluate_population = _generic_population_evaluator(
            enumerate_ranges(root))
    if evaluate_population is not None and fused:
        print("note: --fused K=V settings do not apply to the vmapped "
              "population path (it is already fused; pass a "
              "population_evaluator for custom control)")

    metric = {"label": "-err%"}  # the vmapped path scores -err% always

    def evaluate(_cfg):
        wf = run_workflow(module, fused=fused)
        decision = getattr(wf, "decision", None)
        err = None
        if decision is not None:
            pts = getattr(decision, "best_n_err_pt", None)
            if pts is not None:
                err = pts[1] if pts[1] is not None else pts[2]
            if err is None:
                # MSE decisions track [avg, max, min] mse instead of
                # error percent — fitness is the best (VALID, else
                # TRAIN) average mse
                bm = getattr(decision, "best_metrics", None)
                if bm is not None:
                    for clazz in (1, 2):
                        if bm[clazz] is not None:
                            err = bm[clazz][0]
                            metric["label"] = "-avg_mse"
                            break
        if err is None:
            raise SystemExit("workflow exposes no error metric to "
                             "optimize against")
        return -float(err)

    opt = GeneticsOptimizer(evaluate, root, generations=gens,
                            population_size=pop,
                            evaluate_population=evaluate_population)
    values, fitness = opt.run()
    print("best fitness (%s): %.4f" % (metric["label"], fitness))
    for (container, key, rng), value in zip(opt.sites, values):
        print("  %s = %s  (range %s..%s)" % (key, value, rng.min_value,
                                             rng.max_value))
    return 0


def main(argv=None):
    argv = sys.argv[1:] if argv is None else list(argv)
    if argv and argv[0] == "serve":
        # the serving tier has its own flag set — dispatch before the
        # training parser can reject them
        from znicz_tpu.serving.server import main as serve_main
        return serve_main(argv[1:])
    if argv and argv[0] == "profile":
        # performance introspection: capture a device trace from a
        # running server (URL target) or run a workflow under the full
        # profiler stack (core/profiler.py)
        from znicz_tpu.core.profiler import cli_main as profile_main
        return profile_main(argv[1:])
    if argv and argv[0] == "obs":
        # durable blackbox queries: merged cross-process timeline,
        # --rid request reconstruction, cross-restart --rate, and
        # --postmortem bundles (core/blackbox.py)
        from znicz_tpu.core.blackbox import cli_main as obs_main
        return obs_main(argv[1:])
    parser = argparse.ArgumentParser(
        prog="python -m znicz_tpu",
        description="Run a znicz_tpu workflow (module path, file, or "
                    "sample name); 'python -m znicz_tpu serve ...' "
                    "starts the inference server instead.")
    parser.add_argument("workflow", nargs="?",
                        help="dotted module, .py file, or sample name")
    parser.add_argument("--config", action="append", default=[],
                        metavar="KEY=VALUE",
                        help="config-root override, e.g. "
                             "wine.decision.max_epochs=5")
    parser.add_argument("--snapshot", help="snapshot file to resume from")
    parser.add_argument("--auto-resume", action="store_true",
                        help="elastic recovery: restore the newest "
                             "matching snapshot (if any) and continue "
                             "training — safe to use as the default "
                             "launch mode of a supervised job")
    parser.add_argument("--max-restarts", type=int, default=0,
                        metavar="N",
                        help="supervised mode: catch a crashed run, "
                             "back off, and re-enter with auto-resume "
                             "up to N times (mid-epoch snapshots — "
                             "snapshotter window_interval — make the "
                             "re-entry resume mid-epoch)")
    parser.add_argument("--restart-backoff-ms", type=float,
                        default=1000.0, metavar="MS",
                        help="supervised-restart backoff base (doubles "
                             "per attempt, capped at 30 s)")
    parser.add_argument("--testing", action="store_true",
                        help="forward-only run (reference --test)")
    parser.add_argument("--dry-run", action="store_true",
                        help="build + initialize only")
    parser.add_argument("--dump-graph", metavar="FILE.dot",
                        help="write the workflow control graph as DOT; "
                             "skips training unless combined with "
                             "--testing")
    parser.add_argument("--optimize", metavar="GENSxPOP",
                        help="genetic hyperparameter search over Range "
                             "values in the config (e.g. 4x8 = 4 "
                             "generations, population 8); fitness is "
                             "-validation error")
    parser.add_argument("--parity", action="store_true",
                        help="real-data accuracy parity run: provision "
                             "the dataset (network required), train the "
                             "published config, print the BASELINE.md "
                             "comparison row")
    parser.add_argument("--fused", nargs="?", const=True, default=None,
                        metavar="K=V[,K=V...]",
                        help="fused execution mode: compile the whole "
                             "per-minibatch train step to one SPMD XLA "
                             "computation (e.g. --fused "
                             "mesh=8,model_parallel=2,pool_impl=gather)")
    parser.add_argument("--list", action="store_true",
                        help="list bundled samples and exit")
    args = parser.parse_args(argv)

    if args.list:
        from znicz_tpu.samples import MANIFESTS
        for name in list_samples():
            meta = MANIFESTS.get(name)
            if meta:
                print("%-24s %-22s baseline: %s"
                      % (name, meta["workflow"],
                         meta["baseline"] or "-"))
            else:
                print(name)
        return 0
    if not args.workflow:
        parser.error("workflow required (or --list)")
    # import FIRST: sample modules install their root.<ns> defaults at
    # import time, which would clobber any override applied before it
    from znicz_tpu.launcher import resolve_workflow_module
    module = resolve_workflow_module(args.workflow)
    for assignment in args.config:
        apply_override(root, assignment)
    fused = args.fused
    if isinstance(fused, str):
        cfg = {}
        for pair in fused.split(","):
            key, sep, raw = pair.partition("=")
            if not sep:
                parser.error("--fused wants K=V pairs, got %r" % pair)
            try:
                cfg[key.strip()] = ast.literal_eval(raw)
            except (ValueError, SyntaxError):
                cfg[key.strip()] = raw
        fused = cfg
    if args.parity:
        if args.optimize or args.snapshot or args.testing or \
                args.dry_run or args.dump_graph:
            parser.error("--parity runs the published training config "
                         "standalone")
        from znicz_tpu import parity
        # the module is already resolved — accept any spelling the CLI
        # accepts ('mnist', 'znicz_tpu.samples.mnist', 'samples/mnist.py').
        # Parity trains on the fused path by default; --fused K=V
        # overrides its config (e.g. --fused window=1).
        parity.run_parity(module.__name__.rsplit(".", 1)[-1],
                          fused=fused if fused is not None else "auto")
        return 0
    if args.optimize:
        if args.snapshot or args.testing or args.dry_run or \
                args.dump_graph:
            parser.error("--optimize cannot be combined with --snapshot/"
                         "--testing/--dry-run/--dump-graph")
        if args.max_restarts > 0:
            # loud, not silently inert: the genetics sweep is not
            # supervised
            parser.error("--optimize cannot be combined with "
                         "--max-restarts")
        return run_genetics(module, args.optimize, fused=fused)
    dry_run = args.dry_run or (bool(args.dump_graph) and not args.testing)
    if args.max_restarts > 0:
        from znicz_tpu.launcher import run_supervised
        wf = run_supervised(module, max_restarts=args.max_restarts,
                            restart_backoff_ms=args.restart_backoff_ms,
                            snapshot=args.snapshot, testing=args.testing,
                            dry_run=dry_run, fused=fused,
                            auto_resume=args.auto_resume)
    else:
        wf = run_workflow(module, snapshot=args.snapshot,
                          testing=args.testing, dry_run=dry_run,
                          fused=fused, auto_resume=args.auto_resume)
    if args.dump_graph:
        wf.dump_graph(args.dump_graph)
    decision = getattr(wf, "decision", None)
    if decision is not None and hasattr(decision, "best_n_err_pt"):
        print("best val/train err%%: %s" % (decision.best_n_err_pt,))
    return 0


if __name__ == "__main__":
    sys.exit(main())
