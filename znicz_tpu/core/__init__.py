"""Core runtime — the TPU-era equivalent of the external Veles core platform.

The reference imports ~50 ``veles.*`` modules (SURVEY.md §2.9); this package
provides that observed contract: config root, Logger, seedable PRNG,
Unit/Workflow dataflow engine, mirrored host/device Array, distributable
protocol, snapshotter, dummy launcher.
"""
