"""Seedable PRNG streams.

TPU-era equivalent of ``veles.prng`` (SURVEY.md §2.9).  The reference keeps a
registry of named streams (``prng.get(key)``), seeded from 1024-int32 seed
files by the functional-test harness (tests/functional/standard_test.py:67-73)
and used for weight init (``rand.fill`` / ``rand.fill_normal_real``,
all2all.py:119-127), loader shuffling, and dropout mask generation
(dropout.py:110,149).

TPU-first addition: every stream can also mint ``jax.random`` keys
(:meth:`RandomGenerator.jax_key`) so device-side randomness (dropout,
stochastic pooling) is reproducible from the same seed, replacing the
reference's device-side xorshift state arrays (dropout.py:112-117).
"""

import numpy


class RandomGenerator(object):
    """One seedable random stream wrapping ``numpy.random.RandomState``."""

    def __init__(self, key=None):
        self.key = key
        self._state = numpy.random.RandomState()
        self._seed_arr = None
        self._key_counter = 0
        self.seed(numpy.frombuffer(b"znicz-tpu-default-seed-0123456789ab",
                                   dtype=numpy.uint8))

    # -- seeding ------------------------------------------------------------
    def seed(self, seed, dtype=None, count=None):
        """Seed from an int, an array, or a file path of raw ``dtype`` values.

        Mirrors the reference harness contract
        (tests/functional/standard_test.py:67-73): seed files are raw binary,
        read as ``count`` items of ``dtype``.
        """
        if isinstance(seed, str):
            seed = numpy.fromfile(seed, dtype=dtype or numpy.int32,
                                  count=count or 1024)
        if isinstance(seed, (int, numpy.integer)):
            arr = numpy.asarray([seed], dtype=numpy.uint32)
        else:
            raw = numpy.ascontiguousarray(seed).tobytes()
            raw += b"\x00" * (-len(raw) % 4)
            arr = numpy.frombuffer(raw, dtype=numpy.uint32).copy()
        self._seed_arr = arr
        self._state.seed(arr)
        self._key_counter = 0
        return self

    @property
    def state(self):
        return self._state

    # -- in-place fillers (reference: all2all.py:119-127) -------------------
    def fill(self, arr, vle_min=-1.0, vle_max=1.0):
        """Uniform fill of a numpy array in place."""
        arr[...] = self._state.uniform(
            vle_min, vle_max, size=arr.shape).astype(arr.dtype)

    def fill_normal_real(self, arr, mean=0.0, stddev=1.0, clip_to_sigma=None):
        vals = self._state.normal(mean, stddev, size=arr.shape)
        if clip_to_sigma is not None:
            vals = numpy.clip(vals, mean - clip_to_sigma * stddev,
                              mean + clip_to_sigma * stddev)
        arr[...] = vals.astype(arr.dtype)

    # -- draws --------------------------------------------------------------
    def normal(self, loc=0.0, scale=1.0, size=None):
        return self._state.normal(loc, scale, size)

    def uniform(self, low=0.0, high=1.0, size=None):
        return self._state.uniform(low, high, size)

    def randint(self, low, high=None, size=None, dtype=int):
        return self._state.randint(low, high, size).astype(dtype)

    def rand(self, *shape):
        return self._state.rand(*shape)

    def shuffle(self, arr):
        self._state.shuffle(arr)

    def permutation(self, n):
        return self._state.permutation(n)

    def choice(self, a, size=None, replace=True, p=None):
        return self._state.choice(a, size, replace, p)

    # -- state capture (checkpoint/resume exactness) ------------------------
    def get_state(self):
        """Opaque resumable state (numpy RandomState + key counter)."""
        return {"np": self._state.get_state(),
                "seed_arr": None if self._seed_arr is None
                else numpy.array(self._seed_arr),
                "key_counter": self._key_counter}

    def set_state(self, state):
        self._state.set_state(state["np"])
        self._seed_arr = state["seed_arr"]
        self._key_counter = state["key_counter"]
        return self

    # -- TPU-first: deterministic jax.random keys ---------------------------
    def jax_key(self):
        """Mint the next ``jax.random`` key in this stream.

        Deterministic given the seed: key #n after seeding is always the
        same.  This is how device-side randomness (dropout masks, stochastic
        pooling) stays reproducible under jit.
        """
        import jax
        base = int(self._seed_arr.view(numpy.uint32)[:2].sum()) & 0x7FFFFFFF
        self._key_counter += 1
        return jax.random.fold_in(
            jax.random.PRNGKey(base), self._key_counter)


# -- stream registry (reference: veles.prng.get) ---------------------------
_streams = {}


def get(key=1):
    """Return the process-global stream with the given key (default 1).

    The reference seeds two streams (keys 1 and 2) in functional tests.
    """
    rg = _streams.get(key)
    if rg is None:
        rg = _streams[key] = RandomGenerator(key)
    return rg


def states():
    """Capture every registered stream's state (snapshot payload)."""
    return {key: rg.get_state() for key, rg in _streams.items()}


def restore(state_map):
    """Restore stream states captured by :func:`states` (resume)."""
    for key, st in state_map.items():
        get(key).set_state(st)
