"""Web status server — live workflow observability.

TPU-era equivalent of the reference core's tornado web UI (SURVEY.md
§5.5: workflow status + matplotlib plot streaming).  Dependency-free:
a stdlib ``ThreadingHTTPServer`` on a daemon thread serving

* ``/``            — a small auto-refreshing HTML dashboard,
* ``/status.json`` — workflow status (units, metrics, timings),
* ``/metrics``     — the telemetry registry in Prometheus text
  exposition format (core/telemetry.py; scrape it),
* ``/plots/``      — the pngs the plotters render into <cache>/plots.

Usage::

    server = StatusServer(workflow, port=8080).start()
    ...
    server.stop()
"""

import glob
import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from znicz_tpu.core.config import root
from znicz_tpu.core.logger import Logger
from znicz_tpu.core import telemetry

_PAGE = """<html><head><title>znicz_tpu status</title>
<meta http-equiv="refresh" content="5"></head>
<body><h1>znicz_tpu — %(name)s</h1>
<pre id="status">%(status)s</pre>
%(plots)s
</body></html>"""


class StatusServer(Logger):
    """Serves one workflow's live status over HTTP."""

    def __init__(self, workflow=None, port=0, host="127.0.0.1"):
        super(StatusServer, self).__init__(logger_name="StatusServer")
        self.workflow = workflow
        self.host = host
        self.port = port
        self._httpd = None
        self._thread = None

    # -- status payload -----------------------------------------------------
    def status(self):
        """Status dict — TOLERANT of a workflow queried before (or
        mid-) ``initialize()``: units may lack ``run_count_``/timing
        attributes, the decision may be half-built.  Every section is
        gathered independently; a failing section lands in
        ``payload["errors"]`` instead of turning the whole endpoint
        into a 500 (the dashboard polls from the first second of a
        run)."""
        wf = self.workflow
        payload = {"workflow": None, "errors": {}}
        if wf is not None:
            payload["workflow"] = type(wf).__name__
            try:
                units = list(wf.units)
                payload["units"] = [getattr(u, "name", repr(u))
                                    for u in units]
                payload["run_counts"] = {
                    getattr(u, "name", repr(u)):
                        int(getattr(u, "run_count_", 0) or 0)
                    for u in units}
            except Exception as e:  # noqa: BLE001 - partial payload
                payload["errors"]["units"] = repr(e)
            try:
                decision = getattr(wf, "decision", None)
                if decision is not None:
                    for attr in ("epoch_number", "complete",
                                 "best_n_err_pt", "epoch_n_err_pt"):
                        v = getattr(decision, attr, None)
                        if v is not None:
                            payload[attr] = _plain(v)
            except Exception as e:  # noqa: BLE001 - partial payload
                payload["errors"]["decision"] = repr(e)
            try:
                if hasattr(wf, "unit_timings"):
                    payload["unit_timings"] = [
                        {"unit": u.name, "seconds": round(t, 4),
                         "runs": n}
                        for u, t, n in wf.unit_timings()]
            except Exception as e:  # noqa: BLE001 - partial payload
                payload["errors"]["unit_timings"] = repr(e)
        try:
            payload["plots"] = [os.path.basename(p)
                                for p in self._plot_files()]
        except Exception as e:  # noqa: BLE001 - partial payload
            payload["plots"] = []
            payload["errors"]["plots"] = repr(e)
        if telemetry.enabled():
            payload["telemetry"] = telemetry.snapshot()
        if not payload["errors"]:
            del payload["errors"]
        return payload

    @staticmethod
    def _plot_files():
        return sorted(glob.glob(os.path.join(
            root.common.dirs.cache, "plots", "*.png")))

    # -- lifecycle ----------------------------------------------------------
    def start(self):
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet
                server.debug(fmt, *args)

            def do_GET(self):
                try:
                    if self.path in ("/", "/index.html"):
                        self._send(200, "text/html",
                                   server._render_page().encode())
                    elif self.path == "/status.json":
                        self._send(200, "application/json", json.dumps(
                            server.status(), default=str).encode())
                    elif self.path == "/metrics":
                        self._send(
                            200,
                            "text/plain; version=0.0.4; charset=utf-8",
                            telemetry.prometheus_text().encode())
                    elif self.path.startswith("/plots/"):
                        name = os.path.basename(self.path)
                        path = os.path.join(root.common.dirs.cache,
                                            "plots", name)
                        if os.path.exists(path):
                            with open(path, "rb") as f:
                                self._send(200, "image/png", f.read())
                        else:
                            self._send(404, "text/plain", b"not found")
                    else:
                        self._send(404, "text/plain", b"not found")
                except BrokenPipeError:
                    pass

            def _send(self, code, ctype, body):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="status-server",
            daemon=True)
        self._thread.start()
        self.info("status server on http://%s:%d/", self.host, self.port)
        return self

    def _render_page(self):
        st = self.status()
        plots = "".join('<img src="/plots/%s" width="400"/>' % p
                        for p in st.get("plots", ()))
        return _PAGE % {
            "name": st.get("workflow") or "(no workflow)",
            "status": json.dumps(st, indent=2, default=str),
            "plots": plots,
        }

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


def _plain(obj):
    import numpy
    if isinstance(obj, (list, tuple)):
        return [_plain(v) for v in obj]
    if isinstance(obj, numpy.ndarray):
        return obj.tolist()
    if isinstance(obj, numpy.generic):
        return obj.item()
    if hasattr(obj, "__bool__") and type(obj).__name__ == "Bool":
        return bool(obj)
    return obj
