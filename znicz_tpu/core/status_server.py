"""Web status server — live workflow observability.

TPU-era equivalent of the reference core's tornado web UI (SURVEY.md
§5.5: workflow status + matplotlib plot streaming).  Dependency-free:
a stdlib ``ThreadingHTTPServer`` on a daemon thread serving

* ``/``            — a small auto-refreshing HTML dashboard,
* ``/status.json`` — workflow status (units, metrics, timings),
* ``/metrics``     — the telemetry registry in Prometheus text
  exposition format (core/telemetry.py; scrape it),
* ``/plots/``      — the pngs the plotters render into <cache>/plots,
* ``/debug/health`` — the numeric health monitor's status
  (core/health.py; 503 once a violation was recorded),
* ``/debug/events`` — the flight-recorder journal (core/telemetry.py),
* ``/debug/profile?seconds=N`` — on-demand ``jax.profiler`` capture
  (core/profiler.py; returns the trace directory),
* ``/debug/profiler`` — the performance-introspection report (cost
  registry, device-memory ledger, step-time breakdown),
* ``/debug/timeseries`` — the in-process metric time-series rings
  (core/timeseries.py),
* ``/debug/trace/<rid>`` — sampled per-request span trees
  (znicz_tpu/serving/reqtrace.py),
* ``/debug/pyprof?seconds=N`` — a windowed capture from the
  continuous Python sampling profiler (core/pyprof.py;
  ``format=collapsed|speedscope`` for renderer-ready output).

The HTTP plumbing (handler ``_send`` helpers, daemon-thread lifecycle,
idempotent ``stop()``) lives in :class:`HttpServerBase` /
:class:`HandlerBase`, shared with the serving front end
(:mod:`znicz_tpu.serving.server`).

Usage::

    server = StatusServer(workflow, port=8080).start()
    ...
    server.stop()
"""

import glob
import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from znicz_tpu.core.config import root
from znicz_tpu.core.logger import Logger
from znicz_tpu.core import telemetry
from znicz_tpu.analysis import locksmith

# ONE capture-concurrency guard shared by BOTH capture endpoints
# (/debug/profile and /debug/pyprof): a JAX device trace and a
# frame-walk capture interleaved on the same process would each
# distort what the other measures, so the second concurrent capture
# of EITHER kind gets the 409, not just a same-endpoint repeat.
_capture_guard = locksmith.lock("status_server.debug_capture")

_PAGE = """<html><head><title>znicz_tpu status</title>
<meta http-equiv="refresh" content="5"></head>
<body><h1>znicz_tpu — %(name)s</h1>
<pre id="status">%(status)s</pre>
%(plots)s
</body></html>"""


class BodyTooLargeError(ValueError):
    """Request body over ``root.common.serving.max_body_bytes`` —
    refused BEFORE reading (HTTP 413): one oversized upload must not
    be buffered into server memory.  Subclasses ``ValueError`` so
    body-draining helpers treat it like the other refuse-to-read
    case (Transfer-Encoding)."""


class HandlerBase(BaseHTTPRequestHandler):
    """Shared request-handler plumbing.  Subclasses (closed over their
    owning server) implement ``do_GET``/``do_POST`` with the ``_send*``
    helpers; ``owner`` is the :class:`HttpServerBase` that built the
    handler class."""

    owner = None
    #: served HTTP version — keep-alive for request streams
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # quiet: route to the logger
        if self.owner is not None:
            self.owner.debug(fmt, *args)

    def handle(self):
        # adopt the thread-name registry (core/pyprof.py) at request
        # entry: ThreadingHTTPServer spawns anonymous "Thread-N"
        # threads, and a sample attributed to "Thread-N" is a sample
        # lost to the "unnamed" bucket
        t = threading.current_thread()
        if not t.name.startswith("znicz:"):
            t.name = "znicz:http-handler"
        BaseHTTPRequestHandler.handle(self)

    def _send(self, code, ctype, body, headers=None):
        try:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            if self.close_connection:
                # tell keep-alive clients the truth before we drop the
                # socket (set e.g. when an unreadable body is refused)
                self.send_header("Connection", "close")
            self.end_headers()
            self.wfile.write(body)
        except BrokenPipeError:  # client went away mid-reply
            pass

    def _send_json(self, code, obj, headers=None):
        self._send(code, "application/json",
                   json.dumps(obj, default=str).encode(),
                   headers=headers)

    def _read_body(self):
        if self.headers.get("Transfer-Encoding"):
            # only Content-Length bodies are spoken here; close the
            # connection so an UNREAD chunked payload cannot desync the
            # next request on a keep-alive socket
            self.close_connection = True
            raise ValueError("Transfer-Encoding is not supported — "
                             "send a Content-Length body")
        length = int(self.headers.get("Content-Length") or 0)
        cap = int(root.common.serving.get("max_body_bytes",
                                          16 << 20) or 0)
        if cap and length > cap:
            # refuse BEFORE reading: the unread bytes mean this
            # keep-alive socket cannot be reused, say so honestly
            self.close_connection = True
            raise BodyTooLargeError(
                "request body of %d bytes exceeds the %d-byte limit"
                % (length, cap))
        return self.rfile.read(length) if length > 0 else b""

    def _drain_body(self):
        """Consume (and discard) the request body before an early
        reply — replying with unread Content-Length bytes on the
        socket desyncs every later request of a keep-alive
        connection."""
        try:
            self._read_body()
        except ValueError:
            pass  # Transfer-Encoding: close_connection is already set

    def _send_metrics(self):
        """The Prometheus exposition endpoint — one definition shared
        by the status dashboard and the serving front end."""
        self._send(200, "text/plain; version=0.0.4; charset=utf-8",
                   telemetry.prometheus_text().encode())

    def _handle_debug(self):
        """The diagnostics endpoints every server built on this base
        exposes (status dashboard AND serving front end):

        * ``GET /debug/health`` — the health monitor's status JSON
          (healthz-style: 503 once a violation has been recorded),
        * ``GET /debug/events`` — the flight-recorder journal
          (``?n=`` newest-N cap, default 256; ``?kind=`` prefix
          filter; ``?rid=`` follows one request),
        * ``GET /debug/blackbox`` — the durable blackbox's writer
          stats and segment inventory (``core/blackbox.py``),
        * ``GET /debug/profile?seconds=N`` — capture a ``jax.profiler``
          device trace for N seconds (capped by
          ``root.common.profiler.capture_seconds_cap``) and reply with
          the trace directory; 409 while another capture runs,
        * ``GET /debug/profiler`` — the performance-introspection
          report (cost registry, memory ledger, step breakdown),
        * ``GET /debug/timeseries`` — the in-process metric
          time-series rings + trailing rates
          (``core/timeseries.py``; 404-style empty when disabled),
        * ``GET /debug/trace`` / ``GET /debug/trace/<rid>`` — the
          sampled per-request span trees
          (``znicz_tpu/serving/reqtrace.py``),
        * ``GET /debug/pyprof?seconds=N`` — a windowed capture from
          the continuous Python sampling profiler
          (``core/pyprof.py``; ``format=collapsed|speedscope``
          selects renderer-ready output, default raw JSON;
          ``{"enabled": false}`` when the knob is off).

        The two CAPTURE endpoints (``/debug/profile`` and
        ``/debug/pyprof``) share ONE concurrency guard: while either
        capture runs, the other answers 409 too.

        Returns True when the request was handled."""
        path, _, query = self.path.partition("?")
        if path == "/debug/timeseries":
            from znicz_tpu.core import timeseries
            self._send_json(200, timeseries.snapshot())
            return True
        if path == "/debug/trace" or path.startswith("/debug/trace/"):
            from znicz_tpu.serving import reqtrace
            rid = path[len("/debug/trace/"):] \
                if path.startswith("/debug/trace/") else ""
            if not rid:
                self._send_json(200, {
                    "enabled": reqtrace.enabled(),
                    "rids": reqtrace.rids()})
                return True
            tree = reqtrace.get(rid)
            if tree is None:
                self._send_json(404, {
                    "error": "no sampled trace for rid %r (sampling "
                             "%s; see root.common.serving."
                             "trace_sample_n)"
                             % (rid, "on" if reqtrace.enabled()
                                else "off")})
                return True
            self._send_json(200, tree)
            return True
        if path == "/debug/health":
            from znicz_tpu.core import health
            st = health.status()
            self._send_json(200 if st.get("ok", True) else 503, st)
            return True
        if path == "/debug/events":
            from urllib.parse import parse_qs
            qs = parse_qs(query)
            try:
                n = int(qs.get("n", ["256"])[0])
            except ValueError:
                self._send_json(400, {"error": "n must be an "
                                               "integer"})
                return True
            kind = qs.get("kind", [None])[0]
            rid = qs.get("rid", [None])[0]
            events = telemetry.journal_events()
            total = len(events)
            if kind:
                events = [e for e in events
                          if str(e.get("kind", "")).startswith(kind)]
            if rid:
                events = [e for e in events
                          if rid in (e.get("rid"),
                                     e.get("exemplar_rid"),
                                     e.get("request_id"))]
            matched = len(events)
            if n > 0:
                events = events[-n:]
            self._send_json(200,
                            {"events": events,
                             "total": total,
                             "matched": matched,
                             "dropped": telemetry.journal_dropped()})
            return True
        if path == "/debug/blackbox":
            from znicz_tpu.core import blackbox
            self._send_json(200, blackbox.stats())
            return True
        if path == "/debug/faults":
            from znicz_tpu.core import faults
            self._send_json(200, faults.status())
            return True
        if path == "/debug/profiler":
            from znicz_tpu.core import profiler
            self._send_json(200, profiler.snapshot())
            return True
        if path == "/debug/profile":
            from urllib.parse import parse_qs
            from znicz_tpu.core import profiler
            try:
                seconds = float(
                    parse_qs(query).get("seconds", ["3"])[0])
            except ValueError:
                self._send_json(400, {"error": "seconds must be a "
                                               "number"})
                return True
            if not _capture_guard.acquire(blocking=False):
                self._send_json(409, {
                    "error": "another debug capture (profile or "
                             "pyprof) is already running"})
                return True
            try:
                # blocks THIS handler thread for the capture window
                # (the server is threaded; other requests keep flowing)
                result = profiler.capture_trace(seconds)
            except RuntimeError as e:  # a capture is already running
                self._send_json(409, {"error": str(e)})
                return True
            except Exception as e:  # noqa: BLE001 - always answer HTTP
                self._send_json(500, {"error": repr(e)})
                return True
            finally:
                _capture_guard.release()
            self._send_json(200, result)
            return True
        if path == "/debug/pyprof":
            from urllib.parse import parse_qs
            from znicz_tpu.core import pyprof
            qs = parse_qs(query)
            try:
                seconds = float(qs.get("seconds", ["2"])[0])
            except ValueError:
                self._send_json(400, {"error": "seconds must be a "
                                               "number"})
                return True
            fmt = qs.get("format", ["json"])[0]
            if not pyprof.enabled():
                # the honest disabled answer — no capture, no guard
                self._send_json(200, {"enabled": False})
                return True
            if not _capture_guard.acquire(blocking=False):
                self._send_json(409, {
                    "error": "another debug capture (profile or "
                             "pyprof) is already running"})
                return True
            try:
                # blocks THIS handler thread for the capture window
                prof = pyprof.capture(seconds)
            except Exception as e:  # noqa: BLE001 - always answer HTTP
                self._send_json(500, {"error": repr(e)})
                return True
            finally:
                _capture_guard.release()
            if fmt == "collapsed":
                self._send(200, "text/plain; charset=utf-8",
                           (pyprof.collapsed(prof) + "\n").encode())
            elif fmt == "speedscope":
                self._send_json(200, pyprof.speedscope(prof))
            else:
                self._send_json(200, prof)
            return True
        return False


class _DeepBacklogHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer with a PRODUCTION listen backlog.

    socketserver's default ``request_queue_size`` is 5: a burst of
    concurrent connections (a loadgen storm, a fleet router fanning
    requests at a replica) overflows the SYN backlog and the excess
    connects stall in kernel retransmit for 1–7 s — measured as a
    522 req/s sequential server collapsing to ~85 req/s under 32
    concurrent clients while its own request histogram read 1 ms.
    128 pending connections cost nothing and absorb any storm the
    handler threads can actually serve."""

    request_queue_size = 128


class HttpServerBase(Logger):
    """Daemon-thread stdlib HTTP server lifecycle.

    Subclasses implement :meth:`make_handler` returning a
    :class:`HandlerBase` subclass.  ``stop()`` is idempotent and
    thread-safe: any number of calls (including concurrent ones) shut
    the socket down exactly once and never raise on an already-stopped
    server.
    """

    def __init__(self, port=0, host="127.0.0.1", logger_name=None):
        super(HttpServerBase, self).__init__(
            logger_name=logger_name or type(self).__name__)
        self.host = host
        self.port = port
        self._httpd = None
        self._thread = None
        self._lifecycle_lock = locksmith.lock("status_server.lifecycle")

    def make_handler(self):
        """Return the request-handler class for this server."""
        raise NotImplementedError

    def start(self):
        with self._lifecycle_lock:
            if self._httpd is not None:
                return self
            self._httpd = _DeepBacklogHTTPServer(
                (self.host, self.port), self.make_handler())
            self.port = self._httpd.server_address[1]
            self._thread = threading.Thread(
                target=self._httpd.serve_forever,
                name="znicz:" + type(self).__name__.lower(),
                daemon=True)
            self._thread.start()
        # arm the metric time-series sampler and the continuous
        # Python profiler when their knobs are on — every HTTP
        # surface (status dashboard, serving front end) serves
        # /debug/timeseries and /debug/pyprof, so the server
        # lifecycle is the one natural arming point (each a no-op
        # single predicate when off)
        from znicz_tpu.core import timeseries
        from znicz_tpu.core import pyprof
        from znicz_tpu.core import blackbox
        timeseries.maybe_start()
        pyprof.maybe_start()
        blackbox.maybe_arm()
        self.info("%s on http://%s:%d/", type(self).__name__,
                  self.host, self.port)
        return self

    def stop(self):
        with self._lifecycle_lock:
            httpd, self._httpd = self._httpd, None
            thread, self._thread = self._thread, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if thread is not None:
            thread.join(timeout=5)


class StatusServer(HttpServerBase):
    """Serves one workflow's live status over HTTP."""

    def __init__(self, workflow=None, port=0, host="127.0.0.1"):
        super(StatusServer, self).__init__(port=port, host=host,
                                           logger_name="StatusServer")
        self.workflow = workflow

    # -- status payload -----------------------------------------------------
    def status(self):
        """Status dict — TOLERANT of a workflow queried before (or
        mid-) ``initialize()``: units may lack ``run_count_``/timing
        attributes, the decision may be half-built.  Every section is
        gathered independently; a failing section lands in
        ``payload["errors"]`` instead of turning the whole endpoint
        into a 500 (the dashboard polls from the first second of a
        run)."""
        wf = self.workflow
        payload = {"workflow": None, "errors": {}}
        if wf is not None:
            payload["workflow"] = type(wf).__name__
            try:
                units = list(wf.units)
                payload["units"] = [getattr(u, "name", repr(u))
                                    for u in units]
                payload["run_counts"] = {
                    getattr(u, "name", repr(u)):
                        int(getattr(u, "run_count_", 0) or 0)
                    for u in units}
            except Exception as e:  # noqa: BLE001 - partial payload
                payload["errors"]["units"] = repr(e)
            try:
                decision = getattr(wf, "decision", None)
                if decision is not None:
                    for attr in ("epoch_number", "complete",
                                 "best_n_err_pt", "epoch_n_err_pt"):
                        v = getattr(decision, attr, None)
                        if v is not None:
                            payload[attr] = _plain(v)
            except Exception as e:  # noqa: BLE001 - partial payload
                payload["errors"]["decision"] = repr(e)
            try:
                if hasattr(wf, "unit_timings"):
                    payload["unit_timings"] = [
                        {"unit": u.name, "seconds": round(t, 4),
                         "runs": n}
                        for u, t, n in wf.unit_timings()]
            except Exception as e:  # noqa: BLE001 - partial payload
                payload["errors"]["unit_timings"] = repr(e)
        try:
            payload["plots"] = [os.path.basename(p)
                                for p in self._plot_files()]
        except Exception as e:  # noqa: BLE001 - partial payload
            payload["plots"] = []
            payload["errors"]["plots"] = repr(e)
        if telemetry.enabled():
            payload["telemetry"] = telemetry.snapshot()
        if not payload["errors"]:
            del payload["errors"]
        return payload

    @staticmethod
    def _plot_files():
        return sorted(glob.glob(os.path.join(
            root.common.dirs.cache, "plots", "*.png")))

    def make_handler(self):
        server = self

        class Handler(HandlerBase):
            owner = server

            def do_GET(self):
                if self.path in ("/", "/index.html"):
                    self._send(200, "text/html",
                               server._render_page().encode())
                elif self.path == "/status.json":
                    self._send_json(200, server.status())
                elif self.path == "/metrics":
                    self._send_metrics()
                elif self.path.startswith("/plots/"):
                    name = os.path.basename(self.path)
                    path = os.path.join(root.common.dirs.cache,
                                        "plots", name)
                    if os.path.exists(path):
                        with open(path, "rb") as f:
                            self._send(200, "image/png", f.read())
                    else:
                        self._send(404, "text/plain", b"not found")
                elif self._handle_debug():
                    pass
                else:
                    self._send(404, "text/plain", b"not found")

        return Handler

    def _render_page(self):
        st = self.status()
        plots = "".join('<img src="/plots/%s" width="400"/>' % p
                        for p in st.get("plots", ()))
        return _PAGE % {
            "name": st.get("workflow") or "(no workflow)",
            "status": json.dumps(st, indent=2, default=str),
            "plots": plots,
        }


def _plain(obj):
    import numpy
    if isinstance(obj, (list, tuple)):
        return [_plain(v) for v in obj]
    if isinstance(obj, numpy.ndarray):
        return obj.tolist()
    if isinstance(obj, numpy.generic):
        return obj.item()
    if hasattr(obj, "__bool__") and type(obj).__name__ == "Bool":
        return bool(obj)
    return obj
