"""Accelerated units — backend-dispatching compute nodes.

TPU-era equivalent of ``veles.accelerated_units`` (SURVEY.md layer L3, §3.2).
The reference dispatches ``numpy_run`` / ``ocl_run`` / ``cuda_run``;
znicz_tpu dispatches ``numpy_run`` / ``jax_run``.  ``jax_run`` bodies call
jitted pure functions from :mod:`znicz_tpu.ops` on ``Array.dev`` buffers and
store results with ``Array.set_dev`` — no host round-trips between chained
units (the reference's map/unmap invariant, kept).

There is deliberately NO build_program/get_kernel machinery: XLA tracing is
the kernel JIT.  ``initialize`` is where output shapes are computed and
buffers allocated, mirroring the reference lifecycle.
"""

from znicz_tpu.core.units import Unit
from znicz_tpu.core.memory import Array
from znicz_tpu.core.backends import NumpyDevice, get_device
from znicz_tpu.core.workflow import Workflow


class INumpyUnit(object):
    """Marker: unit has a numpy_run path (parity: veles INumpyUnit)."""


class IJaxUnit(object):
    """Marker: unit has a jax_run path (replaces IOpenCLUnit/ICUDAUnit)."""


class AcceleratedUnit(Unit):
    """A unit whose ``run`` dispatches on the device backend."""

    def __init__(self, workflow, **kwargs):
        super(AcceleratedUnit, self).__init__(workflow, **kwargs)
        self.device = None
        self.intel_opencl_workaround = False  # parity stub (all2all.py:248)

    def initialize(self, device=None, **kwargs):
        super(AcceleratedUnit, self).initialize(device=device, **kwargs)
        self.device = device if device is not None else get_device()

    def run(self):
        if isinstance(self.device, NumpyDevice):
            return self.numpy_run()
        return self.jax_run()

    # Subclasses implement both paths; numpy is the executable spec.
    def numpy_run(self):
        raise NotImplementedError(
            "%s lacks numpy_run" % type(self).__name__)

    def jax_run(self):
        raise NotImplementedError(
            "%s lacks jax_run" % type(self).__name__)

    # -- buffer helpers (reference: init_vectors/unmap_vectors) -------------
    def init_vectors(self, *arrays):
        for a in arrays:
            if a is not None and bool(a):
                a.mem  # materialize host view

    def unmap_vectors(self, *arrays):
        for a in arrays:
            if a is not None and bool(a):
                a.unmap()

    @staticmethod
    def new_array(data=None, name=None):
        return Array(data, name=name)


class TrivialAcceleratedUnit(AcceleratedUnit):
    def numpy_run(self):
        pass

    def jax_run(self):
        pass


class AcceleratedWorkflow(Workflow):
    """Workflow carrying a device for its accelerated units."""

    def __init__(self, workflow=None, **kwargs):
        super(AcceleratedWorkflow, self).__init__(workflow, **kwargs)

    def initialize(self, device=None, **kwargs):
        if device is None:
            device = get_device()
        return super(AcceleratedWorkflow, self).initialize(
            device=device, **kwargs)
