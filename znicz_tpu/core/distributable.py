"""The IDistributable protocol.

Parity with the reference's master-slave data-parallel contract
(SURVEY.md §2.8, §3.3; nn_units.py:178-211, 644-694).  In znicz_tpu the
*performance* path for data parallelism is SPMD psum over the ICI mesh
(znicz_tpu.parallel), but the protocol methods are kept because the
reference uses them in-process too — e.g. weight copy during forward-workflow
extraction (standard_workflow.py:282-286) — and they remain the portable
serialization boundary for elastic multi-process training over DCN.
"""


class IDistributable(object):
    """Units override the subset they need; defaults are no-ops."""

    negotiates_on_connect = False

    def generate_data_for_master(self):
        return None

    def generate_data_for_slave(self, slave=None):
        return None

    def apply_data_from_master(self, data):
        pass

    def apply_data_from_slave(self, data, slave=None):
        pass

    def drop_slave(self, slave=None):
        pass


class TriviallyDistributable(IDistributable):
    """Stateless under distribution (reference: pooling.py:122)."""
