"""Deterministic fault injection + transient-fault recovery primitives.

The reference platform's defining promise was surviving real failure —
Veles ran master–slave training where workers could die and rejoin
(PAPER.md §0).  znicz_tpu provides the TPU-era equivalent as three
cooperating pieces, and this module is the first two:

* **Fault-injection registry** — named injection *sites* threaded
  through the hot paths (``loader.fill``, ``fused.dispatch``,
  ``fused.host_fetch``, ``snapshot.write``, ``serving.forward``).  Each
  site calls :func:`check` behind the one-predicate :func:`enabled`
  gate (the health.py zero-overhead discipline: the disabled path is a
  single config-dict read — zero device syncs, zero compiles, zero
  allocation).  Rules fire **deterministically**: ``at`` (the site's
  N-th invocation), ``every`` (every K-th), or ``p`` with a dedicated
  per-rule ``numpy.random.RandomState(seed)`` — a chaos test replays
  exactly, every time.  Fault kinds model the real failure classes:
  ``io`` (loader/disk), ``xla`` (a transient RESOURCE_EXHAUSTED-style
  runtime error at dispatch/readback), ``stall`` (a slow backend — the
  site sleeps instead of raising) and ``crash`` (a non-transient error
  standing in for preemption/SIGKILL, which the supervised launcher
  must survive).
* **Transient classifier + bounded retry** — :func:`is_transient`
  separates "try again" failures (I/O errors, RESOURCE_EXHAUSTED /
  UNAVAILABLE / DEADLINE_EXCEEDED runtime errors) from real crashes;
  :func:`retry_call` wraps a callable in bounded exponential backoff.
  The loader's minibatch fill and the serving engine's executable
  dispatch retry through it (``root.common.retry`` knobs).

The third piece — supervised restart with mid-epoch resume — lives in
:mod:`znicz_tpu.launcher` (``run_supervised``) and
:mod:`znicz_tpu.core.snapshotter` (the window-interval trigger).

Everything is metered: ``faults.injected`` (+ per-site labeled
counters), ``faults.retries``, journal events (``fault.injected`` /
``fault.retry``), and a ``GET /debug/faults`` view on every HTTP
server built on :class:`~znicz_tpu.core.status_server.HandlerBase`.

Rules install programmatically (:func:`install`) or declaratively via
config — ``root.common.faults.rules`` maps site names to rule dicts,
so a chaos subprocess arms itself with::

    python -m znicz_tpu wine --config \
        "common.faults.enabled=True" --config \
        "common.faults.rules={'fused.dispatch': {'kind': 'crash', 'at': 7}}"
"""

import time

import numpy

from znicz_tpu.core.config import root, Config
from znicz_tpu.analysis import locksmith
from znicz_tpu.core import telemetry

import logging

logger = logging.getLogger("faults")

_cfg = root.common.faults
_retry_cfg = root.common.retry

#: recognized fault kinds (see module docstring)
KINDS = ("io", "xla", "crash", "stall")

#: status-code tokens marking a runtime error as transient — the set
#: XLA uses for "the op may succeed if retried" (plus the plain-OSError
#: class below).  DEADLINE_EXCEEDED/UNAVAILABLE are RPC-layer statuses
#: a tunneled TPU backend surfaces on flaky links.
TRANSIENT_TOKENS = ("RESOURCE_EXHAUSTED", "UNAVAILABLE",
                    "DEADLINE_EXCEEDED", "ABORTED")


class FaultInjectedError(Exception):
    """Marker mixin: every injected exception derives from it, so tests
    and the classifier can tell injected faults from organic ones."""


class InjectedIOError(FaultInjectedError, OSError):
    """Injected loader/disk I/O failure (transient)."""


class InjectedXlaError(FaultInjectedError, RuntimeError):
    """Injected device-runtime failure.  The message carries a real XLA
    status token (``RESOURCE_EXHAUSTED: ...``) so the transient
    classifier treats it exactly like the organic ``XlaRuntimeError``
    it stands in for."""


class InjectedCrashError(FaultInjectedError, RuntimeError):
    """Injected hard crash (non-transient) — the stand-in for
    preemption that only the supervised launcher's restart + resume
    path survives."""


def enabled():
    """The one gate every injection site tests (live config read, so a
    mid-run flip takes effect on the next site hit)."""
    return bool(_cfg.get("enabled", False))


def enable(rules=None, seed=None):
    """Arm the registry (optionally installing ``{site: rule}`` rules
    and the default probability seed)."""
    if seed is not None:
        root.common.faults.seed = int(seed)
    if rules:
        for site, rule in dict(rules).items():
            install(site, **dict(rule))
    root.common.faults.enabled = True
    return True


def disable():
    root.common.faults.enabled = False
    return False


class _Rule(object):
    """One armed fault: where it fires (at/every/p), what it raises,
    and how many times it is allowed to fire."""

    __slots__ = ("site", "kind", "at", "every", "p", "seed", "times",
                 "stall_ms", "message", "fired", "_rand")

    def __init__(self, site, kind="io", at=None, every=None, p=None,
                 seed=None, times=None, stall_ms=50.0, message=None):
        if kind not in KINDS:
            raise ValueError("unknown fault kind %r (known: %s)"
                             % (kind, ", ".join(KINDS)))
        if at is None and every is None and p is None:
            raise ValueError(
                "rule for %r needs a trigger: at=N, every=K or p=x"
                % site)
        self.site = site
        self.kind = kind
        self.at = None if at is None else int(at)
        self.every = None if every is None else int(every)
        self.p = None if p is None else float(p)
        self.seed = seed
        self.times = (1 if self.at is not None and times is None
                      else times)  # at=N naturally fires once
        if self.times is not None:
            self.times = int(self.times)
        self.stall_ms = float(stall_ms)
        self.message = message
        self.fired = 0
        # dedicated stream per rule: the draw sequence depends only on
        # (seed, invocation index), never on other sites' traffic
        self._rand = None
        if self.p is not None:
            base = int(_cfg.get("seed", 0) or 0) if seed is None \
                else int(seed)
            self._rand = numpy.random.RandomState(base & 0x7FFFFFFF)

    def should_fire(self, invocation):
        """Deterministic trigger decision for the site's
        ``invocation``-th call (1-based)."""
        if self.times is not None and self.fired >= self.times:
            return False
        if self.at is not None and invocation == self.at:
            return True
        if self.every is not None and invocation % self.every == 0:
            return True
        if self._rand is not None and \
                float(self._rand.random_sample()) < self.p:
            return True
        return False

    def describe(self):
        d = {"kind": self.kind, "fired": self.fired}
        for k in ("at", "every", "p", "times"):
            v = getattr(self, k)
            if v is not None:
                d[k] = v
        if self.kind == "stall":
            d["stall_ms"] = self.stall_ms
        return d


class _Registry(object):
    """Process-global site bookkeeping: per-site invocation counters
    and the armed rules."""

    def __init__(self):
        self._lock = locksmith.lock("faults.registry")
        self.rules = {}        # site -> _Rule
        self.invocations = {}  # site -> int
        self.injected = {}     # site -> int
        self.retries = 0
    def rule_for(self, site):
        rule = self.rules.get(site)
        if rule is not None:
            return rule
        # lazy adoption of config-declared rules (the CLI /
        # chaos-subprocess path: --config common.faults.rules={...}).
        # The absence of a rule is NOT cached: declaring a site at
        # runtime arms it on the next hit (the live-config contract),
        # and the miss path is two dict reads — cheap, and only ever
        # taken when faults are enabled.
        declared = _cfg.get("rules")
        if declared is None:
            return None
        spec = declared.get(site) if isinstance(
            declared, (dict, Config)) else None
        if spec is None:
            return None
        if isinstance(spec, Config):
            spec = spec.as_dict()
        rule = _Rule(site, **dict(spec))
        self.rules[site] = rule
        return rule


_registry_lock = locksmith.lock("faults.module")
_registry = None


def registry():
    global _registry
    if _registry is None:
        with _registry_lock:
            if _registry is None:
                _registry = _Registry()
    return _registry


def reset():
    """Fresh registry (tests, bench isolation).  Does not touch the
    config gate or declared rules."""
    global _registry
    with _registry_lock:
        _registry = None


def install(site, **spec):
    """Arm (or replace) one site's rule; see :class:`_Rule` for the
    trigger/kind vocabulary.  Returns the rule."""
    reg = registry()
    with reg._lock:
        rule = _Rule(site, **spec)
        reg.rules[site] = rule
    return rule


def clear(site=None):
    """Disarm one site's rule (or all of them)."""
    reg = registry()
    with reg._lock:
        if site is None:
            reg.rules.clear()
        else:
            reg.rules.pop(site, None)


def check(site):
    """One injection-site hit: advance the site's invocation counter
    and fire the armed rule when its deterministic trigger matches.
    ``stall`` sleeps; every other kind raises.  Call sites guard with
    ``if faults.enabled():`` — this function is never on a disabled
    hot path."""
    reg = registry()
    with reg._lock:
        n = reg.invocations.get(site, 0) + 1
        reg.invocations[site] = n
        rule = reg.rule_for(site)
        if rule is None or not rule.should_fire(n):
            return None
        rule.fired += 1
        reg.injected[site] = reg.injected.get(site, 0) + 1
        kind = rule.kind
        stall_ms = rule.stall_ms
        message = rule.message
    if telemetry.enabled():
        telemetry.counter("faults.injected").inc()
        telemetry.counter(
            telemetry.labeled("faults.injected", site=site)).inc()
    telemetry.record_event("fault.injected", site=site, fault=kind,
                           invocation=n)
    logger.warning("injected %s fault at %s (invocation %d)",
                   kind, site, n)
    if kind == "stall":
        time.sleep(stall_ms / 1e3)
        return None
    msg = message or "injected %s fault at %s (invocation %d)" % (
        kind, site, n)
    if kind == "io":
        raise InjectedIOError(msg)
    if kind == "xla":
        raise InjectedXlaError("RESOURCE_EXHAUSTED: " + msg)
    raise InjectedCrashError(msg)


# ---------------------------------------------------------------------------
# Transient-fault classification + bounded retry
# ---------------------------------------------------------------------------

def is_transient(exc):
    """Would retrying plausibly succeed?  True for I/O errors (a flaky
    disk/NFS read) and device-runtime errors carrying a retryable XLA /
    RPC status token — the organic ``XlaRuntimeError`` type name is
    matched so no private jaxlib import is needed.  Injected crash
    faults (and everything else) are terminal."""
    if isinstance(exc, InjectedCrashError):
        return False
    if isinstance(exc, OSError):
        # a flaky disk/NFS read is worth retrying; a missing file or a
        # permission wall is deterministic — retrying only burns the
        # budget before the inevitable crash
        return not isinstance(exc, (FileNotFoundError, PermissionError,
                                    NotADirectoryError,
                                    IsADirectoryError))
    name = type(exc).__name__
    if name == "XlaRuntimeError" or isinstance(exc, InjectedXlaError):
        text = str(exc)
        return any(tok in text for tok in TRANSIENT_TOKENS)
    return False


def note_retry(site, attempt, exc, delay_s):
    """Meter one retry decision (the caller is about to back off and
    try again)."""
    reg = registry()
    with reg._lock:
        reg.retries += 1
    if telemetry.enabled():
        telemetry.counter("faults.retries").inc()
        telemetry.counter(
            telemetry.labeled("faults.retries", site=site)).inc()
    telemetry.record_event("fault.retry", site=site, attempt=attempt,
                           error=repr(exc),
                           backoff_ms=round(delay_s * 1e3, 3))
    logger.warning("transient fault at %s (attempt %d, backing off "
                   "%.1f ms): %r", site, attempt, delay_s * 1e3, exc)


def retry_call(fn, site, attempts=None, classify=is_transient):
    """Call ``fn()`` with bounded exponential-backoff retry on
    transient failures.  ``attempts`` is the number of RETRIES after
    the first try (default ``root.common.retry.attempts``); backoff is
    ``backoff_base_ms * 2**attempt`` capped at ``backoff_max_ms``.
    Non-transient errors (and the final transient one) propagate."""
    if attempts is None:
        attempts = int(_retry_cfg.get("attempts", 3))
    base = float(_retry_cfg.get("backoff_base_ms", 5.0)) / 1e3
    cap = float(_retry_cfg.get("backoff_max_ms", 200.0)) / 1e3
    attempt = 0
    while True:
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 - classified below
            if attempt >= attempts or not classify(e):
                raise
            attempt += 1
            delay = min(base * (2 ** (attempt - 1)), cap)
            note_retry(site, attempt, e, delay)
            if delay > 0:
                time.sleep(delay)


# ---------------------------------------------------------------------------
# Introspection (GET /debug/faults)
# ---------------------------------------------------------------------------

def status():
    """The ``/debug/faults`` payload — safe with the registry cold
    (reports enabled=False and empty counters without creating one)."""
    out = {"enabled": enabled(),
           "retry": {
               "attempts": int(_retry_cfg.get("attempts", 3)),
               "backoff_base_ms": float(
                   _retry_cfg.get("backoff_base_ms", 5.0)),
               "backoff_max_ms": float(
                   _retry_cfg.get("backoff_max_ms", 200.0))},
           "rules": {}, "sites": {}, "retries": 0}
    reg = _registry  # read-only: never allocate just to report
    if reg is None:
        return out
    with reg._lock:
        out["rules"] = {s: r.describe() for s, r in reg.rules.items()}
        out["sites"] = {
            s: {"invocations": n, "injected": reg.injected.get(s, 0)}
            for s, n in reg.invocations.items()}
        out["retries"] = reg.retries
    return out
