"""Continuous statistical Python profiler — the fleet's CPU/GIL ledger.

ROADMAP item 3 names the per-request Python tax (JSON codecs, GIL
hand-offs, http hops on the router<->replica data plane) as the next
perf frontier, but until now those numbers existed only as one-off
hand measurements in the PR 15 notes.  This module makes them a
continuously sampled, attributed, stamped quantity — the layer BELOW
reqtrace's span trees (a span says "reply took 1.6 ms"; the sampler
says "1.1 ms of that was ``json/encoder.py:iterencode`` holding the
GIL"):

* a background **sampler** walks ``sys._current_frames()`` at an
  off-beat rate (``hz``, default 97 — deliberately coprime with the
  1000/100/5 ms cadences of the other planes so it never phase-locks
  with what it measures), folds each thread's stack into bounded
  collapsed-flamegraph aggregates, and attributes every sample to a
  **component** via the thread-name registry: every thread the
  codebase spawns carries a stable ``znicz:<component>`` name
  (:func:`thread_name` / :func:`name_current_thread`; the graftlint
  ``thread-name`` checker keeps spawn sites honest), so a profile
  reads "continuous batcher 41%, http handlers 38%" instead of
  ``Thread-12``;
* each sample's LEAF frame is classified into a fixed vocabulary of
  data-plane **phases** (:data:`PHASES`: ``json_decode`` /
  ``npy_decode`` / ``serialize`` / ``socket_io`` /
  ``device_dispatch`` / ``lock_wait`` / ``other``) — the axes of the
  Python-tax ledger ``bench.py`` stamps as
  ``serving_dataplane_python_pct``;
* a calibrated **scheduling-delay probe** estimates GIL wait as a
  first-class series: a probe thread sleeps a short quantum and
  measures the overshoot; the first ``gil_calib_probes`` overshoots
  establish the host's baseline scheduler latency (median) and only
  the EXCESS above it is attributed to GIL/scheduler contention
  (``pyprof.gil_wait_ms``);
* surfaces: ``GET /debug/pyprof?seconds=N`` on every HandlerBase
  server (collapsed + speedscope via ``format=``, 409 while another
  debug capture runs), the router's fleet merge
  (:func:`merge_profiles` — replica profiles summed into one
  stitched flamegraph with per-source attribution),
  ``pyprof.samples`` / ``pyprof.gil_wait_ms`` telemetry series
  (sampled by core/timeseries.py), and ``tools/profile_summary.py
  --pyprof`` / ``tools/flamegraph.py`` for rendering.

Disabled-by-default discipline (the health.py contract): everything
gates on ``root.common.profiler.pyprof.enabled``.  When off,
:func:`maybe_start` returns without touching anything, no thread
exists, no state dict is ever allocated, and every hook is ONE config
predicate (pinned by a monkeypatch-boom test).  The sampler meters its
own cost (``overhead.pct`` — time inside sample sweeps over wall
time), and ``bench.py`` stamps the armed-vs-disabled goodput tax as
``serving_pyprof_overhead_pct``, gated by tools/bench_gate.py.

Tests drive :func:`sample_once` with injectable frames / thread names
/ clock and :func:`gil_probe_once` with injectable delays, so the fold
math is checkable with zero sleeps and zero real threads.
"""

import os
import sys
import threading
import time

from znicz_tpu.core.config import root
from znicz_tpu.core import telemetry
from znicz_tpu.analysis import locksmith

#: the config node (stable object identity — config.py declares it)
_cfg = root.common.profiler.pyprof

_lock = locksmith.lock("pyprof.state")

telemetry.register_help(
    "pyprof", "continuous Python sampling profiler (core/pyprof.py): "
              "stack samples folded and GIL-wait milliseconds")

#: the thread-name convention every spawn site uses
THREAD_PREFIX = "znicz:"

#: the data-plane phase vocabulary — the axes of the Python-tax
#: ledger.  FIXED by design: the classifier may only ever answer one
#: of these (unknowns are a loud ValueError, never a silent new
#: bucket), so the bench stamp and the docs table can enumerate them.
PHASES = ("json_decode", "npy_decode", "serialize", "socket_io",
          "device_dispatch", "lock_wait", "other")

#: phases counted as the Python data-plane tax (codec + relay work a
#: zero-copy rewrite could remove) in dataplane_python_pct
DATAPLANE_PHASES = ("json_decode", "npy_decode", "serialize",
                    "socket_io")

_thread = None
_gil_thread = None
_stop = threading.Event()

#: lazily created on the first ARMED use — the disabled path never
#: allocates (zero-overhead-off contract)
_state = None


def enabled():
    """The one gate — a live read of
    ``root.common.profiler.pyprof.enabled``."""
    return bool(_cfg.get("enabled", False))


def enable(**overrides):
    for k, v in overrides.items():
        setattr(root.common.profiler.pyprof, k, v)
    root.common.profiler.pyprof.enabled = True
    return True


def disable():
    root.common.profiler.pyprof.enabled = False
    return False


# ---------------------------------------------------------------------------
# Thread-name registry
# ---------------------------------------------------------------------------

def thread_name(component):
    """The ``znicz:<component>`` name a spawn site passes to
    ``threading.Thread(name=...)`` — the other half of the contract is
    the graftlint ``thread-name`` checker flagging unnamed spawns."""
    return THREAD_PREFIX + str(component)


def name_current_thread(component):
    """Adopt the convention for a thread we did not spawn (the serve
    CLI's main thread, a pool handler thread at request entry)."""
    threading.current_thread().name = thread_name(component)


def component_of(name):
    """Thread name -> component: ``znicz:continuous-3`` ->
    ``continuous`` (one trailing ``-<index>`` pool suffix stripped so
    a pool folds into ONE component), anything off-convention ->
    ``unnamed`` — the bucket the >=90%%-attributed acceptance
    criterion counts against."""
    name = str(name or "")
    if not name.startswith(THREAD_PREFIX):
        return "unnamed"
    comp = name[len(THREAD_PREFIX):] or "unnamed"
    head, _, tail = comp.rpartition("-")
    if head and tail.isdigit():
        comp = head
    return comp


# ---------------------------------------------------------------------------
# Phase classification
# ---------------------------------------------------------------------------

_LOCK_FUNCS = frozenset(("wait", "acquire", "join",
                         "_wait_for_tstate_lock", "wait_for"))
_SOCKET_FILES = frozenset(("socket.py", "ssl.py", "selectors.py",
                           "socketserver.py", "client.py",
                           "server.py"))
_SOCKET_DIRS = ("/http/", "/urllib/", "/email/")
_JSON_DECODE_FUNCS = frozenset(("loads", "load", "decode",
                                "raw_decode", "scan_once",
                                "parse_object", "parse_array",
                                "parse_string", "JSONObject",
                                "JSONArray", "py_scanstring"))
_SERIALIZE_FUNCS = frozenset(("dumps", "dump", "encode", "iterencode",
                              "default", "floatstr",
                              "_iterencode", "_iterencode_dict",
                              "_iterencode_list", "tolist"))
_NPY_FUNCS = frozenset(("frombuffer", "read_array", "_read_bytes",
                        "read_magic", "read_array_header_1_0",
                        "write_array", "tobytes", "save"))


def classify(filename, funcname):
    """LEAF frame -> phase.  Total: always answers a member of
    :data:`PHASES` (the fold asserts it — a classifier change that
    invents a phase outside the vocabulary fails loudly rather than
    silently skewing the stamped ledger).  Precedence mirrors what a
    blocked thread actually shows: a thread parked in
    ``threading.wait`` is lock_wait even though threading.py is
    stdlib 'other' territory otherwise."""
    f = str(filename or "").replace("\\", "/")
    base = f.rsplit("/", 1)[-1]
    fn = str(funcname or "")
    if base in ("threading.py", "queue.py") or fn in _LOCK_FUNCS:
        return "lock_wait"
    if "/json/" in f or base in ("decoder.py", "encoder.py",
                                 "scanner.py"):
        if base == "encoder.py" or fn in _SERIALIZE_FUNCS:
            return "serialize"
        return "json_decode"
    if fn in _JSON_DECODE_FUNCS:
        return "json_decode"
    if "/numpy/lib/format" in f or ("/numpy/" in f and fn in
                                    _NPY_FUNCS):
        return "npy_decode"
    if fn in _SERIALIZE_FUNCS:
        return "serialize"
    if base in _SOCKET_FILES or any(d in f for d in _SOCKET_DIRS) \
            or fn in ("sendall", "recv", "recv_into", "readinto",
                      "accept", "makefile", "flush", "urlopen"):
        return "socket_io"
    if "/jax/" in f or "/jaxlib/" in f or fn == "block_until_ready":
        return "device_dispatch"
    if fn in _NPY_FUNCS:
        return "npy_decode"
    return "other"


# ---------------------------------------------------------------------------
# State
# ---------------------------------------------------------------------------

class _State(object):
    """Cumulative aggregates since arm/reset (all mutation under
    ``_lock``)."""

    __slots__ = ("samples", "sweeps", "truncated", "components",
                 "phases", "stacks", "busy_s", "started",
                 "gil_probes", "gil_calib", "gil_baseline_s",
                 "gil_wait_s")

    def __init__(self, now):
        self.samples = 0
        self.sweeps = 0
        self.truncated = 0
        self.components = {}
        self.phases = dict.fromkeys(PHASES, 0)
        self.stacks = {}       # "comp;frame;...;leaf" -> count
        self.busy_s = 0.0      # time spent INSIDE sample sweeps
        self.started = now     # perf_counter at first armed use
        self.gil_probes = 0
        self.gil_calib = []    # overshoots until calibrated
        self.gil_baseline_s = None
        self.gil_wait_s = 0.0


def _ensure_state(now):
    global _state
    if _state is None:
        _state = _State(now)
    return _state


def _modname(path):
    base = str(path or "?").replace("\\", "/").rsplit("/", 1)[-1]
    return base[:-3] if base.endswith(".py") else base


#: code object -> (folded "module:func" label, leaf phase) memo.  The
#: sweep's hot cost is path parsing + label formatting, and blocked
#: threads re-present IDENTICAL frames every sweep — memoizing per
#: code object cuts the per-sweep cost to dict lookups, which is what
#: keeps the 97 Hz default inside the bench-gated overhead budget.
#: Bounded: cleared wholesale past a cap no real program reaches.
_code_memo = {}


def _frame_info(code):
    info = _code_memo.get(code)
    if info is None:
        if len(_code_memo) > 8192:
            _code_memo.clear()
        info = ("%s:%s" % (_modname(code.co_filename), code.co_name),
                classify(code.co_filename, code.co_name))
        _code_memo[code] = info
    return info


def _fold(frame, max_depth):
    """Frame chain -> (collapsed root-first frame list, leaf phase) —
    the flamegraph fold."""
    out = []
    phase = None
    f = frame
    while f is not None and len(out) < max_depth:
        label, leaf_phase = _frame_info(f.f_code)
        if not out:
            phase = leaf_phase
        out.append(label)
        f = f.f_back
    out.reverse()
    return out, phase


# ---------------------------------------------------------------------------
# Sampling
# ---------------------------------------------------------------------------

def sample_once(frames=None, names=None, clock=None):
    """One sampler sweep: fold every live thread's stack into the
    aggregates.  Returns the number of samples recorded (0 when the
    gate is off — the disabled path reads ONE predicate and nothing
    else).  ``frames`` (ident -> frame), ``names`` (ident -> thread
    name) and ``clock`` are injectable so tests drive the fold math
    with synthetic stacks and zero real threads."""
    if not enabled():
        return 0
    clock = clock or time.perf_counter
    t0 = clock()
    if frames is None:
        frames = sys._current_frames()
    if names is None:
        names = {t.ident: t.name for t in threading.enumerate()}
    max_depth = int(_cfg.get("max_depth", 24))
    cap = int(_cfg.get("capacity", 512))
    recorded = 0
    with _lock:
        st = _ensure_state(t0)
        for ident, frame in frames.items():
            name = names.get(ident, "")
            if name.startswith(THREAD_PREFIX + "pyprof"):
                continue   # never profile the profiler's own threads
            comp = component_of(name)
            stack, phase = _fold(frame, max_depth)
            if not stack:
                continue
            if phase not in PHASES:
                raise ValueError(
                    "classify() answered %r — outside the fixed "
                    "phase vocabulary %s" % (phase, list(PHASES)))
            st.samples += 1
            st.components[comp] = st.components.get(comp, 0) + 1
            st.phases[phase] += 1
            key = comp + ";" + ";".join(stack)
            if key in st.stacks:
                st.stacks[key] += 1
            elif len(st.stacks) < cap:
                st.stacks[key] = 1
            else:
                st.truncated += 1
            recorded += 1
        st.sweeps += 1
        st.busy_s += max(0.0, clock() - t0)
    if telemetry.enabled() and recorded:
        telemetry.counter("pyprof.samples").inc(recorded)
    return recorded


def gil_probe_once(delay_s):
    """Feed one measured scheduling overshoot (actual sleep minus
    requested quantum).  The first ``gil_calib_probes`` overshoots
    calibrate the host's baseline scheduler latency (median); after
    that only the EXCESS above baseline counts as GIL/scheduler wait.
    Returns the excess seconds attributed (None when the gate is off,
    0.0 while calibrating)."""
    if not enabled():
        return None
    excess = 0.0
    with _lock:
        st = _ensure_state(time.perf_counter())
        st.gil_probes += 1
        if st.gil_baseline_s is None:
            st.gil_calib.append(max(0.0, float(delay_s)))
            if len(st.gil_calib) >= int(_cfg.get("gil_calib_probes",
                                                 20)):
                ordered = sorted(st.gil_calib)
                st.gil_baseline_s = ordered[len(ordered) // 2]
            return 0.0
        excess = max(0.0, float(delay_s) - st.gil_baseline_s)
        st.gil_wait_s += excess
    if telemetry.enabled() and excess > 0:
        telemetry.counter("pyprof.gil_wait_ms").inc(excess * 1e3)
    return excess


def _run():
    while not _stop.is_set():
        if not enabled():
            return  # gate flipped off: the thread retires itself
        t0 = time.perf_counter()
        try:
            sample_once()
        except Exception:  # noqa: BLE001 - a sampler must never die
            pass
        period = 1.0 / max(1.0, float(_cfg.get("hz", 97.0)))
        _stop.wait(max(0.001, period - (time.perf_counter() - t0)))


def _gil_run():
    while not _stop.is_set():
        if not enabled():
            return
        quantum = float(_cfg.get("gil_interval_ms", 5.0)) / 1e3
        t0 = time.perf_counter()
        if _stop.wait(quantum):
            return
        try:
            gil_probe_once(time.perf_counter() - t0 - quantum)
        except Exception:  # noqa: BLE001 - the probe must never die
            pass


def maybe_start():
    """Start the sampler (and, unless ``gil_probe`` is off, the
    scheduling-delay probe) iff the gate is on and no thread runs —
    idempotent; called by ``HttpServerBase.start`` so arming the knob
    before a server starts is all an operator does.  Returns True when
    a sampler is running after the call."""
    if not enabled():
        return False
    global _thread, _gil_thread
    with _lock:
        if _thread is not None and _thread.is_alive():
            return True
        _stop.clear()
        _ensure_state(time.perf_counter())
        _thread = threading.Thread(
            target=_run, name=thread_name("pyprof-sampler"),
            daemon=True)
        _thread.start()
        if bool(_cfg.get("gil_probe", True)):
            _gil_thread = threading.Thread(
                target=_gil_run, name=thread_name("pyprof-gil"),
                daemon=True)
            _gil_thread.start()
    return True


def stop():
    """Stop the sampler/probe threads (keeps the aggregates)."""
    global _thread, _gil_thread
    with _lock:
        threads = [t for t in (_thread, _gil_thread) if t is not None]
        _thread = _gil_thread = None
    _stop.set()
    for t in threads:
        t.join(timeout=5)
    _stop.clear()


def reset():
    """Drop every aggregate (tests, bench isolation)."""
    global _state
    stop()
    with _lock:
        _state = None
        _code_memo.clear()


def running():
    """True while a sampler thread is alive (tests + /statusz)."""
    with _lock:
        return _thread is not None and _thread.is_alive()


# ---------------------------------------------------------------------------
# Snapshots, captures and the fleet merge
# ---------------------------------------------------------------------------

def _attributed_pct(samples, components):
    if not samples:
        return 0.0
    unnamed = int(components.get("unnamed", 0))
    return round(100.0 * (samples - unnamed) / samples, 2)


def snapshot():
    """Cumulative JSON-able aggregates since arm/reset — what
    ``GET /debug/pyprof`` diffs over its window and the timeseries
    plane samples."""
    with _lock:
        st = _state
        if st is None:
            return {"enabled": enabled(), "samples": 0, "sweeps": 0,
                    "truncated": 0, "components": {}, "phases": {},
                    "stacks": {},
                    "gil": {"probes": 0, "baseline_ms": None,
                            "wait_ms": 0.0},
                    "overhead": {"busy_ms": 0.0, "uptime_ms": 0.0,
                                 "pct": 0.0},
                    "attributed_pct": 0.0}
        uptime = max(0.0, time.perf_counter() - st.started)
        out = {
            "enabled": enabled(),
            "samples": st.samples,
            "sweeps": st.sweeps,
            "truncated": st.truncated,
            "components": dict(st.components),
            "phases": dict(st.phases),
            "stacks": dict(st.stacks),
            "gil": {
                "probes": st.gil_probes,
                "baseline_ms": (None if st.gil_baseline_s is None
                                else round(st.gil_baseline_s * 1e3,
                                           4)),
                "wait_ms": round(st.gil_wait_s * 1e3, 3),
            },
            "overhead": {
                "busy_ms": round(st.busy_s * 1e3, 3),
                "uptime_ms": round(uptime * 1e3, 3),
                "pct": round(100.0 * st.busy_s / uptime, 3)
                if uptime > 0 else 0.0,
            },
        }
    out["attributed_pct"] = _attributed_pct(out["samples"],
                                            out["components"])
    return out


def _diff_counts(after, before):
    out = {}
    for k, v in (after or {}).items():
        d = int(v) - int((before or {}).get(k, 0))
        if d > 0:
            out[k] = d
    return out


def diff_snapshots(before, after):
    """``after - before`` over two :func:`snapshot` payloads: the
    profile of exactly the window between them (the /debug/pyprof
    capture semantics — cumulative aggregates never reset under a
    reader)."""
    samples = int(after.get("samples", 0)) - int(
        before.get("samples", 0))
    components = _diff_counts(after.get("components"),
                              before.get("components"))
    gil_a, gil_b = after.get("gil") or {}, before.get("gil") or {}
    ovh_a, ovh_b = (after.get("overhead") or {},
                    before.get("overhead") or {})
    busy = max(0.0, float(ovh_a.get("busy_ms", 0.0))
               - float(ovh_b.get("busy_ms", 0.0)))
    wall = max(0.0, float(ovh_a.get("uptime_ms", 0.0))
               - float(ovh_b.get("uptime_ms", 0.0)))
    return {
        "enabled": after.get("enabled", False),
        "samples": max(0, samples),
        "sweeps": int(after.get("sweeps", 0)) - int(
            before.get("sweeps", 0)),
        "truncated": max(0, int(after.get("truncated", 0))
                         - int(before.get("truncated", 0))),
        "components": components,
        "phases": _diff_counts(after.get("phases"),
                               before.get("phases")),
        "stacks": _diff_counts(after.get("stacks"),
                               before.get("stacks")),
        "gil": {
            "probes": int(gil_a.get("probes", 0)) - int(
                gil_b.get("probes", 0)),
            "baseline_ms": gil_a.get("baseline_ms"),
            "wait_ms": round(max(0.0, float(gil_a.get("wait_ms", 0.0))
                                 - float(gil_b.get("wait_ms", 0.0))),
                             3),
        },
        "overhead": {
            "busy_ms": round(busy, 3),
            "uptime_ms": round(wall, 3),
            "pct": round(100.0 * busy / wall, 3) if wall > 0 else 0.0,
        },
        "attributed_pct": _attributed_pct(max(0, samples),
                                          components),
    }


def capture(seconds=2.0, sleep=None):
    """Profile exactly the next ``seconds`` (clamped by
    ``capture_seconds_cap``): snapshot, wait, snapshot, diff — what
    ``GET /debug/pyprof?seconds=N`` serves.  ``{"enabled": False}``
    when the gate is off (the endpoint's honest answer); ``sleep`` is
    injectable for tests."""
    if not enabled():
        return {"enabled": False}
    seconds = max(0.05, min(
        float(seconds), float(_cfg.get("capture_seconds_cap", 30.0))))
    before = snapshot()
    (sleep or time.sleep)(seconds)
    out = diff_snapshots(before, snapshot())
    out["seconds"] = seconds
    out["pid"] = os.getpid()
    return out


def merge_profiles(payloads):
    """Merge per-process profiles into ONE stitched fleet flamegraph —
    the router's ``GET /debug/pyprof`` fan-out (PR 16
    merged-timeseries pattern).  ``payloads`` maps a source label
    (replica id, or ``"router"`` for the front end's own capture) to
    its capture/snapshot payload.  Counts SUM (components, phases,
    collapsed stacks, GIL wait); ``sources`` carries each process's
    sample count for attribution; ``overhead.pct`` merges as the MAX
    (the conservative "worst replica" tax view)."""
    out = {"enabled": False, "merged": True, "sources": {},
           "samples": 0, "truncated": 0, "components": {},
           "phases": {}, "stacks": {},
           "gil": {"probes": 0, "wait_ms": 0.0},
           "overhead": {"pct": 0.0}}
    for label in sorted(payloads):
        prof = payloads[label] or {}
        out["enabled"] = out["enabled"] or bool(prof.get("enabled"))
        out["sources"][label] = int(prof.get("samples", 0))
        out["samples"] += int(prof.get("samples", 0))
        out["truncated"] += int(prof.get("truncated", 0))
        for field in ("components", "phases", "stacks"):
            dst = out[field]
            for k, v in (prof.get(field) or {}).items():
                dst[k] = dst.get(k, 0) + int(v)
        gil = prof.get("gil") or {}
        out["gil"]["probes"] += int(gil.get("probes", 0))
        out["gil"]["wait_ms"] = round(
            out["gil"]["wait_ms"] + float(gil.get("wait_ms", 0.0)), 3)
        pct = float((prof.get("overhead") or {}).get("pct", 0.0))
        out["overhead"]["pct"] = max(out["overhead"]["pct"], pct)
    out["attributed_pct"] = _attributed_pct(out["samples"],
                                            out["components"])
    return out


# ---------------------------------------------------------------------------
# Renderers
# ---------------------------------------------------------------------------

def collapsed(profile):
    """The Brendan-Gregg collapsed-stack text of a profile payload:
    one ``component;frame;...;leaf count`` line per aggregate —
    flamegraph.pl / speedscope both import it."""
    stacks = profile.get("stacks") or {}
    return "\n".join("%s %d" % (key, stacks[key])
                     for key in sorted(stacks))


def speedscope(profile, name="pyprof"):
    """A speedscope-importable ``sampled`` profile document built from
    the collapsed aggregates (weights = sample counts)."""
    stacks = profile.get("stacks") or {}
    frames = []
    index = {}
    samples = []
    weights = []
    total = 0
    for key in sorted(stacks):
        chain = key.split(";")
        sample = []
        for fr in chain:
            if fr not in index:
                index[fr] = len(frames)
                frames.append({"name": fr})
            sample.append(index[fr])
        samples.append(sample)
        weights.append(int(stacks[key]))
        total += int(stacks[key])
    return {
        "$schema": "https://www.speedscope.app/file-format-schema"
                   ".json",
        "name": name,
        "shared": {"frames": frames},
        "profiles": [{
            "type": "sampled",
            "name": name,
            "unit": "none",
            "startValue": 0,
            "endValue": total,
            "samples": samples,
            "weights": weights,
        }],
    }
