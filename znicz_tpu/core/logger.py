"""Logger mixin — every unit logs with its own name prefix.

TPU-era equivalent of ``veles.logger.Logger`` (SURVEY.md §5.5).
"""

import logging

_configured = False


def setup_logging(level=logging.INFO):
    global _configured
    if _configured:
        return
    logging.basicConfig(
        level=level,
        format="%(asctime)s %(levelname).1s %(name)s: %(message)s",
        datefmt="%H:%M:%S")
    _configured = True


class Logger(object):
    """Mixin giving self.debug/info/warning/error with class-name prefixes."""

    def __init__(self, **kwargs):
        super(Logger, self).__init__()
        setup_logging()
        self._logger_ = logging.getLogger(
            kwargs.get("logger_name", type(self).__name__))

    @property
    def logger(self):
        try:
            return self._logger_
        except AttributeError:
            self._logger_ = logging.getLogger(type(self).__name__)
            return self._logger_

    def debug(self, msg, *args):
        self.logger.debug(msg, *args)

    def info(self, msg, *args):
        self.logger.info(msg, *args)

    def warning(self, msg, *args):
        self.logger.warning(msg, *args)

    def error(self, msg, *args):
        self.logger.error(msg, *args)

    def exception(self, msg="Exception", *args):
        self.logger.exception(msg, *args)
