"""Publisher — end-of-training report generation.

TPU-era equivalent of the reference's ``veles.publishing.Publisher``
(wired by standard_workflow.py:663-669: gathers IResultProvider metrics,
loader info and workflow metadata once ``decision.complete``).  The
reference renders to Confluence/Jinja backends; here the backends are
dependency-free: ``markdown``, ``json``, and ``html`` files written to a
directory, which the status server (:mod:`znicz_tpu.core.status_server`)
also serves.
"""

import glob
import json
import os
import time

from znicz_tpu.core.config import root
from znicz_tpu.core.units import Unit
from znicz_tpu.core import telemetry


class Publisher(Unit):
    """Gathers a report from the workflow and renders it.

    kwargs:
    * ``backends`` — iterable of {"markdown", "json", "html"}
      (default ("markdown", "json"));
    * ``directory`` — output dir (default <cache>/reports);
    * ``include_plots`` — link rendered plot pngs (default True).

    Attach result providers via ``result_providers.add(unit)`` (units
    implementing get_metric_names/get_metric_values — decisions and
    evaluators) and the loader via ``loader_unit``.
    """

    BACKENDS = ("markdown", "json", "html")

    def __init__(self, workflow, **kwargs):
        super(Publisher, self).__init__(workflow, **kwargs)
        self.backends = tuple(kwargs.get("backends",
                                         ("markdown", "json")))
        for b in self.backends:
            if b not in self.BACKENDS:
                raise ValueError("unknown publisher backend %r" % (b,))
        self.directory = kwargs.get("directory")
        self.include_plots = kwargs.get("include_plots", True)
        self.result_providers = set()
        self.loader_unit = None
        self.report = None       # last gathered report dict
        self.destinations = []   # files written

    def initialize(self, device=None, **kwargs):
        super(Publisher, self).initialize(device=device, **kwargs)
        if not self.directory:
            self.directory = os.path.join(root.common.dirs.cache,
                                          "reports")
        self._t0 = time.time()

    # -- gathering ----------------------------------------------------------
    def gather(self):
        wf = self.workflow
        report = {
            "workflow": type(wf).__name__,
            "name": getattr(wf, "name", type(wf).__name__),
            "time": time.strftime("%Y-%m-%d %H:%M:%S"),
            "elapsed_sec": round(time.time() - self._t0, 3),
            "config": root.as_dict() if hasattr(root, "as_dict") else {},
            "metrics": {},
            "loader": {},
            "unit_timings": [],
            "plots": [],
        }
        for provider in sorted(self.result_providers,
                               key=lambda u: u.name):
            names = provider.get_metric_names()
            values = provider.get_metric_values()
            if isinstance(values, dict):
                metrics = {str(k): values[k] for k in values}
            else:
                metrics = dict(zip(names, values))
            report["metrics"][provider.name] = _plain(metrics)
        ldr = self.loader_unit
        if ldr is not None:
            report["loader"] = _plain({
                "type": type(ldr).__name__,
                "class_lengths": list(getattr(ldr, "class_lengths", ())),
                "epochs": getattr(ldr, "epoch_number", None),
                "minibatch_size": getattr(ldr, "max_minibatch_size", None),
            })
        if hasattr(wf, "unit_timings"):
            report["unit_timings"] = [
                {"unit": u.name, "seconds": round(t, 4), "runs": n}
                for u, t, n in wf.unit_timings()]
        if self.include_plots:
            plot_dir = os.path.join(root.common.dirs.cache, "plots")
            report["plots"] = sorted(glob.glob(
                os.path.join(plot_dir, "*.png")))
        if telemetry.enabled():
            # multi-host runs publish ONE merged view (process 0 is
            # the writer; merged_snapshot is collective and must run
            # on every host of the gang)
            report["telemetry"] = telemetry.merged_snapshot()
        self.report = report
        return report

    # -- rendering ----------------------------------------------------------
    def run(self):
        report = self.gather()
        os.makedirs(self.directory, exist_ok=True)
        del self.destinations[:]
        stamp = time.strftime("%Y%m%d_%H%M%S")
        base = os.path.join(self.directory,
                            "%s_%s" % (report["name"], stamp))
        for backend in self.backends:
            path = getattr(self, "_render_" + backend)(report, base)
            self.destinations.append(path)
            self.info("published %s", path)

    def _render_json(self, report, base):
        path = base + ".json"
        with open(path, "w") as f:
            json.dump(report, f, indent=2, default=str)
        return path

    def _render_markdown(self, report, base):
        lines = ["# %s" % report["name"], "",
                 "*%s — %.1fs elapsed*" % (report["time"],
                                           report["elapsed_sec"]), ""]
        for provider, metrics in report["metrics"].items():
            lines += ["## %s" % provider, ""]
            lines += ["| metric | value |", "|---|---|"]
            lines += ["| %s | %s |" % (k, v) for k, v in metrics.items()]
            lines.append("")
        if report["loader"]:
            lines += ["## Data", ""]
            lines += ["| | |", "|---|---|"]
            lines += ["| %s | %s |" % (k, v)
                      for k, v in report["loader"].items()]
            lines.append("")
        if report["unit_timings"]:
            lines += ["## Unit timings", "",
                      "| unit | seconds | runs |", "|---|---|---|"]
            lines += ["| %s | %s | %s |" % (r["unit"], r["seconds"],
                                            r["runs"])
                      for r in report["unit_timings"][:20]]
            lines.append("")
        tel = report.get("telemetry")
        if tel:
            lines += ["## Telemetry", "",
                      "| series | value |", "|---|---|"]
            for k, v in sorted(tel.get("counters", {}).items()):
                lines.append("| %s | %s |" % (k, v))
            for k, v in sorted(tel.get("gauges", {}).items()):
                lines.append("| %s | %s |" % (k, v))
            for k, h in sorted(tel.get("histograms", {}).items()):
                lines.append(
                    "| %s | n=%s p50=%s p99=%s |"
                    % (k, h.get("count"), h.get("p50"), h.get("p99")))
            lines.append("")
        if report["plots"]:
            lines += ["## Plots", ""]
            lines += ["![%s](%s)" % (os.path.basename(p), p)
                      for p in report["plots"]]
            lines.append("")
        path = base + ".md"
        with open(path, "w") as f:
            f.write("\n".join(lines))
        return path

    def _render_html(self, report, base):
        md_rows = "".join(
            "<tr><td>%s</td><td><pre>%s</pre></td></tr>" % (p, json.dumps(
                m, indent=1, default=str))
            for p, m in report["metrics"].items())
        html = ("<html><head><title>%s</title></head><body>"
                "<h1>%s</h1><p>%s — %.1fs</p><table border=1>%s</table>"
                "%s</body></html>") % (
            report["name"], report["name"], report["time"],
            report["elapsed_sec"], md_rows,
            "".join('<img src="file://%s" width="400"/>' % p
                    for p in report["plots"]))
        path = base + ".html"
        with open(path, "w") as f:
            f.write(html)
        return path


def _plain(obj):
    """Recursively convert numpy scalars/arrays to JSON-able values."""
    import numpy
    if isinstance(obj, dict):
        return {k: _plain(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_plain(v) for v in obj]
    if isinstance(obj, numpy.ndarray):
        return obj.tolist()
    if isinstance(obj, numpy.generic):
        return obj.item()
    return obj
