"""The Unit dataflow-graph node.

TPU-era equivalent of ``veles.units.Unit`` (SURVEY.md layer L3).  Contract
observed at reference call sites:

* ``link_from(*parents)`` — control edges; a unit fires when ALL parents have
  signalled (``Repeater`` fires on ANY, closing the training loop).
* ``link_attrs(other, "a", ("mine", "theirs"))`` — live attribute aliasing;
  reads and writes forward to the source unit (standard_workflow.py:346-363).
* ``gate_block`` / ``gate_skip`` — ``mutable.Bool`` gates: *block* consumes
  the signal (no run, no propagation); *skip* propagates without running
  (standard_workflow.py:365,488,514,528).
* ``demand("attr")`` — attributes that must be non-None by ``initialize``
  (all2all.py:100, conv.py:63).

In znicz_tpu this graph is the *epoch-level control plane*; per-minibatch
compute lives in jitted pure functions (znicz_tpu.ops).  Python-level gating
is cheap at that cadence and semantically identical to the reference.
"""

import time

from znicz_tpu.core.config import root
from znicz_tpu.core.logger import Logger
from znicz_tpu.core.mutable import Bool
from znicz_tpu.core import telemetry


def sync_timings_enabled():
    """Sync the device after each run() so run_time_ measures compute,
    not async dispatch.  Config-backed (``root.common.timings.
    sync_each_run``, default off — it serializes the pipeline; turn on
    when profiling with Workflow.log_unit_timings).  Was the mutable
    class global ``Unit.sync_timings``: a test flipping that leaked
    blocking-sync mode into every later test, while config is
    restored by the harness (tests/conftest.py)."""
    return bool(root.common.timings.get("sync_each_run", False))


class Unit(Logger):
    """A node in the control-plane dataflow graph."""

    def __init__(self, workflow, **kwargs):
        self.name = kwargs.get("name", type(self).__name__)
        super(Unit, self).__init__(logger_name=self.name)
        self._links_from = {}      # src unit -> fired flag
        self._links_to = {}        # dst unit -> True
        self._linked_attrs_ = {}   # my attr -> (src unit, src attr, two_way)
        self.gate_block = kwargs.get("gate_block", Bool(False))
        self.gate_skip = kwargs.get("gate_skip", Bool(False))
        self._demanded = set()
        self.view_group = kwargs.get("view_group", None)
        self._initialized = False
        self.run_was_called = False
        #: per-unit wall-time debug stats (reference nn_units.py:217-239
        #: print_debug_data — here gathered by the engine for every unit)
        self.run_time_ = 0.0
        self.run_count_ = 0
        self.workflow = None
        if workflow is not None:
            workflow.add_unit(self)

    # -- attribute forwarding ----------------------------------------------
    def __getattr__(self, name):
        # Only called when normal lookup fails.
        if name.startswith("_"):
            raise AttributeError(name)
        linked = self.__dict__.get("_linked_attrs_")
        if linked and name in linked:
            src, src_attr, _ = linked[name]
            return getattr(src, src_attr)
        raise AttributeError("%s has no attribute %r" % (self.name, name))

    def __setattr__(self, name, value):
        linked = self.__dict__.get("_linked_attrs_")
        if linked and name in linked:
            src, src_attr, two_way = linked[name]
            if two_way:
                setattr(src, src_attr, value)
            else:
                del linked[name]  # local write detaches a one-way alias
                object.__setattr__(self, name, value)
            return
        object.__setattr__(self, name, value)

    def link_attrs(self, other, *args, two_way=True):
        """Alias attributes of ``other`` as my own (live references).

        ``two_way=False`` makes a read-only alias: a local write detaches
        the link instead of mutating the source unit.
        """
        for arg in args:
            if isinstance(arg, tuple):
                mine, theirs = arg
            else:
                mine = theirs = arg
            if mine in self.__dict__:
                del self.__dict__[mine]
            self._linked_attrs_[mine] = (other, theirs, two_way)
        return self

    def has_linked_attr(self, name):
        return name in self._linked_attrs_

    # -- demands ------------------------------------------------------------
    def demand(self, *names):
        self._demanded.update(names)

    def undemand(self, *names):
        self._demanded.difference_update(names)

    def _check_demands(self):
        missing = []
        for name in sorted(self._demanded):
            try:
                v = getattr(self, name)
            except AttributeError:
                v = None
            if v is None:
                missing.append(name)
        return missing

    # -- control edges -------------------------------------------------------
    def link_from(self, *parents):
        for p in parents:
            self._links_from[p] = False
            p._links_to[self] = True
        return self

    def unlink_from(self, *parents):
        for p in parents:
            self._links_from.pop(p, None)
            p._links_to.pop(self, None)
        return self

    def unlink_all(self):
        for p in list(self._links_from):
            self.unlink_from(p)
        for d in list(self._links_to):
            d.unlink_from(self)
        return self

    @property
    def links_from(self):
        return self._links_from

    @property
    def links_to(self):
        return self._links_to

    # -- firing protocol -----------------------------------------------------
    def _signal(self, src):
        """A parent finished; fire when all parents have."""
        if src in self._links_from:
            self._links_from[src] = True
        if self._ready_to_fire():
            self.workflow._schedule(self)

    def _ready_to_fire(self):
        return all(self._links_from.values())

    def _reset_fired(self):
        for k in self._links_from:
            self._links_from[k] = False

    def _fire(self):
        """Called by the workflow scheduler when this unit's turn comes."""
        self._reset_fired()
        if bool(self.gate_block):
            return  # consume the signal
        if not bool(self.gate_skip):
            t0 = time.perf_counter()
            if telemetry.enabled():
                # the sync stays INSIDE the span so the trace and
                # run_time_/unit.run_seconds agree about the same fire
                with telemetry.span("unit." + self.name,
                                    cls=type(self).__name__):
                    self.run()
                    self._sync_device_for_timings()
            else:
                self.run()
                self._sync_device_for_timings()
            dt = time.perf_counter() - t0
            self.run_time_ += dt
            self.run_count_ += 1
            self.run_was_called = True
            if telemetry.enabled():
                telemetry.counter("unit.runs").inc()
                telemetry.histogram("unit.run_seconds").observe(dt)
        for dst in list(self._links_to):
            dst._signal(self)

    def _sync_device_for_timings(self):
        """Blocking-sync timing mode (sync_timings_enabled): device
        work is dispatched async, so without a sync compute time lands
        on whichever later unit blocks (map_read)."""
        if sync_timings_enabled():
            device = getattr(self, "device", None)
            if device is not None and hasattr(device, "sync"):
                device.sync()

    # -- lifecycle ------------------------------------------------------------
    @property
    def initialized(self):
        return self._initialized

    def initialize(self, device=None, **kwargs):
        """Allocate buffers etc.  Subclasses override; call super() first."""
        self._initialized = True

    def run(self):
        pass

    def stop(self):
        pass

    @property
    def is_slave(self):
        wf = self.workflow
        return wf.is_slave if wf is not None else False

    @property
    def is_master(self):
        wf = self.workflow
        return wf.is_master if wf is not None else False

    @property
    def is_standalone(self):
        return not self.is_slave and not self.is_master

    def __repr__(self):
        return "<%s %r>" % (type(self).__name__, self.name)


class IUnit(object):
    """Marker interface kept for reference parity (veles.units.IUnit)."""


def nothing(*args, **kwargs):
    """No-op placeholder (reference: veles.units.nothing)."""
    return None


class TrivialUnit(Unit):
    """A unit that does nothing when run."""

    def run(self):
        pass
