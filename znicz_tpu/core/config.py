"""Hierarchical attribute-dict configuration tree.

TPU-era equivalent of ``veles.config`` (reference usage:
samples/MNIST/mnist_config.py:43-89, standard_workflow_base.py:56-71).
Namespaces auto-vivify on attribute access; ``update`` merges nested dicts;
values may be arbitrary Python objects (including ``genetics.Range``).
"""

import json


class Config(object):
    """One node of the config tree.  Attribute access auto-creates children."""

    def __init__(self, path="root", **kwargs):
        object.__setattr__(self, "_path_", path)
        for k, v in kwargs.items():
            setattr(self, k, v)

    # -- auto-vivification --------------------------------------------------
    def __getattr__(self, name):
        if name.startswith("_") and name.endswith("_"):
            raise AttributeError(name)
        child = Config("%s.%s" % (self._path_, name))
        object.__setattr__(self, name, child)
        return child

    def __setattr__(self, name, value):
        if isinstance(value, dict):
            node = getattr(self, name)
            if isinstance(node, Config):
                node.update(value)
                return
            value_cfg = Config("%s.%s" % (self._path_, name))
            value_cfg.update(value)
            value = value_cfg
        object.__setattr__(self, name, value)

    # -- dict-ish interface -------------------------------------------------
    def update(self, value=None, **kwargs):
        """Recursively merge a dict (or another Config) into this node."""
        if value is None:
            value = kwargs
        if isinstance(value, Config):
            value = value.as_dict()
        if not isinstance(value, dict):
            raise TypeError(
                "Config.update takes a dict, got %s" % type(value))
        for k, v in value.items():
            if isinstance(v, dict):
                node = getattr(self, k)
                if isinstance(node, Config):
                    node.update(v)
                else:
                    setattr(self, k, v)
            else:
                object.__setattr__(self, k, v)
        return self

    def __contains__(self, name):
        return name in self.__dict__

    def get(self, name, default=None):
        v = self.__dict__.get(name, default)
        return v

    def items(self):
        return ((k, v) for k, v in self.__dict__.items()
                if not (k.startswith("_") and k.endswith("_")))

    def keys(self):
        return (k for k, _ in self.items())

    def as_dict(self):
        out = {}
        for k, v in self.items():
            out[k] = v.as_dict() if isinstance(v, Config) else v
        return out

    @property
    def __content__(self):
        """Reference-compatible dict view (StandardWorkflowBase.dictify)."""
        return self.as_dict()

    # -- presentation -------------------------------------------------------
    def __repr__(self):
        return "<Config %s: %s>" % (self._path_, sorted(self.__dict__))

    def print_(self, indent=0, file=None):
        import sys
        file = file or sys.stdout
        for k, v in sorted(self.items()):
            if isinstance(v, Config):
                print("%s%s:" % ("  " * indent, k), file=file)  # noqa
                v.print_(indent + 1, file)
            else:
                print("%s%s: %s" % ("  " * indent, k, v), file=file)  # noqa

    def to_json(self):
        def default(o):
            if isinstance(o, Config):
                return o.as_dict()
            return repr(o)
        return json.dumps(self.as_dict(), default=default, sort_keys=True)


#: The global configuration root (reference: ``veles.config.root``).
root = Config("root")


# ---------------------------------------------------------------------------
# Knob registry — declare-before-read config hygiene
# ---------------------------------------------------------------------------

#: declared LEAF knobs, dotted paths relative to ``root``
#: (e.g. "common.serving.max_batch")
_KNOBS = set()
#: declared NAMESPACE nodes (e.g. "common.serving") — reading a whole
#: node (to alias it or walk its keys) is legal; reading an undeclared
#: key under one is not
_NODES = set()


def declare(path, value):
    """Declare a knob (scalar ``value``) or a whole namespace (dict
    ``value``) under ``root.<path>``, installing its default and
    registering the path in the knob registry.

    The registry is THE vocabulary ``tools/graftlint.py``'s
    ``knob-vocabulary`` checker enforces: every ``root.common.*`` read
    or write anywhere in the library must resolve to a declared path.
    Auto-vivification makes a typo'd knob a silent default (an
    untouched Config node is even *truthy*), so new knobs must be
    declared here — in exactly one place — before any code reads them.
    """
    parts = path.split(".")
    if not parts or not all(parts):
        raise ValueError("bad knob path %r" % path)
    node = root
    for part in parts[:-1]:
        node = getattr(node, part)
        if not isinstance(node, Config):
            raise ValueError(
                "cannot declare %r: %s is a leaf knob, not a "
                "namespace" % (path, node))
    if isinstance(value, (dict, Config)):
        as_dict = value if isinstance(value, dict) else value.as_dict()
        setattr(node, parts[-1], as_dict)
        if as_dict:
            _register(path, getattr(node, parts[-1]).as_dict())
        else:
            # an empty dict declares an OPEN dict-valued knob — same
            # rule as a nested empty dict (e.g. common.faults.rules):
            # its payload is config data, not vocabulary
            _KNOBS.add(path)
    else:
        if parts[-1] not in node.__dict__:
            # an operator override set before the declaration wins
            setattr(node, parts[-1], value)
        _KNOBS.add(path)
    for i in range(1, len(parts)):
        _NODES.add(".".join(parts[:i]))
    return path


def _register(prefix, tree):
    _NODES.add(prefix)
    for k, v in tree.items():
        sub = "%s.%s" % (prefix, k)
        if isinstance(v, dict) and v:
            _register(sub, v)
        else:
            # an EMPTY dict default declares an open dict-valued knob
            # (e.g. common.faults.rules) — its content is config
            # payload, not vocabulary
            _KNOBS.add(sub)


def declared_knobs():
    """Frozen view of the declared LEAF knob paths."""
    return frozenset(_KNOBS)


def declared_nodes():
    """Frozen view of the declared NAMESPACE paths."""
    return frozenset(_NODES)


def knob_declared(path):
    """Is ``path`` (dotted, relative to ``root``) a legal config read?
    True for declared knobs and namespaces, and for any path UNDER a
    declared leaf knob (data inside a dict-valued knob like
    ``common.faults.rules`` is config payload, not vocabulary)."""
    if path in _KNOBS or path in _NODES:
        return True
    parts = path.split(".")
    for i in range(1, len(parts)):
        if ".".join(parts[:i]) in _KNOBS:
            return True
    return False


# Engine-level defaults observed in the reference
# (samples/CIFAR10/cifar_caffe_config.py:52-53, site_config.py:37-40).
declare("common", {
    "engine": {
        "precision_type": "float",    # "float" | "double" | "bfloat16"
        "precision_level": 0,         # 0: fast, 1: deterministic-ish
        "backend": "auto",            # "numpy" | "jax" | "auto"
        # explicit minibatch/staging dtype override read by
        # Loader.create_minibatch_data and the fused trainer (None:
        # follow the data / precision_type) — was read but UNDECLARED
        # until graftlint's knob-vocabulary checker flagged it
        "precision_dtype": None,
    },
    "dirs": {
        "datasets": "/root/repo/.data",
        "snapshots": "/root/repo/.snapshots",
        "cache": "/root/repo/.cache",
    },
    "disable": {"plotting": True, "publishing": True},
    # interactive Shell unit gate (core/interaction.py) — MUST be
    # declared: an undeclared read would auto-vivify a truthy empty
    # Config node and silently force every Shell interactive on a tty
    "interactive": False,
    # static/runtime analysis layer (znicz_tpu/analysis/) — off by
    # default; when off the locksmith lock factories hand out plain
    # threading primitives after ONE config predicate
    "analysis": {
        "lock_sanitizer": False,
    },
    # unified telemetry (core/telemetry.py) — off by default so every
    # instrumented hot path reduces to a guard-only no-op
    "telemetry": {
        "enabled": False,
        "trace_capacity": 65536,    # span ring-buffer size (events)
        "histogram_window": 2048,   # percentile reservoir per series
        "journal_capacity": 4096,   # flight-recorder ring (events)
        # metric time-series (core/timeseries.py) — a background
        # sampler snapshotting selected counters/gauges/histogram
        # percentiles into bounded timestamped rings, served at
        # GET /debug/timeseries.  Off by default; when off the sampler
        # thread never starts and every hook is ONE config predicate.
        "timeseries": {
            "enabled": False,
            "interval_ms": 1000.0,  # sampling period
            "capacity": 512,        # points retained per series
            # comma-separated family prefixes worth a history (every
            # matching counter/gauge gets a ring; histograms record
            # their p50/p99) — keep it a bounded curated set
            "prefixes":
                "serving,slo,jax,trainer,transfer,loader,pyprof",
        },
        # durable blackbox (core/blackbox.py) — crash-safe on-disk
        # persistence for the journal/timeseries/SLO/trace planes as
        # length-delimited JSONL segments <role>.<pid>.<boot>.<nnn>
        # under ONE shared dir, queried by `python -m znicz_tpu obs`.
        # Off by default; when off maybe_arm() is ONE config predicate
        # and the process never touches the filesystem.
        "blackbox": {
            "enabled": False,
            "dir": None,              # default: <cache>/blackbox —
                                      # the fleet router pins its
                                      # resolved dir into every
                                      # replica so all processes share
            "role": None,             # segment-name role; the fleet
                                      # forwards "replica"/"router",
                                      # else the arming call site's
                                      # default wins
            "segment_bytes": 1 << 20,  # rotate (fsync file, then dir)
                                       # past this size
            "retention_bytes": 64 << 20,  # delete oldest whole
                                          # segments (never the live
                                          # one) past this dir total;
                                          # 0 disables retention
            "checkpoint_every_sweeps": 5,  # persist the timeseries
                                           # frontier every Nth
                                           # sampler sweep
        },
    },
    # numeric training-health monitor (core/health.py) — off by default;
    # when off every check site is a single predicate with ZERO device
    # syncs.  See docs/observability.md for each knob.
    "health": {
        "enabled": False,
        "interval": 1,            # check every N train steps/minibatches
        "policy": "warn",        # "warn" | "snapshot" | "halt"
        "grad_norm_limit": 0.0,   # 0 disables the explosion check
        "param_norm_limit": 0.0,
        "update_norm_limit": 0.0,
        "loss_window": 8,         # divergence detector window (epochs)
        "loss_ema_alpha": 0.3,    # EMA smoothing for the explosion test
        "divergence_factor": 3.0,  # loss > factor*EMA => explosion
        "loss_rise": 0.1,         # net rise across a full window => slope
        "crash_dir": None,        # default: <cache>/crash_reports
    },
    # performance introspection (core/profiler.py) — off by default;
    # when off every hook site is a single predicate with ZERO device
    # syncs and zero compiles.  See docs/observability.md for each knob.
    "profiler": {
        "enabled": False,
        "cost_rtol": 0.5,         # measured/analytic FLOPs agreement
                                  # band: [1-rtol, 1+rtol]
        "leak_epochs": 3,         # consecutive growing epochs before
                                  # the ledger flags a leak suspect
        "leak_min_bytes": 1 << 20,  # ignore sub-MiB epoch growth
        "capture_seconds_cap": 60.0,  # /debug/profile?seconds= ceiling
        "capture_dir": None,      # default: <cache>/profiles
        # continuous Python sampling profiler (core/pyprof.py) — off
        # by default; when off no sampler thread exists and every hook
        # is ONE config predicate.  Attributes sys._current_frames()
        # samples to znicz:<component> thread names and classifies
        # leaves into the fixed data-plane phase vocabulary; a
        # calibrated scheduling-delay probe estimates GIL wait.
        # Served at GET /debug/pyprof (fleet-merged on the router).
        "pyprof": {
            "enabled": False,
            "hz": 97.0,             # sample rate — off-beat on
                                    # purpose (coprime with the
                                    # 1000/100/5 ms plane cadences)
            "capacity": 512,        # distinct collapsed stacks kept
            "max_depth": 24,        # frames folded per stack
            "gil_probe": True,      # scheduling-delay probe thread
            "gil_interval_ms": 5.0,  # probe sleep quantum
            "gil_calib_probes": 20,  # overshoots -> median baseline
            "capture_seconds_cap": 30.0,  # /debug/pyprof?seconds= cap
        },
    },
    # deterministic fault injection (core/faults.py) — off by default;
    # when off every injection site is a single predicate with ZERO
    # device syncs and zero compiles.  Rules map site names to trigger
    # dicts ({"kind": "io"|"xla"|"crash"|"stall", "at": N | "every": K
    # | "p": x, "times": M, "stall_ms": ...}) so chaos tests replay
    # deterministically.  See docs/deployment.md "Fault tolerance".
    "faults": {
        "enabled": False,
        "seed": 0,            # default stream for p-mode rules
        "rules": {},          # site -> rule dict (declarative arming)
    },
    # bounded-retry policy for TRANSIENT faults (loader minibatch fill,
    # serving executable dispatch — core/faults.py retry_call); always
    # armed: a try/except around an already-expensive call costs
    # nothing until a fault actually fires
    "retry": {
        "attempts": 3,          # retries AFTER the first try
        "backoff_base_ms": 5.0,  # exponential base; doubles per retry
        "backoff_max_ms": 200.0,  # backoff ceiling
    },
    # engine timing behavior (was the mutable class global
    # Unit.sync_timings; config-backed so tests can't leak
    # blocking-sync mode into the rest of the suite)
    "timings": {"sync_each_run": False},
    # online inference serving defaults (znicz_tpu/serving/ — see
    # docs/serving.md for every knob's meaning)
    "serving": {
        "host": "127.0.0.1",
        "port": 8899,
        "max_batch": 64,        # micro-batch ceiling = largest bucket
        "max_delay_ms": 5.0,    # batching window after first request
        "queue_limit": 256,     # queued ROWS before 429 backpressure
        "timeout_ms": 1000.0,   # per-request deadline in the queue
        "warmup": True,         # compile every bucket before ready
        # default serving precision recorded in export warmup
        # manifests ("f32" | "f32-fast" | "bf16" | "int8"); engines
        # without an explicit dtype= adopt the source manifest's value
        "dtype": "f32",
        # batch-1 latency fast path (serving dtype "f32-fast"): shape
        # buckets up to this size dispatch the restructured forward —
        # the contraction runs as a STANDALONE dot (kept out of the
        # bias/activation fusion by an optimization barrier) over the
        # dot-native weight layout, which keeps XLA's low-batch dot on
        # its fast path.  Read at engine LOAD time (part of the
        # compile key); larger buckets keep the fused-epilogue path.
        "latency_bucket_max": 8,
        "slow_request_ms": 1000.0,  # log requests slower than this
        # graceful degradation (serving/breaker.py + HandlerBase):
        "breaker_threshold": 5,     # consecutive dispatch failures
                                    # before a bucket's breaker opens
                                    # (0 disables circuit breaking)
        "breaker_cooldown_ms": 1000.0,  # open -> half-open delay; also
                                        # the Retry-After hint on 503s
        "breaker_half_open_max": 1,  # concurrent half-open probes
        "max_body_bytes": 16 << 20,  # request bodies over this get 413
                                     # (0 disables the cap)
        # continuous batching (serving/continuous.py): dispatch slots
        # that admit queued requests the moment capacity frees —
        # max_inflight concurrent engine dispatches across all models
        "max_inflight": 2,
        # multi-model registry (serving/registry.py): device-memory
        # budget for resident models; the least-recently-used cold
        # model's executables + device params are evicted when the
        # resident total exceeds it (0 = unlimited, never evict)
        "registry_memory_budget_bytes": 0,
        # latency SLO used by tools/loadgen.py goodput accounting and
        # stamped by bench.py --serving
        "slo_ms": 100.0,
        # server-side SLO tracking (serving/slo.py): per-model
        # good/total accounting against slo_ms measured from request
        # admission, Google-SRE multi-window burn rates and an
        # error-budget-remaining gauge — the feed for /slo, the
        # /statusz slo block and the future autoscaler.  Off by
        # default; when off the HTTP front end pays ONE predicate.
        "slo_enabled": False,
        "slo_target_pct": 99.0,     # availability target: good/total
        "slo_fast_window_s": 60.0,  # fast burn window (page-now)
        "slo_slow_window_s": 600.0,  # slow burn window (budget window)
        "slo_burn_threshold": 2.0,  # both windows over this -> one
                                    # slo.burn journal event (edge-
                                    # triggered with hysteresis)
        # per-request trace trees (serving/reqtrace.py): head-sample
        # every Nth admitted request into a rid-keyed span tree
        # (admission/queue_wait/assembly/dispatch/device/reply),
        # retrievable at GET /debug/trace/<rid>.  0 = off (the
        # default); when off every hook is ONE config predicate.
        "trace_sample_n": 0,
        "trace_capacity": 256,      # sampled trace trees retained
        # priority lanes (serving/continuous.py): each request carries
        # a priority ("high" | "normal" | "low"; X-Priority header or
        # the body's "priority" field).  Under queue pressure the low
        # lanes shed FIRST: a priority admits only while the queued
        # rows sit under its share of queue_limit, so under overload
        # low-priority traffic turns into fast 429s while
        # high-priority goodput holds.  "normal" (the default lane)
        # keeps the FULL queue — default traffic admits exactly as it
        # always did; lower it (e.g. 85) for three-tier shedding.
        # High additionally wins at DISPATCH (lane rank), so it holds
        # goodput even where admission ceilings tie.
        "priority_queue_pct": {
            "low": 50.0,        # low admits under 50% occupancy
            "normal": 100.0,    # default traffic: full queue
            "high": 100.0,      # high admits up to queue_limit
        },
        # admitted-request-id ring (serving/continuous.py): the
        # batcher remembers the last N admitted rids so the fleet
        # router can prove a request never reached a replica's batcher
        # before retrying it on a peer (GET /admitted/<rid>)
        "admitted_rid_capacity": 4096,
        # binary framed relay (serving/wire.py) — the persistent
        # length-prefixed router<->replica transport; see
        # docs/serving.md "Wire protocol" for the frame layout and
        # the zero-copy ingest contract
        "wire": {
            "enabled": True,         # the binary relay is the DEFAULT
                                     # router<->replica transport;
                                     # False falls back to HTTP/JSON
                                     # everywhere (the documented
                                     # compatibility surface)
            "conns_per_replica": 2,  # persistent mux connections the
                                     # router keeps per replica
            "max_frame_mb": 32.0,    # frame-body ceiling; oversize
                                     # answers a typed error frame
            "read_timeout_ms": 10000.0,  # half-frame (slowloris)
                                         # sweep deadline
            "workers": 128,          # listener dispatch threads.  A
                                     # worker PARKS through the whole
                                     # blocking /predict state
                                     # machine, so this bounds how
                                     # many in-flight frames reach
                                     # lane admission concurrently —
                                     # undersize it and overload
                                     # queues FIFO in the pool AHEAD
                                     # of the priority lanes (HTTP
                                     # got this for free from thread-
                                     # per-connection).  Sized past
                                     # queue-limit so every arriving
                                     # frame is shed or queued BY
                                     # PRIORITY, never by arrival.
        },
        # multi-replica serving fleet (serving/router.py +
        # serving/autoscaler.py) — see docs/serving.md "Fleet
        # topology" for every knob's meaning
        "fleet": {
            "replicas": 2,           # serve --fleet default size
            "spawn_timeout_s": 180.0,  # replica must print its URL +
                                       # pass /healthz within this
            "probe_interval_s": 1.0,   # health-monitor poll period
            "probe_failures": 3,       # consecutive failed probes
                                       # before an ejection
            "route_retries": 2,        # peer retries per request when
                                       # a resend is provably safe
            "overhead_window": 512,    # proxied 200s retained for the
                                       # router_overhead_ms summary
                                       # (/slo + /statusz; PR 16)
            # the autoscaler (serving/autoscaler.py):
            "min_replicas": 1,
            "max_replicas": 4,
            "autoscale_interval_s": 5.0,  # decision cadence
            "scale_up_burn_threshold": 2.0,  # fleet fast+slow burn
                                             # over this -> scale up
            "scale_up_queue_rows": 256.0,    # fleet queued rows per
                                             # replica over this ->
                                             # scale up
            "scale_down_budget_min": 0.97,   # budget comfortably
                                             # green before a retire
            "scale_down_evals": 3,   # consecutive green decisions
                                     # before a scale-down (hysteresis)
            "cooldown_s": 30.0,      # min seconds between actions
        },
        # progressive delivery (serving/release.py) — the SLO-judged
        # shadow -> canary -> promote pipeline; see docs/deployment.md
        # "Continuous delivery" for every knob's meaning.  A POST
        # /release body's "policy" object overrides any knob for that
        # one release.
        "release": {
            "shadow_sample_pct": 100.0,  # % of live traffic mirrored
                                         # to the candidate in shadow
            "shadow_min_compares": 8,    # compared replies required
                                         # before shadow can go green
            "shadow_mismatch_max": 0,    # tolerated out-of-tolerance
                                         # shadow replies (> -> red)
            "shadow_error_max": 3,       # candidate errors during
                                         # shadow before -> failed
            "canary_steps": [5.0, 25.0, 50.0],  # ramp ladder (% of
                                                # real traffic)
            "green_window_s": 5.0,   # BOTH burn windows must stay
                                     # green this long per step
            "min_requests": 12,      # candidate requests per step
                                     # before advancement counts
            "tick_interval_s": 0.25,  # controller evaluation cadence
        },
    },
    # persistent XLA compilation cache (core/compile_cache.py) — the
    # serving cold-start story: executables compile once per cluster,
    # restarted replicas deserialize them from `dir` instead of
    # recompiling.  Off by default; `serve`/bench enable it.
    "compile_cache": {
        "enabled": False,
        "dir": None,              # default: <cache dir>/xla_cache
        "min_compile_time_secs": 0.0,   # cache even instant compiles
        "min_entry_size_bytes": -1,     # ... and tiny executables
    },
})


def apply_override(assignment, root_cfg=None):
    """Apply one CLI ``dotted.path=value`` override onto the config
    root (the ``--config`` flag of the training launcher AND the
    serve CLI — one parser, one literal-or-string rule).  Values
    parse as Python literals, falling back to strings; a leading
    ``root.`` is accepted and stripped."""
    import ast
    path, sep, raw = assignment.partition("=")
    if not sep:
        raise SystemExit("--config needs KEY=VALUE, got %r"
                         % assignment)
    try:
        value = ast.literal_eval(raw)
    except (ValueError, SyntaxError):
        value = raw
    parts = path.strip().split(".")
    if parts and parts[0] == "root":
        parts = parts[1:]
    node = root_cfg if root_cfg is not None else root
    for p in parts[:-1]:
        node = getattr(node, p)
    setattr(node, parts[-1], value)


def get(value, default=None):
    """Return ``value`` unless it is an untouched auto-vivified Config node."""
    if value is None:
        return default
    if isinstance(value, Config) and not any(True for _ in value.keys()):
        return default
    return value


def dtype_map():
    """Numpy dtype for the configured
    ``root.common.engine.precision_type``: ``float`` (f32), ``double``
    (f64), or ``bfloat16`` (the ml_dtypes numpy dtype jax natively
    consumes — the low-precision serving/training tier).  Unknown
    strings fail LOUDLY with the accepted spellings — a typo'd
    precision must never surface as a bare ``KeyError`` deep inside
    workflow initialize."""
    import numpy
    precision = root.common.engine.precision_type
    if precision in ("float", "float32", "f32"):
        return numpy.float32
    if precision in ("double", "float64", "f64"):
        return numpy.float64
    if precision in ("bfloat16", "bf16"):
        import ml_dtypes
        return numpy.dtype(ml_dtypes.bfloat16)
    raise ValueError(
        "unknown root.common.engine.precision_type %r (accepted: "
        "float/float32/f32, double/float64/f64, bfloat16/bf16)"
        % (precision,))
