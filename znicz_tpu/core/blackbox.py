"""Durable blackbox — crash-safe on-disk persistence for the planes.

Every observability plane so far lives in process memory and dies
with the process: the flight-recorder journal is a ring
(core/telemetry.py), the metric time-series are rings
(core/timeseries.py), sampled trace trees are a ring
(serving/reqtrace.py), and ``write_crash_report`` only helps when
Python gets to run an excepthook.  The exact incidents these planes
exist to explain — a SIGKILLed replica, an OOM, an auto-rollback that
tore down its own candidate — destroy their own evidence.  This
module is the flight recorder that survives the crash:

* **write-through journal sink** — every ``telemetry.record_event``
  lands on disk AT EMIT TIME (not ring-dump-at-crash) through the
  sink hook telemetry exposes (:func:`maybe_arm` installs it);
  ``slo.burn`` events (with their exemplar rids) and the release /
  autoscaler decision events ride the same sink, so the fleet's
  control-plane history is durable;
* **timeseries checkpoints** — every ``checkpoint_every_sweeps``-th
  sampler sweep persists the newest point of every ring
  (``timeseries.last_points``), so ``rate()``-style queries span
  process restarts (checkpoints from several boots merge through
  ``timeseries.merge_snapshots`` — the step-function SUM keeps a
  restarted counter monotonic across the boot boundary);
* **trace persistence** — every head-sampled trace tree is written
  when ``reqtrace.finish`` closes it; the router's tree and the
  replica's tree for one rid land in their own segments and the
  query CLI re-stitches them (``reqtrace.stitch``);
* **segments** — length-delimited JSONL files named
  ``<role>.<pid>.<boot>.<nnn>`` under ONE shared directory (the
  fleet router and its replicas point at the same dir).  Each record
  is ``<len> <json>\\n``; a writer killed mid-record leaves a torn
  tail the reader recovers AROUND (every complete record survives,
  the truncated bytes are counted loudly, never silently dropped).
  Rotation closes a segment with the snapshotter's
  fsync-file-then-dir discipline; size-based retention deletes
  oldest segments first (never the live one) so total bytes stay
  bounded under ``retention_bytes``;
* **query CLI** — ``python -m znicz_tpu obs`` (:func:`cli_main`):
  merged cross-process timeline, ``--rid`` follows one request
  across router+replica segments into a reconstructed (stitched)
  trace, ``--rate`` metric queries that span restarts, and
  ``--postmortem <role>`` bundles a dead process's last segments.
  ``GET /debug/blackbox`` on every HandlerBase server answers the
  writer's stats.

Disabled-by-default discipline (the health.py contract): everything
gates on ``root.common.telemetry.blackbox.enabled``.  When off,
:func:`maybe_arm` returns after ONE config predicate, no sink is ever
installed, no writer is allocated, and no filesystem syscall happens
(monkeypatch-boom pinned).  Armed, the write path is one buffered
``write()`` per record (no per-record fsync — the OS page cache
survives SIGKILL; fsync only at rotation, where durability against
power loss matters for the finished segment) — the serving-hot-path
tax is measured by ``bench.py --serving-blackbox`` and gated as
``serving_blackbox_overhead_pct`` (<= 2%).
"""

import json
import os
import re
import time

from znicz_tpu.core.config import root
from znicz_tpu.analysis import locksmith

#: the config node (stable object identity — config.py declares it)
_cfg = root.common.telemetry.blackbox

_lock = locksmith.lock("blackbox.writer")

#: lazily created on the first ARMED use — the disabled path never
#: allocates (zero-overhead-off contract)
_writer = None


def enabled():
    """The one gate — a live read of
    ``root.common.telemetry.blackbox.enabled``."""
    return bool(_cfg.get("enabled", False))


def enable(**overrides):
    for k, v in overrides.items():
        setattr(root.common.telemetry.blackbox, k, v)
    root.common.telemetry.blackbox.enabled = True
    return True


def disable():
    root.common.telemetry.blackbox.enabled = False
    return False


def configured_dir():
    """The shared segment directory: the ``dir`` knob, defaulting to
    ``<cache>/blackbox`` (one host, one dir — the fleet router pins
    the resolved path into every replica's config so all processes
    agree even when ``dirs.cache`` changes between spawns)."""
    return str(_cfg.get("dir", None)
               or os.path.join(root.common.dirs.cache, "blackbox"))


# ---------------------------------------------------------------------------
# Record framing — length-delimited JSONL
# ---------------------------------------------------------------------------
#
# One record = b"<decimal-byte-length> <json-utf8>\n".  The length
# prefix makes the torn-tail test exact: a reader knows precisely how
# many bytes a complete record needs, so a killed writer's partial
# final record is detected (and counted) instead of being half-parsed.

def _frame(record):
    data = json.dumps(record, default=str,
                      separators=(",", ":")).encode("utf-8")
    return b"%d %s\n" % (len(data), data)


def read_segment(path):
    """Recover every complete record of one segment file.

    Returns ``(records, torn_bytes)``: ``records`` is the list of
    decoded dicts, ``torn_bytes`` the length of the truncated /
    corrupt tail a killed writer left (0 for a cleanly closed
    segment).  Tolerates a tail torn ANYWHERE — inside the length
    prefix, the JSON payload, or the trailing newline."""
    with open(path, "rb") as f:
        data = f.read()
    records = []
    pos, end = 0, len(data)
    while pos < end:
        sp = data.find(b" ", pos, pos + 20)
        if sp < 0:
            break  # torn inside (or right after) the length prefix
        try:
            n = int(data[pos:sp])
        except ValueError:
            break  # corrupt length prefix
        start = sp + 1
        stop = start + n
        if stop >= end or data[stop:stop + 1] != b"\n":
            # ">=" not ">": a record missing its newline was torn
            # mid-write — json may parse, durability was not reached
            break
        try:
            records.append(json.loads(data[start:stop].decode("utf-8")))
        except ValueError:
            break  # complete length, corrupt payload: stop loudly
        pos = stop + 1
    return records, end - pos


#: segment file name: <role>.<pid>.<boot>.<nnn> — role may itself be
#: dotted, so pid/boot/seq anchor from the RIGHT
_NAME_RE = re.compile(
    r"^(?P<role>.+)\.(?P<pid>\d+)\.(?P<boot>[0-9a-f]+)\.(?P<seq>\d+)$")


def parse_segment_name(name):
    """``<role>.<pid>.<boot>.<nnn>`` -> dict (None for foreign
    files — the reader skips anything else in a shared dir)."""
    m = _NAME_RE.match(name)
    if m is None:
        return None
    return {"role": m.group("role"), "pid": int(m.group("pid")),
            "boot": m.group("boot"), "seq": int(m.group("seq"))}


# ---------------------------------------------------------------------------
# Writer
# ---------------------------------------------------------------------------

class _Writer(object):
    """The armed process's append-only segment writer (all mutation
    under ``_lock``)."""

    def __init__(self, role, directory):
        self.role = str(role)
        self.dir = directory
        self.pid = os.getpid()
        # boot id: wall-clock millis in hex — two boots of the same
        # pid (pid reuse after a crash loop) stay distinguishable
        self.boot = "%x" % int(time.time() * 1e3)
        self.seq = 0
        self.records = 0
        self.bytes_written = 0
        self.rotations = 0
        self.retention_deleted = 0
        self._f = None
        self._seg_bytes = 0

    def segment_name(self, seq=None):
        return "%s.%d.%s.%03d" % (self.role, self.pid, self.boot,
                                  self.seq if seq is None else seq)

    @property
    def current_path(self):
        return os.path.join(self.dir, self.segment_name())

    def _open_segment(self):
        os.makedirs(self.dir, exist_ok=True)
        # buffering=0: each record is ONE os.write straight to the
        # page cache — a SIGKILLed process loses at most the record
        # being written (the torn tail the reader tolerates), never
        # a stdio buffer full of already-"written" history
        self._f = open(self.current_path, "ab", buffering=0)
        self._seg_bytes = 0
        self._append({"bb": "meta", "t": round(time.time(), 6),
                      "role": self.role, "pid": self.pid,
                      "boot": self.boot, "seq": self.seq})

    def _append(self, record):
        line = _frame(record)
        self._f.write(line)
        self._seg_bytes += len(line)
        self.bytes_written += len(line)
        self.records += 1

    def write(self, record):
        with _lock:
            if self._f is None:
                self._open_segment()
            self._append(record)
            if self._seg_bytes >= int(_cfg.get("segment_bytes",
                                               1 << 20)):
                self._rotate()

    def _rotate(self):
        """Close the full segment with the snapshotter's durability
        discipline (fsync the file, then its directory — a finished
        segment must survive power loss, not just process death),
        open the next one, then enforce retention."""
        f, self._f = self._f, None
        os.fsync(f.fileno())
        f.close()
        dfd = os.open(self.dir, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
        self.seq += 1
        self.rotations += 1
        self._open_segment()
        self._retain()

    def _retain(self):
        """Size-based oldest-first retention: delete whole segments
        (never the live one) until the dir's total is back under
        ``retention_bytes``."""
        budget = int(_cfg.get("retention_bytes", 64 << 20))
        if budget <= 0:
            return
        live = self.current_path
        entries = []
        total = 0
        for name in os.listdir(self.dir):
            if parse_segment_name(name) is None:
                continue
            path = os.path.join(self.dir, name)
            try:
                st = os.stat(path)
            except OSError:
                continue
            total += st.st_size
            entries.append((st.st_mtime, name, path, st.st_size))
        entries.sort()
        for _, _, path, size in entries:
            if total <= budget:
                break
            if path == live:
                continue  # never delete the segment being written
            try:
                os.unlink(path)
            except OSError:
                continue
            total -= size
            self.retention_deleted += 1

    def close(self):
        with _lock:
            f, self._f = self._f, None
        if f is not None:
            try:
                os.fsync(f.fileno())
            except OSError:
                pass
            f.close()


# ---------------------------------------------------------------------------
# Arming — sink installation into the other planes
# ---------------------------------------------------------------------------

def _on_journal(ev):
    """telemetry journal sink: one event -> one durable record, at
    emit time.  ``slo.burn`` / ``release.*`` / ``autoscaler`` events
    (exemplar rids included) ride through here untouched."""
    w = _writer
    if w is not None:
        w.write(dict(ev, bb="journal"))


def _on_sweep(sweeps, now):
    """timeseries checkpoint sink: every ``checkpoint_every_sweeps``
    sampler sweeps, persist the newest point of every ring."""
    w = _writer
    if w is None:
        return
    every = max(1, int(_cfg.get("checkpoint_every_sweeps", 5)))
    if sweeps % every:
        return
    from znicz_tpu.core import timeseries
    series = timeseries.last_points()
    if series:
        w.write({"bb": "ts", "t": round(float(now), 6),
                 "sweeps": int(sweeps), "series": series})


def _on_trace(rid, tree):
    """reqtrace finish sink: one closed head-sampled tree -> one
    durable record (the router's and the replica's trees for a rid
    each land in their OWN process's segment; ``query_rid``
    re-stitches them)."""
    w = _writer
    if w is not None and tree is not None:
        w.write({"bb": "trace", "t": round(time.time(), 6),
                 "rid": rid, "tree": tree})


def maybe_arm(role=None):
    """Arm the durable blackbox iff the gate is on (idempotent; the
    first arm wins the role).  Called by ``HttpServerBase.start`` —
    and earlier, with an explicit role, by the serve CLI and the
    fleet router — so flipping the knob before a server starts is all
    an operator does.  Effective role: the ``role`` knob (the fleet
    router forwards ``role=replica`` to its replicas) over the
    caller's argument over ``"proc"``.  Returns True when a writer is
    armed after the call."""
    if not enabled():
        return False
    global _writer
    with _lock:
        if _writer is None:
            effective = str(_cfg.get("role", None) or role or "proc")
            _writer = _Writer(effective, configured_dir())
    from znicz_tpu.core import telemetry
    from znicz_tpu.core import timeseries
    from znicz_tpu.serving import reqtrace
    telemetry.register_help(
        "blackbox", "durable blackbox (core/blackbox.py): records "
                    "and bytes persisted, rotations, torn tails")
    telemetry.set_journal_sink(_on_journal)
    timeseries.set_checkpoint_sink(_on_sweep)
    reqtrace.set_finish_sink(_on_trace)
    return True


def armed():
    """True while a writer exists (tests + /debug/blackbox)."""
    return _writer is not None


def current_segment():
    """The live segment's path (None when disarmed or before the
    first record) — what ``write_crash_report`` points at so a
    postmortem can jump straight from the crash dir to the durable
    history."""
    w = _writer
    if w is None or w._f is None:
        return None
    return w.current_path


def reset():
    """Close the writer and uninstall every sink (tests, bench
    isolation)."""
    global _writer
    with _lock:
        w, _writer = _writer, None
    if w is not None:
        w.close()
    from znicz_tpu.core import telemetry
    from znicz_tpu.core import timeseries
    from znicz_tpu.serving import reqtrace
    telemetry.set_journal_sink(None)
    timeseries.set_checkpoint_sink(None)
    reqtrace.set_finish_sink(None)


def stats():
    """The ``GET /debug/blackbox`` payload: gate, writer state, and
    the shared dir's segment inventory."""
    out = {"enabled": enabled(), "armed": _writer is not None}
    w = _writer
    if w is not None:
        out.update({
            "role": w.role, "pid": w.pid, "boot": w.boot,
            "dir": w.dir, "segment": w.segment_name(),
            "segment_bytes": w._seg_bytes,
            "records": w.records,
            "bytes_written": w.bytes_written,
            "rotations": w.rotations,
            "retention_deleted": w.retention_deleted,
        })
    directory = w.dir if w is not None else (
        configured_dir() if enabled() else None)
    if directory and os.path.isdir(directory):
        segments = [n for n in os.listdir(directory)
                    if parse_segment_name(n) is not None]
        out["segments_on_disk"] = len(segments)
        out["total_bytes"] = sum(
            os.stat(os.path.join(directory, n)).st_size
            for n in segments)
    return out


# ---------------------------------------------------------------------------
# Reader — scan, merged timeline, rid reconstruction, postmortem
# ---------------------------------------------------------------------------

def scan(directory):
    """Every segment in ``directory``: a list of
    ``{"path", "role", "pid", "boot", "seq", "bytes"}`` sorted by
    (role, pid, boot, seq).  Foreign files are skipped."""
    out = []
    if not os.path.isdir(directory):
        return out
    for name in sorted(os.listdir(directory)):
        meta = parse_segment_name(name)
        if meta is None:
            continue
        path = os.path.join(directory, name)
        try:
            meta["bytes"] = os.stat(path).st_size
        except OSError:
            continue
        meta["path"] = path
        out.append(meta)
    out.sort(key=lambda m: (m["role"], m["pid"], m["boot"], m["seq"]))
    return out


def read_all(directory, roles=None):
    """Recover every record in the dir.  Returns
    ``(records, torn)``: ``records`` is a list of
    ``(source_label, record)`` with ``source_label =
    "<role>.<pid>.<boot>"``; ``torn`` maps a segment path to its
    torn-tail byte count (only segments WITH a torn tail appear —
    the caller reports them loudly).  Recovering a torn segment also
    journals a ``blackbox.torn_tail`` event (counted, not silently
    dropped) when a journal is recording in THIS process."""
    records = []
    torn = {}
    for seg in scan(directory):
        if roles and seg["role"] not in roles:
            continue
        source = "%s.%d.%s" % (seg["role"], seg["pid"], seg["boot"])
        try:
            recs, torn_bytes = read_segment(seg["path"])
        except OSError:
            continue  # retention deleted it mid-scan
        if torn_bytes:
            torn[seg["path"]] = torn_bytes
            from znicz_tpu.core import telemetry
            telemetry.counter("blackbox.torn_tails").inc()
            telemetry.record_event("blackbox.torn_tail",
                                   segment=seg["path"],
                                   bytes=torn_bytes)
        for rec in recs:
            records.append((source, rec))
    return records, torn


def timeline(directory, n=0, kind=None, rid=None, roles=None):
    """The merged cross-process journal timeline: every durable
    journal record in the dir, sorted by wall time, each tagged with
    its source.  ``kind`` is a prefix filter (``slo`` matches
    ``slo.burn``), ``rid`` matches any of the rid-bearing fields, and
    ``n`` keeps only the newest N (0 = all)."""
    records, torn = read_all(directory, roles=roles)
    events = []
    for source, rec in records:
        if rec.get("bb") != "journal":
            continue
        if kind and not str(rec.get("kind", "")).startswith(kind):
            continue
        if rid and rid not in (rec.get("rid"), rec.get("exemplar_rid"),
                               rec.get("request_id")):
            continue
        ev = dict(rec, source=source)
        ev.pop("bb", None)
        events.append(ev)
    events.sort(key=lambda e: float(e.get("t", 0.0)))
    if n and n > 0:
        events = events[-n:]
    return {"events": events, "torn": torn}


def query_rid(directory, rid):
    """Follow one request across every process's segments: its
    journal events, every persisted trace tree, and — when a router
    tree AND a replica (serving-origin) tree both survived — the
    re-stitched cross-process trace (``reqtrace.stitch``, exactly
    what ``GET /debug/trace/<rid>`` would have answered live)."""
    records, torn = read_all(directory)
    events = []
    trees = []  # (source, tree), newest record wins per source
    for source, rec in records:
        if rec.get("bb") == "trace" and rec.get("rid") == rid:
            trees.append((source, rec.get("tree") or {}))
        elif rec.get("bb") == "journal" and rid in (
                rec.get("rid"), rec.get("exemplar_rid"),
                rec.get("request_id")):
            ev = dict(rec, source=source)
            ev.pop("bb", None)
            events.append(ev)
    events.sort(key=lambda e: float(e.get("t", 0.0)))
    router = replica = None
    replica_source = None
    for source, tree in trees:
        if tree.get("origin") == "router":
            router = tree
        else:
            replica = tree
            replica_source = source
    stitched = None
    if router is not None and replica is not None:
        from znicz_tpu.serving import reqtrace
        stitched = reqtrace.stitch(router, replica,
                                   replica=replica_source)
    return {
        "rid": rid,
        "events": events,
        "traces": [{"source": s, "tree": t} for s, t in trees],
        "stitched": stitched,
        "torn": torn,
    }


def checkpoint_payloads(directory, roles=None):
    """Reassemble every source's timeseries checkpoints into
    snapshot-shaped payloads (``{source: {"series": {name: {"kind",
    "points"}}}}``) — directly mergeable by
    ``timeseries.merge_snapshots``, which is what makes cross-restart
    ``rate()`` work: a dead boot's counter latches at its final value
    in the step-merge while the successor boot's counter sums on
    top, so the merged series stays monotonic across the restart."""
    records, _ = read_all(directory, roles=roles)
    payloads = {}
    for source, rec in records:
        if rec.get("bb") != "ts":
            continue
        payload = payloads.setdefault(
            source, {"enabled": True, "sweeps": 0, "series": {}})
        payload["sweeps"] = max(payload["sweeps"],
                                int(rec.get("sweeps", 0)))
        for name, point in (rec.get("series") or {}).items():
            entry = payload["series"].setdefault(
                name, {"kind": point.get("kind"), "points": []})
            entry["points"].append([float(point.get("t", 0.0)),
                                    float(point.get("v", 0.0))])
    for payload in payloads.values():
        for entry in payload["series"].values():
            entry["points"].sort(key=lambda p: p[0])
    return payloads


def query_rate(directory, series, window_s=None, roles=None):
    """Cross-restart ``rate()``: merge every boot's checkpoints and
    rate the merged ring over the trailing window.  Returns
    ``{"series", "rate", "points", "sources"}`` (rate None when
    underdetermined — fewer than two checkpoints)."""
    from znicz_tpu.core import timeseries
    payloads = checkpoint_payloads(directory, roles=roles)
    merged = timeseries.merge_snapshots(payloads, window_s=window_s)
    block = merged["series"].get(series)
    return {
        "series": series,
        "rate": merged["rates"].get(series),
        "points": block["points"] if block else [],
        "sources": sorted(payloads),
    }


def _pid_alive(pid):
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except OSError:
        return True
    return True


def postmortem(directory, role, n=40):
    """Bundle a dead process's last segments: pick the newest boot of
    ``role`` whose pid is gone (falling back to the newest boot
    overall when every pid still runs), and return its final journal
    events, last timeseries checkpoint, persisted trace rids, and
    the torn-tail report — the ``obs --postmortem`` payload the
    deployment runbook points an operator at."""
    segs = [s for s in scan(directory) if s["role"] == role]
    if not segs:
        return {"role": role, "error": "no segments for role %r under "
                                       "%s" % (role, directory)}
    boots = {}
    for seg in segs:
        boots.setdefault((seg["pid"], seg["boot"]), []).append(seg)
    dead = [k for k in boots if not _pid_alive(k[0])]
    pool = dead or list(boots)
    pid, boot = max(pool, key=lambda k: k[1])  # boot id is ms-hex
    chosen = boots[(pid, boot)]
    events = []
    last_ckpt = None
    trace_rids = []
    torn = {}
    for seg in chosen:
        recs, torn_bytes = read_segment(seg["path"])
        if torn_bytes:
            torn[seg["path"]] = torn_bytes
        for rec in recs:
            if rec.get("bb") == "journal":
                ev = dict(rec)
                ev.pop("bb", None)
                events.append(ev)
            elif rec.get("bb") == "ts":
                last_ckpt = rec
            elif rec.get("bb") == "trace":
                trace_rids.append(rec.get("rid"))
    events.sort(key=lambda e: float(e.get("t", 0.0)))
    return {
        "role": role, "pid": pid, "boot": boot,
        "alive": _pid_alive(pid),
        "segments": [s["path"] for s in chosen],
        "events": events[-n:],
        "last_checkpoint": last_ckpt,
        "trace_rids": trace_rids,
        "torn": torn,
    }


# ---------------------------------------------------------------------------
# The obs CLI — python -m znicz_tpu obs
# ---------------------------------------------------------------------------

def _print_event(ev):
    extra = {k: v for k, v in ev.items()
             if k not in ("t", "elapsed", "kind", "source")}
    stamp = time.strftime("%H:%M:%S",
                          time.localtime(float(ev.get("t", 0.0))))
    print("%s  %-24s %-20s %s"  # noqa: T201
          % (stamp, ev.get("source", "?"), ev.get("kind", "?"),
             " ".join("%s=%s" % (k, extra[k]) for k in sorted(extra))))


def _print_torn(torn):
    for path, nbytes in sorted(torn.items()):
        print("!! torn tail: %d byte%s of a truncated record "  # noqa
              "at the end of %s (writer killed mid-write; every "
              "complete record above was recovered)"
              % (nbytes, "" if nbytes == 1 else "s", path))


def cli_main(argv=None):
    """``python -m znicz_tpu obs`` — query a blackbox dir across
    process boundaries and restarts."""
    import argparse
    parser = argparse.ArgumentParser(
        prog="python -m znicz_tpu obs",
        description="Query the durable blackbox (core/blackbox.py): "
                    "merged cross-process journal timeline, --rid "
                    "request reconstruction, cross-restart --rate "
                    "metric queries, --postmortem bundles.")
    parser.add_argument("--dir", default=None,
                        help="blackbox segment dir (default: the "
                             "root.common.telemetry.blackbox.dir "
                             "knob, else <cache>/blackbox)")
    parser.add_argument("-n", type=int, default=50,
                        help="newest N timeline events (0 = all)")
    parser.add_argument("--kind", default=None,
                        help="journal kind prefix filter (e.g. slo "
                             "matches slo.burn)")
    parser.add_argument("--role", action="append", default=None,
                        help="restrict to segments of this role "
                             "(repeatable)")
    parser.add_argument("--rid", default=None,
                        help="follow ONE request: its journal events "
                             "+ persisted trace trees, re-stitched "
                             "across router and replica segments")
    parser.add_argument("--rate", metavar="SERIES", default=None,
                        help="cross-restart rate() of a counter "
                             "series from the persisted checkpoints")
    parser.add_argument("--window", type=float, default=None,
                        help="--rate trailing window seconds "
                             "(default: all checkpoints)")
    parser.add_argument("--postmortem", metavar="ROLE", default=None,
                        help="bundle the newest dead boot of ROLE: "
                             "final journal events, last timeseries "
                             "checkpoint, trace rids, torn tails")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable output")
    args = parser.parse_args(argv)
    directory = args.dir or configured_dir()
    if not os.path.isdir(directory):
        print("no blackbox dir at %s (arm with --config common."  # noqa: T201
              "telemetry.blackbox.enabled=True)" % directory)
        return 1
    if args.rid:
        out = query_rid(directory, args.rid)
        if args.json:
            print(json.dumps(out, default=str))  # noqa: T201
            return 0
        print("rid %s: %d journal event%s, %d persisted trace "  # noqa: T201
              "tree%s%s"
              % (args.rid, len(out["events"]),
                 "" if len(out["events"]) == 1 else "s",
                 len(out["traces"]),
                 "" if len(out["traces"]) == 1 else "s",
                 ", stitched" if out["stitched"] else ""))
        for ev in out["events"]:
            _print_event(ev)
        tree = out["stitched"] or (out["traces"][-1]["tree"]
                                   if out["traces"] else None)
        if tree:
            print("trace (%s, wall %s ms, complete=%s):"  # noqa: T201
                  % (tree.get("origin"), tree.get("wall_ms"),
                     tree.get("complete")))
            for span in tree.get("spans", ()):
                print("  %8.3f ms  %-14s %8.3f ms  [%s]"  # noqa: T201
                      % (span.get("start_ms", 0.0), span["kind"],
                         span.get("duration_ms", 0.0),
                         span.get("process", "serving")))
        _print_torn(out["torn"])
        return 0
    if args.rate:
        out = query_rate(directory, args.rate, window_s=args.window,
                         roles=args.role)
        if args.json:
            print(json.dumps(out, default=str))  # noqa: T201
            return 0
        if out["rate"] is None:
            print("%s: rate underdetermined (%d checkpointed "  # noqa: T201
                  "point%s across %d source%s)"
                  % (args.rate, len(out["points"]),
                     "" if len(out["points"]) == 1 else "s",
                     len(out["sources"]),
                     "" if len(out["sources"]) == 1 else "s"))
            return 1
        print("%s: %.6g/s over %d merged point%s from %s"  # noqa: T201
              % (args.rate, out["rate"], len(out["points"]),
                 "" if len(out["points"]) == 1 else "s",
                 ", ".join(out["sources"])))
        return 0
    if args.postmortem:
        out = postmortem(directory, args.postmortem, n=args.n)
        if args.json:
            print(json.dumps(out, default=str))  # noqa: T201
            return 0
        if out.get("error"):
            print(out["error"])  # noqa: T201
            return 1
        print("postmortem %s pid %d boot %s (%s): %d segment%s"  # noqa: T201
              % (out["role"], out["pid"], out["boot"],
                 "still alive" if out["alive"] else "dead",
                 len(out["segments"]),
                 "" if len(out["segments"]) == 1 else "s"))
        for ev in out["events"]:
            _print_event(dict(ev, source="%s.%d" % (out["role"],
                                                    out["pid"])))
        if out["last_checkpoint"]:
            ck = out["last_checkpoint"]
            print("last checkpoint: sweep %s, %d series"  # noqa: T201
                  % (ck.get("sweeps"), len(ck.get("series") or ())))
        if out["trace_rids"]:
            print("persisted trace rids: %s"  # noqa: T201
                  % ", ".join(str(r) for r in out["trace_rids"]))
        _print_torn(out["torn"])
        return 0
    out = timeline(directory, n=args.n, kind=args.kind,
                   rid=args.rid, roles=args.role)
    if args.json:
        print(json.dumps(out, default=str))  # noqa: T201
        return 0
    for ev in out["events"]:
        _print_event(ev)
    _print_torn(out["torn"])
    return 0
