"""Downloader — dataset fetch-and-extract unit.

TPU-era equivalent of the reference ``veles.downloader.Downloader``
(SURVEY.md §2.9; used by samples, e.g. samples/Wine/wine.py imports it):
given a ``url`` and a target ``directory``, downloads once, extracts
tar/zip archives, and is a no-op when the expected ``files`` already
exist.  Runs at graph-start (link it from start_point before the loader).
"""

import os
import shutil
import tarfile
import urllib.request
import zipfile

from znicz_tpu.core.config import root
from znicz_tpu.core.units import Unit


class Downloader(Unit):
    """kwargs: ``url``, ``directory`` (default <cache>/datasets),
    ``files`` (iterable of paths relative to directory whose existence
    skips the download)."""

    def __init__(self, workflow, **kwargs):
        super(Downloader, self).__init__(workflow, **kwargs)
        self.url = kwargs.get("url")
        self.directory = kwargs.get("directory")
        self.files = tuple(kwargs.get("files", ()))

    def initialize(self, device=None, **kwargs):
        super(Downloader, self).initialize(device=device, **kwargs)
        if not self.directory:
            self.directory = os.path.join(root.common.dirs.cache,
                                          "datasets")

    @property
    def satisfied(self):
        return self.files and all(
            os.path.exists(os.path.join(self.directory, f))
            for f in self.files)

    def run(self):
        if self.satisfied:
            self.debug("all files present under %s", self.directory)
            return
        if not self.url:
            raise ValueError(
                "missing files under %s and no url to fetch them from: %s"
                % (self.directory, ", ".join(self.files)))
        os.makedirs(self.directory, exist_ok=True)
        name = os.path.basename(self.url.rstrip("/")) or "download"
        dest = os.path.join(self.directory, name)
        if not os.path.exists(dest):
            self.info("downloading %s -> %s", self.url, dest)
            with urllib.request.urlopen(self.url) as r, \
                    open(dest + ".part", "wb") as f:
                shutil.copyfileobj(r, f)
            os.replace(dest + ".part", dest)
        self._extract(dest)
        if self.files and not self.satisfied:
            missing = [f for f in self.files if not os.path.exists(
                os.path.join(self.directory, f))]
            raise RuntimeError("downloaded %s but still missing: %s"
                               % (self.url, ", ".join(missing)))

    def _extract(self, dest):
        if tarfile.is_tarfile(dest):
            self.info("extracting tar %s", dest)
            with tarfile.open(dest) as t:
                t.extractall(self.directory, filter="data")
        elif zipfile.is_zipfile(dest):
            self.info("extracting zip %s", dest)
            with zipfile.ZipFile(dest) as z:
                z.extractall(self.directory)
