"""Persistent XLA compilation cache — the serving cold-start story.

A fresh serving replica pays one XLA compile per (model topology,
shape bucket) before it can flip ready.  For a registry of several
models with 7-bucket ladders that is dozens of compiles — minutes of
cold start on real hardware.  This module wires jax's *persistent*
compilation cache (``jax_compilation_cache_dir``) so those executables
are compiled ONCE per cluster, not once per replica: the first replica
to warm a bucket writes the serialized executable to the cache
directory (a shared volume / NFS mount in production), and every later
replica's warmup deserializes it in milliseconds instead of
recompiling.

**Accounting — what "zero fresh compiles" means.**  The installed jax
records a ``backend_compile`` duration event around the whole
compile-*or-load* step, so ``jax.backend_compiles`` ticks even when
the executable came from the persistent cache; the cache hit
additionally fires ``jax.persistent_cache_hits`` (PR 1 wired both).  A
**fresh** compile — actual XLA work — is therefore
``backend_compiles - persistent_cache_hits``, and that is the number a
warm cold start must hold at ZERO (pinned by
``tests/functional/test_compile_cache.py``).  :class:`watch` snapshots
the three counters and exposes the delta.

The cache key covers the serialized computation + jaxlib version +
compile options, NOT array values — so the engine's params-as-argument
design (serving/engine.py) means every model version bump and every
replica of the same topology share one cache entry per bucket.

Pairs with the **warmup manifest** (``export.serving_manifest``): every
deployment package / snapshot topology records the bucket ladder and
sample shape it should be warmed for, so a replica knows its full
compile set ahead of the first request.  Cold start then is: read
manifest -> warm every bucket -> every compile is a persistent-cache
hit -> ready in seconds.

Disabled by default (``root.common.compile_cache.enabled``); the
``serve`` CLI and the serving bench enable it.  Training is untouched
unless explicitly enabled — the off path is one config read.
"""

import glob
import os

from znicz_tpu.core.config import root
from znicz_tpu.core import telemetry
from znicz_tpu.analysis import locksmith

_lock = locksmith.lock("compile_cache")
#: the active cache directory (None = not wired into jax)
_dir = None


def configured_dir():
    """The directory config selects: ``root.common.compile_cache.dir``
    or ``<cache>/xla_cache``."""
    cfg = root.common.compile_cache
    explicit = cfg.get("dir", None)
    if explicit:
        return os.fspath(explicit)
    return os.path.join(root.common.dirs.cache, "xla_cache")


def enabled():
    """True once :func:`enable` wired a cache directory."""
    return _dir is not None


def active_dir():
    return _dir


def enable(cache_dir=None):
    """Point jax's persistent compilation cache at ``cache_dir``
    (default: :func:`configured_dir`).  Idempotent; calling again with
    a different directory re-points the cache.  Returns the directory.

    ``min_compile_time_secs``/``min_entry_size_bytes`` default to
    cache-everything (0 / -1): serving executables are small and the
    whole point is that NO bucket recompiles on restart.
    """
    global _dir
    import jax
    cfg = root.common.compile_cache
    with _lock:
        d = os.path.abspath(os.fspath(cache_dir) if cache_dir
                            else configured_dir())
        os.makedirs(d, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", d)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          float(cfg.get("min_compile_time_secs", 0.0)))
        jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                          int(cfg.get("min_entry_size_bytes", -1)))
        _dir = d
    telemetry.record_event("compile_cache.enable", dir=d)
    return d


def disable():
    """Unwire the cache (tests): jit compiles stop touching disk."""
    global _dir
    import jax
    with _lock:
        jax.config.update("jax_compilation_cache_dir", None)
        _dir = None


def maybe_enable():
    """Honor ``root.common.compile_cache.enabled`` (the declarative
    path — ``serve`` CLI, bench, and subprocess replicas all call
    this); returns the directory or None."""
    if root.common.compile_cache.get("enabled", False):
        return enable()
    return None


def _counter_values():
    return {
        "backend_compiles":
            telemetry.counter("jax.backend_compiles").value,
        "persistent_cache_hits":
            telemetry.counter("jax.persistent_cache_hits").value,
        "persistent_cache_misses":
            telemetry.counter("jax.persistent_cache_misses").value,
    }


class watch(object):
    """Snapshot of the compile counters; ``fresh_compiles()`` is the
    number of ACTUAL XLA compiles since construction (compile-or-load
    events minus persistent-cache loads).  Requires telemetry to be
    enabled — the counters only tick then."""

    def __init__(self):
        self._at = _counter_values()

    def delta(self):
        now = _counter_values()
        return {k: int(now[k] - self._at[k]) for k in now}

    def fresh_compiles(self):
        d = self.delta()
        return d["backend_compiles"] - d["persistent_cache_hits"]


def stats():
    """The cache's observable state — stamped into serving ``stats()``
    and the bench cold-start block."""
    out = {
        "enabled": enabled(),
        "dir": _dir,
    }
    if _dir and os.path.isdir(_dir):
        entries = [p for p in glob.glob(os.path.join(_dir, "*"))
                   if os.path.isfile(p) and not p.endswith("-atime")]
        out["entries"] = len(entries)
        out["bytes"] = sum(os.path.getsize(p) for p in entries)
    if telemetry.enabled():
        out.update(_counter_values())
    return out
