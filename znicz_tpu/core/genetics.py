"""Genetic hyperparameter optimization.

TPU-era equivalent of ``veles.genetics`` (SURVEY.md §3.5): config scalars
wrap in :class:`Range` (reference samples/MNIST/mnist_config.py:56-67),
tests collapse them with :func:`fix_config` (reference
test_mnist_all2all.py:89), and the ``--genetics`` CLI mode evolves
workflow evaluations whose fitness comes from the decision's metrics.

:class:`GeneticsOptimizer` is the driver: a plain generational GA —
tournament selection, blend crossover, per-gene mutation — over the
``Range``-wrapped values of a config tree.  The ``evaluate`` callback
builds + trains a workflow from the patched config and returns a fitness
to MAXIMIZE (e.g. ``-validation_err``).
"""

import numpy

from znicz_tpu.core.config import Config


class Range(object):
    """A tunable config value: default + [min, max] bounds
    (reference veles.genetics.Range)."""

    __slots__ = ("default", "min_value", "max_value")

    def __init__(self, default, min_value, max_value):
        if not min_value <= default <= max_value:
            raise ValueError("default %r outside [%r, %r]"
                             % (default, min_value, max_value))
        self.default = default
        self.min_value = min_value
        self.max_value = max_value

    @property
    def is_integer(self):
        return all(isinstance(v, (int, numpy.integer)) for v in
                   (self.default, self.min_value, self.max_value))

    def clip(self, value):
        value = min(max(value, self.min_value), self.max_value)
        return int(round(value)) if self.is_integer else float(value)

    def sample(self, rand):
        return self.clip(rand.uniform(self.min_value, self.max_value))

    def __repr__(self):
        return "Range(%r, %r, %r)" % (self.default, self.min_value,
                                      self.max_value)


def _walk(node, path=()):
    """Yield (container, key, Range) for every Range in a config tree."""
    if isinstance(node, Config):
        items = list(node.items())
    elif isinstance(node, dict):
        items = list(node.items())
    elif isinstance(node, (list, tuple)):
        items = list(enumerate(node))
    else:
        return
    for key, value in items:
        if isinstance(value, Range):
            yield node, key, value
        else:
            yield from _walk(value, path + (key,))


def _set(container, key, value):
    if isinstance(container, Config):
        setattr(container, key, value)
    elif isinstance(container, dict):
        container[key] = value
    elif isinstance(container, list):
        container[key] = value
    else:  # tuples are immutable; config trees use lists
        raise TypeError("cannot patch %r inside a tuple" % (key,))


def enumerate_ranges(cfg):
    """All Range sites of a config tree, in deterministic order."""
    return list(_walk(cfg))


def fix_config(cfg):
    """Collapse every Range to its default (reference fix_config)."""
    for container, key, rng in enumerate_ranges(cfg):
        _set(container, key, rng.default)
    return cfg


def apply_values(cfg, values):
    """Patch the config's Range sites with concrete values — used by the
    GA before each evaluation.  Returns the (site, value) list."""
    sites = enumerate_ranges(cfg)
    if len(sites) != len(values):
        raise ValueError("%d values for %d Range sites"
                         % (len(values), len(sites)))
    for (container, key, _), value in zip(sites, values):
        _set(container, key, value)
    return sites


class GeneticsOptimizer(object):
    """Generational GA over a config's Range sites.

    ``evaluate(config) -> float`` is called with the patched config and
    returns a fitness to maximize.  The config is restored to defaults
    when evolution finishes.
    """

    def __init__(self, evaluate, config, population_size=8,
                 generations=5, crossover_rate=0.7, mutation_rate=0.15,
                 rand=None, evaluate_population=None):
        self.evaluate = evaluate
        #: optional batch evaluator: ``[value_vector, ...] -> [fitness]``
        #: — evaluates a whole generation CONCURRENTLY (e.g. one vmapped
        #: XLA computation training every individual at once on the
        #: fused path).  The reference sprayed evaluations across a
        #: cluster (SURVEY.md §3.5); on TPU the population batches.
        self.evaluate_population = evaluate_population
        self.config = config
        self.sites = enumerate_ranges(config)
        if not self.sites:
            raise ValueError("config has no Range values to optimize")
        self.ranges = [rng for _, _, rng in self.sites]
        self.population_size = max(3, population_size)
        self.generations = generations
        self.crossover_rate = crossover_rate
        self.mutation_rate = mutation_rate
        self.rand = rand or numpy.random.RandomState(0xEE07)
        self.best_values = None
        self.best_fitness = -numpy.inf
        self.history = []  # per-generation (best, mean) fitness
        self._fitness_cache = {}

    # -- GA operators -------------------------------------------------------
    def _random_individual(self):
        return [rng.sample(self.rand) for rng in self.ranges]

    def _tournament(self, population, fitness):
        i, j = self.rand.randint(0, len(population), 2)
        return population[i] if fitness[i] >= fitness[j] else population[j]

    def _crossover(self, a, b):
        """Blend crossover: child gene = random point between parents."""
        child = []
        for rng, ga, gb in zip(self.ranges, a, b):
            t = self.rand.uniform()
            child.append(rng.clip(ga + t * (gb - ga)))
        return child

    def _mutate(self, ind):
        out = []
        for rng, gene in zip(self.ranges, ind):
            if self.rand.uniform() < self.mutation_rate:
                span = rng.max_value - rng.min_value
                gene = rng.clip(gene + self.rand.normal(0, 0.2 * span))
            out.append(gene)
        return out

    def _fitness_of(self, individual):
        # memoize: the carried-over elite must not re-train every
        # generation (each evaluation is a full workflow run)
        key = tuple(individual)
        cached = self._fitness_cache.get(key)
        if cached is not None:
            return cached
        # use the sites captured at construction: the first patch replaces
        # the Range objects in the tree, so re-enumeration finds nothing
        for (container, k, _), value in zip(self.sites, individual):
            _set(container, k, value)
        fitness = float(self.evaluate(self.config))
        self._fitness_cache[key] = fitness
        return fitness

    def _fitness_many(self, population):
        """Fitness of a whole generation — batched when an
        ``evaluate_population`` callback exists, per-individual
        otherwise; memoized either way (elites must not re-train)."""
        if self.evaluate_population is None:
            return [self._fitness_of(ind) for ind in population]
        missing, seen = [], set()
        for ind in population:
            key = tuple(ind)
            if key not in self._fitness_cache and key not in seen:
                seen.add(key)
                missing.append(list(ind))
        if missing:
            values = self.evaluate_population(missing)
            if len(values) != len(missing):
                raise ValueError(
                    "evaluate_population returned %d fitnesses for %d "
                    "individuals" % (len(values), len(missing)))
            for ind, fit in zip(missing, values):
                self._fitness_cache[tuple(ind)] = float(fit)
        return [self._fitness_cache[tuple(ind)] for ind in population]

    # -- driver -------------------------------------------------------------
    def run(self):
        """Evolve; returns (best_values, best_fitness)."""
        defaults = [rng.default for rng in self.ranges]
        population = [defaults] + [
            self._random_individual()
            for _ in range(self.population_size - 1)]
        try:
            for gen in range(self.generations):
                fitness = self._fitness_many(population)
                order = int(numpy.argmax(fitness))
                if fitness[order] > self.best_fitness:
                    self.best_fitness = fitness[order]
                    self.best_values = list(population[order])
                self.history.append((max(fitness),
                                     float(numpy.mean(fitness))))
                if gen == self.generations - 1:
                    break
                # elitism: the best survives; the rest are offspring
                nxt = [list(population[order])]
                while len(nxt) < self.population_size:
                    a = self._tournament(population, fitness)
                    if self.rand.uniform() < self.crossover_rate:
                        b = self._tournament(population, fitness)
                        child = self._crossover(a, b)
                    else:
                        child = list(a)
                    nxt.append(self._mutate(child))
                population = nxt
        finally:
            # leave the tree in a usable state: best values if found,
            # else the defaults (fix_config semantics)
            winner = self.best_values or defaults
            for (container, key, _), value in zip(self.sites, winner):
                _set(container, key, value)
        return self.best_values, self.best_fitness
