"""Unified telemetry — span tracing, metrics registry, JAX-aware counters.

The reference Veles core shipped live observability as a first-class
tier (SURVEY.md §5.5: web status + plot streaming); znicz_tpu's tier-2
equivalent is this module, shared by the trainer, the loaders, the
snapshotter, ``bench.py`` and the status server.  Three pillars:

* **Span tracer** — nestable ``with telemetry.span("name", **attrs):``
  blocks record complete events into a bounded ring buffer;
  :func:`export_trace` writes Chrome-trace/Perfetto JSON
  (``traceEvents`` schema — load it at https://ui.perfetto.dev).
  Nesting needs no explicit stack: Perfetto nests same-thread events
  by time containment.
* **Metrics registry** — process-global :func:`counter` /
  :func:`gauge` / :func:`histogram` series.  :func:`prometheus_text`
  renders the Prometheus text exposition (served at ``/metrics`` by
  :class:`znicz_tpu.core.status_server.StatusServer`);
  :func:`snapshot` returns the JSON view merged into Publisher
  reports and ``bench.py`` output.
* **Flight recorder** — a bounded structured-event journal
  (:func:`record_event` / :func:`journal_events` /
  :func:`export_journal`): config at start, epoch milestones,
  snapshot/reload events, health violations, slow serving requests.
  On an unhandled exception or SIGTERM (:func:`install_crash_handler`)
  — or explicitly via :func:`write_crash_report` — the last-N events,
  a metrics snapshot and the traceback land in a crash-report
  directory.  Records when telemetry OR the health monitor
  (:mod:`znicz_tpu.core.health`) is enabled.
* **JAX-aware counters** — ``jax.monitoring`` listeners count backend
  compiles (`jax.backend_compiles` + `jax.compile_seconds`), jaxpr
  traces (`jax.traces` — a re-trace on every dispatch means the jit
  cache is MISSING; steady counters with growing step counts mean
  cache hits), and persistent-compilation-cache hits/misses.
  Host↔device traffic is metered where it actually happens —
  ``memory.Array`` map_read/dev and the fused trainer's batched
  ``host_fetch`` (`transfer.d2h_bytes` / `transfer.h2d_bytes`, one
  `transfer.*_calls` bump per round trip).  The asynchronous control
  plane additionally counts its per-segment aggregate readbacks
  (`trainer.readbacks` — == segments when fully async; surfaced as
  ``summary()["readbacks"]`` and `bench.py`'s `readbacks_per_epoch`)
  and gauges the window pipeline (`trainer.inflight_windows`).

Disabled-by-default fast path: everything is gated on
``root.common.telemetry.enabled``.  When off, :func:`span` returns one
shared no-op context manager and :func:`counter`/:func:`gauge`/
:func:`histogram` return one shared null metric — no events, no
registry entries, no allocation.  Hot call sites additionally guard
with ``if telemetry.enabled():`` so the disabled cost is a single
predicate.

Multi-host: every process keeps its own registry;
:func:`merged_snapshot` reduces all hosts' counters into one view
through :func:`znicz_tpu.parallel.multihost.aggregate_telemetry`.
"""

import collections
import json
import logging
import os
import threading
import time

from znicz_tpu.core.config import root
from znicz_tpu.analysis import locksmith

logger = logging.getLogger("telemetry")

#: the config node (object identity is stable: config.py creates it at
#: import and Config merges dict assignments into the existing node)
_cfg = root.common.telemetry

#: trace time origin — spans are stamped relative to module import so
#: timestamps stay small (Chrome trace ts/dur are microseconds)
_T0 = time.perf_counter()

_lock = locksmith.lock("telemetry.registry")


def enabled():
    """The one gate every hook checks.  Reads the live config value so
    flipping ``root.common.telemetry.enabled`` mid-run takes effect
    immediately (the status server can watch a run that enables
    tracing for one epoch).  The first enabled check also installs the
    jax.monitoring listeners — deferring the (heavy) jax import out of
    module import keeps telemetry-importing tools jax-free until
    telemetry is actually turned on."""
    if _cfg.get("enabled", False):
        if not _jax_hooked:
            install_jax_hooks()
        return True
    return False


def enable():
    root.common.telemetry.enabled = True
    install_jax_hooks()
    return True


def disable():
    root.common.telemetry.enabled = False
    return False


# ---------------------------------------------------------------------------
# Span tracer
# ---------------------------------------------------------------------------

class _NullSpan(object):
    """Shared no-op context manager — the disabled-mode span."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Ring(object):
    """Bounded event buffer (oldest events drop first).  Capacity is
    read lazily from ``root.common.telemetry.<cap_key>`` so tests can
    shrink a ring before its first append."""

    def __init__(self, cap_key="trace_capacity", default=65536):
        self._cap_key = cap_key
        self._default = default
        self._events = None
        self.dropped = 0

    def _buf(self):
        if self._events is None:
            cap = int(_cfg.get(self._cap_key, self._default))
            self._events = collections.deque(maxlen=cap)
        return self._events

    def append(self, ev):
        buf = self._buf()
        if len(buf) == buf.maxlen:
            self.dropped += 1
        buf.append(ev)

    def clear(self):
        self._events = None
        self.dropped = 0

    def __len__(self):
        return 0 if self._events is None else len(self._events)

    def events(self):
        return [] if self._events is None else list(self._events)


_ring = _Ring()

#: flight-recorder journal — structured milestone events (config at
#: start, epochs, snapshots, reloads, health violations, slow serving
#: requests), dumped as JSONL by export_journal/write_crash_report
_journal = _Ring("journal_capacity", 4096)


class _Span(object):
    """A live span: records one Chrome-trace complete ("X") event on
    exit.  Exceptions propagate; the span still closes (the trace shows
    where the run died)."""

    __slots__ = ("name", "args", "t0")

    def __init__(self, name, args):
        self.name = name
        self.args = args or None
        self.t0 = None

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        t1 = time.perf_counter()
        _ring.append(("X", self.name, (self.t0 - _T0) * 1e6,
                      (t1 - self.t0) * 1e6, threading.get_ident(),
                      self.args))
        return False


def span(name, **attrs):
    """``with telemetry.span("loader.fill", size=n):`` — a nestable
    traced region.  Returns the shared no-op when telemetry is off."""
    if not enabled():
        return _NULL_SPAN
    return _Span(name, attrs)


def instant(name, **attrs):
    """A zero-duration marker event (epoch boundaries etc.)."""
    if not enabled():
        return
    _ring.append(("i", name, (time.perf_counter() - _T0) * 1e6, 0.0,
                  threading.get_ident(), attrs or None))


def _process_index():
    try:
        import jax
        return int(jax.process_index())
    except Exception:
        return 0


def trace_events():
    """The buffered events as Chrome-trace dicts."""
    pid = _process_index()
    out = []
    for ph, name, ts, dur, tid, args in _ring.events():
        ev = {"name": name, "ph": ph, "ts": round(ts, 3), "pid": pid,
              "tid": tid, "cat": "znicz"}
        if ph == "X":
            ev["dur"] = round(dur, 3)
        elif ph == "i":
            ev["s"] = "t"
        if args:
            ev["args"] = args
        out.append(ev)
    return out


def export_trace(path):
    """Write the ring buffer as Chrome-trace/Perfetto JSON and return
    the path.  Loadable by chrome://tracing and ui.perfetto.dev."""
    events = trace_events()
    payload = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "producer": "znicz_tpu.telemetry",
            "process_index": _process_index(),
            "dropped_events": _ring.dropped,
        },
    }
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(payload, f, default=str)
    return path


# ---------------------------------------------------------------------------
# Flight recorder — the black-box journal
# ---------------------------------------------------------------------------

def journal_enabled():
    """The flight recorder records when telemetry, the health monitor,
    the fault-injection registry, the serving SLO tracker OR the
    durable blackbox is on — a health-only run still wants its black
    box, a chaos run must journal what it injected and how recovery
    went, an SLO-only run must land its ``slo.burn`` threshold
    crossings, and an armed blackbox (core/blackbox.py) needs events
    to flow so its write-through sink can persist them."""
    if _cfg.get("enabled", False):
        return True
    if root.common.health.get("enabled", False):
        return True
    if root.common.faults.get("enabled", False):
        return True
    if root.common.serving.get("slo_enabled", False):
        return True
    return bool(_cfg.blackbox.get("enabled", False))


#: write-through sink: when the durable blackbox arms it installs a
#: callable here and every journal event ALSO lands on disk at emit
#: time (core/blackbox.py) — a ring-dump-at-crash cannot help a
#: SIGKILLed process.  None (one pointer compare on the emit path)
#: in every unarmed process.
_journal_sink = None


def set_journal_sink(fn):
    """Install (or, with None, remove) the durable write-through
    journal sink.  Sink exceptions are swallowed at the emit site —
    instrumentation must never take down the instrumented."""
    global _journal_sink
    _journal_sink = fn


def record_event(kind, **fields):
    """Append one structured event to the bounded journal.  Events are
    plain dicts stamped with wall time and seconds-since-import; the
    ring drops oldest first, so after a crash the journal holds the
    LAST N milestones — what a black box is for.  No-op (and ``None``)
    when neither telemetry nor health is enabled."""
    if not journal_enabled():
        return None
    ev = {"t": round(time.time(), 6),
          "elapsed": round(time.perf_counter() - _T0, 6),
          "kind": kind}
    ev.update(fields)
    _journal.append(ev)
    sink = _journal_sink
    if sink is not None:
        try:
            sink(ev)
        except Exception:  # noqa: BLE001 - never fail the emitter
            logger.debug("journal sink failed", exc_info=True)
    return ev


def journal_events():
    """The buffered journal events (oldest first), as plain dicts."""
    return _journal.events()


def journal_dropped():
    return _journal.dropped


def export_journal(path):
    """Write the journal as JSONL (one event per line — the format
    ``tools/profile_summary.py --journal`` pretty-prints) and return
    the path.  Writes whatever is buffered even when recording is
    currently off (a crash dump must not depend on live config)."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        for ev in _journal.events():
            f.write(json.dumps(ev, default=str) + "\n")
    return path


def write_crash_report(reason="unhandled-exception", exc_info=None,
                       directory=None):
    """Dump the black box to a fresh crash-report directory and return
    its path:

    * ``events.jsonl``  — the last-N journal events,
    * ``metrics.json``  — a full metrics snapshot,
    * ``traceback.txt`` — the active exception (``exc_info`` or
      ``sys.exc_info()``), when there is one,
    * ``report.json``   — reason / time / pid / dropped-event count.

    Called by the health monitor's ``halt`` policy, the launcher's
    unhandled-exception path, and the fatal-signal handler."""
    import sys
    import traceback
    base = (directory or root.common.health.get("crash_dir", None)
            or os.path.join(root.common.dirs.cache, "crash_reports"))
    stamp = time.strftime("%Y%m%d_%H%M%S")
    path = os.path.join(base, "crash_%s_pid%d" % (stamp, os.getpid()))
    n = 0
    while os.path.exists(path):  # same second, same pid: keep both
        n += 1
        path = os.path.join(base, "crash_%s_pid%d_%d"
                            % (stamp, os.getpid(), n))
    os.makedirs(path, exist_ok=True)
    export_journal(os.path.join(path, "events.jsonl"))
    with open(os.path.join(path, "metrics.json"), "w") as f:
        json.dump(snapshot(), f, indent=2, default=str)
    exc_info = exc_info or sys.exc_info()
    if exc_info and exc_info[0] is not None:
        with open(os.path.join(path, "traceback.txt"), "w") as f:
            f.write("".join(traceback.format_exception(*exc_info)))
    try:
        from znicz_tpu.core import blackbox
        blackbox_segment = blackbox.current_segment()
    except Exception:  # noqa: BLE001 - a crash dump must not crash
        blackbox_segment = None
    with open(os.path.join(path, "report.json"), "w") as f:
        json.dump({"reason": str(reason), "time": time.time(),
                   "pid": os.getpid(),
                   "journal_events": len(_journal),
                   "journal_dropped": _journal.dropped,
                   "blackbox_segment": blackbox_segment}, f, indent=2)
    logger.error("crash report -> %s (%s)", path, reason)
    return path


_crash_handler_installed = False


def install_crash_handler():
    """Chain a crash-dumping ``sys.excepthook`` and a SIGTERM handler
    (idempotent).  Both dump only when :func:`journal_enabled` — an
    instrumentation-free run must not grow a crash directory.  The
    SIGTERM handler re-raises the signal with the previous disposition
    restored, so default termination semantics are preserved."""
    global _crash_handler_installed
    if _crash_handler_installed:
        return True
    import sys
    prev_hook = sys.excepthook

    def hook(tp, val, tb):
        try:
            # skip when a report for THIS exception already exists
            # (health halt / the launcher tag the exception)
            if journal_enabled() and \
                    getattr(val, "crash_report", None) is None:
                write_crash_report(reason=repr(val),
                                   exc_info=(tp, val, tb))
        except Exception:  # noqa: BLE001 - never mask the real crash
            pass
        prev_hook(tp, val, tb)

    sys.excepthook = hook
    try:
        import signal
        prev_term = signal.getsignal(signal.SIGTERM)

        def on_term(signum, frame):
            try:
                if journal_enabled():
                    write_crash_report(reason="fatal signal SIGTERM")
            except Exception:  # noqa: BLE001 - still die properly
                pass
            if prev_term == signal.SIG_IGN:
                # the process was IGNORING SIGTERM before we hooked it
                # — dump the black box but preserve that disposition
                # (do not turn an ignored signal into a death)
                return
            signal.signal(signal.SIGTERM,
                          prev_term if prev_term is not None
                          else signal.SIG_DFL)
            os.kill(os.getpid(), signum)

        signal.signal(signal.SIGTERM, on_term)
    except (ValueError, OSError):  # non-main thread / exotic platform
        pass
    _crash_handler_installed = True
    return True


# ---------------------------------------------------------------------------
# Metrics registry
# ---------------------------------------------------------------------------

class _NullMetric(object):
    """Shared do-nothing metric — what the factories hand out when
    telemetry is disabled (no registry entry is created)."""

    __slots__ = ()

    def inc(self, n=1):
        pass

    def set(self, value):
        pass

    def observe(self, value, count=1):
        pass

    @property
    def value(self):
        return 0


_NULL_METRIC = _NullMetric()


class Counter(object):
    """Monotonic counter."""

    kind = "counter"

    def __init__(self, name):
        self.name = name
        self._value = 0
        self._lock = locksmith.lock("telemetry.metric")

    def inc(self, n=1):
        with self._lock:
            self._value += n

    @property
    def value(self):
        return self._value


class Gauge(object):
    """Last-write-wins instantaneous value."""

    kind = "gauge"

    def __init__(self, name):
        self.name = name
        self._value = 0.0

    def set(self, value):
        self._value = value

    @property
    def value(self):
        return self._value


#: default histogram bucket upper bounds — log-spaced seconds, wide
#: enough for sub-ms jitted steps and minute-scale compiles
DEFAULT_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005,
                   0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                   10.0, 30.0, 60.0)


class Histogram(object):
    """Cumulative-bucket histogram + a bounded reservoir of recent
    observations for percentile queries.

    ``observe(v, count=k)`` records ``k`` occurrences of ``v`` in one
    call (the fused window path reports its per-step average once per
    window, weighted by the window's step count).  The reservoir gets
    ``min(k, 256)`` copies so percentile queries stay count-weighted —
    a 1-step epoch-tail window must not weigh as much as a 40-step
    one."""

    kind = "histogram"

    def __init__(self, name, buckets=DEFAULT_BUCKETS):
        self.name = name
        self.buckets = tuple(sorted(buckets))
        self._bucket_counts = [0] * (len(self.buckets) + 1)  # +Inf last
        self._count = 0
        self._sum = 0.0
        window = int(_cfg.get("histogram_window", 2048))
        self._recent = collections.deque(maxlen=window)
        self._lock = locksmith.lock("telemetry.metric")

    def observe(self, value, count=1):
        value = float(value)
        i = 0
        for i, b in enumerate(self.buckets):
            if value <= b:
                break
        else:
            i = len(self.buckets)
        with self._lock:
            self._bucket_counts[i] += count
            self._count += count
            self._sum += value * count
            if count == 1:
                self._recent.append(value)
            else:
                self._recent.extend([value] * min(int(count), 256))

    @property
    def count(self):
        return self._count

    @property
    def sum(self):
        return self._sum

    def percentile(self, p):
        """p in [0, 100] over the bounded reservoir of recent
        observations (None when empty)."""
        with self._lock:
            data = sorted(self._recent)
        if not data:
            return None
        k = max(0, min(len(data) - 1,
                       int(round(p / 100.0 * (len(data) - 1)))))
        return data[k]

    def stats(self):
        with self._lock:
            data = sorted(self._recent)
            count, total = self._count, self._sum
        st = {"count": count, "sum": round(total, 6)}
        if data:
            n = len(data)

            def q(p):
                return data[max(0, min(n - 1,
                                       int(round(p / 100.0 * (n - 1)))))]

            st.update({"min": data[0], "max": data[-1],
                       "p50": q(50), "p90": q(90), "p99": q(99)})
        return st


_metrics = {}


def _get_metric(name, factory):
    if not enabled():
        return _NULL_METRIC
    m = _metrics.get(name)
    if m is None:
        with _lock:
            m = _metrics.get(name)
            if m is None:
                m = factory(name)
                _metrics[name] = m
    return m


def counter(name):
    """Get-or-create the named counter (null metric when disabled)."""
    return _get_metric(name, Counter)


def gauge(name):
    return _get_metric(name, Gauge)


def histogram(name, buckets=DEFAULT_BUCKETS):
    return _get_metric(name, lambda n: Histogram(n, buckets))


def labeled(name, **labels):
    """THE naming convention for per-key series: labels become sorted
    ``key_value`` dotted suffixes — ``labeled("serving.predictions",
    bucket=8)`` -> ``"serving.predictions.bucket_8"``.  Prometheus
    exposition then sanitizes dots to underscores, so dashboards see
    one family prefix per logical series.  Used by the serving tier's
    per-bucket/per-route counters; use it for any bounded label set
    (never for unbounded values like request ids — each distinct name
    is a registry entry)."""
    if not labels:
        return name
    return name + "." + ".".join(
        "%s_%s" % (k, labels[k]) for k in sorted(labels))


def add_bytes(direction, nbytes):
    """Host↔device transfer meter (``direction`` is "d2h" or "h2d").
    Call sites guard with :func:`enabled` so the disabled path never
    computes nbytes."""
    counter("transfer.%s_bytes" % direction).inc(int(nbytes))
    counter("transfer.%s_calls" % direction).inc()


def reset():
    """Drop all metrics, trace events AND the flight-recorder journal
    (tests, bench isolation — a test's health violations must not leak
    into the next test's crash report)."""
    with _lock:
        _metrics.clear()
        _ring.clear()
        _journal.clear()


# ---------------------------------------------------------------------------
# Export: snapshot / Prometheus exposition / bench summary
# ---------------------------------------------------------------------------

def snapshot():
    """JSON-able view of every registered metric."""
    with _lock:
        metrics = list(_metrics.values())
    snap = {"counters": {}, "gauges": {}, "histograms": {}}
    for m in metrics:
        if m.kind == "counter":
            snap["counters"][m.name] = m.value
        elif m.kind == "gauge":
            snap["gauges"][m.name] = m.value
        else:
            snap["histograms"][m.name] = m.stats()
    snap["trace"] = {"buffered_events": len(_ring),
                     "dropped_events": _ring.dropped}
    return snap


def merged_snapshot():
    """:func:`snapshot`, reduced across hosts on multi-process runs
    (one merged view per the SPMD gang; identity single-process)."""
    snap = snapshot()
    try:
        import jax
        if jax.process_count() > 1:
            from znicz_tpu.parallel import multihost
            snap = multihost.aggregate_telemetry(snap)
    except Exception as e:  # noqa: BLE001 - report local rather than die
        logger.warning("telemetry aggregation failed (%s); "
                       "reporting local host only", e)
    return snap


def _prom_name(name):
    """Sanitize a dotted series name into Prometheus [a-zA-Z0-9_:]."""
    out = []
    for ch in name:
        out.append(ch if (ch.isalnum() and ch.isascii()) or ch in "_:"
                   else "_")
    s = "".join(out)
    if s and s[0].isdigit():
        s = "_" + s
    return "znicz_" + s


#: help-string registry: one-liner per series FAMILY, keyed by the
#: longest-matching dotted prefix of the (pre-sanitization) series
#: name.  Emitted as ``# HELP`` ahead of every ``# TYPE`` line of the
#: exposition; modules owning a family register theirs via
#: :func:`register_help` (serving/slo.py, core/timeseries.py)
_HELP = {
    "analysis": "static/runtime analysis layer (graftlint, locksmith)",
    "faults": "deterministic fault injection (core/faults.py)",
    "health": "numeric training-health monitor (core/health.py)",
    "jax.backend_compiles": "XLA backend compilations",
    "jax.compile_seconds": "XLA backend compile wall time",
    "jax.traces": "jaxpr traces (re-traces mean a missing jit cache)",
    "jax.trace_seconds": "jaxpr trace wall time",
    "jax.persistent_cache_hits":
        "persistent compilation-cache hits (core/compile_cache.py)",
    "jax.persistent_cache_misses": "persistent compilation-cache "
                                   "misses",
    "launcher": "supervised-restart lifecycle (launcher.py)",
    "loader": "minibatch loader pipeline",
    "memory": "device-memory ledger (core/profiler.py)",
    "profiler": "performance introspection (core/profiler.py)",
    "registry": "multi-model registry lifecycle "
                "(serving/registry.py)",
    "serving.request_seconds": "end-to-end request latency "
                               "(admission to reply)",
    "serving.queue_wait_seconds": "time queued before a dispatch "
                                  "slot took the request",
    "serving.assembly_seconds": "batch concatenation time",
    "serving.device_seconds": "engine dispatch time per request",
    "serving.batch_rows": "coalesced rows per dispatch",
    "serving.batch_fill": "coalesced rows over the dispatched bucket",
    "serving.pad_overhead": "padding fraction of the dispatched "
                            "bucket",
    "serving.tail_seconds": "per-scenario batch-1 tail latency "
                            "(serving/latency.py)",
    "serving": "online inference serving tier (znicz_tpu/serving/)",
    "snapshotter": "snapshot export/restore (core/snapshotter.py)",
    "trainer": "fused training control plane",
    "transfer": "host<->device transfer meters",
    "unit": "unit-graph execution",
    "workflow": "workflow lifecycle",
}


def register_help(prefix, text):
    """Register (or override) the one-line help for a series-family
    prefix — the ``# HELP`` text every series under it exports."""
    _HELP[str(prefix)] = str(text)
    return prefix


def help_for(name):
    """The registered help for a dotted series name: longest dotted
    prefix wins; a generic family fallback guarantees every exported
    series carries a ``# HELP`` line."""
    parts = name.split(".")
    for i in range(len(parts), 0, -1):
        text = _HELP.get(".".join(parts[:i]))
        if text is not None:
            return text
    return "znicz_tpu telemetry series (family %s)" % parts[0]


def escape_help(text):
    """Escape a ``# HELP`` string per the Prometheus text exposition
    format: backslash and line feed."""
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def escape_label_value(value):
    """Escape a label VALUE per the exposition format: backslash,
    double quote and line feed (in that order — escaping the quote
    first would double-escape the added backslashes)."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def prometheus_text():
    """Prometheus text exposition (format version 0.0.4) of the whole
    registry — what ``/metrics`` serves.  Every series family gets a
    ``# HELP`` line ahead of its ``# TYPE`` (the help-string registry
    above; :func:`register_help` extends it)."""
    with _lock:
        metrics = sorted(_metrics.values(), key=lambda m: m.name)
    lines = []
    for m in metrics:
        name = _prom_name(m.name)
        lines.append("# HELP %s %s"
                     % (name, escape_help(help_for(m.name))))
        if m.kind == "counter":
            lines.append("# TYPE %s counter" % name)
            lines.append("%s %s" % (name, m.value))
        elif m.kind == "gauge":
            lines.append("# TYPE %s gauge" % name)
            lines.append("%s %s" % (name, _fmt(m.value)))
        else:
            lines.append("# TYPE %s histogram" % name)
            # consistent point-in-time view: a scrape racing observe()
            # must never emit +Inf bucket != count (the Prometheus
            # histogram invariant recording rules rely on)
            with m._lock:
                bucket_counts = list(m._bucket_counts)
                total, count = m._sum, m._count
            acc = 0
            for bound, c in zip(m.buckets, bucket_counts):
                acc += c
                lines.append('%s_bucket{le="%s"} %d'
                             % (name, escape_label_value(_fmt(bound)),
                                acc))
            acc += bucket_counts[-1]
            lines.append('%s_bucket{le="+Inf"} %d' % (name, acc))
            lines.append("%s_sum %s" % (name, _fmt(total)))
            lines.append("%s_count %d" % (name, count))
    return "\n".join(lines) + "\n"


def _fmt(v):
    """Float formatting without exponent-capital quirks ('1e-05' style
    is valid Prometheus; plain repr is fine)."""
    return repr(float(v)) if isinstance(v, float) else str(v)


def summary():
    """The compact why-block bench.py stamps into its JSON: compile
    count, transfer bytes, step-time percentiles."""
    snap = snapshot()
    c = snap["counters"]
    h = snap["histograms"]
    out = {
        "backend_compiles": int(c.get("jax.backend_compiles", 0)),
        "jaxpr_traces": int(c.get("jax.traces", 0)),
        "d2h_bytes": int(c.get("transfer.d2h_bytes", 0)),
        "d2h_calls": int(c.get("transfer.d2h_calls", 0)),
        "h2d_bytes": int(c.get("transfer.h2d_bytes", 0)),
    }
    if "trainer.readbacks" in c:
        # async control plane: batched decision-aggregate readbacks the
        # fused trainer paid (== segments when fully asynchronous) —
        # bench.py stamps readbacks_per_epoch from this
        out["readbacks"] = int(c["trainer.readbacks"])
    g = snap.get("gauges") or {}
    if "trainer.data_shards" in g:
        # mesh-sharded control plane: the shard extents the trainer ran
        # under (bench.py --mesh divides d2h bytes by data_shards for
        # the per-device transfer stamp)
        out["data_shards"] = int(g["trainer.data_shards"])
        out["model_shards"] = int(g.get("trainer.model_shards", 1))
    cs = h.get("jax.compile_seconds")
    if cs:
        out["compile_seconds_total"] = round(cs.get("sum", 0.0), 3)
    steps = h.get("trainer.step_seconds") or h.get("unit.run_seconds")
    if steps and steps.get("count"):
        out["step_seconds"] = {
            "count": steps["count"],
            "p50": steps.get("p50"),
            "p99": steps.get("p99"),
        }
    serving = serving_summary(snap)
    if serving is not None:
        out["serving"] = serving
    return out


def serving_summary(snap=None):
    """The serving-tier why-block (requests, rejections, latency
    p50/p99, batch fill) — stamped by ``bench.py --serving`` and the
    serving smoke; None when no serving series exist."""
    snap = snap or snapshot()
    c, h = snap["counters"], snap["histograms"]
    lat = h.get("serving.request_seconds")
    if not lat or not lat.get("count"):
        return None
    out = {
        "requests": int(lat["count"]),
        "latency_p50_ms": (round(lat["p50"] * 1e3, 3)
                           if lat.get("p50") is not None else None),
        "latency_p99_ms": (round(lat["p99"] * 1e3, 3)
                           if lat.get("p99") is not None else None),
        "rejected": int(c.get("serving.rejected", 0)),
        "timeouts": int(c.get("serving.timeouts", 0)),
        "batches": int(c.get("serving.batches", 0)),
    }
    fill = h.get("serving.batch_fill")
    if fill and fill.get("count"):
        out["batch_fill_p50"] = fill.get("p50")
    # request-trace breakdown (PR 3): where a request's latency went
    for series, key in (("serving.queue_wait_seconds",
                         "queue_wait_p50_ms"),
                        ("serving.device_seconds", "device_p50_ms")):
        part = h.get(series)
        if part and part.get("count") and part.get("p50") is not None:
            out[key] = round(part["p50"] * 1e3, 3)
    compiles = {name: int(v) for name, v in c.items()
                if name.startswith("serving.compiles.")}
    if compiles:
        out["bucket_compiles"] = compiles
    return out


# ---------------------------------------------------------------------------
# Self-check validators (shared by tests, the CI smoke, and users
# wiring scrapers/trace viewers — one definition of "valid")
# ---------------------------------------------------------------------------

def validate_trace(doc, require_names=(), require_nested=()):
    """Validate a Chrome-trace document (the dict ``export_trace``
    wrote, already json-loaded) and return its event list.

    * every event must carry the ``traceEvents`` schema fields
      (name/ph/ts, dur for complete events); ``ph: "M"`` metadata
      events (process_name tracks in a stitched cross-process trace,
      reqtrace.stitch) are tolerated and excluded from the span
      checks;
    * ``require_names`` — span names that must be present;
    * ``require_nested`` — (child, parent) name pairs: every child
      span must lie within some parent span on the timeline (the
      containment rule Perfetto nests by).

    Raises ``ValueError`` on any violation.
    """
    events = doc.get("traceEvents")
    if not isinstance(events, list) or not events:
        raise ValueError("missing or empty traceEvents")
    names = set()
    for ev in events:
        if ev.get("ph") == "M":
            if "name" not in ev:
                raise ValueError("malformed metadata event: %r"
                                 % (ev,))
            continue
        if ev.get("ph") not in ("X", "i"):
            raise ValueError("unexpected event phase: %r" % (ev,))
        if not isinstance(ev.get("ts"), (int, float)) or "name" not in ev:
            raise ValueError("malformed event: %r" % (ev,))
        if ev["ph"] == "X" and not isinstance(ev.get("dur"),
                                              (int, float)):
            raise ValueError("complete event without dur: %r" % (ev,))
        names.add(ev["name"])
    missing = set(require_names) - names
    if missing:
        raise ValueError("missing spans %s (have %s)"
                         % (sorted(missing), sorted(names)))
    for child, parent in require_nested:
        spans = [(e["ts"], e["ts"] + e["dur"]) for e in events
                 if e["name"] == parent and e["ph"] == "X"]
        kids = [e for e in events
                if e["name"] == child and e["ph"] == "X"]
        if not kids:
            raise ValueError("no %r spans to nest-check" % child)
        for ev in kids:
            t0, t1 = ev["ts"], ev["ts"] + ev["dur"]
            if not any(a - 1e-3 <= t0 and t1 <= b + 1e-3
                       for a, b in spans):
                raise ValueError("%r span at ts=%s not nested in any "
                                 "%r span" % (child, ev["ts"], parent))
    return events


#: one Prometheus sample line: name{labels} value
_PROM_SAMPLE_RE = None


def parse_prometheus(text):
    """Validate Prometheus text exposition; return {family: type}.
    Raises ``ValueError`` on a malformed sample line."""
    import re
    global _PROM_SAMPLE_RE
    if _PROM_SAMPLE_RE is None:
        _PROM_SAMPLE_RE = re.compile(
            r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})? [0-9eE+.-]+$")
    families = {}
    for line in text.strip().splitlines():
        if line.startswith("# TYPE "):
            _, _, fam, kind = line.split()
            families[fam] = kind
        elif line.startswith("#") or not line:
            continue
        elif not _PROM_SAMPLE_RE.match(line):
            raise ValueError("bad exposition line: %r" % line)
    return families


# ---------------------------------------------------------------------------
# JAX-aware counters (jax.monitoring listeners)
# ---------------------------------------------------------------------------

_jax_hooked = False

#: substring → our counter name for discrete jax.monitoring events
_JAX_EVENT_COUNTERS = (
    ("/jax/compilation_cache/cache_hits", "jax.persistent_cache_hits"),
    ("/jax/compilation_cache/cache_misses",
     "jax.persistent_cache_misses"),
)


def _on_jax_event(event, **kwargs):
    if not enabled():
        return
    for needle, name in _JAX_EVENT_COUNTERS:
        if needle in event:
            # bounded by the literal _JAX_EVENT_COUNTERS table above
            counter(name).inc()  # graftlint: disable=telemetry-series
            return


def _on_jax_duration(event, duration_secs, **kwargs):
    if not enabled():
        return
    if "backend_compile" in event:
        counter("jax.backend_compiles").inc()
        histogram("jax.compile_seconds").observe(duration_secs)
    elif "jaxpr_trace" in event:
        counter("jax.traces").inc()
        histogram("jax.trace_seconds").observe(duration_secs)


def install_jax_hooks():
    """Register the jax.monitoring listeners (idempotent; tolerant of
    a jax-free interpreter so config-only tools can import this
    module).  The callbacks early-return when telemetry is off, so the
    standing cost is one predicate per compile/trace event."""
    global _jax_hooked
    if _jax_hooked:
        return True
    try:
        from jax import monitoring
    except Exception:  # pragma: no cover - jax is a baked-in dep
        return False
    with _lock:
        # re-check under the lock: the status-server thread and the
        # main thread can both see the first enabled() == True, and
        # jax.monitoring has no listener dedup — a double registration
        # would double-count every compile for the process lifetime
        if _jax_hooked:
            return True
        monitoring.register_event_listener(_on_jax_event)
        monitoring.register_event_duration_secs_listener(_on_jax_duration)
        _jax_hooked = True
    return True
