"""Avatar — loader decoupling for pipelined input.

TPU-era equivalent of the reference ``veles.avatar.Avatar`` (SURVEY.md
§2.9: "decouples the loader into a separate producer process/pipeline",
wired by standard_workflow.py:386-404 link_avatar).  The reference ships
minibatches between processes over ZeroMQ; the win — host-side IO and
augmentation overlapping device compute — is had here with a producer
THREAD and a bounded queue: the numpy/file work in loaders releases the
GIL, and the device step runs from the consumer side one minibatch
behind.

The Avatar mirrors the loader's minibatch attributes, so downstream
``link_attrs(loader, ...)`` wiring works identically against the avatar.
"""

import queue
import threading

import numpy

from znicz_tpu.core.memory import Array
from znicz_tpu.core.units import Unit

#: loader attributes mirrored each minibatch (reference Avatar.reals is
#: loader.exports + extras; these cover the Loader contract in
#: znicz_tpu/loader/base.py)
MINIBATCH_ATTRS = (
    "minibatch_data", "minibatch_labels", "minibatch_indices",
    "minibatch_targets", "minibatch_class", "minibatch_size",
    "minibatch_offset", "epoch_ended", "epoch_number", "last_minibatch",
)

#: static attributes cloned once at initialize
STATIC_ATTRS = (
    "class_lengths", "max_minibatch_size", "total_samples", "has_labels",
    "labels_mapping", "normalizer", "target_normalizer", "class_targets",
)


class Avatar(Unit):
    """Prefetching mirror of a loader.

    kwargs: ``loader`` (the real loader unit), ``queue_depth``
    (prefetched minibatches, default 2), ``extra_attrs`` (additional
    attribute names to mirror each minibatch).
    """

    def __init__(self, workflow, **kwargs):
        super(Avatar, self).__init__(workflow, **kwargs)
        self.loader = kwargs.get("loader")
        self.queue_depth = int(kwargs.get("queue_depth", 2))
        self.extra_attrs = tuple(kwargs.get("extra_attrs", ()))
        self._queue = None
        self._thread = None
        self._stop_evt = threading.Event()
        self._error = None
        self._cloned = False
        if self.loader is not None:
            # clone NOW so link-time gate expressions (~avatar.epoch_ended
            # etc.) capture this unit's own mutable objects
            self.clone()

    # -- cloning ------------------------------------------------------------
    def clone(self):
        """Copy the loader's static + current minibatch attributes onto
        this unit (reference Avatar.clone).  Array/Bool attributes become
        NEW objects owned by the avatar — created exactly once, then
        updated in place — so downstream link_attrs and gate expressions
        against the avatar stay valid while the loader races ahead."""
        if self._cloned:
            self._merge({
                name: _snapshot(getattr(self.loader, name))
                for name in (STATIC_ATTRS + MINIBATCH_ATTRS +
                             self.extra_attrs)
                if hasattr(self.loader, name)})
            return
        self._cloned = True
        for name in STATIC_ATTRS + MINIBATCH_ATTRS + self.extra_attrs:
            if not hasattr(self.loader, name):
                continue
            value = getattr(self.loader, name)
            if isinstance(value, Array):
                mirror = Array(name="%s@avatar" % name)
                if value:
                    value.map_read()
                    mirror.reset(numpy.array(value.mem))
                setattr(self, name, mirror)
            elif type(value).__name__ == "Bool":
                # own Bool object so gate expressions built against the
                # avatar keep observing updates
                from znicz_tpu.core.mutable import Bool
                setattr(self, name, Bool(bool(value)))
            else:
                setattr(self, name, _snapshot(value))

    def initialize(self, device=None, **kwargs):
        super(Avatar, self).initialize(device=device, **kwargs)
        if self.loader is None:
            raise ValueError("Avatar needs a loader")
        if not self.loader.initialized:
            self.loader.initialize(device=device, **kwargs)
        self.clone()
        self._queue = queue.Queue(maxsize=self.queue_depth)
        self._stop_evt.clear()
        if self.workflow is not None and \
                hasattr(self.workflow, "on_workflow_finished"):
            self.workflow.on_workflow_finished(self.stop)

    def _ensure_producer(self):
        # started lazily at the first minibatch, NOT in initialize: the
        # workflow's initialize pass may still touch the real loader, and
        # the producer must not race it
        if self._thread is None:
            self._stop_evt.clear()
            self._thread = threading.Thread(
                target=self._produce,
                name="znicz:loader-avatar-%s" % self.loader.name,
                daemon=True)
            self._thread.start()

    # -- producer side ------------------------------------------------------
    def _produce(self):
        try:
            while not self._stop_evt.is_set():
                self.loader.run()
                item = {}
                for name in MINIBATCH_ATTRS + self.extra_attrs:
                    if hasattr(self.loader, name):
                        item[name] = _snapshot(
                            getattr(self.loader, name))
                while not self._stop_evt.is_set():
                    try:
                        self._queue.put(item, timeout=0.1)
                        break
                    except queue.Full:
                        continue
        except BaseException as e:  # surface on the consumer side
            self._error = e
            self._queue.put(None)

    # -- consumer side ------------------------------------------------------
    def run(self):
        self._ensure_producer()
        item = self._queue.get()
        if item is None:
            raise RuntimeError("avatar producer failed") from self._error
        self._merge(item)

    def _merge(self, item):
        """Update this unit's mirrored attributes IN PLACE."""
        for name, value in item.items():
            cur = getattr(self, name, None)
            if isinstance(cur, Array):
                if isinstance(value, numpy.ndarray):
                    if cur and cur.shape == value.shape:
                        cur.map_write()
                        cur.mem[...] = value
                    else:
                        cur.reset(value)
                # else: still-empty source Array — keep the mirror as is
            elif type(cur).__name__ == "Bool":
                cur <<= bool(value)
            else:
                setattr(self, name, value)

    def stop(self):
        self._stop_evt.set()
        if self._thread is not None:
            # unblock a producer waiting on a full queue
            try:
                while True:
                    self._queue.get_nowait()
            except queue.Empty:
                pass
            self._thread.join(timeout=5)
            self._thread = None


def _snapshot(value):
    """Deep-ish copy safe to hand across the thread boundary.  Empty
    Arrays snapshot to None (the consumer keeps its empty mirror)."""
    if isinstance(value, Array):
        if not value:
            return None
        value.map_read()
        return numpy.array(value.mem)
    if isinstance(value, numpy.ndarray):
        return value.copy()
    if hasattr(value, "__bool__") and type(value).__name__ == "Bool":
        return bool(value)
    return value
