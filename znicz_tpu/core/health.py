"""Numeric training-health monitor — detect failure, don't just log it.

PR 1 made the training stack *observable* (core/telemetry.py: spans,
metrics, JAX counters); this module makes it *watched*.  Three pieces:

* **Fused health kernel** — ONE jitted device reduction over any set of
  named pytrees (params / grads / updates) producing a tiny ``(n, 3)``
  array: per-tree NaN flag, Inf flag, sum of squares.  One dispatch,
  one small d2h transfer per check interval — a NaN probe must never
  cost a whole-model host pull (the mistake the reference's
  ``NNSnapshotter`` NaN counter made at AlexNet scale).
* **Loss-divergence detector** — a rolling EMA + window-slope test over
  the decision's per-epoch training metric: trips on non-finite loss,
  on a loss exploding past ``divergence_factor`` × its EMA, and on a
  sustained rise across a full window.
* **Policies** — every violation is counted, gauged, and journaled
  (telemetry flight recorder); ``root.common.health.policy`` then
  decides: ``warn`` logs and continues, ``snapshot`` also writes a
  checkpoint through the workflow's snapshotter (state at the moment of
  the anomaly), ``halt`` writes a crash report and raises the typed
  :class:`HealthViolationError`.

Call sites (fused trainer steps/windows, unit-graph GD units, the
decision's epoch hook) all guard with ``if health.enabled():`` — the
disabled path is a single config-dict predicate with ZERO device syncs,
zero compiles, zero allocation (asserted by tests/unit/test_health.py).

Surfaces: ``health.*`` gauges/counters on ``/metrics``, a
``GET /debug/health`` JSON on the status and serving servers, and the
``health`` block ``bench.py`` stamps so BENCH_*.json tracks monitoring
overhead over time.
"""

import collections
import math
import time

import numpy

from znicz_tpu.core.config import root
from znicz_tpu.analysis import locksmith
from znicz_tpu.core import telemetry
from znicz_tpu.core.memory import Array, DEV, SYNC

import logging

logger = logging.getLogger("health")

_cfg = root.common.health

#: violation policies, mildest first
POLICIES = ("warn", "snapshot", "halt")


class HealthViolationError(RuntimeError):
    """Typed error the ``halt`` policy raises — catch it to distinguish
    "training went numerically bad" from infrastructure failures.
    Carries the violation dict and the crash-report path."""

    def __init__(self, reason, violation=None, crash_report=None):
        super(HealthViolationError, self).__init__(reason)
        self.violation = violation or {}
        self.crash_report = crash_report


def enabled():
    """The one gate every check site tests.  Reads the live config so
    flipping ``root.common.health.enabled`` mid-run takes effect on the
    next step."""
    return bool(_cfg.get("enabled", False))


def enable(**overrides):
    """Turn the monitor on (optionally overriding config knobs)."""
    for k, v in overrides.items():
        setattr(root.common.health, k, v)
    root.common.health.enabled = True
    return True


def disable():
    root.common.health.enabled = False
    return False


# ---------------------------------------------------------------------------
# The fused pytree health kernel
# ---------------------------------------------------------------------------

#: jit cache: pytree structure is part of jit's own cache key, so one
#: compiled kernel per (names, tree-structure) pair — constant per model
_kernel = None


def _get_kernel():
    global _kernel
    if _kernel is None:
        import jax
        import jax.numpy as jnp

        def kernel(trees):
            rows = []
            for name in sorted(trees):
                leaves = [jnp.asarray(l)
                          for l in jax.tree.leaves(trees[name])]
                if not leaves:
                    rows.append(jnp.zeros(3, jnp.float32))
                    continue
                nan = jnp.stack(
                    [jnp.isnan(l).any() for l in leaves]).any()
                inf = jnp.stack(
                    [jnp.isinf(l).any() for l in leaves]).any()
                sq = sum(jnp.sum(jnp.square(l.astype(jnp.float32)))
                         for l in leaves)
                rows.append(jnp.stack([nan.astype(jnp.float32),
                                       inf.astype(jnp.float32), sq]))
            return jnp.stack(rows)

        _kernel = jax.jit(kernel)
    return _kernel


def pytree_health(**trees):
    """Run the fused kernel over named pytrees (None values skipped);
    returns ``{"nan": bool, "inf": bool, "norms": {name: l2},
    "non_finite": [names]}``.  One device dispatch, one (n, 3) d2h."""
    trees = {k: v for k, v in trees.items() if v is not None}
    if not trees:
        return {"nan": False, "inf": False, "norms": {},
                "non_finite": []}
    res = numpy.asarray(_get_kernel()(trees))
    names = sorted(trees)
    report = {"norms": {}, "non_finite": []}
    for i, name in enumerate(names):
        nan, inf, sq = (bool(res[i, 0]), bool(res[i, 1]),
                        float(res[i, 2]))
        report["norms"][name] = (float("nan") if math.isnan(sq)
                                 else math.sqrt(max(sq, 0.0)))
        if nan or inf:
            report["non_finite"].append(name)
        report["nan"] = report.get("nan", False) or nan
        report["inf"] = report.get("inf", False) or inf
    report.setdefault("nan", False)
    report.setdefault("inf", False)
    return report


def _peek(arr):
    """The current authoritative buffer of a :class:`memory.Array`
    WITHOUT forcing a host<->device transfer — the kernel takes either
    side (a numpy leaf is placed by jit; the d2h it saves is the whole
    point on the jax path)."""
    if not isinstance(arr, Array) or not arr:
        return None
    if arr._state in (DEV, SYNC) and arr._dev is not None:
        return arr._dev
    return arr._host


# ---------------------------------------------------------------------------
# Loss-divergence detector
# ---------------------------------------------------------------------------

class DivergenceDetector(object):
    """Rolling train-metric watcher: EMA explosion test + window-slope
    test.  Feed it one scalar per epoch (error %, avg mse, loss);
    :meth:`observe` returns a violation string or None."""

    def __init__(self, window=None, ema_alpha=None, factor=None,
                 rise=None):
        self.window = int(window if window is not None
                          else _cfg.get("loss_window", 8))
        self.alpha = float(ema_alpha if ema_alpha is not None
                           else _cfg.get("loss_ema_alpha", 0.3))
        self.factor = float(factor if factor is not None
                            else _cfg.get("divergence_factor", 3.0))
        self.rise = float(rise if rise is not None
                          else _cfg.get("loss_rise", 0.1))
        self.ema = None
        self.history = collections.deque(maxlen=max(self.window, 2))

    def observe(self, value):
        value = float(value)
        if not math.isfinite(value):
            return "non-finite loss %r" % value
        prev_ema = self.ema
        self.history.append(value)
        self.ema = (value if prev_ema is None
                    else self.alpha * value
                    + (1.0 - self.alpha) * prev_ema)
        if prev_ema is not None and value > prev_ema and \
                value > self.factor * max(abs(prev_ema), 1e-12):
            return ("loss %.6g exploded past %.3gx its EMA %.6g"
                    % (value, self.factor, prev_ema))
        if len(self.history) == self.history.maxlen:
            slope = self._slope()
            first, last = self.history[0], self.history[-1]
            if slope > 0 and \
                    last > first + self.rise * max(abs(first), 1e-12):
                return ("loss rising for %d observations "
                        "(%.6g -> %.6g, slope %.3g/step)"
                        % (len(self.history), first, last, slope))
        return None

    def _slope(self):
        """OLS slope of the window against its index."""
        n = len(self.history)
        mx = (n - 1) / 2.0
        my = sum(self.history) / n
        num = sum((i - mx) * (y - my)
                  for i, y in enumerate(self.history))
        den = sum((i - mx) ** 2 for i in range(n))
        return num / den

    def state(self):
        return {"ema": self.ema, "window": list(self.history)}


# ---------------------------------------------------------------------------
# The monitor
# ---------------------------------------------------------------------------

class HealthMonitor(object):
    """Process-global check state: interval bookkeeping, last report,
    bounded violation history, the divergence detector."""

    VIOLATION_HISTORY = 64

    def __init__(self):
        self.detector = DivergenceDetector()
        self.checks = 0
        self.violation_count = 0
        self.last_report = None
        self.last_violation = None
        self.violations = collections.deque(
            maxlen=self.VIOLATION_HISTORY)
        self._steps = 0
        self._next_check = 0
        self._lock = locksmith.lock("health.monitor")

    # -- interval ------------------------------------------------------------
    def due(self, steps=1):
        """Advance the step counter by ``steps``; True when a check is
        due (every ``interval`` steps — a window of K minibatches
        advances K at once and triggers at most one check)."""
        with self._lock:
            self._steps += steps
            if self._steps >= self._next_check:
                interval = max(int(_cfg.get("interval", 1)), 1)
                self._next_check = self._steps + interval
                return True
            return False

    # -- checking ------------------------------------------------------------
    def check(self, unit=None, context="", **trees):
        """Run the fused kernel over ``trees``; gauge the norms, verify
        the limits, fire the policy on any violation.  Returns the
        report dict."""
        t0 = time.perf_counter()
        report = pytree_health(**trees)
        dt = time.perf_counter() - t0
        self.checks += 1
        self.last_report = dict(report, context=context)
        if telemetry.enabled():
            telemetry.counter("health.checks").inc()
            telemetry.histogram("health.check_seconds").observe(dt)
            for name, norm in report["norms"].items():
                if math.isfinite(norm):
                    telemetry.gauge("health.%s_norm" % name).set(norm)
        if report["nan"] or report["inf"]:
            what = "NaN" if report["nan"] else "Inf"
            self._violate(
                "%s values in %s" % (what,
                                     ", ".join(report["non_finite"])),
                unit=unit, context=context, report=report)
            return report
        for name, limit_key in (("grads", "grad_norm_limit"),
                                ("params", "param_norm_limit"),
                                ("updates", "update_norm_limit")):
            limit = float(_cfg.get(limit_key, 0.0) or 0.0)
            norm = report["norms"].get(name)
            if limit > 0.0 and norm is not None and norm > limit:
                self._violate(
                    "%s norm %.6g exceeds limit %.6g"
                    % (name.rstrip("s"), norm, limit),
                    unit=unit, context=context, report=report)
        return report

    def observe_loss(self, value, unit=None, source="train"):
        """Feed the divergence detector one scalar; fires the policy on
        a detector violation.  Returns the violation string (or None)."""
        why = self.detector.observe(value)
        if telemetry.enabled() and math.isfinite(float(value)):
            telemetry.gauge("health.loss").set(float(value))
        if why is not None:
            self._violate("divergence: " + why, unit=unit,
                          context=source,
                          report={"loss": float(value),
                                  "detector": self.detector.state()})
        return why

    # -- policy --------------------------------------------------------------
    def _violate(self, reason, unit=None, context="", report=None):
        policy = str(_cfg.get("policy", "warn"))
        if policy not in POLICIES:
            logger.warning("unknown health policy %r; using 'warn'",
                           policy)
            policy = "warn"
        violation = {"time": time.time(), "reason": reason,
                     "policy": policy, "context": context,
                     "unit": getattr(unit, "name", None)}
        if report:
            violation["norms"] = report.get("norms")
        self.violation_count += 1
        self.violations.append(violation)
        self.last_violation = violation
        if telemetry.enabled():
            telemetry.counter("health.violations").inc()
        telemetry.record_event("health.violation", **violation)
        logger.warning("health violation (%s policy): %s%s",
                       policy, reason,
                       " [unit %s]" % violation["unit"]
                       if violation["unit"] else "")
        if policy == "snapshot":
            self._emergency_snapshot(unit, reason)
        elif policy == "halt":
            path = telemetry.write_crash_report(
                reason="health halt: " + reason)
            raise HealthViolationError(reason, violation,
                                       crash_report=path)

    def _emergency_snapshot(self, unit, reason):
        """The ``snapshot`` policy: checkpoint the workflow's state at
        the moment of the anomaly (best-effort — a failing snapshotter
        must not turn a warning into a crash)."""
        wf = getattr(unit, "workflow", None)
        snapshotter = getattr(wf, "snapshotter", None) if wf else None
        if snapshotter is None or not hasattr(snapshotter, "export"):
            logger.warning("snapshot policy: no snapshotter reachable "
                           "from %r; state not captured",
                           getattr(unit, "name", unit))
            return None
        try:
            path = snapshotter.export()
            telemetry.record_event("health.snapshot", path=path,
                                   reason=reason)
            return path
        except Exception as e:  # noqa: BLE001 - best-effort capture
            logger.warning("snapshot policy: export failed (%r)", e)
            return None

    # -- introspection -------------------------------------------------------
    def status(self):
        return {
            "enabled": enabled(),
            "ok": self.violation_count == 0,
            "policy": str(_cfg.get("policy", "warn")),
            "interval": int(_cfg.get("interval", 1)),
            "steps": self._steps,
            "checks": self.checks,
            "violations": self.violation_count,
            "last_violation": self.last_violation,
            "last_report": self.last_report,
            "loss": self.detector.state(),
        }


_monitor_lock = locksmith.lock("health.module")
_monitor = None


def monitor():
    """The process-global monitor (created on first use)."""
    global _monitor
    if _monitor is None:
        with _monitor_lock:
            if _monitor is None:
                _monitor = HealthMonitor()
    return _monitor


def reset():
    """Fresh monitor state (tests, bench isolation)."""
    global _monitor
    with _monitor_lock:
        _monitor = None


# ---------------------------------------------------------------------------
# Call-site API (each site guards with enabled() first)
# ---------------------------------------------------------------------------

def check_training_step(unit=None, steps=1, params=None, grads=None,
                        updates=None, context="train_step"):
    """Fused-trainer hook: advance the step counter by ``steps`` (a
    scan window is K steps) and, when due, run ONE fused check over the
    given pytrees.  Returns the report when a check ran, else None.

    Asynchronous control plane interplay: the pytrees the trainer hands
    over are the just-dispatched window's OUTPUT futures, so the check
    piggybacks the same jitted reduction it always ran — no extra
    device syncs are added by the async pipeline.  When a check is due,
    its documented tiny flag/norm fetch transitively waits on the
    window it inspects (armed health at interval=1 therefore paces the
    pipeline to one window, exactly like the armed profiler probe);
    when not due, the hook stays a counter bump and the pipeline keeps
    its depth."""
    if not enabled():
        return None
    m = monitor()
    if not m.due(steps):
        return None
    return m.check(unit=unit, context=context, params=params,
                   grads=grads, updates=updates)


def check_gd_unit(unit):
    """Unit-graph hook: check one GD unit's gradient / weight / update
    Arrays (reading whichever side — host or device — is currently
    authoritative, never forcing a transfer).  The tree kwargs are only
    materialized when a check is actually due."""
    if not enabled():
        return None
    m = monitor()
    if not m.due(1):
        return None
    grads = [g for g in (_peek(getattr(unit, "gradient_weights", None)),
                         _peek(getattr(unit, "gradient_bias", None)))
             if g is not None]
    params = [p for p in (_peek(getattr(unit, "weights", None)),
                          _peek(getattr(unit, "bias", None)))
              if p is not None]
    updates = [u for u in (
        _peek(getattr(unit, "gradient_weights_with_moment", None)),
        _peek(getattr(unit, "gradient_bias_with_moment", None)))
        if u is not None]
    return m.check(unit=unit, context="gd:" + getattr(unit, "name", "?"),
                   params=params or None, grads=grads or None,
                   updates=updates or None)


def observe_loss(value, unit=None, source="train"):
    """Decision-path hook: feed the divergence detector one per-epoch
    scalar.  Returns the violation string (or None)."""
    if not enabled():
        return None
    return monitor().observe_loss(value, unit=unit, source=source)


def status():
    """The ``GET /debug/health`` payload — safe to call with the
    monitor off (reports enabled=False and zero counts without
    creating jax state)."""
    if _monitor is None:
        return {"enabled": enabled(), "ok": True,
                "policy": str(_cfg.get("policy", "warn")),
                "interval": int(_cfg.get("interval", 1)),
                "steps": 0, "checks": 0, "violations": 0,
                "last_violation": None, "last_report": None,
                "loss": {"ema": None, "window": []}}
    return monitor().status()


def summary():
    """The compact block ``bench.py`` stamps: checks run, violations,
    check-overhead p50.  Counts come from the MONITOR (correct on
    health-only runs, where the telemetry counters never increment);
    the p50 needs the telemetry histogram, so it appears only when
    telemetry was also on."""
    m = _monitor  # read-only: never allocate a monitor just to report
    out = {"checks": m.checks if m is not None else 0,
           "violations": m.violation_count if m is not None else 0}
    cs = telemetry.histogram("health.check_seconds")
    p50 = cs.percentile(50) if cs.count else None
    if p50 is not None:
        out["check_seconds_p50"] = round(p50, 6)
    return out
