"""InputJoiner — concatenates several input Arrays along the feature axis.

TPU-era equivalent of ``veles.input_joiner.InputJoiner`` (used by the LSTM
cell sub-workflow, reference lstm.py:91-137).
"""

import numpy

from znicz_tpu.core.accelerated_units import AcceleratedUnit
from znicz_tpu.core.memory import Array


class InputJoiner(AcceleratedUnit):
    def __init__(self, workflow, **kwargs):
        super(InputJoiner, self).__init__(workflow, **kwargs)
        self.inputs = kwargs.get("inputs", [])
        self.output = Array(name="joined")
        self.demand("inputs")

    def link_inputs(self, other, *attrs):
        """Add attributes of ``other`` as inputs (live references)."""
        for attr in attrs:
            self.inputs.append((other, attr))
        return self

    def _resolved_inputs(self):
        out = []
        for item in self.inputs:
            if isinstance(item, tuple):
                unit, attr = item
                out.append(getattr(unit, attr))
            else:
                out.append(item)
        return out

    def initialize(self, device=None, **kwargs):
        super(InputJoiner, self).initialize(device=device, **kwargs)
        ins = self._resolved_inputs()
        if not ins:
            raise ValueError(
                "%s: no inputs configured (pass inputs= or call "
                "link_inputs())" % self.name)
        batch = ins[0].shape[0]
        width = sum(a.sample_size for a in ins)
        self.output.reset(numpy.zeros((batch, width),
                                      dtype=ins[0].dtype))
        # per-input slice geometry (offset_N / length_N) — consumed by the
        # LSTM backward's Cutter1D glue (reference lstm.py:246-301)
        off = 0
        for i, a in enumerate(ins):
            setattr(self, "offset_%d" % i, off)
            setattr(self, "length_%d" % i, a.sample_size)
            off += a.sample_size

    def numpy_run(self):
        ins = self._resolved_inputs()
        self.output.map_invalidate()
        self.output.mem[...] = numpy.concatenate(
            [a.matrix for a in ins], axis=1)

    def jax_run(self):
        import jax.numpy as jnp
        ins = self._resolved_inputs()
        devs = [a.dev.reshape(a.shape[0], -1) for a in ins]
        self.output.set_dev(jnp.concatenate(devs, axis=1))
