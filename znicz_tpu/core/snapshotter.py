"""Checkpoint / resume.

TPU-era equivalent of ``veles.snapshotter`` (SURVEY.md §5.4).  The reference
pickles the entire workflow object (Python-version-fragile — SURVEY hard part
6); znicz_tpu defines an explicit format instead: a compressed pickle of

    {"format": 1, "workflow": <class qualname>, "config": <json>,
     "units": {unit.name: {attr: numpy value for attr in unit.exports}},
     "suffix": "...", "time": ...}

Gating/naming behavior matches the reference: linked after decision, gated
``epoch_ended & improved``, filename suffix like
``validation_1.92_train_0.04`` (standard_workflow.py:493-516,
decision.py:540-548).  Compression gz/bz2/xz selected by ``compression``
kwarg (forge URL parity).  Resume: ``SnapshotterToFile.import_(path)``
returns the state dict; ``Workflow.apply_snapshot`` style loading is done by
NNSnapshotterBase subclasses (znicz_tpu.units.nn_units).
"""

import bz2
import gzip
import lzma
import os
import pickle
import time

from znicz_tpu.core.units import Unit
from znicz_tpu.core.config import root
from znicz_tpu.core.memory import Array
from znicz_tpu.core import faults
from znicz_tpu.core import telemetry

import numpy


_WRITERS = {
    "": open,
    "gz": gzip.open,
    "bz2": bz2.open,
    "xz": lzma.open,
}


class SnapshotterRegistry(type):
    mapping = {}

    def __init__(cls, name, bases, clsdict):
        super(SnapshotterRegistry, cls).__init__(name, bases, clsdict)
        mapping = clsdict.get("MAPPING", None)
        if mapping:
            SnapshotterRegistry.mapping[mapping] = cls


class SnapshotterBase(Unit, metaclass=SnapshotterRegistry):
    """Collects unit exports and writes a snapshot when fired."""

    def __init__(self, workflow, **kwargs):
        super(SnapshotterBase, self).__init__(workflow, **kwargs)
        self.prefix = kwargs.get("prefix", "snapshot")
        self.compression = kwargs.get("compression", "gz")
        self.directory = kwargs.get(
            "directory", root.common.dirs.snapshots)
        self.interval = kwargs.get("interval", 1)
        self.time_interval = kwargs.get("time_interval", 0)
        #: mid-epoch trigger: every N dispatched fused training windows
        #: the trainer's ``window_tick`` call captures a resumable
        #: snapshot under the ``midepoch`` suffix (0 = off).  With the
        #: loader cursor + PRNG streams + the trainer's drained epoch
        #: accumulators all in the payload, a SIGKILLed run resumes
        #: mid-epoch with aggregates exactly equal to an uninterrupted
        #: one (tests/functional/test_fault_tolerance.py).
        self.window_interval = int(kwargs.get("window_interval", 0))
        self.suffix = None
        self.destination = None
        self._last_time = 0.0
        self._since_fire = 0
        self._windows_since = 0

    def initialize(self, device=None, **kwargs):
        super(SnapshotterBase, self).initialize(device=device, **kwargs)
        os.makedirs(self.directory, exist_ok=True)

    def run(self):
        self._since_fire += 1
        if self._since_fire < self.interval:
            return
        if time.time() - self._last_time < self.time_interval:
            return
        self._metered_export("snapshotter.export")
        # interval state advances ONLY after a successful export (a
        # failed write above raised out of run()): a transient write
        # failure must not silently push the next snapshot a full
        # interval/time_interval out — the next fire retries instead
        self._since_fire = 0
        self._last_time = time.time()

    def window_tick(self):
        """Mid-epoch trigger — the fused trainer calls this once per
        dispatched NON-segment-final training window.  Every
        ``window_interval`` windows it exports a snapshot under the
        ``midepoch`` suffix; 0 (the default) keeps this a single
        predicate.  Like :meth:`run`, the counter resets only after a
        successful export, so a failed write retries on the very next
        window.  Returns the written path (None when off/not due)."""
        if not self.window_interval:
            return None
        self._windows_since += 1
        if self._windows_since < self.window_interval:
            return None
        saved = self.suffix
        self.suffix = "midepoch"
        try:
            wrote = self._metered_export("snapshotter.midepoch")
        finally:
            self.suffix = saved
        self._windows_since = 0
        return wrote

    def _metered_export(self, span_name):
        """Telemetry shell shared by the decision-gated :meth:`run` and
        the window-interval :meth:`window_tick` trigger."""
        if not telemetry.enabled():
            return self.export()
        t0 = time.perf_counter()
        with telemetry.span(span_name, prefix=self.prefix):
            wrote = self.export()
        # the series are created on EVERY rank (registries must stay
        # SPMD-identical or cross-host aggregation refuses to merge)
        # but recorded only for actual writes: export() returns the
        # written path, None when it skipped (non-zero ranks of a
        # multi-host gang) — merged counters must not multiply one
        # snapshot by process_count
        exports = telemetry.counter("snapshotter.exports")
        seconds = telemetry.histogram("snapshotter.export_seconds")
        if wrote:
            exports.inc()
            seconds.observe(time.perf_counter() - t0)
        return wrote

    def export(self):
        """Write a snapshot; return the destination path, or None when
        this process skipped the write (telemetry counts only actual
        writes)."""
        raise NotImplementedError

    # -- state collection ---------------------------------------------------
    def collect_state(self):
        """Gather {unit_name: {attr: plain numpy}} from units' ``exports``."""
        wf = self.workflow
        state = {}
        for unit in wf.units:
            exports = getattr(unit, "exports", None)
            if not exports:
                continue
            ustate = {}
            for attr in exports:
                try:
                    v = getattr(unit, attr)
                except AttributeError:
                    continue
                if isinstance(v, Array):
                    v = None if not v else numpy.array(v.mem)
                ustate[attr] = v
            state[unit.name] = ustate
        return state


class SnapshotterToFile(SnapshotterBase):
    """File snapshots (reference MAPPING "file"/"nnfile" family)."""

    MAPPING = "file"

    def export(self, units_state=None):
        from znicz_tpu.core import prng
        import jax
        if jax.process_count() > 1 and jax.process_index() != 0:
            # multi-host SPMD runs the same gang-scheduled program on
            # every process with identical state — one writer (process
            # 0) is sufficient AND necessary (concurrent writers would
            # race on the same prefix); every process restores from the
            # shared directory on resume
            return None
        payload = {
            "format": 1,
            "workflow": type(self.workflow).__name__,
            "config": root.to_json(),
            # a subclass that already collected (NNSnapshotterBase's
            # tensor-stat logging) passes the state through — the
            # epoch_acc export drains the async pipeline, so one
            # collection per capture, not two
            "units": self.collect_state() if units_state is None
            else units_state,
            # PRNG stream states make resume-retrain EXACT (the reference
            # gets this by pickling the whole workflow, prng included)
            "prng": prng.states(),
            "suffix": self.suffix,
            "time": time.time(),
        }
        topology = self._forward_topology()
        if topology is not None:
            payload["topology"] = topology
        ext = "" if not self.compression else "." + self.compression
        name = "%s_%s.%d.pickle%s" % (
            self.prefix, self.suffix or "current", os.getpid(), ext)
        self.destination = os.path.join(self.directory, name)
        opener = _WRITERS[self.compression or ""]
        # atomic publish: a crash/SIGKILL mid-write must never leave a
        # truncated file where auto-resume (launcher --auto-resume) will
        # look for the newest snapshot
        if faults.enabled():
            faults.check("snapshot.write")
        tmp = self.destination + ".part"
        with opener(tmp, "wb") as f:
            pickle.dump(payload, f, protocol=4)
        # crash-DURABLE publish: os.replace is atomic against readers
        # but not against power loss — the .part data blocks (fsynced
        # after close so compressed trailers are included) and the
        # directory entry must both hit disk, or a crash can leave the
        # published name pointing at truncated bytes
        fd = os.open(tmp, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
        os.replace(tmp, self.destination)
        dfd = os.open(os.path.dirname(self.destination) or ".",
                      os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
        self.info("snapshot -> %s", self.destination)
        telemetry.record_event("snapshot", path=self.destination,
                               suffix=self.suffix)
        return self.destination

    def _forward_topology(self):
        """Typed layer list describing the workflow's forward stack
        (export.forward_topology) — the sidecar that lets the serving
        engine reconstruct a jitted forward straight from the snapshot.
        None (with a warning) when the workflow's forwards are not
        package-describable; a snapshot must never fail over serving
        metadata."""
        wf = self.workflow
        if not getattr(wf, "forwards", None):
            return None
        try:
            from znicz_tpu.export import forward_topology
            topology = forward_topology(wf)
        except Exception as e:  # noqa: BLE001 - serving is optional
            self.warning("snapshot carries no serving topology (%s)", e)
            return None
        return topology if topology["layers"] else None

    @staticmethod
    def import_(file_name):
        """Load a snapshot state dict (resume contract,
        reference test: test_mnist_all2all.py:118+)."""
        ext = os.path.splitext(file_name)[1].lstrip(".")
        opener = _WRITERS.get(ext if ext in _WRITERS else "", open)
        with opener(file_name, "rb") as f:
            return pickle.load(f)


class SnapshotterToDB(SnapshotterBase):
    """ODBC snapshot parity stub — stores to a file-backed 'db' directory.

    The reference's ToDB variant (nn_units.py:849-854) needs an ODBC server;
    out of scope for a single-box build, behavior-compatible via files.
    """

    MAPPING = "odbc"

    def export(self):  # pragma: no cover - parity stub
        return SnapshotterToFile.export(self)
