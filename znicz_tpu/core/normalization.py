"""Data normalizers.

TPU-era equivalent of ``veles.normalization`` (SURVEY.md §2.9).  A normalizer
is fit ("analyzed") on the training set and applied in place everywhere.
Names follow the reference configs: "none", "pointwise", "linear",
"mean_disp", "external_mean".
"""

import numpy

_registry = {}


def register(name):
    def deco(cls):
        _registry[name] = cls
        cls.NAME = name
        return cls
    return deco


def create(name, **kwargs):
    try:
        return _registry[name](**kwargs)
    except KeyError:
        raise KeyError("Unknown normalization %r; known: %s"
                       % (name, sorted(_registry)))


class NormalizerBase(object):
    def __init__(self, **kwargs):
        self.state = {}

    def analyze(self, data):
        pass

    def normalize(self, data):
        raise NotImplementedError

    def denormalize(self, data):
        raise NotImplementedError


@register("none")
class NoneNormalizer(NormalizerBase):
    def normalize(self, data):
        return data

    def denormalize(self, data):
        return data


@register("pointwise")
class PointwiseNormalizer(NormalizerBase):
    """Per-feature linear map to [-1, 1] fit on the training set."""

    def analyze(self, data):
        mn = data.min(axis=0)
        mx = data.max(axis=0)
        span = mx - mn
        span[span == 0] = 1.0
        self.state = {"mul": 2.0 / span, "sub": mn, "span": span}

    def normalize(self, data):
        data -= self.state["sub"]
        data *= self.state["mul"]
        data -= 1.0
        return data

    def denormalize(self, data):
        data += 1.0
        data /= self.state["mul"]
        data += self.state["sub"]
        return data


@register("linear")
class LinearNormalizer(NormalizerBase):
    """Whole-tensor linear map to [-1, 1]."""

    def __init__(self, interval=(-1, 1), **kwargs):
        super(LinearNormalizer, self).__init__(**kwargs)
        self.interval = interval

    def analyze(self, data):
        self.state = {"min": float(data.min()), "max": float(data.max())}

    def normalize(self, data):
        lo, hi = self.interval
        span = self.state["max"] - self.state["min"] or 1.0
        data -= self.state["min"]
        data *= (hi - lo) / span
        data += lo
        return data

    def denormalize(self, data):
        lo, hi = self.interval
        span = self.state["max"] - self.state["min"] or 1.0
        data -= lo
        data *= span / (hi - lo)
        data += self.state["min"]
        return data


@register("range_linear")
class RangeLinearNormalizer(LinearNormalizer):
    """Whole-tensor linear map to a configurable interval (parity:
    the reference's "range_linear" target normalizer, Kanji config)."""


@register("internal_mean")
class InternalMeanNormalizer(NormalizerBase):
    """Subtract the training set's mean sample (Caffe-style; reference
    "internal_mean", used by the CIFAR caffe config)."""

    def analyze(self, data):
        self.state = {"mean": data.mean(axis=0)}

    def normalize(self, data):
        data -= self.state["mean"].reshape(1, -1)
        return data

    def denormalize(self, data):
        data += self.state["mean"].reshape(1, -1)
        return data


@register("mean_disp")
class MeanDispNormalizer(NormalizerBase):
    """Subtract per-feature mean, divide by per-feature dispersion
    (parity: veles.mean_disp_normalizer.MeanDispNormalizer; the imagenet
    loader feeds precomputed mean/rdisp arrays via kwargs)."""

    def __init__(self, mean=None, rdisp=None, **kwargs):
        super(MeanDispNormalizer, self).__init__(**kwargs)
        if mean is not None:
            self.state = {"mean": numpy.asarray(mean),
                          "rdisp": numpy.asarray(rdisp)}

    def analyze(self, data):
        if self.state:
            return
        mean = data.mean(axis=0)
        disp = data.max(axis=0) - data.min(axis=0)
        disp[disp == 0] = 1.0
        self.state = {"mean": mean, "rdisp": 1.0 / disp}

    def normalize(self, data):
        data -= self.state["mean"].reshape(1, -1)
        data *= self.state["rdisp"].reshape(1, -1)
        return data

    def denormalize(self, data):
        data /= self.state["rdisp"].reshape(1, -1)
        data += self.state["mean"].reshape(1, -1)
        return data
