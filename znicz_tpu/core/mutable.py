"""Mutable lazy booleans used as unit gates.

TPU-era equivalent of ``veles.mutable.Bool`` (SURVEY.md §2.9).  Contract
observed at the reference call sites:

* ``b <<= value`` assigns the underlying value in place, so every derived
  expression referencing ``b`` sees the change
  (decision.py:441 ``gd_skip <<= minibatch_class != TRAIN``).
* ``~b``, ``a | b``, ``a & b`` build *lazy* derived Bools re-evaluated at
  each ``bool()`` (standard_workflow.py:488,514,528,598).
"""


class Bool(object):
    __slots__ = ("_value", "_expr", "name")

    def __init__(self, value=False, expr=None, name=None):
        self._value = bool(value)
        self._expr = expr
        self.name = name

    def __bool__(self):
        if self._expr is not None:
            return bool(self._expr())
        return self._value

    __nonzero__ = __bool__

    def __ilshift__(self, value):
        """In-place assignment: ``b <<= True`` / ``b <<= other_bool``."""
        if self._expr is not None:
            raise ValueError("Cannot assign to a derived Bool expression")
        self._value = bool(value)
        return self

    def __invert__(self):
        return Bool(expr=lambda: not bool(self))

    def __or__(self, other):
        return Bool(expr=lambda: bool(self) or bool(other))

    def __and__(self, other):
        return Bool(expr=lambda: bool(self) and bool(other))

    def __xor__(self, other):
        return Bool(expr=lambda: bool(self) != bool(other))

    def __repr__(self):
        kind = "expr" if self._expr is not None else "value"
        return "<Bool %s %s=%s>" % (self.name or "", kind, bool(self))
