"""Interactive shell unit.

TPU-era equivalent of the reference ``veles.interaction.Shell`` (wired by
standard_workflow.py link_ipython: a unit that drops into a live console
between epochs, gated on ``decision.epoch_ended``).  The reference embeds
IPython; here the stdlib :mod:`code` console is used, with IPython picked
up when importable.  Interaction only happens when explicitly enabled
(kwarg or ``root.common.interactive``) AND stdin is a tty — so headless
runs and tests are never blocked.
"""

import sys

from znicz_tpu.core.config import root
from znicz_tpu.core.units import Unit


class Shell(Unit):
    """Opens an interactive console with the workflow in scope.

    The banner documents the conventional locals: ``workflow``, ``unit``
    (this shell), and ``root`` (the config tree)."""

    def __init__(self, workflow, **kwargs):
        kwargs.setdefault("view_group", "SERVICE")
        super(Shell, self).__init__(workflow, **kwargs)
        self.enabled = kwargs.get("enabled", None)
        self.interactions = 0

    @property
    def should_interact(self):
        enabled = self.enabled
        if enabled is None:
            # read the DECLARED knob via .get: a getattr on the config
            # tree auto-vivifies a truthy empty Config node, which
            # silently turned every tty run interactive (graftlint's
            # knob-vocabulary checker now rejects undeclared reads)
            enabled = bool(root.common.get("interactive", False))
        return enabled and sys.stdin is not None and \
            hasattr(sys.stdin, "isatty") and sys.stdin.isatty()

    def run(self):
        if not self.should_interact:
            self.debug("non-interactive, skipping shell")
            return
        self.interactions += 1
        banner = ("znicz_tpu shell — locals: workflow, unit, root. "
                  "Ctrl-D to continue the workflow.")
        local = {"workflow": self.workflow, "unit": self, "root": root}
        try:
            import IPython
            IPython.embed(banner1=banner, user_ns=local)
        except ImportError:
            import code
            code.interact(banner=banner, local=local)
