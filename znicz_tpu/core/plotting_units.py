"""Plotting units — data-recording plotters with optional PNG rendering.

TPU-era equivalent of the core ``veles.plotting_units`` API surface
(SURVEY.md §2.9: AccumulatingPlotter, MatrixPlotter, MultiHistogram,
ImagePlotter, ImmediatePlotter, TableMaxMin).  The reference streams to a
matplotlib-backed web status server; here every plotter records its data
(inspectable, testable) and — unless ``root.common.disable.plotting`` —
renders a PNG into ``root.common.dirs.cache/plots`` on each redraw.
"""

import os

import numpy

from znicz_tpu.core.config import root
from znicz_tpu.core.units import Unit


class IPlotter(object):
    """Marker interface (parity: veles.plotter.IPlotter)."""


class Plotter(Unit, IPlotter):
    """Base plotter: gather data in ``run``, render in ``redraw``."""

    def __init__(self, workflow, **kwargs):
        kwargs.setdefault("view_group", "PLOTTER")
        super(Plotter, self).__init__(workflow, **kwargs)
        self.clear_plot = kwargs.get("clear_plot", False)
        self.redraw_plot = kwargs.get("redraw_plot", True)
        self._fig_path = None

    @property
    def plotting_enabled(self):
        return not root.common.disable.plotting

    def _figure(self):
        import matplotlib
        matplotlib.use("Agg", force=False)
        import matplotlib.pyplot as plt
        return plt

    def _save_figure(self, plt):
        out_dir = os.path.join(root.common.dirs.cache, "plots")
        os.makedirs(out_dir, exist_ok=True)
        self._fig_path = os.path.join(out_dir, "%s.png" % self.name)
        plt.savefig(self._fig_path)
        plt.close("all")

    def run(self):
        self.fill()
        if self.plotting_enabled and self.redraw_plot:
            self.redraw()

    def fill(self):
        pass

    def redraw(self):
        pass

    @staticmethod
    def resolve(value, field=None):
        """Shared input resolution: optional field lookup (attr name,
        container key, or integer row index into array-likes), Array
        map_read, numpy view."""
        if field is not None:
            if isinstance(value, (dict, list, tuple)):
                value = value[field]
            elif isinstance(field, int):
                # integer field on an array-valued input = row index
                # (reference input_fields semantics: inputs[i][field])
                if hasattr(value, "map_read"):
                    value.map_read()
                    value = value.mem
                if value is None:
                    return None
                value = numpy.asarray(value)[field]
            else:
                value = getattr(value, field)
        if value is None:
            return None
        if hasattr(value, "map_read"):
            value.map_read()
            value = value.mem
        return numpy.asarray(value)


class AccumulatingPlotter(Plotter):
    """Accumulates scalar values over time (error curves)."""

    def __init__(self, workflow, **kwargs):
        super(AccumulatingPlotter, self).__init__(workflow, **kwargs)
        self.plot_style = kwargs.get("plot_style", "r-")
        self.label = kwargs.get("name", self.name)
        self.input = None  # value source (attr or Array)
        self.input_field = kwargs.get("input_field", None)
        self.input_offset = kwargs.get("input_offset", 0)
        self.values = []

    def _current_value(self):
        arr = self.resolve(self.input, self.input_field)
        if arr is None or (arr.ndim == 0 and arr == None):  # noqa: E711
            return None
        if arr.dtype == object:
            return None
        if arr.ndim:
            arr = arr.ravel()[self.input_offset]
        return float(arr)

    def fill(self):
        v = self._current_value()
        if v is not None:
            self.values.append(v)

    def redraw(self):
        plt = self._figure()
        plt.figure()
        plt.plot(self.values, self.plot_style)
        plt.title(self.label)
        self._save_figure(plt)


class MatrixPlotter(Plotter):
    """Renders a matrix (confusion matrix)."""

    def __init__(self, workflow, **kwargs):
        super(MatrixPlotter, self).__init__(workflow, **kwargs)
        self.input = None
        self.input_field = kwargs.get("input_field", None)
        self.current = None

    def fill(self):
        self.current = numpy.array(self.resolve(self.input,
                                                self.input_field))

    def redraw(self):
        if self.current is None:
            return
        plt = self._figure()
        plt.figure()
        plt.imshow(self.current, interpolation="nearest", cmap="viridis")
        plt.colorbar()
        plt.title(self.name)
        self._save_figure(plt)


class MultiHistogram(Plotter):
    """Histograms of several weight rows."""

    def __init__(self, workflow, **kwargs):
        super(MultiHistogram, self).__init__(workflow, **kwargs)
        self.input = None
        self.hist_number = kwargs.get("hist_number", 16)
        self.n_bars = kwargs.get("n_bars", 25)
        self.histograms = []

    def fill(self):
        # weightless layers carry EMPTY Arrays, not None
        if self.input is None or \
                (hasattr(self.input, "__bool__") and not self.input):
            return
        mem = self.resolve(self.input)
        if mem is None or mem.ndim == 0:
            return
        rows = mem.reshape(mem.shape[0], -1)
        self.histograms = [
            numpy.histogram(rows[i], bins=self.n_bars)
            for i in range(min(self.hist_number, rows.shape[0]))]

    def redraw(self):
        if not self.histograms:
            return
        plt = self._figure()
        n = len(self.histograms)
        cols = int(numpy.ceil(numpy.sqrt(n)))
        rows_n = int(numpy.ceil(n / cols))
        fig, axes = plt.subplots(rows_n, cols, squeeze=False)
        for i, (hist, edges) in enumerate(self.histograms):
            ax = axes[i // cols][i % cols]
            ax.bar(edges[:-1], hist, width=numpy.diff(edges))
        self._save_figure(plt)


class ImagePlotter(Plotter):
    """Renders input samples as images."""

    def __init__(self, workflow, **kwargs):
        super(ImagePlotter, self).__init__(workflow, **kwargs)
        self.inputs = []
        self.input_fields = []
        self.current = None

    def fill(self):
        self.current = [
            numpy.array(self.resolve(v, field))
            for v, field in zip(
                self.inputs,
                self.input_fields or [None] * len(self.inputs))]

    def redraw(self):
        if not self.current:
            return
        plt = self._figure()
        fig, axes = plt.subplots(1, len(self.current), squeeze=False)
        for ax, img in zip(axes[0], self.current):
            img = numpy.squeeze(numpy.asarray(img, dtype=numpy.float64))
            if img.ndim == 1:
                ax.plot(img)
            else:
                ax.imshow(img if img.ndim == 2 else img[..., :3],
                          cmap="gray")
        self._save_figure(plt)


class ImmediatePlotter(Plotter):
    """Plots a list of 1D arrays each redraw."""

    def __init__(self, workflow, **kwargs):
        super(ImmediatePlotter, self).__init__(workflow, **kwargs)
        self.inputs = []
        self.input_fields = []
        self.input_styles = kwargs.get("input_styles", ["k-", "g-", "b-"])
        self.current = []

    def fill(self):
        self.current = [
            self.resolve(v, field).ravel()
            for v, field in zip(
                self.inputs,
                self.input_fields or [None] * len(self.inputs))]

    def redraw(self):
        plt = self._figure()
        plt.figure()
        for arr, style in zip(self.current, self.input_styles):
            plt.plot(arr, style)
        self._save_figure(plt)


class TableMaxMin(Plotter):
    """Logs a table of max/min of given arrays."""

    def __init__(self, workflow, y_max_rows=2, x_cols=1, **kwargs):
        super(TableMaxMin, self).__init__(workflow, **kwargs)
        self.y = []
        self.col_labels = []
        self.rows = []

    def fill(self):
        row = []
        for v in self.y:
            # skip empty Arrays (weightless layers)
            if v is None or (hasattr(v, "__bool__") and not v):
                row.append((float("nan"), float("nan")))
                continue
            arr = self.resolve(v)
            if arr is None or arr.ndim == 0:
                row.append((float("nan"), float("nan")))
                continue
            row.append((float(arr.max()), float(arr.min())))
        self.rows.append(row)
        for label, (mx, mn) in zip(self.col_labels, row):
            self.debug("%s: max %.6f min %.6f", label, mx, mn)
