"""Performance introspection — XLA cost accounting, device-memory
ledger, and step-time breakdown.

PR 1 made the training stack observable (telemetry), PR 3 made it
watched (health).  This module makes it *explainable*: it answers the
three questions every perf PR needs answered before it starts —

* **What did XLA actually compile?**  An **executable cost registry**:
  every jitted entry point (the fused train step and scan windows, the
  GD-unit update kernels, the serving forward buckets) registers its
  lowered ``cost_analysis()`` FLOPs and bytes-accessed via
  :func:`register_jit_cost`.  That gives *measured* MFU and the
  roofline operational intensity (FLOPs / byte — Williams et al.,
  "Roofline: An Insightful Visual Performance Model") per executable,
  cross-checked against the analytic ``flops_per_image`` estimate the
  bench has always used (the PaLM-style MFU accounting).  Registration
  lowers the ALREADY-TRACED function before its first dispatch, so it
  adds zero backend compiles (the dispatch reuses the trace cache).
* **Where did the memory go?**  A **device-memory ledger**:
  ``core/memory.py:Array`` device buffers are byte-accounted on every
  upload / ``set_dev`` / ``reset`` with per-Array-name attribution, a
  high-water-mark gauge, optional ``device.memory_stats()`` sampling
  (TPU; returns None on backends without it), and an epoch-boundary
  leak check that flags ``leak_epochs`` consecutive epochs of ledger
  growth.  The ledger counts *logical* per-Array references — two
  Arrays adopting views of one buffer both account it — which is the
  right invariant for leak detection (a reference that never goes away
  is the leak, aliased or not).
* **Why is the step slow?**  A **step-time breakdown**: per training
  window, wall time is partitioned into loader/data-wait, host
  dispatch, device compute (an explicit ``block_until_ready`` — paid
  only while the profiler is armed), and host readback, accumulated
  into an input-bound / compute-bound / host-bound verdict
  (:func:`breakdown_summary`).  Plus on-demand ``jax.profiler``
  capture: ``GET /debug/profile?seconds=N`` on the status and serving
  servers (:func:`capture_trace`) and a ``python -m znicz_tpu
  profile`` CLI (:func:`cli_main`).

Disabled-by-default discipline (the contract ``health.py``
established, pinned by ``tests/unit/test_profiler.py``): every hook
site guards with ``if profiler.enabled():`` and every public hook
re-guards internally — with the flag off there are ZERO extra
compiles, ZERO device syncs, zero allocation; no profiler state is
even created.  Everything is exported through the existing machinery:
``profiler.*`` counters/gauges/histograms in the telemetry registry
(``/metrics``), ``profiler.*`` flight-recorder journal events, the
``roofline`` / ``step_breakdown`` blocks ``bench.py`` stamps, and the
``--roofline`` / ``--ledger`` modes of ``tools/profile_summary.py``.
"""

import collections
import glob
import json
import logging
import os
import time

from znicz_tpu.core.config import root
from znicz_tpu.core import telemetry
from znicz_tpu.analysis import locksmith

logger = logging.getLogger("profiler")

_cfg = root.common.profiler

#: breakdown part names, display order (sum over parts == wall)
PARTS = ("data_wait", "host_collect", "dispatch", "device", "readback")

#: the possible :func:`breakdown_summary` verdicts
VERDICTS = ("input-bound", "compute-bound", "host-bound")


def enabled():
    """The one gate every hook site tests.  Reads the live config so
    flipping ``root.common.profiler.enabled`` mid-run takes effect on
    the next step."""
    return bool(_cfg.get("enabled", False))


def enable(**overrides):
    """Arm the profiler (optionally overriding config knobs)."""
    for k, v in overrides.items():
        setattr(root.common.profiler, k, v)
    root.common.profiler.enabled = True
    return True


def disable():
    root.common.profiler.enabled = False
    return False


# ---------------------------------------------------------------------------
# Process-global state (created on first ENABLED use only — the
# disabled path must not allocate)
# ---------------------------------------------------------------------------

class DeviceLedger(object):
    """Byte-accounting of live device buffers, attributed by Array
    name.  ``swap(name, old, new)`` is the one mutation: it frees
    ``old`` bytes and allocates ``new`` (either may be 0), matching the
    replace-don't-mutate lifecycle of ``memory.Array._dev``."""

    def __init__(self):
        self.by_name = collections.defaultdict(int)
        self.live_bytes = 0
        self.high_water_bytes = 0
        self.allocs = 0
        self.frees = 0
        #: frees of bytes the ledger never saw allocated (clamped to
        #: keep counts non-negative) — any such event means the window
        #: of observation missed allocations (profiler armed mid-run,
        #: or reset() while buffers were live) and the live totals are
        #: LOWER BOUNDS, not exact
        self.clamped_frees = 0
        self._lock = locksmith.lock("profiler.ledger")

    def swap(self, name, old_nbytes, new_nbytes):
        name = name or "<unnamed>"
        with self._lock:
            if old_nbytes:
                self.frees += 1
                # clamp: arming the profiler mid-run may free buffers
                # it never saw allocated (best-effort accounting)
                drop = min(int(old_nbytes), self.by_name[name])
                if drop < int(old_nbytes):
                    self.clamped_frees += 1
                self.by_name[name] -= drop
                self.live_bytes -= drop
            if new_nbytes:
                self.allocs += 1
                self.by_name[name] += int(new_nbytes)
                self.live_bytes += int(new_nbytes)
                if self.live_bytes > self.high_water_bytes:
                    self.high_water_bytes = self.live_bytes

    def summary(self, top=16):
        with self._lock:
            names = {k: v for k, v in self.by_name.items() if v}
            live, hwm = self.live_bytes, self.high_water_bytes
            allocs, frees = self.allocs, self.frees
            clamped = self.clamped_frees
        ranked = sorted(names.items(), key=lambda kv: -kv[1])
        return {
            "live_bytes": live,
            "high_water_bytes": hwm,
            "allocs": allocs,
            "frees": frees,
            # the trust invariant: every observed free was matched by
            # an observed allocation.  False means the ledger missed
            # part of the buffer lifecycle (armed mid-run / reset with
            # live buffers) and the totals are lower bounds.
            "balanced": clamped == 0,
            "clamped_frees": clamped,
            "by_name": dict(ranked[:top]),
            "tracked_names": len(names),
        }


class _ProfilerState(object):
    """Everything the armed profiler accumulates."""

    def __init__(self):
        self.cost = {}                    # name -> cost-registry entry
        self.ledger = DeviceLedger()
        self.parts = collections.defaultdict(float)
        self.wall = 0.0
        self.windows = 0
        self.steps = 0
        self.probes_active = 0
        #: (epoch, ledger live bytes) at each epoch boundary
        self.epoch_bytes = []
        self.leak_suspects = 0
        self.lock = locksmith.lock("profiler.state")


_state = None
_state_lock = locksmith.lock("profiler.module")


def _prof():
    """The process-global profiler state (created on first use)."""
    global _state
    if _state is None:
        with _state_lock:
            if _state is None:
                _state = _ProfilerState()
    return _state


def reset():
    """Fresh profiler state (tests, bench per-attempt isolation)."""
    global _state
    with _state_lock:
        _state = None


# ---------------------------------------------------------------------------
# Pillar 1: the executable cost registry
# ---------------------------------------------------------------------------

def _cost_dict(lowered):
    """Normalize ``Lowered.cost_analysis()`` output across jax
    versions (dict, or a per-device list of dicts)."""
    ca = lowered.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return ca or {}


def register_jit_cost(name, fn, args, kwargs=None, analytic_flops=None,
                      scan_steps=1, **meta):
    """Register one jitted entry point's lowered cost analysis.

    Call BEFORE the first dispatch with the exact dispatch arguments:
    ``fn.lower(*args)`` traces abstractly (shapes only — donated or
    huge buffers are fine) and the dispatch that follows reuses the
    trace cache, so registration costs one extra trace and ZERO extra
    backend compiles.  Duplicate names return the existing entry
    without re-lowering, so per-dispatch call sites stay cheap.

    ``analytic_flops`` is the closed-form estimate to cross-check
    against (e.g. ``3 * flops_per_image * batch * steps`` for a train
    window); the entry records the measured/analytic ratio and whether
    it falls inside the ``cost_rtol`` agreement band.  Extra ``meta``
    kwargs (steps, batch, ...) ride on the entry for report math.

    ``scan_steps``: HLO cost analysis counts a ``lax.scan``/while-loop
    BODY once (the trip count is not static at the HLO level), so for
    an executable whose hot path is a K-step scan the caller passes
    ``scan_steps=K`` and the measured numbers are scaled by it (the
    entry is flagged ``scan_scaled``).
    """
    if not enabled():
        return None
    p = _prof()
    with p.lock:
        entry = p.cost.get(name)
    if entry is not None:
        return entry
    entry = {"name": name, "flops": None, "bytes_accessed": None,
             "operational_intensity": None}
    scan_steps = max(int(scan_steps), 1)
    try:
        lowered = fn.lower(*args, **(kwargs or {}))
        ca = _cost_dict(lowered)
        flops = float(ca.get("flops", 0.0) or 0.0) * scan_steps
        nbytes = float(ca.get("bytes accessed", 0.0) or 0.0) * scan_steps
        entry["flops"] = flops
        entry["bytes_accessed"] = nbytes
        if nbytes:
            entry["operational_intensity"] = flops / nbytes
        if "transcendentals" in ca:
            entry["transcendentals"] = \
                float(ca["transcendentals"]) * scan_steps
        if scan_steps > 1:
            entry["scan_scaled"] = True
            entry["scan_steps"] = scan_steps
    except Exception as e:  # noqa: BLE001 - introspection must not kill a run
        entry["error"] = repr(e)
        logger.warning("cost_analysis failed for %s: %r", name, e)
    if analytic_flops:
        entry["analytic_flops"] = float(analytic_flops)
        if entry["flops"]:
            ratio = entry["flops"] / float(analytic_flops)
            rtol = float(_cfg.get("cost_rtol", 0.5))
            entry["flops_ratio_measured_vs_analytic"] = ratio
            entry["agreement"] = bool(1.0 - rtol <= ratio <= 1.0 + rtol)
    if meta:
        entry["meta"] = meta
    with p.lock:
        # first registration wins (a racing duplicate lowered the same
        # program; keep one entry so dedup stays O(1) per dispatch)
        entry = p.cost.setdefault(name, entry)
        count = len(p.cost)
    telemetry.gauge("profiler.executables").set(count)
    telemetry.record_event(
        "profiler.cost_registered", name=name, flops=entry.get("flops"),
        bytes_accessed=entry.get("bytes_accessed"),
        analytic_flops=entry.get("analytic_flops"))
    return entry


def cost_entry(name):
    """The registered entry for ``name`` (None when absent/disabled)."""
    if _state is None:
        return None
    with _state.lock:
        return _state.cost.get(name)


def cost_registry():
    """All registered entries, registration order (empty when the
    profiler never armed)."""
    if _state is None:
        return []
    with _state.lock:
        return list(_state.cost.values())


def cost_entries_by_meta(**match):
    """Registered entries whose ``meta`` carries every given
    key=value — e.g. ``cost_entries_by_meta(dtype="int8")`` selects
    the int8 serving-forward executables for the per-dtype roofline
    bench.py stamps."""
    return [e for e in cost_registry()
            if all((e.get("meta") or {}).get(k) == v
                   for k, v in match.items())]


def cost_report():
    """The cross-check view: every entry that carries an analytic
    estimate plus an overall ``agree`` verdict (True only when every
    comparable entry sits inside the ``cost_rtol`` band)."""
    entries = cost_registry()
    compared = [e for e in entries if e.get("analytic_flops")
                and e.get("flops")]
    return {
        "executables": entries,
        "compared": len(compared),
        "agree": all(e.get("agreement", False) for e in compared)
        if compared else None,
        "cost_rtol": float(_cfg.get("cost_rtol", 0.5)),
    }


# ---------------------------------------------------------------------------
# Pillar 2: the device-memory ledger
# ---------------------------------------------------------------------------

def ledger_swap(name, old_nbytes, new_nbytes):
    """``memory.Array`` hook: the Array named ``name`` replaced a
    device buffer of ``old_nbytes`` with one of ``new_nbytes`` (either
    0).  Call sites guard with :func:`enabled`; this re-guards so a
    stray call is still free."""
    if not enabled():
        return None
    p = _prof()
    p.ledger.swap(name, old_nbytes, new_nbytes)
    telemetry.gauge("profiler.ledger_bytes").set(p.ledger.live_bytes)
    telemetry.gauge("profiler.ledger_high_water_bytes").set(
        p.ledger.high_water_bytes)
    return True


def ledger_summary(top=16):
    """Ledger totals + per-name attribution (zeros when never armed)."""
    if _state is None:
        return DeviceLedger().summary(top)
    return _state.ledger.summary(top)


def epoch_check(epoch):
    """Epoch-boundary leak check (called by ``Loader.run`` when an
    epoch wraps): record the ledger's live bytes and flag a leak
    suspect after ``leak_epochs`` CONSECUTIVE epochs of growth
    totalling more than ``leak_min_bytes``.  Returns the suspect dict
    when one fired, else None."""
    if not enabled():
        return None
    p = _prof()
    with p.lock:
        p.epoch_bytes.append((int(epoch), p.ledger.live_bytes))
        window = int(_cfg.get("leak_epochs", 3))
        tail = p.epoch_bytes[-(window + 1):]
        if len(tail) < window + 1:
            return None
        deltas = [b - a for (_, a), (_, b) in zip(tail, tail[1:])]
        growth = tail[-1][1] - tail[0][1]
        if not (all(d > 0 for d in deltas)
                and growth >= int(_cfg.get("leak_min_bytes", 1 << 20))):
            return None
        p.leak_suspects += 1
    suspect = {"epoch": int(epoch), "grown_bytes": int(growth),
               "epochs": window, "live_bytes": tail[-1][1]}
    telemetry.counter("profiler.leak_suspects").inc()
    telemetry.instant("profiler.leak_suspect", **suspect)
    telemetry.record_event("profiler.leak_suspect", **suspect)
    logger.warning("device-memory leak suspect: ledger grew %d bytes "
                   "over %d consecutive epochs (live %d)",
                   growth, window, tail[-1][1])
    return suspect


def sample_device_memory():
    """``device.memory_stats()`` where the backend provides it (TPU:
    bytes_in_use / peak_bytes_in_use; CPU returns None).  Gauges
    ``profiler.device<N>_bytes_in_use`` per device and returns the
    per-device dict — None entries mean the backend has no counter."""
    try:
        import jax
        devices = jax.local_devices()
    except Exception:  # pragma: no cover - jax is a baked-in dep
        return None
    out = {}
    for d in devices:
        stats = None
        try:
            stats = d.memory_stats()
        except Exception:  # backend without the API
            stats = None
        out[str(d.id)] = stats
        if stats and "bytes_in_use" in stats:
            telemetry.gauge(telemetry.labeled(
                "profiler.device_bytes_in_use", device=d.id)).set(
                int(stats["bytes_in_use"]))
    return out


# ---------------------------------------------------------------------------
# Pillar 3: the step-time breakdown
# ---------------------------------------------------------------------------

def _add_parts(parts, wall, steps=0, windows=0):
    p = _prof()
    with p.lock:
        for k, v in parts.items():
            if v:
                p.parts[k] += v
        p.wall += wall
        p.steps += steps
        p.windows += windows
    for k, v in parts.items():
        if v:
            telemetry.histogram("profiler.%s_seconds" % k).observe(v)


def note_data_wait(dt):
    """Loader hook: ``dt`` seconds were spent serving (selecting +
    filling) one minibatch.  Inside a window probe the wall time is
    owned by the probe; standalone (unit graph / VALID fills) it
    advances the global wall too — so parts always sum to wall."""
    if not enabled():
        return None
    p = _prof()
    with p.lock:
        p.parts["data_wait"] += dt
        if p.probes_active == 0:
            p.wall += dt
    telemetry.histogram("profiler.data_wait_seconds").observe(dt)
    return True


def note_gd_step(unit, t0):
    """Unit-graph hook (``GradientDescentBase.run``): partition one GD
    unit's step into host dispatch (``t0`` .. now) and device compute
    (an explicit block on the unit's device-resident weight/bias
    buffers — the sync is the price of attribution, paid only while
    the profiler is armed)."""
    if not enabled():
        return None
    t1 = time.perf_counter()
    dev = []
    for attr in ("weights", "bias"):
        arr = getattr(unit, attr, None)
        # peek the device side without forcing a transfer ("dev"/"sync"
        # are memory.py's state constants; kept as literals so the
        # profiler never imports memory — memory imports US)
        if arr is not None and \
                getattr(arr, "_state", None) in ("dev", "sync"):
            d = getattr(arr, "_dev", None)
            if d is not None:
                dev.append(d)
    t2 = t1
    if dev:
        try:
            import jax
            jax.block_until_ready(dev)
            t2 = time.perf_counter()
        except Exception:  # noqa: BLE001 - never kill a training step
            t2 = t1
    _add_parts({"dispatch": t1 - t0, "device": t2 - t1},
               wall=t2 - t0, steps=1)
    return True


class _WindowProbe(object):
    """One training window's wall-time partition.  Lifecycle (driven
    by the fused trainer):

    ``probe = profiler.window_probe()`` (None when disabled) →
    ``probe.collected()`` once the minibatch window is assembled →
    ``probe.dispatched(stats)`` right after the compiled dispatch
    returns (this BLOCKS on the result tree — device time becomes
    explicit) → ``probe.done(steps)`` after the host readback.

    Parts: ``data_wait`` (loader time inside the collection, reported
    by ``Loader.run`` itself), ``host_collect`` (collection minus
    loader), ``dispatch``, ``device``, ``readback``.  Their sum equals
    the probe's wall time by construction.

    Asynchronous control plane: the armed probe's ``dispatched`` block
    IS its documented per-window device sync — it drains the trainer's
    window pipeline, so breakdowns taken while profiling reflect the
    synchronous schedule (that is the point: attribution needs the
    wait).  Unarmed, mid-epoch windows never block and ``readback``
    accrues only on segment-final windows."""

    __slots__ = ("t0", "t_collect", "t_dispatch", "t_device", "_wait0",
                 "_closed")

    def __init__(self):
        p = _prof()
        with p.lock:
            p.probes_active += 1
            self._wait0 = p.parts["data_wait"]
        self.t0 = time.perf_counter()
        self.t_collect = None
        self.t_dispatch = None
        self.t_device = None
        self._closed = False

    def collected(self):
        self.t_collect = time.perf_counter()

    def dispatched(self, tree):
        self.t_dispatch = time.perf_counter()
        try:
            import jax
            jax.block_until_ready(tree)
        except Exception:  # noqa: BLE001 - breakdown must not kill a run
            pass
        self.t_device = time.perf_counter()

    def done(self, steps=1):
        """Close the probe and accumulate its parts.  Idempotent — call
        sites close in a ``finally`` so an exception mid-window cannot
        leak ``probes_active`` (which would stop loader data-wait from
        advancing the global wall)."""
        if self._closed:
            return None
        self._closed = True
        t1 = time.perf_counter()
        tc = self.t_collect if self.t_collect is not None else self.t0
        td = self.t_dispatch if self.t_dispatch is not None else tc
        tv = self.t_device if self.t_device is not None else td
        p = _prof()
        with p.lock:
            waited = max(0.0, p.parts["data_wait"] - self._wait0)
            p.probes_active = max(0, p.probes_active - 1)
        parts = {
            "data_wait": 0.0,  # already accumulated by Loader.run
            "host_collect": max(0.0, (tc - self.t0) - waited),
            "dispatch": td - tc,
            "device": tv - td,
            "readback": t1 - tv,
        }
        # the probe owns this window's wall; the loader's data_wait
        # seconds were parts-only while the probe was active
        _add_parts(parts, wall=(t1 - self.t0), steps=steps, windows=1)
        return parts


def window_probe():
    """A new :class:`_WindowProbe`, or None when disabled (call sites
    additionally guard — the disabled cost is one predicate)."""
    if not enabled():
        return None
    return _WindowProbe()


def breakdown_summary():
    """The accumulated partition + the bound verdict.  Fractions are
    over total wall time; the verdict names the LARGEST consumer:
    ``input-bound`` (data wait), ``compute-bound`` (device), or
    ``host-bound`` (collect + dispatch + readback).  None when nothing
    was recorded."""
    if _state is None:
        return None
    p = _state
    with p.lock:
        parts = {k: p.parts.get(k, 0.0) for k in PARTS}
        wall, steps, windows = p.wall, p.steps, p.windows
    total = sum(parts.values())
    if total <= 0.0:
        return None
    data = parts["data_wait"]
    device = parts["device"]
    host = total - data - device
    if data >= device and data >= host:
        verdict = "input-bound"
    elif device >= host:
        verdict = "compute-bound"
    else:
        verdict = "host-bound"
    return {
        "parts_seconds": {k: round(v, 6) for k, v in parts.items()},
        "fractions": {"data_wait": round(data / total, 4),
                      "device": round(device / total, 4),
                      "host": round(host / total, 4)},
        "wall_seconds": round(wall, 6),
        "steps": steps,
        "windows": windows,
        "verdict": verdict,
    }


# ---------------------------------------------------------------------------
# On-demand jax.profiler capture (/debug/profile + the CLI)
# ---------------------------------------------------------------------------

_capture_lock = locksmith.lock("profiler.capture")
_heartbeat = None


def capture_trace(seconds=3.0, directory=None):
    """Capture a ``jax.profiler`` trace for ``seconds`` and return
    ``{"trace_dir", "seconds", "files"}``.  On-demand — works whether
    or not the profiler flag is armed (the request itself is the
    opt-in).  One capture at a time; a concurrent request raises
    ``RuntimeError`` (the HTTP endpoint maps it to 409).  A tiny
    jitted heartbeat is executed inside the window so the trace always
    contains at least one device event."""
    global _heartbeat
    seconds = max(0.05, min(
        float(seconds), float(_cfg.get("capture_seconds_cap", 60.0))))
    base = (directory or _cfg.get("capture_dir", None)
            or os.path.join(root.common.dirs.cache, "profiles"))
    stamp = time.strftime("%Y%m%d_%H%M%S")
    path = os.path.join(base, "capture_%s_pid%d" % (stamp, os.getpid()))
    n = 0
    while os.path.exists(path):
        n += 1
        path = os.path.join(base, "capture_%s_pid%d_%d"
                            % (stamp, os.getpid(), n))
    if not _capture_lock.acquire(blocking=False):
        raise RuntimeError("a profiler capture is already running")
    try:
        import jax
        import jax.numpy as jnp
        os.makedirs(path, exist_ok=True)
        if _heartbeat is None:
            _heartbeat = jax.jit(lambda a: a + 1.0)
        jax.profiler.start_trace(path)
        try:
            deadline = time.perf_counter() + seconds
            while True:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                time.sleep(min(0.05, remaining))
            jax.block_until_ready(_heartbeat(jnp.zeros(())))
        finally:
            jax.profiler.stop_trace()
    finally:
        _capture_lock.release()
    files = sorted(
        os.path.relpath(f, path)
        for f in glob.glob(os.path.join(path, "**", "*"), recursive=True)
        if os.path.isfile(f))
    telemetry.record_event("profiler.capture", trace_dir=path,
                           seconds=seconds, files=len(files))
    logger.info("profiler capture (%.2fs) -> %s (%d files)",
                seconds, path, len(files))
    return {"trace_dir": path, "seconds": seconds, "files": files}


# ---------------------------------------------------------------------------
# Reports
# ---------------------------------------------------------------------------

def snapshot():
    """JSON-able view of all three pillars (what ``export_report``
    writes and ``GET /debug/profiler`` serves)."""
    return {
        "enabled": enabled(),
        "cost_registry": cost_registry(),
        "ledger": ledger_summary(),
        "breakdown": breakdown_summary(),
        "device_memory": sample_device_memory(),
        "leak_suspects": (_state.leak_suspects
                          if _state is not None else 0),
    }


def export_report(path):
    """Write :func:`snapshot` as JSON (the file
    ``tools/profile_summary.py --roofline / --ledger`` renders)."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(snapshot(), f, indent=2, default=str)
    return path


# ---------------------------------------------------------------------------
# CLI: python -m znicz_tpu profile
# ---------------------------------------------------------------------------

def cli_main(argv=None):
    """``python -m znicz_tpu profile TARGET``.

    * TARGET is a URL (``http://host:port``) — hit the running
      server's ``GET /debug/profile?seconds=N`` and print the reply.
    * TARGET is a workflow spec (sample name / module / .py file) —
      run it with the profiler and telemetry armed under
      ``jax.profiler.trace``, then write ``profiler_report.json`` next
      to the device trace and print the three-pillar summary.
    """
    import argparse
    parser = argparse.ArgumentParser(
        prog="python -m znicz_tpu profile",
        description="Capture a device trace from a running server "
                    "(URL target) or run a workflow under the full "
                    "introspection stack (workflow target).")
    parser.add_argument("target",
                        help="http://host:port of a running status/"
                             "serving server, OR a workflow spec "
                             "(sample name, dotted module, .py file)")
    parser.add_argument("--seconds", type=float, default=3.0,
                        help="capture window for the URL mode "
                             "(default 3)")
    parser.add_argument("--out", default=None,
                        help="output directory for the workflow mode "
                             "(default <cache>/profiles/cli_<stamp>)")
    args = parser.parse_args(argv)

    if args.target.startswith(("http://", "https://")):
        import urllib.request
        url = (args.target.rstrip("/")
               + "/debug/profile?seconds=%g" % args.seconds)
        with urllib.request.urlopen(url,
                                    timeout=args.seconds + 60) as r:
            doc = json.loads(r.read())
        print(json.dumps(doc, indent=2))  # noqa: T201 - CLI output
        return 0

    telemetry.enable()
    enable()
    out = args.out or os.path.join(
        root.common.dirs.cache, "profiles",
        "cli_%s" % time.strftime("%Y%m%d_%H%M%S"))
    os.makedirs(out, exist_ok=True)
    from znicz_tpu.launcher import run_workflow
    import jax
    with jax.profiler.trace(out):
        run_workflow(args.target)
        import jax.numpy as jnp
        jax.block_until_ready(jnp.zeros(()) + 0)  # drain before close
    report = export_report(os.path.join(out, "profiler_report.json"))
    bd = breakdown_summary()
    print("device trace -> %s" % out)  # noqa: T201 - CLI output
    print("profiler report -> %s" % report)  # noqa: T201
    print("executables registered: %d"  # noqa: T201
          % len(cost_registry()))
    led = ledger_summary()
    print("ledger: live %d B, high water %d B, balanced=%s"  # noqa: T201
          % (led["live_bytes"], led["high_water_bytes"],
             led["balanced"]))
    if bd:
        print("step breakdown: %s (data %.1f%% / device %.1f%% / "  # noqa
              "host %.1f%%)"
              % (bd["verdict"], 100 * bd["fractions"]["data_wait"],
                 100 * bd["fractions"]["device"],
                 100 * bd["fractions"]["host"]))
    print("summarize: python tools/profile_summary.py %s"  # noqa: T201
          % out)
    return 0
