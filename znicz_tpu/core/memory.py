"""Mirrored host/device tensor buffers.

TPU-era equivalent of ``veles.memory.Array`` (SURVEY.md layer L1).  The
reference's central invariant — crossing the host/device boundary is explicit
and lazy via ``map_read/map_write/map_invalidate/unmap`` — is kept, but the
device side is an immutable ``jax.Array``: device "writes" replace the buffer
(:meth:`Array.set_dev`), which is exactly how XLA wants it.  Chains of units
pass device buffers to each other without host round-trips; ``.mem`` pulls to
host on demand.

States:
  HOST  — host numpy copy is authoritative (device stale/absent)
  DEV   — device jax.Array is authoritative (host stale/absent)
  SYNC  — both valid
"""

import numpy

from znicz_tpu.core import profiler
from znicz_tpu.core import telemetry

HOST, DEV, SYNC = "host", "dev", "sync"


def roundup(n, m):
    """Round ``n`` up to a multiple of ``m``
    (reference: veles.memory.roundup)."""
    r = n % m
    return n if r == 0 else n + m - r


class Array(object):
    """A tensor mirrored between host numpy and device jax.Array."""

    __slots__ = ("_host", "_dev", "_state", "name", "_dev_nbytes")

    def __init__(self, data=None, name=None):
        self._host = None
        self._dev = None
        self._state = HOST
        self.name = name
        #: device bytes this Array has accounted in the profiler's
        #: memory ledger (stays 0 while the profiler is disabled)
        self._dev_nbytes = 0
        if data is not None:
            self.mem = data

    def _ledger_swap(self, new_dev):
        """Device-memory ledger hook — called ONLY when the profiler is
        enabled, at the three points ``_dev`` changes (upload, set_dev,
        reset)."""
        nbytes = int(getattr(new_dev, "nbytes", 0) or 0) \
            if new_dev is not None else 0
        profiler.ledger_swap(self.name, self._dev_nbytes, nbytes)
        self._dev_nbytes = nbytes

    # -- allocation / reset -------------------------------------------------
    def reset(self, arr=None):
        """Drop current contents; optionally adopt a new host array.

        Reference: ``Array.reset`` (used by unit initialize to realloc).
        """
        if self._dev is not None and profiler.enabled():
            self._ledger_swap(None)
        self._host = None if arr is None else numpy.asarray(arr)
        self._dev = None
        self._state = HOST
        return self

    @property
    def mem(self):
        """Host numpy view (syncs from device if the device copy is newer)."""
        if self._state == DEV:
            self._host = numpy.asarray(self._dev)
            self._state = SYNC
            if telemetry.enabled():
                telemetry.add_bytes("d2h", self._host.nbytes)
        return self._host

    @mem.setter
    def mem(self, value):
        if value is None:
            self.reset()
            return
        self._host = value if isinstance(value, numpy.ndarray) \
            else numpy.asarray(value)
        self._state = HOST

    # -- explicit mapping (reference contract, nn_units.py:51) --------------
    def map_read(self):
        if self._state == DEV:
            self._host = numpy.asarray(self._dev)
            self._state = SYNC
            if telemetry.enabled():
                telemetry.add_bytes("d2h", self._host.nbytes)
        return self

    def map_write(self):
        self.map_read()
        if self._host is not None and not self._host.flags.writeable:
            self._host = numpy.array(self._host)  # jax gives read-only views
        self._state = HOST
        return self

    def map_invalidate(self):
        """Host will be overwritten wholesale; skip device download."""
        if self._host is None and self._dev is not None:
            self._host = numpy.empty(self._dev.shape,
                                     dtype=numpy.dtype(str(self._dev.dtype)))
        elif self._host is not None and not self._host.flags.writeable:
            self._host = numpy.empty_like(self._host)
        self._state = HOST
        return self

    def unmap(self):
        """Hand ownership to the device (uploads if host was dirty)."""
        self.dev
        return self

    # -- device side --------------------------------------------------------
    @property
    def dev(self):
        """Device jax.Array (uploads host if the host copy is newer).

        On the CPU backend the upload hands the device a PRIVATE copy:
        ``jax.device_put`` of a numpy array is zero-copy there (the
        jax.Array aliases the host buffer), so an in-place host write —
        e.g. the loader refilling ``minibatch_data`` for the next
        minibatch — would otherwise race with still-pending async
        computations that read this value.  The copy is what makes the
        reference's map/unmap ownership contract actually hold under
        jax's async dispatch.  Accelerator backends DMA a copy into
        device memory anyway, so no extra host copy is paid there.
        """
        import jax
        if self._state == HOST:
            if self._host is None:
                return None
            host = self._host
            if jax.default_backend() == "cpu":
                host = numpy.array(host)
            self._dev = jax.device_put(host)
            self._state = SYNC
            if telemetry.enabled():
                telemetry.add_bytes("h2d", host.nbytes)
            if profiler.enabled():
                self._ledger_swap(self._dev)
        return self._dev

    def set_dev(self, arr):
        """Adopt a new device array as authoritative (a device 'write')."""
        if profiler.enabled():
            self._ledger_swap(arr)
        self._dev = arr
        self._state = DEV
        return self

    @property
    def devmem(self):  # reference-compatible alias
        return self.dev

    # -- shape & views ------------------------------------------------------
    def __bool__(self):
        return self._host is not None or self._dev is not None

    __nonzero__ = __bool__

    @property
    def shape(self):
        if self._state == DEV and self._dev is not None:
            return tuple(self._dev.shape)
        return self._host.shape if self._host is not None else \
            (tuple(self._dev.shape) if self._dev is not None else None)

    @shape.setter
    def shape(self, value):
        self.mem = self.mem.reshape(value)

    @property
    def size(self):
        s = self.shape
        return 0 if s is None else int(numpy.prod(s)) if s else 1

    @property
    def sample_size(self):
        """Elements per sample = size / shape[0] (reference semantics)."""
        s = self.shape
        return 0 if not s else self.size // s[0]

    @property
    def dtype(self):
        if self._host is not None:
            return self._host.dtype
        if self._dev is not None:
            return numpy.dtype(str(self._dev.dtype))
        return None

    @property
    def matrix(self):
        """2D (n_samples, sample_size) host view."""
        m = self.mem
        return m.reshape(m.shape[0], -1)

    @property
    def plain(self):
        """Flat host view."""
        return self.mem.reshape(-1)

    def __len__(self):
        s = self.shape
        return s[0] if s else 0

    def __getitem__(self, idx):
        return self.mem[idx]

    def __setitem__(self, idx, value):
        self.map_write()
        self.mem[idx] = value

    def __repr__(self):
        return "<Array %s %s %s state=%s>" % (
            self.name or "", self.shape, self.dtype, self._state)


def reshape(arr, shape):
    """Reshape an Array's host view (reference: veles.memory.reshape)."""
    arr.mem = arr.mem.reshape(shape)
    return arr.mem


def reshape_transposed(arr):
    m = arr.mem
    return m.reshape(m.shape[::-1])


def ravel(arr):
    return arr.mem.reshape(-1)


def interleave(arr):
    """CHW → HWC style interleave helper used by image tooling."""
    if arr.ndim == 3:
        return numpy.transpose(arr, (1, 2, 0))
    if arr.ndim == 4:
        return numpy.transpose(arr, (0, 2, 3, 1))
    raise ValueError("interleave expects 3D/4D")


class NumDiff(object):
    """Five-point numeric differentiation helper.

    Reference: ``veles.memory.NumDiff`` used by the gradient numdiff harness
    (tests/unit/gd_numdiff.py:74-78) — valid in float64 only.
    """

    #: Perturbation offsets in units of h.
    points = (2.0, 1.0, -1.0, -2.0)
    #: Five-point stencil coefficients / (12 h).
    coeffs = numpy.array([-1.0, 8.0, -8.0, 1.0], dtype=numpy.float64)
    divizor = 12.0
    h = 1.0e-4  # matches NumDiff usage scale in the reference tests

    def __init__(self):
        self.errs = numpy.zeros(len(NumDiff.points), dtype=numpy.float64)

    @property
    def derivative(self):
        return (self.errs * NumDiff.coeffs).sum() / (
            NumDiff.divizor * NumDiff.h)
