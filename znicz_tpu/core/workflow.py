"""Workflow — a container of units with a queue-based dataflow scheduler.

TPU-era equivalent of ``veles.workflow`` (SURVEY.md layer L3, §3.1).  The
reference runs an event-driven async engine; at TPU epoch-level cadence a
synchronous FIFO scheduler is semantically identical and much simpler:
units fire when all their parents have signalled and their gates permit.

The canonical training graph (standard_workflow.py:173-208) is a loop:
repeater -> loader -> forwards -> evaluator -> decision -> snapshotter ->
gds -> (back to repeater), with ``decision.complete`` gating the repeater
(block) and the end_point (pass).
"""

from collections import deque

from znicz_tpu.core.units import Unit
from znicz_tpu.core import profiler
from znicz_tpu.core import prng as random_generator
from znicz_tpu.core import telemetry


class NoMoreJobs(Exception):
    """Raised by a decision when the training run is over
    (reference: veles.workflow.NoMoreJobs, decision.py:218-220)."""


class StartPoint(Unit):
    def run(self):
        pass


class EndPoint(Unit):
    def run(self):
        self.workflow._on_end_point()


class Repeater(Unit):
    """Fires on ANY parent signal — the loop-closing unit
    (reference: veles.workflow.Repeater)."""

    def _ready_to_fire(self):
        return any(self._links_from.values()) or not self._links_from

    def _reset_fired(self):
        for k in self._links_from:
            self._links_from[k] = False


class FireStarter(Unit):
    """Re-arms gates of listed units (reference: veles.plumbing.FireStarter,
    linked by standard_workflow_base.link_fire_starter)."""

    def __init__(self, workflow, **kwargs):
        super(FireStarter, self).__init__(workflow, **kwargs)
        self.units = kwargs.get("units", [])

    def run(self):
        for u in self.units:
            u.gate_block <<= False


class Workflow(Unit):
    """A unit container + scheduler.  Nestable (a Workflow is a Unit)."""

    def __init__(self, workflow=None, **kwargs):
        self._units = []
        super(Workflow, self).__init__(workflow, **kwargs)
        self.start_point = StartPoint(self, name="start_point")
        self.end_point = EndPoint(self, name="end_point")
        self._queue = deque()
        self._running = False
        self._stopped_by_end_point = False
        self.launcher = kwargs.get("launcher", None)
        self._is_slave = False
        self._is_master = False
        self.device = None
        self._finished_callbacks = []

    # -- container -----------------------------------------------------------
    def add_unit(self, unit):
        if unit.workflow is not None and unit.workflow is not self:
            raise ValueError(
                "%s already belongs to workflow %s" % (unit.name,
                                                       unit.workflow.name))
        if unit.workflow is None:
            unit.workflow = self
            self._units.append(unit)
        return unit

    def add_ref(self, unit):  # reference-compatible alias
        return self.add_unit(unit)

    def del_ref(self, unit):
        if unit in self._units:
            self._units.remove(unit)
            unit.workflow = None

    @property
    def units(self):
        return list(self._units)

    def __iter__(self):
        return iter(self._units)

    # -- roles ---------------------------------------------------------------
    @property
    def is_slave(self):
        return self._is_slave

    @property
    def is_master(self):
        return self._is_master

    @property
    def is_standalone(self):
        return not (self._is_slave or self._is_master)

    # -- lifecycle -----------------------------------------------------------
    def initialize(self, device=None, **kwargs):
        """Initialize all units in graph order with demand-driven retries.

        Some units' demanded attrs are produced by other units' initialize
        (e.g. forwards allocate ``output`` consumed by the next layer), so we
        sweep until quiescent (the reference initializes in graph order with
        the same effect).
        """
        super(Workflow, self).initialize(device=device, **kwargs)
        self.device = device
        if telemetry.journal_enabled():
            # the black box's first entry: which workflow, which config
            # (export_journal serializes with default=str, so arbitrary
            # config values are fine)
            from znicz_tpu.core.config import root
            telemetry.record_event("config", workflow=self.name,
                                   config=root.as_dict())
        pending = [u for u in self._units if not u.initialized]
        order = self._graph_order()
        pending.sort(key=lambda u: order.get(u, len(order)))
        max_sweeps = len(pending) + 2
        for _ in range(max_sweeps):
            if not pending:
                break
            deferred = []
            for u in pending:
                missing = u._check_demands()
                if missing:
                    deferred.append((u, missing))
                    continue
                u.initialize(device=device, **kwargs)
                u._initialized = True
            if len(deferred) == len(pending):
                lines = "; ".join("%s needs %s" % (u.name, m)
                                  for u, m in deferred)
                raise RuntimeError(
                    "Workflow.initialize deadlock — unsatisfied demands: "
                    + lines)
            pending = [u for u, _ in deferred]
        return self

    def _graph_order(self):
        """BFS order over control links from start_point."""
        order, seen = {}, set()
        q = deque([self.start_point])
        seen.add(self.start_point)
        i = 0
        while q:
            u = q.popleft()
            order[u] = i
            i += 1
            for dst in u._links_to:
                if dst not in seen:
                    seen.add(dst)
                    q.append(dst)
        return order

    # -- scheduler -----------------------------------------------------------
    def _schedule(self, unit):
        self._queue.append(unit)

    def run(self):
        """Run the dataflow until quiescence or end_point.  Each
        scheduled unit's run() is span-traced by the engine
        (core/units.py _fire) under this workflow-level span."""
        self._running = True
        self._stopped_by_end_point = False
        self._queue.clear()
        for u in self._units:
            u._reset_fired()
        self._schedule(self.start_point)
        if telemetry.enabled():
            telemetry.counter("workflow.runs").inc()
        telemetry.record_event("workflow.run", workflow=self.name)
        try:
            with telemetry.span("workflow.run", workflow=self.name):
                while self._queue and self._running:
                    unit = self._queue.popleft()
                    unit._fire()
        except NoMoreJobs:
            pass
        self._running = False
        if profiler.enabled():
            # end-of-run device-memory gauge sample (TPU backends; a
            # backend without memory_stats reports None entries)
            profiler.sample_device_memory()
        for cb in self._finished_callbacks:
            cb()
        return self

    def _on_end_point(self):
        self._stopped_by_end_point = True
        self._running = False

    def stop(self):
        self._running = False

    def stopped(self):
        return not self._running

    def on_workflow_finished(self, callback=None):
        if callback is not None:
            self._finished_callbacks.append(callback)

    # -- misc reference-parity helpers ----------------------------------------
    @property
    def run_is_blocked(self):
        return False

    def as_dot(self):
        """Graphviz DOT text of the control graph (reference: the veles
        core renders workflow.png the same way).  Solid edges = control
        links; the box label carries the unit class."""
        lines = ["digraph %s {" % type(self).__name__,
                 '  rankdir=TB; node [shape=box, fontsize=10];']
        ids = {u: "u%d" % i for i, u in enumerate(self._units)}
        for u in self._units:
            label = u.name if u.name == type(u).__name__ else \
                "%s\\n(%s)" % (u.name, type(u).__name__)
            lines.append('  %s [label="%s"];' % (ids[u], label))
        for u in self._units:
            for child in u.links_to:
                if child in ids:
                    lines.append("  %s -> %s;" % (ids[u], ids[child]))
        lines.append("}")
        return "\n".join(lines)

    def dump_graph(self, path):
        """Write the DOT graph to ``path`` (render with graphviz)."""
        with open(path, "w") as f:
            f.write(self.as_dot())
        self.info("workflow graph -> %s", path)
        return path

    def run_profiled(self, log_dir):
        """Run under the JAX/XLA profiler: device traces land in
        ``log_dir`` (view with xprof/tensorboard).  The TPU-era
        replacement for the reference's per-kernel GPU profiling
        (SURVEY.md §5.1) — pair with :meth:`log_unit_timings` for the
        host-side view."""
        import jax
        import jax.numpy as jnp
        with jax.profiler.trace(str(log_dir)):
            result = self.run()
            # drain the device queue before the trace closes: dispatch
            # is async and per-device program-ordered, so blocking on a
            # trailing no-op covers all in-flight work
            jax.block_until_ready(jnp.zeros(()) + 0)
        return result

    # -- per-unit timing stats (reference nn_units.py:217-239) ---------------
    def unit_timings(self):
        """[(unit, total_seconds, run_count)] sorted by total time desc —
        the engine times every unit's run() (core/units.py _fire).

        NOTE: device work is dispatched asynchronously, so by default a
        unit's time covers dispatch only and compute lands on whichever
        unit blocks first (map_read).  Set
        ``root.common.timings.sync_each_run = True`` before the run to
        charge compute to the unit that issued it."""
        rows = [(u, getattr(u, "run_time_", 0.0),
                 getattr(u, "run_count_", 0)) for u in self._units
                if getattr(u, "run_count_", 0)]
        rows.sort(key=lambda r: -r[1])
        return rows

    def log_unit_timings(self):
        """Log the per-unit wall-time table at INFO."""
        rows = self.unit_timings()
        total = sum(r[1] for r in rows) or 1.0
        self.info("unit timings (%d runs total):", sum(r[2] for r in rows))
        for unit, t, n in rows:
            self.info("  %-28s %8.3fs %6d runs  %5.1f%%",
                      unit.name, t, n, 100.0 * t / total)


class DummyLauncher(object):
    """In-process launcher stand-in (reference: veles.dummy.DummyLauncher,
    used by the functional-test harness standard_test.py:64-65)."""

    def __init__(self, **kwargs):
        self.testing = kwargs.get("testing", False)
        self.device = None
        self.workflow = None
        self.interactive = False

    def add_ref(self, workflow):
        self.workflow = workflow

    def del_ref(self, workflow):
        pass

    @property
    def is_slave(self):
        return False

    @property
    def is_master(self):
        return False

    @property
    def is_standalone(self):
        return True

    def initialize(self, **kwargs):
        if self.workflow is not None:
            self.workflow.initialize(**kwargs)

    def run(self):
        if self.workflow is not None:
            self.workflow.run()

    def stop(self):
        if self.workflow is not None:
            self.workflow.stop()


class DummyWorkflow(Workflow):
    """A standalone workflow with a DummyLauncher parent
    (reference: veles.dummy.DummyWorkflow)."""

    def __init__(self, **kwargs):
        super(DummyWorkflow, self).__init__(None, **kwargs)
        self.launcher = DummyLauncher()
        self.launcher.add_ref(self)


class DummyUnit(Unit):
    """Bag-of-attributes unit for tests (reference: veles.dummy.DummyUnit)."""

    def __init__(self, workflow=None, **kwargs):
        super(DummyUnit, self).__init__(workflow, **kwargs)
        for k, v in kwargs.items():
            setattr(self, k, v)


# Seed the default PRNG streams on import so standalone scripts behave
# deterministically (tests re-seed from seed files).
random_generator.get(1)
random_generator.get(2)
