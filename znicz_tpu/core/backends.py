"""Device backends.

TPU-era equivalent of ``veles.backends`` (SURVEY.md layer L0).  The reference
dispatches NumpyDevice / OpenCL / CUDA; znicz_tpu dispatches NumpyDevice /
JaxDevice.  A JaxDevice wraps whatever jax platform is live (TPU on real
hardware, CPU in tests) — XLA JIT specialization replaces the reference's
per-shape ``#define`` kernel builds (conv.py:185-213).
"""


from znicz_tpu.core.config import root


class Device(object):
    backend_name = "abstract"

    def sync(self):
        pass

    @property
    def exists(self):
        return True

    def __repr__(self):
        return "<%s>" % type(self).__name__


class NumpyDevice(Device):
    """Pure-numpy reference backend — the executable spec
    (reference test pattern: tests/unit/test_all2all.py:95-152)."""

    backend_name = "numpy"


class JaxDevice(Device):
    """XLA-backed device (TPU on hardware, CPU host platform in tests)."""

    backend_name = "jax"

    def __init__(self, platform=None):
        import jax
        self._jax = jax
        devices = jax.devices(platform) if platform else jax.devices()
        self.jax_device = devices[0]
        self.platform = self.jax_device.platform

    def sync(self):
        # Block until all dispatched work completes.
        import jax
        jax.effects_barrier()

    def __repr__(self):
        return "<JaxDevice %s>" % (self.jax_device,)


_default_device = None


def get_device(backend=None):
    """Resolve the process-default device per config
    (root.common.engine.backend: numpy | jax | auto)."""
    global _default_device
    backend = backend or root.common.engine.backend
    if backend == "numpy":
        return NumpyDevice()
    if backend == "jax":
        return JaxDevice()
    # auto
    if _default_device is None:
        try:
            _default_device = JaxDevice()
        except Exception:  # pragma: no cover - jax always present here
            _default_device = NumpyDevice()
    return _default_device
