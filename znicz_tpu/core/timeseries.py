"""Metric time-series — bounded in-process history of the registry.

PR 1's telemetry registry (:mod:`znicz_tpu.core.telemetry`) is
*cumulative*: ``/metrics`` answers "how many so far", never "how fast
right now".  The serving autoscaler direction (ROADMAP item 2) and any
operator staring at a tail-latency incident need the **over-time**
view: queue depth a minute ago, the request rate across the last
burn window, whether a counter spiked when the journal says a breaker
opened.  This module keeps that history in process:

* a background **sampler** (daemon thread, period
  ``root.common.telemetry.timeseries.interval_ms``) snapshots every
  counter/gauge whose family matches the curated ``prefixes`` knob —
  plus the ``p50``/``p99`` of matching histograms — into bounded
  timestamped rings (``capacity`` points per series, oldest drop
  first);
* **query helpers** — :func:`rate` (per-second increase of a counter
  over a trailing window) and :func:`windowed_delta` (absolute
  increase) — the exact quantities a burn-rate alert or an autoscaler
  consumes;
* ``GET /debug/timeseries`` on every ``HandlerBase`` server (status
  dashboard AND serving front end) serves :func:`snapshot`;
  ``tools/profile_summary.py --timeseries`` renders a saved payload;
* :func:`merge_snapshots` — the fleet view: the router fans the
  endpoint out to its replicas and timestamp-merges the rings
  (step-function SUM for counters/gauges, MAX for quantiles) with
  per-source attribution, so ``rate()`` works at the front door
  (serving/router.py, PR 16).

Disabled-by-default discipline (the health.py contract): everything
gates on ``root.common.telemetry.timeseries.enabled``.  When off,
:func:`maybe_start` returns without touching anything, the thread
never exists, and no ring is ever allocated — the standing cost is ONE
config predicate (pinned by a monkeypatch-boom test).  Tests drive
:func:`sample_once` directly with an injectable ``now`` so the math is
checkable with zero sleeps.
"""

import collections
import threading
import time

from znicz_tpu.core.config import root
from znicz_tpu.core import telemetry
from znicz_tpu.analysis import locksmith

#: the config node (stable object identity — config.py declares it)
_cfg = root.common.telemetry.timeseries

_lock = locksmith.lock("timeseries.registry")

telemetry.register_help(
    "timeseries", "metric time-series sampler (core/timeseries.py): "
                  "sweeps completed and series ring count")

#: name -> _Series; created lazily per sampled series
_series = {}

_thread = None
_stop = threading.Event()

#: monotonic count of completed sampler sweeps (tests + /debug view)
_sweeps = 0


def enabled():
    """The one gate — a live read of
    ``root.common.telemetry.timeseries.enabled``."""
    return bool(_cfg.get("enabled", False))


def enable(**overrides):
    for k, v in overrides.items():
        setattr(root.common.telemetry.timeseries, k, v)
    root.common.telemetry.timeseries.enabled = True
    return True


def disable():
    root.common.telemetry.timeseries.enabled = False
    return False


class _Series(object):
    """One bounded timestamped ring: (unix_seconds, value) points."""

    __slots__ = ("name", "kind", "points")

    def __init__(self, name, kind, capacity):
        self.name = name
        self.kind = kind
        self.points = collections.deque(maxlen=capacity)


def _prefixes():
    raw = _cfg.get("prefixes",
                   "serving,slo,jax,trainer,transfer,loader,pyprof")
    return tuple(p.strip() for p in str(raw).split(",") if p.strip())


def _wanted(name, prefixes):
    return name.split(".")[0] in prefixes


def sample_once(now=None):
    """One sampler sweep: append the current value of every selected
    counter/gauge (and matching histograms' p50/p99) to its ring.
    Returns the number of series touched (0 when the gate is off —
    the disabled path reads ONE predicate and nothing else)."""
    if not enabled():
        return 0
    snap = telemetry.snapshot()
    t = float(now if now is not None else time.time())
    prefixes = _prefixes()
    cap = int(_cfg.get("capacity", 512))
    touched = 0
    with _lock:
        for kind_key, kind in (("counters", "counter"),
                               ("gauges", "gauge")):
            for name, value in snap[kind_key].items():
                if not _wanted(name, prefixes):
                    continue
                s = _series.get(name)
                if s is None:
                    s = _series[name] = _Series(name, kind, cap)
                s.points.append((t, float(value)))
                touched += 1
        for name, st in snap["histograms"].items():
            if not _wanted(name, prefixes) or not st.get("count"):
                continue
            for q in ("p50", "p99"):
                if st.get(q) is None:
                    continue
                qname = "%s.%s" % (name, q)
                s = _series.get(qname)
                if s is None:
                    s = _series[qname] = _Series(qname, "quantile", cap)
                s.points.append((t, float(st[q])))
                touched += 1
    global _sweeps
    _sweeps += 1
    if telemetry.enabled():
        telemetry.counter("timeseries.sweeps").inc()
        telemetry.gauge("timeseries.series").set(len(_series))
    sink = _checkpoint_sink
    if sink is not None:
        try:
            sink(_sweeps, t)
        except Exception:  # noqa: BLE001 - never fail the sampler
            pass
    return touched


#: durable-checkpoint sink: the blackbox (core/blackbox.py) installs
#: a ``fn(sweeps, now)`` here when armed and persists
#: :func:`last_points` every Nth sweep, so rate() queries survive
#: process restarts.  None (one pointer compare) when unarmed.
_checkpoint_sink = None


def set_checkpoint_sink(fn):
    """Install (or, with None, remove) the per-sweep checkpoint
    sink."""
    global _checkpoint_sink
    _checkpoint_sink = fn


def last_points():
    """The newest point of every ring —
    ``{name: {"kind", "t", "v"}}`` — the blackbox checkpoint payload
    (a checkpoint needs only the frontier: the previous checkpoints
    already persisted the history)."""
    with _lock:
        return {s.name: {"kind": s.kind,
                         "t": s.points[-1][0], "v": s.points[-1][1]}
                for s in _series.values() if s.points}


def _run():
    while not _stop.is_set():
        if not enabled():
            return  # gate flipped off: the thread retires itself
        try:
            sample_once()
        except Exception:  # noqa: BLE001 - a sampler must never die
            pass
        _stop.wait(float(_cfg.get("interval_ms", 1000.0)) / 1e3)


def maybe_start():
    """Start the background sampler iff the gate is on and no thread
    runs (idempotent; called by ``HttpServerBase.start`` so arming the
    knob before a server starts is all an operator does).  Returns
    True when a sampler is running after the call."""
    if not enabled():
        return False
    global _thread
    with _lock:
        if _thread is not None and _thread.is_alive():
            return True
        _stop.clear()
        _thread = threading.Thread(target=_run,
                                   name="znicz:timeseries",
                                   daemon=True)
        _thread.start()
    return True


def stop():
    """Stop the sampler thread (keeps the collected rings)."""
    global _thread
    with _lock:
        thread, _thread = _thread, None
    _stop.set()
    if thread is not None:
        thread.join(timeout=5)
    _stop.clear()


def reset():
    """Drop every ring and the sweep count (tests, bench isolation)."""
    global _sweeps
    stop()
    with _lock:
        _series.clear()
    _sweeps = 0


def series_names():
    with _lock:
        return sorted(_series)


def points(name):
    """The (t, value) points of one series, oldest first."""
    with _lock:
        s = _series.get(name)
        return list(s.points) if s is not None else []


def _window_points(pts, window_s, now=None):
    if not pts:
        return []
    if window_s is None:
        return pts
    horizon = float(now if now is not None else pts[-1][0]) \
        - float(window_s)
    return [p for p in pts if p[0] >= horizon]


def windowed_delta(name, window_s=None, now=None):
    """Absolute increase of ``name`` across the trailing ``window_s``
    seconds (whole ring when None).  None with fewer than two points
    in the window — no delta is not a zero delta."""
    pts = _window_points(points(name), window_s, now)
    if len(pts) < 2:
        return None
    return pts[-1][1] - pts[0][1]


def rate(name, window_s=None, now=None):
    """Per-second increase of a counter series over the trailing
    window (the PromQL ``rate()`` analogue on the in-process rings).
    None with fewer than two points or zero elapsed time."""
    pts = _window_points(points(name), window_s, now)
    if len(pts) < 2:
        return None
    dt = pts[-1][0] - pts[0][0]
    if dt <= 0:
        return None
    return (pts[-1][1] - pts[0][1]) / dt


def _trailing_rate(pts, window_s):
    """Per-second increase over the trailing window of one counter
    ring (None when underdetermined) — shared by :func:`snapshot` and
    :func:`merge_snapshots` so the router's merged view rates exactly
    like a replica's local one."""
    if len(pts) < 2 or pts[-1][0] <= pts[0][0]:
        return None
    win = [p for p in pts
           if window_s is None or p[0] >= pts[-1][0] - window_s]
    if len(win) < 2 or win[-1][0] <= win[0][0]:
        return None
    return round((win[-1][1] - win[0][1])
                 / (win[-1][0] - win[0][0]), 6)


def snapshot(window_s=None):
    """The JSON payload ``GET /debug/timeseries`` serves: every ring's
    points plus per-counter trailing rates (over ``window_s``, whole
    ring when None) — directly renderable by
    ``tools/profile_summary.py --timeseries``."""
    with _lock:
        items = [(s.name, s.kind, list(s.points))
                 for s in _series.values()]
    out = {"enabled": enabled(), "sweeps": _sweeps,
           "interval_ms": float(_cfg.get("interval_ms", 1000.0)),
           "series": {}, "rates": {}}
    for name, kind, pts in sorted(items):
        out["series"][name] = {
            "kind": kind, "points": [[round(t, 3), v] for t, v in pts]}
        if kind == "counter":
            rate_v = _trailing_rate(pts, window_s)
            if rate_v is not None:
                out["rates"][name] = rate_v
    return out


def _step_merge(sources, use_max=False):
    """Timestamp-merge several (t, value) rings into one: at every
    instant ANY source sampled, the merged value is the sum (max for
    quantile series) of each source's most recent value at-or-before
    that instant — the step-function semantics PromQL uses when
    summing counters across instances.  A source contributes nothing
    before its first point (a replica that joined the fleet late must
    not read as a counter reset)."""
    times = sorted({t for ring in sources.values() for t, _ in ring})
    idx = dict.fromkeys(sources, 0)
    last = dict.fromkeys(sources)
    merged = []
    for t in times:
        for label, ring in sources.items():
            i = idx[label]
            while i < len(ring) and ring[i][0] <= t:
                last[label] = ring[i][1]
                i += 1
            idx[label] = i
        vals = [v for v in last.values() if v is not None]
        if vals:
            merged.append((t, max(vals) if use_max else sum(vals)))
    return merged


def merge_snapshots(payloads, window_s=None):
    """Merge several :func:`snapshot` payloads into one fleet view —
    the router's ``GET /debug/timeseries`` fan-out
    (serving/router.py).  ``payloads`` maps a source label (replica
    id, or ``"router"`` for the front end's own rings) to its
    snapshot dict.

    Counters and gauges merge by :func:`_step_merge` SUM (fleet
    request rate = the sum of replica rates; fleet queue depth = the
    sum of replica depths); quantile series merge as the step-wise
    MAX — the conservative tail view, matching the /slo burn-rate
    aggregation.  Each merged series carries a ``sources`` block
    (per-source LAST value) for per-replica attribution, and
    ``rates`` is recomputed over the merged rings so ``rate()``-style
    queries work at the front door."""
    names = {}
    enabled_any = False
    sweeps = 0
    interval = None
    for label in sorted(payloads):
        snap = payloads[label] or {}
        enabled_any = enabled_any or bool(snap.get("enabled"))
        sweeps += int(snap.get("sweeps") or 0)
        if interval is None and snap.get("interval_ms") is not None:
            interval = float(snap["interval_ms"])
        for name, block in (snap.get("series") or {}).items():
            entry = names.setdefault(
                name, {"kind": block.get("kind"), "sources": {}})
            entry["sources"][label] = [
                (float(t), float(v))
                for t, v in (block.get("points") or ())]
    cap = int(_cfg.get("capacity", 512))
    out = {"enabled": enabled_any, "merged": True,
           "sources": sorted(payloads),
           "sweeps": sweeps,
           "interval_ms": interval if interval is not None else 0.0,
           "series": {}, "rates": {}}
    for name in sorted(names):
        entry = names[name]
        pts = _step_merge(entry["sources"],
                          use_max=entry["kind"] == "quantile")[-cap:]
        out["series"][name] = {
            "kind": entry["kind"],
            "points": [[round(t, 3), v] for t, v in pts],
            "sources": {
                label: (ring[-1][1] if ring else None)
                for label, ring in sorted(entry["sources"].items())},
        }
        if entry["kind"] == "counter":
            rate_v = _trailing_rate(pts, window_s)
            if rate_v is not None:
                out["rates"][name] = rate_v
    return out
