"""StandardWorkflow — the one-stop training-graph builder.

TPU-era equivalent of reference standard_workflow.py (1201 LoC — SURVEY.md
§2.1).  ``create_workflow`` assembles the canonical train graph::

    repeater -> loader -> forwards[0..n] -> evaluator -> decision
      -> snapshotter -> gds[n..0] -> (loop back to repeater) -> end_point

from the declarative ``layers`` config, pairing each forward with its
registered backward (reference standard_workflow.py:173-208, 289-374).
"""

from znicz_tpu.standard_workflow_base import StandardWorkflowBase
from znicz_tpu.core.snapshotter import SnapshotterRegistry
from znicz_tpu.units.conv import ConvolutionalBase
from znicz_tpu.units.gd_pooling import GDPooling
from znicz_tpu.units.decision import DecisionsRegistry
from znicz_tpu.units.evaluator import EvaluatorsRegistry
# Importing the units package registers every layer type — keep even if
# it looks unused (reference standard_workflow.py:58-60).
import znicz_tpu.units  # noqa: F401


class StandardWorkflow(StandardWorkflowBase):
    """(reference standard_workflow.py:81-1172)"""

    def __init__(self, workflow=None, **kwargs):
        super(StandardWorkflow, self).__init__(workflow, **kwargs)
        self.loss_function = kwargs.get("loss_function", "softmax")
        if self.loss_function not in EvaluatorsRegistry.evaluators:
            raise ValueError("Unknown loss_function %r (known: %s)" % (
                self.loss_function,
                sorted(EvaluatorsRegistry.evaluators)))
        self.decision_name = kwargs.get(
            "decision_name",
            "decision_gd" if self.loss_function == "softmax"
            else "decision_mse")
        self.snapshotter_name = kwargs.get("snapshotter_name", "nnfile")
        self.evaluator_config = self.config2kwargs(
            kwargs.get("evaluator_config"))
        self.decision_config = self.config2kwargs(
            kwargs.get("decision_config"))
        self.snapshotter_config = self.config2kwargs(
            kwargs.get("snapshotter_config"))
        if not self.preprocessing:
            self.create_workflow()

    # -- canonical graph (reference 173-208) --------------------------------
    def create_workflow(self):
        self.link_repeater(self.start_point)
        self.link_loader(self.repeater)
        self.link_forwards(("input", "minibatch_data"), self.loader)
        self.link_evaluator(self.forwards[-1])
        self.link_decision(self.evaluator)
        self.link_snapshotter(self.decision)
        last_gd = self.link_gds(self.snapshotter)
        self.link_loop(last_gd)
        self.link_end_point(last_gd)

    # -- backward chain (reference 289-374) ---------------------------------
    def link_gds(self, *parents):
        if not isinstance(self.layers, (tuple, list)):
            raise ValueError("layers should be a list of dicts")
        self.gds[:] = [None] * len(self.layers)
        first_gd = None
        units_to_delete = []
        for i, layer in reversed(list(enumerate(self.layers))):
            tpe, _, kwargs = self._get_layer_type_kwargs(layer)
            if not isinstance(self.forwards[i], self.layer_map[tpe].forward):
                raise TypeError(
                    "Forward layer %s at position %d is not an instance "
                    "of %s" % (self.forwards[i], i,
                               self.layer_map[tpe].forward))
            try:
                backward_cls = next(self.layer_map[tpe].backwards)
            except StopIteration:
                units_to_delete.append(i)
                continue
            unit = backward_cls(self, **kwargs)
            self.gds[i] = unit

            if first_gd is not None:
                unit.link_from(first_gd) \
                    .link_attrs(first_gd, ("err_output", "err_input"))
            else:
                unit.link_from(*parents) \
                    .link_attrs(self.evaluator, "err_output")
            first_gd = unit

            try_link = {"input", "weights", "bias", "input_offset",
                        "mask", "output"}
            if isinstance(unit, ConvolutionalBase):
                try_link.update(ConvolutionalBase.CONV_ATTRS)
            if isinstance(unit, GDPooling):
                try_link.update(GDPooling.POOL_ATTRS)
            attrs = [a for a in sorted(try_link)
                     if getattr(self.forwards[i], a, None) is not None]
            unit.link_attrs(self.forwards[i], *attrs)
            unit.link_attrs(self.loader, ("batch_size", "minibatch_size"))
            if getattr(unit, "mask", None) is not None or "mask" in attrs:
                unit.link_attrs(self.loader, "minibatch_class")
            unit.gate_skip = self.decision.gd_skip

        for i in units_to_delete:
            del self.gds[i]
        self.gds[0].need_err_input = False
        return first_gd

    # -- evaluator (reference 413-448) --------------------------------------
    def link_evaluator(self, *parents):
        self.evaluator = EvaluatorsRegistry.evaluators[self.loss_function](
            self, name="evaluator", **self.evaluator_config)
        self.evaluator.link_from(*parents) \
            .link_attrs(self.forwards[-1], "output") \
            .link_attrs(self.loader,
                        ("batch_size", "minibatch_size"),
                        ("labels", "minibatch_labels"),
                        ("max_samples_per_epoch", "total_samples"),
                        "class_lengths",
                        ("offset", "minibatch_offset"))
        if self.loss_function == "softmax":
            self.evaluator.link_attrs(self.forwards[-1], "max_idx")
        elif self.loss_function == "mse":
            self.evaluator.link_attrs(
                self.loader, ("target", "minibatch_targets"))
            if getattr(self.loader, "class_targets", None) is not None:
                self.evaluator.link_attrs(self.loader, "class_targets",
                                          ("labels", "minibatch_labels"))
        return self.evaluator

    # -- decision (reference 451-490) ---------------------------------------
    def link_decision(self, *parents):
        self.decision = DecisionsRegistry.decisions[self.decision_name](
            self, name="decision", **self.decision_config)
        self.decision.link_from(*parents) \
            .link_attrs(self.loader, "minibatch_class", "last_minibatch",
                        "minibatch_size", "class_lengths", "epoch_ended",
                        "epoch_number")
        self.decision.link_attrs(self.evaluator,
                                 ("minibatch_n_err", "n_err"))
        if self.decision_name == "decision_gd":
            self.decision.link_attrs(
                self.evaluator,
                ("minibatch_confusion_matrix", "confusion_matrix"),
                ("minibatch_max_err_y_sum", "max_err_output_sum"))
        elif self.decision_name == "decision_mse":
            self.decision.link_attrs(self.loader, "minibatch_offset")
            self.decision.link_attrs(self.evaluator,
                                     ("minibatch_metrics", "metrics"),
                                     ("minibatch_mse", "mse"))
        self.repeater.gate_block = self.decision.complete
        self.real_loader.gate_block = self.decision.complete
        return self.decision

    # -- snapshotter (reference 493-516) ------------------------------------
    def link_snapshotter(self, *parents):
        name = self.snapshotter_name or "nnfile"
        self.snapshotter = SnapshotterRegistry.mapping[name](
            self, name="snapshotter", **self.snapshotter_config)
        self.snapshotter.link_from(*parents) \
            .link_attrs(self.decision, ("suffix", "snapshot_suffix"))
        self.snapshotter.gate_skip = ~self.loader.epoch_ended
        self.snapshotter.skip = ~self.decision.improved
        return self.snapshotter

    def link_loop(self, *parents):
        """Close the training loop back into the repeater."""
        self.repeater.link_from(*parents)
        return self.repeater

    def link_end_point(self, *parents):
        self.end_point.link_from(*parents)
        self.end_point.gate_block = ~self.decision.complete
        return self.end_point

    # -- inference extraction (reference 210-286) ---------------------------
    def extract_forward_workflow(self, loader_name=None, loader_config=None,
                                 loader_factory=None):
        """Build a forward-only workflow with this one's weights copied in
        via the master-slave broadcast protocol
        (reference standard_workflow.py:282-286)."""
        kwargs = dict(layers=self.layers, preprocessing=False)
        if loader_name is not None:
            kwargs["loader_name"] = loader_name
        elif loader_factory is not None:
            kwargs["loader_factory"] = loader_factory
        else:
            kwargs["loader_factory"] = self.loader_factory
        if loader_config is not None:
            kwargs["loader_config"] = loader_config
        fwd_wf = StandardWorkflowBase(None, **kwargs)
        fwd_wf.create_workflow()
        for fwd_exp, fwd_imp in zip(self.forwards, fwd_wf.forwards):
            data = fwd_exp.generate_data_for_slave(None)
            if data is not None:
                fwd_imp.apply_data_from_master(data)
            fwd_imp.forward_mode = True
        return fwd_wf
