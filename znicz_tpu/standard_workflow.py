"""StandardWorkflow — the one-stop training-graph builder.

TPU-era equivalent of reference standard_workflow.py (1201 LoC — SURVEY.md
§2.1).  ``create_workflow`` assembles the canonical train graph::

    repeater -> loader -> forwards[0..n] -> evaluator -> decision
      -> snapshotter -> gds[n..0] -> (loop back to repeater) -> end_point

from the declarative ``layers`` config, pairing each forward with its
registered backward (reference standard_workflow.py:173-208, 289-374).
"""

from znicz_tpu.standard_workflow_base import StandardWorkflowBase
from znicz_tpu.core.snapshotter import SnapshotterRegistry
from znicz_tpu.units.conv import ConvolutionalBase
from znicz_tpu.units.gd_pooling import GDPooling
from znicz_tpu.units.decision import DecisionsRegistry
from znicz_tpu.units.evaluator import EvaluatorsRegistry
# Importing the units package registers every layer type — keep even if
# it looks unused (reference standard_workflow.py:58-60).
import znicz_tpu.units  # noqa: F401


class StandardWorkflow(StandardWorkflowBase):
    """(reference standard_workflow.py:81-1172)"""

    def __init__(self, workflow=None, **kwargs):
        super(StandardWorkflow, self).__init__(workflow, **kwargs)
        self.loss_function = kwargs.get("loss_function", "softmax")
        if self.loss_function not in EvaluatorsRegistry.evaluators:
            raise ValueError("Unknown loss_function %r (known: %s)" % (
                self.loss_function,
                sorted(EvaluatorsRegistry.evaluators)))
        self.decision_name = kwargs.get(
            "decision_name",
            "decision_gd" if self.loss_function == "softmax"
            else "decision_mse")
        self.snapshotter_name = kwargs.get("snapshotter_name", "nnfile")
        self.evaluator_config = self.config2kwargs(
            kwargs.get("evaluator_config"))
        self.decision_config = self.config2kwargs(
            kwargs.get("decision_config"))
        self.snapshotter_config = self.config2kwargs(
            kwargs.get("snapshotter_config"))
        if not self.preprocessing:
            self.create_workflow()

    # -- canonical graph (reference 173-208) --------------------------------
    def create_workflow(self):
        self.link_repeater(self.start_point)
        self.link_loader(self.repeater)
        self.link_forwards(("input", "minibatch_data"), self.loader)
        self.link_evaluator(self.forwards[-1])
        self.link_decision(self.evaluator)
        self.link_snapshotter(self.decision)
        last_gd = self.link_gds(self.snapshotter)
        self.link_loop(last_gd)
        self.link_end_point(last_gd)

    # -- backward chain (reference 289-374) ---------------------------------
    def link_gds(self, *parents):
        if not isinstance(self.layers, (tuple, list)):
            raise ValueError("layers should be a list of dicts")
        self.gds[:] = [None] * len(self.layers)
        first_gd = None
        units_to_delete = []
        for i, layer in reversed(list(enumerate(self.layers))):
            tpe, _, kwargs = self._get_layer_type_kwargs(layer)
            if not isinstance(self.forwards[i], self.layer_map[tpe].forward):
                raise TypeError(
                    "Forward layer %s at position %d is not an instance "
                    "of %s" % (self.forwards[i], i,
                               self.layer_map[tpe].forward))
            try:
                backward_cls = next(self.layer_map[tpe].backwards)
            except StopIteration:
                units_to_delete.append(i)
                continue
            unit = backward_cls(self, **kwargs)
            self.gds[i] = unit

            if first_gd is not None:
                unit.link_from(first_gd) \
                    .link_attrs(first_gd, ("err_output", "err_input"))
            else:
                unit.link_from(*parents) \
                    .link_attrs(self.evaluator, "err_output")
            first_gd = unit

            try_link = {"input", "weights", "bias", "input_offset",
                        "mask", "output"}
            if isinstance(unit, ConvolutionalBase):
                try_link.update(ConvolutionalBase.CONV_ATTRS)
            if isinstance(unit, GDPooling):
                try_link.update(GDPooling.POOL_ATTRS)
            attrs = [a for a in sorted(try_link)
                     if getattr(self.forwards[i], a, None) is not None]
            unit.link_attrs(self.forwards[i], *attrs)
            unit.link_attrs(self.loader, ("batch_size", "minibatch_size"))
            if getattr(unit, "mask", None) is not None or "mask" in attrs:
                unit.link_attrs(self.loader, "minibatch_class")
            unit.gate_skip = self.decision.gd_skip

        for i in units_to_delete:
            del self.gds[i]
        self.gds[0].need_err_input = False
        return first_gd

    # -- evaluator (reference 413-448) --------------------------------------
    def link_evaluator(self, *parents):
        self.evaluator = EvaluatorsRegistry.evaluators[self.loss_function](
            self, name="evaluator", **self.evaluator_config)
        self.evaluator.link_from(*parents) \
            .link_attrs(self.forwards[-1], "output") \
            .link_attrs(self.loader,
                        ("batch_size", "minibatch_size"),
                        ("labels", "minibatch_labels"),
                        ("max_samples_per_epoch", "total_samples"),
                        "class_lengths",
                        ("offset", "minibatch_offset"))
        if self.loss_function == "softmax":
            self.evaluator.link_attrs(self.forwards[-1], "max_idx")
        elif self.loss_function == "mse":
            self.evaluator.link_attrs(
                self.loader, ("target", "minibatch_targets"))
            # linked attrs resolve lazily, so this works for loaders that
            # only fill class_targets inside load_data (the evaluator
            # checks for None again at run time)
            if hasattr(self.loader, "class_targets"):
                self.evaluator.link_attrs(self.loader, "class_targets",
                                          ("labels", "minibatch_labels"))
        return self.evaluator

    # -- decision (reference 451-490) ---------------------------------------
    def link_decision(self, *parents):
        self.decision = DecisionsRegistry.decisions[self.decision_name](
            self, name="decision", **self.decision_config)
        self.decision.link_from(*parents) \
            .link_attrs(self.loader, "minibatch_class", "last_minibatch",
                        "minibatch_size", "class_lengths", "epoch_ended",
                        "epoch_number")
        self.decision.link_attrs(self.evaluator,
                                 ("minibatch_n_err", "n_err"))
        if self.decision_name == "decision_gd":
            self.decision.link_attrs(
                self.evaluator,
                ("minibatch_confusion_matrix", "confusion_matrix"),
                ("minibatch_max_err_y_sum", "max_err_output_sum"))
        elif self.decision_name == "decision_mse":
            self.decision.link_attrs(self.loader, "minibatch_offset")
            self.decision.link_attrs(self.evaluator,
                                     ("minibatch_metrics", "metrics"),
                                     ("minibatch_mse", "mse"))
        self.repeater.gate_block = self.decision.complete
        self.real_loader.gate_block = self.decision.complete
        return self.decision

    # -- snapshotter (reference 493-516) ------------------------------------
    def link_snapshotter(self, *parents):
        name = self.snapshotter_name or "nnfile"
        self.snapshotter = SnapshotterRegistry.mapping[name](
            self, name="snapshotter", **self.snapshotter_config)
        self.snapshotter.link_from(*parents) \
            .link_attrs(self.decision, ("suffix", "snapshot_suffix"))
        self.snapshotter.gate_skip = ~self.loader.epoch_ended
        self.snapshotter.skip = ~self.decision.improved
        return self.snapshotter

    def link_loop(self, *parents):
        """Close the training loop back into the repeater."""
        self.repeater.link_from(*parents)
        return self.repeater

    # -- training amenities (reference 533-600, 573-591) --------------------
    def link_lr_adjuster(self, *parents, **kwargs):
        """Per-iteration LR schedules on every GD unit
        (reference standard_workflow.py:573-591)."""
        from znicz_tpu.units.lr_adjust import LearningRateAdjust
        cfg = self.config2kwargs(kwargs.pop("lr_adjuster_config", None)) \
            or kwargs
        self.lr_adjuster = LearningRateAdjust(
            self, name="lr_adjuster", **cfg)
        for gd in self.gds:
            self.lr_adjuster.add_gd_unit(gd)
        self.lr_adjuster.link_from(*parents)
        return self.lr_adjuster

    def link_rollback(self, *parents, **kwargs):
        """Divergence recovery (reference standard_workflow.py:594-600)."""
        from znicz_tpu.units.nn_rollback import NNRollback
        self.rollback = NNRollback(self, name="rollback", **kwargs)
        self.rollback.link_from(*parents)
        self.rollback.link_attrs(self.decision, "improved")
        self.rollback.gate_skip = ~self.loader.epoch_ended
        for gd in self.gds:
            self.rollback.add_gd(gd)
        return self.rollback

    def link_image_saver(self, *parents, **kwargs):
        """Dump misclassified samples, gated on improvement
        (reference standard_workflow.py:533-569)."""
        from znicz_tpu.units.image_saver import ImageSaver
        self.image_saver = ImageSaver(self, name="image_saver", **kwargs)
        self.image_saver.link_from(*parents)
        self.image_saver.link_attrs(self.forwards[-1], "output")
        if self.loss_function == "softmax":
            self.image_saver.link_attrs(self.forwards[-1], "max_idx")
        self.image_saver.link_attrs(
            self.loader,
            ("input", "minibatch_data"),
            ("indices", "minibatch_indices"),
            ("labels", "minibatch_labels"),
            "minibatch_class", "minibatch_size", "epoch_number")
        self.image_saver.gate_skip = ~self.decision.improved
        return self.image_saver

    def link_error_plotter(self, *parents):
        """Per-epoch error curve (reference standard_workflow.py:672-700)."""
        from znicz_tpu.core.plotting_units import AccumulatingPlotter
        self.error_plotter = []
        prev = parents
        for i in (1, 2):  # validation, train
            p = AccumulatingPlotter(self, name="error_%d" % i,
                                    input_field=i)
            p.input = self.decision.epoch_n_err_pt
            p.link_from(*prev)
            p.gate_skip = ~self.decision.epoch_ended
            self.error_plotter.append(p)
            prev = (p,)
        return self.error_plotter[-1]

    def link_weights_plotter(self, *parents, **kwargs):
        """Weight-image grids per layer
        (reference standard_workflow.py:853-891)."""
        from znicz_tpu.units.nn_plotting_units import Weights2D
        limit = kwargs.get("limit", 64)
        self.weights_plotter = []
        prev = parents
        for i, fwd in enumerate(self.forwards):
            # weight Arrays are still empty at link time; Weights2D.fill
            # skips empty arrays at run time (weightless units stay empty)
            if getattr(fwd, "weights", None) is None:
                continue
            p = Weights2D(self, name="weights_%d" % i, limit=limit)
            p.input = fwd.weights
            p.link_from(*prev)
            p.gate_skip = ~self.decision.epoch_ended
            self.weights_plotter.append(p)
            prev = (p,)
        return self.weights_plotter[-1] if self.weights_plotter \
            else parents[0]

    def link_conf_matrix_plotter(self, *parents):
        """(reference standard_workflow.py:723-743)"""
        from znicz_tpu.core.plotting_units import MatrixPlotter
        self.conf_matrix_plotter = MatrixPlotter(
            self, name="conf_matrix")
        self.conf_matrix_plotter.input = self.evaluator.confusion_matrix
        self.conf_matrix_plotter.link_from(*parents)
        self.conf_matrix_plotter.gate_skip = ~self.decision.epoch_ended
        return self.conf_matrix_plotter

    def link_mse_plotter(self, *parents):
        """(reference standard_workflow.py:702-721)"""
        from znicz_tpu.units.nn_plotting_units import MSEHistogram
        self.mse_plotter = MSEHistogram(self, name="mse_histogram")
        self.mse_plotter.link_attrs(self.evaluator, "mse")
        self.mse_plotter.link_from(*parents)
        self.mse_plotter.gate_skip = ~self.decision.epoch_ended
        return self.mse_plotter

    def link_end_point(self, *parents):
        self.end_point.link_from(*parents)
        self.end_point.gate_block = ~self.decision.complete
        return self.end_point

    # -- inference extraction (reference 210-286) ---------------------------
    def extract_forward_workflow(self, loader_name=None, loader_config=None,
                                 loader_factory=None):
        """Build a forward-only workflow with this one's weights copied in
        via the master-slave broadcast protocol
        (reference standard_workflow.py:282-286)."""
        kwargs = dict(layers=self.layers, preprocessing=False)
        if loader_name is not None:
            kwargs["loader_name"] = loader_name
        elif loader_factory is not None:
            kwargs["loader_factory"] = loader_factory
        else:
            kwargs["loader_factory"] = self.loader_factory
        if loader_config is not None:
            kwargs["loader_config"] = loader_config
        fwd_wf = StandardWorkflowBase(None, **kwargs)
        fwd_wf.create_workflow()
        for fwd_exp, fwd_imp in zip(self.forwards, fwd_wf.forwards):
            data = fwd_exp.generate_data_for_slave(None)
            if data is not None:
                fwd_imp.apply_data_from_master(data)
            fwd_imp.forward_mode = True
        return fwd_wf
