"""StandardWorkflow — the one-stop training-graph builder.

TPU-era equivalent of reference standard_workflow.py (1201 LoC — SURVEY.md
§2.1).  ``create_workflow`` assembles the canonical train graph::

    repeater -> loader -> forwards[0..n] -> evaluator -> decision
      -> snapshotter -> gds[n..0] -> (loop back to repeater) -> end_point

from the declarative ``layers`` config, pairing each forward with its
registered backward (reference standard_workflow.py:173-208, 289-374).
"""

from znicz_tpu.standard_workflow_base import StandardWorkflowBase
from znicz_tpu.core.snapshotter import SnapshotterRegistry
from znicz_tpu.units.conv import ConvolutionalBase
from znicz_tpu.units.gd_pooling import GDPooling
from znicz_tpu.units.decision import DecisionsRegistry
from znicz_tpu.units.evaluator import EvaluatorsRegistry
# Importing the units package registers every layer type — keep even if
# it looks unused (reference standard_workflow.py:58-60).
import znicz_tpu.units  # noqa: F401


class StandardWorkflow(StandardWorkflowBase):
    """(reference standard_workflow.py:81-1172)"""

    def __init__(self, workflow=None, **kwargs):
        super(StandardWorkflow, self).__init__(workflow, **kwargs)
        self.loss_function = kwargs.get("loss_function", "softmax")
        if self.loss_function not in EvaluatorsRegistry.evaluators:
            raise ValueError("Unknown loss_function %r (known: %s)" % (
                self.loss_function,
                sorted(EvaluatorsRegistry.evaluators)))
        self.decision_name = kwargs.get(
            "decision_name",
            "decision_gd" if self.loss_function == "softmax"
            else "decision_mse")
        self.snapshotter_name = kwargs.get("snapshotter_name", "nnfile")
        self.evaluator_config = self.config2kwargs(
            kwargs.get("evaluator_config"))
        self.decision_config = self.config2kwargs(
            kwargs.get("decision_config"))
        self.snapshotter_config = self.config2kwargs(
            kwargs.get("snapshotter_config"))
        if not self.preprocessing:
            self.create_workflow()

    # -- canonical graph (reference 173-208) --------------------------------
    def create_workflow(self):
        if self.fused_config is not None:
            return self.create_fused_workflow()
        self.link_repeater(self.start_point)
        self.link_loader(self.repeater)
        self.link_forwards(("input", "minibatch_data"), self.loader)
        self.link_evaluator(self.forwards[-1])
        self.link_decision(self.evaluator)
        self.link_snapshotter(self.decision)
        last_gd = self.link_gds(self.snapshotter)
        self.link_loop(last_gd)
        self.link_end_point(last_gd)

    def create_fused_workflow(self):
        """The same control-plane graph with the forwards+gds chain
        collapsed into one compiled SPMD train-step unit (SURVEY.md §7
        design stance: unit graph = epoch-level control plane around the
        jitted step)."""
        self.link_repeater(self.start_point)
        self.link_loader(self.repeater)
        self.link_fused_trainer(self.loader)
        self.link_evaluator(self.fused_trainer)
        self.link_decision(self.evaluator)
        self.link_snapshotter(self.decision)
        self.link_loop(self.snapshotter)
        self.link_end_point(self.snapshotter)

    def link_fused_trainer(self, *parents):
        """Create the fused train-step unit from the ``layers`` config
        (fused twin of link_forwards + link_gds).  ``fused_config`` keys:
        ``mesh`` (a jax Mesh, or an int device count),
        ``model_parallel`` (with an int mesh), ``compute_dtype``,
        ``dtype``, ``dropout_seed``, ``defaults``."""
        from znicz_tpu.units.fused_trainer import FusedForwardBackward
        cfg = dict(self.fused_config or {})
        mesh = cfg.pop("mesh", None)
        if mesh == "hybrid":
            # all processes' devices, model axis inside one host's ICI
            # domain (multi-host SPMD; launcher calls
            # multihost.initialize() from env before this)
            from znicz_tpu.parallel import multihost
            mesh = multihost.make_hybrid_mesh(
                model_parallel=cfg.pop("model_parallel", 1))
        elif isinstance(mesh, int):
            from znicz_tpu.parallel import make_mesh
            mesh = make_mesh(mesh,
                             model_parallel=cfg.pop("model_parallel", 1))
        cfg.setdefault("loss", self.loss_function)
        self.fused_trainer = FusedForwardBackward(
            self, name="fused_trainer", layers=self.layers, mesh=mesh,
            **cfg)
        self.fused_trainer.link_from(*parents)
        self.fused_trainer.link_attrs(
            self.loader, ("input", "minibatch_data"),
            "minibatch_class", "minibatch_size")
        if self.loss_function == "mse":
            self.fused_trainer.link_attrs(
                self.loader, ("target", "minibatch_targets"))
        else:
            self.fused_trainer.link_attrs(
                self.loader, ("labels", "minibatch_labels"))
        self.fused_trainer.label_source = self.real_loader
        # window collection drives the loader directly (scan windows —
        # the compiled hot loop batches K TRAIN minibatches per dispatch)
        self.fused_trainer.loader_unit = self.loader
        # the trainer IS the forward chain for downstream linkers
        # (link_evaluator/link_image_saver read forwards[-1])
        self.forwards[:] = [self.fused_trainer]
        return self.fused_trainer

    # -- backward chain (reference 289-374) ---------------------------------
    def link_gds(self, *parents):
        if not isinstance(self.layers, (tuple, list)):
            raise ValueError("layers should be a list of dicts")
        self.gds[:] = [None] * len(self.layers)
        first_gd = None
        units_to_delete = []
        for i, layer in reversed(list(enumerate(self.layers))):
            tpe, _, kwargs = self._get_layer_type_kwargs(layer, i)
            if not isinstance(self.forwards[i], self.layer_map[tpe].forward):
                raise TypeError(
                    "Forward layer %s at position %d is not an instance "
                    "of %s" % (self.forwards[i], i,
                               self.layer_map[tpe].forward))
            try:
                backward_cls = next(self.layer_map[tpe].backwards)
            except StopIteration:
                units_to_delete.append(i)
                continue
            unit = backward_cls(self, **kwargs)
            self.gds[i] = unit
            if hasattr(unit, "bind_forward"):
                # pairs sharing structured parameters (e.g. the scan
                # LSTM's gate pytree) take the forward directly instead
                # of linking singular weights/bias Arrays
                unit.bind_forward(self.forwards[i])

            if first_gd is not None:
                unit.link_from(first_gd) \
                    .link_attrs(first_gd, ("err_output", "err_input"))
            else:
                unit.link_from(*parents) \
                    .link_attrs(self.evaluator, "err_output")
            first_gd = unit

            try_link = {"input", "weights", "bias", "input_offset",
                        "mask", "output"}
            if isinstance(unit, ConvolutionalBase):
                try_link.update(ConvolutionalBase.CONV_ATTRS)
            if isinstance(unit, GDPooling):
                try_link.update(GDPooling.POOL_ATTRS)
            attrs = [a for a in sorted(try_link)
                     if getattr(self.forwards[i], a, None) is not None]
            unit.link_attrs(self.forwards[i], *attrs)
            unit.link_attrs(self.loader, ("batch_size", "minibatch_size"))
            if getattr(unit, "mask", None) is not None or "mask" in attrs:
                unit.link_attrs(self.loader, "minibatch_class")
            unit.gate_skip = self.decision.gd_skip

        for i in units_to_delete:
            del self.gds[i]
        self.gds[0].need_err_input = False
        return first_gd

    # -- evaluator (reference 413-448) --------------------------------------
    def link_evaluator(self, *parents):
        self.evaluator = EvaluatorsRegistry.evaluators[self.loss_function](
            self, name="evaluator", **self.evaluator_config)
        self.evaluator.link_from(*parents) \
            .link_attrs(self.forwards[-1], "output") \
            .link_attrs(self.loader,
                        ("batch_size", "minibatch_size"),
                        ("labels", "minibatch_labels"),
                        ("max_samples_per_epoch", "total_samples"),
                        "class_lengths",
                        ("offset", "minibatch_offset"))
        if self.loss_function == "softmax":
            self.evaluator.link_attrs(self.forwards[-1], "max_idx")
            if self.fused_trainer is not None:
                # windowed TRAIN dispatches hand the evaluator their
                # in-scan aggregated stats (the output buffer holds only
                # the window's LAST minibatch)
                self.evaluator.stats_source = self.fused_trainer
                self.fused_trainer.stats_mean = self.evaluator.mean
        elif self.loss_function == "mse":
            self.evaluator.link_attrs(
                self.loader, ("target", "minibatch_targets"))
            # linked attrs resolve lazily, so this works for loaders that
            # only fill class_targets inside load_data (the evaluator
            # checks for None again at run time)
            if hasattr(self.loader, "class_targets"):
                self.evaluator.link_attrs(self.loader, "class_targets",
                                          ("labels", "minibatch_labels"))
            if self.fused_trainer is not None:
                # windowed MSE TRAIN dispatches hand the evaluator
                # their in-scan [sum,max,min] metrics (+ class-target
                # n_err); mirror the evaluator's flags into the scan
                self.evaluator.stats_source = self.fused_trainer
                self.fused_trainer.stats_mean = self.evaluator.mean
                self.fused_trainer.stats_root = self.evaluator.root
        return self.evaluator

    # -- decision (reference 451-490) ---------------------------------------
    def link_decision(self, *parents):
        self.decision = DecisionsRegistry.decisions[self.decision_name](
            self, name="decision", **self.decision_config)
        self.decision.link_from(*parents) \
            .link_attrs(self.loader, "minibatch_class", "last_minibatch",
                        "minibatch_size", "class_lengths", "epoch_ended",
                        "epoch_number")
        self.decision.link_attrs(self.evaluator,
                                 ("minibatch_n_err", "n_err"))
        if self.decision_name == "decision_gd":
            self.decision.link_attrs(
                self.evaluator,
                ("minibatch_confusion_matrix", "confusion_matrix"),
                ("minibatch_max_err_y_sum", "max_err_output_sum"))
        elif self.decision_name == "decision_mse":
            self.decision.link_attrs(self.loader, "minibatch_offset")
            self.decision.link_attrs(self.evaluator,
                                     ("minibatch_metrics", "metrics"),
                                     ("minibatch_mse", "mse"))
        self.repeater.gate_block = self.decision.complete
        self.real_loader.gate_block = self.decision.complete
        return self.decision

    # -- snapshotter (reference 493-516) ------------------------------------
    def link_snapshotter(self, *parents):
        name = self.snapshotter_name or "nnfile"
        self.snapshotter = SnapshotterRegistry.mapping[name](
            self, name="snapshotter", **self.snapshotter_config)
        self.snapshotter.link_from(*parents) \
            .link_attrs(self.decision, ("suffix", "snapshot_suffix"))
        self.snapshotter.gate_skip = ~self.loader.epoch_ended
        self.snapshotter.skip = ~self.decision.improved
        return self.snapshotter

    def link_loop(self, *parents):
        """Close the training loop back into the repeater."""
        self.repeater.link_from(*parents)
        return self.repeater

    # -- training amenities (reference 533-600, 573-591) --------------------
    def link_lr_adjuster(self, *parents, **kwargs):
        """Per-iteration LR schedules on every GD unit
        (reference standard_workflow.py:573-591)."""
        from znicz_tpu.units.lr_adjust import LearningRateAdjust
        cfg = self.config2kwargs(kwargs.pop("lr_adjuster_config", None)) \
            or kwargs
        self.lr_adjuster = LearningRateAdjust(
            self, name="lr_adjuster", **cfg)
        if self.fused_trainer is not None:
            # fused mode: the proxies carry the hyperparameter surface;
            # the schedule's new LR reaches the jitted step as a traced
            # argument (no recompile).  The adjuster fires between the
            # loader and the train step — the unit graph runs it before
            # the GD updates of the SAME minibatch (snapshotter ->
            # adjuster -> gds), so update k must use policy(k), not
            # policy(k-1); ``parents`` are ignored for this insertion.
            for proxy in self.fused_trainer.gd_proxies:
                self.lr_adjuster.add_gd_unit(proxy)
            self.lr_adjuster.train_gate_loader = self.loader
            self.fused_trainer.unlink_from(self.loader)
            self.lr_adjuster.link_from(self.loader)
            self.fused_trainer.link_from(self.lr_adjuster)
            # window collection ticks the schedule per collected
            # minibatch, so policy(k) reaches step k INSIDE the window
            self.fused_trainer.hyper_tick = self.lr_adjuster.run
            return self.lr_adjuster
        for gd in self.gds:
            self.lr_adjuster.add_gd_unit(gd)
        self.lr_adjuster.link_from(*parents)
        return self.lr_adjuster

    def link_rollback(self, *parents, **kwargs):
        """Divergence recovery (reference standard_workflow.py:594-600)."""
        if self.fused_trainer is not None:
            from znicz_tpu.units.fused_trainer import FusedNNRollback
            self.rollback = FusedNNRollback(
                self, name="rollback", trainer=self.fused_trainer,
                **kwargs)
            self.rollback.link_from(*parents)
            self.rollback.link_attrs(self.decision, "improved")
            self.rollback.gate_skip = ~self.loader.epoch_ended
            return self.rollback
        from znicz_tpu.units.nn_rollback import NNRollback
        self.rollback = NNRollback(self, name="rollback", **kwargs)
        self.rollback.link_from(*parents)
        self.rollback.link_attrs(self.decision, "improved")
        self.rollback.gate_skip = ~self.loader.epoch_ended
        for gd in self.gds:
            self.rollback.add_gd(gd)
        return self.rollback

    def link_image_saver(self, *parents, **kwargs):
        """Dump misclassified samples, gated on improvement
        (reference standard_workflow.py:533-569)."""
        from znicz_tpu.units.image_saver import ImageSaver
        self.image_saver = ImageSaver(self, name="image_saver", **kwargs)
        self.image_saver.link_from(*parents)
        self.image_saver.link_attrs(self.forwards[-1], "output")
        if self.loss_function == "softmax":
            self.image_saver.link_attrs(self.forwards[-1], "max_idx")
        self.image_saver.link_attrs(
            self.loader,
            ("input", "minibatch_data"),
            ("indices", "minibatch_indices"),
            ("labels", "minibatch_labels"),
            "minibatch_class", "minibatch_size", "epoch_number")
        self.image_saver.gate_skip = ~self.decision.improved
        return self.image_saver

    def link_error_plotter(self, *parents):
        """Per-epoch error curve (reference standard_workflow.py:672-700)."""
        from znicz_tpu.core.plotting_units import AccumulatingPlotter
        self.error_plotter = []
        prev = parents
        for i in (1, 2):  # validation, train
            p = AccumulatingPlotter(self, name="error_%d" % i,
                                    input_field=i)
            p.input = self.decision.epoch_n_err_pt
            p.link_from(*prev)
            p.gate_skip = ~self.decision.epoch_ended
            self.error_plotter.append(p)
            prev = (p,)
        return self.error_plotter[-1]

    def _plottable_weight_sources(self):
        """[(index, weights Array)] across both execution modes — the
        unit graph's forward units or the fused trainer's device-backed
        weight views (created at construction, populated at
        initialize; Weights2D.fill skips empty Arrays at run time)."""
        if self.fused_trainer is not None:
            return list(self.fused_trainer.weight_views)
        out = []
        for i, fwd in enumerate(self.forwards):
            if getattr(fwd, "weights", None) is not None:
                out.append((i, fwd.weights))
        return out

    def link_weights_plotter(self, *parents, **kwargs):
        """Weight-image grids per layer
        (reference standard_workflow.py:853-891); works in fused mode
        through the trainer's weight views."""
        from znicz_tpu.units.nn_plotting_units import Weights2D
        limit = kwargs.get("limit", 64)
        self.weights_plotter = []
        prev = parents
        for i, weights in self._plottable_weight_sources():
            p = Weights2D(self, name="weights_%d" % i, limit=limit)
            p.input = weights
            p.link_from(*prev)
            p.gate_skip = ~self.decision.epoch_ended
            self.weights_plotter.append(p)
            prev = (p,)
        return self.weights_plotter[-1] if self.weights_plotter \
            else parents[0]

    def link_conf_matrix_plotter(self, *parents):
        """(reference standard_workflow.py:723-743)"""
        from znicz_tpu.core.plotting_units import MatrixPlotter
        self.conf_matrix_plotter = MatrixPlotter(
            self, name="conf_matrix")
        self.conf_matrix_plotter.input = self.evaluator.confusion_matrix
        self.conf_matrix_plotter.link_from(*parents)
        self.conf_matrix_plotter.gate_skip = ~self.decision.epoch_ended
        return self.conf_matrix_plotter

    def link_mse_plotter(self, *parents):
        """(reference standard_workflow.py:702-721)"""
        from znicz_tpu.units.nn_plotting_units import MSEHistogram
        self.mse_plotter = MSEHistogram(self, name="mse_histogram")
        self.mse_plotter.link_attrs(self.evaluator, "mse")
        self.mse_plotter.link_from(*parents)
        self.mse_plotter.gate_skip = ~self.decision.epoch_ended
        return self.mse_plotter

    def link_err_y_plotter(self, *parents):
        """Last-layer max gradient sum curve
        (reference standard_workflow.py:738-771)."""
        from znicz_tpu.core.plotting_units import AccumulatingPlotter
        self.err_y_plotters = []
        prev = parents
        for i in (1, 2):  # validation, train
            p = AccumulatingPlotter(
                self, name="err_y_%d" % i, input_field=i)
            p.input = self.decision.max_err_y_sums
            p.link_from(*prev)
            p.gate_skip = ~self.decision.epoch_ended
            self.err_y_plotters.append(p)
            prev = (p,)
        return self.err_y_plotters[-1]

    def link_multi_hist_plotter(self, *parents, **kwargs):
        """Per-layer weight histograms
        (reference standard_workflow.py:773-816)."""
        from znicz_tpu.core.plotting_units import MultiHistogram
        weights_input = kwargs.get("weights_input", "weights")
        self.multi_hist_plotter = []
        prev = parents
        if weights_input == "weights":
            sources = self._plottable_weight_sources()
        else:
            sources = [(i, getattr(fwd, weights_input))
                       for i, fwd in enumerate(self.forwards)
                       if getattr(fwd, weights_input, None) is not None]
        for i, arr in sources:
            p = MultiHistogram(self, name="hist_%d" % i,
                               hist_number=kwargs.get("hist_number", 16),
                               n_bars=kwargs.get("n_bars", 25))
            p.input = arr
            p.link_from(*prev)
            p.gate_skip = ~self.decision.epoch_ended
            self.multi_hist_plotter.append(p)
            prev = (p,)
        return self.multi_hist_plotter[-1] if self.multi_hist_plotter \
            else parents[0]

    def link_similar_weights_plotter(self, *parents, **kwargs):
        """Weight-diversity grids (reference standard_workflow.py:874-931,
        znicz diversity.SimilarWeights2D)."""
        from znicz_tpu.units.diversity import SimilarWeights2D
        weights_input = kwargs.pop("weights_input", "weights")
        self.similar_weights_plotter = []
        prev = parents
        for i, fwd in enumerate(self.forwards):
            if getattr(fwd, weights_input, None) is None:
                continue
            # non-square weight rows are skipped at RUN time by
            # SimilarWeights2D.fill (shapes are unknown at link time)
            p = SimilarWeights2D(self, name="similar_%d" % i, **kwargs)
            p.input = getattr(fwd, weights_input)
            p.link_from(*prev)
            p.gate_skip = ~self.decision.epoch_ended
            self.similar_weights_plotter.append(p)
            prev = (p,)
        return self.similar_weights_plotter[-1] \
            if self.similar_weights_plotter else parents[0]

    def link_table_plotter(self, *parents):
        """Max/min table over weights and gradients
        (reference standard_workflow.py:934-969)."""
        from znicz_tpu.core.plotting_units import TableMaxMin
        self.table_plotter = TableMaxMin(self, name="table")
        for i, fwd in enumerate(self.forwards):
            if getattr(fwd, "weights", None) is None:
                continue
            self.table_plotter.y.append(fwd.weights)
            self.table_plotter.col_labels.append("weights_%d" % i)
        for i, g in enumerate(self.gds):
            if g is None or getattr(g, "gradient_weights", None) is None:
                continue
            self.table_plotter.y.append(g.gradient_weights)
            self.table_plotter.col_labels.append("gd_%d" % i)
        self.table_plotter.link_from(*parents)
        self.table_plotter.gate_skip = ~self.decision.epoch_ended
        return self.table_plotter

    def link_min_max_plotter(self, is_min, *parents):
        """Epoch-metric extremum curve
        (reference standard_workflow.py:1004-1042)."""
        from znicz_tpu.core.plotting_units import AccumulatingPlotter
        p = AccumulatingPlotter(
            self, name="mse_min" if is_min else "mse_max",
            input_field=2, input_offset=2 if is_min else 1)
        p.input = self.decision.epoch_metrics
        p.link_from(*parents)
        p.gate_skip = ~self.decision.epoch_ended
        if is_min:
            self.min_plotter = p
        else:
            self.max_plotter = p
        return p

    def link_image_plotter(self, *parents):
        """Output vs input sample images
        (reference standard_workflow.py:1044-1066)."""
        from znicz_tpu.core.plotting_units import ImagePlotter
        self.image_plotter = ImagePlotter(self, name="output_sample")
        self.image_plotter.inputs.append(self.forwards[-1].output)
        self.image_plotter.input_fields.append(0)
        self.image_plotter.inputs.append(self.forwards[0].input)
        self.image_plotter.input_fields.append(0)
        self.image_plotter.link_from(*parents)
        self.image_plotter.gate_skip = ~self.decision.epoch_ended
        return self.image_plotter

    def link_immediate_plotter(self, *parents):
        """Data / target / output curves
        (reference standard_workflow.py:1068-1101)."""
        from znicz_tpu.core.plotting_units import ImmediatePlotter
        self.immediate_plotter = ImmediatePlotter(
            self, name="immediate")
        del self.immediate_plotter.inputs[:]
        del self.immediate_plotter.input_fields[:]
        for src in (self.loader.minibatch_data,
                    getattr(self.loader, "minibatch_targets", None),
                    self.forwards[-1].output):
            if src is None:
                continue
            self.immediate_plotter.inputs.append(src)
            self.immediate_plotter.input_fields.append(0)
        self.immediate_plotter.link_from(*parents)
        self.immediate_plotter.gate_skip = ~self.decision.epoch_ended
        return self.immediate_plotter

    # -- aux-service linkers (reference 386-411, 648-670, 1121-1149) --------
    def link_avatar(self, *extra_attrs):
        """Replace the just-linked loader with its prefetching Avatar so
        host-side loading overlaps device compute.  Call right after
        link_loader, BEFORE anything links against the loader (same
        constraint as the reference, standard_workflow.py:386-404)."""
        from znicz_tpu.core.avatar import Avatar
        real = self.loader
        avatar = Avatar(self, loader=real, extra_attrs=tuple(extra_attrs),
                        name="avatar")
        parents = list(real.links_from)
        real.unlink_all()  # the producer thread drives the real loader
        # and remove it from the unit container: the snapshotter must not
        # pickle loader state the producer thread is mutating (and which
        # runs AHEAD of the consumed stream).  Trade-off vs the plain
        # loader: snapshots of avatar workflows restart the data stream
        # at an epoch boundary instead of the exact minibatch position.
        self.del_ref(real)
        if parents:
            avatar.link_from(*parents)
        self.real_loader = real
        self.loader = avatar
        return avatar

    def link_meandispnorm(self, *parents):
        """On-the-fly minibatch normalization from the loader's
        mean/rdisp arrays (reference standard_workflow.py:603-624);
        wire the forwards from its ("input", "output")."""
        from znicz_tpu.units.mean_disp_normalizer import \
            MeanDispNormalizer
        self.meandispnorm = MeanDispNormalizer(self, name="meandispnorm")
        self.meandispnorm.link_attrs(
            self.loader, ("input", "minibatch_data"), "mean", "rdisp")
        self.meandispnorm.link_from(*parents)
        return self.meandispnorm

    def link_gd_diff_stats(self, *parents, **kwargs):
        """Gradient-statistics probe over the backward chain
        (reference standard_workflow.py:626-646).  The history is
        flushed to ``file_name`` when the workflow finishes."""
        from znicz_tpu.units.diff_stats import DiffStats
        kwargs.setdefault("arrays",
                          {u: ("gradient_weights",)
                           for u in self.gds if u is not None})
        self.gd_diff_stats = DiffStats(self, name="gd_diff_stats",
                                       **kwargs)
        self.gd_diff_stats.link_from(*parents)
        self.gd_diff_stats.gate_skip = self.decision.gd_skip
        self.on_workflow_finished(self.gd_diff_stats.flush)
        return self.gd_diff_stats

    def link_downloader(self, *parents, **kwargs):
        """(reference standard_workflow.py:407-411)"""
        from znicz_tpu.core.downloader import Downloader
        self.downloader = Downloader(self, name="downloader", **kwargs)
        self.downloader.link_from(*parents)
        return self.downloader

    def link_ipython(self, *parents):
        """Between-epochs interactive shell
        (reference standard_workflow.py:648-661)."""
        from znicz_tpu.core.interaction import Shell
        self.ipython = Shell(self, name="shell")
        self.ipython.link_from(*parents)
        self.ipython.gate_skip = ~self.decision.epoch_ended
        return self.ipython

    def link_publisher(self, *parents, **kwargs):
        """End-of-training report (reference standard_workflow.py:663-670)."""
        from znicz_tpu.core.publishing import Publisher
        self.publisher = Publisher(self, name="publisher", **kwargs)
        self.publisher.link_from(*parents)
        self.publisher.result_providers.add(self.decision)
        self.publisher.loader_unit = getattr(self, "real_loader",
                                             self.loader)
        self.publisher.gate_skip = ~self.decision.complete
        return self.publisher

    def link_data_saver(self, *parents, **kwargs):
        """Record the observed minibatch stream
        (reference standard_workflow.py:1121-1149)."""
        from znicz_tpu.loader.saver import MinibatchesSaver
        self.data_saver = MinibatchesSaver(self, name="data_saver",
                                           **kwargs)
        self.data_saver.link_attrs(
            self.loader, "minibatch_data", "minibatch_labels",
            "minibatch_class", "minibatch_size", "class_lengths",
            "max_minibatch_size", "has_labels", "epoch_ended")
        self.data_saver.link_from(*parents)
        return self.data_saver

    def link_end_point(self, *parents):
        self.end_point.link_from(*parents)
        self.end_point.gate_block = ~self.decision.complete
        return self.end_point

    # -- inference extraction (reference 210-286) ---------------------------
    def extract_forward_workflow(self, loader_name=None, loader_config=None,
                                 loader_factory=None):
        """Build a forward-only workflow with this one's weights copied in
        via the master-slave broadcast protocol
        (reference standard_workflow.py:282-286)."""
        kwargs = dict(layers=self.layers, preprocessing=False)
        if loader_name is not None:
            kwargs["loader_name"] = loader_name
        elif loader_factory is not None:
            kwargs["loader_factory"] = loader_factory
        else:
            kwargs["loader_factory"] = self.loader_factory
        if loader_config is not None:
            kwargs["loader_config"] = loader_config
        fwd_wf = StandardWorkflowBase(None, **kwargs)
        fwd_wf.create_workflow()
        if self.fused_trainer is not None:
            # fused params map 1:1 onto the layer list — inject through
            # the same master->slave broadcast entry point
            params = self.fused_trainer.host_params()
            for fwd_imp, p in zip(fwd_wf.forwards, params):
                if p:
                    fwd_imp.apply_data_from_master(
                        [p.get("w"), p.get("b")])
                fwd_imp.forward_mode = True
            return fwd_wf
        for fwd_exp, fwd_imp in zip(self.forwards, fwd_wf.forwards):
            data = fwd_exp.generate_data_for_slave(None)
            if data is not None:
                fwd_imp.apply_data_from_master(data)
            fwd_imp.forward_mode = True
        return fwd_wf
