"""Approximator sample — MLP regression via the MSE pipeline.

Parity target: reference tests/research/Approximator (approximator.py +
approximator_config.py — all2all_tanh stack trained with EvaluatorMSE /
DecisionMSE on per-sample targets; published baseline MSE 12.81,
BASELINE.md).  The reference reads measurement ``.dat`` files; this sample
reads ``dataset_file``/``targets_file`` .npy pairs when present and
otherwise synthesizes a smooth nonlinear map (zero-egress box), keeping
the same loader contract (FullBatchLoaderMSE).
"""

import os

import numpy

from znicz_tpu.core.config import root
from znicz_tpu.loader.base import (
    FullBatchLoaderMSE, IFullBatchLoader, TEST, VALID, TRAIN)
from znicz_tpu.standard_workflow import StandardWorkflow


class ApproximatorLoader(FullBatchLoaderMSE, IFullBatchLoader):
    """Full-batch (data, target) pairs; TRAIN + VALID split."""

    MAPPING = "approximator_loader"

    #: synthetic-set geometry (used when no dataset files exist)
    SYNTH_TRAIN = 600
    SYNTH_VALID = 200
    N_IN = 10
    N_OUT = 3

    def __init__(self, workflow, **kwargs):
        kwargs.setdefault("normalization_type", "mean_disp")
        kwargs.setdefault("targets_normalization_type", "mean_disp")
        super(ApproximatorLoader, self).__init__(workflow, **kwargs)
        self.dataset_file = kwargs.get("dataset_file", os.path.join(
            root.common.dirs.datasets, "approximator", "data.npy"))
        self.targets_file = kwargs.get("targets_file", os.path.join(
            root.common.dirs.datasets, "approximator", "targets.npy"))

    def _synthesize(self):
        """Smooth nonlinear R^10 -> R^3 map, deterministic."""
        n = self.SYNTH_TRAIN + self.SYNTH_VALID
        r = numpy.random.RandomState(0xA112)
        x = r.uniform(-1.0, 1.0, (n, self.N_IN)).astype(numpy.float32)
        w = r.uniform(-1.0, 1.0, (self.N_IN, self.N_OUT))
        y = numpy.stack([
            numpy.sin(x @ w[:, 0]),
            numpy.cos(x @ w[:, 1]) * (x @ w[:, 2]),
            numpy.tanh(2.0 * x @ w[:, 2]),
        ], axis=1).astype(numpy.float32)
        return x, y

    def load_data(self):
        if os.path.exists(self.dataset_file) and \
                os.path.exists(self.targets_file):
            x = numpy.load(self.dataset_file).astype(numpy.float32)
            y = numpy.load(self.targets_file).astype(numpy.float32)
            if x.shape[0] != y.shape[0]:
                raise ValueError(
                    "%s has %d samples but %s has %d targets"
                    % (self.dataset_file, x.shape[0],
                       self.targets_file, y.shape[0]))
            n_valid = max(1, x.shape[0] // 4)
        else:
            x, y = self._synthesize()
            n_valid = self.SYNTH_VALID
        # dataset layout [TEST | VALID | TRAIN] (Loader.class_index_range)
        self.original_data.mem = numpy.ascontiguousarray(x)
        self.original_targets.mem = numpy.ascontiguousarray(y)
        self.class_lengths[TEST] = 0
        self.class_lengths[VALID] = n_valid
        self.class_lengths[TRAIN] = x.shape[0] - n_valid


root.approximator.update({
    "decision": {"fail_iterations": 20, "max_epochs": 75},
    "snapshotter": {"prefix": "approximator", "interval": 1,
                    "time_interval": 0, "compression": ""},
    "loss_function": "mse",
    "loader_name": "approximator_loader",
    "loader": {"minibatch_size": 100},
    "layers": [
        {"name": "fc_tanh1", "type": "all2all_tanh",
         "->": {"output_sample_shape": 81,
                "weights_filling": "uniform", "weights_stddev": 0.05,
                "bias_filling": "uniform", "bias_stddev": 0.05},
         "<-": {"learning_rate": 0.02, "weights_decay": 0.0,
                "gradient_moment": 0.9}},
        # output width auto-set from the loader's target shape
        # (standard_workflow_base.link_forwards MSE branch)
        {"name": "fc_out", "type": "all2all_tanh",
         "->": {"weights_filling": "uniform", "weights_stddev": 0.05,
                "bias_filling": "uniform", "bias_stddev": 0.05},
         "<-": {"learning_rate": 0.02, "weights_decay": 0.0,
                "gradient_moment": 0.9}}],
})


class ApproximatorWorkflow(StandardWorkflow):
    """Model created for functions approximation
    (reference Approximator/approximator.py)."""


def build(layers=None, loader_config=None, decision_config=None,
          snapshotter_config=None, **kwargs):
    cfg = root.approximator
    loader_cfg = cfg.loader.as_dict()
    loader_cfg.update(loader_config or {})
    decision_cfg = cfg.decision.as_dict()
    decision_cfg.update(decision_config or {})
    snap_cfg = cfg.snapshotter.as_dict()
    snap_cfg.update(snapshotter_config or {})
    kwargs.setdefault("loss_function", cfg.loss_function)
    return ApproximatorWorkflow(
        layers=layers if layers is not None else cfg.layers,
        loader_name=cfg.loader_name,
        loader_config=loader_cfg,
        decision_config=decision_cfg,
        snapshotter_config=snap_cfg,
        **kwargs)


def run_sample(device=None, **kwargs):
    wf = build(**kwargs)
    wf.initialize(device=device)
    wf.run()
    return wf


if __name__ == "__main__":
    wf = run_sample()
    print("best epoch MSE:", wf.decision.best_metrics)


def run(load, main):
    """Launcher contract (reference samples/DemoKohonen-style run())."""
    load(build)
    main()
