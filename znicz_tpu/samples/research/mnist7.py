"""Mnist7 — MNIST digits regressed onto 7-segment display codes (MSE).

Parity target: reference tests/research/Mnist7 (mnist7.py:60-90: each
digit's target is its seven-segment encoding in {-1, 1}^7; layers
[100, 100, 7], EvaluatorMSE with class_targets for the
nearest-class-target error metric; published baseline 2.83% val err /
MSE 0.111, BASELINE.md)."""

import numpy

from znicz_tpu.core.config import root
from znicz_tpu.loader.base import FullBatchLoaderMSEMixin, IFullBatchLoader
from znicz_tpu.loader.loader_mnist import MnistLoader
from znicz_tpu.core.memory import Array
from znicz_tpu.standard_workflow import StandardWorkflow

#: seven-segment encodings of 0..9 (reference mnist7.py:72-82)
SEVEN_SEGMENT = numpy.array(
    [[1, 1, 1, -1, 1, 1, 1],      # 0
     [-1, -1, 1, -1, -1, 1, -1],  # 1
     [1, -1, 1, 1, 1, -1, 1],     # 2
     [1, -1, 1, 1, -1, 1, 1],     # 3
     [-1, 1, 1, 1, -1, 1, -1],    # 4
     [1, 1, -1, 1, -1, 1, 1],     # 5
     [1, 1, -1, 1, 1, 1, 1],      # 6
     [1, 1, 1, -1, -1, 1, -1],    # 7
     [1, 1, 1, 1, 1, 1, 1],       # 8
     [1, 1, 1, 1, -1, 1, 1]],     # 9
    dtype=numpy.float32)


class Mnist7Loader(FullBatchLoaderMSEMixin, MnistLoader, IFullBatchLoader):
    """MNIST data with 7-segment MSE targets."""

    MAPPING = "mnist7_loader"

    def load_data(self):
        super(Mnist7Loader, self).load_data()
        self.class_targets = Array(SEVEN_SEGMENT.copy(),
                                   name="class_targets")
        targets = numpy.zeros((len(self.original_labels), 7),
                              dtype=numpy.float32)
        for i, label in enumerate(self.original_labels):
            targets[i] = SEVEN_SEGMENT[label]
        self.original_targets.reset(targets)


root.mnist7.update({
    "decision": {"fail_iterations": 25, "max_epochs": 1000},
    "snapshotter": {"prefix": "mnist7", "interval": 1,
                    "time_interval": 0, "compression": ""},
    "loss_function": "mse",
    "loader_name": "mnist7_loader",
    "loader": {"minibatch_size": 60, "normalization_type": "linear"},
    "layers": [
        {"name": "fc_tanh1", "type": "all2all_tanh",
         "->": {"output_sample_shape": 100},
         "<-": {"learning_rate": 0.01, "weights_decay": 0.00005}},
        {"name": "fc_tanh2", "type": "all2all_tanh",
         "->": {"output_sample_shape": 100},
         "<-": {"learning_rate": 0.01, "weights_decay": 0.00005}},
        {"name": "fc_out", "type": "all2all_tanh",
         "->": {},  # width auto-set from targets_shape
         "<-": {"learning_rate": 0.01, "weights_decay": 0.00005}}],
})


class Mnist7Workflow(StandardWorkflow):
    """(reference tests/research/Mnist7/mnist7.py:92+)"""


def build(layers=None, loader_config=None, decision_config=None, **kwargs):
    cfg = root.mnist7
    loader_cfg = cfg.loader.as_dict()
    loader_cfg.update(loader_config or {})
    decision_cfg = cfg.decision.as_dict()
    decision_cfg.update(decision_config or {})
    kwargs.setdefault("loss_function", cfg.loss_function)
    return Mnist7Workflow(
        layers=layers if layers is not None else cfg.layers,
        loader_name=cfg.loader_name, loader_config=loader_cfg,
        decision_config=decision_cfg,
        snapshotter_config=cfg.snapshotter.as_dict(), **kwargs)


def run_sample(device=None, **kwargs):
    wf = build(**kwargs)
    wf.initialize(device=device)
    wf.run()
    return wf


def run(load, main):
    """Launcher contract (reference tests/research/Mnist7)."""
    load(build)
    main()
