"""MnistSimple — tuned single-hidden-layer MNIST MLP.

Parity target: reference tests/research/MnistSimple (mnist_config.py:
layers [364, 10], GA-tuned learning_rate/weights_decay/factor_ortho,
linear normalization, minibatch 88; published baseline 1.48% val err,
BASELINE.md)."""

from znicz_tpu.core.config import root
from znicz_tpu.standard_workflow import StandardWorkflow
import znicz_tpu.loader.loader_mnist  # noqa: F401 (registers mnist_loader)

root.mnist_simple.update({
    "decision": {"fail_iterations": 300, "max_epochs": 1000},
    "snapshotter": {"prefix": "mnist_simple", "interval": 1,
                    "time_interval": 0, "compression": ""},
    "loader_name": "mnist_loader",
    "loader": {"minibatch_size": 88, "normalization_type": "linear"},
    "layers": [
        {"name": "fc_tanh1", "type": "all2all_tanh",
         "->": {"output_sample_shape": 364, "weights_stddev": 0.05,
                "bias_stddev": 0.05},
         "<-": {"learning_rate": 0.028557478339518444,
                "weights_decay": 0.00012315096341168246,
                "factor_ortho": 0.001}},
        {"name": "fc_softmax2", "type": "softmax",
         "->": {"output_sample_shape": 10, "weights_stddev": 0.05,
                "bias_stddev": 0.05},
         "<-": {"learning_rate": 0.028557478339518444,
                "weights_decay": 0.00012315096341168246}}],
})


class MnistSimpleWorkflow(StandardWorkflow):
    """(reference tests/research/MnistSimple/mnist.py)"""


def build(layers=None, loader_config=None, decision_config=None, **kwargs):
    cfg = root.mnist_simple
    loader_cfg = cfg.loader.as_dict()
    loader_cfg.update(loader_config or {})
    decision_cfg = cfg.decision.as_dict()
    decision_cfg.update(decision_config or {})
    return MnistSimpleWorkflow(
        layers=layers if layers is not None else cfg.layers,
        loader_name=cfg.loader_name, loader_config=loader_cfg,
        decision_config=decision_cfg,
        snapshotter_config=cfg.snapshotter.as_dict(), **kwargs)


def run_sample(device=None, **kwargs):
    wf = build(**kwargs)
    wf.initialize(device=device)
    wf.run()
    return wf


def run(load, main):
    """Launcher contract (reference tests/research/MnistSimple)."""
    load(build)
    main()
