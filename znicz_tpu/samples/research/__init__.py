"""Research-model tier — the reference's tests/research model zoo
(SURVEY.md §2: MnistSimple, Mnist7, WineRelu, Hands, TvChannels,
MnistAE, VideoAE, Stl10, SpamKohonen, AlexNet, ImagenetAE; MnistRBM
lives in znicz_tpu.samples.mnist_rbm).

Each module follows the sample contract: config in ``root.<ns>``,
``build()``, ``run_sample()``, and the launcher's ``run(load, main)``.
"""
