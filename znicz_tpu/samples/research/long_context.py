"""Long-context demo — needle retrieval trained through ring attention.

The long-context mandate made concrete: a retrieval task whose answer
requires attending across the WHOLE sequence (a MARKER token appears at
a random position; the label is the token right after it), solved by a
model whose attention runs sequence-parallel over the device mesh
(:func:`znicz_tpu.parallel.sequence.ring_attention`) — the sequence
axis is sharded, K/V ride the ppermute ring, and gradients flow back
through the ring (tests/unit/test_sequence_parallel.py pins grad
exactness).

Model: embed -> ring attention (learned Q/K/V projections) -> readout
at the final position -> softmax CE, trained by plain SGD on jax.grad.
"""

import math

import numpy

import jax
import jax.numpy as jnp

from znicz_tpu.core.config import root
from znicz_tpu.parallel import make_mesh
from znicz_tpu.parallel.sequence import ring_attention

root.long_context.update({
    "vocab": 16,      # last id is the MARKER
    "embed": 32,
    "heads": 2,
    "seq_len": 64,
    "batch": 32,
    "steps": 800,
    "learning_rate": 1.0,
})


def make_batch(rand, batch, seq_len, vocab):
    """Sequences with one MARKER; label = the token following it."""
    marker = vocab - 1
    x = rand.randint(0, marker, (batch, seq_len))
    pos = rand.randint(0, seq_len - 1, batch)
    labels = x[numpy.arange(batch), pos + 1].astype(numpy.int32)
    x[numpy.arange(batch), pos] = marker
    return x.astype(numpy.int32), labels


def init_params(rand, vocab, embed, heads):
    scale = 1.0 / math.sqrt(embed)
    p = {
        "embed": rand.normal(0, scale, (vocab, embed)),
        # projections read [token, previous-token] features (2E)
        "wq": rand.normal(0, scale, (2 * embed, embed)),
        "wk": rand.normal(0, scale, (2 * embed, embed)),
        "wv": rand.normal(0, scale, (2 * embed, embed)),
        "bq": numpy.zeros(embed),   # learnable probe (see forward)
        "wo": rand.normal(0, scale, (embed, vocab)),
    }
    return {k: jnp.asarray(v, jnp.float32) for k, v in p.items()}


def forward(params, x, mesh, heads):
    """Single-hop retrieval head: each position's features are [its
    token, the PREVIOUS token], so the position after the marker keys on
    "previous == MARKER" and values its own token; the learned query
    bias ``bq`` lets the readout position emit a content-independent
    probe for that key."""
    b, t = x.shape
    e = params["embed"].shape[1]
    h = params["embed"][x]                              # (B, T, E)
    # concatenate, not jnp.pad: pad's VJP lowers to a
    # dynamic-update-slice whose index arithmetic mixes s64/s32 under
    # x64 + spmd partitioning on this jaxlib (hlo verifier rejects it);
    # the concat VJP is plain slices and is numerically identical
    h_prev = jnp.concatenate(
        [jnp.zeros_like(h[:, :1]), h[:, :-1]], axis=1)
    h2 = jnp.concatenate([h, h_prev], axis=-1)          # (B, T, 2E)
    q = (h2 @ params["wq"] + params["bq"]).reshape(b, t, heads,
                                                  e // heads)
    k = (h2 @ params["wk"]).reshape(b, t, heads, e // heads)
    v = (h2 @ params["wv"]).reshape(b, t, heads, e // heads)
    a = ring_attention(q, k, v, mesh, causal=False)
    a = a.reshape(b, t, e)
    # read out at the last position via a one-hot contraction: the VJP
    # of a[:, -1] is a pad/dynamic-update-slice on the t-sharded axis,
    # which this jaxlib's spmd partitioner rejects under x64 (mixed
    # s64/s32 offset compare); the mask-multiply VJP is elementwise
    last = (jnp.arange(t) == t - 1).astype(a.dtype)
    a_last = (a * last[None, :, None]).sum(axis=1)
    return a_last @ params["wo"]


def loss_fn(params, x, labels, mesh, heads):
    logits = forward(params, x, mesh, heads)
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], 1))


def run_sample(steps=None, mesh=None, seed=0x10C, **overrides):
    """Train the retriever; returns (final accuracy, params, mesh)."""
    cfg = root.long_context
    vocab, embed = cfg.vocab, cfg.embed
    heads, t = cfg.heads, cfg.seq_len
    batch = overrides.get("batch", cfg.batch)
    lr = overrides.get("learning_rate", cfg.learning_rate)
    steps = steps if steps is not None else cfg.steps
    mesh = mesh or make_mesh(min(8, len(jax.devices())),
                             model_parallel=1)
    rand = numpy.random.RandomState(seed)
    params = init_params(rand, vocab, embed, heads)
    grad = jax.jit(jax.grad(
        lambda p, x, y: loss_fn(p, x, y, mesh, heads)))
    for _ in range(steps):
        x, y = make_batch(rand, batch, t, vocab)
        g = grad(params, x, y)
        params = jax.tree.map(lambda p, gg: p - lr * gg, params, g)
    # evaluate on fresh data
    x, y = make_batch(rand, 256, t, vocab)
    pred = numpy.asarray(jnp.argmax(forward(params, x, mesh, heads), -1))
    accuracy = float((pred == y).mean())
    return accuracy, params, mesh


def run(load, main):
    """Launcher contract (demo tier — prints the retrieval accuracy)."""
    accuracy, _, _ = run_sample()
    print("needle-retrieval accuracy: %.2f%%" % (100 * accuracy))
    _ = (load, main)  # pure-jax demo: no unit graph to construct
