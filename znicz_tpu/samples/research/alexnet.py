"""AlexNet — the reference's ImageNet classification workflow.

Parity target: reference tests/research/AlexNet
(imagenet_workflow_config.py:111-230): conv_str 96 11x11 s4 ->
max_pool 3x3 s2 -> LRN -> ZeroFiller(grouping 2) -> conv_str 256 5x5
pad 2 -> pool -> LRN -> ZeroFiller -> conv_str 384 3x3 pad 1 ->
conv_str 384 -> ZeroFiller -> conv_str 256 -> pool -> ZeroFiller ->
fc 4096 -> str -> dropout .5 -> fc 4096 -> str -> dropout .5 ->
softmax 1000; gaussian init, arbitrary_step LR policy, momentum 0.9.
Published baseline 40.68% val err (BASELINE.md).  The reference feeds
preprocessed ImageNet pickles; absent data is synthesized as
prototype-class 227x227x3 images through the same full-batch contract."""

import numpy

from znicz_tpu.core.config import root
from znicz_tpu.loader.base import (FullBatchLoader, IFullBatchLoader,
                                   TEST, VALID, TRAIN)
from znicz_tpu.standard_workflow import StandardWorkflow

BASE_LR = 0.01
WD = 0.0005
_CONV_BWD = {"learning_rate": BASE_LR, "learning_rate_bias": BASE_LR * 2,
             "weights_decay": WD, "weights_decay_bias": 0,
             "gradient_moment": 0.9, "gradient_moment_bias": 0.9}


def make_layers(n_classes=1000):
    """The AlexNet layer list (reference config:111-230)."""
    return [
        {"name": "conv_str1", "type": "conv_str",
         "->": {"n_kernels": 96, "kx": 11, "ky": 11,
                "padding": (0, 0, 0, 0), "sliding": (4, 4),
                "weights_filling": "gaussian", "weights_stddev": 0.01,
                "bias_filling": "constant", "bias_stddev": 0},
         "<-": dict(_CONV_BWD, factor_ortho=0.001)},
        {"name": "max_pool1", "type": "max_pooling",
         "->": {"kx": 3, "ky": 3, "sliding": (2, 2)}},
        {"name": "norm1", "type": "norm",
         "n": 5, "alpha": 0.0001, "beta": 0.75},
        {"name": "grouping1", "type": "zero_filter", "grouping": 2},
        {"name": "conv_str2", "type": "conv_str",
         "->": {"n_kernels": 256, "kx": 5, "ky": 5,
                "padding": (2, 2, 2, 2), "sliding": (1, 1),
                "weights_filling": "gaussian", "weights_stddev": 0.01,
                "bias_filling": "constant", "bias_stddev": 1},
         "<-": dict(_CONV_BWD)},
        {"name": "max_pool2", "type": "max_pooling",
         "->": {"kx": 3, "ky": 3, "sliding": (2, 2)}},
        {"name": "norm2", "type": "norm",
         "n": 5, "alpha": 0.0001, "beta": 0.75},
        {"name": "grouping2", "type": "zero_filter", "grouping": 2},
        {"name": "conv_str3", "type": "conv_str",
         "->": {"n_kernels": 384, "kx": 3, "ky": 3,
                "padding": (1, 1, 1, 1), "sliding": (1, 1),
                "weights_filling": "gaussian", "weights_stddev": 0.01,
                "bias_filling": "constant", "bias_stddev": 0},
         "<-": dict(_CONV_BWD)},
        {"name": "conv_str4", "type": "conv_str",
         "->": {"n_kernels": 384, "kx": 3, "ky": 3,
                "padding": (1, 1, 1, 1), "sliding": (1, 1),
                "weights_filling": "gaussian", "weights_stddev": 0.01,
                "bias_filling": "constant", "bias_stddev": 1},
         "<-": dict(_CONV_BWD)},
        {"name": "grouping3", "type": "zero_filter", "grouping": 2},
        {"name": "conv_str5", "type": "conv_str",
         "->": {"n_kernels": 256, "kx": 3, "ky": 3,
                "padding": (1, 1, 1, 1), "sliding": (1, 1),
                "weights_filling": "gaussian", "weights_stddev": 0.01,
                "bias_filling": "constant", "bias_stddev": 1},
         "<-": dict(_CONV_BWD)},
        {"name": "max_pool5", "type": "max_pooling",
         "->": {"kx": 3, "ky": 3, "sliding": (2, 2)}},
        {"name": "grouping5", "type": "zero_filter", "grouping": 2},
        {"name": "fc6", "type": "all2all",
         "->": {"output_sample_shape": 4096,
                "weights_filling": "gaussian", "weights_stddev": 0.005,
                "bias_filling": "constant", "bias_stddev": 1},
         "<-": dict(_CONV_BWD)},
        {"name": "relu6", "type": "activation_str"},
        {"name": "drop6", "type": "dropout", "dropout_ratio": 0.5},
        {"name": "fc7", "type": "all2all",
         "->": {"output_sample_shape": 4096,
                "weights_filling": "gaussian", "weights_stddev": 0.005,
                "bias_filling": "constant", "bias_stddev": 1},
         "<-": dict(_CONV_BWD)},
        {"name": "relu7", "type": "activation_str"},
        {"name": "drop7", "type": "dropout", "dropout_ratio": 0.5},
        {"name": "fc_softmax8", "type": "softmax",
         "->": {"output_sample_shape": n_classes,
                "weights_filling": "gaussian", "weights_stddev": 0.01,
                "bias_filling": "constant", "bias_stddev": 0},
         "<-": dict(_CONV_BWD)}]


class SyntheticImagenetLoader(FullBatchLoader, IFullBatchLoader):
    """Prototype-class RGB images through the full-batch contract."""

    MAPPING = "synthetic_imagenet_loader"

    def __init__(self, workflow, **kwargs):
        kwargs.setdefault("normalization_type", "linear")
        super(SyntheticImagenetLoader, self).__init__(workflow, **kwargs)
        self.n_classes = kwargs.get("n_classes", 10)
        self.n_train = kwargs.get("n_train", 40)
        self.n_valid = kwargs.get("n_valid", 20)
        self.size = kwargs.get("size", 227)

    def load_data(self):
        r = numpy.random.RandomState(0x1337)
        n = self.n_train + self.n_valid
        protos = r.uniform(0, 255,
                           (self.n_classes, self.size, self.size, 3))
        labels = (numpy.arange(n) % self.n_classes).astype(int)
        data = numpy.empty((n, self.size, self.size, 3), numpy.float32)
        for i in range(n):
            data[i] = protos[labels[i]] + r.normal(
                0, 25, (self.size, self.size, 3))
        self.original_data.reset(data)
        del self._original_labels[:]
        self._original_labels.extend(int(v) for v in labels)
        self.class_lengths[TEST] = 0
        self.class_lengths[VALID] = self.n_valid
        self.class_lengths[TRAIN] = self.n_train


root.alexnet.update({
    "decision": {"fail_iterations": 10000, "max_epochs": 10000},
    "snapshotter": {"prefix": "alexnet", "interval": 1,
                    "time_interval": 0, "compression": ""},
    "loss_function": "softmax",
    "loader_name": "synthetic_imagenet_loader",
    "loader": {"minibatch_size": 4, "n_classes": 10},
    "lr_adjuster": {"do": True, "lr_policy_name": "arbitrary_step",
                    "bias_lr_policy_name": "arbitrary_step",
                    "lr_parameters": {
                        "lrs_with_lengths": [(1, 100000), (0.1, 100000),
                                             (0.01, 100000000)]},
                    "bias_lr_parameters": {
                        "lrs_with_lengths": [(1, 100000), (0.1, 100000),
                                             (0.01, 100000000)]}},
})


class AlexNetWorkflow(StandardWorkflow):
    """(reference tests/research/AlexNet/imagenet_workflow.py)"""


def build(layers=None, loader_config=None, decision_config=None, **kwargs):
    cfg = root.alexnet
    loader_cfg = cfg.loader.as_dict()
    loader_cfg.update(loader_config or {})
    decision_cfg = cfg.decision.as_dict()
    decision_cfg.update(decision_config or {})
    kwargs.setdefault("loss_function", cfg.loss_function)
    n_classes = loader_cfg.get("n_classes", 10)
    snap_cfg = cfg.snapshotter.as_dict()
    snap_cfg.update(kwargs.pop("snapshotter_config", None) or {})
    return AlexNetWorkflow(
        layers=layers if layers is not None else make_layers(n_classes),
        loader_name=cfg.loader_name, loader_config=loader_cfg,
        decision_config=decision_cfg,
        snapshotter_config=snap_cfg, **kwargs)


def run_sample(device=None, **kwargs):
    wf = build(**kwargs)
    wf.initialize(device=device)
    wf.run()
    return wf


def run(load, main):
    """Launcher contract (reference tests/research/AlexNet)."""
    load(build)
    main()
