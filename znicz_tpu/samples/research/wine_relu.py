"""WineRelu — Wine classification through the softplus-"relu" activation.

Parity target: reference tests/research/WineRelu (wine_relu_config.py:
all2all_relu 10 -> softmax, lr 0.03, minibatch 10; published baseline
0.00% train err, BASELINE.md)."""

from znicz_tpu.core.config import root
from znicz_tpu.standard_workflow import StandardWorkflow
import znicz_tpu.loader.loader_wine  # noqa: F401 (registers wine_loader)

root.wine_relu.update({
    "decision": {"fail_iterations": 250, "max_epochs": 200},
    "snapshotter": {"prefix": "wine_relu", "interval": 1,
                    "time_interval": 0, "compression": ""},
    "loader_name": "wine_loader",
    "loader": {"minibatch_size": 10},
    "layers": [
        {"name": "fc_relu1", "type": "all2all_relu",
         "->": {"output_sample_shape": 10},
         "<-": {"learning_rate": 0.03, "weights_decay": 0.0}},
        {"name": "fc_softmax2", "type": "softmax",
         "->": {"output_sample_shape": 3},
         "<-": {"learning_rate": 0.03, "weights_decay": 0.0}}],
})


class WineReluWorkflow(StandardWorkflow):
    """(reference tests/research/WineRelu/wine_relu.py)"""


def build(layers=None, loader_config=None, decision_config=None, **kwargs):
    cfg = root.wine_relu
    loader_cfg = cfg.loader.as_dict()
    loader_cfg.update(loader_config or {})
    decision_cfg = cfg.decision.as_dict()
    decision_cfg.update(decision_config or {})
    return WineReluWorkflow(
        layers=layers if layers is not None else cfg.layers,
        loader_name=cfg.loader_name, loader_config=loader_cfg,
        decision_config=decision_cfg,
        snapshotter_config=cfg.snapshotter.as_dict(), **kwargs)


def run_sample(device=None, **kwargs):
    wf = build(**kwargs)
    wf.initialize(device=device)
    wf.run()
    return wf


def run(load, main):
    """Launcher contract (reference tests/research/WineRelu)."""
    load(build)
    main()
