"""SpamKohonen — spam clustering on an 8x8 SOM, with validation.

Parity target: reference tests/research/SpamKohonen (spam_kohonen.py +
spam_kohonen_config.py: bag-of-words spam/ham vectors, 8x8 Kohonen map,
decaying gradient/radius schedules, KohonenValidator fitness against
labels, ResultsExporter writing per-sample winner ids).  The reference
downloads spam.tar; absent files are synthesized as sparse
bag-of-words-like vectors from two word distributions (spam vs ham)."""

import gzip
import os

import numpy

from znicz_tpu.core.config import root
from znicz_tpu.core.workflow import Repeater, Workflow
from znicz_tpu.loader.base import (FullBatchLoader, IFullBatchLoader,
                                   TRAIN)
from znicz_tpu.units import kohonen as koh_units

DATASET_FILE = os.path.join(root.common.dirs.datasets, "spam",
                            "spam.txt.gz")
N_FEATURES = 24

root.spam_kohonen.update({
    "forward": {"shape": (8, 8), "weights_stddev": 0.05,
                "weights_filling": "uniform"},
    "decision": {"epochs": 60},
    "loader": {"minibatch_size": 80,
               "file": DATASET_FILE},
    "train": {"gradient_decay": lambda t: 0.002 / (1.0 + t * 0.00002),
              "radius_decay": lambda t: 1.0 / (1.0 + t * 0.00002)},
    "exporter": {"file": "classified.txt"},
})


class SpamLoader(FullBatchLoader, IFullBatchLoader):
    """label + feature rows (the reference spam.txt layout: first column
    is the class id, the rest are lemma frequencies)."""

    MAPPING = "spam_loader"

    def __init__(self, workflow, **kwargs):
        kwargs.setdefault("normalization_type", "pointwise")
        super(SpamLoader, self).__init__(workflow, **kwargs)
        self.file = kwargs.get("file", DATASET_FILE)
        self.samples_by_label = {}

    def _materialize(self):
        r = numpy.random.RandomState(0x5BA1)
        os.makedirs(os.path.dirname(self.file), exist_ok=True)
        # two word distributions; each message samples ~30 word draws
        p_spam = r.dirichlet(numpy.full(N_FEATURES, 0.15))
        p_ham = r.dirichlet(numpy.full(N_FEATURES, 0.15))
        with gzip.open(self.file, "wt") as f:
            for i in range(400):
                label = int(i % 2)
                p = p_spam if label else p_ham
                counts = r.multinomial(30, p)
                f.write("%d %s\n" % (label,
                                     " ".join(str(c) for c in counts)))

    def load_data(self):
        if not os.path.exists(self.file):
            self._materialize()
        opener = gzip.open if self.file.endswith(".gz") else open
        labels, rows = [], []
        with opener(self.file, "rt") as f:
            for line in f:
                vals = line.split()
                if not vals:
                    continue
                labels.append(int(vals[0]))
                rows.append([float(v) for v in vals[1:]])
        self.original_data.mem = numpy.array(rows, dtype=numpy.float32)
        del self._original_labels[:]
        self._original_labels.extend(labels)
        self.class_lengths[TRAIN] = len(rows)
        self.samples_by_label = {}
        for i, label in enumerate(labels):
            self.samples_by_label.setdefault(label, set()).add(i)


class ResultsExporter(koh_units.Unit):
    """Writes one winner-neuron id per sample
    (reference spam_kohonen.py ResultsExporter)."""

    def __init__(self, workflow, file_name, **kwargs):
        super(ResultsExporter, self).__init__(workflow, **kwargs)
        self.file_name = file_name
        self.demand("total", "shuffled_indices")

    def run(self):
        self.total.map_read()
        indices = numpy.asarray(self.shuffled_indices)
        order = numpy.argsort(indices)
        os.makedirs(os.path.dirname(os.path.abspath(self.file_name)),
                    exist_ok=True)
        with open(self.file_name, "w") as f:
            for i in order:
                f.write("%d\n" % int(self.total.mem[i]))
        self.info("exported %d results -> %s", len(order), self.file_name)


class SpamKohonenWorkflow(Workflow):
    """loader -> trainer -> forward(total) -> decision loop + validator
    (reference spam_kohonen.py)."""

    def __init__(self, workflow=None, **kwargs):
        super(SpamKohonenWorkflow, self).__init__(workflow, **kwargs)
        cfg = root.spam_kohonen
        self.repeater = Repeater(self)
        self.repeater.link_from(self.start_point)

        loader_cfg = cfg.loader.as_dict()
        loader_cfg.update(kwargs.get("loader_config") or {})
        loader_cfg.setdefault("file", cfg.loader.file)
        loader_cfg.pop("minibatch_size_", None)
        self.loader = SpamLoader(self, name="loader", **loader_cfg)
        self.loader.link_from(self.repeater)

        fwd_cfg = cfg.forward.as_dict()
        self.trainer = koh_units.KohonenTrainer(
            self, shape=tuple(fwd_cfg["shape"]),
            weights_stddev=fwd_cfg.get("weights_stddev", 0.05),
            weights_filling=fwd_cfg.get("weights_filling", "uniform"),
            gradient_decay=cfg.train.gradient_decay,
            radius_decay=cfg.train.radius_decay)
        self.trainer.link_from(self.loader)
        self.trainer.link_attrs(self.loader, ("input", "minibatch_data"))

        self.forward = koh_units.KohonenForward(self, total=True)
        self.forward.link_from(self.trainer)
        self.forward.link_attrs(self.loader, ("input", "minibatch_data"),
                                ("batch_size", "total_samples"),
                                "minibatch_offset", "minibatch_size")
        self.forward.link_attrs(self.trainer, "weights", "argmins")

        self.validator = koh_units.KohonenValidator(self)
        self.validator.link_attrs(self.trainer, "shape")
        self.validator.link_attrs(self.forward, ("input", "output"))
        self.validator.link_attrs(self.loader, "minibatch_indices",
                                  "minibatch_size", "samples_by_label")
        self.validator.link_from(self.forward)

        epochs = kwargs.get("epochs", cfg.decision.epochs)
        self.decision = koh_units.KohonenDecision(
            self, name="decision", max_epochs=epochs)
        self.decision.link_from(self.validator)
        self.decision.link_attrs(self.loader, "minibatch_class",
                                 "last_minibatch", "minibatch_size",
                                 "class_lengths", "epoch_ended",
                                 "epoch_number")
        self.decision.link_attrs(self.trainer, "weights", "winners")

        self.exporter = ResultsExporter(
            self, kwargs.get("exporter_file",
                             os.path.join(root.common.dirs.cache,
                                          cfg.exporter.file)))
        self.exporter.link_from(self.decision)
        self.exporter.link_attrs(self.forward, "total")
        self.exporter.link_attrs(self.loader, "shuffled_indices")
        self.exporter.gate_skip = ~self.decision.complete

        self.repeater.link_from(self.decision)
        self.repeater.gate_block = self.decision.complete
        self.loader.gate_block = self.decision.complete
        self.end_point.link_from(self.exporter)
        self.end_point.gate_block = ~self.decision.complete


def build(**kwargs):
    return SpamKohonenWorkflow(**kwargs)


def run_sample(device=None, **kwargs):
    wf = build(**kwargs)
    wf.initialize(device=device)
    wf.run()
    return wf


def run(load, main):
    """Launcher contract (reference tests/research/SpamKohonen)."""
    load(SpamKohonenWorkflow)
    main()
