"""MnistAE — convolutional autoencoder on MNIST.

Parity target: reference tests/research/MnistAE (mnist_ae.py:64-190):
conv 5x5x5 (no bias) -> StochasticAbsPooling 3x3 slide (2,2) ->
depooling (the GDMaxAbsPooling scatter reused as a forward unit) ->
Deconv SHARING the conv's weights (output shaped from the conv's input)
-> EvaluatorMSE against the input frames -> DecisionMSE -> GDDeconv as
the only trained gradient unit.  Published baseline MSE 0.5478/0.5482
(BASELINE.md)."""


from znicz_tpu.core.config import root
from znicz_tpu.units import nn_units
from znicz_tpu.units import conv as conv_units
from znicz_tpu.units import pooling as pooling_units
from znicz_tpu.units import gd_pooling as gd_pooling_units
from znicz_tpu.units import deconv as deconv_units
from znicz_tpu.units import evaluator as evaluator_units
from znicz_tpu.units import decision as decision_units
from znicz_tpu.loader.loader_mnist import MnistLoader


class MnistAELoader(MnistLoader):
    """MNIST with an explicit channel axis — Deconv's output shape
    source must be NHWC (reference mnist_ae.py:64-70)."""

    MAPPING = "mnist_ae_loader"

    def load_data(self):
        super(MnistAELoader, self).load_data()
        d = self.original_data.mem
        self.original_data.reset(d.reshape(d.shape[0], 28, 28, 1))

root.mnist_ae.update({
    "decision": {"fail_iterations": 20, "max_epochs": 1000},
    "snapshotter": {"prefix": "mnist_ae", "interval": 1,
                    "time_interval": 0, "compression": ""},
    "loader": {"minibatch_size": 100, "normalization_type": "linear"},
    "learning_rate": 0.000001,
    "weights_decay": 0.00005,
    "gradient_moment": 0.00001,
    "n_kernels": 5,
    "kx": 5,
    "ky": 5,
    "include_bias": False,
    "unsafe_padding": True,
    "pooling": {"kx": 3, "ky": 3, "sliding": (2, 2)},
})


class MnistAEWorkflow(nn_units.NNWorkflow):
    """conv -> abs-pool -> depool -> weight-shared deconv, MSE to input
    (reference mnist_ae.py:107-190)."""

    def __init__(self, workflow=None, **kwargs):
        super(MnistAEWorkflow, self).__init__(workflow, **kwargs)
        cfg = root.mnist_ae
        loader_cfg = cfg.loader.as_dict()
        loader_cfg.update(kwargs.get("loader_config") or {})
        decision_cfg = cfg.decision.as_dict()
        decision_cfg.update(kwargs.get("decision_config") or {})

        self.repeater.link_from(self.start_point)

        self.loader = MnistAELoader(self, **loader_cfg)
        self.loader.link_from(self.repeater)

        self.conv = conv_units.Conv(
            self, n_kernels=cfg.n_kernels, kx=cfg.kx, ky=cfg.ky,
            weights_filling="uniform",
            include_bias=cfg.include_bias)
        self.conv.link_from(self.loader)
        self.conv.link_attrs(self.loader, ("input", "minibatch_data"))

        self.pool = pooling_units.StochasticAbsPooling(
            self, kx=cfg.pooling.kx, ky=cfg.pooling.ky,
            sliding=tuple(cfg.pooling.sliding))
        self.pool.link_from(self.conv)
        self.pool.link_attrs(self.conv, ("input", "output"))

        # depooling: the abs-pool backward scatter reused as a forward
        # stage (err_output = pool.output -> err_input has input shape)
        self.depool = gd_pooling_units.GDMaxAbsPooling(
            self, kx=cfg.pooling.kx, ky=cfg.pooling.ky,
            sliding=tuple(cfg.pooling.sliding))
        self.depool.link_from(self.pool)
        self.depool.link_attrs(self.pool, "input", "input_offset",
                               ("err_output", "output"))

        self.deconv = deconv_units.Deconv(
            self, unsafe_padding=cfg.unsafe_padding)
        self.deconv.link_from(self.depool)
        self.deconv.link_attrs(self.conv, "weights")
        self.deconv.link_conv_attrs(self.conv)
        self.deconv.link_attrs(self.depool, ("input", "err_input"))
        self.deconv.link_attrs(self.conv, ("output_shape_source", "input"))

        self.evaluator = evaluator_units.EvaluatorMSE(self)
        self.evaluator.link_from(self.deconv)
        self.evaluator.link_attrs(self.deconv, "output")
        self.evaluator.link_attrs(
            self.loader,
            ("batch_size", "minibatch_size"),
            ("normalizer", "target_normalizer"),
            ("target", "minibatch_data"))

        self.decision = decision_units.DecisionMSE(
            self, fail_iterations=decision_cfg.get("fail_iterations", 20),
            max_epochs=decision_cfg.get("max_epochs", 1000))
        self.decision.link_from(self.evaluator)
        self.decision.link_attrs(self.loader, "minibatch_class",
                                 "minibatch_size", "last_minibatch",
                                 "class_lengths", "epoch_ended",
                                 "epoch_number")
        self.decision.link_attrs(self.evaluator,
                                 ("minibatch_metrics", "metrics"))

        self.snapshotter = nn_units.NNSnapshotterToFile(
            self, **cfg.snapshotter.as_dict())
        self.snapshotter.link_from(self.decision)
        self.snapshotter.link_attrs(self.decision,
                                    ("suffix", "snapshot_suffix"))
        self.snapshotter.gate_skip = \
            ~self.loader.epoch_ended | ~self.decision.improved

        self.gd_deconv = deconv_units.GDDeconv(
            self, learning_rate=cfg.learning_rate,
            weights_decay=cfg.weights_decay,
            gradient_moment=cfg.gradient_moment)
        self.gd_deconv.link_attrs(self.evaluator, "err_output")
        self.gd_deconv.link_attrs(
            self.deconv, "weights", "input", "hits", "n_kernels",
            "kx", "ky", "sliding", "padding")
        self.gd_deconv.link_from(self.snapshotter)
        self.gd_deconv.gate_skip = self.decision.gd_skip
        self.gd_deconv.need_err_input = False

        self.repeater.link_from(self.gd_deconv)
        self.end_point.link_from(self.gd_deconv)
        self.end_point.gate_block = ~self.decision.complete
        self.loader.gate_block = self.decision.complete

    def reconstruction_mse(self):
        return self.decision.epoch_metrics[2]


def build(**kwargs):
    return MnistAEWorkflow(**kwargs)


def run_sample(device=None, **kwargs):
    wf = build(**kwargs)
    wf.initialize(device=device)
    wf.run()
    return wf


def run(load, main):
    """Launcher contract (reference tests/research/MnistAE)."""
    load(MnistAEWorkflow)
    main()
