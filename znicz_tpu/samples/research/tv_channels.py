"""TvChannels — TV channel logo classification.

Parity target: reference tests/research/TvChannels (channels_config.py:
per-channel logo image dirs, validation_ratio 0.15, mean_disp
normalization, MLP head; published baseline 0.74% val err, BASELINE.md).
The reference downloads channels_train.tar; absent files are
materialized as synthetic per-channel logo images (distinct geometric
glyph + corner position per channel)."""

import os

import numpy

from znicz_tpu.core.config import root
from znicz_tpu.standard_workflow import StandardWorkflow
import znicz_tpu.loader.image  # noqa: F401 (registers image loaders)

DATA_DIR = os.path.join(root.common.dirs.datasets, "channels_train")
N_CHANNELS = 6

root.channels.update({
    "decision": {"fail_iterations": 50, "max_epochs": 1000},
    "loss_function": "softmax",
    "snapshotter": {"prefix": "channels", "interval": 1,
                    "time_interval": 0, "compression": ""},
    "loader_name": "full_batch_auto_label_file_image",
    "loader": {"minibatch_size": 30, "validation_ratio": 0.15,
               "normalization_type": "mean_disp",
               "train_paths": [DATA_DIR]},
    "layers": [
        {"name": "fc_tanh1", "type": "all2all_tanh",
         "->": {"output_sample_shape": 100},
         "<-": {"learning_rate": 0.01, "weights_decay": 0.00005}},
        {"name": "fc_softmax2", "type": "softmax",
         "->": {},
         "<-": {"learning_rate": 0.01, "weights_decay": 0.00005}}],
})


def materialize_synthetic(data_dir=None, per_class=30, size=32,
                          seed=0x7C11):
    """Synthetic logos: each channel is a distinct glyph (rect/disc/bar
    pattern) at a fixed corner over random background frames."""
    from PIL import Image
    data_dir = data_dir or DATA_DIR
    if os.path.isdir(data_dir) and os.listdir(data_dir):
        return data_dir
    r = numpy.random.RandomState(seed)
    for c in range(N_CHANNELS):
        class_dir = os.path.join(data_dir, "channel%02d" % c)
        os.makedirs(class_dir, exist_ok=True)
        gx = (c % 2) * (size - 10)    # logo corner
        gy = (c // 2 % 2) * (size - 10)
        for i in range(per_class):
            img = r.uniform(0, 0.3, (size, size))  # "program" noise
            logo = numpy.zeros((10, 10))
            if c % 3 == 0:
                logo[2:8, 2:8] = 1.0
            elif c % 3 == 1:
                yy, xx = numpy.mgrid[0:10, 0:10]
                logo[((xx - 5) ** 2 + (yy - 5) ** 2) < 12] = 1.0
            else:
                logo[::2, :] = 1.0
            if c >= 3:
                logo = 1.0 - logo
            img[gy:gy + 10, gx:gx + 10] = 0.7 * logo + 0.3
            img = (255 * numpy.clip(img, 0, 1)).astype(numpy.uint8)
            Image.fromarray(img).save(
                os.path.join(class_dir, "frame%03d.png" % i))
    return data_dir


class ChannelsWorkflow(StandardWorkflow):
    """(reference tests/research/TvChannels/channels.py)"""


def build(layers=None, loader_config=None, decision_config=None, **kwargs):
    cfg = root.channels
    loader_cfg = cfg.loader.as_dict()
    loader_cfg.update(loader_config or {})
    train_paths = loader_cfg.get("train_paths") or []
    if not any(os.path.isdir(p) and os.listdir(p) for p in train_paths):
        materialize_synthetic(train_paths[0] if train_paths else None)
    decision_cfg = cfg.decision.as_dict()
    decision_cfg.update(decision_config or {})
    kwargs.setdefault("loss_function", cfg.loss_function)
    return ChannelsWorkflow(
        layers=layers if layers is not None else cfg.layers,
        loader_name=cfg.loader_name, loader_config=loader_cfg,
        decision_config=decision_cfg,
        snapshotter_config=cfg.snapshotter.as_dict(), **kwargs)


def run_sample(device=None, **kwargs):
    wf = build(**kwargs)
    wf.initialize(device=device)
    wf.run()
    return wf


def run(load, main):
    """Launcher contract (reference tests/research/TvChannels)."""
    load(build)
    main()
