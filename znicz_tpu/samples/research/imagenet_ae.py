"""ImagenetAE — convolutional autoencoder stage on ImageNet-scale images.

Parity target: reference tests/research/ImagenetAE (imagenet_ae.py +
imagenet_ae_config.py): stacked conv AE trained stage-wise (conv 108
9x9 s3 as the first stage, later 192/224/256 stages added from
snapshots), each stage conv -> stochastic abs pooling -> depooling ->
weight-shared Deconv with MSE against the stage input; published
baseline score 55.29pt (BASELINE.md).  Stage-wise pretraining lives
HERE: ``n_stages`` builds earlier stages as frozen forwards and trains
only the last stage's AE tail; ``restore_stage_weights`` carries the
previous stage's trained conv weights into the grown workflow (the
reference's from_snapshot_add_layer growth step)."""

import numpy

from znicz_tpu.core.config import root
from znicz_tpu.loader.base import (FullBatchLoader, IFullBatchLoader,
                                   TEST, VALID, TRAIN)
from znicz_tpu.units import nn_units
from znicz_tpu.units import conv as conv_units
from znicz_tpu.units import pooling as pooling_units
from znicz_tpu.units import gd_pooling as gd_pooling_units
from znicz_tpu.units import deconv as deconv_units
from znicz_tpu.units import evaluator as evaluator_units
from znicz_tpu.units import decision as decision_units

root.imagenet_ae.update({
    "decision": {"fail_iterations": 20, "max_epochs": 1000},
    "snapshotter": {"prefix": "imagenet_ae", "interval": 1,
                    "time_interval": 0, "compression": ""},
    "loader": {"minibatch_size": 8, "size": 63, "n_images": 32},
    "learning_rate": 0.0000003,
    "weights_decay": 0.00005,
    "gradient_moment": 0.00001,
    "include_bias": False,
    "unsafe_padding": True,
    "pooling": {"kx": 3, "ky": 3, "sliding": (2, 2)},
    #: stage-wise pretraining ladder (reference imagenet_ae_config.py:
    #: 101-165 conv geometries 108/192/224/256)
    "stages": [
        {"n_kernels": 108, "kx": 9, "ky": 9, "sliding": (3, 3)},
        {"n_kernels": 192, "kx": 5, "ky": 5, "sliding": (1, 1)},
        {"n_kernels": 224, "kx": 5, "ky": 5, "sliding": (1, 1)},
        {"n_kernels": 256, "kx": 3, "ky": 3, "sliding": (1, 1)}],
})


class SyntheticImageLoader(FullBatchLoader, IFullBatchLoader):
    """Natural-image-like synthetic RGB frames (smooth random fields)."""

    MAPPING = "imagenet_ae_loader"

    def __init__(self, workflow, **kwargs):
        kwargs.setdefault("normalization_type", "linear")
        super(SyntheticImageLoader, self).__init__(workflow, **kwargs)
        self.size = kwargs.get("size", 63)
        self.n_images = kwargs.get("n_images", 32)

    def load_data(self):
        r = numpy.random.RandomState(0xAE)
        n, s = self.n_images, self.size
        # smooth fields: low-frequency cosine mixtures + noise
        yy, xx = numpy.mgrid[0:s, 0:s].astype(numpy.float32) / s
        data = numpy.empty((n, s, s, 3), numpy.float32)
        for i in range(n):
            img = numpy.zeros((s, s))
            for _ in range(4):
                fx, fy = r.uniform(1, 4, 2)
                ph = r.uniform(0, 2 * numpy.pi, 2)
                img += r.uniform(0.2, 1.0) * numpy.cos(
                    2 * numpy.pi * fx * xx + ph[0]) * numpy.cos(
                    2 * numpy.pi * fy * yy + ph[1])
            for c in range(3):
                data[i, :, :, c] = img * r.uniform(0.5, 1.0) + \
                    r.normal(0, 0.05, (s, s))
        self.original_data.reset(data)
        n_valid = n // 4
        self.class_lengths[TEST] = 0
        self.class_lengths[VALID] = n_valid
        self.class_lengths[TRAIN] = n - n_valid


class ImagenetAEWorkflow(nn_units.NNWorkflow):
    """One AE stage: conv -> abs-pool -> depool -> weight-shared deconv,
    MSE to the stage input (reference imagenet_ae.py:182-266)."""

    def __init__(self, workflow=None, **kwargs):
        super(ImagenetAEWorkflow, self).__init__(workflow, **kwargs)
        cfg = root.imagenet_ae
        loader_cfg = cfg.loader.as_dict()
        loader_cfg.update(kwargs.get("loader_config") or {})
        decision_cfg = cfg.decision.as_dict()
        decision_cfg.update(kwargs.get("decision_config") or {})
        stages = kwargs.get("stages") or cfg.stages
        self.n_stages = int(kwargs.get("n_stages", 1))
        if not 1 <= self.n_stages <= len(stages):
            raise ValueError("n_stages must be 1..%d" % len(stages))

        self.repeater.link_from(self.start_point)
        self.loader = SyntheticImageLoader(self, name="loader",
                                           **loader_cfg)
        self.loader.link_from(self.repeater)

        # earlier stages are FROZEN forwards (conv + abs pooling); the
        # LAST stage gets the autoencoder tail and is the only one
        # trained — the reference's stage-wise pretraining
        # (imagenet_ae.py from_snapshot_add_layer)
        self.convs = []
        prev_unit, prev_attr = self.loader, "minibatch_data"
        for s in range(self.n_stages):
            geo = dict(stages[s])
            conv = conv_units.Conv(
                self, name="conv%d" % s,
                n_kernels=geo["n_kernels"], kx=geo["kx"], ky=geo["ky"],
                sliding=tuple(geo.get("sliding", (1, 1))),
                weights_filling="uniform",
                include_bias=cfg.include_bias)
            conv.link_from(prev_unit)
            conv.link_attrs(prev_unit, ("input", prev_attr))
            self.convs.append(conv)
            if s < self.n_stages - 1:
                frozen_pool = pooling_units.StochasticAbsPooling(
                    self, name="pool%d" % s,
                    kx=cfg.pooling.kx, ky=cfg.pooling.ky,
                    sliding=tuple(cfg.pooling.sliding))
                frozen_pool.link_from(conv)
                frozen_pool.link_attrs(conv, ("input", "output"))
                prev_unit, prev_attr = frozen_pool, "output"
        self.conv = self.convs[-1]

        self.pool = pooling_units.StochasticAbsPooling(
            self, name="pool%d" % (self.n_stages - 1),
            kx=cfg.pooling.kx, ky=cfg.pooling.ky,
            sliding=tuple(cfg.pooling.sliding))
        self.pool.link_from(self.conv)
        self.pool.link_attrs(self.conv, ("input", "output"))

        self.depool = gd_pooling_units.GDMaxAbsPooling(
            self, kx=cfg.pooling.kx, ky=cfg.pooling.ky,
            sliding=tuple(cfg.pooling.sliding))
        self.depool.link_from(self.pool)
        self.depool.link_attrs(self.pool, "input", "input_offset",
                               ("err_output", "output"))

        self.deconv = deconv_units.Deconv(
            self, unsafe_padding=cfg.unsafe_padding)
        self.deconv.link_from(self.depool)
        self.deconv.link_attrs(self.conv, "weights")
        self.deconv.link_conv_attrs(self.conv)
        self.deconv.link_attrs(self.depool, ("input", "err_input"))
        self.deconv.link_attrs(self.conv, ("output_shape_source", "input"))

        self.evaluator = evaluator_units.EvaluatorMSE(self)
        self.evaluator.link_from(self.deconv)
        self.evaluator.link_attrs(self.deconv, "output")
        self.evaluator.link_attrs(
            self.loader,
            ("batch_size", "minibatch_size"),
            ("normalizer", "target_normalizer"))
        # reconstruct the LAST stage's input (reference imagenet_ae.py:
        # 262 "target" <- last_conv "input") — the raw images for stage 0
        self.evaluator.link_attrs(self.conv, ("target", "input"))

        self.decision = decision_units.DecisionMSE(
            self, fail_iterations=decision_cfg.get("fail_iterations", 20),
            max_epochs=decision_cfg.get("max_epochs", 1000))
        self.decision.link_from(self.evaluator)
        self.decision.link_attrs(self.loader, "minibatch_class",
                                 "minibatch_size", "last_minibatch",
                                 "class_lengths", "epoch_ended",
                                 "epoch_number")
        self.decision.link_attrs(self.evaluator,
                                 ("minibatch_metrics", "metrics"))

        self.snapshotter = nn_units.NNSnapshotterToFile(
            self, **cfg.snapshotter.as_dict())
        self.snapshotter.link_from(self.decision)
        self.snapshotter.link_attrs(self.decision,
                                    ("suffix", "snapshot_suffix"))
        self.snapshotter.gate_skip = \
            ~self.loader.epoch_ended | ~self.decision.improved

        self.gd_deconv = deconv_units.GDDeconv(
            self, learning_rate=cfg.learning_rate,
            weights_decay=cfg.weights_decay,
            gradient_moment=cfg.gradient_moment)
        self.gd_deconv.link_attrs(self.evaluator, "err_output")
        self.gd_deconv.link_attrs(
            self.deconv, "weights", "input", "hits", "n_kernels",
            "kx", "ky", "sliding", "padding")
        self.gd_deconv.link_from(self.snapshotter)
        self.gd_deconv.gate_skip = self.decision.gd_skip
        self.gd_deconv.need_err_input = False

        self.repeater.link_from(self.gd_deconv)
        self.end_point.link_from(self.gd_deconv)
        self.end_point.gate_block = ~self.decision.complete
        self.loader.gate_block = self.decision.complete

    def reconstruction_mse(self):
        return self.decision.epoch_metrics[2]


def restore_stage_weights(snapshot_path, wf):
    """Load the conv weights of EARLIER stages from a previous stage's
    snapshot into a freshly-built (and initialized) workflow — the
    growth step of stage-wise pretraining.  Only conv* units restore
    (decision/loader/PRNG state starts fresh for the new stage), and a
    geometry mismatch between the snapshot and the built conv fails
    fast instead of deep inside the conv op."""
    from znicz_tpu.core.snapshotter import SnapshotterToFile
    from znicz_tpu.units.nn_units import load_snapshot_into_workflow
    state = SnapshotterToFile.import_(snapshot_path)
    units = {u.name: u for u in wf.units}
    conv_states = {}
    for name, ustate in state["units"].items():
        if not name.startswith("conv") or name not in units:
            continue
        saved_w = ustate.get("weights")
        built_w = units[name].weights
        if saved_w is not None and built_w and \
                tuple(saved_w.shape) != tuple(built_w.shape):
            raise ValueError(
                "%s: snapshot weights %s do not fit the built conv %s — "
                "stage geometry changed since the snapshot"
                % (name, saved_w.shape, built_w.shape))
        conv_states[name] = ustate
    load_snapshot_into_workflow({"units": conv_states}, wf)
    return sorted(conv_states)


def build(n_stages=1, **kwargs):
    return ImagenetAEWorkflow(n_stages=n_stages, **kwargs)


def run_sample(device=None, restore_snapshot=None, **kwargs):
    wf = build(**kwargs)
    wf.initialize(device=device)
    if restore_snapshot:
        names = restore_stage_weights(restore_snapshot, wf)
        wf.info("restored stage weights: %s", ", ".join(names))
    wf.run()
    return wf


def run(load, main):
    """Launcher contract (reference tests/research/ImagenetAE)."""
    load(ImagenetAEWorkflow)
    main()
