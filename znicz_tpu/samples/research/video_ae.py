"""VideoAE — fully-connected frame autoencoder.

Parity target: reference tests/research/VideoAE (video_ae_config.py:
layers [9, [90, 160]] — 9-unit bottleneck reconstructing 90x160
grayscale frames, MSE vs the input frames, lr 0.01; published baseline
MSE 0.0000/0.2596, BASELINE.md).  The reference downloads video_ae.tar
of frames; absent files are synthesized as smooth moving-blob frames
(a 'video') with the same loader contract (targets == data)."""

import numpy

from znicz_tpu.core.config import root
from znicz_tpu.loader.base import (FullBatchLoaderMSE, IFullBatchLoader,
                                   TEST, VALID, TRAIN)
from znicz_tpu.standard_workflow import StandardWorkflow

FRAME = (18, 32)  # scaled-down 90x160 for the zero-egress box


class VideoAELoader(FullBatchLoaderMSE, IFullBatchLoader):
    """Frames in, the SAME frames as targets (autoencoder contract)."""

    MAPPING = "video_ae_loader"

    def __init__(self, workflow, **kwargs):
        kwargs.setdefault("normalization_type", "linear")
        super(VideoAELoader, self).__init__(workflow, **kwargs)
        self.n_frames = kwargs.get("n_frames", 120)
        self.frame_shape = tuple(kwargs.get("frame_shape", FRAME))

    def load_data(self):
        h, w = self.frame_shape
        r = numpy.random.RandomState(0x51DE0)
        t = numpy.arange(self.n_frames, dtype=numpy.float32)
        yy, xx = numpy.mgrid[0:h, 0:w].astype(numpy.float32)
        # one blob orbiting + one bouncing: smooth, low-dimensional video
        cx1 = w * (0.5 + 0.3 * numpy.cos(t / 9))
        cy1 = h * (0.5 + 0.3 * numpy.sin(t / 9))
        cx2 = w * (0.5 + 0.4 * numpy.sin(t / 5))
        cy2 = numpy.full_like(t, h * 0.5)
        frames = numpy.empty((self.n_frames, h, w), numpy.float32)
        for i in range(self.n_frames):
            frames[i] = (
                numpy.exp(-((xx - cx1[i]) ** 2 + (yy - cy1[i]) ** 2) /
                          (2 * (h / 6) ** 2)) +
                numpy.exp(-((xx - cx2[i]) ** 2 + (yy - cy2[i]) ** 2) /
                          (2 * (h / 8) ** 2)))
        frames += r.normal(0, 0.01, frames.shape).astype(numpy.float32)
        self.original_data.reset(frames)
        self.original_targets.reset(frames.reshape(self.n_frames, -1)
                                    .copy())
        n_valid = self.n_frames // 5
        self.class_lengths[TEST] = 0
        self.class_lengths[VALID] = n_valid
        self.class_lengths[TRAIN] = self.n_frames - n_valid


root.video_ae.update({
    "decision": {"fail_iterations": 100, "max_epochs": 1000},
    "snapshotter": {"prefix": "video_ae", "interval": 1,
                    "time_interval": 0, "compression": ""},
    "loss_function": "mse",
    "loader_name": "video_ae_loader",
    "loader": {"minibatch_size": 50},
    "layers": [
        {"name": "bottleneck", "type": "all2all_tanh",
         "->": {"output_sample_shape": 9},
         "<-": {"learning_rate": 0.01, "weights_decay": 0.00005}},
        {"name": "reconstruct", "type": "all2all_tanh",
         "->": {},  # width auto-set from targets_shape
         "<-": {"learning_rate": 0.01, "weights_decay": 0.00005}}],
})


class VideoAEWorkflow(StandardWorkflow):
    """(reference tests/research/VideoAE/video_ae.py)"""


def build(layers=None, loader_config=None, decision_config=None, **kwargs):
    cfg = root.video_ae
    loader_cfg = cfg.loader.as_dict()
    loader_cfg.update(loader_config or {})
    decision_cfg = cfg.decision.as_dict()
    decision_cfg.update(decision_config or {})
    kwargs.setdefault("loss_function", cfg.loss_function)
    return VideoAEWorkflow(
        layers=layers if layers is not None else cfg.layers,
        loader_name=cfg.loader_name, loader_config=loader_cfg,
        decision_config=decision_cfg,
        snapshotter_config=cfg.snapshotter.as_dict(), **kwargs)


def run_sample(device=None, **kwargs):
    wf = build(**kwargs)
    wf.initialize(device=device)
    wf.run()
    return wf


def run(load, main):
    """Launcher contract (reference tests/research/VideoAE)."""
    load(build)
    main()
